// Client-side embedding cache with bounded staleness.
//
// Capability parity with the reference's src/hetu_cache (~1.2k LoC C++):
//  - versioned cache lines: data value, locally-accumulated grad, version
//    (-1 = never synced), update count (include/embedding.h:19-40)
//  - eviction policies LRU / LFU / LFUOpt (src/{lru,lfu,lfuopt}_cache.cc);
//    LFUOpt promotes lines that reach a frequency cap into a permanent store
//  - batched, deduplicated lookup/update; dirty evicted lines are buffered
//    and flushed with the next push (src/cache.cc:140-166)
//  - bounded-staleness sync protocol with the PS server: lookups pull only
//    rows the server has advanced more than `pull_bound` updates past the
//    local version; updates push only rows with more than `push_bound` local
//    updates (src/hetu_client.cc, ps-lite cachetable.h)
//  - async API: ops run on the cache's worker thread and return tickets;
//    perf counters per batch (num_all/num_unique/num_miss/num_evict/
//    num_transfered/time — cstable.py:126-187)
//
// Redesigned: no pybind11 (ctypes C API instead), one worker thread per cache
// (ops on one cache serialize anyway under the reference's mutex), and the
// transport is the hetups::PsWorker agent rather than ps-lite.
#pragma once

#include <chrono>
#include <condition_variable>
#include <cstring>
#include <deque>
#include <functional>
#include <list>
#include <map>
#include <memory>
#include <mutex>
#include <sstream>
#include <thread>
#include <unordered_map>
#include <vector>

#include "ps/worker.h"

namespace hetucache {

using cache_key_t = uint64_t;
using version_t = int64_t;

// One cached embedding row (reference Line<T>, embedding.h:19).
struct Line {
  cache_key_t key;
  version_t version = -1;  // -1: never synced with the server
  version_t updates = 0;   // local updates not yet pushed
  std::vector<float> data;
  std::vector<float> grad;
  bool has_data = true;

  Line(cache_key_t k, size_t width, bool init_data = true)
      : key(k), has_data(init_data) {
    if (init_data) data.assign(width, 0.0f);
  }

  void accumulate(const float* g, size_t width) {
    if (grad.empty()) grad.assign(width, 0.0f);
    for (size_t i = 0; i < width; ++i) grad[i] += g[i];
    if (has_data)
      for (size_t i = 0; i < width; ++i) data[i] += g[i];
    ++updates;
  }

  // re-apply unpushed local grads after the server value overwrote data
  void addup() {
    if (!grad.empty())
      for (size_t i = 0; i < data.size(); ++i) data[i] += grad[i];
  }

  void zero_grad() {
    std::fill(grad.begin(), grad.end(), 0.0f);
    updates = 0;
  }
};

using LinePtr = std::shared_ptr<Line>;

struct PerfRecord {
  const char* type;  // "Pull" or "Push"
  bool is_full;
  size_t num_all, num_unique, num_miss, num_evict, num_transfered;
  double time_ms;
};

class CacheBase {
 public:
  CacheBase(size_t limit, size_t length, size_t width, int node_id,
            hetups::PsWorker* ps)
      : limit_(limit), len_(length), width_(width), node_id_(node_id),
        ps_(ps) {
    worker_ = std::thread([this] { loop(); });
  }

  virtual ~CacheBase() { stop(); }

  void stop() {
    {
      std::lock_guard<std::mutex> g(qmu_);
      if (stopping_) return;
      stopping_ = true;
    }
    qcv_.notify_all();
    if (worker_.joinable()) worker_.join();
  }

  size_t limit() const { return limit_; }
  size_t width() const { return width_; }
  version_t pull_bound = 100;
  version_t push_bound = 100;

  void set_bypass(bool v) { bypass_ = v; }
  void set_perf_enabled(bool v) { perf_enabled_ = v; }
  // rollup-only mode: keep the O(1) counters but skip the per-batch
  // perf_ append — the telemetry poll arms THIS for long runs, where an
  // unbounded record vector would grow for the life of the process
  void set_perf_log(bool v) { perf_log_ = v; }

  // -- policy interface --------------------------------------------------
  virtual size_t size() = 0;
  virtual int count(cache_key_t k) = 0;
  virtual void insert(LinePtr e) = 0;
  virtual LinePtr lookup(cache_key_t k) = 0;
  virtual std::vector<cache_key_t> keys() = 0;

  // -- async API: enqueue, get a ticket; wait(ticket) joins --------------
  using ticket_t = int64_t;

  // INPUT buffers (keys, grads) are COPIED at enqueue time: callers may
  // free them immediately, ticket kept or not — a fire-and-forget
  // update_async must never read a buffer the caller has released (the
  // use-after-free shows up as astronomically large "gradients" pushed to
  // the server under concurrency). OUTPUT buffers (lookup dest) inherently
  // must outlive the op — the result lands there; wait() before reading.

  ticket_t lookup_async(const cache_key_t* keys, size_t n, float* dest) {
    std::vector<cache_key_t> k(keys, keys + n);
    return enqueue([this, k = std::move(k), n, dest] {
      do_lookup(k.data(), n, dest);
    });
  }

  ticket_t update_async(const cache_key_t* keys, const float* grads,
                        size_t n) {
    std::vector<cache_key_t> k(keys, keys + n);
    std::vector<float> g(grads, grads + n * width_);
    return enqueue([this, k = std::move(k), g = std::move(g), n] {
      do_update(k.data(), n, g.data());
    });
  }

  ticket_t push_pull_async(const cache_key_t* pull_keys, size_t n_pull,
                           float* dest, const cache_key_t* push_keys,
                           const float* grads, size_t n_push) {
    std::vector<cache_key_t> pk(pull_keys, pull_keys + n_pull);
    std::vector<cache_key_t> uk(push_keys, push_keys + n_push);
    std::vector<float> g(grads, grads + n_push * width_);
    return enqueue([this, pk = std::move(pk), uk = std::move(uk),
                    g = std::move(g), n_pull, dest, n_push] {
      do_push_pull(pk.data(), n_pull, dest, uk.data(), g.data(), n_push);
    });
  }

  // Returns empty string on success, the error message otherwise.
  std::string wait(ticket_t t) {
    std::unique_lock<std::mutex> g(qmu_);
    done_cv_.wait(g, [&] { return completed_ >= t; });
    auto it = errors_.find(t);
    if (it == errors_.end()) return "";
    std::string e = it->second;
    errors_.erase(it);
    return e;
  }

  // -- single-key debug API (reference cstable.py:150-161) ---------------
  std::mutex mtx;  // guards the policy structures

  bool lookup_one(cache_key_t k, float* out, version_t* version,
                  version_t* updates) {
    std::lock_guard<std::mutex> g(mtx);
    LinePtr p = lookup(k);
    if (!p) return false;
    if (out && p->has_data) std::memcpy(out, p->data.data(), width_ * 4);
    if (version) *version = p->version;
    if (updates) *updates = p->updates;
    return true;
  }

  void insert_one(cache_key_t k, const float* data) {
    auto line = std::make_shared<Line>(k, width_);
    std::memcpy(line->data.data(), data, width_ * 4);
    line->version = 0;
    std::lock_guard<std::mutex> g(mtx);
    insert(line);
  }

  std::vector<PerfRecord> perf() {
    std::lock_guard<std::mutex> g(perf_mu_);
    return perf_;
  }

  // O(1) cumulative rollup maintained alongside the per-batch log:
  // [batches, evictions, pull_miss, pull_uniq, transfered, num_all].
  // The telemetry poll reads THIS every N steps — re-serializing the
  // whole perf_ vector as JSON would cost O(batches) per poll.
  std::vector<long long> perf_rollup() {
    std::lock_guard<std::mutex> g(perf_mu_);
    return {rollup_batches_, rollup_evictions_, rollup_pull_miss_,
            rollup_pull_uniq_, rollup_transfered_, rollup_num_all_};
  }

  std::string repr() {
    std::ostringstream os;
    os << "<hetu_tpu.CacheSparseTable limit=" << limit_ << " size=" << size()
       << " width=" << width_ << " node=" << node_id_ << ">";
    return os.str();
  }

 protected:
  // -- batched core (runs on the cache worker thread) --------------------
  struct Uniqued {
    std::vector<cache_key_t> uniq;
    std::vector<size_t> inv;  // original slot -> uniq slot
  };

  static Uniqued unique_keys(const cache_key_t* keys, size_t n) {
    Uniqued u;
    u.inv.resize(n);
    std::unordered_map<cache_key_t, size_t> first;
    first.reserve(n * 2);
    for (size_t i = 0; i < n; ++i) {
      auto it = first.find(keys[i]);
      if (it == first.end()) {
        first.emplace(keys[i], u.uniq.size());
        u.inv[i] = u.uniq.size();
        u.uniq.push_back(keys[i]);
      } else {
        u.inv[i] = it->second;
      }
    }
    return u;
  }

  std::vector<LinePtr> batched_lookup(const std::vector<cache_key_t>& ks) {
    std::lock_guard<std::mutex> g(mtx);
    std::vector<LinePtr> out(ks.size());
    if (bypass_) return out;
    for (size_t i = 0; i < ks.size(); ++i) out[i] = lookup(ks[i]);
    return out;
  }

  void batched_insert(std::vector<LinePtr>& lines) {
    std::lock_guard<std::mutex> g(mtx);
    if (bypass_) return;
    for (auto& l : lines) insert(l);
  }

  // Pull path (reference cache.cc:60-110 _embeddingLookup).
  void do_lookup(const cache_key_t* keys, size_t n, float* dest) {
    auto t0 = std::chrono::steady_clock::now();
    auto u = unique_keys(keys, n);
    auto lines = batched_lookup(u.uniq);
    std::vector<LinePtr> should_insert;
    for (size_t i = 0; i < u.uniq.size(); ++i) {
      if (!lines[i]) {
        lines[i] = std::make_shared<Line>(u.uniq[i], width_);
        should_insert.push_back(lines[i]);
      }
    }
    // bounded-staleness sync: server returns only stale/never-seen rows
    std::vector<int64_t> vers(lines.size());
    for (size_t i = 0; i < lines.size(); ++i) vers[i] = lines[i]->version;
    std::vector<size_t> pos;
    std::vector<float> rows;
    std::vector<int64_t> new_vers;
    ps_->sync_embedding(node_id_, u.uniq.data(), vers.data(), u.uniq.size(),
                        pull_bound, &pos, &rows, &new_vers);
    for (size_t i = 0; i < pos.size(); ++i) {
      LinePtr& l = lines[pos[i]];
      l->version = new_vers[i];
      std::memcpy(l->data.data(), rows.data() + i * width_, width_ * 4);
      l->addup();
    }
    for (size_t i = 0; i < n; ++i)
      std::memcpy(dest + i * width_, lines[u.inv[i]]->data.data(),
                  width_ * 4);
    batched_insert(should_insert);
    if (perf_enabled_) {
      auto t1 = std::chrono::steady_clock::now();
      note_perf({"Pull", size() == limit_, n, u.uniq.size(),
                 should_insert.size(), 0, pos.size(),
                 std::chrono::duration<double, std::milli>(t1 - t0)
                     .count()});
    }
  }

  // Push path (reference cache.cc:131-197 _embeddingUpdate).
  void do_update(const cache_key_t* keys, size_t n, const float* grads) {
    auto t0 = std::chrono::steady_clock::now();
    auto u = unique_keys(keys, n);
    auto lines = batched_lookup(u.uniq);
    size_t miss = 0;
    std::vector<LinePtr> evicted;
    {
      std::lock_guard<std::mutex> g(mtx);
      evicted = std::move(evict_);
      evict_.clear();
    }
    for (size_t i = 0; i < n; ++i) {
      LinePtr& l = lines[u.inv[i]];
      if (!l) {
        // grad-only line: value unknown locally, must push
        l = std::make_shared<Line>(u.uniq[u.inv[i]], width_, false);
        ++miss;
      }
      l->accumulate(grads + i * width_, width_);
    }
    // rows over the push bound (or with no local value) + dirty evictions
    std::vector<LinePtr> should_push;
    for (auto& l : evicted) should_push.push_back(l);
    for (auto& l : lines)
      if (l->updates > push_bound || !l->has_data) should_push.push_back(l);
    if (!should_push.empty()) {
      std::vector<cache_key_t> pkeys(should_push.size());
      std::vector<float> pgrads(should_push.size() * width_);
      std::vector<int64_t> pups(should_push.size());
      for (size_t i = 0; i < should_push.size(); ++i) {
        pkeys[i] = should_push[i]->key;
        pups[i] = should_push[i]->updates;
        if (!should_push[i]->grad.empty())
          std::memcpy(pgrads.data() + i * width_,
                      should_push[i]->grad.data(), width_ * 4);
      }
      ps_->push_embedding(node_id_, pkeys.data(), pgrads.data(), pups.data(),
                          pkeys.size());
      // pushed lines that stay cached advance their version by their own
      // update count (the server did the same) and reset local grads
      for (auto& l : should_push) {
        if (l->has_data) {
          l->version += l->updates;
          l->zero_grad();
        }
      }
    }
    if (perf_enabled_) {
      auto t1 = std::chrono::steady_clock::now();
      note_perf({"Push", size() == limit_, n, u.uniq.size(), miss,
                 evicted.size(), should_push.size(),
                 std::chrono::duration<double, std::milli>(t1 - t0)
                     .count()});
    }
  }

  // Combined path (reference cache.cc _embeddingPushPull): accumulate the
  // push grads, then ONE kPushSyncEmbedding RPC per server applies the
  // over-bound pushes and returns the stale pull rows.
  void do_push_pull(const cache_key_t* pull_keys, size_t n_pull, float* dest,
                    const cache_key_t* push_keys, const float* grads,
                    size_t n_push) {
    auto t0 = std::chrono::steady_clock::now();
    // push side: accumulate into cached lines
    auto up = unique_keys(push_keys, n_push);
    auto push_lines = batched_lookup(up.uniq);
    std::vector<LinePtr> evicted;
    {
      std::lock_guard<std::mutex> g(mtx);
      evicted = std::move(evict_);
      evict_.clear();
    }
    size_t miss = 0;
    for (size_t i = 0; i < n_push; ++i) {
      LinePtr& l = push_lines[up.inv[i]];
      if (!l) {
        l = std::make_shared<Line>(up.uniq[up.inv[i]], width_, false);
        ++miss;
      }
      l->accumulate(grads + i * width_, width_);
    }
    std::vector<LinePtr> should_push;
    for (auto& l : evicted) should_push.push_back(l);
    for (auto& l : push_lines)
      if (l->updates > push_bound || !l->has_data) should_push.push_back(l);

    // pull side: cached lines + fresh lines for misses
    auto uq = unique_keys(pull_keys, n_pull);
    auto lines = batched_lookup(uq.uniq);
    std::vector<LinePtr> should_insert;
    for (size_t i = 0; i < uq.uniq.size(); ++i) {
      if (!lines[i]) {
        lines[i] = std::make_shared<Line>(uq.uniq[i], width_);
        should_insert.push_back(lines[i]);
      }
    }
    std::vector<int64_t> vers(lines.size());
    for (size_t i = 0; i < lines.size(); ++i) vers[i] = lines[i]->version;

    // one combined RPC per server
    std::vector<cache_key_t> pkeys(should_push.size());
    std::vector<float> pgrads(should_push.size() * width_, 0.0f);
    std::vector<int64_t> pups(should_push.size());
    for (size_t i = 0; i < should_push.size(); ++i) {
      pkeys[i] = should_push[i]->key;
      pups[i] = should_push[i]->updates;
      if (!should_push[i]->grad.empty())
        std::memcpy(pgrads.data() + i * width_, should_push[i]->grad.data(),
                    width_ * 4);
    }
    std::vector<size_t> pos;
    std::vector<float> rows;
    std::vector<int64_t> new_vers;
    ps_->push_sync_embedding(node_id_, pkeys.data(), pgrads.data(),
                             pups.data(), pkeys.size(), uq.uniq.data(),
                             vers.data(), uq.uniq.size(), pull_bound, &pos,
                             &rows, &new_vers);
    for (auto& l : should_push) {
      if (l->has_data) {
        l->version += l->updates;
        l->zero_grad();
      }
    }
    for (size_t i = 0; i < pos.size(); ++i) {
      LinePtr& l = lines[pos[i]];
      l->version = new_vers[i];
      std::memcpy(l->data.data(), rows.data() + i * width_, width_ * 4);
      l->addup();
    }
    for (size_t i = 0; i < n_pull; ++i)
      std::memcpy(dest + i * width_, lines[uq.inv[i]]->data.data(),
                  width_ * 4);
    batched_insert(should_insert);
    if (perf_enabled_) {
      auto t1 = std::chrono::steady_clock::now();
      note_perf({"Push", size() == limit_, n_push, up.uniq.size(), miss,
                 evicted.size(), should_push.size(),
                 std::chrono::duration<double, std::milli>(t1 - t0)
                     .count()});
      note_perf({"Pull", size() == limit_, n_pull, uq.uniq.size(),
                 should_insert.size(), 0, pos.size(), 0.0});
    }
  }

  ticket_t enqueue(std::function<void()> f) {
    std::lock_guard<std::mutex> g(qmu_);
    ticket_t t = ++next_ticket_;
    q_.push_back({t, std::move(f)});
    qcv_.notify_one();
    return t;
  }

  void loop() {
    for (;;) {
      std::pair<ticket_t, std::function<void()>> item;
      {
        std::unique_lock<std::mutex> g(qmu_);
        qcv_.wait(g, [this] { return stopping_ || !q_.empty(); });
        if (q_.empty()) {
          if (stopping_) return;
          continue;
        }
        item = std::move(q_.front());
        q_.pop_front();
      }
      try {
        item.second();
      } catch (const std::exception& e) {
        std::lock_guard<std::mutex> g(qmu_);
        errors_[item.first] = e.what();
      }
      {
        std::lock_guard<std::mutex> g(qmu_);
        completed_ = item.first;
      }
      done_cv_.notify_all();
    }
  }

  size_t limit_, len_, width_;
  int node_id_;
  hetups::PsWorker* ps_;
  bool bypass_ = false;
  bool perf_enabled_ = false;
  bool perf_log_ = true;   // per-batch records (the reference perf surface)
  std::vector<LinePtr> evict_;  // dirty evicted lines awaiting flush

  std::mutex perf_mu_;
  std::vector<PerfRecord> perf_;
  long long rollup_batches_ = 0, rollup_evictions_ = 0,
            rollup_pull_miss_ = 0, rollup_pull_uniq_ = 0,
            rollup_transfered_ = 0, rollup_num_all_ = 0;

  // single entry point for perf accounting: appends the per-batch record
  // AND folds it into the rollup counters under one lock acquisition
  void note_perf(PerfRecord r) {
    std::lock_guard<std::mutex> g(perf_mu_);
    rollup_batches_++;
    rollup_evictions_ += static_cast<long long>(r.num_evict);
    if (r.type[2] == 'l') {  // "Pull" (vs "Push")
      rollup_pull_miss_ += static_cast<long long>(r.num_miss);
      rollup_pull_uniq_ += static_cast<long long>(r.num_unique);
    }
    rollup_transfered_ += static_cast<long long>(r.num_transfered);
    rollup_num_all_ += static_cast<long long>(r.num_all);
    if (perf_log_) perf_.push_back(r);
  }

  std::thread worker_;
  std::mutex qmu_;
  std::condition_variable qcv_, done_cv_;
  std::deque<std::pair<ticket_t, std::function<void()>>> q_;
  std::unordered_map<ticket_t, std::string> errors_;
  ticket_t next_ticket_ = 0;
  ticket_t completed_ = 0;
  bool stopping_ = false;
};

// ---------------------------------------------------------------------------
// LRU: hash + recency list (reference lru_cache.cc).
// ---------------------------------------------------------------------------
class LRUCache : public CacheBase {
 public:
  using CacheBase::CacheBase;
  ~LRUCache() override { stop(); }  // join worker before members/vtable die

  size_t size() override { return map_.size(); }
  int count(cache_key_t k) override { return map_.count(k); }

  void insert(LinePtr e) override {
    auto it = map_.find(e->key);
    if (it != map_.end()) list_.erase(it->second);
    list_.push_front(e);
    map_[e->key] = list_.begin();
    if (map_.size() > limit_) {
      LinePtr victim = list_.back();
      map_.erase(victim->key);
      list_.pop_back();
      if (victim->updates != 0) evict_.push_back(victim);
    }
  }

  LinePtr lookup(cache_key_t k) override {
    auto it = map_.find(k);
    if (it == map_.end()) return nullptr;
    LinePtr e = *it->second;
    list_.erase(it->second);
    list_.push_front(e);
    map_[k] = list_.begin();
    return e;
  }

  std::vector<cache_key_t> keys() override {
    std::vector<cache_key_t> out;
    for (auto& kv : map_) out.push_back(kv.first);
    std::sort(out.begin(), out.end());
    return out;
  }

 private:
  std::list<LinePtr> list_;  // front = most recent
  std::unordered_map<cache_key_t, std::list<LinePtr>::iterator> map_;
};

// ---------------------------------------------------------------------------
// LFU: frequency buckets, each an LRU list (reference lfu_cache.cc).
// Evicts from the lowest-frequency bucket's tail.
// ---------------------------------------------------------------------------
class LFUCache : public CacheBase {
 public:
  using CacheBase::CacheBase;
  ~LFUCache() override { stop(); }

  size_t size() override { return map_.size(); }
  int count(cache_key_t k) override { return map_.count(k); }

  void insert(LinePtr e) override {
    auto it = map_.find(e->key);
    if (it != map_.end()) {
      it->second.second->ptr = e;
      touch(it);
      return;
    }
    if (map_.size() >= limit_) evict_one();
    auto& bucket = buckets_[1];
    bucket.push_front({e, 1});
    map_[e->key] = {1, bucket.begin()};
  }

  LinePtr lookup(cache_key_t k) override {
    auto it = map_.find(k);
    if (it == map_.end()) return nullptr;
    LinePtr e = it->second.second->ptr;
    touch(it);
    return e;
  }

  std::vector<cache_key_t> keys() override {
    std::vector<cache_key_t> out;
    for (auto& kv : map_) out.push_back(kv.first);
    std::sort(out.begin(), out.end());
    return out;
  }

 private:
  struct Block {
    LinePtr ptr;
    size_t freq;
  };
  using Bucket = std::list<Block>;
  // freq -> bucket; std::map so begin() is the lowest frequency
  std::map<size_t, Bucket> buckets_;
  std::unordered_map<cache_key_t, std::pair<size_t, Bucket::iterator>> map_;

  void touch(decltype(map_)::iterator it) {
    auto [freq, bit] = it->second;
    LinePtr e = bit->ptr;
    buckets_[freq].erase(bit);
    if (buckets_[freq].empty()) buckets_.erase(freq);
    auto& nb = buckets_[freq + 1];
    nb.push_front({e, freq + 1});
    it->second = {freq + 1, nb.begin()};
  }

  void evict_one() {
    if (buckets_.empty()) return;
    auto& [freq, bucket] = *buckets_.begin();
    LinePtr victim = bucket.back().ptr;
    bucket.pop_back();
    map_.erase(victim->key);
    if (victim->updates != 0) evict_.push_back(victim);
    if (bucket.empty()) buckets_.erase(buckets_.begin());
  }
};

// ---------------------------------------------------------------------------
// LFUOpt: LFU with a frequency cap; lines that reach the cap are promoted to
// a permanent store exempt from eviction (reference lfuopt_cache.cc).
// ---------------------------------------------------------------------------
class LFUOptCache : public CacheBase {
 public:
  using CacheBase::CacheBase;
  ~LFUOptCache() override { stop(); }
  static constexpr size_t kUseCntMax = 10;

  size_t size() override { return map_.size() + store_.size(); }
  int count(cache_key_t k) override {
    return map_.count(k) + store_.count(k);
  }

  void insert(LinePtr e) override {
    if (store_.count(e->key)) {
      store_[e->key] = e;
      return;
    }
    auto it = map_.find(e->key);
    if (it != map_.end()) {
      it->second.second->ptr = e;
      return;
    }
    if (size() >= limit_) {
      if (!map_.empty())
        evict_one();
      else
        return;  // everything is permanent: drop the insert
    }
    auto& bucket = buckets_[1];
    bucket.push_front({e, 1});
    map_[e->key] = {1, bucket.begin()};
  }

  LinePtr lookup(cache_key_t k) override {
    auto sit = store_.find(k);
    if (sit != store_.end()) return sit->second;
    auto it = map_.find(k);
    if (it == map_.end()) return nullptr;
    LinePtr e = it->second.second->ptr;
    auto [freq, bit] = it->second;
    if (freq + 1 >= kUseCntMax) {
      // promote to the permanent store
      buckets_[freq].erase(bit);
      if (buckets_[freq].empty()) buckets_.erase(freq);
      map_.erase(it);
      store_[k] = e;
    } else {
      buckets_[freq].erase(bit);
      if (buckets_[freq].empty()) buckets_.erase(freq);
      auto& nb = buckets_[freq + 1];
      nb.push_front({e, freq + 1});
      map_[k] = {freq + 1, nb.begin()};
    }
    return e;
  }

  std::vector<cache_key_t> keys() override {
    std::vector<cache_key_t> out;
    for (auto& kv : store_) out.push_back(kv.first);
    for (auto& kv : map_) out.push_back(kv.first);
    std::sort(out.begin(), out.end());
    return out;
  }

 private:
  struct Block {
    LinePtr ptr;
    size_t freq;
  };
  using Bucket = std::list<Block>;
  std::map<size_t, Bucket> buckets_;
  std::unordered_map<cache_key_t, std::pair<size_t, Bucket::iterator>> map_;
  std::unordered_map<cache_key_t, LinePtr> store_;

  void evict_one() {
    if (buckets_.empty()) return;
    auto& [freq, bucket] = *buckets_.begin();
    LinePtr victim = bucket.back().ptr;
    bucket.pop_back();
    map_.erase(victim->key);
    if (victim->updates != 0) evict_.push_back(victim);
    if (bucket.empty()) buckets_.erase(buckets_.begin());
  }
};

}  // namespace hetucache
