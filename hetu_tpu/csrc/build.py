"""Build the native components (C++17, no external deps) into shared libs.

Replaces the reference's cmake build (CMakeLists.txt, cmake/config.example.cmake)
with a dependency-free g++ invocation; libraries are rebuilt automatically when
sources are newer than the .so (so `import hetu_tpu.ps` always works after a
checkout, mirroring how the reference loads prebuilt .so files in _base.py:78-90).
"""
from __future__ import annotations

import os
import subprocess
import sys

_CSRC = os.path.dirname(os.path.abspath(__file__))
_BUILD = os.path.join(_CSRC, "build")

# One library: the cache shares the PS worker agent's process globals
# (the reference links hetu_cache against ps-lite the same way).
_TARGETS = {
    "libhetu_ps.so": {
        "srcs": ["ps/capi.cc", "cache/cache_capi.cc"],
        "deps": ["ps/net.h", "ps/store.h", "ps/server.h", "ps/scheduler.h",
                 "ps/worker.h", "ps/ring.h", "ps/chaos.h", "cache/cache.h"],
    },
}


def _mtime(path):
    try:
        return os.path.getmtime(path)
    except OSError:
        return 0.0


def build(name: str) -> str:
    """Build (if stale) and return the path to the named shared library."""
    spec = _TARGETS[name]
    out = os.path.join(_BUILD, name)
    srcs = [os.path.join(_CSRC, s) for s in spec["srcs"]]
    deps = srcs + [os.path.join(_CSRC, d) for d in spec["deps"]]
    missing = [p for p in deps if not os.path.exists(p)]
    if missing:
        raise FileNotFoundError(f"cannot build {name}: missing {missing}")
    if _mtime(out) >= max(_mtime(p) for p in deps):
        return out
    os.makedirs(_BUILD, exist_ok=True)
    cmd = ["g++", "-std=c++17", "-O2", "-fPIC", "-shared", "-pthread",
           "-I", _CSRC, "-o", out] + srcs
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    except subprocess.CalledProcessError as e:
        sys.stderr.write(e.stderr)
        raise RuntimeError(f"native build of {name} failed: {' '.join(cmd)}")
    return out
