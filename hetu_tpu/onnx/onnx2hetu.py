"""ONNX -> hetu_tpu graph import (reference ``python/hetu/onnx/onnx2hetu.py``
and ``X2hetu/``).

``load(path)`` parses a standard ``.onnx`` protobuf and rebuilds the graph
with this framework's ops: initializers become trainable Variables, graph
inputs become fed placeholders, and each ONNX node maps through the handler
registry below (the inverse of ``hetu2onnx``).
"""
from __future__ import annotations

from typing import Any, Callable

import numpy as np

from ..graph import ops as O
from ..graph.node import Variable
from . import proto as P

_IMPORTERS: dict[str, Callable] = {}


def imports(*op_types):
    def deco(fn):
        for t in op_types:
            _IMPORTERS[t] = fn
        return fn
    return deco


class ImportContext:
    def __init__(self):
        self.values: dict[str, Any] = {}    # name -> Op node
        self.consts: dict[str, np.ndarray] = {}  # names with static values
        self.inputs: dict[str, Any] = {}    # fed placeholders by name

    def const(self, name):
        """Static value of an input (initializer / Constant output), if any."""
        return self.consts.get(name)


def _attrs(node: P.NodeProto) -> dict[str, Any]:
    return {a.name: P.attr_value(a) for a in node.attribute}


@imports("Add", "Mul", "Div", "Sub")
def _i_binop(ctx, node, ins, attrs):
    a, b = ins
    ops = {"Add": O.add_op, "Mul": O.mul_op, "Div": O.div_op}
    if node.op_type == "Sub":
        return O.add_op(a, O.opposite_op(b))
    return ops[node.op_type](a, b)


@imports("Relu", "Sigmoid", "Tanh", "Sqrt", "Neg", "Exp", "Log", "Identity",
         "Dropout")
def _i_unary(ctx, node, ins, attrs):
    ops = {"Relu": O.relu_op, "Sigmoid": O.sigmoid_op, "Tanh": O.tanh_op,
           "Sqrt": O.sqrt_op, "Neg": O.opposite_op, "Exp": O.exp_op,
           "Log": O.log_op}
    if node.op_type in ("Identity", "Dropout"):  # inference dropout = id
        return ins[0]
    return ops[node.op_type](ins[0])


@imports("LeakyRelu")
def _i_leaky(ctx, node, ins, attrs):
    return O.leaky_relu_op(ins[0], attrs.get("alpha", 0.01))


@imports("Softmax")
def _i_softmax(ctx, node, ins, attrs):
    axis = attrs.get("axis", -1)
    if axis != -1:
        raise NotImplementedError(
            f"Softmax axis={axis}: only last-axis softmax is supported "
            "(transpose around the op to import axis-k softmax)")
    return O.softmax_op(ins[0])


@imports("MatMul")
def _i_matmul(ctx, node, ins, attrs):
    # ONNX MatMul has numpy-matmul semantics (batched over leading dims);
    # batch_matmul_op is jnp.matmul, rank-polymorphic — matmul_op is the
    # strictly-2D reference MatrixMult and 6D-explodes on batched inputs
    return O.batch_matmul_op(ins[0], ins[1])


@imports("Gemm")
def _i_gemm(ctx, node, ins, attrs):
    y = O.matmul_op(ins[0], ins[1], trans_A=bool(attrs.get("transA", 0)),
                    trans_B=bool(attrs.get("transB", 0)))
    alpha, beta = attrs.get("alpha", 1.0), attrs.get("beta", 1.0)
    if alpha != 1.0:
        y = O.mul_byconst_op(y, alpha)
    if len(ins) > 2:
        b = ins[2] if beta == 1.0 else O.mul_byconst_op(ins[2], beta)
        y = O.add_op(y, O.broadcastto_op(b, y))
    return y


@imports("Conv")
def _i_conv(ctx, node, ins, attrs):
    pads = attrs.get("pads", [0, 0, 0, 0])
    strides = attrs.get("strides", [1, 1])
    assert pads[0] == pads[1] == pads[2] == pads[3], \
        f"only symmetric conv pads supported, got {pads}"
    assert strides[0] == strides[1], strides
    y = O.conv2d_op(ins[0], ins[1], padding=pads[0], stride=strides[0])
    if len(ins) > 2:  # bias
        y = O.add_op(y, O.conv2d_broadcastto_op(ins[2], y))
    return y


@imports("MaxPool", "AveragePool")
def _i_pool(ctx, node, ins, attrs):
    kh, kw = attrs["kernel_shape"]
    pads = attrs.get("pads", [0, 0, 0, 0])
    strides = attrs.get("strides", [1, 1])
    assert pads[0] == pads[1] == pads[2] == pads[3], pads
    assert strides[0] == strides[1], strides
    if node.op_type == "MaxPool":
        return O.max_pool2d_op(ins[0], kh, kw, pads[0], strides[0])
    if pads[0] != 0 and not attrs.get("count_include_pad", 0):
        raise NotImplementedError(
            "AveragePool with pads and count_include_pad=0: this framework's "
            "avg pool divides by the full kernel area (reference semantics)")
    return O.avg_pool2d_op(ins[0], kh, kw, pads[0], strides[0])


@imports("BatchNormalization")
def _i_bn(ctx, node, ins, attrs):
    x, scale, bias, mean, var = ins
    # imported BN starts from the exported running stats; they continue to
    # update if the imported graph is trained
    op = O.batch_normalization_op(x, scale, bias,
                                  momentum=attrs.get("momentum", 0.9),
                                  eps=attrs.get("epsilon", 1e-5))
    mean_v = ctx.const(node.input[3])
    var_v = ctx.const(node.input[4])
    if mean_v is not None and var_v is not None:
        op.state_init = lambda: {"mean": np.asarray(mean_v, np.float32),
                                 "var": np.asarray(var_v, np.float32)}
    return op


@imports("Reshape")
def _i_reshape(ctx, node, ins, attrs):
    shape = ctx.const(node.input[1])
    assert shape is not None, "Reshape with dynamic shape input unsupported"
    return O.array_reshape_op(ins[0], tuple(int(s) for s in shape))


@imports("Transpose")
def _i_transpose(ctx, node, ins, attrs):
    return O.transpose_op(ins[0], attrs.get("perm"))


@imports("Concat")
def _i_concat(ctx, node, ins, attrs):
    out = ins[0]
    for nxt in ins[1:]:
        out = O.concat_op(out, nxt, axis=attrs["axis"])
    return out


@imports("Slice")
def _i_slice(ctx, node, ins, attrs):
    starts = ctx.const(node.input[1])
    ends = ctx.const(node.input[2])
    assert starts is not None and ends is not None, \
        "Slice with dynamic starts/ends unsupported"
    imax = np.iinfo(np.int64).max
    size = [-1 if e >= imax else int(e - s) for s, e in zip(starts, ends)]
    return O.slice_op(ins[0], [int(s) for s in starts], size)


@imports("Pad")
def _i_pad(ctx, node, ins, attrs):
    pads = ctx.const(node.input[1])
    assert pads is not None, "Pad with dynamic pads unsupported"
    n = len(pads) // 2
    paddings = [(int(pads[i]), int(pads[i + n])) for i in range(n)]
    cval = 0.0
    if len(node.input) > 2:
        cv = ctx.const(node.input[2])
        if cv is not None:
            cval = float(np.asarray(cv).ravel()[0])
    return O.pad_op(ins[0], paddings, constant_values=cval)


@imports("ReduceSum")
def _i_reduce_sum(ctx, node, ins, attrs):
    if len(node.input) > 1:  # opset 13: axes as input
        axes = ctx.const(node.input[1])
        assert axes is not None, "ReduceSum with dynamic axes unsupported"
    else:
        axes = attrs.get("axes")
    if axes is None:
        raise NotImplementedError(
            "ReduceSum with axes omitted (reduce over ALL axes) needs the "
            "input rank, which is not tracked at import")
    return O.reduce_sum_op(ins[0], [int(a) for a in axes],
                           keepdims=bool(attrs.get("keepdims", 1)))


@imports("ReduceMean")
def _i_reduce_mean(ctx, node, ins, attrs):
    return O.reduce_mean_op(ins[0], [int(a) for a in attrs["axes"]],
                            keepdims=bool(attrs.get("keepdims", 1)))


@imports("Cast")
def _i_cast(ctx, node, ins, attrs):
    return ins[0]  # dtypes are managed by the executor (f32/bf16 compute)


@imports("Gather")
def _i_gather(ctx, node, ins, attrs):
    assert attrs.get("axis", 0) == 0, "Gather only on axis 0"
    return O.embedding_lookup_op(ins[0], ins[1])


@imports("OneHot")
def _i_onehot(ctx, node, ins, attrs):
    depth = ctx.const(node.input[1])
    assert depth is not None, "OneHot with dynamic depth unsupported"
    return O.one_hot_op(ins[0], int(np.asarray(depth).ravel()[0]))


@imports("Expand")
def _i_expand(ctx, node, ins, attrs):
    # Expand(x, Shape(y)) round-trips broadcastto_op; the shape source node
    # is recovered from the producing Shape node (see _import_graph)
    shape_src = ctx.values.get("__shape_src__" + node.input[1])
    if shape_src is not None:
        return O.broadcastto_op(ins[0], shape_src)
    shape = ctx.const(node.input[1])
    assert shape is not None, "Expand needs a Shape() input or static shape"
    return O.broadcast_shape_op(ins[0], tuple(int(s) for s in shape))


@imports("Where")
def _i_where(ctx, node, ins, attrs):
    return O.where_op(ins[0], ins[1], ins[2])


def load(path: str):
    """Parse ``path`` and rebuild the graph.

    Returns ``(inputs, outputs)``: dict of input name -> fed placeholder
    Variable, and list of output nodes (in graph output order).
    """
    model = P.load_model(path)
    return import_graph(model.graph)


def import_graph(graph: P.GraphProto):
    ctx = ImportContext()
    for init in graph.initializer:
        value = P.numpy_from_tensor(init)
        ctx.consts[init.name] = value
        ctx.values[init.name] = Variable(init.name, value=value)
    for vi in graph.input:
        if vi.name in ctx.values:
            continue  # initializers may be re-listed as inputs
        v = Variable(vi.name, trainable=False)
        ctx.values[vi.name] = v
        ctx.inputs[vi.name] = v

    for node in graph.node:
        attrs = _attrs(node)
        if node.op_type == "Constant":
            value = attrs["value"]
            ctx.consts[node.output[0]] = np.asarray(value)
            # constants are NOT trainable — a Variable with a value defaults
            # to trainable=True and the optimizer would update it
            ctx.values[node.output[0]] = Variable(
                node.output[0], value=np.asarray(value), trainable=False)
            continue
        if node.op_type == "Shape":
            # keep the source node so Expand can rebuild broadcastto
            ctx.values["__shape_src__" + node.output[0]] = \
                ctx.values[node.input[0]]
            ctx.values[node.output[0]] = None  # consumed only via the marker
            continue
        if node.op_type == "ConstantOfShape":
            src = ctx.values.get("__shape_src__" + node.input[0])
            assert src is not None, "ConstantOfShape needs a Shape() input"
            fill = float(np.asarray(attrs.get("value", np.zeros(1))).ravel()[0])
            out = (O.zeroslike_op(src) if fill == 0.0 else
                   O.mul_byconst_op(O.oneslike_op(src), fill)
                   if fill != 1.0 else O.oneslike_op(src))
            ctx.values[node.output[0]] = out
            continue
        handler = _IMPORTERS.get(node.op_type)
        if handler is None:
            raise NotImplementedError(
                f"no import handler for ONNX op {node.op_type}")
        ins = [ctx.values[n] for n in node.input if n]
        out = handler(ctx, node, ins, attrs)
        ctx.values[node.output[0]] = out

    outputs = [ctx.values[vi.name] for vi in graph.output]
    return ctx.inputs, outputs
