"""X2hetu: import a TensorFlow GraphDef into the hetu_tpu op graph.

Reference: ``python/hetu/onnx/X2hetu/handler.py`` (TF1 graph -> hetu graph,
per-op handler registry). TF is not installable in this image, so this
importer reads the GraphDef protobuf DIRECTLY with the same hand-written
wire codec the ONNX bridge uses (``proto.py`` Message) — field numbers per
tensorflow/core/framework/{graph,node_def,attr_value,tensor,tensor_shape,
types}.proto. The supported op set covers the frozen inference graphs the
reference handler targets: Placeholder/Const/Identity, MatMul, Add/AddV2/
BiasAdd/Sub/Mul, Relu/Sigmoid/Tanh/Softmax, Reshape.

Usage::

    nodes = tf2hetu(graphdef_bytes_or_path)
    y = nodes["softmax"]          # any TF node name -> hetu op
    ex = ht.Executor([y])
    ex.run(feed_dict={nodes["x"]: batch})
"""
from __future__ import annotations

import numpy as np

from .proto import Message

# ---------------------------------------------------------------------------
# TF protobuf schema subset
# ---------------------------------------------------------------------------


class TfDim(Message):
    FIELDS = {"size": (1, "int"), "name": (2, "string")}


class TfTensorShape(Message):
    FIELDS = {"dim": (2, [TfDim]), "unknown_rank": (3, "int")}


class TfTensor(Message):
    FIELDS = {
        "dtype": (1, "int"),
        "tensor_shape": (2, TfTensorShape),
        "version_number": (3, "int"),
        "tensor_content": (4, "bytes"),
        "float_val": (5, ["float"]),
        # double_val (6) intentionally omitted: packed 8-byte doubles would
        # misparse as floats — unknown fields are skipped, and DT_DOUBLE
        # constants arrive via tensor_content (frombuffer handles them)
        "int_val": (7, ["int"]),
        "int64_val": (10, ["int"]),
    }


class TfAttrValue(Message):
    FIELDS = {
        "s": (2, "bytes"),
        "i": (3, "int"),
        "f": (4, "float"),
        "b": (5, "int"),
        "type": (6, "int"),
        "shape": (7, TfTensorShape),
        "tensor": (8, TfTensor),
    }


class TfAttrEntry(Message):   # map<string, AttrValue> entry
    FIELDS = {"key": (1, "string"), "value": (2, TfAttrValue)}


class TfNodeDef(Message):
    FIELDS = {
        "name": (1, "string"),
        "op": (2, "string"),
        "input": (3, ["string"]),
        "device": (4, "string"),
        "attr": (5, [TfAttrEntry]),
    }


class TfGraphDef(Message):
    FIELDS = {"node": (1, [TfNodeDef])}


# TF DataType enum values we accept
DT_FLOAT, DT_DOUBLE, DT_INT32, DT_INT64 = 1, 2, 3, 9
_DT_NUMPY = {DT_FLOAT: np.float32, DT_DOUBLE: np.float64,
             DT_INT32: np.int32, DT_INT64: np.int64}


def tensor_to_numpy(t: TfTensor) -> np.ndarray:
    dt = _DT_NUMPY.get(t.dtype)
    if dt is None:
        raise NotImplementedError(f"TF dtype enum {t.dtype}")
    shape = tuple(int(d.size) for d in (t.tensor_shape.dim
                                        if t.tensor_shape else []))
    n = int(np.prod(shape)) if shape else 1
    if t.tensor_content:
        arr = np.frombuffer(t.tensor_content, dtype=dt)
    elif t.float_val:
        arr = np.asarray(t.float_val, dt)
    elif t.int_val:
        arr = np.asarray(t.int_val, dt)
    elif t.int64_val:
        arr = np.asarray(t.int64_val, dt)
    elif n == 0:
        arr = np.zeros(0, dt)
    else:
        # TF never emits a value-less non-empty TensorProto; a "zeros"
        # guess here would be silently wrong numerics (e.g. a DT_DOUBLE
        # scalar stored in double_val, which this codec does not parse)
        raise NotImplementedError(
            "TF tensor carries no parseable values (tensor_content/"
            "float_val/int_val/int64_val all empty) — unsupported encoding")
    if arr.size == 1 and n > 1:     # splat-encoded constant
        arr = np.full(n, arr.ravel()[0], dt)
    return arr.reshape(shape)


def _attrs(node: TfNodeDef) -> dict:
    return {e.key: e.value for e in node.attr}


def _clean(name: str) -> str:
    """'node:0' output refs and '^ctrl' control deps -> plain node name."""
    if name.startswith("^"):
        return ""
    return name.split(":")[0]


# ---------------------------------------------------------------------------
# per-op handlers (reference handler.py's registry shape)
# ---------------------------------------------------------------------------

_HANDLERS = {}


def _handles(*ops):
    def reg(fn):
        for o in ops:
            _HANDLERS[o] = fn
        return fn
    return reg


@_handles("Placeholder")
def _placeholder(ht, node, inputs, attrs, consts):
    return ht.Variable(name=node.name, trainable=False)


@_handles("Const")
def _const(ht, node, inputs, attrs, consts):
    value = tensor_to_numpy(attrs["value"].tensor)
    return ht.Variable(name=node.name, value=value, trainable=False,
                       dtype=value.dtype)


@_handles("Identity")
def _identity(ht, node, inputs, attrs, consts):
    return inputs[0]


@_handles("MatMul")
def _matmul(ht, node, inputs, attrs, consts):
    ta = bool(attrs["transpose_a"].b) if "transpose_a" in attrs else False
    tb = bool(attrs["transpose_b"].b) if "transpose_b" in attrs else False
    return ht.matmul_op(inputs[0], inputs[1], trans_A=ta, trans_B=tb)


@_handles("Add", "AddV2", "BiasAdd")
def _add(ht, node, inputs, attrs, consts):
    if node.op == "BiasAdd" and "data_format" in attrs \
            and attrs["data_format"].s == b"NCHW":
        raise NotImplementedError(
            f"BiasAdd {node.name!r} with data_format=NCHW: only the "
            "default NHWC/last-axis broadcast is supported")
    return ht.add_op(inputs[0], inputs[1])


@_handles("Sub")
def _sub(ht, node, inputs, attrs, consts):
    # opposite_op (jnp.negative) preserves integer dtypes, matching the
    # ONNX importer's Sub lowering (onnx2hetu.py)
    return ht.add_op(inputs[0], ht.opposite_op(inputs[1]))


@_handles("Mul")
def _mul(ht, node, inputs, attrs, consts):
    return ht.mul_op(inputs[0], inputs[1])


@_handles("Relu")
def _relu(ht, node, inputs, attrs, consts):
    return ht.relu_op(inputs[0])


@_handles("Sigmoid")
def _sigmoid(ht, node, inputs, attrs, consts):
    return ht.sigmoid_op(inputs[0])


@_handles("Tanh")
def _tanh(ht, node, inputs, attrs, consts):
    return ht.tanh_op(inputs[0])


@_handles("Softmax")
def _softmax(ht, node, inputs, attrs, consts):
    return ht.softmax_op(inputs[0])


@_handles("Reshape")
def _reshape(ht, node, inputs, attrs, consts):
    shape = consts.get(id(inputs[1]))
    if shape is None:
        raise NotImplementedError(
            f"Reshape {node.name!r}: target shape must be a Const")
    return ht.array_reshape_op(inputs[0], tuple(int(s) for s in shape))


# ---------------------------------------------------------------------------
# importer
# ---------------------------------------------------------------------------

def tf2hetu(graphdef) -> dict:
    """Import a serialized TF GraphDef (bytes or file path). Returns
    {tf node name: hetu op}; Placeholders become feedable Variables."""
    import hetu_tpu as ht

    if isinstance(graphdef, str):
        with open(graphdef, "rb") as f:
            graphdef = f.read()
    g = TfGraphDef.FromString(graphdef)

    nodes: dict[str, object] = {}
    consts: dict[int, np.ndarray] = {}   # id(ht node) -> const value
    for node in g.node:
        handler = _HANDLERS.get(node.op)
        if handler is None:
            raise NotImplementedError(
                f"TF op {node.op!r} (node {node.name!r}) has no X2hetu "
                f"handler; supported: {sorted(_HANDLERS)}")
        in_names = [_clean(i) for i in node.input]
        inputs = [nodes[i] for i in in_names if i]
        attrs = _attrs(node)
        out = handler(ht, node, inputs, attrs, consts)
        if node.op == "Const":
            consts[id(out)] = out.value
        nodes[node.name] = out
    return nodes


def save_graphdef(g: TfGraphDef, path: str):
    with open(path, "wb") as f:
        f.write(g.SerializeToString())
