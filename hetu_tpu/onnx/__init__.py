"""ONNX bridge (reference ``python/hetu/onnx/`` — export ``hetu2onnx.py:27``,
import ``onnx2hetu.py`` + ``X2hetu/``).

Self-contained: serialization uses the vendored wire codec in ``proto.py``
(the ``onnx`` pip package is not required); files written/read are standard
``.onnx`` protobufs.
"""
from . import hetu2onnx, onnx2hetu, proto, x2hetu

__all__ = ["hetu2onnx", "onnx2hetu", "proto", "x2hetu"]
