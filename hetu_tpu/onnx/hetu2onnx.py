"""hetu_tpu graph -> ONNX export (reference ``python/hetu/onnx/hetu2onnx.py:27``).

API parity: ``export(executor, inputs, outputs, path)``. Each graph op maps to
standard ONNX ops via the handler registry below (mirroring the reference's
``onnx_opset`` per-op handler modules); parameter values come from the
executor's state (or the PS for PS-hosted params), BatchNorm running stats
export as inference-mode mean/var initializers.
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import numpy as np

from ..graph.node import Op, PlaceholderOp
from ..graph.ops.dropout import DropoutOp
from ..graph.ops.norm import BatchNormOp
from . import proto as P

OPSET_VERSION = 13

_HANDLERS: dict[str, Callable] = {}


def handles(*opnames):
    def deco(fn):
        for n in opnames:
            _HANDLERS[n] = fn
        return fn
    return deco


class ExportContext:
    """Name allocation + graph assembly state for one export."""

    def __init__(self, executor):
        self.executor = executor
        self.nodes: list[P.NodeProto] = []
        self.initializers: list[P.TensorProto] = []
        self._names: dict[int, str] = {}
        self._used: set[str] = set()
        self.shapes: dict[int, tuple] = {}  # id(op) -> inferred shape

    def name_of(self, op: Op) -> str:
        if id(op) not in self._names:
            base = op.name
            name, k = base, 1
            while name in self._used:
                name, k = f"{base}_{k}", k + 1
            self._used.add(name)
            self._names[id(op)] = name
        return self._names[id(op)]

    def fresh(self, base: str) -> str:
        name, k = base, 1
        while name in self._used:
            name, k = f"{base}_{k}", k + 1
        self._used.add(name)
        return name

    def add_node(self, op_type: str, inputs: list[str], outputs: list[str],
                 name: Optional[str] = None, **attrs):
        self.nodes.append(P.NodeProto(
            op_type=op_type, input=inputs, output=outputs,
            name=name or self.fresh(op_type),
            attribute=[P.make_attr(k, v) for k, v in attrs.items()
                       if v is not None]))

    def add_initializer(self, value: np.ndarray, base_name: str) -> str:
        name = self.fresh(base_name)
        self.initializers.append(P.tensor_from_numpy(np.asarray(value), name))
        return name

    def shape(self, op: Op):
        return self.shapes.get(id(op))


# ---------------------------------------------------------------------------
# per-op handlers: (ctx, op, in_names, out_name) -> None (append NodeProtos)
# ---------------------------------------------------------------------------

_DIRECT = {
    "AddElewise": "Add", "MultiplyElewise": "Mul", "Division": "Div",
    "Relu": "Relu", "Sigmoid": "Sigmoid", "Tanh": "Tanh", "Sqrt": "Sqrt",
    "Opposite": "Neg", "Exp": "Exp", "Log": "Log",
}


@handles("AddElewise", "MultiplyElewise", "Division", "Relu", "Sigmoid",
         "Tanh", "Sqrt", "Opposite", "Exp", "Log")
def _direct(ctx, op, ins, out):
    ctx.add_node(_DIRECT[op.opname], ins, [out])


@handles("OnesLike", "ZerosLike")
def _constlike(ctx, op, ins, out):
    shape = ctx.shape(op.inputs[0])
    fill = 1.0 if op.opname == "OnesLike" else 0.0
    if shape is not None and None not in shape:
        ctx.add_node("Constant", [], [out],
                     value=np.full(shape, fill, np.float32))
    else:
        sname = ctx.fresh(out + "_shape")
        ctx.add_node("Shape", ins, [sname])
        ctx.add_node("ConstantOfShape", [sname], [out],
                     value=np.asarray([fill], np.float32))


@handles("AddConst", "MultiplyConst", "DivConst")
def _const_binop(ctx, op, ins, out):
    c = ctx.add_initializer(np.asarray(op.export_attrs["const_val"],
                                       np.float32), out + "_const")
    onnx_op = {"AddConst": "Add", "MultiplyConst": "Mul",
               "DivConst": "Div"}[op.opname]
    # DivConst is const/x — constant is the FIRST operand
    pair = [c, ins[0]] if op.opname == "DivConst" else [ins[0], c]
    ctx.add_node(onnx_op, pair, [out])


@handles("LeakyRelu")
def _leaky(ctx, op, ins, out):
    ctx.add_node("LeakyRelu", ins, [out], alpha=float(op.export_attrs["alpha"]))


@handles("Softmax")
def _softmax(ctx, op, ins, out):
    ctx.add_node("Softmax", ins, [out], axis=-1)


@handles("MatMul", "BatchMatMul")
def _matmul(ctx, op, ins, out):
    def swap_last_two(name, node_in, tag):
        shape = ctx.shape(node_in)
        if shape is None:
            raise NotImplementedError(
                f"{op.name}: exporting a transposed matmul operand needs its "
                "rank; pass input_shapes to export()")
        rank = len(shape)
        perm = list(range(rank - 2)) + [rank - 1, rank - 2]
        t = ctx.fresh(out + tag)
        ctx.add_node("Transpose", [name], [t], perm=perm)
        return t

    a, b = ins
    if op.export_attrs.get("trans_A"):
        a = swap_last_two(a, op.inputs[0], "_ta")
    if op.export_attrs.get("trans_B"):
        b = swap_last_two(b, op.inputs[1], "_tb")
    ctx.add_node("MatMul", [a, b], [out])


@handles("Conv2d")
def _conv(ctx, op, ins, out):
    p, s = op.export_attrs["padding"], op.export_attrs["stride"]
    ctx.add_node("Conv", ins, [out], pads=[p, p, p, p], strides=[s, s])


@handles("MaxPool2d", "AvgPool2d")
def _pool(ctx, op, ins, out):
    a = op.export_attrs
    kw = dict(kernel_shape=[a["kernel_H"], a["kernel_W"]],
              pads=[a["padding"]] * 4, strides=[a["stride"]] * 2)
    if op.opname == "MaxPool2d":
        ctx.add_node("MaxPool", ins, [out], **kw)
    else:
        # our avg divides by the full kernel area (reference semantics)
        ctx.add_node("AveragePool", ins, [out], count_include_pad=1, **kw)


@handles("ArrayReshape")
def _reshape(ctx, op, ins, out):
    shape = ctx.add_initializer(
        np.asarray(op.export_attrs["output_shape"], np.int64), out + "_shape")
    ctx.add_node("Reshape", [ins[0], shape], [out])


@handles("Transpose")
def _transpose(ctx, op, ins, out):
    perm = op.export_attrs.get("perm")
    if perm is None:
        ctx.add_node("Transpose", ins, [out])
    else:
        ctx.add_node("Transpose", ins, [out], perm=list(perm))


@handles("Concat")
def _concat(ctx, op, ins, out):
    ctx.add_node("Concat", ins, [out], axis=int(op.export_attrs["axis"]))


@handles("Slice")
def _slice(ctx, op, ins, out):
    begin = op.export_attrs["begin"]
    size = op.export_attrs["size"]
    in_shape = ctx.shape(op.inputs[0])
    ends = []
    for i, (b, sz) in enumerate(zip(begin, size)):
        if sz == -1:
            ends.append(np.iinfo(np.int64).max if in_shape is None
                        else in_shape[i])
        else:
            ends.append(b + sz)
    starts = ctx.add_initializer(np.asarray(begin, np.int64), out + "_starts")
    ends_n = ctx.add_initializer(np.asarray(ends, np.int64), out + "_ends")
    ctx.add_node("Slice", [ins[0], starts, ends_n], [out])


@handles("Pad")
def _pad(ctx, op, ins, out):
    pads = op.export_attrs["paddings"]
    rank = len(ctx.shape(op.inputs[0]) or pads)
    full = [(0, 0)] * (rank - len(pads)) + list(pads)
    onnx_pads = [p[0] for p in full] + [p[1] for p in full]
    pads_n = ctx.add_initializer(np.asarray(onnx_pads, np.int64), out + "_pads")
    cval = ctx.add_initializer(
        np.asarray(op.export_attrs["constant_values"], np.float32),
        out + "_cval")
    ctx.add_node("Pad", [ins[0], pads_n, cval], [out], mode="constant")


def _emit_reduce_sum(ctx, ins, out, axes, keepdims):
    # opset 13 moved ReduceSum's axes from attribute to input
    axes_n = ctx.add_initializer(np.asarray(axes, np.int64), out + "_axes")
    ctx.add_node("ReduceSum", [ins[0], axes_n], [out], keepdims=int(keepdims))


@handles("ReduceSum", "ReduceMean")
def _reduce(ctx, op, ins, out):
    a = op.export_attrs
    if op.opname == "ReduceSum":
        _emit_reduce_sum(ctx, ins, out, list(a["axes"]), a["keepdims"])
    else:  # ReduceMean keeps axes as an attribute through opset 17
        ctx.add_node("ReduceMean", ins, [out], axes=list(a["axes"]),
                     keepdims=int(a["keepdims"]))


@handles("ReduceSumAxisZero")
def _reduce0(ctx, op, ins, out):
    _emit_reduce_sum(ctx, ins, out, [0], 0)


@handles("OneHot")
def _onehot(ctx, op, ins, out):
    n = op.export_attrs["num_classes"]
    idx = ctx.fresh(out + "_idx64")
    ctx.add_node("Cast", ins, [idx], to=P.TensorProto.INT64)
    depth = ctx.add_initializer(np.asarray(n, np.int64), out + "_depth")
    values = ctx.add_initializer(np.asarray([0.0, 1.0], np.float32),
                                 out + "_values")
    ctx.add_node("OneHot", [idx, depth, values], [out], axis=-1)


@handles("BroadcastTo")
def _broadcast(ctx, op, ins, out):
    sname = ctx.fresh(out + "_shape")
    ctx.add_node("Shape", [ins[1]], [sname])
    ctx.add_node("Expand", [ins[0], sname], [out])


@handles("BroadcastShape")
def _broadcast_shape(ctx, op, ins, out):
    """Static-shape broadcast: optional Reshape (inserting the add_axes 1s)
    then Expand with the target shape as an initializer. Imports back as
    broadcast_shape_op (onnx2hetu's static-shape Expand path)."""
    a = op.export_attrs
    cur = ins[0]
    if a["add_axes"]:
        in_shape = ctx.shape(op.inputs[0])
        if in_shape is None:
            raise NotImplementedError(
                f"{op.name}: exporting BroadcastShape with add_axes needs "
                "the input rank; pass input_shapes to export()")
        # mirror jnp.expand_dims applied sequentially over sorted axes,
        # including negative axes (position = ndim + 1 + ax)
        shape_list = list(in_shape)
        for ax in sorted(a["add_axes"]):
            pos = ax if ax >= 0 else len(shape_list) + 1 + ax
            shape_list.insert(pos, 1)
        rname = ctx.fresh(out + "_unsq")
        rshape = ctx.add_initializer(np.asarray(shape_list, np.int64),
                                     out + "_unsq_shape")
        ctx.add_node("Reshape", [cur, rshape], [rname])
        cur = rname
    sname = ctx.add_initializer(np.asarray(a["shape"], np.int64),
                                out + "_shape")
    ctx.add_node("Expand", [cur, sname], [out])


@handles("Conv2dBroadcastTo")
def _conv_broadcast(ctx, op, ins, out):
    # (C,) bias -> (N,C,H,W): reshape to (1,C,1,1) then Expand to x's shape
    shp = ctx.add_initializer(np.asarray([1, -1, 1, 1], np.int64),
                              out + "_bshape")
    r = ctx.fresh(out + "_r")
    ctx.add_node("Reshape", [ins[0], shp], [r])
    sname = ctx.fresh(out + "_shape")
    ctx.add_node("Shape", [ins[1]], [sname])
    ctx.add_node("Expand", [r, sname], [out])


@handles("Conv2dReduceSum")
def _conv_reduce(ctx, op, ins, out):
    _emit_reduce_sum(ctx, ins, out, [0, 2, 3], 0)


@handles("Where")
def _where(ctx, op, ins, out):
    cond = ctx.fresh(out + "_cond")
    ctx.add_node("Cast", [ins[0]], [cond], to=P.TensorProto.BOOL)
    ctx.add_node("Where", [cond, ins[1], ins[2]], [out])


@handles("EmbeddingLookUp")
def _gather(ctx, op, ins, out):
    idx = ctx.fresh(out + "_idx64")
    ctx.add_node("Cast", [ins[1]], [idx], to=P.TensorProto.INT64)
    ctx.add_node("Gather", [ins[0], idx], [out], axis=0)


@handles("LayerNorm")
def _layernorm(ctx, op, ins, out):
    # fn closes over eps; LayerNormalization is opset 17 — export the
    # composition instead for wide consumer support
    eps = op.fn.__defaults__[0] if op.fn.__defaults__ else 1e-2
    mean = ctx.fresh(out + "_mean")
    ctx.add_node("ReduceMean", [ins[0]], [mean], axes=[-1], keepdims=1)
    cent = ctx.fresh(out + "_cent")
    ctx.add_node("Sub", [ins[0], mean], [cent])
    sq = ctx.fresh(out + "_sq")
    ctx.add_node("Mul", [cent, cent], [sq])
    var = ctx.fresh(out + "_var")
    ctx.add_node("ReduceMean", [sq], [var], axes=[-1], keepdims=1)
    eps_n = ctx.add_initializer(np.asarray(eps, np.float32), out + "_eps")
    ve = ctx.fresh(out + "_ve")
    ctx.add_node("Add", [var, eps_n], [ve])
    std = ctx.fresh(out + "_std")
    ctx.add_node("Sqrt", [ve], [std])
    norm = ctx.fresh(out + "_norm")
    ctx.add_node("Div", [cent, std], [norm])
    scaled = ctx.fresh(out + "_scaled")
    ctx.add_node("Mul", [norm, ins[1]], [scaled])
    ctx.add_node("Add", [scaled, ins[2]], [out])


def _handle_batchnorm(ctx, op: BatchNormOp, ins, out):
    ex = ctx.executor
    state = None
    if ex is not None:
        state = ex.state["op_state"].get(id(op))
    if state is None:
        c = int(np.prod(op.inputs[1].shape))
        state = {"mean": np.zeros(c, np.float32), "var": np.ones(c, np.float32)}
    mean = ctx.add_initializer(np.asarray(state["mean"], np.float32),
                               out + "_mean")
    var = ctx.add_initializer(np.asarray(state["var"], np.float32),
                              out + "_var")
    ctx.add_node("BatchNormalization", [ins[0], ins[1], ins[2], mean, var],
                 [out], epsilon=float(op.eps), momentum=float(op.momentum))


def _handle_dropout(ctx, op: DropoutOp, ins, out):
    ctx.add_node("Dropout", ins, [out], )  # inference: identity


# ---------------------------------------------------------------------------
# shape inference over the graph (export needs ranks/sizes for several ops)
# ---------------------------------------------------------------------------

def _infer_shapes(topo, input_shapes: dict[int, tuple], ctx: ExportContext):
    for op in topo:
        if id(op) in input_shapes:
            ctx.shapes[id(op)] = tuple(input_shapes[id(op)])
            continue
        if isinstance(op, PlaceholderOp):
            if op.shape is not None:
                ctx.shapes[id(op)] = tuple(op.shape)
            continue
        in_shapes = [ctx.shapes.get(id(i)) for i in op.inputs]
        if any(s is None for s in in_shapes):
            continue
        try:
            if isinstance(op, BatchNormOp):
                ctx.shapes[id(op)] = in_shapes[0]
            elif isinstance(op, DropoutOp):
                ctx.shapes[id(op)] = in_shapes[0]
            else:
                ctx.shapes[id(op)] = tuple(op.infer_shape(in_shapes))
        except Exception:  # noqa: BLE001 — shapes are advisory for export
            pass


# ---------------------------------------------------------------------------
# export driver
# ---------------------------------------------------------------------------

def export(executor, inputs: list, outputs: list, path: str,
           job_name: str = None, input_shapes: Optional[dict] = None):
    """Export the subgraph computing ``outputs`` from ``inputs``.

    ``executor`` supplies parameter values (pass None for an untrained graph —
    initializers then come from Variable values). ``input_shapes`` optionally
    maps input node -> shape when the placeholders carry none.
    """
    assert inputs and outputs
    ctx = ExportContext(executor)
    input_ids = {id(n) for n in inputs}
    # topo CUT at the input boundary: nodes upstream of a declared input are
    # outside the exported subgraph (they would otherwise be emitted dead and
    # their feeds demanded as model inputs)
    topo = []
    visited: set[int] = set()

    def _dfs(node):
        if id(node) in visited:
            return
        visited.add(id(node))
        if id(node) not in input_ids:
            for i in node.inputs:
                _dfs(i)
        topo.append(node)

    for n in outputs:
        _dfs(n)

    shape_map = {}
    if input_shapes:
        shape_map = {id(k): tuple(v) for k, v in input_shapes.items()}
    _infer_shapes(topo, shape_map, ctx)

    # parameter values
    def param_value(node: PlaceholderOp) -> np.ndarray:
        if executor is not None:
            ps = getattr(executor, "ps_runtime", None)
            if ps is not None and id(node) in ps.params:
                p = ps.params[id(node)]
                if p.sparse:
                    rows = int(node.shape[0])
                    return ps.pull_sparse_rows(
                        p, np.arange(rows)).reshape(node.shape)
                return ps.pull_dense_value(p)
            val = executor.state["params"].get(id(node))
            if val is not None:
                return np.asarray(val)
        return np.asarray(node.instantiate(_init_key()), np.float32)

    graph_inputs = []
    for node in topo:
        if id(node) in input_ids:
            graph_inputs.append(
                P.make_value_info(ctx.name_of(node), ctx.shape(node)))
            continue
        if isinstance(node, PlaceholderOp):
            if node.trainable or node.value is not None \
                    or node.initializer is not None:
                ctx.initializers.append(P.tensor_from_numpy(
                    param_value(node), ctx.name_of(node)))
            else:
                graph_inputs.append(
                    P.make_value_info(ctx.name_of(node), ctx.shape(node)))
                input_ids.add(id(node))
            continue
        if node.is_dataloader:
            raise ValueError(
                f"{node.name}: dataloader nodes cannot be exported; list "
                "them in `inputs` replaced by placeholders")
        ins = [ctx.name_of(i) for i in node.inputs]
        out = ctx.name_of(node)
        if isinstance(node, BatchNormOp):
            _handle_batchnorm(ctx, node, ins, out)
        elif isinstance(node, DropoutOp):
            _handle_dropout(ctx, node, ins, out)
        else:
            opname = getattr(node, "opname", None)
            handler = _HANDLERS.get(opname)
            if handler is None:
                raise NotImplementedError(
                    f"no ONNX handler for op {opname or type(node).__name__} "
                    f"({node.name})")
            handler(ctx, node, ins, out)

    graph_outputs = [P.make_value_info(ctx.name_of(n), ctx.shape(n))
                     for n in outputs]
    graph = P.GraphProto(node=ctx.nodes, name=job_name or "HetuTpuToOnnx",
                         initializer=ctx.initializers,
                         input=graph_inputs, output=graph_outputs)
    model = P.ModelProto(ir_version=8, producer_name="hetu_tpu",
                         producer_version="0.1", graph=graph,
                         opset_import=[P.OperatorSetIdProto(domain="",
                                                            version=OPSET_VERSION)])
    P.save_model(model, path)
    return model


def _init_key():
    import jax
    return jax.random.PRNGKey(0)
