"""Minimal ONNX protobuf wire-format codec (pure Python, no dependencies).

The reference's ONNX bridge (``/root/reference/python/hetu/onnx/hetu2onnx.py:27``)
leans on the ``onnx`` pip package; that package is not in this image, so the
message subset the bridge needs — ModelProto, GraphProto, NodeProto,
TensorProto, AttributeProto, ValueInfoProto and friends — is encoded/decoded
here directly against the standard ONNX IR field numbers. Files produced are
ordinary ``.onnx`` protobufs loadable by stock onnx/onnxruntime.
"""
from __future__ import annotations

import struct
from typing import Any, Optional

import numpy as np


# ---------------------------------------------------------------------------
# protobuf wire primitives
# ---------------------------------------------------------------------------

def _write_varint(buf: bytearray, v: int):
    if v < 0:
        v &= (1 << 64) - 1  # two's-complement 64-bit, proto convention
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            buf.append(b | 0x80)
        else:
            buf.append(b)
            return


def _read_varint(data: bytes, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _signed64(v: int) -> int:
    return v - (1 << 64) if v >= (1 << 63) else v


def _tag(num: int, wire: int) -> bytes:
    buf = bytearray()
    _write_varint(buf, (num << 3) | wire)
    return bytes(buf)


def _write_len_delimited(buf: bytearray, num: int, payload: bytes):
    buf += _tag(num, 2)
    _write_varint(buf, len(payload))
    buf += payload


# ---------------------------------------------------------------------------
# message base: FIELDS = {py_name: (field_number, kind)} where kind is one of
# 'int' (varint int64), 'float' (fixed32), 'bytes', 'string', a Message class,
# or a list [kind] for repeated fields (scalars packed on write).
# ---------------------------------------------------------------------------

class Message:
    FIELDS: dict[str, tuple] = {}

    def __init__(self, **kwargs):
        for name, (num, kind) in self.FIELDS.items():
            default = [] if isinstance(kind, list) else None
            setattr(self, name, kwargs.pop(name, default))
        if kwargs:
            raise TypeError(f"unknown fields {list(kwargs)} for {type(self).__name__}")

    # -- encode ---------------------------------------------------------
    def SerializeToString(self) -> bytes:
        buf = bytearray()
        for name, (num, kind) in self.FIELDS.items():
            val = getattr(self, name)
            if val is None or (isinstance(val, list) and not val):
                continue
            if isinstance(kind, list):
                elem = kind[0]
                if elem == "int":   # packed varints
                    payload = bytearray()
                    for v in val:
                        _write_varint(payload, int(v))
                    _write_len_delimited(buf, num, bytes(payload))
                elif elem == "float":  # packed fixed32
                    _write_len_delimited(
                        buf, num, b"".join(struct.pack("<f", float(v)) for v in val))
                elif elem == "string":
                    for v in val:
                        _write_len_delimited(buf, num, v.encode("utf-8"))
                elif elem == "bytes":
                    for v in val:
                        _write_len_delimited(buf, num, v)
                else:  # repeated message
                    for v in val:
                        _write_len_delimited(buf, num, v.SerializeToString())
            elif kind == "int":
                buf += _tag(num, 0)
                _write_varint(buf, int(val))
            elif kind == "float":
                buf += _tag(num, 5)
                buf += struct.pack("<f", float(val))
            elif kind == "string":
                _write_len_delimited(buf, num, val.encode("utf-8"))
            elif kind == "bytes":
                _write_len_delimited(buf, num, val)
            else:  # nested message
                _write_len_delimited(buf, num, val.SerializeToString())
        return bytes(buf)

    # -- decode ---------------------------------------------------------
    @classmethod
    def FromString(cls, data: bytes) -> "Message":
        self = cls()
        by_num = {num: (name, kind) for name, (num, kind) in cls.FIELDS.items()}
        pos = 0
        while pos < len(data):
            key, pos = _read_varint(data, pos)
            num, wire = key >> 3, key & 7
            if num not in by_num:  # skip unknown field
                if wire == 0:
                    _, pos = _read_varint(data, pos)
                elif wire == 1:
                    pos += 8
                elif wire == 2:
                    ln, pos = _read_varint(data, pos)
                    pos += ln
                elif wire == 5:
                    pos += 4
                else:
                    raise ValueError(f"unsupported wire type {wire}")
                continue
            name, kind = by_num[num]
            if isinstance(kind, list):
                elem = kind[0]
                lst = getattr(self, name)
                if wire == 2:
                    ln, pos = _read_varint(data, pos)
                    chunk, pos = data[pos:pos + ln], pos + ln
                    if elem == "int":      # packed
                        p = 0
                        while p < len(chunk):
                            v, p = _read_varint(chunk, p)
                            lst.append(_signed64(v))
                    elif elem == "float":  # packed
                        lst.extend(struct.unpack(f"<{len(chunk)//4}f", chunk))
                    elif elem == "string":
                        lst.append(chunk.decode("utf-8"))
                    elif elem == "bytes":
                        lst.append(chunk)
                    else:
                        lst.append(elem.FromString(chunk))
                elif wire == 0 and elem == "int":  # unpacked varint
                    v, pos = _read_varint(data, pos)
                    lst.append(_signed64(v))
                elif wire == 5 and elem == "float":
                    lst.append(struct.unpack("<f", data[pos:pos + 4])[0])
                    pos += 4
                else:
                    raise ValueError(f"bad wire {wire} for repeated {elem}")
            elif kind == "int":
                v, pos = _read_varint(data, pos)
                setattr(self, name, _signed64(v))
            elif kind == "float":
                setattr(self, name, struct.unpack("<f", data[pos:pos + 4])[0])
                pos += 4
            elif kind in ("string", "bytes"):
                ln, pos = _read_varint(data, pos)
                chunk = data[pos:pos + ln]
                pos += ln
                setattr(self, name, chunk.decode("utf-8") if kind == "string"
                        else chunk)
            else:
                ln, pos = _read_varint(data, pos)
                setattr(self, name, kind.FromString(data[pos:pos + ln]))
                pos += ln
        return self

    def __repr__(self):
        fields = {n: getattr(self, n) for n in self.FIELDS
                  if getattr(self, n) not in (None, [])}
        return f"{type(self).__name__}({fields})"


# ---------------------------------------------------------------------------
# ONNX IR messages (field numbers per onnx/onnx.proto)
# ---------------------------------------------------------------------------

class TensorProto(Message):
    # DataType enum values
    FLOAT, UINT8, INT8, UINT16, INT16, INT32, INT64, STRING, BOOL = range(1, 10)
    FLOAT16, DOUBLE, UINT32, UINT64 = 10, 11, 12, 13
    BFLOAT16 = 16

    FIELDS = {
        "dims": (1, ["int"]),
        "data_type": (2, "int"),
        "float_data": (4, ["float"]),
        "int32_data": (5, ["int"]),
        "int64_data": (7, ["int"]),
        "name": (8, "string"),
        "raw_data": (9, "bytes"),
    }


_NP_TO_ONNX = {
    np.dtype(np.float32): TensorProto.FLOAT,
    np.dtype(np.float64): TensorProto.DOUBLE,
    np.dtype(np.int32): TensorProto.INT32,
    np.dtype(np.int64): TensorProto.INT64,
    np.dtype(np.uint8): TensorProto.UINT8,
    np.dtype(np.bool_): TensorProto.BOOL,
}
_ONNX_TO_NP = {v: k for k, v in _NP_TO_ONNX.items()}


def tensor_from_numpy(arr: np.ndarray, name: str) -> TensorProto:
    arr = np.ascontiguousarray(arr)
    if arr.dtype not in _NP_TO_ONNX:
        arr = arr.astype(np.float32)
    return TensorProto(dims=list(arr.shape), data_type=_NP_TO_ONNX[arr.dtype],
                       raw_data=arr.tobytes(), name=name)


def numpy_from_tensor(t: TensorProto) -> np.ndarray:
    dtype = _ONNX_TO_NP.get(t.data_type)
    if dtype is None:
        raise ValueError(f"unsupported ONNX tensor data_type {t.data_type}")
    shape = tuple(t.dims)
    if t.raw_data:
        return np.frombuffer(t.raw_data, dtype=dtype).reshape(shape).copy()
    if t.float_data:
        return np.asarray(t.float_data, np.float32).astype(dtype).reshape(shape)
    if t.int64_data:
        return np.asarray(t.int64_data, np.int64).astype(dtype).reshape(shape)
    if t.int32_data:
        return np.asarray(t.int32_data, np.int32).astype(dtype).reshape(shape)
    return np.zeros(shape, dtype)


class AttributeProto(Message):
    # AttributeType enum
    FLOAT, INT, STRING, TENSOR, GRAPH, FLOATS, INTS, STRINGS = range(1, 9)

    FIELDS = {
        "name": (1, "string"),
        "f": (2, "float"),
        "i": (3, "int"),
        "s": (4, "bytes"),
        "t": (5, TensorProto),
        "floats": (7, ["float"]),
        "ints": (8, ["int"]),
        "strings": (9, ["bytes"]),
        "type": (20, "int"),
    }


def make_attr(name: str, value: Any) -> AttributeProto:
    if isinstance(value, bool):
        return AttributeProto(name=name, i=int(value), type=AttributeProto.INT)
    if isinstance(value, (int, np.integer)):
        return AttributeProto(name=name, i=int(value), type=AttributeProto.INT)
    if isinstance(value, (float, np.floating)):
        return AttributeProto(name=name, f=float(value), type=AttributeProto.FLOAT)
    if isinstance(value, str):
        return AttributeProto(name=name, s=value.encode("utf-8"),
                              type=AttributeProto.STRING)
    if isinstance(value, np.ndarray):
        return AttributeProto(name=name, t=tensor_from_numpy(value, name),
                              type=AttributeProto.TENSOR)
    if isinstance(value, (list, tuple)):
        if all(isinstance(v, (int, np.integer)) for v in value):
            return AttributeProto(name=name, ints=[int(v) for v in value],
                                  type=AttributeProto.INTS)
        if all(isinstance(v, (int, float, np.floating, np.integer))
               for v in value):
            return AttributeProto(name=name, floats=[float(v) for v in value],
                                  type=AttributeProto.FLOATS)
    raise TypeError(f"cannot make ONNX attribute from {type(value)}")


def attr_value(a: AttributeProto) -> Any:
    if a.type == AttributeProto.FLOAT:
        return a.f
    if a.type == AttributeProto.INT:
        return a.i
    if a.type == AttributeProto.STRING:
        return a.s.decode("utf-8")
    if a.type == AttributeProto.TENSOR:
        return numpy_from_tensor(a.t)
    if a.type == AttributeProto.FLOATS:
        return list(a.floats)
    if a.type == AttributeProto.INTS:
        return list(a.ints)
    raise ValueError(f"unsupported attribute type {a.type}")


class NodeProto(Message):
    FIELDS = {
        "input": (1, ["string"]),
        "output": (2, ["string"]),
        "name": (3, "string"),
        "op_type": (4, "string"),
        "attribute": (5, []),  # patched below (forward ref)
        "doc_string": (6, "string"),
        "domain": (7, "string"),
    }


NodeProto.FIELDS["attribute"] = (5, [AttributeProto])


class DimProto(Message):
    FIELDS = {"dim_value": (1, "int"), "dim_param": (2, "string")}


class TensorShapeProto(Message):
    FIELDS = {"dim": (1, [DimProto])}


class TensorTypeProto(Message):
    FIELDS = {"elem_type": (1, "int"), "shape": (2, TensorShapeProto)}


class TypeProto(Message):
    FIELDS = {"tensor_type": (1, TensorTypeProto)}


class ValueInfoProto(Message):
    FIELDS = {"name": (1, "string"), "type": (2, TypeProto),
              "doc_string": (3, "string")}


def make_value_info(name: str, shape, elem_type=TensorProto.FLOAT) -> ValueInfoProto:
    """``shape=None`` means unknown RANK: the shape field is omitted entirely
    (declaring a wrong rank would break consumers' shape inference)."""
    if shape is None:
        return ValueInfoProto(name=name, type=TypeProto(
            tensor_type=TensorTypeProto(elem_type=elem_type)))
    dims = []
    for d in shape:
        if d is None:
            dims.append(DimProto(dim_param="N"))
        else:
            dims.append(DimProto(dim_value=int(d)))
    return ValueInfoProto(name=name, type=TypeProto(tensor_type=TensorTypeProto(
        elem_type=elem_type, shape=TensorShapeProto(dim=dims))))


def value_info_shape(vi: ValueInfoProto):
    tt = vi.type.tensor_type if vi.type else None
    if tt is None or tt.shape is None:
        return None
    out = []
    for d in tt.shape.dim:
        out.append(int(d.dim_value) if d.dim_value is not None else None)
    return tuple(out)


class GraphProto(Message):
    FIELDS = {
        "node": (1, [NodeProto]),
        "name": (2, "string"),
        "initializer": (5, [TensorProto]),
        "doc_string": (10, "string"),
        "input": (11, [ValueInfoProto]),
        "output": (12, [ValueInfoProto]),
        "value_info": (13, [ValueInfoProto]),
    }


class OperatorSetIdProto(Message):
    FIELDS = {"domain": (1, "string"), "version": (2, "int")}


class ModelProto(Message):
    FIELDS = {
        "ir_version": (1, "int"),
        "producer_name": (2, "string"),
        "producer_version": (3, "string"),
        "domain": (4, "string"),
        "model_version": (5, "int"),
        "doc_string": (6, "string"),
        "graph": (7, GraphProto),
        "opset_import": (8, [OperatorSetIdProto]),
    }


def load_model(path: str) -> ModelProto:
    with open(path, "rb") as f:
        return ModelProto.FromString(f.read())


def save_model(model: ModelProto, path: str):
    with open(path, "wb") as f:
        f.write(model.SerializeToString())
