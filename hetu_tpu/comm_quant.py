"""hetuq: quantized communication for DP gradient sync and PS traffic.

Two independent wire paths share the one policy knob
(``HetuConfig(comm_quant="off"|"int8"|"fp8")`` / ``HETU_COMM_QUANT``):

- **DP AllReduce** (in-trace, pure XLA): the gradient all-reduce is
  decomposed as reduce-scatter (f32, exact accumulation) + all-gather of a
  blockwise-quantized payload (int8 or fp8 with one f32 scale per ~256-
  element block), expressed entirely through sharding constraints so GSPMD
  materializes the int8 collective — the JAX-level analogue of EQuARX's
  in-XLA blockwise AllReduce (PAPERS.md arXiv:2506.17615; GSPMD offers no
  trace-level handle on per-replica partial sums, so the reduction half
  stays exact f32 and only the broadcast half rides the wire compressed).
  An optional error-feedback residual (executor-managed state) carries the
  quantization error into the next step so compression error does not
  accumulate in the parameters.

- **PS sparse/dense traffic** (host/C++): row-wise int8 with one f32 scale
  per row for sparse push/pull payloads and block-wise int8 for dense
  push/push-pull, carried by the ``ArgType::kQI8`` wire container
  (``csrc/ps/net.h``). The server dequantizes on receipt and applies in
  f32, so dedup-sums, the snapshot format, the resend-dedup ledger, and
  exact lost-update accounting are all untouched. :func:`np_quantize_blocks`
  is the bit-exact Python mirror of the C++ quantizer (same f32 ops, same
  round-half-even), which is what the dedup-exactness tests assert against.

Scheme (both paths): symmetric linear quantization per block —
``scale = max(|block|) / Q`` (Q = 127 for int8, 448 for fp8-e4m3),
``q = round_half_even(v / scale)``, ``dq = q * scale``; an all-zero block
stores scale 0 and dequantizes to exact zeros. Max error per element is
``scale / 2`` for int8. See docs/COMM_QUANT.md for the error-feedback math
and the exemption policy.
"""
from __future__ import annotations

import os
from typing import Optional

import numpy as np

MODES = ("off", "int8", "fp8")

# wire block for dense (non-row-structured) payloads, both the XLA and the
# PS paths; sparse rows use the row width as the block so one scale serves
# one row
DEFAULT_BLOCK = 256
# params below this element count are exempt (biases, norm scales — tiny
# payloads where quantization risk buys no measurable wire saving)
DEFAULT_MIN_SIZE = 2048

_INT8_Q = 127.0
_FP8_Q = 448.0  # float8_e4m3fn max finite


def _env(name, dflt):
    v = os.environ.get(name)
    return v if v not in (None, "") else dflt


def _env_bool(name, dflt):
    v = os.environ.get(name)
    if v is None or v == "":
        return dflt
    return v.strip().lower() in ("1", "true", "yes", "on")


def fp8_dtype():
    """The fp8 wire dtype (``float8_e4m3fn``) or None when this jax build
    has no float8 support."""
    import jax.numpy as jnp
    return getattr(jnp, "float8_e4m3fn", None)


class QuantPolicy:
    """Per-parameter quantization decisions for one executor.

    ``mode``: "off" | "int8" | "fp8" (fp8 applies to the AllReduce path
    only; the PS wire container is int8). ``block``: scale granularity for
    dense payloads. ``min_size``: params with fewer elements are exempt.
    ``error_feedback``: carry the AllReduce quantization error as residual
    state. ``force``: param names quantized regardless of the size
    threshold (an override hetulint warns about when it defeats the
    exemption — see ``comm-quant-forced-small``).
    """

    def __init__(self, mode="off", block=DEFAULT_BLOCK,
                 min_size=DEFAULT_MIN_SIZE, error_feedback=True, force=()):
        if mode not in MODES:
            raise ValueError(
                f"comm_quant must be one of {MODES}, got {mode!r}")
        if int(block) <= 0:
            raise ValueError(f"comm_quant block must be positive, got {block}")
        self.mode = mode
        self.block = int(block)
        self.min_size = int(min_size)
        self.error_feedback = bool(error_feedback)
        self.force = tuple(force or ())
        if mode == "fp8" and fp8_dtype() is None:
            raise ValueError(
                "comm_quant='fp8' needs a jax build with float8_e4m3fn; "
                "use 'int8' on this environment")

    @property
    def active(self) -> bool:
        return self.mode != "off"

    def applies(self, param_node, size: int) -> bool:
        """Does this policy quantize a param of ``size`` elements?"""
        if not self.active:
            return False
        name = getattr(param_node, "name", None)
        if name is not None and name in self.force:
            return True
        return int(size) >= self.min_size

    def __repr__(self):
        return (f"QuantPolicy({self.mode!r}, block={self.block}, "
                f"min_size={self.min_size}, ef={self.error_feedback})")


def resolve_policy(mode=None, block=None, min_size=None, error_feedback=None,
                   force=()) -> QuantPolicy:
    """Config-or-env resolution (the telemetry/introspect convention):
    explicit arguments win, then ``HETU_COMM_QUANT`` /
    ``HETU_COMM_QUANT_BLOCK`` / ``HETU_COMM_QUANT_MIN`` /
    ``HETU_COMM_QUANT_EF``, then the defaults (off)."""
    if mode is None:
        mode = _env("HETU_COMM_QUANT", "off")
    if block is None:
        block = int(_env("HETU_COMM_QUANT_BLOCK", DEFAULT_BLOCK))
    if min_size is None:
        min_size = int(_env("HETU_COMM_QUANT_MIN", DEFAULT_MIN_SIZE))
    if error_feedback is None:
        error_feedback = _env_bool("HETU_COMM_QUANT_EF", True)
    return QuantPolicy(mode, block=block, min_size=min_size,
                       error_feedback=error_feedback, force=force)


# ---------------------------------------------------------------------------
# traced (jnp) blockwise quantize/dequantize — the AllReduce path
# ---------------------------------------------------------------------------

def quantize_blocks(x, block: int, mode: str = "int8"):
    """Blockwise symmetric quantization of a flat f32 array inside a trace.

    Returns ``(q, scales, n)``: ``q`` is the padded quantized payload
    (int8 or fp8, length ``ceil(n/block)*block``), ``scales`` one f32 per
    block, ``n`` the original element count. Deterministic (round half to
    even), so every replica of a replicated input quantizes identically.
    """
    import jax.numpy as jnp
    if mode not in ("int8", "fp8"):
        raise ValueError(f"quantize_blocks: mode must be int8/fp8, "
                         f"got {mode!r}")
    flat = x.reshape(-1).astype(jnp.float32)
    n = flat.size
    nb = -(-n // block)
    pad = nb * block - n
    if pad:
        flat = jnp.pad(flat, (0, pad))
    blocks = flat.reshape(nb, block)
    amax = jnp.max(jnp.abs(blocks), axis=1, keepdims=True)
    if mode == "fp8":
        f8 = fp8_dtype()
        scales = amax / _FP8_Q
        safe = jnp.where(scales > 0, scales, 1.0)
        q = (blocks / safe).astype(f8)
    else:
        scales = amax / _INT8_Q
        safe = jnp.where(scales > 0, scales, 1.0)
        q = jnp.clip(jnp.round(blocks / safe), -127, 127).astype(jnp.int8)
    return q.reshape(-1), scales.reshape(-1), n


def dequantize_blocks(q, scales, n: int, block: int):
    """Inverse of :func:`quantize_blocks` (drops the padding tail)."""
    import jax.numpy as jnp
    nb = scales.size
    vals = (q.reshape(nb, block).astype(jnp.float32)
            * scales.reshape(nb, 1)).reshape(-1)
    return vals[:n]


def quantized_allreduce(x, residual, mesh, dp_axis: str, out_sharding,
                        policy: QuantPolicy):
    """One quantized DP gradient all-reduce inside the jitted step.

    ``x`` is the logical (full-batch) gradient; under GSPMD its physical
    realization before the first replication constraint is per-replica
    partial sums. The lowering is reduce-scatter (f32 — the accumulation
    stays exact) via a dp-sharded constraint, blockwise quantize of the
    shards, all-gather of the compressed payload via a replicated
    constraint, then dequantize. ``residual`` (or None) is the error-
    feedback state: it is added before quantization and the new residual
    ``(input - dequantized)`` is returned for the executor to thread into
    the next step.

    Returns ``(value, new_residual_or_None)`` with ``value`` constrained to
    ``out_sharding`` (the target parameter's own spec).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    orig_dtype = x.dtype
    g = x.astype(jnp.float32) if x.dtype != jnp.float32 else x
    if residual is not None:
        g = g + residual.astype(jnp.float32)
    flat = g.reshape(-1)
    # reduce-scatter point: the f32 partial-sum reduction lands here, into
    # dp shards (exact accumulation — quantization error never enters the
    # sum itself, which is also why error feedback only needs to model the
    # quantizer)
    flat = jax.lax.with_sharding_constraint(
        flat, NamedSharding(mesh, P(dp_axis)))
    # hetukern quant-fused legs (docs/KERNELS.md): the quantize fused into
    # the reduce-scatter output and the dequantize into the all-gather
    # output each become ONE Pallas pass over the shard when the kernel
    # tier is active — bit-identical wire payloads to this module's jnp
    # path (asserted in tests/test_kernels.py), so mixed fleets agree
    from .kernels import quant_comm as _qk
    q, scales, n = _qk.quantize_blocks(flat, policy.block, policy.mode)
    # all-gather point: the wire payload here is the 1-byte-per-element
    # compressed tensor plus one f32 scale per block
    q = jax.lax.with_sharding_constraint(q, NamedSharding(mesh, P()))
    scales = jax.lax.with_sharding_constraint(
        scales, NamedSharding(mesh, P()))
    dq = _qk.dequantize_blocks(q, scales, n, policy.block)
    new_residual = None
    if residual is not None:
        new_residual = (g.reshape(-1) - dq).reshape(x.shape)
    out = dq.reshape(x.shape).astype(orig_dtype)
    out = jax.lax.with_sharding_constraint(out, out_sharding)
    return out, new_residual


def allreduce_wire_report(sizes: dict, policy: QuantPolicy,
                          dp: int) -> dict:
    """Analytic per-step wire accounting for the quantized AllReduce path
    (``sizes``: quantized-param name -> element count). ``raw_bytes`` is
    the baseline f32 all-reduce payload (reduce-scatter + all-gather =
    2·N·4 per step), ``wire_bytes`` the quantized decomposition's
    (f32 reduce-scatter + 1-byte all-gather + scales). Exported as the
    ``hetu_comm_quant_raw_bytes`` / ``_wire_bytes`` gauges and reported by
    the bench DP cell; the PS path reports *measured* counters instead
    (worker.h)."""
    raw = wire = 0
    for n in sizes.values():
        nb = -(-n // policy.block)
        raw += 2 * n * 4
        wire += n * 4 + n + nb * 4
    return {"params": len(sizes), "elements": sum(sizes.values()),
            "raw_bytes": raw, "wire_bytes": wire, "dp": dp,
            "ratio": round(raw / wire, 3) if wire else None}


# ---------------------------------------------------------------------------
# numpy mirror of the C++ wire quantizer (csrc/ps/net.h make_qi8_arg)
# ---------------------------------------------------------------------------

def np_quantize_blocks(vals, block: int):
    """Bit-exact host mirror of the C++ int8 quantizer: same f32 ops, same
    round-half-even (``lrintf`` under the default rounding mode). Tests
    assert the PS server's applied values equal this mirror EXACTLY, which
    proves dedup-sums happened in f32 before quantization."""
    flat = np.ascontiguousarray(vals, np.float32).ravel()
    n = flat.size
    nb = -(-n // block)
    padded = np.zeros(nb * block, np.float32)
    padded[:n] = flat
    blocks = padded.reshape(nb, block)
    amax = np.max(np.abs(blocks), axis=1).astype(np.float32)
    scales = (amax / np.float32(_INT8_Q)).astype(np.float32)
    safe = np.where(scales > 0, scales, np.float32(1.0)).astype(np.float32)
    q = np.clip(np.rint(blocks / safe[:, None]), -127, 127).astype(np.int8)
    return q.reshape(-1)[: nb * block], scales, n


def np_dequantize_blocks(q, scales, n: int, block: int):
    nb = scales.size
    vals = (q.reshape(nb, block).astype(np.float32)
            * scales[:, None].astype(np.float32)).reshape(-1)
    return vals[:n]


def np_roundtrip(vals, block: int):
    """Quantize→dequantize through the wire mirror; shape-preserving."""
    a = np.ascontiguousarray(vals, np.float32)
    q, s, n = np_quantize_blocks(a, block)
    return np_dequantize_blocks(q, s, n, block).reshape(a.shape)
