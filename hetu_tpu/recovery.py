"""hetusave: coordinated job-wide consistent checkpoints + exactly-once
whole-job crash recovery (docs/FAULT_TOLERANCE.md "Coordinated job
snapshots").

Every durable piece of state in the stack is recoverable *separately* —
worker emergency checkpoints, per-server PS snapshots with lost-update
accounting, dataloader cursors and the elastic world log — but a
whole-job failure (power loss, pool preemption, OOM-killer sweep) leaves
them mutually INCONSISTENT: worker state at step N, PS shards at
assorted update counts, cursors somewhere in between. This module makes
them one recovery point:

- :func:`take_job_snapshot` rides the two-phase resize machinery
  (propose -> drain-park -> abort) as a **quiesce barrier**: the worker
  parks at a step boundary with all in-flight pushes drained through the
  req_id dedup ledger (``pushes_ok == sum(server updates)`` is the
  quiesce PROOF, not an assumption), every PS server writes one
  epoch-stamped snapshot (``kSnapshotNow``) under the per-param shared
  locks, the worker persists params, optimizer slots, ``qresid``,
  dataloader cursors, RNG and the world log, and ONE job manifest is
  committed atomically (temp+rename). A torn or uncommitted epoch is
  never eligible for restore.
- :func:`prepare_restore` + :func:`load_worker_state` reconstruct the
  job from the newest COMMITTED manifest — including into a different
  world size via the offline key-range re-split (:func:`resplit_epoch`,
  optimizer slots move bit-for-bit with their rows), with the
  update-counter algebra verified before training resumes
  (:func:`verify_restored_job`).
- :func:`run_soak` proves the protocol under whole-job kills injected at
  every snapshot phase (``PHASES``): the restored lineage's losses,
  consumed-sample multiset and final params are compared BIT-IDENTICALLY
  against an uninterrupted fault-free twin.

Everything above ``take_job_snapshot`` is stdlib+numpy (``bin/hetusave
--check`` must run jax-free); jax/hetu imports are lazy in the drivers.
"""
from __future__ import annotations

import json
import os
import pickle
import shutil
import sys
import threading
import time
from typing import Callable, Optional

import numpy as np

from . import faults

#: the crash windows of one coordinated snapshot, in protocol order —
#: the ``job_kill@step:phase`` fault kind targets exactly these, and the
#: shared fault registry (hetu_tpu.faults) owns the tuple so the injector
#: grammar and this module can never disagree:
#:   pre_barrier   before the quiesce barrier is even proposed
#:   server_write  after the FIRST server snapshot landed (torn epoch:
#:                 some servers newer than others, no manifest)
#:   pre_commit    all state written, job manifest NOT yet committed
#:   post_commit   manifest committed (the epoch must be restorable)
PHASES = faults.JOB_KILL_PHASES

MANIFEST_FORMAT = 1
_MANIFEST_PREFIX = "job_epoch_"
_EPOCH_PREFIX = "epoch_"

# per-server snapshot manifest constants (csrc/ps/server.h) — the offline
# re-split writes manifests the native restore path parses directly
_PS_MANIFEST_MAGIC = -7001


class RecoveryError(RuntimeError):
    """A broken recovery invariant (failed quiesce proof, no committed
    epoch, counter-algebra mismatch) — never swallowed."""


class JobKilled(BaseException):
    """The simulated whole-job death the soak injects mid-snapshot.
    Derives from BaseException so ordinary ``except Exception`` hardening
    inside the job cannot absorb it — a power loss is not absorbable."""


# ---------------------------------------------------------------------------
# job_kill arming (consumed by take_job_snapshot at phase boundaries)
# ---------------------------------------------------------------------------

_armed_kill: dict = {"phase": None}


def arm_job_kill(phase: str) -> None:
    """Arm a whole-job kill at ``phase`` of the NEXT coordinated snapshot
    (the ``job_kill@step:phase`` fault kind's executor). Consumed once."""
    if phase not in PHASES:
        raise ValueError(f"job_kill phase {phase!r} not in {PHASES}")
    _armed_kill["phase"] = phase


def armed_kill_phase() -> Optional[str]:
    return _armed_kill["phase"]


def kill_whole_job(step: Optional[int] = None,
                   phase: Optional[str] = None) -> None:
    """Whole-job death, no grace, no cleanup: SIGKILL every live
    local-cluster process (scheduler + servers), then this worker —
    the power-loss / pool-sweep shape only a committed job epoch
    recovers from. HETU_TEST_MODE-gated like every destructive hook."""
    import signal as _signal

    from .resilience import test_mode_enabled
    if not test_mode_enabled():
        raise RuntimeError("job_kill requires HETU_TEST_MODE")
    where = f"phase {phase}" if phase else f"step {step}"
    print(f"# hetu fault: job_kill — whole job dying at {where}",
          file=sys.stderr, flush=True)
    try:
        from .ps.local_cluster import get_live_cluster
        for p in get_live_cluster().get("procs", []):
            try:
                p.kill()
            except Exception:  # noqa: BLE001 — already dead is fine
                pass
    except Exception:  # noqa: BLE001 — no live cluster: still die
        pass
    os.kill(os.getpid(), _signal.SIGKILL)


def _maybe_kill(phase: str) -> None:
    """Fire an armed job_kill when the snapshot reaches its phase."""
    if _armed_kill["phase"] == phase:
        _armed_kill["phase"] = None
        kill_whole_job(phase=phase)


# ---------------------------------------------------------------------------
# Job manifest: ONE atomic commit per epoch (jax-free)
# ---------------------------------------------------------------------------

def epoch_dir_name(epoch: int) -> str:
    return f"{_EPOCH_PREFIX}{int(epoch)}"


def manifest_path(jobdir: str, epoch: int) -> str:
    return os.path.join(jobdir, f"{_MANIFEST_PREFIX}{int(epoch)}.json")


def commit_manifest(jobdir: str, manifest: dict) -> str:
    """THE commit point of a snapshot epoch: the manifest JSON lands via
    write-temp + fsync + rename, so it either exists complete or not at
    all — a job that dies mid-write leaves a ``.tmp`` that
    :func:`latest_committed_manifest` never looks at."""
    path = manifest_path(jobdir, manifest["epoch"])
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def _epoch_numbers(jobdir: str) -> list[int]:
    """Every epoch number with ANY on-disk trace (manifest or epoch dir,
    committed or torn) — what next_epoch must never collide with."""
    out = set()
    try:
        names = os.listdir(jobdir)
    except FileNotFoundError:
        return []
    for n in names:
        num = None
        if n.startswith(_MANIFEST_PREFIX) and n.endswith(".json"):
            num = n[len(_MANIFEST_PREFIX):-len(".json")]
        elif n.startswith(_EPOCH_PREFIX):
            num = n[len(_EPOCH_PREFIX):].split("_", 1)[0]
        if num and num.isdigit():
            out.add(int(num))
    return sorted(out)


def next_epoch(jobdir: str) -> int:
    nums = _epoch_numbers(jobdir)
    return (nums[-1] + 1) if nums else 1


def _manifest_complete(jobdir: str, m: dict) -> Optional[str]:
    """None when every file the manifest references exists (the epoch is
    restorable); else a human-readable reason it is torn."""
    if m.get("format") != MANIFEST_FORMAT:
        return f"unknown manifest format {m.get('format')!r}"
    edir = os.path.join(jobdir, epoch_dir_name(m.get("epoch", -1)))
    if not os.path.isdir(edir):
        return f"epoch dir {edir} missing"
    for s in m.get("servers", []):
        snap = os.path.join(edir, s.get("snapshot", ""))
        if not os.path.isfile(os.path.join(snap, "manifest.bin")):
            return f"server snapshot {snap} missing/incomplete"
        ptr = os.path.join(edir, f"LATEST_s{s.get('rank')}")
        if not os.path.isfile(ptr):
            return f"pointer {ptr} missing"
    for w in m.get("workers", []):
        wf = os.path.join(edir, w.get("state_file", ""))
        if not os.path.isfile(wf):
            return f"worker state {wf} missing"
    return None


def latest_committed_manifest(jobdir: str) -> Optional[tuple[dict, str]]:
    """The NEWEST epoch whose manifest is committed AND whose referenced
    files all exist: ``(manifest, epoch_dir)``; None when no epoch is
    restorable. Torn epochs — an uncommitted ``.tmp`` manifest, a
    manifest whose snapshot dirs never all landed, unparseable JSON —
    are skipped (with a stderr note), never selected: the core
    crash-consistency guarantee the job_kill soak pins."""
    candidates: list[tuple[int, str]] = []
    try:
        names = os.listdir(jobdir)
    except FileNotFoundError:
        return None
    for n in names:
        if n.startswith(_MANIFEST_PREFIX) and n.endswith(".json"):
            num = n[len(_MANIFEST_PREFIX):-len(".json")]
            if num.isdigit():
                candidates.append((int(num), os.path.join(jobdir, n)))
    for epoch, path in sorted(candidates, reverse=True):
        try:
            with open(path) as f:
                m = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"# hetusave: skipping unreadable manifest {path}: {e}",
                  file=sys.stderr)
            continue
        reason = _manifest_complete(jobdir, m)
        if reason is not None:
            print(f"# hetusave: skipping torn epoch {epoch}: {reason}",
                  file=sys.stderr)
            continue
        return m, os.path.join(jobdir, epoch_dir_name(epoch))
    return None


def list_epochs(jobdir: str) -> list[dict]:
    """Inventory for ``bin/hetusave --list``: every on-disk epoch with
    its committed/torn status and (when committed) step + world."""
    out = []
    for epoch in _epoch_numbers(jobdir):
        row: dict = {"epoch": epoch}
        path = manifest_path(jobdir, epoch)
        if not os.path.isfile(path):
            row["status"] = "torn (no committed manifest)"
        else:
            try:
                with open(path) as f:
                    m = json.load(f)
            except (OSError, json.JSONDecodeError) as e:
                row["status"] = f"torn (unreadable manifest: {e})"
            else:
                reason = _manifest_complete(jobdir, m)
                if reason is None:
                    row.update(status="committed", step=m.get("step"),
                               world=m.get("world"),
                               total_updates=m.get("total_updates"))
                else:
                    row["status"] = f"torn ({reason})"
        out.append(row)
    return out


def _write_pointer(dirpath: str, rank: int, snap_name: str) -> None:
    """LATEST_s<rank> pointer file, temp+rename like the server's own
    flip — a crash mid-write can never leave a torn pointer."""
    ptr = os.path.join(dirpath, f"LATEST_s{rank}")
    tmp = os.path.join(dirpath, f".LATEST_s{rank}.tmp")
    with open(tmp, "w") as f:
        f.write(snap_name)
    os.replace(tmp, ptr)


# ---------------------------------------------------------------------------
# Offline re-split: restore into a DIFFERENT world size (jax-free)
# ---------------------------------------------------------------------------

def _write_ps_manifest(path: str, counter: int, n_params: int) -> None:
    """A per-server snapshot manifest the native ``load_manifest``
    (csrc/ps/server.h) parses: magic, {version, counter, n_params,
    n_clients=0}. The resend-dedup ledger is deliberately EMPTY: a
    restored job's workers are fresh incarnations whose req_id streams
    start over, so no pre-crash resend can ever arrive — dropping the
    ledger loses nothing and can never mask a replay."""
    with open(path, "wb") as f:
        np.asarray([_PS_MANIFEST_MAGIC], np.int64).tofile(f)
        np.asarray([1, counter, n_params, 0], np.uint64).tofile(f)


def _split_counter(total: int, n: int) -> list[int]:
    """Distribute the job's total update counter over ``n`` restored
    shards, sum-preserving. The per-shard split is ARBITRARY (update
    counts are a per-server odometer, not per-key bookkeeping), so the
    even split here is just a convention; the invariant restore verifies
    is the SUM (:func:`verify_restored_job`)."""
    base = int(total) // n
    out = [base] * n
    out[0] += int(total) - base * n
    return out


def resplit_epoch(epoch_dir: str, dst_dir: str, new_ns: int,
                  manifest: dict) -> dict:
    """Re-shard one committed epoch's PS state from its recorded world
    size into ``new_ns`` key-range shards, offline (no cluster). Rows
    move WITH their optimizer slots and version counters bit-for-bit
    (``elastic.repartition_key`` — the same split formula the live
    worker partitioner uses, following the cross-replica optimizer
    sharding discipline of arXiv:2004.13336). Output layout matches a
    native snapshot root (``snap_s<r>_v1`` dirs + ``LATEST_s<r>``
    pointers + per-server manifests), so servers restore from it through
    the unchanged ``DMLC_PS_RESTORE_DIR`` path. Built in a temp dir and
    renamed into place: a torn re-split is never restore-eligible."""
    from .elastic import read_v2_shard, repartition_key, write_v2_shard
    old = sorted(manifest["servers"], key=lambda s: s["rank"])
    old_ns = len(old)
    new_ns = int(new_ns)
    if new_ns < 1:
        raise RecoveryError("re-split needs at least one server")
    tmp = dst_dir + ".tmp"
    shutil.rmtree(tmp, ignore_errors=True)
    snap_dirs = []
    for r in range(new_ns):
        d = os.path.join(tmp, f"snap_s{r}_v1")
        os.makedirs(d)
        snap_dirs.append(d)
    # key inventory: every param shard file in every old snapshot dir
    keys: set[int] = set()
    for s in old:
        sdir = os.path.join(epoch_dir, s["snapshot"])
        for n in os.listdir(sdir):
            if n.startswith("param_") and n.endswith(".bin"):
                k = n[len("param_"):].split("_", 1)[0]
                if k.isdigit():
                    keys.add(int(k))
    n_keys = 0
    for key in sorted(keys):
        shards = []
        for s in old:
            p = os.path.join(epoch_dir, s["snapshot"],
                             f"param_{key}_shard{s['rank']}.bin")
            if os.path.isfile(p):
                shards.append(read_v2_shard(p))
        if not shards:
            continue
        if len(shards) != old_ns:
            raise RecoveryError(
                f"param {key}: only {len(shards)}/{old_ns} shards present "
                f"in committed epoch — manifest claims a complete epoch")
        for r, d in enumerate(repartition_key(shards, new_ns)):
            write_v2_shard(
                os.path.join(snap_dirs[r], f"param_{key}_shard{r}.bin"), d)
        n_keys += 1
    counters = _split_counter(manifest["total_updates"], new_ns)
    for r in range(new_ns):
        _write_ps_manifest(os.path.join(snap_dirs[r], "manifest.bin"),
                           counters[r], n_keys)
        _write_pointer(tmp, r, f"snap_s{r}_v1")
    shutil.rmtree(dst_dir, ignore_errors=True)
    os.rename(tmp, dst_dir)
    return {"old_n_servers": old_ns, "new_n_servers": new_ns,
            "n_params": n_keys, "counters": counters,
            "total_updates": int(manifest["total_updates"]),
            "dst": dst_dir}


def prepare_restore(jobdir: str, n_servers: Optional[int] = None) -> dict:
    """Resolve a restore: pick the newest COMMITTED epoch and (when the
    target world size differs from the recorded one) build the offline
    re-split. Returns ``manifest``, ``epoch_dir``, the directory servers
    should restore from (``server_restore_dir`` — pass as
    DMLC_PS_RESTORE_DIR), the effective ``n_servers``, and the re-split
    report (None when the world size is unchanged). Raises
    :class:`RecoveryError` when nothing is restorable."""
    got = latest_committed_manifest(jobdir)
    if got is None:
        raise RecoveryError(
            f"no committed snapshot epoch under {jobdir} — torn epochs are "
            "never restore-eligible")
    m, epoch_dir = got
    ns_rec = int(m["world"]["n_servers"])
    ns = int(n_servers) if n_servers else ns_rec
    resplit = None
    restore_dir = epoch_dir
    if ns != ns_rec:
        restore_dir = f"{epoch_dir}_resplit{ns}"
        resplit = resplit_epoch(epoch_dir, restore_dir, ns, m)
    return {"manifest": m, "epoch_dir": epoch_dir,
            "server_restore_dir": restore_dir, "n_servers": ns,
            "resplit": resplit}


def verify_restored_job(manifest: dict, server_stats: list[dict]) -> dict:
    """The update-counter algebra gate BEFORE training resumes: the sum
    of the counters the restored servers actually loaded must equal the
    total the job manifest committed — anything else means a shard
    restored from the wrong epoch (or a torn re-split) and the job must
    not silently train on it."""
    restored = sum(max(int(s.get("restored_updates", -1)), 0)
                   for s in server_stats)
    want = int(manifest["total_updates"])
    ok = restored == want
    report = {"name": "restored_counter_algebra", "ok": ok,
              "restored_updates": restored, "manifest_updates": want,
              "epoch": manifest["epoch"]}
    if not ok:
        raise RecoveryError(
            f"restored update counters {restored} != committed total "
            f"{want} (epoch {manifest['epoch']}) — a shard restored from "
            "the wrong state; refusing to resume")
    return report


# ---------------------------------------------------------------------------
# The coordinator (lazy jax/hetu imports from here down)
# ---------------------------------------------------------------------------

def take_job_snapshot(ex, jobdir: str, *,
                      on_phase: Optional[Callable[[str], None]] = None,
                      timeout: float = 120.0) -> dict:
    """ONE globally consistent snapshot epoch of the whole job, riding
    the two-phase resize machinery as a quiesce barrier:

    1. drain this worker's async PS traffic, then propose an
       IDENTICAL-world resize (scheduler accepts; nothing migrates);
    2. park the worker's rank at the drain barrier (a side thread blocks
       in ``commit_resize`` while this thread coordinates) and poll
       until every survivor is parked;
    3. prove quiescence: ``pushes_ok == sum(updates - restored)`` across
       servers — every write this incarnation issued has been applied,
       nothing is in flight;
    4. drive each server's epoch-stamped ``kSnapshotNow`` (synchronous:
       snapshot dir published + LATEST pointer flipped before it
       replies), then COPY the pinned snapshot dirs into the epoch dir —
       the epoch owns immutable state the server's own prune can never
       touch, and restore pins exactly the manifest's snapshots;
    5. persist the worker: params, optimizer slots, qresid, dataloader
       cursors, RNG, plus the scheduler's era log;
    6. commit ONE job manifest atomically (:func:`commit_manifest`);
    7. ABORT the "resize" — every parked worker resumes under the old
       world, training state untouched.

    Any failure (or armed job_kill) aborts the barrier best-effort and
    re-raises; a death at any point leaves either the previous committed
    epoch or a torn epoch restore never selects.

    Multi-worker jobs are refused up front (:class:`RecoveryError`,
    before the barrier is proposed): this coordinator persists only its
    own rank's worker state, and an epoch missing ranks must never
    commit — it would pass every completeness check yet be unrestorable.
    """
    from . import ps as ps_pkg
    from .elastic import (commit_resize, finish_resize, propose_resize,
                          resize_log, resize_state, sched_addr_from_env)
    rt = getattr(ex, "ps_runtime", None)
    if rt is None:
        raise RecoveryError(
            "coordinated snapshot needs a PS job (comm_mode='PS')")
    snap_root = os.environ.get("DMLC_PS_SNAPSHOT_DIR")
    if not snap_root:
        raise RecoveryError(
            "coordinated snapshot needs servers launched with "
            "DMLC_PS_SNAPSHOT_DIR (heturun --ha / local_cluster(ha=True))")
    comm = ps_pkg.get_worker_communicate()
    host, port = sched_addr_from_env()
    rank = int(os.environ.get("WORKER_ID", "0"))
    step = int(ex.state.get("step", 0))
    t0 = time.perf_counter()

    def _phase(name: str) -> None:
        if on_phase is not None:
            on_phase(name)
        _maybe_kill(name)

    os.makedirs(jobdir, exist_ok=True)
    epoch = next_epoch(jobdir)
    edir = os.path.join(jobdir, epoch_dir_name(epoch))

    _phase("pre_barrier")
    rt.drain()
    st = resize_state(host, port)
    nw, ns = int(st["n_workers"]), int(st["n_servers"])
    if nw != 1:
        # this coordinator captures only its OWN rank's worker state; a
        # committed epoch for a bigger world would pass every on-disk
        # completeness check yet be unrestorable (load_worker_state raises
        # for every other rank). Refuse up front — before the barrier is
        # even proposed — rather than hand the operator an epoch that
        # looks restorable and is not. Multi-rank capture is the lift
        # required to relax this.
        raise RecoveryError(
            f"coordinated snapshot with {nw} workers is not supported: "
            "the coordinator persists only its own rank's state, so the "
            "committed epoch could never restore the other ranks — "
            "refusing to write an unrestorable epoch")
    propose_resize(host, port, nw, ns)

    parked: dict = {}

    def _park():
        try:
            parked["world"] = commit_resize(host, port, rank, step,
                                            timeout=timeout)
        except Exception as e:  # noqa: BLE001 — surfaced by coordinator
            parked["error"] = e

    th = threading.Thread(target=_park, name="hetusave-park", daemon=True)
    released = False
    try:
        th.start()
        deadline = time.monotonic() + timeout
        while True:
            st = resize_state(host, port)
            if st["pending_version"] and \
                    st["drain_count"] >= st["drain_needed"]:
                break
            if "error" in parked:
                raise RecoveryError(
                    f"drain barrier failed: {parked['error']!r}")
            if time.monotonic() > deadline:
                raise RecoveryError(
                    f"drain barrier timeout: {st['drain_count']}/"
                    f"{st['drain_needed']} survivors parked after "
                    f"{timeout}s")
            # tight poll: the whole drain window is on the snapshot's
            # critical path, and the bench's stall budget is single-digit
            # percent — 2ms keeps the barrier sub-step-scale while still
            # yielding the GIL to the parked commit thread
            time.sleep(0.002)

        # quiesce proof — the dedup-ledger accounting invariant, exact
        # because the nw == 1 gate above guarantees this worker's
        # pushes_ok is the WHOLE job's push count
        cs = comm.ClientStats()
        sstats = [comm.ServerStats(s) for s in range(ns)]
        applied = sum(int(s["updates"]) - max(int(s["restored_updates"]), 0)
                      for s in sstats)
        pushed = int(cs["pushes_ok"])
        if pushed != applied:
            raise RecoveryError(
                f"quiesce proof failed: client pushes_ok {pushed} != "
                f"servers' applied updates {applied} — in-flight writes "
                "survived the drain barrier; refusing to snapshot")

        shutil.rmtree(edir, ignore_errors=True)
        os.makedirs(edir)
        servers = []
        for s in range(ns):
            res = comm.SnapshotNow(s, epoch)
            if res["counter"] != res["updates"]:
                raise RecoveryError(
                    f"server {s} advanced mid-snapshot (covered "
                    f"{res['counter']} != live {res['updates']}) inside "
                    "the drain window — quiescence broken")
            name = f"snap_s{s}_v{res['version']}"
            shutil.copytree(os.path.join(snap_root, name),
                            os.path.join(edir, name))
            _write_pointer(edir, s, name)
            servers.append({"rank": s, "snapshot": name,
                            "version": int(res["version"]),
                            "counter": int(res["counter"]),
                            "updates": int(res["updates"])})
            if s == 0:
                _phase("server_write")
        if ns == 1:
            # the server_write window must exist even with one server
            pass

        from .resilience import capture_executor_state
        wstate = capture_executor_state(ex)
        # hetuq error-feedback residuals ride along (Executor._save keeps
        # them for the same reason: a resumed run's first quantized steps
        # must not re-pay absorbed compression error)
        wstate["qresid"] = {
            str(i): np.asarray(ex.state["qresid"][id(n)])
            for i, n in enumerate(ex._qresid_ordered())}
        wstate["client_stats"] = cs
        wfile = f"worker_{rank}.pkl"
        with open(os.path.join(edir, wfile), "wb") as f:
            pickle.dump(wstate, f)
        eras = resize_log(host, port)

        _phase("pre_commit")
        wall_ms = round((time.perf_counter() - t0) * 1e3, 3)
        manifest = {
            "format": MANIFEST_FORMAT, "epoch": epoch, "step": step,
            "world": {"n_workers": nw, "n_servers": ns,
                      "world_version": int(st["world_version"])},
            "servers": servers,
            "total_updates": sum(s["counter"] for s in servers),
            "pushes_ok": pushed,
            "workers": [{"rank": rank, "state_file": wfile}],
            "eras": eras,
            "wall_ms": wall_ms,
        }
        commit_manifest(jobdir, manifest)
        _phase("post_commit")

        # snapshot=True tags this abort as the release of a COMMITTED
        # epoch — the scheduler counts snapshot_epochs from the tag, so a
        # failed snapshot's best-effort abort (the except path below)
        # never inflates the counter
        finish_resize(host, port, abort=True, snapshot=True)
        released = True
        th.join(timeout=timeout)
        if "error" in parked:
            raise RecoveryError(
                f"parked worker failed to release: {parked['error']!r}")
        _export_snapshot_telemetry(epoch, wall_ms)
        return manifest
    except BaseException:
        # best-effort release of every parked worker before propagating —
        # a failed snapshot must not leave the job wedged at the barrier
        if not released:
            try:
                finish_resize(host, port, abort=True)
            except Exception:  # noqa: BLE001 — scheduler may be gone
                pass
            th.join(timeout=5.0)
        raise


def _export_snapshot_telemetry(epoch: int, wall_ms: float) -> None:
    """hetu_job_epoch + snapshot-duration gauges through the telemetry
    bus (no-op when telemetry is off). Never raises."""
    try:
        from . import telemetry as _telemetry
        tel = _telemetry.get()
        if tel is None:
            return
        tel.metrics.gauge("hetu_job_epoch").set(int(epoch))
        tel.metrics.gauge("hetu_snapshot_last_ms").set(float(wall_ms))
        tel.metrics.histogram("hetu_snapshot_duration_ms").observe(
            float(wall_ms))
    except Exception:  # noqa: BLE001 — observability only
        pass


class JobCheckpointer:
    """The Supervisor-facing handle: ``save(ex, step)`` takes one
    coordinated epoch into ``jobdir`` and prunes old ones; wire it as
    ``Supervisor(job_ckptr=...)`` so a SIGTERM grace window upgrades the
    worker-local emergency save to a globally consistent epoch, and/or
    call :meth:`maybe_save` at a step cadence.

    ``barrier_timeout`` bounds the drain barrier (and every other wait
    inside :func:`take_job_snapshot`) for cadence saves; ``None`` means
    take_job_snapshot's 120s default. :meth:`save_preempt` — the
    Supervisor's SIGTERM-grace upgrade path — instead bounds the barrier
    by the preemption grace budget (``grace_s`` or the
    ``HETU_PREEMPT_GRACE_S`` env var, defaulting to heturun's 30s
    window) minus 5s of headroom (floor 2s): a coordinated save
    attempted inside a grace window must fail with time LEFT, so the
    worker-local fallback save still lands before the SIGKILL."""

    #: headroom (seconds) reserved inside the grace window for the
    #: worker-local fallback save after a hung/failed barrier
    GRACE_HEADROOM_S = 5.0

    def __init__(self, jobdir: str, every: Optional[int] = None,
                 keep: int = 2,
                 on_phase: Optional[Callable[[str], None]] = None,
                 barrier_timeout: Optional[float] = None,
                 grace_s: Optional[float] = None):
        self.jobdir = jobdir
        self.every = every
        self.keep = max(1, int(keep))
        self.on_phase = on_phase
        if grace_s is None:
            env = os.environ.get("HETU_PREEMPT_GRACE_S")
            # heturun's SIGTERM grace default is 30s; assume it rather
            # than let a hung barrier ride a 120s default into SIGKILL
            grace_s = float(env) if env else 30.0
        self.grace_s = float(grace_s)
        self.barrier_timeout = barrier_timeout
        self.last_manifest: Optional[dict] = None

    def grace_timeout(self) -> float:
        """Barrier bound for a save inside the preemption grace window."""
        t = max(2.0, self.grace_s - self.GRACE_HEADROOM_S)
        if self.barrier_timeout is not None:
            t = min(t, float(self.barrier_timeout))
        return t

    def save(self, ex, step: int, *,
             timeout: Optional[float] = None) -> dict:
        t = timeout if timeout is not None else self.barrier_timeout
        kw = {"timeout": float(t)} if t is not None else {}
        m = take_job_snapshot(ex, self.jobdir, on_phase=self.on_phase,
                              **kw)
        self.last_manifest = m
        self._prune()
        return m

    def save_preempt(self, ex, step: int) -> dict:
        """The SIGTERM grace-window save: same epoch, but the drain
        barrier is bounded a few seconds below the known grace period so
        the caller's except-based worker-local fallback still runs."""
        return self.save(ex, step, timeout=self.grace_timeout())

    def maybe_save(self, ex, step: int) -> Optional[dict]:
        if self.every and (int(step) + 1) % int(self.every) == 0:
            return self.save(ex, step)
        return None

    def _prune(self) -> None:
        """Keep the newest ``keep`` COMMITTED epochs; drop older ones and
        any torn epoch older than the newest committed one (a torn epoch
        NEWER than it is evidence from a crash-in-progress — left for
        post-mortems, restore skips it anyway)."""
        committed = [e["epoch"] for e in list_epochs(self.jobdir)
                     if e["status"] == "committed"]
        if not committed:
            return
        survivors = set(committed[-self.keep:])
        newest = committed[-1]
        for epoch in _epoch_numbers(self.jobdir):
            if epoch in survivors or epoch > newest:
                continue
            for path in (manifest_path(self.jobdir, epoch),
                         os.path.join(self.jobdir,
                                      epoch_dir_name(epoch))):
                if os.path.isdir(path):
                    shutil.rmtree(path, ignore_errors=True)
                else:
                    try:
                        os.remove(path)
                    except FileNotFoundError:
                        pass
            # stale re-splits of a pruned epoch go with it
            base = os.path.join(self.jobdir, epoch_dir_name(epoch))
            for n in list(os.listdir(self.jobdir)):
                full = os.path.join(self.jobdir, n)
                if full.startswith(base + "_resplit"):
                    shutil.rmtree(full, ignore_errors=True)


def load_worker_state(ex, manifest: dict, epoch_dir: str) -> dict:
    """Re-impose this rank's persisted state onto a freshly built
    executor (params, optimizer slots, op state, dataloader cursors +
    RNG, step, qresid). The executor must have been built with
    HETU_ELASTIC_JOIN=1 so its init did not overwrite the restored PS
    tables. Returns the raw state dict (the soak reads its
    client_stats)."""
    from .resilience import load_executor_state
    rank = int(os.environ.get("WORKER_ID", "0"))
    rec = next((w for w in manifest["workers"] if int(w["rank"]) == rank),
               None)
    if rec is None:
        raise RecoveryError(
            f"manifest epoch {manifest['epoch']} has no state for worker "
            f"rank {rank}")
    with open(os.path.join(epoch_dir, rec["state_file"]), "rb") as f:
        state = pickle.load(f)
    load_executor_state(ex, state)
    qr = state.get("qresid", {})
    if qr:
        import jax.numpy as jnp
        for i, n in enumerate(ex._qresid_ordered()):
            if str(i) in qr:
                ex.state["qresid"][id(n)] = jnp.asarray(qr[str(i)],
                                                        jnp.float32)
    return state


def restore_executor_from_env(ex, jobdir: str) -> dict:
    """``heturun --restore`` worker leg (Executor calls this when the
    launcher set HETU_RESTORE_DIR): re-resolve the newest committed
    epoch — deterministic, so every rank and the launcher agree without
    another coordination round — re-impose this rank's state, and gate
    on the counter algebra across the restored servers."""
    got = latest_committed_manifest(jobdir)
    if got is None:
        raise RecoveryError(
            f"HETU_RESTORE_DIR={jobdir}: no committed snapshot epoch")
    m, edir = got
    state = load_worker_state(ex, m, edir)
    from . import ps as ps_pkg
    comm = ps_pkg.get_worker_communicate()
    ns = int(os.environ.get("DMLC_NUM_SERVER", "0")) or \
        int(m["world"]["n_servers"])
    verify_restored_job(m, [comm.ServerStats(s) for s in range(ns)])
    print(f"# hetusave: worker restored from epoch {m['epoch']} "
          f"(step {m['step']}, {m['total_updates']} updates verified)",
          file=sys.stderr)
    return state


# ---------------------------------------------------------------------------
# Soak driver (live local_cluster job; modeled on hetu_tpu.chaos.run_job)
# ---------------------------------------------------------------------------

#: the soak job's fixed shape (tiny: one seed's full twin+kill+restore
#: cycle must stay in CI time)
SOAK_ROWS, SOAK_WIDTH, SOAK_SLOTS, SOAK_BATCH = 60, 8, 4, 16


def _soak_batch(seed: int, step: int):
    """Batches are a PURE function of (seed, step): a restored leg
    regenerates exactly the batches the dead job would have consumed —
    the determinism the bit-identity proof needs."""
    rng = np.random.RandomState((int(seed) * 1000003 + int(step))
                                % (2 ** 31 - 1))
    bidx = rng.randint(0, SOAK_ROWS,
                       (SOAK_BATCH, SOAK_SLOTS)).astype(np.float32)
    by = ((bidx >= SOAK_ROWS // 2).sum(axis=1) >
          SOAK_SLOTS // 2).reshape(-1, 1).astype(np.float32)
    return bidx, by


class _scoped_env:
    """Set env vars for one leg, restoring previous values on exit (the
    soak runs several clusters in one process — a leaked DMLC_PS_*
    would contaminate the next leg)."""

    def __init__(self, **kv):
        self.kv = {k: v for k, v in kv.items() if v is not None}
        self.saved: dict = {}

    def __enter__(self):
        for k, v in self.kv.items():
            self.saved[k] = os.environ.get(k)
            os.environ[k] = str(v)
        return self

    def __exit__(self, *exc):
        for k, old in self.saved.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old


def run_leg(seed: int, total_steps: int, n_servers: int, jobdir: str,
            snapshot_root: str, *, snap_steps=(), kill_phase=None,
            kill_at_snap: int = 0, restore: bool = False) -> dict:
    """One life of the job. Fresh start or restore-from-jobdir, train to
    ``total_steps`` on (seed, step)-pure batches, coordinated snapshots
    after completing each step in ``snap_steps``; ``kill_phase`` arms a
    simulated whole-job death (every cluster process SIGKILLed, then
    :class:`JobKilled`) at that phase of snapshot number
    ``kill_at_snap`` (0-based among this leg's snapshots)."""
    from .ps.local_cluster import get_live_cluster, local_cluster
    from . import ps as ps_pkg

    prep = prepare_restore(jobdir, n_servers) if restore else None
    snap_count = {"n": 0}

    def on_phase(phase: str) -> None:
        if kill_phase is not None and phase == kill_phase \
                and snap_count["n"] == kill_at_snap:
            for p in get_live_cluster().get("procs", []):
                try:
                    p.kill()
                except Exception:  # noqa: BLE001
                    pass
            raise JobKilled(f"job_kill at {phase} of snapshot "
                            f"#{kill_at_snap}")

    env = {"DMLC_PS_SNAPSHOT_DIR": snapshot_root}
    if restore:
        env["DMLC_PS_RESTORE_DIR"] = prep["server_restore_dir"]
        env["HETU_ELASTIC_JOIN"] = "1"
    killed = None
    with _scoped_env(**env):
        with local_cluster(n_servers=n_servers, n_workers=1):
            import hetu_tpu as ht
            ps_pkg.worker_init()
            comm = ps_pkg.get_worker_communicate()
            embed = ht.init.random_normal(
                (SOAK_ROWS, SOAK_WIDTH), stddev=0.1, name="save_embed",
                is_embed=True)
            idx = ht.Variable(name="idx", trainable=False)
            y_ = ht.Variable(name="y_", trainable=False)
            vec = ht.embedding_lookup_op(embed, idx)
            flat = ht.array_reshape_op(vec, (-1, SOAK_SLOTS * SOAK_WIDTH))
            w = ht.init.xavier_uniform((SOAK_SLOTS * SOAK_WIDTH, 1),
                                       name="save_w")
            prob = ht.sigmoid_op(ht.matmul_op(flat, w))
            loss = ht.reduce_mean_op(
                ht.binarycrossentropy_op(prob, y_), [0])
            train_op = ht.optim.SGDOptimizer(0.1).minimize(loss)
            ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0),
                             seed=0, comm_mode="PS", prefetch=False)
            losses, consumed, restored_report = [], [], None
            start = 0
            try:
                if restore:
                    load_worker_state(ex, prep["manifest"],
                                      prep["epoch_dir"])
                    sstats = [comm.ServerStats(s)
                              for s in range(n_servers)]
                    restored_report = verify_restored_job(
                        prep["manifest"], sstats)
                    start = int(prep["manifest"]["step"])
                    ex.state["step"] = start
                for step in range(start, int(total_steps)):
                    bidx, by = _soak_batch(seed, step)
                    out = ex.run("train", feed_dict={idx: bidx, y_: by})
                    losses.append(float(out[0].asnumpy()))
                    consumed.append(step * SOAK_BATCH +
                                    np.arange(SOAK_BATCH))
                    if (step + 1) in snap_steps:
                        take_job_snapshot(ex, jobdir, on_phase=on_phase)
                        snap_count["n"] += 1
                rt = ex.ps_runtime
                rt.drain()
                finals = []
                for p in sorted(rt.params.values(),
                                key=lambda p: p.ps_id):
                    if p.sparse:
                        finals.append(rt.pull_sparse_rows(
                            p, np.arange(SOAK_ROWS)))
                    else:
                        finals.append(rt.pull_dense_value(p))
                client_stats = comm.ClientStats()
                server_stats = [comm.ServerStats(s)
                                for s in range(n_servers)]
            except JobKilled as e:
                killed = str(e)
                finals, client_stats, server_stats = [], {}, []
            finally:
                try:
                    ex.close()
                except Exception:  # noqa: BLE001 — cluster may be dead
                    pass
                try:
                    ps_pkg.worker_finish()
                except Exception:  # noqa: BLE001
                    pass
            return {"losses": losses, "finals": finals,
                    "consumed": (np.concatenate(consumed) if consumed
                                 else np.zeros(0, np.int64)),
                    "start": start, "killed": killed,
                    "client_stats": client_stats,
                    "server_stats": server_stats,
                    "restored": restored_report,
                    "prep": prep}


def _check_restored_accounting(client_stats: dict,
                               server_stats: list[dict]) -> dict:
    """Exactly-once write accounting for a RESTORED leg: the fresh
    incarnation's ``pushes_ok`` must equal the updates applied SINCE
    restore (live counter minus restored stamp) summed over servers —
    a lost update or a replayed pre-crash resend breaks the equality."""
    applied = sum(int(s["updates"]) - max(int(s["restored_updates"]), 0)
                  for s in server_stats)
    pushed = int(client_stats.get("pushes_ok", -1))
    ok = pushed == applied
    report = {"name": "restored_update_accounting", "ok": ok,
              "pushes_ok": pushed, "applied_since_restore": applied}
    if not ok:
        from .chaos import InvariantViolation
        raise InvariantViolation(
            f"restored-leg accounting broken: pushes_ok {pushed} != "
            f"updates applied since restore {applied}")
    return report


def run_soak(seed: int, steps: int = 12, n_servers: int = 2,
             kill_phase: str = "pre_commit",
             restore_n_servers: Optional[int] = None,
             jobdir: Optional[str] = None) -> dict:
    """One seeded acceptance cycle: fault-free twin (no snapshots), then
    a life that snapshots twice and is whole-job-killed at
    ``kill_phase`` of the SECOND snapshot, then the restore leg —
    optionally into a different world size (``restore_n_servers``).
    Proves, per docs/FAULT_TOLERANCE.md "Coordinated job snapshots":

    - restore selects the newest COMMITTED epoch only (the kill leaves a
      torn epoch 2 for every phase except post_commit, and the restored
      step pins which epoch was chosen);
    - the restored lineage is loss-bit-identical to the twin and its
      final params match bit-for-bit;
    - sample consumption is exactly-once along the committed lineage;
    - update-counter algebra holds across death and restore;
    - a world-size-changed restore re-splits optimizer state bit-equal.

    Requires HETU_TEST_MODE (set by bin/hetusave like bin/hetuchaos).
    Raises on any broken invariant; returns the full report dict."""
    import tempfile

    from .chaos import (InvariantViolation, check_bit_identical,
                        check_exactly_once_consumption)
    if kill_phase not in PHASES:
        raise ValueError(f"kill_phase {kill_phase!r} not in {PHASES}")
    steps = int(steps)
    snap1, snap2 = max(1, steps // 3), max(2, (2 * steps) // 3)
    owned = jobdir is None
    jobdir = jobdir or tempfile.mkdtemp(prefix="hetusave_job_")
    snaproot = tempfile.mkdtemp(prefix="hetusave_snap_")
    restore_ns = int(restore_n_servers or n_servers)
    try:
        twin = run_leg(seed, steps, n_servers, jobdir + "_twin", snaproot)
        assert twin["killed"] is None

        leg1 = run_leg(seed, steps, n_servers, jobdir, snaproot,
                       snap_steps=(snap1, snap2), kill_phase=kill_phase,
                       kill_at_snap=1)
        if leg1["killed"] is None:
            raise InvariantViolation(
                f"kill at {kill_phase} never fired (snapshots at "
                f"{snap1}/{snap2}, {steps} steps)")

        # the committed lineage the restore must land on
        expect_step = snap2 if kill_phase == "post_commit" else snap1
        got = latest_committed_manifest(jobdir)
        if got is None:
            raise InvariantViolation("no committed epoch after the kill")
        if int(got[0]["step"]) != expect_step:
            raise InvariantViolation(
                f"restore selected step {got[0]['step']}, expected "
                f"{expect_step} — a torn epoch was chosen after a "
                f"{kill_phase} kill")
        torn = [e for e in list_epochs(jobdir)
                if e["status"] != "committed"]
        if kill_phase in ("server_write", "pre_commit") and not torn:
            raise InvariantViolation(
                f"a {kill_phase} kill must leave a torn epoch on disk "
                "(it proves torn-epoch skipping) — none found")

        leg2 = run_leg(seed, steps, restore_ns, jobdir, snaproot,
                       restore=True)
        assert leg2["killed"] is None and leg2["start"] == expect_step

        checks = [
            leg2["restored"],
            _check_restored_accounting(leg2["client_stats"],
                                       leg2["server_stats"]),
            check_bit_identical(
                [np.asarray(leg2["losses"])],
                [np.asarray(twin["losses"][expect_step:])],
                "restored-lineage losses"),
            check_exactly_once_consumption(
                leg2["consumed"],
                twin["consumed"][expect_step * SOAK_BATCH:]),
            check_bit_identical(leg2["finals"], twin["finals"],
                                "final params"),
        ]
        resplit_check = None
        if restore_ns != n_servers:
            resplit_check = _check_resplit_bit_equal(
                leg2["prep"], n_servers)
            checks.append(resplit_check)
        report = {
            "seed": int(seed), "steps": steps, "kill_phase": kill_phase,
            "n_servers": n_servers, "restore_n_servers": restore_ns,
            "snap_steps": [snap1, snap2],
            "restored_step": expect_step,
            "epochs": list_epochs(jobdir),
            "checks": checks,
            "final_loss": leg2["losses"][-1] if leg2["losses"] else None,
            "ok": all(c["ok"] for c in checks),
        }
        return report
    finally:
        shutil.rmtree(snaproot, ignore_errors=True)
        if owned:
            shutil.rmtree(jobdir, ignore_errors=True)
            shutil.rmtree(jobdir + "_twin", ignore_errors=True)


def _check_resplit_bit_equal(prep: dict, old_ns: int) -> dict:
    """The world-size-changed restore's optimizer-state proof: for every
    param, the concatenation of the re-split shards (data + accum +
    accum2 + versions) must be BIT-EQUAL to the concatenation of the
    committed epoch's original shards — rows moved, nothing changed."""
    from .chaos import InvariantViolation
    from .elastic import read_v2_shard
    m = prep["manifest"]
    edir, rdir = prep["epoch_dir"], prep["server_restore_dir"]
    new_ns = prep["n_servers"]
    old = sorted(m["servers"], key=lambda s: s["rank"])
    keys: set[int] = set()
    for s in old:
        for n in os.listdir(os.path.join(edir, s["snapshot"])):
            if n.startswith("param_") and n.endswith(".bin"):
                keys.add(int(n[len("param_"):].split("_", 1)[0]))
    bad = []
    for key in sorted(keys):
        olds = [read_v2_shard(os.path.join(
            edir, s["snapshot"], f"param_{key}_shard{s['rank']}.bin"))
            for s in old]
        news = [read_v2_shard(os.path.join(
            rdir, f"snap_s{r}_v1", f"param_{key}_shard{r}.bin"))
            for r in range(new_ns)]
        for sect in ("data", "accum", "accum2", "versions"):
            a = np.concatenate([s[sect] for s in olds])
            b = np.concatenate([s[sect] for s in news])
            if a.shape != b.shape or (a.tobytes() != b.tobytes()):
                bad.append((key, sect))
    ok = not bad
    report = {"name": "resplit_bit_equal", "ok": ok,
              "n_params": len(keys), "old_n_servers": old_ns,
              "new_n_servers": new_ns, "mismatches": bad}
    if not ok:
        raise InvariantViolation(
            f"re-split changed optimizer state bits: {bad}")
    return report


# ---------------------------------------------------------------------------
# jax-free self-test (bin/hetusave --check)
# ---------------------------------------------------------------------------

def _fake_epoch(jobdir: str, epoch: int, step: int, n_servers: int = 1,
                commit: bool = True, torn: Optional[str] = None) -> dict:
    """A synthetic epoch for the manifest-selection tests: real files,
    no cluster. ``torn`` drops one referenced piece."""
    edir = os.path.join(jobdir, epoch_dir_name(epoch))
    servers = []
    for r in range(n_servers):
        name = f"snap_s{r}_v{epoch}"
        d = os.path.join(edir, name)
        os.makedirs(d, exist_ok=True)
        _write_ps_manifest(os.path.join(d, "manifest.bin"), 10 * epoch, 1)
        _write_pointer(edir, r, name)
        servers.append({"rank": r, "snapshot": name, "version": epoch,
                        "counter": 10 * epoch, "updates": 10 * epoch})
    wfile = "worker_0.pkl"
    with open(os.path.join(edir, wfile), "wb") as f:
        pickle.dump({"step": step}, f)
    m = {"format": MANIFEST_FORMAT, "epoch": epoch, "step": step,
         "world": {"n_workers": 1, "n_servers": n_servers,
                   "world_version": 1},
         "servers": servers,
         "total_updates": sum(s["counter"] for s in servers),
         "workers": [{"rank": 0, "state_file": wfile}], "eras": []}
    if torn == "manifest.bin":
        os.remove(os.path.join(edir, servers[0]["snapshot"],
                               "manifest.bin"))
    elif torn == "worker":
        os.remove(os.path.join(edir, wfile))
    elif torn == "pointer":
        os.remove(os.path.join(edir, "LATEST_s0"))
    if commit:
        commit_manifest(jobdir, m)
    elif torn == "tmp_manifest":
        # a commit that died mid-write: .tmp exists, manifest does not
        with open(manifest_path(jobdir, epoch) + ".tmp", "w") as f:
            f.write(json.dumps(m)[: len(json.dumps(m)) // 2])
    return m


def self_check(out=None) -> int:
    """CI smoke with no cluster and no jax: manifest commit atomicity +
    newest-committed-only selection (torn epochs of every shape
    skipped), epoch numbering, re-split bit-equality + counter algebra,
    the per-server manifest writer's binary layout, phase validation,
    and the job_kill spec-grammar round trip. Returns 0 on success."""
    import struct
    import tempfile
    out = out or sys.stdout

    with tempfile.TemporaryDirectory(prefix="hetusave_check_") as td:
        # -- manifest selection: newest COMMITTED only ---------------------
        assert latest_committed_manifest(td) is None
        _fake_epoch(td, 1, step=4)
        got = latest_committed_manifest(td)
        assert got is not None and got[0]["epoch"] == 1
        # epoch 2 torn in each shape: never selected over committed 1
        for torn in ("tmp_manifest", "manifest.bin", "worker", "pointer"):
            shutil.rmtree(os.path.join(td, epoch_dir_name(2)),
                          ignore_errors=True)
            for leftover in (manifest_path(td, 2),
                             manifest_path(td, 2) + ".tmp"):
                if os.path.exists(leftover):
                    os.remove(leftover)
            _fake_epoch(td, 2, step=8, commit=torn != "tmp_manifest",
                        torn=torn)
            got = latest_committed_manifest(td)
            assert got is not None and got[0]["epoch"] == 1, torn
        # unparseable JSON: skipped, not fatal
        with open(manifest_path(td, 3), "w") as f:
            f.write("{not json")
        assert latest_committed_manifest(td)[0]["epoch"] == 1
        os.remove(manifest_path(td, 3))
        # a COMMITTED epoch 2 wins
        shutil.rmtree(os.path.join(td, epoch_dir_name(2)))
        os.remove(manifest_path(td, 2))
        _fake_epoch(td, 2, step=8)
        assert latest_committed_manifest(td)[0]["epoch"] == 2
        # next_epoch never collides with torn leftovers
        assert next_epoch(td) == 3
        rows = list_epochs(td)
        assert [r["status"] for r in rows] == ["committed", "committed"]

    # -- re-split: bit-equality + counter algebra --------------------------
    from .elastic import read_v2_shard, write_v2_shard, _range_split
    with tempfile.TemporaryDirectory(prefix="hetusave_check_") as td:
        edir = os.path.join(td, epoch_dir_name(1))
        rng = np.random.RandomState(7)
        rows, width = 10, 3
        full = {
            "data": rng.randn(rows * width).astype(np.float32),
            "accum": rng.randn(rows * width).astype(np.float32),
            "accum2": rng.randn(rows * width).astype(np.float32),
            "versions": np.arange(rows, dtype=np.int64)}
        servers = []
        for r, (lo, hi) in enumerate(_range_split(rows, 2)):
            name = f"snap_s{r}_v1"
            d = os.path.join(edir, name)
            os.makedirs(d)
            sl = slice(lo * width, hi * width)
            write_v2_shard(
                os.path.join(d, f"param_5_shard{r}.bin"),
                {"kind": 1, "rows": hi - lo, "len": (hi - lo) * width,
                 "width": width, "otype": 4, "step": 9,
                 "lrs": np.asarray([0.1], np.float32),
                 "data": full["data"][sl], "accum": full["accum"][sl],
                 "accum2": full["accum2"][sl],
                 "versions": full["versions"][lo:hi]})
            _write_ps_manifest(os.path.join(d, "manifest.bin"), 21, 1)
            _write_pointer(edir, r, name)
            servers.append({"rank": r, "snapshot": name, "version": 1,
                            "counter": 21, "updates": 21})
        m = {"format": 1, "epoch": 1, "step": 9,
             "world": {"n_workers": 1, "n_servers": 2, "world_version": 1},
             "servers": servers, "total_updates": 42,
             "workers": [], "eras": []}
        for new_ns in (1, 3):
            dst = os.path.join(td, f"re{new_ns}")
            rep = resplit_epoch(edir, dst, new_ns, m)
            assert rep["n_params"] == 1
            assert sum(rep["counters"]) == 42  # sum-preserving
            news = [read_v2_shard(os.path.join(
                dst, f"snap_s{r}_v1", f"param_5_shard{r}.bin"))
                for r in range(new_ns)]
            for sect in ("data", "accum", "accum2", "versions"):
                cat = np.concatenate([s[sect] for s in news])
                assert cat.tobytes() == full[sect].tobytes(), sect
            # native-manifest layout: magic + {version, counter, n, 0}
            with open(os.path.join(dst, "snap_s0_v1", "manifest.bin"),
                      "rb") as f:
                raw = f.read()
            magic, = struct.unpack("<q", raw[:8])
            version, counter, n_params, n_clients = struct.unpack(
                "<4Q", raw[8:40])
            assert magic == _PS_MANIFEST_MAGIC and version == 1
            assert counter == rep["counters"][0]
            assert n_params == 1 and n_clients == 0
            # pointer files name existing dirs (atomic flip contract)
            for r in range(new_ns):
                with open(os.path.join(dst, f"LATEST_s{r}")) as f:
                    assert os.path.isdir(os.path.join(dst,
                                                      f.read().strip()))
        # counter-algebra gate: accept exact, refuse drift
        verify_restored_job(m, [{"restored_updates": 21},
                                {"restored_updates": 21}])
        try:
            verify_restored_job(m, [{"restored_updates": 21},
                                    {"restored_updates": 20}])
            raise AssertionError("counter drift not caught")
        except RecoveryError:
            pass
        try:
            prepare_restore(os.path.join(td, "nowhere"))
            raise AssertionError("missing jobdir not caught")
        except RecoveryError:
            pass

    # -- phases + the job_kill spec grammar --------------------------------
    assert PHASES == ("pre_barrier", "server_write", "pre_commit",
                      "post_commit")
    try:
        arm_job_kill("mid_flight")
        raise AssertionError("bad phase accepted")
    except ValueError:
        pass
    arm_job_kill("pre_commit")
    assert armed_kill_phase() == "pre_commit"
    _armed_kill["phase"] = None
    from .resilience import FaultInjector
    fi = FaultInjector("job_kill@3:server_write")
    assert fi.entries[0]["arg"] == "server_write"
    assert FaultInjector("job_kill@2").entries[0]["arg"] is None
    for bad in ("job_kill@2:mid_flight", "job_murder@2"):
        try:
            FaultInjector(bad)
            raise AssertionError(f"{bad!r} accepted")
        except ValueError as e:
            # rejections must NAME the legal vocabulary
            assert ("pre_barrier" in str(e)) or ("nan_grads" in str(e))

    print("hetusave --check: manifest atomicity + newest-committed "
          "selection, re-split bit-equality, counter algebra, and the "
          "job_kill grammar OK", file=out)
    return 0


# ---------------------------------------------------------------------------
# CLI (bin/hetusave)
# ---------------------------------------------------------------------------

def main(argv=None) -> int:
    """``hetusave --seed S``: live whole-job-kill soak (twin + killed
    life + restore, every invariant checked). ``--seeds`` rotates the
    kill through every snapshot phase; ``--resize N`` restores the last
    seed into N servers; ``--check`` is the jax-free CI self-test;
    ``--list DIR`` inventories a job's epochs; ``--restore-prep DIR``
    resolves (and, with --servers, re-splits) the newest committed
    epoch without starting a job. Exit 0 = green."""
    import argparse
    import json as _json

    ap = argparse.ArgumentParser(
        prog="hetusave",
        description="coordinated job-wide consistent checkpoints + "
                    "whole-job crash recovery (docs/FAULT_TOLERANCE.md)")
    ap.add_argument("--check", action="store_true",
                    help="jax-free self-test (CI smoke); exit 0/1")
    ap.add_argument("--list", metavar="DIR", default=None,
                    help="inventory a job dir's epochs (committed/torn)")
    ap.add_argument("--restore-prep", metavar="DIR", default=None,
                    help="resolve the newest committed epoch (with "
                         "--servers N: build the re-split) and print it")
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--seeds", type=str, default=None,
                    help="comma-separated seed list (overrides --seed); "
                         "kill phase rotates per seed")
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--servers", type=int, default=2)
    ap.add_argument("--phase", choices=PHASES, default=None,
                    help="kill phase (default: rotate through all)")
    ap.add_argument("--resize", type=int, default=None,
                    help="restore the LAST seed into this many servers "
                         "(world-size-changed recovery)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable per-seed reports on stdout")
    args = ap.parse_args(argv)

    if args.check:
        return self_check()
    if args.list is not None:
        for row in list_epochs(args.list):
            print(_json.dumps(row, sort_keys=True))
        return 0
    if args.restore_prep is not None:
        prep = prepare_restore(args.restore_prep, args.servers
                               if args.resize is None else args.resize)
        print(_json.dumps(
            {"epoch": prep["manifest"]["epoch"],
             "step": prep["manifest"]["step"],
             "server_restore_dir": prep["server_restore_dir"],
             "n_servers": prep["n_servers"],
             "resplit": prep["resplit"]}, sort_keys=True))
        return 0

    os.environ.setdefault("HETU_TEST_MODE", "1")
    seeds = ([int(s) for s in args.seeds.split(",")]
             if args.seeds else [args.seed])
    rc = 0
    for i, seed in enumerate(seeds):
        phase = args.phase or PHASES[i % len(PHASES)]
        resize = (args.resize if args.resize is not None
                  and i == len(seeds) - 1 else None)
        try:
            report = run_soak(seed, steps=args.steps,
                              n_servers=args.servers, kill_phase=phase,
                              restore_n_servers=resize)
        except Exception as e:  # noqa: BLE001 — report and fail the seed
            print(f"seed {seed} [{phase}]: FAIL — {e}", file=sys.stderr)
            rc = 1
            continue
        if args.json:
            print(_json.dumps(report, default=str, sort_keys=True))
        else:
            print(f"seed {seed} [{phase}"
                  f"{f' -> {resize} servers' if resize else ''}]: "
                  f"restored step {report['restored_step']}, "
                  f"{len(report['checks'])} checks green, final loss "
                  f"{report['final_loss']:.6f}")
        if not report["ok"]:
            rc = 1
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
