"""``heturun`` — cluster launcher (reference ``python/runner.py`` +
``bin/heturun``).

Usage: ``heturun -c cluster.yml python train.py [args...]``

The yaml lists nodes with host/servers/workers/chief (reference
runner.py:158-184). On a single machine, PS roles run as local processes and
workers as subprocesses with WORKER_ID env. Across machines, remote roles are
started over ``ssh`` (the reference uses paramiko + mpirun; TPU pods use one
process per host, so workers get ``jax.distributed`` coordinator env vars
instead of an MPI world).
"""
from __future__ import annotations

import argparse
import multiprocessing
import os
import shlex
import signal
import socket
import subprocess
import sys
import time

import yaml

# mirrored from hetu_tpu.resilience (EXIT_PREEMPTED/EXIT_WATCHDOG) without
# importing the package here: the launcher parent must stay jax-free
EXIT_PREEMPTED = 75
EXIT_WATCHDOG = 85

_procs: list = []
_shells: list = []
_tel_dir: str = ""   # --telemetry-dir (run summary written at every exit)


def _story_mod():
    """The shared ledger reader (hetu_tpu/telemetry/story.py), loaded by
    file path: the launcher parent must stay jax-free, and importing the
    hetu_tpu package would pay the jax import (story.py is stdlib-only)."""
    mod = (sys.modules.get("hetu_tpu.telemetry.story")
           or sys.modules.get("_hetustory"))
    if mod is not None:
        return mod
    import importlib.util
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "telemetry", "story.py")
    spec = importlib.util.spec_from_file_location("_hetustory", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["_hetustory"] = mod
    spec.loader.exec_module(mod)
    return mod


def _scan_rank_jsonl(tel_dir):
    """Per-rank final step + the elastic world/resize history from the
    rank JSONL files (via the shared hetustory reader, which orders each
    file's rotated ``.1`` backup before its live generation): the
    post-mortem of an elastic run should start from run_summary.json, not
    from re-deriving the membership timeline by hand."""
    story = _story_mod()
    final_steps = {}
    resizes = []
    world_versions = set()
    plan = None
    for path in story.ledger_files("metrics", tel_dir):
        # iterate, never slurp: an uncapped (HETU_TELEMETRY_MAX_MB unset)
        # long-run rank file can be huge, and this runs in the launcher
        for row in story.iter_rows(path):
            rec = row.rec
            rank = rec.get("rank")
            if rec.get("kind") == "step" and "step" in rec:
                key = str(rank if rank is not None else "?")
                final_steps[key] = max(final_steps.get(key, -1),
                                       int(rec["step"]))
            elif rec.get("kind") == "event" and \
                    str(rec.get("name", "")).startswith("resize"):
                ev = {k: rec.get(k) for k in
                      ("ts", "name", "rank", "step", "world_version",
                       "n_workers", "n_servers", "duration_ms")
                      if rec.get(k) is not None}
                resizes.append(ev)
                if rec.get("world_version") is not None:
                    world_versions.add(int(rec["world_version"]))
            elif rec.get("kind") == "plan" and plan is None:
                # the hetuwatch plan stamp (docs/OBSERVABILITY.md
                # pillar 6): the adopted layout, per-param comm
                # decisions and predicted step — rank 0 stamps first;
                # every rank adopts the same plan, so first wins
                plan = {k: rec.get(k) for k in
                        ("mesh", "comm_mode", "comm_quant", "zero1",
                         "remat", "predicted_step_ms",
                         "predicted_legs", "params")
                        if rec.get(k) is not None}
    resizes.sort(key=lambda e: e.get("ts", 0))
    return final_steps, resizes, sorted(world_versions), plan


def _write_telemetry_summary(rc, preempted, num_workers):
    """Aggregate the run's per-rank telemetry files into one manifest
    (run_summary.json) in the shared directory — ranks already write
    metrics-r<N>.jsonl / trace-r<N>.json side by side (WORKER_ID keys the
    file names), so the launcher's job is the closing inventory + outcome,
    per-rank final steps, and the elastic resize/world-version history."""
    if not _tel_dir:
        return
    import glob
    import json
    final_steps, resizes, world_versions, plan = _scan_rank_jsonl(_tel_dir)
    summary = {
        "workers": num_workers,
        "exit_code": rc,
        "preempted": bool(preempted),
        "final_steps": final_steps,
        "files": sorted(os.path.basename(p) for p in
                        glob.glob(os.path.join(_tel_dir, "*"))
                        if not p.endswith(".tmp")
                        and os.path.basename(p) != "run_summary.json"),
    }
    if resizes:
        summary["resizes"] = resizes
        summary["world_versions"] = world_versions
    if plan:
        summary["plan"] = plan
    # hetupilot actuation history (docs/FAULT_TOLERANCE.md "Self-tuning
    # with guardrails"): the era ledger rolls up next to the plan it tuned
    try:
        from hetu_tpu.pilot import summarize_dir
        pilot = summarize_dir(os.path.join(_tel_dir, "pilot")) \
            or summarize_dir(_tel_dir)
        if pilot is not None:
            summary["pilot"] = pilot
    except Exception as e:  # noqa: BLE001 — the summary must still land
        print(f"# heturun: pilot summary skipped ({e})", file=sys.stderr)
    try:
        with open(os.path.join(_tel_dir, "run_summary.json"), "w") as f:
            json.dump(summary, f, indent=1)
    except OSError as e:
        print(f"# heturun: telemetry summary skipped ({e})",
              file=sys.stderr)


def _signal_handler(sig, frame):
    """Preemption-aware teardown: forward the signal to the WORKERS first so
    their resilience.PreemptionHandler can take the emergency checkpoint,
    give them a grace window, then tear down the PS roles. Exits with
    EXIT_PREEMPTED on SIGTERM (the cluster-level 'preempted cleanly' code)
    and the conventional 130 on SIGINT."""
    for p in _shells:
        if p.poll() is None:
            try:
                p.send_signal(sig)
            except OSError:
                pass
    grace = float(os.environ.get("HETU_PREEMPT_GRACE_S", "30"))
    deadline = time.time() + grace
    for p in _shells:
        try:
            p.wait(timeout=max(0.1, deadline - time.time()))
        except subprocess.TimeoutExpired:
            # SIGKILL: it already had the SIGTERM + grace window — a wedged
            # worker must not outlive the launcher as an orphan
            p.kill()
    for p in _procs:
        p.terminate()
    rc = EXIT_PREEMPTED if sig == signal.SIGTERM else 130
    _write_telemetry_summary(rc, sig == signal.SIGTERM, len(_shells))
    sys.exit(rc)


def _get_available_port(addr: str) -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind((addr, 0))
        return s.getsockname()[1]


def parse_cluster(path):
    settings = yaml.safe_load(open(path).read())
    attributes = {"host", "servers", "workers", "chief"}
    hosts, servers, workers = [], {}, {}
    chief = None
    for node in settings["nodes"]:
        assert set(node.keys()) <= attributes, \
            f"invalid node attributes: {set(node.keys())} / {attributes}"
        hosts.append(node["host"])
        if node.get("servers", 0):
            servers[node["host"]] = int(node["servers"])
        if node.get("workers", 0):
            workers[node["host"]] = int(node["workers"])
        if node.get("chief", False):
            assert chief is None, "there should be only one chief"
            chief = node["host"]
    assert chief, "there should be one chief"
    return hosts, servers, workers, chief


def _sched_entry(env):
    from hetu_tpu.launcher import start_sched
    start_sched(env)


def _server_entry(server_id, env):
    from hetu_tpu.launcher import start_server
    start_server(server_id, env)


def main(argv=None):
    signal.signal(signal.SIGINT, _signal_handler)
    signal.signal(signal.SIGTERM, _signal_handler)
    parser = argparse.ArgumentParser(prog="heturun")
    parser.add_argument("-c", "--config", required=True,
                        help="cluster yaml (nodes: host/servers/workers/chief)")
    parser.add_argument("-i", "--identify", default="",
                        help="SSH identity file for multi-machine launch")
    parser.add_argument("-r", "--max-restarts", type=int, default=0,
                        help="restart a worker that exits with a recoverable "
                             "(nonzero, non-preempted) code up to N times "
                             "total, with exponential backoff — workers "
                             "resume from their checkpointer (single-host "
                             "mode; see docs/FAULT_TOLERANCE.md)")
    parser.add_argument("--ps-max-respawns", type=int, default=0,
                        help="PS high availability (single-host mode): "
                             "servers write continuous shard snapshots "
                             "(DMLC_PS_SNAPSHOT_DIR/_MS) and a supervisor "
                             "respawns a dead server from the freshest "
                             "snapshot up to N times total; workers get a "
                             "failover deadline (DMLC_PS_FAILOVER_DEADLINE_"
                             "MS) so in-flight requests re-issue instead of "
                             "failing (see docs/FAULT_TOLERANCE.md)")
    parser.add_argument("--elastic", action="store_true",
                        help="elastic membership (single-host PS mode): a "
                             "worker that exits abnormally becomes a "
                             "planned DEPARTURE (the launcher proposes a "
                             "world shrink via the scheduler's two-phase "
                             "resize instead of restarting it); SIGUSR1 "
                             "grows the world by one worker, SIGUSR2 by "
                             "one PS server (key ranges migrate live). "
                             "Workers run with HETU_ELASTIC=1 and drain/"
                             "commit at step boundaries (see 'Elastic "
                             "membership' in docs/FAULT_TOLERANCE.md)")
    parser.add_argument("--restore", metavar="JOBDIR", default="",
                        help="reconstruct the whole job from the newest "
                             "COMMITTED coordinated snapshot epoch under "
                             "JOBDIR (written by hetusave / "
                             "resilience.JobCheckpointer): servers restore "
                             "the epoch's pinned shard snapshots "
                             "(DMLC_PS_RESTORE_DIR), workers re-impose "
                             "params/optimizer/dataloader/RNG state and "
                             "verify the update-counter algebra before "
                             "step one. The epoch may be restored into a "
                             "DIFFERENT world size — key ranges re-split "
                             "offline, optimizer state rides bit-for-bit "
                             "(single-host PS mode; see "
                             "docs/FAULT_TOLERANCE.md 'Coordinated job "
                             "snapshots')")
    parser.add_argument("--telemetry-dir", default="",
                        help="shared telemetry directory: workers run with "
                             "HETU_TELEMETRY_DIR set (HETU_TELEMETRY "
                             "defaults to 'metrics' unless already set), "
                             "each rank writes metrics-r<N>.jsonl / "
                             "trace-r<N>.json there, the PS supervisor "
                             "appends ps_supervisor.jsonl, and the launcher "
                             "writes run_summary.json on exit; inspect with "
                             "bin/hetutop (docs/OBSERVABILITY.md)")
    parser.add_argument("--pilot", action="store_true",
                        help="bounded self-tuning (single-host PS mode): "
                             "workers run with HETU_PILOT=1 (HETU_WATCH "
                             "defaults on) so the hetupilot controller acts "
                             "on hetuwatch's plan-divergence/SLO "
                             "recommendations — each actuation is an era "
                             "through the elastic two-phase protocol, "
                             "measured for K windows and rolled back on "
                             "regression. The actuation ledger "
                             "(pilot.jsonl) lands under the telemetry dir "
                             "and is folded into run_summary.json; inspect "
                             "with bin/hetupilot (docs/FAULT_TOLERANCE.md "
                             "'Self-tuning with guardrails')")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="worker command, e.g. python train.py")
    args = parser.parse_args(argv)
    hosts, servers, workers, chief = parse_cluster(args.config)
    num_servers = sum(servers.values())
    num_workers = sum(workers.values())
    enable_ps = num_servers > 0
    chief_address = (socket.gethostbyname(socket.gethostname())
                     if len(hosts) > 1 else "127.0.0.1")
    port = _get_available_port(chief_address)
    print(f"Cluster: {{ chief: {chief}, servers({num_servers}): {servers}, "
          f"workers({num_workers}): {workers} }}")

    env = dict(os.environ)
    # Run identity (docs/OBSERVABILITY.md pillar 7): every JSONL row,
    # pilot ledger line and flight ring this job writes carries
    # (run_id, inc). A fresh launch mints the id; a relaunch that inherited
    # HETU_RUN_ID (an outer supervisor / k8s restart) keeps it and bumps
    # the incarnation — so a reused telemetry dir disambiguates runs
    # instead of silently interleaving them.
    if env.get("HETU_RUN_ID"):
        try:
            run_inc = int(env.get("HETU_RUN_INCARNATION", "-1")) + 1
        except ValueError:
            run_inc = 1
    else:
        env["HETU_RUN_ID"] = (time.strftime("%Y%m%d-%H%M%S")
                              + f"-{os.getpid()}")
        run_inc = 0
    env["HETU_RUN_INCARNATION"] = str(run_inc)
    os.environ["HETU_RUN_ID"] = env["HETU_RUN_ID"]
    os.environ["HETU_RUN_INCARNATION"] = env["HETU_RUN_INCARNATION"]
    if args.telemetry_dir:
        global _tel_dir
        _tel_dir = os.path.abspath(args.telemetry_dir)
        os.makedirs(_tel_dir, exist_ok=True)
        env["HETU_TELEMETRY_DIR"] = _tel_dir
        env.setdefault("HETU_TELEMETRY", "metrics")
        # the PS supervisor runs in THIS process and reads the env directly
        os.environ["HETU_TELEMETRY_DIR"] = _tel_dir
        # hetutrail (docs/OBSERVABILITY.md pillar 5): HETU_TRAIL=1 arms the
        # PS-wire span rings for EVERY role, flushing next to the metrics
        # files so hetutrail joins them from one directory
        if os.environ.get("HETU_TRAIL", "").strip().lower() in (
                "1", "true", "yes", "on"):
            env.setdefault("HETU_TRAIL_DIR", _tel_dir)
            os.environ.setdefault("HETU_TRAIL_DIR", _tel_dir)
    pilot_on = args.pilot and enable_ps and len(hosts) == 1
    if args.pilot and not pilot_on:
        # never let an operator believe self-tuning is armed when it is not
        print("# heturun: --pilot requires single-host PS mode; the "
              "self-tuning controller is OFF for this cluster",
              file=sys.stderr)
    if pilot_on:
        env["HETU_PILOT"] = "1"
        # the controller consumes the sentinel's stream: watching defaults
        # on (explicit HETU_WATCH=0 still wins and disables both)
        env.setdefault("HETU_WATCH", "1")
        if _tel_dir:
            env.setdefault("HETU_PILOT_DIR", os.path.join(_tel_dir, "pilot"))
    ps_ha = enable_ps and args.ps_max_respawns > 0 and len(hosts) == 1
    if enable_ps and args.ps_max_respawns > 0 and len(hosts) > 1:
        # don't let an operator believe HA is armed when it is not: the
        # supervisor only drives local children (remote respawn needs a
        # per-host agent), so multi-host runs get no self-healing yet
        print("# heturun: --ps-max-respawns is single-host only; PS "
              "high availability is OFF for this multi-host cluster",
              file=sys.stderr)
    if enable_ps:
        env.update({
            "DMLC_PS_ROOT_URI": chief_address,
            "DMLC_PS_ROOT_PORT": str(port),
            "DMLC_NUM_SERVER": str(num_servers),
            "DMLC_NUM_WORKER": str(num_workers),
        })
    ps_snap_created = None
    if ps_ha:
        # PS high availability: snapshots + supervised respawn + worker
        # failover. Explicit env wins over the defaults.
        from hetu_tpu.ps.supervisor import apply_ha_env_defaults
        ps_snap_created = apply_ha_env_defaults(env)
    if args.restore:
        if not (enable_ps and len(hosts) == 1):
            print("# heturun: --restore requires single-host PS mode",
                  file=sys.stderr)
            return 2
        # resolve (and, on a world-size change, re-split) BEFORE any role
        # spawns: a job must never half-start against an unrestorable dir
        from hetu_tpu.recovery import RecoveryError, prepare_restore
        try:
            prep = prepare_restore(os.path.abspath(args.restore),
                                   num_servers)
        except RecoveryError as e:
            print(f"# heturun: --restore failed: {e}", file=sys.stderr)
            return 2
        m = prep["manifest"]
        env["DMLC_PS_RESTORE_DIR"] = prep["server_restore_dir"]
        # workers: Executor re-imposes this rank's state from the job dir
        # and verifies the counter algebra (recovery.restore_executor_from_env)
        env["HETU_RESTORE_DIR"] = os.path.abspath(args.restore)
        # restored workers are JOINERS: InitTensor must not push fresh
        # values over the restored tables, and init barriers are moot
        env["HETU_ELASTIC_JOIN"] = "1"
        rs = prep["resplit"]
        print(f"# heturun --restore: epoch {m['epoch']} (step {m['step']}, "
              f"{m['total_updates']} updates) from {args.restore}"
              + (f"; re-split {rs['old_n_servers']} -> "
                 f"{rs['new_n_servers']} servers" if rs else ""))
    elastic_on = args.elastic and enable_ps and len(hosts) == 1
    elastic_dir = None
    if args.elastic and not elastic_on:
        # never let an operator believe elasticity is armed when it is not
        print("# heturun: --elastic requires single-host PS mode; elastic "
              "membership is OFF for this cluster", file=sys.stderr)
    if elastic_on:
        import tempfile
        elastic_dir = tempfile.mkdtemp(prefix="hetu_elastic_")
        env["HETU_ELASTIC"] = "1"
        env["HETU_ELASTIC_DIR"] = elastic_dir

    ctx = multiprocessing.get_context("spawn")
    ps_sup = None
    if len(hosts) == 1:
        server_procs = {}
        if enable_ps:
            _procs.append(ctx.Process(target=_sched_entry, args=(env,)))
            for i in range(num_servers):
                server_procs[i] = ctx.Process(target=_server_entry,
                                              args=(i, env))
                _procs.append(server_procs[i])
            for p in _procs:
                p.start()
            if ps_ha:
                from hetu_tpu.ps.supervisor import start_mp_supervisor
                ps_sup = start_mp_supervisor(
                    ctx, _server_entry, env, server_procs, _procs.append,
                    max_respawns=args.ps_max_respawns)
        def spawn_worker(w, join=False, incarnation=0):
            wenv = dict(env)
            wenv["WORKER_ID"] = str(w)
            if incarnation:
                # an auto-resume respawn is a new incarnation of the same
                # run: its telemetry rows must not be indistinguishable
                # from its dead predecessor's
                try:
                    base_inc = int(env.get("HETU_RUN_INCARNATION", "0"))
                except ValueError:
                    base_inc = 0
                wenv["HETU_RUN_INCARNATION"] = str(base_inc + incarnation)
            if enable_ps:
                wenv["DMLC_ROLE"] = "worker"
            if join:
                # late joiner: skip init pushes/barriers, bootstrap step +
                # data partition from the scheduler's world log
                wenv["HETU_ELASTIC_JOIN"] = "1"
            # multi-chip single host: each worker is one jax process
            wenv["HETU_NUM_WORKER"] = str(num_workers)
            p = subprocess.Popen(args.command, env=wenv)
            _shells.append(p)   # visible to the signal handler
            return p

        # -- elastic membership (docs/FAULT_TOLERANCE.md) -------------------
        # The launcher parent IS the resize coordinator: worker deaths
        # propose shrinks, SIGUSR1/SIGUSR2 (or the supervisor's scale
        # policy) propose grows. All resizes run inline in the reap loop —
        # the drain completes when the survivors reach their next step
        # boundary, bounded by HETU_ELASTIC_DRAIN_TIMEOUT_S.
        usr_grow = {"worker": 0, "server": 0}
        if elastic_on:
            signal.signal(signal.SIGUSR1,
                          lambda *_: usr_grow.__setitem__(
                              "worker", usr_grow["worker"] + 1))
            signal.signal(signal.SIGUSR2,
                          lambda *_: usr_grow.__setitem__(
                              "server", usr_grow["server"] + 1))
        # supervisor-thread grow requests ride their own queue: usr_grow's
        # read-modify-write is only safe from the signal handlers (which
        # run on the main thread); a cross-thread += would race the main
        # loop's decrement and duplicate or drop a grow. list.append/pop
        # are atomic under the GIL.
        scale_requests: list = []
        if elastic_on and ps_sup is not None:
            # telemetry-driven scale policy: the supervisor feeds raw
            # kServerStats rows each poll; a grow recommendation takes the
            # same path as an operator SIGUSR2
            from hetu_tpu.elastic import ScalePolicy
            ps_sup.scale_policy = ScalePolicy(max_servers=int(os.environ.get(
                "HETU_ELASTIC_MAX_SERVERS", str(num_servers + 2))))
            ps_sup.on_scale = lambda d: scale_requests.append(d)

        def elastic_coord():
            from hetu_tpu.elastic import ElasticCoordinator
            return ElasticCoordinator(
                env.get("DMLC_PS_ROOT_URI", "127.0.0.1"),
                int(env.get("DMLC_PS_ROOT_PORT", "13200")),
                drain_timeout_s=float(os.environ.get(
                    "HETU_ELASTIC_DRAIN_TIMEOUT_S", "60")))

        def elastic_world():
            from hetu_tpu.elastic import resize_state
            return resize_state(env.get("DMLC_PS_ROOT_URI", "127.0.0.1"),
                                int(env.get("DMLC_PS_ROOT_PORT", "13200")))

        # ranks that left the world but are not yet removed from the
        # scheduler's member set: abnormal exits resize immediately; clean
        # (rc=0) completions defer to the next resize — their partitions
        # are fully consumed, and resizing on every natural completion
        # would stall teardown when the whole fleet finishes together
        pending_departed: dict = {}

        def note_departure(w):
            step = -1
            try:
                with open(os.path.join(elastic_dir,
                                       f"progress_r{w}")) as f:
                    step = int(f.read().strip())
            except (OSError, ValueError):
                pass  # unknown progress: the scheduler falls back
            pending_departed[w] = step

        def elastic_resize(d_workers=0, d_servers=0):
            """One membership change folding in every pending departure.
            ``d_workers``/``d_servers`` grow the world by that many."""
            st = elastic_world()
            removed = [r for r in pending_departed if r in st["members"]]
            steps = [pending_departed[r] for r in removed]
            new_nw = len(st["members"]) - len(removed) + d_workers
            new_ns = st["n_servers"] + d_servers
            if new_nw < 1:
                return None  # the last worker left: nothing to resize for

            spawned_sids: list = []

            def spawn_srv(sid):
                p = ctx.Process(target=_server_entry, args=(sid, env))
                p.start()
                _procs.append(p)
                server_procs[sid] = p
                spawned_sids.append(sid)
                if ps_sup is not None:
                    ps_sup.watch_server(sid, p)

            try:
                report = elastic_coord().resize(
                    new_nw, new_ns, removed=removed, removed_steps=steps,
                    spawn_server=spawn_srv if d_servers else None,
                    spawn_worker=(lambda r: running.__setitem__(
                        r, spawn_worker(r, join=True)))
                    if d_workers else None)
            except Exception:
                # an aborted grow must not leave the joining server as an
                # orphan: it never became part of the committed world, so
                # reap it and drop it from supervision (its death must not
                # burn respawn budget)
                for sid in spawned_sids:
                    p = server_procs.pop(sid, None)
                    if p is not None:
                        p.terminate()
                        p.join(timeout=10)
                    if ps_sup is not None:
                        ps_sup.unwatch_server(sid)
                raise
            for r in removed:
                pending_departed.pop(r, None)
            return report

        # hetutrail straggler watch (docs/OBSERVABILITY.md pillar 5): tail
        # the rank JSONLs for cross-rank step skew; K-consecutive straggler
        # events land in trail-events.jsonl and — under --elastic — reach
        # the supervisor's ScalePolicy like any other pressure signal.
        skew_mon = None
        skew_next_poll = 0.0
        if _tel_dir and num_workers > 1:
            try:
                from hetu_tpu.telemetry.trail import SkewMonitor

                def _on_straggler(ev):
                    print(f"# heturun: straggler rank {ev.get('rank')} @ "
                          f"step {ev.get('step')}: {ev.get('step_ms')}ms vs "
                          f"median {ev.get('median_ms')}ms",
                          file=sys.stderr, flush=True)
                    if ps_sup is not None and \
                            getattr(ps_sup, "scale_policy", None) is not None:
                        rec = ps_sup.scale_policy.note_straggler(ev)
                        if rec is not None:
                            scale_requests.append(rec)

                skew_mon = SkewMonitor(_tel_dir, on_event=_on_straggler)
            except Exception as e:  # noqa: BLE001 — watch is best-effort
                print(f"# heturun: straggler watch off ({e!r})",
                      file=sys.stderr)

        running = {w: spawn_worker(w) for w in range(num_workers)}
        respawn_at = {}   # worker id -> monotonic deadline (backoff pending)
        worker_respawns = {}   # worker id -> incarnation bump count
        restarts, delay = 0, 2.0
        rc_final, preempted = 0, False
        teardown_at = None
        while running or respawn_at:
            for w, p in list(running.items()):
                rc = p.poll()
                if rc is None:
                    continue
                del running[w]
                if elastic_on and running:
                    # elastic: every exit is a membership event. Clean
                    # completions defer (their partition is consumed);
                    # abnormal exits — crash, SIGKILL, preemption — are
                    # DEPARTURES: shrink the world so survivors
                    # re-partition, instead of restarting
                    note_departure(w)
                    if rc == 0:
                        continue
                    if rc == EXIT_PREEMPTED:
                        preempted = True
                    print(f"# heturun: worker {w} exited rc={rc}; elastic: "
                          "proposing shrink", file=sys.stderr, flush=True)
                    try:
                        elastic_resize()
                        continue
                    except Exception as e:  # noqa: BLE001
                        # falling back to RESTART means this rank is not
                        # departed after all — a stale pending_departed
                        # entry would decommission the respawned worker at
                        # the next resize and double-consume its samples
                        pending_departed.pop(w, None)
                        print(f"# heturun: elastic shrink failed ({e!r}); "
                              "falling back to restart/fail handling",
                              file=sys.stderr, flush=True)
                if rc == 0:
                    continue
                if rc == EXIT_PREEMPTED:
                    # clean preemption: emergency checkpoint written; never
                    # counted against the restart budget
                    preempted = True
                    continue
                if restarts < args.max_restarts:
                    restarts += 1
                    print(f"# heturun: worker {w} exited rc={rc}; auto-"
                          f"resume restart {restarts}/{args.max_restarts} "
                          f"in {delay:.0f}s", file=sys.stderr, flush=True)
                    # deadline, not an inline sleep: other workers' exits
                    # (preemption!) must keep being reaped during backoff
                    respawn_at[w] = time.monotonic() + delay
                    delay *= 2
                elif not rc_final:
                    # first failure wins: survivors killed by the teardown
                    # below exit -15, which must not mask the real code
                    rc_final = rc
            while elastic_on and usr_grow["worker"] > 0 and running:
                usr_grow["worker"] -= 1
                try:
                    print("# heturun: elastic: growing by one worker",
                          file=sys.stderr, flush=True)
                    elastic_resize(d_workers=1)
                except Exception as e:  # noqa: BLE001
                    print(f"# heturun: elastic worker grow failed ({e!r})",
                          file=sys.stderr, flush=True)
            while elastic_on and scale_requests and running:
                scale_requests.pop()
                usr_grow["server"] += 1  # main thread: safe to merge here
            while elastic_on and usr_grow["server"] > 0 and running:
                usr_grow["server"] -= 1
                try:
                    print("# heturun: elastic: growing by one PS server",
                          file=sys.stderr, flush=True)
                    elastic_resize(d_servers=1)
                except Exception as e:  # noqa: BLE001
                    print(f"# heturun: elastic server grow failed ({e!r})",
                          file=sys.stderr, flush=True)
            now = time.monotonic()
            if ps_sup is not None and ps_sup.fatal and not rc_final:
                # the PS tier is permanently down (respawn budget exhausted
                # or a respawn failed): fail the run now instead of letting
                # every worker grind through its failover deadline. A worker
                # failure that already landed keeps its code (first failure
                # wins, the PR 1 convention).
                print(f"# heturun: PS supervisor fatal: {ps_sup.fatal}",
                      file=sys.stderr, flush=True)
                rc_final = 1
            if rc_final:
                # a permanently failed worker strands the survivors in
                # dead-rank collectives — preempt them (SIGTERM so they can
                # emergency-checkpoint, then terminate after the grace
                # window) instead of polling forever
                respawn_at.clear()
                if teardown_at is None:
                    print(f"# heturun: worker failed rc={rc_final} with no "
                          "restart budget; preempting remaining workers",
                          file=sys.stderr, flush=True)
                    for p in running.values():
                        if p.poll() is None:
                            try:
                                p.send_signal(signal.SIGTERM)
                            except OSError:
                                pass
                    teardown_at = now + float(
                        os.environ.get("HETU_PREEMPT_GRACE_S", "30"))
                elif now >= teardown_at:
                    for p in running.values():
                        if p.poll() is None:
                            # SIGKILL, not terminate(): a worker wedged in a
                            # hung collective already ignored the SIGTERM
                            p.kill()
            for w, when in list(respawn_at.items()):
                if now >= when:
                    del respawn_at[w]
                    worker_respawns[w] = worker_respawns.get(w, 0) + 1
                    running[w] = spawn_worker(
                        w, incarnation=worker_respawns[w])
            if skew_mon is not None and now >= skew_next_poll:
                skew_next_poll = now + 2.0
                try:
                    skew_mon.poll()
                except Exception:  # noqa: BLE001 — watch is best-effort
                    pass
            if running or respawn_at:
                time.sleep(0.2)
        if ps_sup is not None:
            ps_sup.stop()  # before terminate(): teardown is not a death
        for p in _procs:
            p.terminate()
            p.join(timeout=10)
        if ps_snap_created:
            from hetu_tpu.ps.supervisor import cleanup_snapshot_root
            cleanup_snapshot_root(ps_snap_created)
        if elastic_dir:
            import shutil
            shutil.rmtree(elastic_dir, ignore_errors=True)
        rc = rc_final if rc_final else (EXIT_PREEMPTED if preempted else 0)
        _write_telemetry_summary(rc, preempted, num_workers)
        sys.exit(rc)
    else:
        # multi-machine: ssh remote roles; workers get jax.distributed
        # coordinator env (reference: paramiko remote PS + mpirun -host)
        ssh_opts = ["-o", "StrictHostKeyChecking=no"]
        if args.identify:
            ssh_opts += ["-i", args.identify]
        coord = f"{chief_address}:{_get_available_port(chief_address)}"
        # forward the PS config AND the telemetry toggles: --telemetry-dir
        # promises every rank writes to the (shared) dir, so the ssh'd
        # ranks need the env too, not just the chief-host children.
        # Values are shell-quoted — the telemetry dir is a user-supplied
        # path that may carry spaces/metacharacters into the remote line
        env_exports = " ".join(
            f"{k}={shlex.quote(str(v))}" for k, v in env.items()
            if k.startswith("DMLC_") or k.startswith("HETU_TELEMETRY"))
        sid = 0
        if enable_ps:
            _procs.append(ctx.Process(target=_sched_entry, args=(env,)))
            for p in _procs:
                p.start()
        pidx = 0
        total_procs = sum(workers.values())
        for host in hosts:
            for _ in range(servers.get(host, 0)):
                cmd = (f"{env_exports} SERVER_ID={sid} DMLC_ROLE=server "
                       f"python -m hetu_tpu.launcher_remote_server")
                _shells.append(subprocess.Popen(
                    ["ssh", *ssh_opts, host, cmd]))
                sid += 1
            for _ in range(workers.get(host, 0)):
                wcmd = (f"{env_exports} WORKER_ID={pidx} DMLC_ROLE=worker "
                        f"HETU_NUM_WORKER={num_workers} "
                        f"JAX_COORDINATOR_ADDRESS={coord} "
                        f"JAX_NUM_PROCESSES={total_procs} "
                        f"JAX_PROCESS_ID={pidx} " + " ".join(args.command))
                if host == chief:
                    _shells.append(subprocess.Popen(
                        args.command, env={**env, "WORKER_ID": str(pidx),
                                           "DMLC_ROLE": "worker",
                                           "HETU_NUM_WORKER": str(num_workers),
                                           "JAX_COORDINATOR_ADDRESS": coord,
                                           "JAX_NUM_PROCESSES": str(total_procs),
                                           "JAX_PROCESS_ID": str(pidx)}))
                else:
                    _shells.append(subprocess.Popen(
                        ["ssh", *ssh_opts, host, wcmd]))
                pidx += 1
        rc = 0
        for p in _shells:
            rc |= p.wait()
        for p in _procs:
            p.terminate()
        # multi-host: only this host's files are visible unless the dir is
        # on a shared filesystem — the summary still inventories what's here
        _write_telemetry_summary(rc, False, num_workers)
        sys.exit(rc)


if __name__ == "__main__":
    main()
