"""Device contexts and array handles for the TPU-native framework.

Capability parity with the reference's ``python/hetu/ndarray.py`` (DLContext
:10, NDArray :132, IndexedSlices :482), redesigned for JAX: an ``NDArray`` is a
thin, duck-typed wrapper over a ``jax.Array`` — allocation, layout, strides,
copies and streams are all owned by XLA, so none of the reference's manual
memory machinery (lazy strided views, memory planning) is reimplemented here.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp


class DLContext:
    """A device placement tag: ``cpu(0)``, ``tpu(3)``, ``rtpu('host2', 1)``.

    Mirrors the reference DLContext (ndarray.py:10) including the remote
    (hostname-qualified) form used by DeviceGroup strings. ``gpu`` is accepted
    as an alias for ``tpu`` so reference scripts run unchanged.
    """

    __slots__ = ("device_type", "device_id", "hostname")

    def __init__(self, device_type: str, device_id: int = 0, hostname: str = "localhost"):
        if device_type == "gpu":  # compat alias: reference scripts say gpu
            device_type = "tpu"
        assert device_type in ("cpu", "tpu"), device_type
        self.device_type = device_type
        self.device_id = int(device_id)
        self.hostname = hostname

    # -- resolution to a physical jax device -------------------------------
    def jax_device(self):
        """Resolve to a local jax.Device, falling back gracefully.

        On a CPU-only test host ``tpu(0)`` resolves to a CPU device so the
        same script runs anywhere (the reference hard-fails without CUDA).
        """
        if self.device_type == "tpu":
            try:
                devs = [d for d in jax.devices() if d.platform != "cpu"]
            except RuntimeError:
                devs = []
            if not devs:
                devs = jax.devices()
        else:
            try:
                devs = jax.devices("cpu")
            except RuntimeError:
                devs = jax.devices()
        return devs[self.device_id % len(devs)]

    @property
    def local(self) -> bool:
        return self.hostname in ("localhost", "127.0.0.1")

    def relocalize(self):
        self.hostname = "localhost"

    def __eq__(self, other):
        return (
            isinstance(other, DLContext)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
            and self.hostname == other.hostname
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id, self.hostname))

    def __repr__(self):
        if self.local:
            return f"{self.device_type}({self.device_id})"
        return f"{self.hostname}:{self.device_type}({self.device_id})"


def cpu(dev_id: int = 0) -> DLContext:
    return DLContext("cpu", dev_id)


def tpu(dev_id: int = 0) -> DLContext:
    return DLContext("tpu", dev_id)


# The reference exposes gpu()/rgpu(); on the TPU build these are aliases.
def gpu(dev_id: int = 0) -> DLContext:
    return DLContext("tpu", dev_id)


def rcpu(hostname: str, dev_id: int = 0) -> DLContext:
    return DLContext("cpu", dev_id, hostname=hostname)


def rtpu(hostname: str, dev_id: int = 0) -> DLContext:
    return DLContext("tpu", dev_id, hostname=hostname)


rgpu = rtpu


def is_gpu_ctx(ctx) -> bool:
    """Compat shim (reference ndarray.py:106): true when ctx is an accelerator."""
    return isinstance(ctx, DLContext) and ctx.device_type == "tpu"


def is_tpu_ctx(ctx) -> bool:
    return is_gpu_ctx(ctx)


class NDArray:
    """Thin handle over a ``jax.Array`` with the reference's surface.

    Reference parity: ndarray.py:132 (asnumpy :2xx, copyto, shape/dtype).
    There is no manual alloc/free — XLA owns memory.
    """

    __slots__ = ("handle", "ctx")

    def __init__(self, handle, ctx: DLContext | None = None):
        self.handle = handle
        self.ctx = ctx

    @property
    def shape(self):
        return tuple(self.handle.shape)

    @property
    def dtype(self):
        return np.dtype(self.handle.dtype)

    def asnumpy(self) -> np.ndarray:
        return np.asarray(self.handle)

    def copyto(self, target):
        if isinstance(target, DLContext):
            return array(self.handle, ctx=target)
        if isinstance(target, NDArray):
            target.handle = jax.device_put(self.handle, target.handle.sharding)
            return target
        raise ValueError(f"Unsupported target {target!r}")

    def __array__(self, dtype=None):
        arr = np.asarray(self.handle)
        return arr.astype(dtype) if dtype is not None else arr

    def __repr__(self):
        return f"NDArray(shape={self.shape}, dtype={self.dtype}, ctx={self.ctx})"


def array(arr, ctx: DLContext | None = None, dtype=None) -> NDArray:
    """Create an NDArray on ``ctx`` (reference ndarray.py:419 ``array``)."""
    if isinstance(arr, NDArray):
        arr = arr.handle
    if dtype is None and not hasattr(arr, "dtype"):
        dtype = np.float32
    if dtype is None and np.issubdtype(np.asarray(arr).dtype, np.floating):
        dtype = np.float32
    np_arr = np.asarray(arr, dtype=dtype)
    dev = ctx.jax_device() if ctx is not None else None
    handle = jax.device_put(np_arr, dev)
    return NDArray(handle, ctx)


def empty(shape, ctx: DLContext | None = None, dtype=np.float32) -> NDArray:
    """Allocate an uninitialized-contents array (zeros under XLA)."""
    dev = ctx.jax_device() if ctx is not None else None
    handle = jax.device_put(jnp.zeros(shape, dtype=dtype), dev)
    return NDArray(handle, ctx)


class ND_Sparse_Array:
    """CSR sparse matrix handle (reference ndarray.py:411 ``ND_Sparse_Array``).

    Stored as (data, indices, indptr) jax arrays; consumed by csrmv/csrmm ops.
    """

    __slots__ = ("data", "row", "col", "nrow", "ncol", "ctx")

    def __init__(self, data, row, col, nrow, ncol, ctx=None):
        self.data = data
        self.row = row
        self.col = col
        self.nrow = nrow
        self.ncol = ncol
        self.ctx = ctx

    @property
    def shape(self):
        return (self.nrow, self.ncol)


def sparse_array(values, indices, shape, ctx=None) -> ND_Sparse_Array:
    """Build a CSR array from COO-style (values, (row, col)) like the reference
    (ndarray.py:452)."""
    row, col = indices
    dev = ctx.jax_device() if ctx is not None else None
    put = lambda a, dt: jax.device_put(np.asarray(a, dtype=dt), dev)
    return ND_Sparse_Array(
        put(values, np.float32), put(row, np.int32), put(col, np.int32),
        int(shape[0]), int(shape[1]), ctx,
    )


@jax.tree_util.register_pytree_node_class
class SparseValue:
    """Traced CSR/COO value: (data, row, col) arrays + static (nrow, ncol).

    Registered as a pytree so it can cross the jit boundary with the matrix
    dims as static aux data (segment_sum needs a static segment count).
    Iterable as a 5-tuple for ergonomic unpacking in op bodies.
    """

    def __init__(self, data, row, col, nrow, ncol):
        self.data, self.row, self.col = data, row, col
        self.nrow, self.ncol = int(nrow), int(ncol)

    def tree_flatten(self):
        return (self.data, self.row, self.col), (self.nrow, self.ncol)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, *aux)

    def __iter__(self):
        return iter((self.data, self.row, self.col, self.nrow, self.ncol))


class IndexedSlices:
    """Sparse gradient as (indices, values) pair (reference ndarray.py:482).

    ``deduplicate`` sums duplicate rows — on TPU this is a segment-sum, which
    XLA lowers to an efficient sorted scatter.
    """

    __slots__ = ("indices", "values", "dense_shape")

    def __init__(self, indices=None, values=None, dense_shape=None):
        self.indices = indices
        self.values = values
        self.dense_shape = dense_shape

    def to_dense(self):
        out = jnp.zeros(self.dense_shape, dtype=self.values.dtype)
        flat_idx = self.indices.reshape(-1)
        flat_val = self.values.reshape((-1,) + tuple(self.dense_shape[1:]))
        return out.at[flat_idx].add(flat_val)

    def deduplicate(self):
        flat_idx = np.asarray(self.indices).reshape(-1)
        flat_val = np.asarray(self.values).reshape((flat_idx.shape[0], -1))
        uniq, inverse = np.unique(flat_idx, return_inverse=True)
        summed = np.zeros((uniq.shape[0], flat_val.shape[1]), dtype=flat_val.dtype)
        np.add.at(summed, inverse, flat_val)
        return IndexedSlices(jnp.asarray(uniq), jnp.asarray(summed), self.dense_shape)

    cpu_deduplicate = deduplicate
