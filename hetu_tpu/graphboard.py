"""Graphboard: visualize an Executor's graph topology
(reference ``python/graphboard/graph2fig.py:11-31`` — graphviz render + tiny
HTTP server).

Self-contained redesign: the image has no ``dot`` binary, so alongside the
DOT source (``output.dot``, loadable by any graphviz) the module renders its
own SVG with a layered longest-path layout — ``show(executor)`` writes
``output.svg`` + ``index.html`` and serves them on a background
``http.server`` thread; ``close()`` stops it.
"""
from __future__ import annotations

import html
import http.server
import os
import socketserver
import threading
from typing import Optional

_server: Optional[socketserver.TCPServer] = None
_thread: Optional[threading.Thread] = None

_KIND_COLORS = {
    "PlaceholderOp": "#a7c7e7",   # params/feeds
    "DataloaderOp": "#c3e6cb",
    "OptimizerOp": "#f5c6cb",
    "GradientOp": "#ffe8a1",
}

# finding annotation: severity -> (fill, stroke); errors outrank warns
_SEV_COLORS = {"error": ("#f8d7da", "#c0392b"),
               "warn": ("#ffe5b4", "#d68910"),
               "note": (None, "#888888")}
_SEV_ORDER = ("error", "warn", "note")

# timings overlay (render(..., timings=True)): phase display names
_PHASE_NAMES = {"prestep_ms": "feed/PS-pull (pre-step)",
                "dispatch_ms": "compute (dispatch)",
                "poststep_ms": "PS-push/bookkeeping (post-step)",
                "compile_ms": "compile"}


def _heat(frac: float) -> str:
    """Heat ramp for the timings overlay: share of step time -> pale
    amber .. red."""
    f = max(0.0, min(1.0, frac))
    c0, c1 = (0xff, 0xf3, 0xe0), (0xe5, 0x39, 0x35)
    return "#%02x%02x%02x" % tuple(int(a + (b - a) * f)
                                   for a, b in zip(c0, c1))


def step_timings(executor, name=None):
    """The last instrumented step's per-phase wall times for one
    subexecutor ({"step_ms", "step", "prestep_ms", ...}), or None when no
    step has run with telemetry enabled (HetuConfig(telemetry=...))."""
    subs = getattr(executor, "subexecutors", None)
    if subs:
        sub = subs[name if name is not None else next(iter(subs))]
    else:
        sub = executor
    return getattr(sub, "last_phases", None)


def _phase_of_node(node, ps_ids):
    """Which host-side step phase a node's work lands in (heuristic for the
    overlay): dataloaders/feeds stage pre-step, PS gradient pushes post-
    step, PS-hosted lookups pull pre-step; everything else runs inside the
    dispatched XLA program. Non-feed placeholders (device-resident params)
    have no phase — returns None."""
    if getattr(node, "is_dataloader", False):
        return "prestep_ms"
    if getattr(node, "is_placeholder", False):
        return "prestep_ms" if getattr(node, "is_feed", False) else None
    if type(node).__name__ == "ParameterServerCommunicateOp":
        return "poststep_ms"
    embed = getattr(node, "embed_node", None)
    if embed is not None and id(embed) in ps_ids:
        return "prestep_ms"
    return "dispatch_ms"


def _timing_overlay(executor, topo, tdict):
    """{op_id: (frac_of_step, tooltip)} for the timings overlay."""
    if not tdict:
        return {}
    step_ms = tdict.get("step_ms") or 0.0
    rt = getattr(executor, "ps_runtime", None)
    ps_ids = set(rt.params.keys()) if rt is not None else set()
    out = {}
    for node in topo:
        phase = _phase_of_node(node, ps_ids)
        if phase is None or phase not in tdict:
            continue
        ms = tdict[phase]
        frac = ms / step_ms if step_ms else 0.0
        out[node.id] = (frac,
                        f"{_PHASE_NAMES.get(phase, phase)}: {ms:.3f} ms of "
                        f"{step_ms:.3f} ms step ({100 * frac:.0f}%)")
    return out


def _topo_of(executor, name=None):
    subs = getattr(executor, "subexecutors", None)
    if subs:
        if name is None:
            name = next(iter(subs))
        return subs[name].topo
    return executor.topo  # a bare SubExecutor


def _findings_by_op(findings):
    """{op_id: [Finding, ...]} for the node-level findings."""
    by_op: dict[int, list] = {}
    for f in findings or ():
        if f.op_id is not None:
            by_op.setdefault(f.op_id, []).append(f)
    return by_op


def _worst_severity(fs):
    for sev in _SEV_ORDER:
        if any(f.severity == sev for f in fs):
            return sev
    return "note"


def lint_findings(executor, name=None):
    """Tier A findings for the executor's graph (used by ``render(...,
    lint=True)``); Tier B findings are appended when a step has run."""
    from . import analysis
    topo = _topo_of(executor, name)
    eval_nodes = getattr(executor, "eval_node_dict", None)
    graph = eval_nodes if eval_nodes is not None else list(topo)
    findings = analysis.GraphAnalyzer(
        graph, config=getattr(executor, "config", None), target=name).run()
    if hasattr(executor, "subexecutors"):
        findings += analysis.analyze_executor(executor)
    return findings


def make_dot(executor, name=None, findings=None, timings=None) -> str:
    """DOT source of the topo (the reference's Digraph, sans dependency).
    ``findings`` (hetulint output) annotate nodes with severity colors and
    tooltips; ``timings`` (a :func:`step_timings` dict) heat-colors nodes by
    their phase's share of the last instrumented step."""
    lines = ["digraph hetu {", "  rankdir=TB;",
             '  node [shape=box, style="rounded,filled", '
             'fillcolor="#eeeeee", fontname="Helvetica"];']
    topo = _topo_of(executor, name)
    by_op = _findings_by_op(findings)
    overlay = _timing_overlay(executor, topo, timings)
    for node in topo:
        color = _KIND_COLORS.get(type(node).__name__, "#eeeeee")
        label = node.name.replace('"', "'")
        extra = ""
        fs = by_op.get(node.id)
        tlay = overlay.get(node.id)
        tips = []
        if tlay is not None:
            color = _heat(tlay[0])
            tips.append(tlay[1].replace('"', "'"))
        if fs:
            # findings outrank the heat fill — a lint error must stay visible
            sev = _worst_severity(fs)
            fill, stroke = _SEV_COLORS[sev]
            color = fill or color
            tips = [str(f).replace('"', "'") for f in fs] + tips
            extra = f', color="{stroke}", penwidth=2'
        if tips:
            tip = "\\n".join(tips)
            extra += f', tooltip="{tip}"'
        lines.append(
            f'  n{node.id} [label="{label}", fillcolor="{color}"{extra}];')
    for node in topo:
        for src in node.inputs:
            lines.append(f"  n{src.id} -> n{node.id};")
    lines.append("}")
    return "\n".join(lines)


def _layout(topo):
    """Layered layout: rank = longest path from a source; x = slot in rank."""
    rank: dict[int, int] = {}
    for node in topo:  # topo order: inputs are ranked first
        rank[id(node)] = 1 + max((rank[id(i)] for i in node.inputs),
                                 default=-1)
    by_rank: dict[int, list] = {}
    for node in topo:
        by_rank.setdefault(rank[id(node)], []).append(node)
    pos = {}
    for r, nodes in by_rank.items():
        for i, node in enumerate(nodes):
            pos[id(node)] = (i, r)
    return pos, max(by_rank) + 1, max(len(v) for v in by_rank.values())


NODE_W, NODE_H, GAP_X, GAP_Y = 150, 34, 30, 46


def make_svg(executor, name=None, findings=None, timings=None) -> str:
    topo = _topo_of(executor, name)
    by_op = _findings_by_op(findings)
    overlay = _timing_overlay(executor, topo, timings)
    pos, n_ranks, width = _layout(topo)
    W = width * (NODE_W + GAP_X) + GAP_X
    H = n_ranks * (NODE_H + GAP_Y) + GAP_Y

    def xy(node):
        c, r = pos[id(node)]
        return (GAP_X + c * (NODE_W + GAP_X),
                GAP_Y + r * (NODE_H + GAP_Y))

    parts = [f'<svg xmlns="http://www.w3.org/2000/svg" width="{W}" '
             f'height="{H}" viewBox="0 0 {W} {H}">',
             '<defs><marker id="arr" markerWidth="8" markerHeight="8" '
             'refX="7" refY="3" orient="auto"><path d="M0,0 L8,3 L0,6 z" '
             'fill="#666"/></marker></defs>',
             f'<rect width="{W}" height="{H}" fill="white"/>']
    for node in topo:
        x2, y2 = xy(node)
        for src in node.inputs:
            x1, y1 = xy(src)
            parts.append(
                f'<path d="M{x1 + NODE_W / 2},{y1 + NODE_H} '
                f'C{x1 + NODE_W / 2},{y1 + NODE_H + 24} '
                f'{x2 + NODE_W / 2},{y2 - 24} {x2 + NODE_W / 2},{y2}" '
                'stroke="#666" fill="none" marker-end="url(#arr)"/>')
    for node in topo:
        x, y = xy(node)
        color = _KIND_COLORS.get(type(node).__name__, "#eeeeee")
        stroke, swidth = "#888", 1
        tips = []
        tlay = overlay.get(node.id)
        if tlay is not None:
            color = _heat(tlay[0])
            tips.append(tlay[1])
        fs = by_op.get(node.id)
        if fs:
            # findings outrank the heat fill — a lint error must stay visible
            sev = _worst_severity(fs)
            fill, stroke = _SEV_COLORS[sev]
            color = fill or color
            swidth = 2
            tips = [str(f) for f in fs] + tips
        tip = ("<title>" + html.escape("\n".join(tips)) + "</title>"
               if tips else "")
        label = node.name if len(node.name) <= 22 else node.name[:20] + "…"
        label = html.escape(label)  # escape AFTER truncating: cutting inside
        # an entity would emit a bare '&' and break the XML
        parts.append(
            f'<g>{tip}<rect x="{x}" y="{y}" width="{NODE_W}" height="{NODE_H}" '
            f'rx="6" fill="{color}" stroke="{stroke}" '
            f'stroke-width="{swidth}"/>'
            f'<text x="{x + NODE_W / 2}" y="{y + NODE_H / 2 + 4}" '
            'font-family="Helvetica" font-size="11" text-anchor="middle">'
            f'{label}</text></g>')
    parts.append("</svg>")
    return "\n".join(parts)


def render(executor, name=None, out_dir="graphboard_out", findings=None,
           lint=False, timings=False):
    """Write output.dot / output.svg / index.html; returns out_dir.

    ``lint=True`` runs the hetulint analyzer over the graph (plus Tier B if
    a step has executed) and annotates offending nodes — severity-colored
    with hover tooltips — and appends the finding list to index.html.
    Explicit ``findings`` skip the analyzer run.

    ``timings=True`` overlays the LAST instrumented step's per-phase wall
    times from the telemetry layer (heat coloring by phase share + hover
    tooltips, plus a phase table in index.html); requires a step to have
    run with ``HetuConfig(telemetry=...)`` enabled — rendered without the
    overlay (with a note) otherwise. Pass a :func:`step_timings`-shaped
    dict to overlay explicit numbers."""
    os.makedirs(out_dir, exist_ok=True)
    if lint and findings is None:
        findings = lint_findings(executor, name)
    tdict = None
    if timings:
        tdict = timings if isinstance(timings, dict) \
            else step_timings(executor, name)
    with open(os.path.join(out_dir, "output.dot"), "w") as f:
        f.write(make_dot(executor, name, findings=findings, timings=tdict))
    svg = make_svg(executor, name, findings=findings, timings=tdict)
    with open(os.path.join(out_dir, "output.svg"), "w") as f:
        f.write(svg)
    body = "<!doctype html><title>hetu_tpu graphboard</title>" \
           "<h3>Executor graph</h3>" + svg
    if tdict:
        rows = "".join(
            f"<tr><td>{html.escape(_PHASE_NAMES.get(k, k))}</td>"
            f"<td>{tdict[k]:.3f}</td></tr>"
            for k in ("prestep_ms", "compile_ms", "dispatch_ms",
                      "poststep_ms") if k in tdict)
        body += (f"<h3>step {tdict.get('step')} phase timings "
                 f"({tdict.get('step_ms', 0):.3f} ms total)</h3>"
                 f"<table border=1 cellpadding=4><tr><th>phase</th>"
                 f"<th>ms</th></tr>{rows}</table>")
    elif timings:
        body += ("<p><em>timings requested but no telemetry data — run a "
                 "step with HetuConfig(telemetry=&quot;metrics&quot;) or "
                 "HETU_TELEMETRY=metrics first "
                 "(docs/OBSERVABILITY.md)</em></p>")
    if findings:
        items = "".join(
            f"<li><code>{html.escape(str(f))}</code></li>"
            for f in findings)
        body += (f"<h3>hetulint findings ({len(findings)})</h3>"
                 f"<ul>{items}</ul>")
    with open(os.path.join(out_dir, "index.html"), "w") as f:
        f.write(body)
    return out_dir


def show(executor, port=9997, name=None, out_dir="graphboard_out",
         findings=None, lint=False, timings=False):
    """Render + serve on a background thread (reference show :11)."""
    global _server, _thread
    render(executor, name, out_dir, findings=findings, lint=lint,
           timings=timings)
    close()

    def _make(*a, **k):
        return http.server.SimpleHTTPRequestHandler(
            *a, directory=os.path.abspath(out_dir), **k)

    socketserver.TCPServer.allow_reuse_address = True
    _server = socketserver.TCPServer(("127.0.0.1", port), _make)
    _thread = threading.Thread(target=_server.serve_forever, daemon=True)
    _thread.start()
    return f"http://127.0.0.1:{port}/"


def close():
    """Stop the server (reference close :29)."""
    global _server, _thread
    if _server is not None:
        _server.shutdown()
        _server.server_close()
        _server = None
        _thread = None
