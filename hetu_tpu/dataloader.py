"""In-memory dataloader with background prefetch (reference
``python/hetu/dataloader.py``).

The reference keeps a 3-deep ring of pinned host buffers and overlaps H2D
copies on a dedicated stream (:26-55). Under JAX, dispatch is asynchronous —
``device_put`` of the next batch overlaps the current step's compute — so the
ring reduces to an index cursor plus an optional async device_put of the next
batch. Data-parallel sharding by rank (init_states :19-24) becomes sharding
the *global* batch across the mesh's dp axis in the executor.
"""
from __future__ import annotations

import time
from typing import Optional

import numpy as np

from . import telemetry as _telemetry
from .graph.node import Op


class Dataloader:
    def __init__(self, raw_data, batch_size, name="default", func=None,
                 drop_last=True, shuffle=False, seed=0):
        self.raw_data = np.asarray(raw_data)
        if self.raw_data.dtype == np.float64:
            self.raw_data = self.raw_data.astype(np.float32)
        self.batch_size = int(batch_size)
        self.name = name
        self.func = func
        self.drop_last = drop_last
        self.shuffle = shuffle
        self._rng = np.random.RandomState(seed)
        self.rank = None
        self.nrank = None
        self.init_states()

    def init_states(self, rank: Optional[int] = None, nrank: Optional[int] = None):
        """DP sharding by process rank for multi-host (reference :19-24).

        Single-process multi-chip DP does NOT shard here: the executor feeds
        the global batch and shards it over the mesh.
        """
        self.rank, self.nrank = rank, nrank
        n = self.raw_data.shape[0]
        if rank is not None and nrank is not None and nrank > 1:
            per = n // nrank
            self._data = self.raw_data[rank * per:(rank + 1) * per]
        else:
            self._data = self.raw_data
        self._order = np.arange(self._data.shape[0])
        n = self._data.shape[0]
        if self.drop_last:
            self.batch_num = n // self.batch_size
        else:
            self.batch_num = int(np.ceil(n / self.batch_size))
        self._cursor = 0

    def _maybe_reshuffle(self):
        if self._cursor == 0 and self.shuffle:
            self._rng.shuffle(self._order)

    def _next_batch(self) -> np.ndarray:
        self._maybe_reshuffle()
        i = self._cursor
        idx = self._order[i * self.batch_size:(i + 1) * self.batch_size]
        batch = self._data[idx]
        if self.func is not None:
            batch = self.func(batch)
        self._cursor = (self._cursor + 1) % self.batch_num
        return batch

    _peeked: Optional[np.ndarray] = None

    # -- elastic membership (hetu_tpu/elastic.py) --------------------------
    def load_elastic_partition(self, indices) -> None:
        """Re-point this loader at an explicit sample subset of
        ``raw_data`` (the exactly-once remaining-sample partition
        ``elastic.era_partitions`` computed at a resize commit). Cursor and
        any peeked batch reset — the new partition starts from its first
        batch; ``state_dict``/``load_state_dict`` keep working against the
        new partition's shape."""
        idx = np.asarray(indices, dtype=np.int64)
        self._data = self.raw_data[idx]
        self._order = np.arange(self._data.shape[0])
        n = self._data.shape[0]
        if self.drop_last:
            self.batch_num = n // self.batch_size
        else:
            self.batch_num = int(np.ceil(n / self.batch_size))
        self._cursor = 0
        self._peeked = None

    # -- resume support (resilience layer) ---------------------------------
    def state_dict(self) -> dict:
        """Epoch position as a flat dict of numpy arrays (checkpointable by
        TrainCheckpointer): cursor, shuffle order, MT19937 RNG position, and
        any peeked-but-unconsumed batch — restoring reproduces the exact
        batch sequence an uninterrupted run would have seen."""
        key, pos, has_gauss, cached = self._rng.get_state()[1:5]
        # copy: the epoch-wrap reshuffle mutates _order IN PLACE, and a
        # state captured mid-epoch must keep naming the permutation it saw
        d = {"cursor": np.asarray(self._cursor, np.int64),
             "order": np.array(self._order, copy=True),
             "rng_key": np.asarray(key),
             "rng_pos": np.asarray(pos, np.int64),
             "rng_has_gauss": np.asarray(has_gauss, np.int64),
             "rng_cached_gaussian": np.asarray(cached, np.float64)}
        if self._peeked is not None:
            d["peeked"] = np.asarray(self._peeked)
        return d

    def load_state_dict(self, d: dict) -> None:
        order = np.asarray(d["order"])
        if order.shape != self._order.shape:
            raise ValueError(
                f"dataloader state has {order.shape[0]} samples, this "
                f"loader has {self._order.shape[0]} — restoring onto a "
                "different dataset/sharding would silently skew batches")
        self._order = order.copy()
        self._cursor = int(d["cursor"])
        self._rng.set_state(("MT19937", np.asarray(d["rng_key"], np.uint32),
                             int(d["rng_pos"]), int(d["rng_has_gauss"]),
                             float(d["rng_cached_gaussian"])))
        self._peeked = (np.asarray(d["peeked"]) if "peeked" in d else None)

    _tel_handles = None   # (telemetry instance, wait histogram, cursor gauge)

    def get_arr(self) -> np.ndarray:
        tel = _telemetry.get()
        if tel is None:
            if self._peeked is not None:
                batch, self._peeked = self._peeked, None
                return batch
            return self._next_batch()
        # batch-wait: what the step actually waits on — ~0 on a peeked
        # (prefetched) batch, the transform cost otherwise; the cursor gauge
        # is the state_dict position an operator sees in hetutop. Handles
        # cached per telemetry instance: a registry lookup per batch is
        # measurable on sub-ms steps.
        h = self._tel_handles
        if h is None or h[0] is not tel:
            h = self._tel_handles = (
                tel,
                tel.metrics.histogram("hetu_dataloader_wait_ms",
                                      {"loader": self.name}),
                tel.metrics.gauge("hetu_dataloader_cursor",
                                  {"loader": self.name}))
        t0 = time.perf_counter()
        if self._peeked is not None:
            batch, self._peeked = self._peeked, None
        else:
            batch = self._next_batch()
        h[1].observe((time.perf_counter() - t0) * 1e3)
        h[2].set(self._cursor)
        return batch

    def peek_arr(self) -> np.ndarray:
        """The batch the next ``get_arr`` will return, without consuming it.
        Lets the PS runtime pull batch N+1's embedding rows while step N runs
        (reference prefetch, ParameterServerCommunicate.py:122-231)."""
        if self._peeked is None:
            self._peeked = self._next_batch()
        return self._peeked

    def get_cur_shape(self):
        return (self.batch_size,) + tuple(self._data.shape[1:])


class DataloaderOp(Op):
    """Graph node multiplexing one Dataloader per subexecutor name
    (reference dataloader.py:134)."""

    is_dataloader = True

    def __init__(self, dataloaders):
        super().__init__([], None)
        self.dataloaders = {d.name: d for d in dataloaders}
        self.name = f"DataloaderOp_{self.id}"

    def get_batch_num(self, name):
        return self.dataloaders[name].batch_num

    def get_batch(self, name):
        return self.dataloaders[name].get_arr()

    def peek_batch(self, name):
        return self.dataloaders[name].peek_arr()

    def get_cur_shape(self, name):
        return self.dataloaders[name].get_cur_shape()

    def set_dp_rank(self, rank, nrank):
        for d in self.dataloaders.values():
            d.init_states(rank, nrank)

    def state_dict(self, name) -> Optional[dict]:
        dl = self.dataloaders.get(name)
        return None if dl is None else dl.state_dict()

    def load_state_dict(self, name, d) -> None:
        if name in self.dataloaders:
            self.dataloaders[name].load_state_dict(d)

    def compute(self, input_vals, tc):
        raise AssertionError("Dataloader batches are supplied by the executor")


def dataloader_op(dataloaders):
    """Accepts [Dataloader, ...] or [[raw_data, batch_size, name], ...]
    (both forms appear in reference examples)."""
    dls = []
    for d in dataloaders:
        if isinstance(d, Dataloader):
            dls.append(d)
        else:
            dls.append(Dataloader(*d))
    return DataloaderOp(dls)


class GNNDataLoaderOp(Op):
    """Double-buffered graph-batch loader (reference dataloader.py:98).

    The handler produces the next graph tensor on each ``step``; kept
    host-driven like the reference, fed into the jitted step as a batch input.
    """

    is_dataloader = True
    _ops: list["GNNDataLoaderOp"] = []

    def __init__(self, handler, ctx=None):
        super().__init__([], ctx)
        self.handler = handler
        self._cur = None
        self._next = None
        GNNDataLoaderOp._ops.append(self)

    def close(self):
        """Deregister from the class-level step() registry — REQUIRED when a
        training run ends but the process lives on, or a later run's
        step() would fire this op's stale handler too."""
        if self in GNNDataLoaderOp._ops:
            GNNDataLoaderOp._ops.remove(self)

    def get_batch_num(self, name):
        return None

    def get_batch(self, name):
        return self._cur

    def get_cur_shape(self, name):
        return None if self._cur is None else tuple(np.asarray(self._cur).shape)

    @classmethod
    def step(cls, graph):
        for op in cls._ops:
            op._cur = op._next
            op._next = op.handler(graph)

    def compute(self, input_vals, tc):
        raise AssertionError("Dataloader batches are supplied by the executor")
