"""Shared utilities for examples, tests and the driver entry points."""
from __future__ import annotations

import os

import jax


def shard_map(f, mesh=None, in_specs=None, out_specs=None,
              axis_names=None, **kwargs):
    """``jax.shard_map`` across jax versions: newer jax exports it at top
    level (manual axes named via ``axis_names``); 0.4.x only has
    ``jax.experimental.shard_map``, where the same intent is spelled as its
    complement (``auto`` = the axes NOT manual).

    Known 0.4.x limit: forward-only and fully-manual programs work
    (ring attention, DistGCN), but differentiating through a PARTIAL-auto
    shard_map (the pp-pipeline step builders) still trips 0.4.x's
    experimental autodiff — those paths need the newer jax the seed was
    written against."""
    if hasattr(jax, "shard_map"):
        if axis_names is not None:
            kwargs["axis_names"] = axis_names
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kwargs)
    from jax.experimental.shard_map import shard_map as _shard_map
    if axis_names is not None:
        auto = frozenset(mesh.axis_names) - frozenset(axis_names)
        if auto:
            kwargs["auto"] = auto
    # 0.4.x's replication checker predates the varying-manual-axes (vma)
    # type system the pipeline carries rely on (pvary below is an identity
    # there) — it would reject those programs, so it is off by default
    kwargs.setdefault("check_rep", False)
    return _shard_map(f, mesh, in_specs=in_specs, out_specs=out_specs,
                      **kwargs)


def pvary(x, axis_names):
    """Mark ``x`` device-varying over the named manual axes: newer jax's
    ``lax.pcast(..., to="varying")`` feeds the vma type system; on jax
    without it this is an identity (no vma tracking to satisfy)."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, tuple(axis_names), to="varying")
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(x, tuple(axis_names))
    return x


def ensure_devices(n_devices: int) -> None:
    """Ensure >= n_devices jax devices exist, forcing a virtual CPU mesh if
    the host has fewer real chips (the reference requires a physical GPU per
    rank; the TPU build validates multi-chip layouts on virtual devices,
    SURVEY.md §4's local-process-cluster strategy).

    Works whether or not backends are initialized: clear first, then
    reconfigure — ``jax_num_cpu_devices`` refuses updates while a backend is
    live, and a sitecustomize may pin another platform, so the config updates
    are authoritative, not env vars.
    """
    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        # honor an explicit CPU request BEFORE the first jax.devices() call:
        # the sitecustomize pins the tunneled platform, whose backend INIT
        # can hang outright when the tunnel is down (observed 2026-07-30) —
        # the driver's CPU-mesh dryrun must never depend on tunnel health.
        # (if a backend is already live this update is a silent no-op; the
        # device-count check below handles that case)
        jax.config.update("jax_platforms", "cpu")
    if len(jax.devices()) >= n_devices:
        return
    import jax.extend.backend as jax_backend
    jax_backend.clear_backends()
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", n_devices)
    assert len(jax.devices()) >= n_devices, (
        f"virtual CPU mesh provisioning failed: need {n_devices}, "
        f"got {len(jax.devices())}")
