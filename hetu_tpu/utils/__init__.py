"""Shared utilities for examples, tests and the driver entry points."""
from __future__ import annotations

import os

import jax


def ensure_devices(n_devices: int) -> None:
    """Ensure >= n_devices jax devices exist, forcing a virtual CPU mesh if
    the host has fewer real chips (the reference requires a physical GPU per
    rank; the TPU build validates multi-chip layouts on virtual devices,
    SURVEY.md §4's local-process-cluster strategy).

    Works whether or not backends are initialized: clear first, then
    reconfigure — ``jax_num_cpu_devices`` refuses updates while a backend is
    live, and a sitecustomize may pin another platform, so the config updates
    are authoritative, not env vars.
    """
    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        # honor an explicit CPU request BEFORE the first jax.devices() call:
        # the sitecustomize pins the tunneled platform, whose backend INIT
        # can hang outright when the tunnel is down (observed 2026-07-30) —
        # the driver's CPU-mesh dryrun must never depend on tunnel health.
        # (if a backend is already live this update is a silent no-op; the
        # device-count check below handles that case)
        jax.config.update("jax_platforms", "cpu")
    if len(jax.devices()) >= n_devices:
        return
    import jax.extend.backend as jax_backend
    jax_backend.clear_backends()
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", n_devices)
    assert len(jax.devices()) >= n_devices, (
        f"virtual CPU mesh provisioning failed: need {n_devices}, "
        f"got {len(jax.devices())}")
