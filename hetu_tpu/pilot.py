"""hetupilot: the bounded self-tuning controller (ROADMAP item 5, leg 2).

hetuwatch (telemetry/watch.py) judges the live run against the adopted
plan and emits machine-readable ``PlanDelta`` recommendations
(``watch.DELTA_KINDS`` — the ONE registry of bounded deltas). This
module is what finally *acts* on them, under guardrails strict enough to
trust against a production job:

- **Eras.** Every actuation is one era: propose (ledger record +
  pre-actuation baseline) -> actuate inside a parked identity-resize
  barrier of the elastic two-phase protocol (the hetusave shape:
  propose/drain/quiesce-proof/work/tagged-abort — the abort path is the
  safety valve, so any failure releases the old world untouched) ->
  measure K post-actuation watch windows -> verdict. A commit seals the
  era with a ``pilot_commit``-tagged barrier; a regression (after/before
  step-time above ``regress_ratio``) REVERTS the delta through the same
  protocol under a ``pilot_rollback`` tag, restoring host params,
  optimizer slots, qresid AND every PS shard bit-for-bit from the era's
  pre-actuation capture, then blacklists the delta for a cool-down. The
  scheduler's ``kResizeState`` era counters attribute every sealed era
  to its cause (``wire_constants.ACTUATION_TAGS``).

- **Hysteretic governor.** Minimum inter-actuation spacing, per-delta
  blacklist with cool-down, a global actuation budget, and abstention
  while a resize is pending, while another worker exists (the hetusave
  single-rank refusal), or while the client's chaos/retry/timeout/CRC
  counters are climbing — a flaky network must make the controller sit
  on its hands, not oscillate (``plan_flap`` in faults.py is the
  adversarial test driver).

- **Persistent ledger.** ``pilot.jsonl`` records every phase of every
  era (propose/actuate/verdict/abstain). A crash mid-actuation leaves an
  open era; the next incarnation (state rebuilt from config + hetusave
  restore, i.e. the pre-actuation plan) marks it ``interrupted``, counts
  it against the budget and blacklists the delta — restores always land
  in a known era. ``heturun`` folds the ledger into run_summary.json.

jax-free at module level on purpose: ``bin/hetupilot`` loads this file
standalone (the bin/hetuwatch pattern) for the ledger report and the
``--check`` self-test; everything that needs jax / the PS runtime is
imported lazily inside the actuator methods.
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time
from collections import deque
from typing import Optional

# -- knob defaults (docs/FAULT_TOLERANCE.md "Self-tuning with guardrails") --
DEFAULT_K = 5               # post-actuation watch windows per verdict
DEFAULT_WARMUP = 2          # windows discarded after actuation (re-warm)
DEFAULT_BASELINE = 5        # pre-actuation windows in the baseline median
DEFAULT_REGRESS_RATIO = 1.10   # after/before above this rolls back
DEFAULT_SPACING = 50        # min steps between actuations
DEFAULT_COOLDOWN = 200      # blacklist steps after a rollback/failure
DEFAULT_BUDGET = 3          # actuation eras per run, total
DEFAULT_ALLOW = "comm_quant,comm_mode_flip"   # ps_server_grow/remesh opt-in
BARRIER_TIMEOUT_S = 120.0


class PilotError(RuntimeError):
    """Refused or failed actuation; the step that raised it continues."""


def _watch_mod():
    """The PlanDelta registry's home (telemetry/watch.py), importable from
    BOTH contexts: inside the hetu_tpu package, or standalone when
    bin/hetupilot loaded this file by path (watch.py is stdlib-only at
    module level, so the fallback never drags jax in)."""
    try:
        from .telemetry import watch
        return watch
    except ImportError:
        import importlib.util
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "telemetry", "watch.py")
        mod = sys.modules.get("_hetuwatch")
        if mod is not None:
            return mod
        spec = importlib.util.spec_from_file_location("_hetuwatch", path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules["_hetuwatch"] = mod
        spec.loader.exec_module(mod)
        return mod


def _story_mod():
    """The shared ledger reader's home (telemetry/story.py), resolved the
    same two-context way as :func:`_watch_mod` — story.py is stdlib-only,
    so the standalone fallback never drags jax in."""
    try:
        from .telemetry import story
        return story
    except ImportError:
        import importlib.util
        mod = sys.modules.get("_hetustory")
        if mod is not None:
            return mod
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "telemetry", "story.py")
        spec = importlib.util.spec_from_file_location("_hetustory", path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules["_hetustory"] = mod
        spec.loader.exec_module(mod)
        return mod


def delta_signature(delta: dict) -> str:
    """Blacklist identity of one PlanDelta: kind + target + arg — two
    recommendations proposing the same change share one cool-down."""
    return (f"{delta.get('kind')}:{delta.get('target') or ''}"
            f":{delta.get('arg') or ''}")


def median(vals):
    s = sorted(float(v) for v in vals)
    n = len(s)
    if not n:
        raise ValueError("median of an empty window")
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


# ---------------------------------------------------------------------------
# Governor: the hysteretic actuation gate (pure, jax-free)
# ---------------------------------------------------------------------------

class Governor:
    """Decides whether one proposed delta may actuate NOW. Stateful but
    pure (no I/O): the caller supplies every runtime fact as a keyword.
    ``consider`` returns ``"ok"`` or a stable refusal reason — the
    ledger/abstain records and the tests key on these exact strings."""

    REFUSALS = ("budget-exhausted", "spacing", "blacklisted",
                "multi-worker", "resize-pending", "chaos-climbing")

    def __init__(self, spacing: int = DEFAULT_SPACING,
                 cooldown: int = DEFAULT_COOLDOWN,
                 budget: int = DEFAULT_BUDGET):
        self.spacing = max(0, int(spacing))
        self.cooldown = max(0, int(cooldown))
        self.budget = max(0, int(budget))
        self.spent = 0
        self.last_actuation_step: Optional[int] = None
        self._ban: dict = {}       # signature -> step the ban expires at

    def consider(self, delta: dict, step: int, *, n_workers: int = 1,
                 resize_pending: bool = False,
                 chaos_climbing: bool = False) -> str:
        step = int(step)
        if self.spent >= self.budget:
            return "budget-exhausted"
        if self.last_actuation_step is not None \
                and step - self.last_actuation_step < self.spacing:
            return "spacing"
        until = self._ban.get(delta_signature(delta))
        if until is not None and step < until:
            return "blacklisted"
        if n_workers != 1:
            # the hetusave precedent: this controller captures and
            # restores only its OWN rank's state — a rollback in a bigger
            # world would leave the other ranks on the new plan
            return "multi-worker"
        if resize_pending:
            return "resize-pending"
        if chaos_climbing:
            return "chaos-climbing"
        return "ok"

    def note_actuation(self, step: int) -> None:
        self.spent += 1
        self.last_actuation_step = int(step)

    def ban(self, signature: str, step: int) -> None:
        self._ban[signature] = int(step) + self.cooldown

    def banned_until(self, signature: str) -> Optional[int]:
        return self._ban.get(signature)


# ---------------------------------------------------------------------------
# Ledger: pilot.jsonl (append-only, crash-ordered)
# ---------------------------------------------------------------------------

class ActuationLedger:
    """One JSONL line per phase of every era. The file is the pilot's
    ONLY persistent state: interrupted-era detection, the run summary and
    ``bin/hetupilot``'s report all read it back."""

    def __init__(self, path: str):
        self.path = path
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)

    def append(self, **rec) -> None:
        rec.setdefault("ts", round(time.time(), 3))
        # run identity (heturun-generated, env-inherited): restarted-run
        # rows in the same directory disambiguate instead of interleaving
        run_id = os.environ.get("HETU_RUN_ID")
        if run_id:
            rec.setdefault("run_id", run_id)
            try:
                rec.setdefault("inc", int(os.environ.get(
                    "HETU_RUN_INCARNATION", "0")))
            except ValueError:
                pass
        with open(self.path, "a", encoding="utf-8") as f:
            f.write(json.dumps(rec, sort_keys=True) + "\n")
            f.flush()
            os.fsync(f.fileno())

    def records(self) -> list:
        """Object rows, torn tail from a crash mid-write tolerated (the
        shared hetustory reader)."""
        return _story_mod().read_jsonl(self.path)

    def last_era(self) -> int:
        return max((int(r["era"]) for r in self.records()
                    if r.get("era") is not None), default=0)

    @staticmethod
    def open_eras(records: list) -> list:
        """Eras that actuated but never reached a verdict — exactly the
        crash-mid-actuation survivors the next incarnation must seal."""
        actuated, decided = set(), set()
        for r in records:
            era = r.get("era")
            if era is None:
                continue
            if r.get("phase") in ("propose", "actuate"):
                actuated.add(int(era))
            elif r.get("phase") == "verdict":
                decided.add(int(era))
        return sorted(actuated - decided)

    @staticmethod
    def summarize(records: list) -> dict:
        """The run_summary.json / ``bin/hetupilot`` rollup: era history
        (cause, delta, before/after, verdict) + counts."""
        eras: dict = {}
        abstains = 0
        for r in records:
            if r.get("phase") == "abstain":
                abstains += 1
                continue
            era = r.get("era")
            if era is None:
                continue
            e = eras.setdefault(int(era), {"era": int(era)})
            if r.get("phase") == "propose":
                e["delta"] = r.get("delta")
                e["cause"] = r.get("cause")
                e["step"] = r.get("step")
                e["baseline_ms"] = r.get("baseline_ms")
            elif r.get("phase") == "verdict":
                e["verdict"] = r.get("verdict")
                for k in ("after_ms", "ratio", "error"):
                    if r.get(k) is not None:
                        e[k] = r.get(k)
        history = [eras[k] for k in sorted(eras)]
        verdicts = [e.get("verdict") for e in history]
        return {"eras": len(history),
                "commits": verdicts.count("commit"),
                "rollbacks": verdicts.count("rollback"),
                "regressed_kept": verdicts.count("regressed"),
                "failed": verdicts.count("failed"),
                "interrupted": verdicts.count("interrupted"),
                "open": sum(1 for v in verdicts if v is None),
                "abstains": abstains,
                "history": history}


def summarize_dir(directory: str) -> Optional[dict]:
    """Summary of a pilot directory's ledger (None when there is none) —
    what heturun folds into run_summary.json under ``"pilot"``."""
    path = os.path.join(directory, "pilot.jsonl")
    if not os.path.exists(path):
        return None
    return ActuationLedger.summarize(ActuationLedger(path).records())


# ---------------------------------------------------------------------------
# The controller
# ---------------------------------------------------------------------------

class Pilot:
    """Feedback controller attached to one Executor (PS/Hybrid jobs only:
    the actuation barrier and the era counters live in the PS scheduler).
    ``step_boundary`` is the ONLY hot entry point — it runs at the same
    safe point as the elastic agent, after that agent's own commit, and
    pays a couple of attribute checks when nothing is pending."""

    def __init__(self, ex, *, k: int = DEFAULT_K,
                 warmup: int = DEFAULT_WARMUP,
                 baseline_n: int = DEFAULT_BASELINE,
                 regress_ratio: float = DEFAULT_REGRESS_RATIO,
                 spacing: int = DEFAULT_SPACING,
                 cooldown: int = DEFAULT_COOLDOWN,
                 budget: int = DEFAULT_BUDGET,
                 directory: str = "hetu_pilot",
                 allow=None, force: Optional[str] = None,
                 timeout: float = BARRIER_TIMEOUT_S):
        self.ex = ex
        self.k = max(1, int(k))
        self.warmup = max(0, int(warmup))
        self.baseline_n = max(2, int(baseline_n))
        self.regress_ratio = float(regress_ratio)
        self.timeout = float(timeout)
        self.allow = tuple(s.strip() for s in
                           (allow if allow is not None
                            else DEFAULT_ALLOW).split(",")
                           if s.strip()) if isinstance(allow, str) or \
            allow is None else tuple(allow)
        self.dir = directory
        self.ledger = ActuationLedger(os.path.join(directory, "pilot.jsonl"))
        self.governor = Governor(spacing=spacing, cooldown=cooldown,
                                 budget=budget)
        self.state = "idle"               # "idle" | "measuring"
        self._rows: deque = deque(maxlen=max(64, self.baseline_n
                                             + self.warmup + self.k + 8))
        self._pending = None              # (delta, cause) awaiting governor
        self._era = None                  # live era dict while measuring
        self._boundary_step = None        # idempotence across delegation
        self._last_decision = None        # (sig, reason) abstain throttle
        self._chaos_sample = None         # last ClientStats chaos counters
        self._force = self._parse_force(force)
        self._lock = threading.Lock()     # ledger/era state vs feed threads
        tel = getattr(ex, "telemetry", None)
        self._g_state = self._c_act = self._c_rb = None
        if tel is not None:
            self._g_state = tel.metrics.gauge("hetu_pilot_state")
            self._c_act = tel.metrics.counter("hetu_pilot_actuations_total")
            self._c_rb = tel.metrics.counter("hetu_pilot_rollbacks_total")
            self._g_state.set(0.0)
        self._seal_interrupted_eras()

    # -- construction -------------------------------------------------------
    @classmethod
    def from_env(cls, ex):
        env = os.environ
        directory = env.get("HETU_PILOT_DIR", "")
        if not directory:
            tel_dir = env.get("HETU_TELEMETRY_DIR", "")
            directory = (os.path.join(tel_dir, "pilot") if tel_dir
                         else "hetu_pilot")
        return cls(
            ex,
            k=int(env.get("HETU_PILOT_K", str(DEFAULT_K))),
            warmup=int(env.get("HETU_PILOT_WARMUP", str(DEFAULT_WARMUP))),
            baseline_n=int(env.get("HETU_PILOT_BASELINE",
                                   str(DEFAULT_BASELINE))),
            regress_ratio=float(env.get("HETU_PILOT_REGRESS_RATIO",
                                        str(DEFAULT_REGRESS_RATIO))),
            spacing=int(env.get("HETU_PILOT_SPACING", str(DEFAULT_SPACING))),
            cooldown=int(env.get("HETU_PILOT_COOLDOWN",
                                 str(DEFAULT_COOLDOWN))),
            budget=int(env.get("HETU_PILOT_BUDGET", str(DEFAULT_BUDGET))),
            directory=directory,
            allow=env.get("HETU_PILOT_ALLOW", None),
            force=env.get("HETU_PILOT_FORCE", None))

    @staticmethod
    def _parse_force(spec: Optional[str]):
        """``HETU_PILOT_FORCE=kind[:target[:arg]]@step`` — inject one
        delta at a step regardless of divergence (the governor still
        applies). HETU_TEST_MODE-gated like the fault kinds: forcing an
        actuation is a test/chaos instrument, not an operator surface."""
        if not spec:
            return None
        from_env = os.environ.get("HETU_TEST_MODE", "")
        if from_env in ("", "0"):
            raise PilotError(
                "HETU_PILOT_FORCE requires HETU_TEST_MODE=1 (it is a test "
                "instrument, not an operator control)")
        body, _, at = spec.partition("@")
        if not at:
            raise PilotError(
                f"HETU_PILOT_FORCE={spec!r}: expected kind[:target[:arg]]"
                "@step")
        parts = body.split(":")
        kind = parts[0]
        target = parts[1] if len(parts) > 1 and parts[1] else None
        arg = parts[2] if len(parts) > 2 and parts[2] else None
        delta = _watch_mod().make_delta(kind, target=target, arg=arg,
                                        expected_gain=0.0, confidence=1.0)
        return (delta, int(at))

    def _seal_interrupted_eras(self) -> None:
        """Crash-mid-actuation recovery: this incarnation's plan came from
        config (+ hetusave restore), i.e. the PRE-actuation era, so an
        open era needs no revert — it needs sealing: verdict
        ``interrupted``, budget consumed, delta blacklisted."""
        records = self.ledger.records()
        open_eras = ActuationLedger.open_eras(records)
        if not open_eras:
            return
        by_era = {}
        for r in records:
            if r.get("phase") == "propose" and r.get("era") is not None:
                by_era[int(r["era"])] = r
        for era in open_eras:
            prop = by_era.get(era, {})
            delta = prop.get("delta") or {}
            sig = delta_signature(delta) if delta else "?"
            step = int(prop.get("step", 0))
            self.ledger.append(era=era, phase="verdict",
                               verdict="interrupted", step=step, delta=delta)
            if delta:
                self.governor.ban(sig, step)
            self.governor.spent += 1
            # hetustory post-mortem: the previous incarnation died
            # mid-actuation — freeze the window around the interrupted era
            try:
                from .resilience import _incident
                _incident("pilot_interrupted", step=step, era=int(era),
                          delta_signature=sig)
            except Exception:  # noqa: BLE001
                pass

    # -- feeds (called from SubExecutor._watch_observe) ---------------------
    def feed_row(self, row: dict) -> None:
        """One watch observation (the residual stream). Abstain markers
        and row shapes without a step time contribute nothing."""
        if "abstain" in row or "step_ms" not in row:
            return
        self._rows.append((int(row["step"]), float(row["step_ms"])))

    def feed_recommendation(self, delta: dict, cause: dict) -> None:
        """A machine-readable PlanDelta latched by the watch (the
        plan_divergence path). Kept pending until the governor admits or
        durably refuses it at a step boundary."""
        if self.state != "idle" or self._pending is not None:
            return
        if delta.get("kind") not in self.allow:
            self._abstain(delta_signature(delta), "kind-not-allowed",
                          int(cause.get("step", 0)))
            return
        self._pending = (dict(delta), dict(cause))

    def feed_event(self, name: str, event: dict) -> None:
        """SLO breaches carry no delta of their own: re-ask the
        recommender with the watch's current worst leg."""
        if name != "slo_breach" or self.state != "idle" \
                or self._pending is not None:
            return
        pw = getattr(self.ex, "plan_watch", None)
        if pw is None or not pw._ewma:
            return
        leg = max(pw._ewma, key=pw._ewma.get)
        rec = _watch_mod().recommend(pw.plan or {}, leg,
                                     float(pw._ewma[leg]))
        if rec.get("delta") is not None:
            cause = dict(event)
            cause["via"] = "slo_breach"
            self.feed_recommendation(rec["delta"], cause)

    # -- the step-boundary hook ---------------------------------------------
    def step_boundary(self, sub, step: int) -> None:
        """Actuate / verdict at the training-loop safe point. Never
        raises: a refused or failed actuation logs and training
        continues. Idempotent per step — an actuation rebuilds the
        subexecutors and the stale one delegates its run(), which calls
        back into this hook at the same step."""
        step = int(step)
        if self._boundary_step == step:
            return
        self._boundary_step = step
        try:
            if self._force is not None and self.state == "idle" \
                    and self._pending is None and step >= self._force[1]:
                delta, at = self._force
                self._force = None
                self._pending = (delta, {"forced": True, "step": at})
            if self.state == "measuring":
                self._maybe_verdict(step)
            elif self._pending is not None:
                self._maybe_actuate(step)
        except Exception as e:  # noqa: BLE001 — controller must never
            # take the training step down with it
            print(f"# hetupilot: step {step}: {e!r}", file=sys.stderr,
                  flush=True)

    # -- actuation ----------------------------------------------------------
    def _abstain(self, sig: str, reason: str, step: int) -> None:
        if self._last_decision == (sig, reason):
            return   # one ledger line per distinct decision, not per step
        self._last_decision = (sig, reason)
        self.ledger.append(phase="abstain", signature=sig, reason=reason,
                           step=int(step))

    def _chaos_climbing(self) -> bool:
        """True while the client's failure counters (retries, timeouts,
        CRC rejects, chaos faults) moved since the LAST check — the
        network is misbehaving, so measurements are untrustworthy and the
        governor sits out."""
        rt = getattr(self.ex, "ps_runtime", None)
        if rt is None:
            return False
        try:
            cs = rt.comm.ClientStats()
        except Exception:  # noqa: BLE001 — stats are advisory
            return False
        sample = tuple(int(cs.get(k, 0)) for k in
                       ("retries", "timeouts", "crc_rejects",
                        "chaos_faults"))
        prev, self._chaos_sample = self._chaos_sample, sample
        if prev is None:
            return False
        return any(b > a for a, b in zip(prev, sample))

    def _maybe_actuate(self, step: int) -> None:
        delta, cause = self._pending
        sig = delta_signature(delta)
        # cheap, pure gates first (no RPC)
        reason = self.governor.consider(delta, step)
        if reason == "ok" and self._chaos_climbing():
            reason = "chaos-climbing"
        st = None
        if reason == "ok":
            st = self._scheduler_state()
            reason = self.governor.consider(
                delta, step, n_workers=st["n_workers"],
                resize_pending=bool(st["pending_version"]))
        if reason != "ok":
            self._abstain(sig, reason, step)
            if reason in ("budget-exhausted", "blacklisted", "multi-worker"):
                self._pending = None   # durable refusal: drop the delta
            return
        if len(self._rows) < 2:
            self._abstain(sig, "no-baseline", step)
            return
        self._last_decision = None
        baseline = median([ms for _, ms in
                           list(self._rows)[-self.baseline_n:]])
        era = self.ledger.last_era() + 1
        era_dir = os.path.join(self.dir, f"era_{era:04d}")
        self.ledger.append(era=era, phase="propose", step=step, delta=delta,
                           cause=_jsonable(cause),
                           baseline_ms=round(baseline, 4))
        self._maybe_kill("propose")
        try:
            if delta["kind"] == "ps_server_grow":
                snapshot = undo = None
                self._actuate_grow()
            else:
                def work(st, addrs):
                    snap = self._capture(era_dir, addrs)
                    self._maybe_kill("actuate")
                    return snap, self._apply(delta)
                snapshot, undo = self._barrier(work, tag="none")
        except Exception as e:  # noqa: BLE001 — a failed actuation is a
            # sealed era, never a dead job: the barrier's abort released
            # the old world untouched
            self.ledger.append(era=era, phase="verdict", verdict="failed",
                               step=step, delta=delta, error=repr(e))
            self.governor.ban(sig, step)
            self.governor.note_actuation(step)
            self._pending = None
            print(f"# hetupilot: era {era} actuation failed: {e!r}",
                  file=sys.stderr, flush=True)
            return
        self._pending = None
        self.governor.note_actuation(step)
        self.ledger.append(era=era, phase="actuate", step=step, delta=delta)
        self._era = {"era": era, "delta": delta, "sig": sig, "dir": era_dir,
                     "baseline": baseline, "snapshot": snapshot,
                     "undo": undo, "actuated_step": step}
        self.state = "measuring"
        if self._c_act is not None:
            self._c_act.inc()
            self._g_state.set(1.0)
        self._tel_event("pilot_actuate", era=era, step=step,
                        kind=delta["kind"], target=delta.get("target"),
                        arg=_jsonable(delta.get("arg")),
                        baseline_ms=round(baseline, 4))

    def _maybe_verdict(self, step: int) -> None:
        era = self._era
        after_rows = [ms for s, ms in self._rows
                      if s > era["actuated_step"]]
        usable = after_rows[self.warmup:]
        if len(usable) < self.k:
            return
        after = median(usable[-self.k:])
        ratio = after / max(era["baseline"], 1e-9)
        delta, sig = era["delta"], era["sig"]
        self._maybe_kill("pre_verdict")
        reversible = _watch_mod().DELTA_KINDS.get(
            delta["kind"], {}).get("reversible", False)
        if ratio <= self.regress_ratio:
            verdict = "commit"
            self._barrier(lambda st, addrs: None, tag="pilot_commit")
        elif not reversible or era["undo"] is None:
            verdict = "regressed"   # irreversible: keep, blacklist, record
            self.governor.ban(sig, step)
        else:
            verdict = "rollback"

            def work(st, addrs):
                era["undo"]()
                self._restore(era["snapshot"], era["dir"])
            self._barrier(work, tag="pilot_rollback")
            self.governor.ban(sig, step)
            if self._c_rb is not None:
                self._c_rb.inc()
        self.ledger.append(era=era["era"], phase="verdict", verdict=verdict,
                           step=step, delta=delta,
                           before_ms=round(era["baseline"], 4),
                           after_ms=round(after, 4),
                           ratio=round(ratio, 4))
        self._tel_event(f"pilot_{verdict}", era=era["era"], step=step,
                        kind=delta["kind"], before_ms=round(era["baseline"], 4),
                        after_ms=round(after, 4), ratio=round(ratio, 4))
        self._era = None
        self.state = "idle"
        self._last_decision = None
        if self._g_state is not None:
            self._g_state.set(0.0)

    # -- the two-phase barrier (the hetusave park/quiesce/release shape) ----
    def _scheduler_state(self) -> dict:
        from .elastic import resize_state, sched_addr_from_env
        host, port = sched_addr_from_env()
        return resize_state(host, port)

    def _barrier(self, work, tag: str):
        """Run ``work(state, server_addrs)`` inside a parked identity
        resize: propose -> this worker's own commit thread parks as the
        one drained survivor -> quiesce proof (pushes_ok == applied
        updates, the exactly-once ledger algebra) -> work -> tagged abort
        releases the old world. Any failure aborts untagged, so the era
        counters only ever count completed work."""
        from . import ps as ps_pkg
        from .elastic import (_query_book, commit_resize, finish_resize,
                              propose_resize, resize_state,
                              sched_addr_from_env)
        ex = self.ex
        rt = ex.ps_runtime
        comm = ps_pkg.get_worker_communicate()
        host, port = sched_addr_from_env()
        rank = int(os.environ.get("WORKER_ID", "0"))
        step = int(ex.state.get("step", 0))
        rt.drain()
        st = resize_state(host, port)
        nw, ns = int(st["n_workers"]), int(st["n_servers"])
        if nw != 1:
            raise PilotError(f"actuation with {nw} workers is not "
                             "supported (single-rank capture/restore)")
        if st["pending_version"]:
            raise PilotError("a resize is already pending")
        propose_resize(host, port, nw, ns)
        parked: dict = {}

        def _park():
            try:
                parked["world"] = commit_resize(host, port, rank, step,
                                                timeout=self.timeout)
            except Exception as e:  # noqa: BLE001 — surfaced by the poll
                parked["error"] = e

        th = threading.Thread(target=_park, name="hetupilot-park",
                              daemon=True)
        released = False
        try:
            th.start()
            deadline = time.monotonic() + self.timeout
            while True:
                st = resize_state(host, port)
                if st["pending_version"] and \
                        st["drain_count"] >= st["drain_needed"]:
                    break
                if "error" in parked:
                    raise PilotError(
                        f"drain barrier failed: {parked['error']!r}")
                if time.monotonic() > deadline:
                    raise PilotError(
                        f"drain barrier timeout after {self.timeout}s")
                time.sleep(0.002)
            # quiesce proof: every push this (only) worker ever made has
            # been applied — nothing in flight can land mid-actuation
            cs = comm.ClientStats()
            applied = 0
            for s in range(ns):
                ss = comm.ServerStats(s)
                applied += int(ss["updates"]) - max(
                    int(ss["restored_updates"]), 0)
            if int(cs["pushes_ok"]) != applied:
                raise PilotError(
                    f"quiesce proof failed: pushes_ok {cs['pushes_ok']} != "
                    f"applied updates {applied}")
            addrs, _alive = _query_book(host, port)
            result = work(st, addrs)
            finish_resize(host, port, abort=True, tag=tag)
            released = True
            th.join(timeout=self.timeout)
            if "error" in parked:
                raise PilotError(
                    f"parked worker failed to release: {parked['error']!r}")
            return result
        except BaseException:
            if not released:
                try:   # best-effort untagged release — never count the era
                    finish_resize(host, port, abort=True)
                except Exception:  # noqa: BLE001 — scheduler may be gone
                    pass
                th.join(timeout=5.0)
            raise

    # -- capture / restore --------------------------------------------------
    def _capture(self, era_dir: str, addrs) -> dict:
        """Pre-actuation state, complete enough for a bit-identical
        rollback: host params/slots/op-state/cursors via the checkpoint
        capture, qresid alongside (the hetusave pattern), and EVERY PS
        shard (data + server optimizer slots + versions) into the era
        directory via per-key kParamSave."""
        import numpy as np

        from .elastic import server_list_params, server_param_save
        from .resilience import capture_executor_state
        ex = self.ex
        snap = capture_executor_state(ex)
        snap["qresid"] = {
            str(i): np.asarray(ex.state["qresid"][id(n)])
            for i, n in enumerate(ex._qresid_ordered())}
        os.makedirs(era_dir, exist_ok=True)
        keys_by_addr: dict = {}
        for addr in addrs:
            for row in server_list_params(addr):
                server_param_save(addr, row["key"], era_dir)
                keys_by_addr.setdefault(addr, []).append(row["key"])
        snap["_ps_keys"] = keys_by_addr
        return snap

    def _restore(self, snap: dict, era_dir: str) -> None:
        """Rollback restore (inside the barrier, AFTER the delta's undo
        rewired the graph back): PS shards from the era dir, then host
        state — params, slots, op state, qresid, dataloader cursors."""
        import jax
        import jax.numpy as jnp

        from .elastic import server_param_load
        from .resilience import load_executor_state
        ex = self.ex
        for addr, keys in snap.get("_ps_keys", {}).items():
            for key in keys:
                server_param_load(addr, key, era_dir)
        rt = ex.ps_runtime
        rt._prefetched.clear()   # prefetched rows predate the restore
        for p in rt.params.values():
            if not p.sparse:
                p.host_value = rt.pull_dense_value(p)
        load_executor_state(ex, snap)
        for i, n in enumerate(ex._qresid_ordered()):
            key = str(i)
            if key in snap.get("qresid", {}):
                v = jnp.asarray(snap["qresid"][key], jnp.float32)
                if ex.config.mesh is not None:
                    from jax.sharding import NamedSharding
                    from jax.sharding import PartitionSpec as P
                    v = jax.device_put(
                        v, NamedSharding(ex.config.mesh, P()))
                ex.state["qresid"][id(n)] = v

    # -- actuators ----------------------------------------------------------
    def _apply(self, delta: dict):
        """Apply one delta to the live executor; returns the undo
        callable a rollback runs BEFORE restoring values."""
        kind = delta["kind"]
        if kind == "comm_quant":
            return self._apply_comm_quant(delta)
        if kind == "comm_mode_flip":
            return self._apply_comm_mode_flip(delta)
        if kind == "remesh":
            return self._apply_remesh(delta)
        raise PilotError(f"no actuator for delta kind {kind!r}")

    def _apply_comm_quant(self, delta: dict):
        """Arm/disarm the PS int8 wire (the EQuARX trade): pure wire-level
        — the traced program never changes, so no rebuild."""
        ex = self.ex
        rt = ex.ps_runtime
        new = delta.get("arg") or "int8"
        old = rt.comm_quant
        if new == old:
            raise PilotError(f"comm_quant already {new!r}")
        if not hasattr(rt.comm, "SetCommQuant"):
            raise PilotError("worker communicator has no SetCommQuant")
        rt.comm.SetCommQuant(new != "off")
        rt.comm_quant = new
        pw = getattr(ex, "plan_watch", None)
        if pw is not None and pw.plan:
            pw.plan["comm_quant"] = new

        def undo():
            rt.comm.SetCommQuant(old != "off")
            rt.comm_quant = old
            if pw is not None and pw.plan:
                pw.plan["comm_quant"] = old
        return undo

    def _find_opt(self, var):
        for opt in self.ex._opt_nodes():
            for i, v in enumerate(opt.vars):
                if v is var:
                    return opt, i
        raise PilotError(f"param {var.name!r} has no optimizer slot")

    def _apply_comm_mode_flip(self, delta: dict):
        ex = self.ex
        target, mode = delta.get("target"), delta.get("arg")
        if mode not in ("AllReduce", "PS"):
            raise PilotError(f"comm_mode_flip arg must be AllReduce or PS, "
                             f"got {mode!r}")
        if mode == "AllReduce":
            p = next((p for p in ex.ps_runtime.params.values()
                      if p.node.name == target), None)
            if p is None:
                raise PilotError(f"no PS-resident param {target!r} to flip")
            if p.sparse:
                raise PilotError(
                    f"{target!r} is a sparse embedding: lookups need the "
                    "PS row pulls, only dense decisions flip")
            old_ps_id = p.ps_id
            self._flip_ps_to_allreduce(p)

            def undo():
                var = next(n for n in ex.param_nodes if n.name == target)
                self._flip_allreduce_to_ps(var, ps_id=old_ps_id)
            return undo
        var = next((n for n in ex.param_nodes if n.name == target), None)
        if var is None:
            raise PilotError(f"no device-resident param {target!r} to flip")
        self._flip_allreduce_to_ps(var)

        def undo():
            p = ex.ps_runtime.params.get(id(var))
            if p is not None:
                self._flip_ps_to_allreduce(p)
        return undo

    def _flip_ps_to_allreduce(self, p) -> None:
        """Move one dense param's ownership server -> device: pull value +
        server optimizer slots, rewire the optimizer's grad input from the
        PS push to an in-program AllReduce, rebuild the subexecutors."""
        import numpy as np

        ex = self.ex
        rt = ex.ps_runtime
        var = p.node
        value = rt.pull_dense_value(p)
        slot_host = self._pull_server_slots(p)
        opt, i = self._find_opt(var)
        from .graph.ops.comm import allreduceCommunicate_op
        push = opt.inputs[i]
        grad = push.inputs[0]
        opt.inputs[i] = allreduceCommunicate_op(grad, param_node=var)
        del rt.params[id(var)]
        placed = ex._place_param(var, value)
        ex.param_nodes.append(var)
        ex.state["params"][id(var)] = placed
        ex.config.placeholder_to_arr_map[var] = placed
        slots = list(ex.state["slots"][id(opt)])
        slots[i] = self._host_slot(opt.optimizer, placed, slot_host,
                                   value.shape, np)
        ex.state["slots"][id(opt)] = tuple(slots)
        self._rebuild_subexecutors()

    @staticmethod
    def _host_slot(optimizer, placed, slot_host, shape, np):
        """Server shard slots -> this optimizer's host slot pytree. The
        mapping is explicit per optimizer family (store.h alloc_slots):
        momentum/nesterov accum -> velocity, adagrad accum -> accum,
        adam accum/accum2 -> m/v with t from the server step counter."""
        import jax.numpy as jnp
        slot = optimizer.slot_init(placed)
        if slot_host is None or not isinstance(slot, dict):
            return slot
        accum = slot_host.get("accum")
        accum2 = slot_host.get("accum2")
        step = slot_host.get("step", 0)
        out = dict(slot)
        if "velocity" in out and accum is not None and accum.size:
            out["velocity"] = jnp.asarray(accum.reshape(shape), jnp.float32)
        if "accum" in out and accum is not None and accum.size:
            out["accum"] = jnp.asarray(accum.reshape(shape), jnp.float32)
        if "m" in out and accum is not None and accum.size:
            out["m"] = jnp.asarray(accum.reshape(shape), jnp.float32)
        if "v" in out and accum2 is not None and accum2.size:
            out["v"] = jnp.asarray(accum2.reshape(shape), jnp.float32)
        if "t" in out:
            out["t"] = jnp.asarray(float(step), jnp.float32)
        return out

    def _pull_server_slots(self, p):
        """Merge one dense param's server-side optimizer slots across
        shards (v2 shard files as the transfer medium — the migration
        path's format, so rows keep their state bit-for-bit)."""
        import shutil
        import tempfile

        import numpy as np

        from .elastic import (_query_book, read_v2_shard,
                              sched_addr_from_env, server_param_save)
        host, port = sched_addr_from_env()
        addrs, _ = _query_book(host, port)
        tmp = tempfile.mkdtemp(prefix="hetupilot_slots_")
        try:
            shards = []
            for rank, addr in enumerate(addrs):
                server_param_save(addr, p.ps_id, tmp)
                path = os.path.join(tmp,
                                    f"param_{p.ps_id}_shard{rank}.bin")
                if os.path.exists(path):
                    shards.append(read_v2_shard(path))
            if not shards:
                return None
            return {"accum": np.concatenate([s["accum"] for s in shards])
                    if shards[0]["accum"].size else np.empty(0, np.float32),
                    "accum2": np.concatenate([s["accum2"] for s in shards])
                    if shards[0]["accum2"].size else np.empty(0, np.float32),
                    "step": max(int(s.get("step", 0)) for s in shards)}
        finally:
            shutil.rmtree(tmp, ignore_errors=True)

    def _flip_allreduce_to_ps(self, var, ps_id: Optional[int] = None) -> None:
        """Move one dense param's ownership device -> server: register the
        tensor (InitTensor is idempotent), transfer value + host optimizer
        slots via the v2 shard format (raw assignment — the server
        optimizer must never see them as gradients), rewire the
        optimizer's grad input to a PS push, rebuild."""
        import numpy as np

        ex = self.ex
        rt = ex.ps_runtime
        from .graph.ops.ps import parameterServerCommunicate_op
        from .graph.ps_runtime import PSParam
        opt, i = self._find_opt(var)
        ar = opt.inputs[i]
        grad = ar.inputs[0]
        # retire the AllReduce op's hetuq marks — it leaves the graph
        if ar in getattr(ex, "qar_ops", []):
            ex.qar_ops.remove(ar)
            ex.state["qresid"].pop(id(ar), None)
        value = np.asarray(ex.state["params"][id(var)], np.float32)
        slot = ex.state["slots"][id(opt)][i]
        if ps_id is None:
            base = int(os.environ.get("HETU_PS_ID_BASE", "0"))
            ps_id = max((q.ps_id for q in rt.params.values()),
                        default=base - 1) + 1
        sopt = rt._server_opt
        rows = int(np.prod(value.shape))
        rt.comm.InitTensor(ps_id, 0, rows, 1, "constant", 0.0, 1.0,
                           seed=ex.config.seed + ps_id,
                           opt_type=sopt["otype"], lrs=sopt["lrs"])
        if sopt["otype"] == "sgd":
            rt.comm.Assign(ps_id, value.ravel())
        else:
            self._push_shards(ps_id, value, slot, sopt)
        p = PSParam(var, ps_id, False)
        p.host_value = value.reshape(var.shape)
        rt.params[id(var)] = p
        opt.inputs[i] = parameterServerCommunicate_op(
            grad, ps_id=var.name, optimizer=opt.optimizer)
        opt.inputs[i].ps_param_node = var
        ex.param_nodes.remove(var)
        del ex.state["params"][id(var)]
        ex.config.placeholder_to_arr_map.pop(var, None)
        slots = list(ex.state["slots"][id(opt)])
        slots[i] = ()   # the server owns the optimizer state now
        ex.state["slots"][id(opt)] = tuple(slots)
        self._rebuild_subexecutors()

    def _push_shards(self, ps_id: int, value, slot, sopt) -> None:
        """Host optimizer slots -> server shards: split value/accum/accum2
        with the worker partitioner's exact formula and kParamLoad each
        server's shard (Assign would zero the slots)."""
        import numpy as np

        from .elastic import (_query_book, repartition_key,
                              sched_addr_from_env, server_param_load,
                              write_v2_shard)
        wire_otype = {"sgd": 0, "momentum": 1, "nesterov": 2,
                      "adagrad": 3, "adam": 4}[sopt["otype"]]
        flat = value.ravel().astype(np.float32)
        accum = accum2 = np.empty(0, np.float32)
        step = 0
        if isinstance(slot, dict):
            for k in ("velocity", "accum", "m"):
                if k in slot:
                    accum = np.asarray(slot[k], np.float32).ravel()
                    break
            if "v" in slot:
                accum2 = np.asarray(slot["v"], np.float32).ravel()
            if "t" in slot:
                step = int(np.asarray(slot["t"]))
        whole = {"kind": 0, "rows": 0, "len": flat.size, "width": 1,
                 "otype": wire_otype, "step": step,
                 "lrs": np.asarray(sopt["lrs"], np.float32),
                 "data": flat, "accum": accum, "accum2": accum2,
                 "versions": np.empty(0, np.int64)}
        host, port = sched_addr_from_env()
        addrs, _ = _query_book(host, port)
        shards = repartition_key([whole], len(addrs))
        import tempfile
        tmp = tempfile.mkdtemp(prefix="hetupilot_push_")
        try:
            for rank, (addr, shard) in enumerate(zip(addrs, shards)):
                path = os.path.join(tmp, f"param_{ps_id}_shard{rank}.bin")
                write_v2_shard(path, shard)
                server_param_load(addr, ps_id, tmp)
        finally:
            import shutil
            shutil.rmtree(tmp, ignore_errors=True)

    def _apply_remesh(self, delta: dict):
        """Re-adopt a different data-parallel mesh via Executor.remesh —
        the arg must be a concrete jax Mesh (API/forced use; the
        recommendation's mesh STRING is advisory only)."""
        from jax.sharding import Mesh
        ex = self.ex
        mesh = delta.get("arg")
        if not isinstance(mesh, Mesh):
            raise PilotError(
                "remesh actuation needs a concrete jax.sharding.Mesh arg "
                "(drive it through the Pilot API; the recommendation's "
                "mesh string is advisory)")
        old = ex.config.mesh
        if old is None:
            raise PilotError("no current mesh to revert to — refusing an "
                             "irreversible remesh")
        ex.remesh(mesh)

        def undo():
            ex.remesh(old)
        return undo

    def _actuate_grow(self) -> None:
        """PS tier +1 via the SIGUSR2/ScalePolicy grow path — a REAL
        resize (the worker side parks in the elastic agent), so the pilot
        runs no barrier of its own. Irreversible: scale-down is refused
        by the scheduler, so a regression blacklists instead of
        reverting."""
        if getattr(self.ex, "elastic", None) is None:
            raise PilotError(
                "ps_server_grow needs the elastic agent (HETU_ELASTIC=1): "
                "the grow commits through the worker's step-boundary hook")
        from .elastic import grow_local_cluster_server
        grow_local_cluster_server()

    def _rebuild_subexecutors(self) -> None:
        """A rewired graph invalidates every compiled program AND the
        SubExecutors' cached topo/PS classifications — rebuild them from
        the same eval_node_dict. Dataloader cursors carry over; the
        in-flight run() notices the swap and delegates to its
        replacement."""
        ex = self.ex
        old = ex.subexecutors
        ex.subexecutors = {}
        for name, sub in old.items():
            fresh = type(sub)(name, ex.eval_node_dict[name], ex)
            fresh._dl_cursor.update(sub._dl_cursor)
            ex.subexecutors[name] = fresh

    # -- small helpers ------------------------------------------------------
    def _tel_event(self, name: str, **fields) -> None:
        tel = getattr(self.ex, "telemetry", None)
        if tel is not None:
            try:
                tel.event(name, **fields)
            except Exception:  # noqa: BLE001 — observability only
                pass

    @staticmethod
    def _maybe_kill(phase: str) -> None:
        """HETU_PILOT_KILL=<phase> (HETU_TEST_MODE-gated): die at an
        actuation phase — the crash-mid-actuation restore test's
        instrument, mirroring hetusave's job_kill phases."""
        if os.environ.get("HETU_TEST_MODE", "") in ("", "0"):
            return
        if os.environ.get("HETU_PILOT_KILL", "") == phase:
            print(f"# hetupilot: armed kill at phase {phase!r}",
                  file=sys.stderr, flush=True)
            os._exit(86)


def _jsonable(v):
    """Ledger-safe rendering of cause/arg payloads (a remesh arg may be a
    live Mesh object)."""
    try:
        json.dumps(v)
        return v
    except (TypeError, ValueError):
        if isinstance(v, dict):
            return {str(k): _jsonable(x) for k, x in v.items()}
        return repr(v)


# ---------------------------------------------------------------------------
# CLI: report + self-test (jax-free — the bin/hetuwatch contract)
# ---------------------------------------------------------------------------

def render_report(directory: str, out=sys.stdout) -> int:
    path = os.path.join(directory, "pilot.jsonl")
    if not os.path.exists(path):
        alt = os.path.join(directory, "pilot", "pilot.jsonl")
        if os.path.exists(alt):
            path = alt
        else:
            print(f"hetupilot: no pilot.jsonl under {directory}",
                  file=sys.stderr)
            return 2
    records = ActuationLedger(path).records()
    s = ActuationLedger.summarize(records)
    print(f"hetupilot ledger: {path}", file=out)
    print(f"  eras {s['eras']} · commits {s['commits']} · rollbacks "
          f"{s['rollbacks']} · regressed-kept {s['regressed_kept']} · "
          f"failed {s['failed']} · interrupted {s['interrupted']} · "
          f"open {s['open']} · abstains {s['abstains']}", file=out)
    for e in s["history"]:
        d = e.get("delta") or {}
        before = e.get("baseline_ms")
        after = e.get("after_ms")
        ab = (f" {before}ms -> {after}ms (x{e.get('ratio')})"
              if before is not None and after is not None else "")
        print(f"  era {e['era']}: {d.get('kind')}"
              f"{' ' + str(d.get('target')) if d.get('target') else ''}"
              f" -> {d.get('arg')} @step {e.get('step')}"
              f" · {e.get('verdict') or 'OPEN'}{ab}", file=out)
    return 0


def self_check(out=sys.stdout) -> int:
    """Synthetic event stream -> governor decisions -> ledger round-trip.
    No jax, no cluster, no executor — everything here is the pure
    decision/persistence layer the live controller runs on."""
    import tempfile
    failures = []

    def expect(cond, what):
        print(("ok   " if cond else "FAIL ") + what, file=out)
        if not cond:
            failures.append(what)

    w = _watch_mod()
    d = w.make_delta("comm_mode_flip", target="w", arg="AllReduce",
                     expected_gain=0.4, confidence=0.7)
    expect(delta_signature(d) == "comm_mode_flip:w:AllReduce",
           "delta signature is kind:target:arg")
    try:
        w.make_delta("full_replan")
        expect(False, "unknown delta kind raises naming the catalogue")
    except ValueError as e:
        expect("comm_quant" in str(e),
               "unknown delta kind raises naming the catalogue")

    # governor: spacing + budget + blacklist-with-expiry
    g = Governor(spacing=10, cooldown=50, budget=2)
    expect(g.consider(d, 100) == "ok", "fresh governor admits a delta")
    g.note_actuation(100)
    expect(g.consider(d, 105) == "spacing",
           "second actuation inside the spacing window is refused")
    g.ban(delta_signature(d), 110)
    expect(g.consider(d, 120) == "blacklisted",
           "a banned signature is refused during its cool-down")
    expect(g.consider(d, 160) == "ok",
           "the ban expires after cooldown steps")
    g.note_actuation(160)
    expect(g.consider(d, 300) == "budget-exhausted",
           "the global budget caps total actuations")
    g2 = Governor()
    expect(g2.consider(d, 0, n_workers=2) == "multi-worker",
           "multi-worker jobs are refused (hetusave precedent)")
    expect(g2.consider(d, 0, resize_pending=True) == "resize-pending",
           "a pending resize holds the governor")
    expect(g2.consider(d, 0, chaos_climbing=True) == "chaos-climbing",
           "climbing chaos counters hold the governor")

    # anti-flap: a plan_flap-shaped stream (the delta looks good on the
    # "off" half-period, regresses on the "on" half) must not oscillate —
    # each regression bans the signature, and the budget bounds the total
    g3 = Governor(spacing=5, cooldown=100, budget=3)
    actuations = []
    step = 0
    while step < 1000:
        if g3.consider(d, step) == "ok":
            g3.note_actuation(step)
            actuations.append(step)
            g3.ban(delta_signature(d), step + 10)   # measured regression
        step += 8   # the flap period — every boundary re-offers the delta
    expect(len(actuations) <= 3,
           f"flapping recommendation is budget-bounded "
           f"({len(actuations)} actuations over 1000 steps)")
    gaps = [b - a for a, b in zip(actuations, actuations[1:])]
    expect(all(gap >= 100 for gap in gaps),
           "consecutive identical actuations are cool-down separated")

    # ledger round-trip + interrupted-era detection + summary
    with tempfile.TemporaryDirectory() as tmp:
        led = ActuationLedger(os.path.join(tmp, "pilot.jsonl"))
        led.append(era=1, phase="propose", step=50, delta=d,
                   cause={"leg": "ps_push"}, baseline_ms=12.5)
        led.append(era=1, phase="actuate", step=50, delta=d)
        led.append(era=1, phase="verdict", verdict="commit", step=62,
                   delta=d, before_ms=12.5, after_ms=9.1, ratio=0.728)
        led.append(phase="abstain", signature="x", reason="spacing",
                   step=70)
        led.append(era=2, phase="propose", step=200, delta=d,
                   baseline_ms=9.0)
        led.append(era=2, phase="actuate", step=200, delta=d)
        # era 2 never reaches a verdict: the crash-mid-actuation shape
        with open(led.path, "a") as f:
            f.write('{"torn": ')   # crash mid-write: torn tail line
        records = led.records()
        expect(len(records) == 6, "torn tail line is tolerated on read")
        expect(ActuationLedger.open_eras(records) == [2],
               "the crashed era is detected as open")
        s = ActuationLedger.summarize(records)
        expect(s["eras"] == 2 and s["commits"] == 1 and s["open"] == 1
               and s["abstains"] == 1,
               "summary counts eras/commits/open/abstains")
        expect(s["history"][0]["after_ms"] == 9.1,
               "summary history carries before/after step time")
        rc = render_report(tmp, out=out if out is not sys.stdout
                           else open(os.devnull, "w"))
        expect(rc == 0, "report renders the ledger")

    # verdict arithmetic
    expect(median([3.0, 1.0, 2.0]) == 2.0 and median([1.0, 2.0]) == 1.5,
           "median is exact for odd and even windows")

    print(("hetupilot self-test: PASS" if not failures
           else f"hetupilot self-test: {len(failures)} FAILURE(S)"),
          file=out)
    return 0 if not failures else 1


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="hetupilot",
        description="bounded self-tuning controller: actuation-ledger "
                    "report + jax-free self-test "
                    "(docs/FAULT_TOLERANCE.md 'Self-tuning with "
                    "guardrails')")
    ap.add_argument("dir", nargs="?", default=None,
                    help="pilot directory (or telemetry dir) holding "
                         "pilot.jsonl")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable summary")
    ap.add_argument("--check", action="store_true",
                    help="run the jax-free self-test and exit")
    args = ap.parse_args(argv)
    if args.check:
        return self_check()
    if not args.dir:
        ap.print_usage(sys.stderr)
        return 2
    if args.as_json:
        s = summarize_dir(args.dir) or summarize_dir(
            os.path.join(args.dir, "pilot"))
        if s is None:
            print(f"hetupilot: no pilot.jsonl under {args.dir}",
                  file=sys.stderr)
            return 2
        print(json.dumps(s, indent=1))
        return 0
    return render_report(args.dir)


if __name__ == "__main__":
    sys.exit(main())
