"""HuggingFace GPT-2 checkpoint import — the flagship trunk IS GPT-2.

The flagship decoder (``models/transformer.py``) is architecturally GPT-2
once ``attn_proj_bias=True``: pre-LN blocks (ln_1 -> attention -> residual,
ln_2 -> MLP -> residual), learned positions, tanh-approximate gelu
(HF ``gelu_new``), LN eps 1e-5, a final ``ln_f``, and the LM head tied to
the token embedding (``cfg.tied_head``). So loading a
GPT-2 checkpoint is a pure weight relayout — no dialect switch — and the
imported model rides every flagship path: dp/tp/sp meshes, flash
attention, the fused LM-CE kernel, and the one-scan KV-cache decode
(``models/generate.py``), which is token-exact against the training
forward by test. The LM head is TIED to the token embedding
(``cfg.tied_head``) exactly as HF ties lm_head to wte — no transposed
copy, shared gradients under fine-tuning.

Beyond reference parity: the reference's NLP example trains its
transformer from scratch only; it has no checkpoint interop.

HF layout notes (tests/test_hf_gpt2.py pins all of this numerically):
- ``Conv1D`` stores weight as (in, out) — our einsum orientation exactly,
  no transposes anywhere in the blocks;
- ``c_attn`` is the fused (D, 3D) qkv projection = our ``wqkv``;
- ``lm_head.weight`` is tied to ``wte`` (V, D) = our tied head.
"""
from __future__ import annotations

from typing import Any, Dict

import numpy as np
import jax.numpy as jnp

from .hf_common import np_f32, tree_to_jnp
from .transformer import TransformerConfig


def config_from_hf(hf_config, **overrides) -> TransformerConfig:
    """transformers.GPT2Config -> a flagship TransformerConfig. Refuses
    attention variants the flagship does not implement — importing them
    would run but be numerically wrong."""
    act = getattr(hf_config, "activation_function", "gelu_new")
    if act not in ("gelu_new", "gelu_pytorch_tanh", "gelu"):
        raise NotImplementedError(f"activation {act!r}: only gelu variants")
    unsupported = [flag for flag, bad in (
        ("scale_attn_by_inverse_layer_idx", True),  # scores / (layer+1)
        ("reorder_and_upcast_attn", True),
        ("scale_attn_weights", False),              # skip the 1/sqrt(hd)
        ("add_cross_attention", True),
    ) if getattr(hf_config, flag, not bad) == bad]
    if unsupported:
        raise NotImplementedError(
            "GPT-2 attention variant(s) not supported: "
            + ", ".join(unsupported))
    kw = dict(
        vocab_size=hf_config.vocab_size,
        d_model=hf_config.n_embd,
        n_heads=hf_config.n_head,
        n_layers=hf_config.n_layer,
        d_ff=(hf_config.n_inner if hf_config.n_inner
              else 4 * hf_config.n_embd),
        max_seq_len=hf_config.n_positions,
        ln_eps=hf_config.layer_norm_epsilon,
        gelu_exact=(act == "gelu"),
        attn_proj_bias=True,
        tied_head=True,      # lm_head shares wte, as in HF
        causal=True,
        dtype=jnp.float32,
    )
    kw.update(overrides)
    return TransformerConfig(**kw)


def params_from_hf(model, cfg: TransformerConfig = None):
    """(transformers GPT2Model/GPT2LMHeadModel, cfg?) -> (params, cfg).

    A caller-supplied ``cfg`` is validated against the checkpoint (shape
    AND dialect fields) — a silent truncated/reshaped import must refuse.
    """
    if cfg is None:
        cfg = config_from_hf(model.config)
    want = config_from_hf(model.config)
    mismatched = [f
                  for f in ("vocab_size", "d_model", "n_heads", "n_layers",
                            "d_ff", "max_seq_len", "ln_eps", "gelu_exact",
                            "attn_proj_bias", "causal", "post_ln",
                            "tied_head", "n_experts")
                  if getattr(cfg, f) != getattr(want, f)]
    if mismatched:
        raise ValueError(
            "cfg disagrees with the checkpoint's architecture on "
            + ", ".join(f"{f} ({getattr(cfg, f)} != {getattr(want, f)})"
                        for f in mismatched))
    sd: Dict[str, Any] = {}
    for k, v in model.state_dict().items():
        if k.startswith("transformer."):
            k = k[len("transformer."):]
        if not (k.startswith(("h.", "wte.", "wpe.", "ln_f."))):
            continue   # lm_head.weight (tied duplicate of wte), buffers
        if ".attn.bias" in k or ".attn.masked_bias" in k:
            continue   # causal-mask buffers on older transformers versions
        sd[k] = np_f32(v)
    L = cfg.n_layers

    def layer(i, name):
        return sd[f"h.{i}.{name}"]

    blocks = {
        "ln1_scale": np.stack([layer(i, "ln_1.weight") for i in range(L)]),
        "ln1_bias": np.stack([layer(i, "ln_1.bias") for i in range(L)]),
        "wqkv": np.stack([layer(i, "attn.c_attn.weight")
                          for i in range(L)]),             # (L, D, 3D)
        "bqkv": np.stack([layer(i, "attn.c_attn.bias") for i in range(L)]),
        "wo": np.stack([layer(i, "attn.c_proj.weight") for i in range(L)]),
        "bo": np.stack([layer(i, "attn.c_proj.bias") for i in range(L)]),
        "ln2_scale": np.stack([layer(i, "ln_2.weight") for i in range(L)]),
        "ln2_bias": np.stack([layer(i, "ln_2.bias") for i in range(L)]),
        "w1": np.stack([layer(i, "mlp.c_fc.weight") for i in range(L)]),
        "b1": np.stack([layer(i, "mlp.c_fc.bias") for i in range(L)]),
        "w2": np.stack([layer(i, "mlp.c_proj.weight") for i in range(L)]),
        "b2": np.stack([layer(i, "mlp.c_proj.bias") for i in range(L)]),
    }
    params = {
        # cfg.tied_head: the LM head IS this embedding (no copy), so
        # fine-tuning keeps HF's tied-weight training dynamics and the
        # weights stay exportable as a tied checkpoint
        "embed": sd["wte.weight"],
        "pos": sd["wpe.weight"],
        "blocks": blocks,
        "lnf_scale": sd["ln_f.weight"],
        "lnf_bias": sd["ln_f.bias"],
    }
    return tree_to_jnp(params), cfg


def state_dict_from_params(params, cfg: TransformerConfig):
    """Inverse of ``params_from_hf``: params -> HF-named numpy state dict
    (unscoped ``wte/wpe/h.N/ln_f`` names) so TPU-trained weights deploy
    back through ``transformers``. Conv1D keeps the (in, out) layout, so
    this is transpose-free like the import."""
    blocks = {k: np.asarray(v) for k, v in params["blocks"].items()}
    sd = {
        "wte.weight": np.asarray(params["embed"]),
        "wpe.weight": np.asarray(params["pos"]),
        "ln_f.weight": np.asarray(params["lnf_scale"]),
        "ln_f.bias": np.asarray(params["lnf_bias"]),
    }
    for i in range(cfg.n_layers):
        p = f"h.{i}."
        sd[p + "ln_1.weight"] = blocks["ln1_scale"][i]
        sd[p + "ln_1.bias"] = blocks["ln1_bias"][i]
        sd[p + "attn.c_attn.weight"] = blocks["wqkv"][i]
        sd[p + "attn.c_attn.bias"] = blocks["bqkv"][i]
        sd[p + "attn.c_proj.weight"] = blocks["wo"][i]
        sd[p + "attn.c_proj.bias"] = blocks["bo"][i]
        sd[p + "ln_2.weight"] = blocks["ln2_scale"][i]
        sd[p + "ln_2.bias"] = blocks["ln2_bias"][i]
        sd[p + "mlp.c_fc.weight"] = blocks["w1"][i]
        sd[p + "mlp.c_fc.bias"] = blocks["b1"][i]
        sd[p + "mlp.c_proj.weight"] = blocks["w2"][i]
        sd[p + "mlp.c_proj.bias"] = blocks["b2"][i]
    return sd


def export_to_hf(params, cfg: TransformerConfig, model):
    """Load params into a live transformers GPT-2 ``model`` (GPT2Model or
    GPT2LMHeadModel). Requires ``cfg.tied_head``: HF GPT-2 architecturally
    ties lm_head to wte (one tensor), so an untied flagship head has no
    faithful place in the target — loading it into lm_head would silently
    overwrite wte through the tie. Returns the model."""
    if not cfg.tied_head:
        raise ValueError(
            "export_to_hf needs cfg.tied_head=True: HF GPT-2 ties lm_head "
            "to wte, so a separately trained (D, V) head cannot be "
            "represented in a GPT-2 checkpoint")
    import torch
    from .hf_common import load_into_hf
    sd = dict(state_dict_from_params(params, cfg))
    if any(k.startswith("lm_head.") for k in model.state_dict()):
        sd["lm_head.weight"] = sd["wte.weight"]   # the tie, explicitly
    return load_into_hf(
        sd, model, scope="transformer.",
        # causal-mask buffers on older transformers versions
        skip_target=lambda k: (".attn.bias" in k
                               or ".attn.masked_bias" in k))
