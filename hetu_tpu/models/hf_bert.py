"""HuggingFace BERT checkpoint import — weight-for-weight, logit-for-logit.

Beyond reference parity: the reference's NLP suite trains BERT-family
models from scratch only (``examples/nlp/processBertData.py`` + its
transformer example); there is no pretrained-checkpoint interop anywhere
in it. This module loads any ``transformers`` BERT checkpoint
(``BertModel`` / ``BertForPreTraining`` / ``BertForSequenceClassification``)
into ``models/bert.py`` params such that forward outputs MATCH the torch
model numerically (tests/test_hf_bert.py pins logits to ~1e-4 in f32) —
so a user can pretrain/finetune a real ``bert-base-uncased`` through the
TPU-native stack (dp/tp meshes, flash attention, fused MLM CE and all).

Architecture note: HF BERT is the canonical post-LN dialect
(``BertConfig.hf()``): LN after each residual add, an embedding LayerNorm
(mapped onto the trunk's ``lnf`` params, which the post-LN path applies
after the embedding sum), erf gelu, eps 1e-12, and bias terms on every
projection. The import refuses configs that disagree (loading post-LN
weights into the pre-LN trunk would run but be numerically meaningless).

No torch tensors leak out: everything is converted to numpy, then jnp.
"""
from __future__ import annotations

from typing import Any, Dict

import numpy as np
import jax.numpy as jnp

from .bert import BertConfig
from .hf_common import np_f32, tree_to_jnp


def config_from_hf(hf_config) -> BertConfig:
    """transformers.BertConfig -> BertConfig.hf() with matching shapes."""
    act = getattr(hf_config, "hidden_act", "gelu")
    if act not in ("gelu", "gelu_new", "gelu_pytorch_tanh"):
        raise NotImplementedError(f"hidden_act={act!r}: only gelu variants")
    pe = getattr(hf_config, "position_embedding_type", "absolute")
    if pe != "absolute":
        # relative_key(_query) adds distance-embedding terms inside the
        # attention scores; importing would silently drop them
        raise NotImplementedError(
            f"position_embedding_type={pe!r}: only 'absolute'")
    if getattr(hf_config, "is_decoder", False) or getattr(
            hf_config, "add_cross_attention", False):
        raise NotImplementedError(
            "decoder/cross-attention BERT variants are not supported")
    return BertConfig.hf(
        vocab_size=hf_config.vocab_size,
        d_model=hf_config.hidden_size,
        n_heads=hf_config.num_attention_heads,
        n_layers=hf_config.num_hidden_layers,
        d_ff=hf_config.intermediate_size,
        max_seq_len=hf_config.max_position_embeddings,
        type_vocab_size=hf_config.type_vocab_size,
        ln_eps=hf_config.layer_norm_eps,
        gelu_exact=(act == "gelu"),
        dtype=jnp.float32,
    )


def _strip_prefix(sd: Dict[str, Any]) -> Dict[str, np.ndarray]:
    """Normalize a state dict: drop the leading ``bert.`` scope if present
    (BertForPreTraining nests the encoder under it; BertModel does not)."""
    out = {}
    for k, v in sd.items():
        if k.startswith("bert."):
            k = k[len("bert."):]
        out[k] = np_f32(v)
    return out


def params_from_hf(model, cfg: BertConfig = None):
    """(transformers BERT model, cfg?) -> (params, cfg).

    ``model``: BertModel, BertForPreTraining, or
    BertForSequenceClassification (anything whose state dict carries the
    ``embeddings./encoder.`` keys). Heads present in the checkpoint are
    mapped (MLM transform + bias, NSP, pooler, classifier); absent heads
    are simply missing from the returned params — calling a head that
    needs them raises a KeyError, and callers wanting fresh heads graft
    them from ``init_params`` / ``init_classifier_params``.

    A caller-supplied ``cfg`` is validated against the checkpoint: dialect
    (post-LN, biases, gelu flavor, LN eps) AND shapes — a truncated or
    reshaped import must refuse, not silently produce a different model.
    """
    if cfg is None:
        cfg = config_from_hf(model.config)
    if not (cfg.post_ln and cfg.attn_proj_bias):
        raise ValueError(
            "HF BERT weights are post-LN with projection biases; build the "
            "config with BertConfig.hf() (got post_ln=%s attn_proj_bias=%s)"
            % (cfg.post_ln, cfg.attn_proj_bias))
    want = config_from_hf(model.config)
    mismatched = [f
                  for f in ("vocab_size", "d_model", "n_heads", "n_layers",
                            "d_ff", "max_seq_len", "type_vocab_size",
                            "ln_eps", "gelu_exact")
                  if getattr(cfg, f) != getattr(want, f)]
    if mismatched:
        raise ValueError(
            "cfg disagrees with the checkpoint's architecture on "
            + ", ".join(f"{f} ({getattr(cfg, f)} != {getattr(want, f)})"
                        for f in mismatched))
    sd = _strip_prefix(model.state_dict())
    L, D = cfg.n_layers, cfg.d_model

    def layer(i, name):
        return sd[f"encoder.layer.{i}.{name}"]

    # per-layer stacks, leading L axis (the trunk scans over it)
    wqkv = np.stack([
        np.concatenate([layer(i, "attention.self.query.weight").T,
                        layer(i, "attention.self.key.weight").T,
                        layer(i, "attention.self.value.weight").T], axis=1)
        for i in range(L)])                                   # (L, D, 3D)
    bqkv = np.stack([
        np.concatenate([layer(i, "attention.self.query.bias"),
                        layer(i, "attention.self.key.bias"),
                        layer(i, "attention.self.value.bias")])
        for i in range(L)])                                   # (L, 3D)
    blocks = {
        "wqkv": wqkv,
        "bqkv": bqkv,
        "wo": np.stack([layer(i, "attention.output.dense.weight").T
                        for i in range(L)]),
        "bo": np.stack([layer(i, "attention.output.dense.bias")
                        for i in range(L)]),
        # post-LN: ln1 runs after the attention residual, ln2 after the MLP
        "ln1_scale": np.stack([layer(i, "attention.output.LayerNorm.weight")
                               for i in range(L)]),
        "ln1_bias": np.stack([layer(i, "attention.output.LayerNorm.bias")
                              for i in range(L)]),
        "w1": np.stack([layer(i, "intermediate.dense.weight").T
                        for i in range(L)]),
        "b1": np.stack([layer(i, "intermediate.dense.bias")
                        for i in range(L)]),
        "w2": np.stack([layer(i, "output.dense.weight").T
                        for i in range(L)]),
        "b2": np.stack([layer(i, "output.dense.bias") for i in range(L)]),
        "ln2_scale": np.stack([layer(i, "output.LayerNorm.weight")
                               for i in range(L)]),
        "ln2_bias": np.stack([layer(i, "output.LayerNorm.bias")
                              for i in range(L)]),
    }
    params = {
        "embed": sd["embeddings.word_embeddings.weight"],
        "pos": sd["embeddings.position_embeddings.weight"],
        "type_emb": sd["embeddings.token_type_embeddings.weight"],
        # post-LN repurposes lnf as the embedding LayerNorm (bert.encode)
        "lnf_scale": sd["embeddings.LayerNorm.weight"],
        "lnf_bias": sd["embeddings.LayerNorm.bias"],
        "blocks": blocks,
    }
    if "pooler.dense.weight" in sd:
        params["pool_w"] = sd["pooler.dense.weight"].T
        params["pool_b"] = sd["pooler.dense.bias"]
    # BertForPreTraining heads (cls.* keys never carry the bert. prefix)
    if "cls.predictions.transform.dense.weight" in sd:
        params["mlm_dense"] = sd["cls.predictions.transform.dense.weight"].T
        params["mlm_dense_b"] = sd["cls.predictions.transform.dense.bias"]
        params["mlm_ln_scale"] = sd[
            "cls.predictions.transform.LayerNorm.weight"]
        params["mlm_ln_bias"] = sd["cls.predictions.transform.LayerNorm.bias"]
        params["mlm_bias"] = sd["cls.predictions.bias"]
        # the decode matmul is tied to params["embed"], as in HF
    if "cls.seq_relationship.weight" in sd:
        params["nsp_w"] = sd["cls.seq_relationship.weight"].T
        params["nsp_b"] = sd["cls.seq_relationship.bias"]
    # BertForSequenceClassification head -> the fine-tune params
    if "classifier.weight" in sd:
        params["cls_w"] = sd["classifier.weight"].T
        params["cls_b"] = sd["classifier.bias"]
    return tree_to_jnp(params), cfg


def state_dict_from_params(params, cfg: BertConfig):
    """Inverse of ``params_from_hf``: params -> HF-named numpy state dict
    (unscoped ``embeddings./encoder./pooler.`` names plus whatever heads
    are present) — so TPU-trained/fine-tuned weights deploy back through
    ``transformers``. ``export_to_hf`` loads it into a model instance."""
    blocks = {k: np.asarray(v) for k, v in params["blocks"].items()}
    D = cfg.d_model
    sd = {
        "embeddings.word_embeddings.weight": np.asarray(params["embed"]),
        "embeddings.position_embeddings.weight": np.asarray(params["pos"]),
        "embeddings.token_type_embeddings.weight":
            np.asarray(params["type_emb"]),
        "embeddings.LayerNorm.weight": np.asarray(params["lnf_scale"]),
        "embeddings.LayerNorm.bias": np.asarray(params["lnf_bias"]),
    }
    for i in range(cfg.n_layers):
        p = f"encoder.layer.{i}."
        wqkv, bqkv = blocks["wqkv"][i], blocks["bqkv"][i]
        sd[p + "attention.self.query.weight"] = wqkv[:, :D].T
        sd[p + "attention.self.key.weight"] = wqkv[:, D:2 * D].T
        sd[p + "attention.self.value.weight"] = wqkv[:, 2 * D:].T
        sd[p + "attention.self.query.bias"] = bqkv[:D]
        sd[p + "attention.self.key.bias"] = bqkv[D:2 * D]
        sd[p + "attention.self.value.bias"] = bqkv[2 * D:]
        sd[p + "attention.output.dense.weight"] = blocks["wo"][i].T
        sd[p + "attention.output.dense.bias"] = blocks["bo"][i]
        sd[p + "attention.output.LayerNorm.weight"] = blocks["ln1_scale"][i]
        sd[p + "attention.output.LayerNorm.bias"] = blocks["ln1_bias"][i]
        sd[p + "intermediate.dense.weight"] = blocks["w1"][i].T
        sd[p + "intermediate.dense.bias"] = blocks["b1"][i]
        sd[p + "output.dense.weight"] = blocks["w2"][i].T
        sd[p + "output.dense.bias"] = blocks["b2"][i]
        sd[p + "output.LayerNorm.weight"] = blocks["ln2_scale"][i]
        sd[p + "output.LayerNorm.bias"] = blocks["ln2_bias"][i]
    if "pool_w" in params:
        sd["pooler.dense.weight"] = np.asarray(params["pool_w"]).T
        sd["pooler.dense.bias"] = np.asarray(params["pool_b"])
    if "mlm_dense" in params:
        sd["cls.predictions.transform.dense.weight"] = \
            np.asarray(params["mlm_dense"]).T
        sd["cls.predictions.transform.dense.bias"] = \
            np.asarray(params["mlm_dense_b"])
        sd["cls.predictions.transform.LayerNorm.weight"] = \
            np.asarray(params["mlm_ln_scale"])
        sd["cls.predictions.transform.LayerNorm.bias"] = \
            np.asarray(params["mlm_ln_bias"])
        sd["cls.predictions.bias"] = np.asarray(params["mlm_bias"])
        # HF ties cls.predictions.decoder to word_embeddings; emit it
        # explicitly so un-tied consumers load the right matrix too
        sd["cls.predictions.decoder.weight"] = np.asarray(params["embed"])
        sd["cls.predictions.decoder.bias"] = np.asarray(params["mlm_bias"])
    if "nsp_w" in params:
        sd["cls.seq_relationship.weight"] = np.asarray(params["nsp_w"]).T
        sd["cls.seq_relationship.bias"] = np.asarray(params["nsp_b"])
    if "cls_w" in params:
        sd["classifier.weight"] = np.asarray(params["cls_w"]).T
        sd["classifier.bias"] = np.asarray(params["cls_b"])
    return sd


def export_to_hf(params, cfg: BertConfig, model):
    """Load params into a live transformers BERT ``model`` (any of the
    supported classes), scoped under ``bert.`` for the ForXxx wrappers.
    Validation is bidirectional (``hf_common.load_into_hf``): a trunk key
    with no target slot (e.g. more layers than the model) raises, a target
    key the export cannot fill raises — only HEADS the target class lacks
    (cls.*/classifier./pooler.) may be dropped, because deploying an
    encoder into a different-head wrapper is a legitimate export."""
    from .hf_common import load_into_hf
    sd = state_dict_from_params(params, cfg)
    return load_into_hf(
        sd, model, scope="bert.",
        # registered buffers (position_ids/token_type_ids on some
        # transformers versions) are positional constants, not weights
        skip_target=lambda k: k.endswith(("position_ids",
                                          "token_type_ids")),
        droppable=("cls.", "classifier.", "pooler."))
