"""BERT — bidirectional encoder pretraining (MLM + NSP), TPU-native.

The reference's NLP suite stops at a causal Transformer example plus the
WordPiece tokenizer and the pretrain data pipeline
(``python/hetu/tokenizers/bert_tokenizer.py``,
``examples/nlp/processBertData.py``); BASELINE.md names BERT-base pretrain
as a north-star config. This module completes the path: the encoder reuses
the flagship transformer trunk (``models/transformer.py``) with
``causal=False`` — same Pallas flash-attention kernel (bidirectional mask),
same lax.scan-over-stacked-layers + remat structure, same Megatron tp
sharding — and adds what BERT needs on top:

- token-type (segment) embeddings,
- MLM head: transform (dense+gelu+LN) then decode TIED to the token
  embedding, plus an output bias,
- NSP head on the pooled [CLS] vector,
- a fused pretrain step consuming exactly the data pipeline's rows
  (input_ids, input_mask, segment_ids, mlm_positions, mlm_ids, nsp_label).

Padded batches: ``input_mask`` becomes an additive attention bias on the
unfused path (the fused kernel assumes packed/dense batches, standard for
pretrain throughput).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import transformer as tfm


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    d_model: int = 768
    n_heads: int = 12
    n_layers: int = 12
    d_ff: int = 3072
    max_seq_len: int = 512
    type_vocab_size: int = 2
    dtype: Any = jnp.bfloat16
    remat: bool = True
    attn_impl: str = "auto"
    # MLM loss through the fused Pallas linear+softmax-CE kernel
    # (kernels/fused_ce.py) — never materializes the (B*P, V) logits in
    # HBM. "auto": engaged on the single-program TPU path (under a mesh
    # the vocab-sharded decode rides the einsum form — GSPMD cannot
    # partition the custom kernel; off-TPU interpret mode would be slower
    # than the einsum). True forces it (tests), False disables.
    fused_mlm_ce: Any = "auto"
    # Architecture dialect. The default is the modern pre-LN trunk (the
    # training-throughput configuration every bench/test uses). ``hf()``
    # flips all four knobs to the canonical Devlin/HuggingFace BERT
    # architecture — post-LN blocks, embedding LayerNorm (the trunk's lnf
    # params, applied after the embedding sum instead of after the last
    # block), erf gelu, eps 1e-12, qkv/out projection biases — so
    # ``models/hf_bert.py`` can load HF checkpoints weight-for-weight.
    post_ln: bool = False
    ln_eps: float = 1e-5
    gelu_exact: bool = False
    attn_proj_bias: bool = False

    @classmethod
    def hf(cls, **overrides) -> "BertConfig":
        """The canonical (HuggingFace-compatible) BERT architecture."""
        overrides.setdefault("post_ln", True)
        overrides.setdefault("ln_eps", 1e-12)
        overrides.setdefault("gelu_exact", True)
        overrides.setdefault("attn_proj_bias", True)
        return cls(**overrides)

    def trunk(self) -> tfm.TransformerConfig:
        return tfm.TransformerConfig(
            vocab_size=self.vocab_size, d_model=self.d_model,
            n_heads=self.n_heads, n_layers=self.n_layers, d_ff=self.d_ff,
            max_seq_len=self.max_seq_len, dtype=self.dtype, remat=self.remat,
            attn_impl=self.attn_impl, causal=False,
            post_ln=self.post_ln, ln_eps=self.ln_eps,
            gelu_exact=self.gelu_exact, attn_proj_bias=self.attn_proj_bias)


BERT_BASE = BertConfig()


def init_params(rng, cfg: BertConfig):
    D, V = cfg.d_model, cfg.vocab_size
    ks = jax.random.split(rng, 5)
    params = tfm.init_params(ks[0], cfg.trunk())
    del params["head"]   # MLM decode is TIED to the token embedding
    params["type_emb"] = jax.random.normal(
        ks[1], (cfg.type_vocab_size, D), jnp.float32) * 0.02
    params["mlm_dense"] = jax.random.normal(ks[2], (D, D), jnp.float32) * 0.02
    if cfg.attn_proj_bias:   # the "biases everywhere" (canonical) dialect
        params["mlm_dense_b"] = jnp.zeros((D,), jnp.float32)
    params["mlm_ln_scale"] = jnp.ones((D,), jnp.float32)
    params["mlm_ln_bias"] = jnp.zeros((D,), jnp.float32)
    params["mlm_bias"] = jnp.zeros((V,), jnp.float32)
    params["pool_w"] = jax.random.normal(ks[3], (D, D), jnp.float32) * 0.02
    params["pool_b"] = jnp.zeros((D,), jnp.float32)
    params["nsp_w"] = jax.random.normal(ks[4], (D, 2), jnp.float32) * 0.02
    params["nsp_b"] = jnp.zeros((2,), jnp.float32)
    return params


def param_specs(cfg: BertConfig):
    specs = tfm.param_specs(cfg.trunk())
    del specs["head"]
    if cfg.attn_proj_bias:
        specs["mlm_dense_b"] = P("tp")
    specs.update({
        "type_emb": P(None, None),
        "mlm_dense": P(None, "tp"),
        "mlm_ln_scale": P(None),
        "mlm_ln_bias": P(None),
        "mlm_bias": P("tp"),
        "pool_w": P(None, None),
        "pool_b": P(None),
        "nsp_w": P(None, None),
        "nsp_b": P(None),
    })
    return specs


def encode(params, input_ids, segment_ids, cfg: BertConfig,
           mesh: Optional[Mesh] = None, input_mask=None):
    """-> final hidden states (B, T, D). Pre-LN (default): trunk then the
    final LN (lnf). Post-LN (canonical BERT): lnf is the EMBEDDING
    LayerNorm — applied after the word+pos+type sum, as HF's
    ``BertEmbeddings.LayerNorm`` — and the trunk output is final as-is
    (each block already ends in a LayerNorm)."""
    trunk = cfg.trunk()
    h = tfm.embed_tokens(params, input_ids, trunk)
    h = h + params["type_emb"][segment_ids].astype(h.dtype)
    if cfg.post_ln:
        h = tfm._layer_norm(h, params["lnf_scale"], params["lnf_bias"],
                            cfg.ln_eps)
    attn_bias = None
    if input_mask is not None:
        # (B, T) 1/0 -> additive (B, 1, 1, T): padded keys get -1e30
        attn_bias = (1.0 - input_mask.astype(jnp.float32)
                     )[:, None, None, :] * -1e30
    h, _aux = tfm.encode(params, h, trunk, mesh, attn_bias)
    if cfg.post_ln:
        return h
    return tfm._layer_norm(h, params["lnf_scale"], params["lnf_bias"],
                           cfg.ln_eps)


def mlm_transform(params, h, positions, cfg: BertConfig):
    """Gather (B, P) masked positions from h (B, T, D) and run the MLM
    transform (dense + bias + gelu + LN) -> (B, P, D). ``cfg`` is required:
    the gelu flavor and LN eps are dialect-dependent, and HF-imported
    params silently lose checkpoint parity under the wrong dialect."""
    g = jnp.take_along_axis(h, positions[..., None], axis=1)      # (B, P, D)
    g = jnp.einsum("bpd,de->bpe", g, params["mlm_dense"].astype(g.dtype),
                   preferred_element_type=jnp.float32).astype(g.dtype)
    if "mlm_dense_b" in params:
        g = g + params["mlm_dense_b"].astype(g.dtype)
    g = tfm._gelu(g, cfg)
    return tfm._layer_norm(g, params["mlm_ln_scale"], params["mlm_ln_bias"],
                           cfg.ln_eps)


def mlm_logits(params, h, positions, cfg: BertConfig):
    """MLM transform + decode tied to the token embedding -> (B, P, V) f32
    (the materializing form; the fused path skips this tensor entirely)."""
    g = mlm_transform(params, h, positions, cfg)
    logits = jnp.einsum("bpd,vd->bpv", g, params["embed"].astype(g.dtype),
                        preferred_element_type=jnp.float32)
    return logits + params["mlm_bias"]


def _pool(params, h):
    """Tanh-dense pooling of the [CLS] vector -> (B, D) f32."""
    return jnp.tanh(h[:, 0, :].astype(jnp.float32) @ params["pool_w"]
                    + params["pool_b"])


def nsp_logits(params, h):
    """Pooled [CLS] -> (B, 2) f32."""
    return _pool(params, h) @ params["nsp_w"] + params["nsp_b"]


def pretrain_loss(params, batch, cfg: BertConfig, mesh=None):
    """batch: dict with the data pipeline's rows. Returns (loss, (mlm, nsp))
    where mlm is averaged over real (weighted) prediction slots."""
    h = encode(params, batch["input_ids"], batch["segment_ids"], cfg, mesh,
               batch.get("input_mask"))
    from ..kernels.fused_ce import should_fuse
    if should_fuse(cfg.fused_mlm_ce, mesh):
        from ..kernels.fused_ce import fused_linear_nll
        g = mlm_transform(params, h, batch["mlm_positions"], cfg)
        B, Pm, D = g.shape
        per_slot = fused_linear_nll(
            g.reshape(B * Pm, D),
            params["embed"].astype(g.dtype), params["mlm_bias"],
            batch["mlm_ids"].reshape(-1)).reshape(B, Pm)
    else:
        logits = mlm_logits(params, h, batch["mlm_positions"], cfg)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        per_slot = -jnp.take_along_axis(
            logp, batch["mlm_ids"][..., None], -1)[..., 0]        # (B, P)
    w = batch["mlm_weights"].astype(jnp.float32)
    mlm = jnp.sum(per_slot * w) / jnp.maximum(jnp.sum(w), 1.0)
    nl = jax.nn.log_softmax(nsp_logits(params, h), -1)
    nsp = -jnp.mean(jnp.take_along_axis(nl, batch["nsp_label"][:, None],
                                        -1)[:, 0])
    return mlm + nsp, (mlm, nsp)


def make_pretrain_step(cfg: BertConfig, mesh: Optional[Mesh] = None,
                       lr: float = 1e-4):
    """Jitted (params, opt_state, batch) -> (loss, (mlm, nsp), params, opt);
    AdamW fused into the step, buffers donated, GSPMD dp/tp sharding."""

    def step(params, opt_state, batch):
        (loss, parts), grads = jax.value_and_grad(
            pretrain_loss, has_aux=True)(params, batch, cfg, mesh)
        new_params, new_opt = tfm.adamw_update(params, grads, opt_state,
                                               lr=lr)
        return loss, parts, new_params, new_opt

    if mesh is None:
        return jax.jit(step, donate_argnums=(0, 1))
    specs = param_specs(cfg)
    pshard = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                          is_leaf=lambda x: isinstance(x, P))
    opt_shard = {"m": pshard, "v": pshard, "t": NamedSharding(mesh, P())}
    # pytree-prefix sharding: every batch leaf is (B, ...), dp-sharded on
    # dim 0, whether or not the optional input_mask key is present
    dshard = NamedSharding(mesh, P(("dp",)))
    scalar = NamedSharding(mesh, P())
    return jax.jit(step,
                   in_shardings=(pshard, opt_shard, dshard),
                   out_shardings=(scalar, (scalar, scalar), pshard,
                                  opt_shard),
                   donate_argnums=(0, 1))


# ---------------------------------------------------------------------------
# fine-tuning: swap the pretrain heads for a task head on the pooled [CLS]
# (the standard BERT downstream recipe; no reference counterpart — its nlp
# suite stops at pretraining machinery)
# ---------------------------------------------------------------------------

def init_classifier_params(rng, cfg: BertConfig, n_classes: int,
                           pretrained=None):
    """Task params: the (possibly pretrained) encoder trunk + pooler, with a
    fresh classification head. ``pretrained``: params from
    ``init_params``/pretraining — trunk and pooler are reused, MLM/NSP
    heads dropped."""
    k_trunk, k_head = jax.random.split(rng)
    base = pretrained if pretrained is not None else init_params(k_trunk, cfg)
    # deep-copy reused leaves: the fine-tune step donates its params, and a
    # donated alias would invalidate the caller's pretrained tree
    params = {k: jax.tree.map(jnp.array, v) for k, v in base.items()
              if k not in ("mlm_dense", "mlm_dense_b", "mlm_ln_scale",
                           "mlm_ln_bias", "mlm_bias", "nsp_w", "nsp_b")}
    D = cfg.d_model
    params["cls_w"] = jax.random.normal(k_head, (D, n_classes),
                                        jnp.float32) * 0.02
    params["cls_b"] = jnp.zeros((n_classes,), jnp.float32)
    return params


def classify_logits(params, input_ids, segment_ids, cfg: BertConfig,
                    mesh=None, input_mask=None):
    h = encode(params, input_ids, segment_ids, cfg, mesh, input_mask)
    return _pool(params, h) @ params["cls_w"] + params["cls_b"]


def make_finetune_step(cfg: BertConfig, lr: float = 2e-5, mesh=None):
    """Jitted (params, opt_state, batch{input_ids, segment_ids, label,
    [input_mask]}) -> (loss, acc, params, opt)."""

    def step(params, opt_state, batch):
        def loss_fn(params):
            logits = classify_logits(params, batch["input_ids"],
                                     batch["segment_ids"], cfg, mesh,
                                     batch.get("input_mask"))
            lp = jax.nn.log_softmax(logits, -1)
            loss = -jnp.mean(jnp.take_along_axis(
                lp, batch["label"][:, None], -1)[:, 0])
            acc = jnp.mean((jnp.argmax(logits, -1) ==
                            batch["label"]).astype(jnp.float32))
            return loss, acc
        (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        new_params, new_opt = tfm.adamw_update(params, grads, opt_state,
                                               lr=lr)
        return loss, acc, new_params, new_opt

    return jax.jit(step, donate_argnums=(0, 1))


def batch_from_instances(instances):
    """Stack rows from the pretrain data pipeline
    (examples/nlp/processBertData.create_instances_from_document) into the
    batch dict ``pretrain_loss`` consumes. Prediction-slot weights are
    derived from the position padding (index 0 is always [CLS], which the
    masker never selects, so pos==0 marks a padded slot)."""
    cols = list(zip(*instances))
    ids, mask, seg, pos, mids = (np.stack(c).astype(np.int32)
                                 for c in cols[:5])
    return {"input_ids": ids, "input_mask": mask, "segment_ids": seg,
            "mlm_positions": pos, "mlm_ids": mids,
            "mlm_weights": (pos != 0).astype(np.float32),
            "nsp_label": np.asarray(cols[5], np.int32)}


init_opt_state = tfm.init_opt_state


count_params = tfm.count_params
