"""Vision Transformer on the flagship trunk — TPU-native, HF-compatible.

The reference's vision coverage is the CNN zoo plus a graph-API ViT
example (``examples/cnn/models/ViT.py``); this module is the FLAGSHIP
functional ViT: the same ``models/transformer.py`` trunk that runs the
LM/BERT paths (lax.scan over stacked layers, remat, Megatron tp specs,
flash attention for block-divisible sequence lengths) under a
patch-embedding front end. Architecturally HF ViT is the trunk's pre-LN
dialect with projection biases (``layernorm_before`` -> ln1 before
attention, ``layernorm_after`` -> ln2 before the MLP, erf gelu,
eps 1e-12, final LayerNorm -> lnf), so ``models/hf_vit.py`` loads
``transformers`` ViT checkpoints weight-for-weight.

Patch embedding is expressed as reshape + ONE matmul (the stride=P conv
is exactly a linear map over non-overlapping patches) — MXU-shaped, no
conv lowering needed at inference or training time.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import transformer as tfm


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    n_channels: int = 3
    d_model: int = 768
    n_heads: int = 12
    n_layers: int = 12
    d_ff: int = 3072
    n_classes: int = 0          # 0 = no classification head
    dtype: Any = jnp.float32
    remat: bool = False
    attn_impl: str = "auto"
    # canonical ViT dialect (HF-compatible); the trunk stays pre-LN
    ln_eps: float = 1e-12
    gelu_exact: bool = True

    @property
    def n_patches(self) -> int:
        assert self.image_size % self.patch_size == 0
        return (self.image_size // self.patch_size) ** 2

    @property
    def seq_len(self) -> int:
        return self.n_patches + 1   # + [CLS]

    def trunk(self) -> tfm.TransformerConfig:
        return tfm.TransformerConfig(
            vocab_size=2,            # unused (no token embedding)
            d_model=self.d_model, n_heads=self.n_heads,
            n_layers=self.n_layers, d_ff=self.d_ff,
            max_seq_len=self.seq_len, dtype=self.dtype, remat=self.remat,
            attn_impl=self.attn_impl, causal=False,
            ln_eps=self.ln_eps, gelu_exact=self.gelu_exact,
            attn_proj_bias=True)


VIT_BASE = ViTConfig()


def init_params(rng, cfg: ViTConfig):
    D = cfg.d_model
    pdim = cfg.patch_size * cfg.patch_size * cfg.n_channels
    ks = jax.random.split(rng, 5)
    # blocks + final norm only: no dead token-embedding/pos/head tensors
    trunk = tfm.init_trunk_params(ks[0], cfg.trunk())
    params = {
        "patch_w": jax.random.normal(ks[1], (pdim, D), jnp.float32) * 0.02,
        "patch_b": jnp.zeros((D,), jnp.float32),
        "cls_token": jax.random.normal(ks[2], (1, 1, D), jnp.float32) * 0.02,
        "pos": jax.random.normal(ks[3], (cfg.seq_len, D), jnp.float32) * 0.02,
        "blocks": trunk["blocks"],
        "lnf_scale": trunk["lnf_scale"],
        "lnf_bias": trunk["lnf_bias"],
    }
    if cfg.n_classes:
        params["cls_w"] = jax.random.normal(
            ks[4], (D, cfg.n_classes), jnp.float32) * 0.02
        params["cls_b"] = jnp.zeros((cfg.n_classes,), jnp.float32)
    return params


def param_specs(cfg: ViTConfig):
    trunk = tfm.param_specs(cfg.trunk())
    specs = {
        "patch_w": P(None, "tp"),
        "patch_b": P("tp"),
        "cls_token": P(None, None, None),
        "pos": P(None, "tp"),
        "blocks": trunk["blocks"],
        "lnf_scale": P(None),
        "lnf_bias": P(None),
    }
    if cfg.n_classes:
        specs["cls_w"] = P(None, None)
        specs["cls_b"] = P(None)
    return specs


def patchify(images, cfg: ViTConfig):
    """images (B, C, H, W) -> (B, N, P*P*C) non-overlapping patches, each
    flattened in (c, ph, pw) order — the stride=P conv's receptive field
    layout, so HF conv kernels map onto ``patch_w`` by pure reshape."""
    B, C, H, W = images.shape
    Ps = cfg.patch_size
    x = images.reshape(B, C, H // Ps, Ps, W // Ps, Ps)
    x = x.transpose(0, 2, 4, 1, 3, 5)          # (B, gh, gw, C, Ps, Ps)
    return x.reshape(B, (H // Ps) * (W // Ps), C * Ps * Ps)


def encode(params, images, cfg: ViTConfig, mesh: Optional[Mesh] = None):
    """images (B, C, H, W) f32 -> final hidden states (B, N+1, D) after
    the final LayerNorm ([CLS] first, as in HF)."""
    B = images.shape[0]
    patches = patchify(images.astype(jnp.float32), cfg)
    h = (jnp.einsum("bnp,pd->bnd", patches,
                    params["patch_w"].astype(cfg.dtype),
                    preferred_element_type=jnp.float32)
         + params["patch_b"]).astype(cfg.dtype)
    cls = jnp.broadcast_to(params["cls_token"].astype(cfg.dtype),
                           (B, 1, cfg.d_model))
    h = jnp.concatenate([cls, h], axis=1)
    h = h + params["pos"].astype(cfg.dtype)[None]
    h, _aux = tfm.encode(params, h, cfg.trunk(), mesh)
    return tfm._layer_norm(h, params["lnf_scale"], params["lnf_bias"],
                           cfg.ln_eps)


def classify_logits(params, images, cfg: ViTConfig, mesh=None):
    """-> (B, n_classes) f32 from the [CLS] hidden state (HF's
    ViTForImageClassification head: classifier on hidden[:, 0])."""
    h = encode(params, images, cfg, mesh)
    return (h[:, 0, :].astype(jnp.float32) @ params["cls_w"]
            + params["cls_b"])


def make_train_step(cfg: ViTConfig, lr: float = 1e-3,
                    mesh: Optional[Mesh] = None):
    """Jitted (params, opt_state, images, labels) ->
    (loss, acc, params, opt_state); AdamW fused in, buffers donated."""
    assert cfg.n_classes > 0, "training needs a classification head"

    def step(params, opt_state, images, labels):
        def loss_fn(params):
            logits = classify_logits(params, images, cfg, mesh)
            lp = jax.nn.log_softmax(logits, -1)
            loss = -jnp.mean(jnp.take_along_axis(
                lp, labels[:, None], -1)[:, 0])
            acc = jnp.mean((jnp.argmax(logits, -1) == labels)
                           .astype(jnp.float32))
            return loss, acc
        (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        new_params, new_opt = tfm.adamw_update(params, grads, opt_state,
                                               lr=lr)
        return loss, acc, new_params, new_opt

    if mesh is None:
        return jax.jit(step, donate_argnums=(0, 1))
    pshard = jax.tree.map(lambda s: NamedSharding(mesh, s),
                          param_specs(cfg),
                          is_leaf=lambda x: isinstance(x, P))
    opt_shard = {"m": pshard, "v": pshard, "t": NamedSharding(mesh, P())}
    dshard = NamedSharding(mesh, P(("dp",)))
    scalar = NamedSharding(mesh, P())
    return jax.jit(step,
                   in_shardings=(pshard, opt_shard, dshard, dshard),
                   out_shardings=(scalar, scalar, pshard, opt_shard),
                   donate_argnums=(0, 1))


init_opt_state = tfm.init_opt_state
count_params = tfm.count_params
