"""HuggingFace ViT checkpoint import — the vision side of the interop.

``transformers`` ViT (ViTModel / ViTForImageClassification) is the
flagship trunk's pre-LN dialect with projection biases: HF's
``layernorm_before`` is ln1 (before attention), ``layernorm_after`` is
ln2 (before the MLP), activation is erf gelu at eps 1e-12, and the final
``layernorm`` is lnf. The stride=P patch-projection conv flattens to
``models/vit.py``'s single patch matmul by pure reshape (the kernel's
(C, Ps, Ps) receptive field is exactly one flattened patch).
``tests/test_hf_vit.py`` pins hidden states and classifier logits to the
torch forward. The reference has no pretrained-checkpoint interop.
"""
from __future__ import annotations

from typing import Any, Dict

import numpy as np

from .hf_common import np_f32, tree_to_jnp
from .vit import ViTConfig


def config_from_hf(hf_config, **overrides) -> ViTConfig:
    act = getattr(hf_config, "hidden_act", "gelu")
    if act not in ("gelu", "gelu_new", "gelu_pytorch_tanh"):
        raise NotImplementedError(f"hidden_act={act!r}: only gelu variants")
    if not getattr(hf_config, "qkv_bias", True):
        raise NotImplementedError("qkv_bias=False ViT variants")
    kw = dict(
        image_size=hf_config.image_size,
        patch_size=hf_config.patch_size,
        n_channels=hf_config.num_channels,
        d_model=hf_config.hidden_size,
        n_heads=hf_config.num_attention_heads,
        n_layers=hf_config.num_hidden_layers,
        d_ff=hf_config.intermediate_size,
        ln_eps=hf_config.layer_norm_eps,
        gelu_exact=(act == "gelu"),
    )
    kw.update(overrides)
    return ViTConfig(**kw)


def params_from_hf(model, cfg: ViTConfig = None):
    """(transformers ViTModel/ViTForImageClassification, cfg?) ->
    (params, cfg); a caller-supplied cfg is validated against the
    checkpoint's architecture (including the classifier head: an
    n_classes that disagrees with the checkpoint's refuses; n_classes=0
    explicitly DROPS the checkpoint's head)."""
    # num_labels is the authoritative HF field; id2label can be absent or
    # inconsistent on hand-edited configs
    ckpt_classes = ((getattr(model.config, "num_labels", 0)
                     or len(getattr(model.config, "id2label", {}) or {}))
                    if _has_classifier(model) else 0)
    want = config_from_hf(model.config, n_classes=ckpt_classes)
    if cfg is None:
        cfg = want
    mismatched = [f
                  for f in ("image_size", "patch_size", "n_channels",
                            "d_model", "n_heads", "n_layers", "d_ff",
                            "ln_eps", "gelu_exact")
                  if getattr(cfg, f) != getattr(want, f)]
    if cfg.n_classes not in (0, ckpt_classes):
        mismatched.append("n_classes")
    if mismatched:
        raise ValueError(
            "cfg disagrees with the checkpoint's architecture on "
            + ", ".join(f"{f} ({getattr(cfg, f)} != {getattr(want, f)})"
                        for f in mismatched))
    sd: Dict[str, Any] = {}
    for k, v in model.state_dict().items():
        if k.startswith("vit."):
            k = k[len("vit."):]
        sd[k] = np_f32(v)
    L, D = cfg.n_layers, cfg.d_model

    def layer(i, name):
        return sd[f"encoder.layer.{i}.{name}"]

    wqkv = np.stack([
        np.concatenate([layer(i, "attention.attention.query.weight").T,
                        layer(i, "attention.attention.key.weight").T,
                        layer(i, "attention.attention.value.weight").T],
                       axis=1)
        for i in range(L)])                                   # (L, D, 3D)
    bqkv = np.stack([
        np.concatenate([layer(i, "attention.attention.query.bias"),
                        layer(i, "attention.attention.key.bias"),
                        layer(i, "attention.attention.value.bias")])
        for i in range(L)])
    blocks = {
        "wqkv": wqkv,
        "bqkv": bqkv,
        "wo": np.stack([layer(i, "attention.output.dense.weight").T
                        for i in range(L)]),
        "bo": np.stack([layer(i, "attention.output.dense.bias")
                        for i in range(L)]),
        # pre-LN: layernorm_before runs before attention (ln1),
        # layernorm_after before the MLP (ln2)
        "ln1_scale": np.stack([layer(i, "layernorm_before.weight")
                               for i in range(L)]),
        "ln1_bias": np.stack([layer(i, "layernorm_before.bias")
                              for i in range(L)]),
        "ln2_scale": np.stack([layer(i, "layernorm_after.weight")
                               for i in range(L)]),
        "ln2_bias": np.stack([layer(i, "layernorm_after.bias")
                              for i in range(L)]),
        "w1": np.stack([layer(i, "intermediate.dense.weight").T
                        for i in range(L)]),
        "b1": np.stack([layer(i, "intermediate.dense.bias")
                        for i in range(L)]),
        "w2": np.stack([layer(i, "output.dense.weight").T
                        for i in range(L)]),
        "b2": np.stack([layer(i, "output.dense.bias") for i in range(L)]),
    }
    # the stride=P conv kernel (D, C, Ps, Ps): its (C, Ps, Ps) receptive
    # field flattens to one patch row, so reshape+transpose IS the matmul
    # weight (no resampling of any kind)
    conv_w = sd["embeddings.patch_embeddings.projection.weight"]
    params = {
        "patch_w": conv_w.reshape(D, -1).T.copy(),     # (C*Ps*Ps, D)
        "patch_b": sd["embeddings.patch_embeddings.projection.bias"],
        "cls_token": sd["embeddings.cls_token"],
        "pos": sd["embeddings.position_embeddings"][0],
        "lnf_scale": sd["layernorm.weight"],
        "lnf_bias": sd["layernorm.bias"],
        "blocks": blocks,
    }
    if "classifier.weight" in sd and cfg.n_classes:
        params["cls_w"] = sd["classifier.weight"].T
        params["cls_b"] = sd["classifier.bias"]
    return tree_to_jnp(params), cfg


def _has_classifier(model) -> bool:
    return any(k.startswith("classifier.") for k in model.state_dict())


def state_dict_from_params(params, cfg: ViTConfig):
    """Inverse of ``params_from_hf``: params -> HF-named numpy state dict
    so TPU-trained/fine-tuned ViT weights deploy back through
    ``transformers``."""
    blocks = {k: np.asarray(v) for k, v in params["blocks"].items()}
    D = cfg.d_model
    sd = {
        "embeddings.cls_token": np.asarray(params["cls_token"]),
        "embeddings.position_embeddings": np.asarray(params["pos"])[None],
        "embeddings.patch_embeddings.projection.weight":
            np.asarray(params["patch_w"]).T.reshape(
                D, cfg.n_channels, cfg.patch_size, cfg.patch_size),
        "embeddings.patch_embeddings.projection.bias":
            np.asarray(params["patch_b"]),
        "layernorm.weight": np.asarray(params["lnf_scale"]),
        "layernorm.bias": np.asarray(params["lnf_bias"]),
    }
    for i in range(cfg.n_layers):
        p = f"encoder.layer.{i}."
        wqkv, bqkv = blocks["wqkv"][i], blocks["bqkv"][i]
        sd[p + "attention.attention.query.weight"] = wqkv[:, :D].T
        sd[p + "attention.attention.key.weight"] = wqkv[:, D:2 * D].T
        sd[p + "attention.attention.value.weight"] = wqkv[:, 2 * D:].T
        sd[p + "attention.attention.query.bias"] = bqkv[:D]
        sd[p + "attention.attention.key.bias"] = bqkv[D:2 * D]
        sd[p + "attention.attention.value.bias"] = bqkv[2 * D:]
        sd[p + "attention.output.dense.weight"] = blocks["wo"][i].T
        sd[p + "attention.output.dense.bias"] = blocks["bo"][i]
        sd[p + "layernorm_before.weight"] = blocks["ln1_scale"][i]
        sd[p + "layernorm_before.bias"] = blocks["ln1_bias"][i]
        sd[p + "layernorm_after.weight"] = blocks["ln2_scale"][i]
        sd[p + "layernorm_after.bias"] = blocks["ln2_bias"][i]
        sd[p + "intermediate.dense.weight"] = blocks["w1"][i].T
        sd[p + "intermediate.dense.bias"] = blocks["b1"][i]
        sd[p + "output.dense.weight"] = blocks["w2"][i].T
        sd[p + "output.dense.bias"] = blocks["b2"][i]
    if "cls_w" in params:
        sd["classifier.weight"] = np.asarray(params["cls_w"]).T
        sd["classifier.bias"] = np.asarray(params["cls_b"])
    return sd


def export_to_hf(params, cfg: ViTConfig, model):
    """Load params into a live transformers ViT ``model``
    (ViTForImageClassification, or ViTModel built with
    ``add_pooling_layer=False`` — our ViT has no pooler, and silently
    leaving a random pooler in the target would be a partial deploy).
    Bidirectionally validated via ``hf_common.load_into_hf``."""
    from .hf_common import load_into_hf
    sd = state_dict_from_params(params, cfg)
    return load_into_hf(sd, model, scope="vit.",
                        droppable=("classifier.",))
