"""Flagship Transformer LM — the multi-chip tpu-native training path.

The reference's NLP coverage is a single-GPU Transformer example
(``examples/nlp/hetu_transformer.py``, unfused BatchMatMul attention). This
module goes well beyond reference parity, because long-context and
distributed are first-class here:

- **dp**: batch sharded over the ``dp`` mesh axis; GSPMD inserts the gradient
  all-reduce over ICI.
- **tp**: Megatron-style sharding — qkv/mlp-in column-parallel, out/mlp-out
  row-parallel over ``tp``; attention heads sharded over ``tp``.
- **sp**: sequence dimension sharded over ``sp``; k/v are gathered for
  attention (Ulysses-style; a Pallas ring-attention path lives in
  ``hetu_tpu/ops/pallas``).
- **ep**: switch-style top-1 MoE with capacity; experts sharded over ``ep``,
  token dispatch/combine become all-to-alls.
- **pp**: see ``hetu_tpu/parallel/pipeline.py`` (explicit ppermute GPipe).

Params are f32, compute in bf16 (MXU native), losses/reductions f32.
Per-layer params are stacked on a leading L axis and the blocks run under
``lax.scan`` with ``jax.checkpoint`` — one compiled block, L iterations,
activation memory traded for recompute.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 8
    d_ff: int = 2048
    max_seq_len: int = 1024
    n_experts: int = 0          # 0 = dense MLP; >0 = switch MoE
    capacity_factor: float = 1.25
    dropout_rate: float = 0.0
    dtype: Any = jnp.bfloat16   # compute dtype
    remat: bool = True
    causal: bool = True         # False = bidirectional encoder (BERT)
    # attention implementation: "auto" picks ring when the mesh shards the
    # sequence (sp>1), the fused Pallas kernel on TPU for block-divisible
    # sequences, and the unfused dot-product form otherwise
    attn_impl: str = "auto"     # auto | dot | flash | ring
    # LM loss through the fused Pallas linear+softmax-CE kernel
    # (kernels/fused_ce.py): skips the (B*T, V) logits tensor on the
    # single-program TPU path. "auto" = TPU only; True forces (tests);
    # False = always materialize. Meshes keep the einsum form (GSPMD
    # cannot partition the custom kernel).
    fused_lm_ce: Any = "auto"
    # Canonical-BERT architecture knobs (default = the flagship pre-LN
    # trunk; models/hf_bert.py flips all four to load HuggingFace BERT
    # checkpoints weight-for-weight):
    post_ln: bool = False       # LN after each residual add (original
                                # Transformer/BERT) instead of before the
                                # sublayer; the final lnf is NOT applied by
                                # the trunk in this mode (BERT has no final
                                # LN — callers repurpose lnf as the
                                # embedding LN)
    ln_eps: float = 1e-5        # HF BERT uses 1e-12
    gelu_exact: bool = False    # erf gelu (HF "gelu") vs tanh approximation
    attn_proj_bias: bool = False  # bias terms on the qkv and output
                                  # projections (BERT has them; GPT-style
                                  # flagship configs do not)
    tied_head: bool = False     # LM head shares the token embedding (GPT-2
                                # semantics): no separate "head" param, the
                                # vocab projection is embed itself — halves
                                # embedding memory and keeps fine-tuned
                                # weights exportable as a tied checkpoint
    # Llama-family dialect knobs (models/hf_llama.py flips these to load
    # HF Llama/Mistral-class checkpoints weight-for-weight):
    norm: str = "layernorm"     # "rmsnorm": x·rsqrt(mean(x²)+eps)·scale,
                                # no bias/mean-centering (the *_bias params
                                # exist but are ignored so pytree structure
                                # is dialect-independent)
    rope: bool = False          # rotary position embeddings on q/k (the
                                # cache stores ROTATED keys); replaces the
                                # learned "pos" table
    rope_theta: float = 10000.0
    mlp: str = "gelu"           # "swiglu": down(silu(gate(x))·up(x)) with
                                # an extra w3 (up) weight, no biases used
    n_kv_heads: int = 0         # grouped-query attention: 0 = n_heads
                                # (MHA); otherwise k/v project to n_kv
                                # heads and broadcast to the q heads
    use_pos_emb: bool = True    # False: no learned position table (rope
                                # carries positions)

    def __post_init__(self):
        if self.mlp == "swiglu" and self.n_experts > 0:
            raise ValueError(
                "mlp='swiglu' with n_experts>0: the MoE expert MLP is "
                "gelu-only — a swiglu config would silently train a "
                "different architecture than requested")

    @property
    def kv_heads(self):
        n = self.n_kv_heads or self.n_heads
        assert self.n_heads % n == 0
        return n

    @property
    def head_dim(self):
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads


# ---------------------------------------------------------------------------
# parameter init + sharding rules
# ---------------------------------------------------------------------------

def _init_normal(key, shape, scale):
    return jax.random.normal(key, shape, jnp.float32) * scale


def init_trunk_params(rng, cfg: TransformerConfig):
    """The block stack + final norm ONLY — for trunk-reusing families
    (ViT) that would otherwise materialize a dead embedding/pos/head just
    to throw them away. ``init_params`` shares the same key schedule, so a
    trunk initialized here is bit-identical to one sliced out of it."""
    return _init_trunk(jax.random.split(rng, 12), cfg)


def _init_trunk(ks, cfg: TransformerConfig):
    L, D, F = cfg.n_layers, cfg.d_model, cfg.d_ff
    E = cfg.n_experts
    norm = _init_normal

    qkv_width = (cfg.n_heads + 2 * cfg.kv_heads) * cfg.head_dim
    blocks = {
        "ln1_scale": jnp.ones((L, D), jnp.float32),
        "ln1_bias": jnp.zeros((L, D), jnp.float32),
        "wqkv": norm(ks[0], (L, D, qkv_width), 0.02),
        "wo": norm(ks[1], (L, D, D), 0.02 / np.sqrt(2 * L)),
        "ln2_scale": jnp.ones((L, D), jnp.float32),
        "ln2_bias": jnp.zeros((L, D), jnp.float32),
    }
    if cfg.attn_proj_bias:
        blocks["bqkv"] = jnp.zeros((L, qkv_width), jnp.float32)
        blocks["bo"] = jnp.zeros((L, D), jnp.float32)
    if cfg.mlp == "swiglu":
        blocks["w3"] = norm(ks[8], (L, D, F), 0.02)
    if E > 0:
        blocks.update({
            "router": norm(ks[2], (L, D, E), 0.02),
            "w1": norm(ks[3], (L, E, D, F), 0.02),
            "b1": jnp.zeros((L, E, F), jnp.float32),
            "w2": norm(ks[4], (L, E, F, D), 0.02 / np.sqrt(2 * L)),
            "b2": jnp.zeros((L, E, D), jnp.float32),
        })
    else:
        blocks.update({
            "w1": norm(ks[3], (L, D, F), 0.02),
            "b1": jnp.zeros((L, F), jnp.float32),
            "w2": norm(ks[4], (L, F, D), 0.02 / np.sqrt(2 * L)),
            "b2": jnp.zeros((L, D), jnp.float32),
        })
    return {
        "blocks": blocks,
        "lnf_scale": jnp.ones((D,), jnp.float32),
        "lnf_bias": jnp.zeros((D,), jnp.float32),
    }


def init_params(rng, cfg: TransformerConfig):
    D, V = cfg.d_model, cfg.vocab_size
    ks = jax.random.split(rng, 12)
    params = _init_trunk(ks, cfg)
    params["embed"] = _init_normal(ks[5], (V, D), 0.02)
    if cfg.use_pos_emb:
        params["pos"] = _init_normal(ks[6], (cfg.max_seq_len, D), 0.02)
    if not cfg.tied_head:
        params["head"] = _init_normal(ks[7], (D, V), 0.02)
    return params


def param_specs(cfg: TransformerConfig):
    """PartitionSpecs: Megatron tp sharding; experts over ep; rest replicated
    (dp/sp shard activations, not weights)."""
    moe = cfg.n_experts > 0
    blocks = {
        "ln1_scale": P(None, None),
        "ln1_bias": P(None, None),
        "wqkv": P(None, None, "tp"),
        "wo": P(None, "tp", None),
        "ln2_scale": P(None, None),
        "ln2_bias": P(None, None),
    }
    if cfg.attn_proj_bias:
        blocks["bqkv"] = P(None, "tp")
        blocks["bo"] = P(None, None)
    if cfg.mlp == "swiglu":
        blocks["w3"] = P(None, None, "tp")
    if moe:
        blocks.update({
            "router": P(None, None, None),
            "w1": P(None, "ep", None, "tp"),
            "b1": P(None, "ep", "tp"),
            "w2": P(None, "ep", "tp", None),
            "b2": P(None, "ep", None),
        })
    else:
        blocks.update({
            "w1": P(None, None, "tp"),
            "b1": P(None, "tp"),
            "w2": P(None, "tp", None),
            "b2": P(None, None),
        })
    specs = {
        "embed": P(None, "tp"),
        "blocks": blocks,
        "lnf_scale": P(None),
        "lnf_bias": P(None),
    }
    if cfg.use_pos_emb:
        specs["pos"] = P(None, "tp")
    if not cfg.tied_head:
        specs["head"] = P(None, "tp")
    return specs


def _constrain(x, mesh, *spec):
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P(*spec)))


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _dropout(x, rate, rng):
    """Inverted dropout; identity when rate == 0 or rng is None (eval)."""
    if rate == 0.0 or rng is None:
        return x
    keep = jax.random.bernoulli(rng, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), 0.0).astype(x.dtype)


def _layer_norm(x, scale, bias, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, -1, keepdims=True)
    var = jnp.var(x32, -1, keepdims=True)
    return ((x32 - mu) * jax.lax.rsqrt(var + eps) * scale + bias).astype(x.dtype)


def _gelu(x, cfg: TransformerConfig):
    # HF BERT's "gelu" is the exact erf form; jax.nn.gelu defaults to the
    # tanh approximation (fine for training-from-scratch, wrong for
    # checkpoint-exact parity)
    return jax.nn.gelu(x, approximate=not cfg.gelu_exact)


def _rms_norm(x, scale, eps):
    x32 = x.astype(jnp.float32)
    x32 = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, -1, keepdims=True) + eps)
    return (x32 * scale).astype(x.dtype)


def _norm(x, scale, bias, cfg: TransformerConfig):
    """Dialect-dispatched normalization: LayerNorm (default) or RMSNorm
    (Llama family — ``bias`` exists in the pytree but is ignored)."""
    if cfg.norm == "rmsnorm":
        return _rms_norm(x, scale, cfg.ln_eps)
    return _layer_norm(x, scale, bias, cfg.ln_eps)


def _rope(x, pos0, theta):
    """Rotary position embeddings, HF rotate_half convention: x (B, nh, T,
    hd) at absolute positions pos0..pos0+T-1; the head dim splits into two
    halves rotated by position-dependent angles."""
    B, nh, T, hd = x.shape
    inv = 1.0 / (theta ** (jnp.arange(0, hd, 2, dtype=jnp.float32) / hd))
    t = pos0 + jnp.arange(T, dtype=jnp.float32)
    freqs = jnp.outer(t, inv)                       # (T, hd/2)
    cos = jnp.concatenate([jnp.cos(freqs)] * 2, -1)  # (T, hd)
    sin = jnp.concatenate([jnp.sin(freqs)] * 2, -1)
    x32 = x.astype(jnp.float32)
    x1, x2 = x32[..., :hd // 2], x32[..., hd // 2:]
    rotated = jnp.concatenate([-x2, x1], -1)
    return (x32 * cos + rotated * sin).astype(x.dtype)


def _is_key_padding_bias(attn_bias):
    """A (B, 1, 1, T) additive bias is per-KEY (the padding-mask form BERT
    builds from input_mask) — the flash kernel folds it into its score
    blocks. Any other bias shape needs the unfused path."""
    return (attn_bias is not None and attn_bias.ndim == 4
            and attn_bias.shape[1] == 1 and attn_bias.shape[2] == 1)


def _resolve_attn_impl(cfg: TransformerConfig, mesh, T, attn_bias=None):
    impl = cfg.attn_impl
    if attn_bias is not None and not _is_key_padding_bias(attn_bias):
        # only the unfused path applies a general additive bias; an
        # explicitly requested fused/ring impl must not degrade SILENTLY —
        # such batches materialize full (B, nh, T, T) f32 scores per layer
        if impl not in ("auto", "dot"):
            import warnings
            warnings.warn(
                f"attn_impl={impl!r} requested but a non-key-padding "
                "attn_bias is present: falling back to the unfused 'dot' "
                "path", stacklevel=3)
        return "dot"
    if attn_bias is not None and impl == "flash" and T % min(128, T):
        # masked configs used to ride the unfused fallback regardless of T;
        # keep that grace instead of letting the kernel's block-divisibility
        # check raise on a previously-working masked batch
        import warnings
        warnings.warn(
            f"attn_impl='flash' with a padding mask needs seq_len divisible "
            f"by 128 (got {T}): falling back to the unfused 'dot' path",
            stacklevel=3)
        return "dot"
    if impl != "auto":
        return impl
    if mesh is not None and "sp" in mesh.axis_names and mesh.shape["sp"] > 1:
        return "ring"   # key-padding biases rotate with the k/v chunks
    if jax.default_backend() == "tpu" and T % 128 == 0:
        return "flash"
    return "dot"


def _attention_core(q, k, v, cfg: TransformerConfig, mesh, impl,
                    attn_bias=None):
    """q/k/v: (B, nh, T, hd) -> (B, nh, T, hd). Three paths:
    - ring: sequence-parallel exact attention over the sp axis (shard_map +
      ppermute ring, hetu_tpu/parallel/ring_attention.py)
    - flash: fused Pallas online-softmax kernel (hetu_tpu/kernels); folds a
      key-padding ``attn_bias`` (B, 1, 1, T) into its score blocks
    - dot: unfused reference form (the reference framework's
      BatchMatMul+Softmax attention); applies any additive ``attn_bias``"""
    hd = q.shape[-1]
    # (B, 1, 1, T) key-padding bias -> (B, T) per-key form shared by the
    # fused paths; a broadcast-batch (1, 1, 1, T) mask expands to the real
    # batch so dp/sp sharding of the bias is always well-formed
    kb = None
    if attn_bias is not None:
        kb = attn_bias.reshape(attn_bias.shape[0], attn_bias.shape[-1])
        if kb.shape[0] == 1 and q.shape[0] > 1:
            kb = jnp.broadcast_to(kb, (q.shape[0], kb.shape[1]))
    if impl == "ring":
        from ..parallel.ring_attention import ring_attention
        from ..utils import shard_map
        spec = P("dp", "tp", "sp", None)
        fn_part = functools.partial(ring_attention, axis_name="sp",
                                    causal=cfg.causal)
        if kb is not None:
            # the bias shards like k's sequence axis; each column rotates
            # around the ring with its k/v chunk
            fn = shard_map(fn_part, mesh=mesh,
                           in_specs=(spec, spec, spec, P("dp", "sp")),
                           out_specs=spec)
            return fn(q, k, v, kb)
        fn = shard_map(fn_part, mesh=mesh, in_specs=(spec,) * 3,
                       out_specs=spec)
        return fn(q, k, v)
    if impl == "flash":
        from ..kernels.flash_attention import flash_attention
        return flash_attention(q, k, v, cfg.causal, k_bias=kb)
    T = q.shape[2]
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) / np.sqrt(hd)
    if cfg.causal:
        qpos = jax.lax.broadcasted_iota(jnp.int32, (T, T), 0)
        kpos = jax.lax.broadcasted_iota(jnp.int32, (T, T), 1)
        scores = jnp.where(kpos <= qpos, scores, -1e30)
    if attn_bias is not None:
        scores = scores + attn_bias.astype(jnp.float32)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v,
                      preferred_element_type=jnp.float32).astype(q.dtype)


def _attention(h, p, cfg: TransformerConfig, mesh, attn_bias=None):
    B, T, D = h.shape
    nh, hd = cfg.n_heads, cfg.head_dim
    nkv = cfg.kv_heads
    impl = _resolve_attn_impl(cfg, mesh, T, attn_bias)
    qkv = jnp.einsum("btd,de->bte", h, p["wqkv"].astype(h.dtype),
                     preferred_element_type=jnp.float32).astype(h.dtype)
    if cfg.attn_proj_bias:
        qkv = qkv + p["bqkv"].astype(h.dtype)
    q, k, v = jnp.split(qkv, [nh * hd, (nh + nkv) * hd], axis=-1)
    q = q.reshape(B, T, nh, hd).transpose(0, 2, 1, 3)
    if impl == "ring":
        # k/v stay sequence-sharded: the ring rotates chunks over ICI
        k = k.reshape(B, T, nkv, hd).transpose(0, 2, 1, 3)
        v = v.reshape(B, T, nkv, hd).transpose(0, 2, 1, 3)
    else:
        # Ulysses-style: gather k/v over sp, heads stay tp-sharded
        k = _constrain(k, mesh, "dp", None, "tp").reshape(
            B, T, nkv, hd).transpose(0, 2, 1, 3)
        v = _constrain(v, mesh, "dp", None, "tp").reshape(
            B, T, nkv, hd).transpose(0, 2, 1, 3)
    if cfg.rope:
        # rotate BEFORE any gqa broadcast (rope is per-kv-head)
        q = _rope(q, 0, cfg.rope_theta)
        k = _rope(k, 0, cfg.rope_theta)
    if nkv != nh:
        # grouped-query: broadcast each kv head to its query group; every
        # attention impl then sees matching head counts
        k = jnp.repeat(k, nh // nkv, axis=1)
        v = jnp.repeat(v, nh // nkv, axis=1)
    out = _attention_core(q, k, v, cfg, mesh, impl, attn_bias)
    out = out.transpose(0, 2, 1, 3).reshape(B, T, D)
    out = jnp.einsum("btd,de->bte", out, p["wo"].astype(h.dtype),
                     preferred_element_type=jnp.float32).astype(h.dtype)
    if cfg.attn_proj_bias:
        out = out + p["bo"].astype(h.dtype)
    return out


def _dense_mlp(h, p, cfg, mesh):
    if cfg.mlp == "swiglu":
        # Llama MLP: down(silu(gate(x)) * up(x)); the b1/b2 params exist
        # but are zero/unused in this dialect (no biases in the family)
        gate = jnp.einsum("btd,df->btf", h, p["w1"].astype(h.dtype),
                          preferred_element_type=jnp.float32)
        up = jnp.einsum("btd,df->btf", h, p["w3"].astype(h.dtype),
                        preferred_element_type=jnp.float32)
        u = (jax.nn.silu(gate) * up).astype(h.dtype)
        return jnp.einsum("btf,fd->btd", u, p["w2"].astype(h.dtype),
                          preferred_element_type=jnp.float32).astype(h.dtype)
    u = jnp.einsum("btd,df->btf", h, p["w1"].astype(h.dtype),
                   preferred_element_type=jnp.float32).astype(h.dtype)
    u = _gelu(u + p["b1"].astype(h.dtype), cfg)
    out = jnp.einsum("btf,fd->btd", u, p["w2"].astype(h.dtype),
                     preferred_element_type=jnp.float32).astype(h.dtype)
    return out + p["b2"].astype(h.dtype)


def _moe_mlp(h, p, cfg: TransformerConfig, mesh):
    """Switch-style top-1 MoE with capacity (experts sharded over ep; the
    dispatch/combine einsums become all-to-alls under GSPMD)."""
    B, T, D = h.shape
    E = cfg.n_experts
    S = B * T
    cap = max(1, int(cfg.capacity_factor * S / E))
    x = h.reshape(S, D)
    logits = jnp.einsum("sd,de->se", x.astype(jnp.float32),
                        p["router"].astype(jnp.float32))
    probs = jax.nn.softmax(logits, -1)
    gate, expert = jnp.max(probs, -1), jnp.argmax(probs, -1)
    # position of each token within its expert's capacity buffer
    onehot = jax.nn.one_hot(expert, E, dtype=jnp.int32)
    pos_in_expert = jnp.cumsum(onehot, axis=0) * onehot
    pos = jnp.max(pos_in_expert, axis=-1) - 1          # (S,)
    keep = pos < cap
    dispatch = (jax.nn.one_hot(expert, E, dtype=x.dtype)[:, :, None] *
                jax.nn.one_hot(pos, cap, dtype=x.dtype)[:, None, :] *
                keep[:, None, None].astype(x.dtype))    # (S, E, cap)
    expert_in = jnp.einsum("sec,sd->ecd", dispatch, x)  # (E, cap, D)
    expert_in = _constrain(expert_in, mesh, "ep", None, None)
    u = jnp.einsum("ecd,edf->ecf", expert_in, p["w1"].astype(x.dtype),
                   preferred_element_type=jnp.float32).astype(x.dtype)
    u = _gelu(u + p["b1"][:, None, :].astype(x.dtype), cfg)
    y = jnp.einsum("ecf,efd->ecd", u, p["w2"].astype(x.dtype),
                   preferred_element_type=jnp.float32).astype(x.dtype)
    y = y + p["b2"][:, None, :].astype(x.dtype)
    combine = dispatch * gate[:, None, None].astype(x.dtype)
    out = jnp.einsum("sec,ecd->sd", combine, y)
    # aux load-balancing loss (Switch Transformer eq. 4)
    density = jnp.mean(jax.nn.one_hot(expert, E, dtype=jnp.float32), axis=0)
    density_proxy = jnp.mean(probs, axis=0)
    aux = E * jnp.sum(density * density_proxy)
    return out.reshape(B, T, D), aux


def _block(h, layer_params, cfg: TransformerConfig, mesh, attn_bias=None,
           dropout_rng=None):
    """One transformer block. Pre-LN (flagship default): LN -> sublayer ->
    residual. Post-LN (``cfg.post_ln``, canonical BERT / original
    Transformer): sublayer -> residual -> LN, with ln1 after attention and
    ln2 after the MLP.

    LOCKSTEP CONTRACT: any new dialect knob added here must be mirrored
    in ``generate._decode_layer`` (the KV-cache form of this block) or
    decode silently diverges from training for that config."""
    post = cfg.post_ln
    h = _constrain(h, mesh, "dp", "sp", None)
    attn_in = h if post else _norm(
        h, layer_params["ln1_scale"], layer_params["ln1_bias"], cfg)
    attn_out = _attention(attn_in, layer_params, cfg, mesh, attn_bias)
    if dropout_rng is not None:
        k1, k2 = jax.random.split(dropout_rng)
        attn_out = _dropout(attn_out, cfg.dropout_rate, k1)
    h = h + attn_out
    if post:
        h = _norm(h, layer_params["ln1_scale"],
                  layer_params["ln1_bias"], cfg)
    h = _constrain(h, mesh, "dp", "sp", None)
    mlp_in = h if post else _norm(
        h, layer_params["ln2_scale"], layer_params["ln2_bias"], cfg)
    if cfg.n_experts > 0:
        out, aux = _moe_mlp(mlp_in, layer_params, cfg, mesh)
    else:
        out, aux = _dense_mlp(mlp_in, layer_params, cfg, mesh), jnp.zeros((), jnp.float32)
    if dropout_rng is not None:
        out = _dropout(out, cfg.dropout_rate, k2)
    h = h + out
    if post:
        h = _norm(h, layer_params["ln2_scale"],
                  layer_params["ln2_bias"], cfg)
    return h, aux


def embed_tokens(params, tokens, cfg: TransformerConfig):
    """(..., T) int32 -> (..., T, D) embeddings (+ learned positions,
    unless the dialect carries positions via rope)."""
    T = tokens.shape[-1]
    h = params["embed"][tokens].astype(cfg.dtype)
    if cfg.use_pos_emb:
        h = h + params["pos"][:T].astype(cfg.dtype)
    return h


def lm_head(params, h, cfg: TransformerConfig):
    """Final norm + vocab projection -> f32 logits. In post-LN mode the
    blocks already end LayerNormed and canonical post-LN has no final LN,
    so only the projection applies. Tied configs project against the token
    embedding itself (no transposed copy is materialized)."""
    if not cfg.post_ln:
        h = _norm(h, params["lnf_scale"], params["lnf_bias"], cfg)
    if cfg.tied_head:
        return jnp.einsum("btd,vd->btv", h, params["embed"].astype(h.dtype),
                          preferred_element_type=jnp.float32)
    return jnp.einsum("btd,dv->btv", h, params["head"].astype(h.dtype),
                      preferred_element_type=jnp.float32)


def nll_loss(logits, targets):
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
    return jnp.mean(-jnp.take_along_axis(logp, targets[..., None], -1)[..., 0])


def encode(params, h, cfg: TransformerConfig, mesh: Optional[Mesh] = None,
           attn_bias=None, dropout_rng=None):
    """Run the block stack on embedded input h (B, T, D) -> (h, aux_sum).
    The trunk shared by the causal LM and the bidirectional encoder (BERT);
    ``attn_bias`` (a padding mask, constant across layers) is a scan
    constant via closure. ``dropout_rng``: training-time dropout when
    ``cfg.dropout_rate > 0`` — omit for deterministic eval."""
    block_fn = functools.partial(_block, cfg=cfg, mesh=mesh)
    if cfg.remat:
        block_fn = jax.checkpoint(block_fn)
    L = cfg.n_layers

    def scan_body(carry, xs):
        h, aux_sum = carry
        layer_params, li = xs
        rng = (None if dropout_rng is None
               else jax.random.fold_in(dropout_rng, li))
        h, aux = block_fn(h, layer_params, attn_bias=attn_bias,
                          dropout_rng=rng)
        return (h, aux_sum + aux), None

    (h, aux_sum), _ = jax.lax.scan(
        scan_body, (h, jnp.zeros((), jnp.float32)),
        (params["blocks"], jnp.arange(L)))
    return h, aux_sum


def forward_hidden(params, tokens, cfg: TransformerConfig,
                   mesh: Optional[Mesh] = None, dropout_rng=None):
    """tokens (B, T) int32 -> (hidden (B, T, D), aux) before the LM head."""
    h = embed_tokens(params, tokens, cfg)
    h = _constrain(h, mesh, "dp", "sp", None)
    return encode(params, h, cfg, mesh, dropout_rng=dropout_rng)


def forward(params, tokens, cfg: TransformerConfig, mesh: Optional[Mesh] = None,
            dropout_rng=None):
    """tokens (B, T) int32 -> logits (B, T, V)."""
    h, aux_sum = forward_hidden(params, tokens, cfg, mesh,
                                dropout_rng=dropout_rng)
    return lm_head(params, h, cfg), aux_sum


def loss_fn(params, tokens, targets, cfg: TransformerConfig, mesh=None,
            aux_weight=0.01, dropout_rng=None):
    from ..kernels.fused_ce import should_fuse
    if should_fuse(cfg.fused_lm_ce, mesh):
        # fused linear+CE: the (B*T, V) logits never exist in HBM; the
        # head keeps its native (D, V) orientation (no transpose copy)
        from ..kernels.fused_ce import fused_linear_nll
        h, aux = forward_hidden(params, tokens, cfg, mesh,
                                dropout_rng=dropout_rng)
        if not cfg.post_ln:
            h = _norm(h, params["lnf_scale"], params["lnf_bias"], cfg)
        B, T, D = h.shape
        # both weight orientations are kernel-native (no vocab-sized
        # transpose): tied configs stream the (V, D) embedding, untied the
        # (D, V) head
        if cfg.tied_head:
            w, layout = params["embed"].astype(h.dtype), "vd"
        else:
            w, layout = params["head"].astype(h.dtype), "dv"
        V = w.shape[0] if layout == "vd" else w.shape[1]
        per = fused_linear_nll(h.reshape(B * T, D), w,
                               jnp.zeros((V,), jnp.float32),
                               targets.reshape(-1), w_layout=layout)
        return jnp.mean(per) + aux_weight * aux
    logits, aux = forward(params, tokens, cfg, mesh, dropout_rng=dropout_rng)
    return nll_loss(logits, targets) + aux_weight * aux


# ---------------------------------------------------------------------------
# train step (adamw fused into the step, buffers donated)
# ---------------------------------------------------------------------------

def count_params(params) -> int:
    """Total parameter count of any params pytree (shared by every model
    family — bert/vit re-export it)."""
    return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))


def init_opt_state(params):
    zeros = lambda p: jnp.zeros_like(p)
    return {"m": jax.tree.map(zeros, params),
            "v": jax.tree.map(zeros, params),
            "t": jnp.zeros((), jnp.float32)}


def adamw_update(params, grads, opt_state, lr=1e-3, b1=0.9, b2=0.999,
                 eps=1e-8, wd=0.01):
    t = opt_state["t"] + 1.0
    new_m = jax.tree.map(lambda m, g: b1 * m + (1 - b1) * g, opt_state["m"], grads)
    new_v = jax.tree.map(lambda v, g: b2 * v + (1 - b2) * g * g, opt_state["v"], grads)
    def upd(p, m, v):
        mh = m / (1 - b1 ** t)
        vh = v / (1 - b2 ** t)
        return p - lr * (mh / (jnp.sqrt(vh) + eps) + wd * p)
    new_params = jax.tree.map(upd, params, new_m, new_v)
    return new_params, {"m": new_m, "v": new_v, "t": t}


def zero1_opt_specs(cfg: TransformerConfig, mesh: Mesh):
    """ZeRO-1 (optimizer-state sharding over dp): each AdamW m/v slot is
    additionally sharded over the ``dp`` axis on its first free, divisible
    dimension. GSPMD then materializes the classic dataflow on its own —
    gradients reduce-scatter into the shard, the update computes sharded,
    and the fresh params all-gather back to their training layout
    (the 'Automatic Cross-Replica Sharding of Weight Update' recipe,
    arXiv:2004.13336, expressed as sharding annotations). Memory:
    optimizer state shrinks by ~dp x; step math is bit-identical."""
    dp = mesh.shape["dp"]
    specs = param_specs(cfg)
    shapes = jax.eval_shape(
        lambda: init_params(jax.random.PRNGKey(0), cfg))
    return jax.tree.map(
        lambda s, sh: shard_first_free_dim(s, sh, dp), specs, shapes,
        is_leaf=lambda x: isinstance(x, P))


def shard_first_free_dim(spec, shape, dp: int):
    """Add 'dp' to a PartitionSpec on the first unsharded, dp-divisible
    dimension (the ZeRO-1 slot layout rule — shared with the pipeline
    builders, whose block specs carry a leading 'pp' dim)."""
    parts = tuple(spec) + (None,) * (len(shape.shape) - len(tuple(spec)))
    for ax, part in enumerate(parts):
        if part is None and shape.shape[ax] % dp == 0:
            return P(*parts[:ax], "dp", *parts[ax + 1:])
    return P(*parts)


def place_opt_state(opt_state, specs, mesh: Mesh):
    """Place an AdamW state on the mesh: m/v per the param specs, the
    step counter replicated — the one placement recipe shared by the
    trunk and both pipeline schedule builders."""
    shard = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                         is_leaf=lambda x: isinstance(x, P))
    put = functools.partial(jax.tree.map, jax.device_put)
    return {"m": put(opt_state["m"], shard), "v": put(opt_state["v"], shard),
            "t": jax.device_put(opt_state["t"], NamedSharding(mesh, P()))}


def shard_opt_state(opt_state, cfg: TransformerConfig, mesh: Mesh,
                    zero1: bool = False):
    """Place an optimizer state on the mesh — the ZeRO-1 layout when
    ``zero1`` (jit pins committed input shardings, so the state must be
    placed before the first step)."""
    specs = zero1_opt_specs(cfg, mesh) if zero1 else param_specs(cfg)
    return place_opt_state(opt_state, specs, mesh)


def make_train_step(cfg: TransformerConfig, mesh: Optional[Mesh] = None,
                    lr=1e-3, accum_steps: int = 1, zero1: bool = False):
    """Returns jitted (params, opt_state, tokens, targets) ->
    (loss, params, opt_state) with GSPMD dp/tp/sp/ep sharding.

    ``zero1=True`` (mesh only): AdamW m/v shard over dp — see
    ``zero1_opt_specs``; place the state with
    ``shard_opt_state(opt, cfg, mesh, zero1=True)`` before the first
    step. Optimizer state memory drops ~dp x; numerics are unchanged
    (the same update, computed shard-wise).

    ``accum_steps > 1``: gradient accumulation — tokens/targets gain a
    leading accumulation axis (A, B, T); microbatch grads are averaged by a
    ``lax.scan`` (one compiled block, sequential activation memory) before
    the single optimizer apply, numerically identical to one big batch of
    A*B under mean-loss (with dropout OFF; each microbatch draws its own
    dropout mask, so the dropout-on accumulation is the usual
    independent-masks estimate, not a big-batch replica).

    ``cfg.dropout_rate > 0``: the step takes a trailing ``dropout_rng``
    argument (pass a fresh fold of your training key each step)."""
    use_dropout = cfg.dropout_rate > 0.0

    def step(params, opt_state, tokens, targets, dropout_rng=None):
        if use_dropout:
            # a forgotten key must not silently train WITHOUT dropout
            assert dropout_rng is not None, (
                "cfg.dropout_rate > 0: pass dropout_rng to the train step")
        if accum_steps == 1:
            loss, grads = jax.value_and_grad(loss_fn)(
                params, tokens, targets, cfg, mesh,
                dropout_rng=dropout_rng)
        else:
            assert tokens.shape[0] == accum_steps, (
                f"leading (accumulation) axis {tokens.shape[0]} != "
                f"accum_steps {accum_steps}")

            def micro(carry, xs):
                loss_sum, gsum = carry
                tok, tgt, mi = xs
                rng = (None if dropout_rng is None
                       else jax.random.fold_in(dropout_rng, mi))
                l, g = jax.value_and_grad(loss_fn)(params, tok, tgt, cfg,
                                                   mesh, dropout_rng=rng)
                return (loss_sum + l,
                        jax.tree.map(jnp.add, gsum, g)), None

            zeros = jax.tree.map(jnp.zeros_like, params)
            (loss_sum, gsum), _ = jax.lax.scan(
                micro, (jnp.zeros((), jnp.float32), zeros),
                (tokens, targets, jnp.arange(accum_steps)))
            loss = loss_sum / accum_steps
            grads = jax.tree.map(lambda g: g / accum_steps, gsum)
        new_params, new_opt = adamw_update(params, grads, opt_state, lr=lr)
        return loss, new_params, new_opt

    if not use_dropout:
        # keep the historical 4-arg signature for deterministic configs
        det = lambda params, opt_state, tokens, targets: step(  # noqa: E731
            params, opt_state, tokens, targets)
        step_fn = det
    else:
        step_fn = step

    if mesh is None:
        return jax.jit(step_fn, donate_argnums=(0, 1))

    specs = param_specs(cfg)
    pshard = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                          is_leaf=lambda x: isinstance(x, P))
    if zero1:
        oshard = jax.tree.map(lambda s: NamedSharding(mesh, s),
                              zero1_opt_specs(cfg, mesh),
                              is_leaf=lambda x: isinstance(x, P))
    else:
        oshard = pshard
    opt_shard = {"m": oshard, "v": oshard,
                 "t": NamedSharding(mesh, P())}
    data_shard = NamedSharding(mesh, P(("dp",), None) if accum_steps == 1
                               else P(None, ("dp",), None))
    in_sh = (pshard, opt_shard, data_shard, data_shard)
    if use_dropout:
        in_sh = in_sh + (NamedSharding(mesh, P()),)   # replicated rng key
    return jax.jit(
        step_fn,
        in_shardings=in_sh,
        out_shardings=(NamedSharding(mesh, P()), pshard, opt_shard),
        donate_argnums=(0, 1),
    )


def shard_params(params, cfg: TransformerConfig, mesh: Mesh):
    specs = param_specs(cfg)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs)
