"""Shared helpers for the HuggingFace checkpoint importers
(``hf_bert.py``, ``hf_gpt2.py``) — one place for the torch->numpy->jnp
conversion so dtype handling cannot drift between model families."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp


def np_f32(t) -> np.ndarray:
    """torch tensor -> float32 numpy (covers f16/bf16 checkpoints)."""
    return t.detach().to("cpu").float().numpy()


def tree_to_jnp(params: dict) -> dict:
    """One-level params dict (leaves or one nested dict) -> jnp arrays."""
    return {k: (jnp.asarray(v) if not isinstance(v, dict)
                else {kk: jnp.asarray(vv) for kk, vv in v.items()})
            for k, v in params.items()}
