"""Shared helpers for the HuggingFace checkpoint importers
(``hf_bert.py``, ``hf_gpt2.py``) — one place for the torch->numpy->jnp
conversion so dtype handling cannot drift between model families."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp


def np_f32(t) -> np.ndarray:
    """torch tensor -> float32 numpy (covers f16/bf16 checkpoints)."""
    return t.detach().to("cpu").float().numpy()


def tree_to_jnp(params: dict) -> dict:
    """One-level params dict (leaves or one nested dict) -> jnp arrays."""
    return {k: (jnp.asarray(v) if not isinstance(v, dict)
                else {kk: jnp.asarray(vv) for kk, vv in v.items()})
            for k, v in params.items()}


def load_into_hf(sd: dict, model, scope: str, skip_target=lambda k: False,
                 droppable=()):
    """Load an unscoped HF-named numpy state dict into a live transformers
    ``model``, shared by both exporters so the validation cannot drift.

    Validates BOTH directions, so a silently partial deploy cannot happen:
    - every exported key must land in the target (an unmatched trunk key —
      e.g. ``encoder.layer.8.*`` against a 6-layer model — is an
      architecture mismatch and raises; keys under a ``droppable`` prefix,
      i.e. heads the target model class does not have, may be dropped);
    - every target key must be filled (except ``skip_target`` buffers);
    - shape mismatches raise inside ``load_state_dict`` itself.
    """
    import torch
    target = model.state_dict()
    scoped, unmatched = {}, []
    for k, v in sd.items():
        name = (k if k in target
                else scope + k if scope + k in target else None)
        if name is None:
            if not k.startswith(tuple(droppable)):
                unmatched.append(k)
            continue
        # owning copy: jax->numpy views are read-only, torch warns on them
        scoped[name] = torch.tensor(np.asarray(v))
    if unmatched:
        raise ValueError(
            f"export keys with no slot in the target model (architecture "
            f"mismatch?): {unmatched[:6]}{'...' if len(unmatched) > 6 else ''}")
    missing = [k for k in target if k not in scoped and not skip_target(k)]
    if missing:
        raise ValueError(f"export cannot fill target keys: {missing}")
    model.load_state_dict(scoped, strict=False)
    return model
