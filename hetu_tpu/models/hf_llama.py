"""HuggingFace Llama-family checkpoint import — RoPE/RMSNorm/SwiGLU/GQA.

``transformers`` Llama (LlamaModel / LlamaForCausalLM; windowless
Mistral-class configs share the layout — sliding-window attention and
non-default head_dim/rope_scaling refuse at import) is the flagship
trunk's Llama dialect:
pre-LN with RMSNorm (``input_layernorm`` -> ln1, ``post_attention_layernorm``
-> ln2, final ``model.norm`` -> lnf; the unused *_bias params import as
zeros), rotary position embeddings (HF rotate_half convention =
``transformer._rope``), SwiGLU MLP (gate/up/down -> w1/w3/w2), optional
grouped-query attention (num_key_value_heads < num_attention_heads), no
learned position table, and an untied (D, V) lm_head unless the config
ties it. Import is a pure weight relayout; the imported model rides the
KV-cache decode (rotated keys in the cache), speculative decoding, and
the training step. ``tests/test_hf_llama.py`` pins logits, decode, and
generation against the torch forward. The reference has no checkpoint
interop of any kind.
"""
from __future__ import annotations

from typing import Any, Dict

import numpy as np
import jax.numpy as jnp

from .hf_common import np_f32, tree_to_jnp
from .transformer import TransformerConfig


def config_from_hf(hf_config, **overrides) -> TransformerConfig:
    """transformers.LlamaConfig -> a flagship TransformerConfig; refuses
    variants the trunk does not implement (importing them would run but
    be numerically wrong)."""
    act = getattr(hf_config, "hidden_act", "silu")
    if act not in ("silu", "swish"):
        raise NotImplementedError(f"hidden_act={act!r}: only silu")
    if getattr(hf_config, "attention_bias", False):
        raise NotImplementedError("attention_bias=True Llama variants")
    if getattr(hf_config, "sliding_window", None):
        # Mistral-style windowed attention: the trunk attends fully, so
        # any sequence longer than the window would silently diverge
        raise NotImplementedError(
            f"sliding_window={hf_config.sliding_window}: only full "
            "attention (windowless Mistral-class configs import fine)")
    hd = hf_config.hidden_size // hf_config.num_attention_heads
    if getattr(hf_config, "head_dim", hd) not in (None, hd):
        raise NotImplementedError(
            f"head_dim={hf_config.head_dim} != hidden_size/num_heads "
            f"({hd}): the trunk derives head_dim from d_model")
    scaling = getattr(hf_config, "rope_scaling", None)
    if scaling not in (None, {}) and (
            not isinstance(scaling, dict)
            or scaling.get("rope_type", scaling.get("type")) != "default"):
        raise NotImplementedError(f"rope_scaling={scaling!r}")
    kw = dict(
        vocab_size=hf_config.vocab_size,
        d_model=hf_config.hidden_size,
        n_heads=hf_config.num_attention_heads,
        n_kv_heads=(hf_config.num_key_value_heads
                    if hf_config.num_key_value_heads
                    != hf_config.num_attention_heads else 0),
        n_layers=hf_config.num_hidden_layers,
        d_ff=hf_config.intermediate_size,
        max_seq_len=hf_config.max_position_embeddings,
        ln_eps=hf_config.rms_norm_eps,
        norm="rmsnorm",
        rope=True,
        rope_theta=float(getattr(hf_config, "rope_theta", 10000.0)),
        mlp="swiglu",
        use_pos_emb=False,
        tied_head=bool(getattr(hf_config, "tie_word_embeddings", False)),
        causal=True,
        dtype=jnp.float32,
    )
    kw.update(overrides)
    return TransformerConfig(**kw)


def params_from_hf(model, cfg: TransformerConfig = None):
    """(transformers LlamaModel/LlamaForCausalLM, cfg?) -> (params, cfg);
    a caller-supplied cfg is validated against the checkpoint."""
    if cfg is None:
        cfg = config_from_hf(model.config)
    want = config_from_hf(model.config)
    mismatched = [f
                  for f in ("vocab_size", "d_model", "n_heads",
                            "n_kv_heads", "n_layers", "d_ff", "max_seq_len",
                            "ln_eps", "norm", "rope", "rope_theta", "mlp",
                            "use_pos_emb", "tied_head", "causal",
                            "post_ln", "attn_proj_bias", "n_experts")
                  if getattr(cfg, f) != getattr(want, f)]
    if mismatched:
        raise ValueError(
            "cfg disagrees with the checkpoint's architecture on "
            + ", ".join(f"{f} ({getattr(cfg, f)} != {getattr(want, f)})"
                        for f in mismatched))
    sd: Dict[str, Any] = {}
    for k, v in model.state_dict().items():
        if k.startswith("model."):
            k = k[len("model."):]
        if "rotary_emb" in k:
            continue              # inv_freq buffers; recomputed by _rope
        sd[k] = np_f32(v)
    L, D, F = cfg.n_layers, cfg.d_model, cfg.d_ff

    def layer(i, name):
        return sd[f"layers.{i}.{name}"]

    wqkv = np.stack([
        np.concatenate([layer(i, "self_attn.q_proj.weight").T,
                        layer(i, "self_attn.k_proj.weight").T,
                        layer(i, "self_attn.v_proj.weight").T], axis=1)
        for i in range(L)])               # (L, D, (nh+2nkv)*hd)
    blocks = {
        "wqkv": wqkv,
        "wo": np.stack([layer(i, "self_attn.o_proj.weight").T
                        for i in range(L)]),
        "ln1_scale": np.stack([layer(i, "input_layernorm.weight")
                               for i in range(L)]),
        "ln1_bias": np.zeros((L, D), np.float32),   # unused (rmsnorm)
        "ln2_scale": np.stack([layer(i, "post_attention_layernorm.weight")
                               for i in range(L)]),
        "ln2_bias": np.zeros((L, D), np.float32),
        "w1": np.stack([layer(i, "mlp.gate_proj.weight").T
                        for i in range(L)]),
        "w3": np.stack([layer(i, "mlp.up_proj.weight").T
                        for i in range(L)]),
        "w2": np.stack([layer(i, "mlp.down_proj.weight").T
                        for i in range(L)]),
        "b1": np.zeros((L, F), np.float32),         # unused (swiglu)
        "b2": np.zeros((L, D), np.float32),
    }
    params = {
        "embed": sd["embed_tokens.weight"],
        "blocks": blocks,
        "lnf_scale": sd["norm.weight"],
        "lnf_bias": np.zeros((D,), np.float32),     # unused (rmsnorm)
    }
    if not cfg.tied_head:
        if "lm_head.weight" in sd:
            params["head"] = sd["lm_head.weight"].T.copy()
        else:
            raise ValueError(
                "untied config but the checkpoint has no lm_head (pass a "
                "LlamaForCausalLM, or a config with tie_word_embeddings)")
    return tree_to_jnp(params), cfg


def state_dict_from_params(params, cfg: TransformerConfig):
    """Inverse relayout: params -> HF-named numpy state dict (unscoped
    ``embed_tokens/layers.N/norm`` names + ``lm_head`` when untied)."""
    blocks = {k: np.asarray(v) for k, v in params["blocks"].items()}
    nh, nkv, hd = cfg.n_heads, cfg.kv_heads, cfg.head_dim
    sd = {
        "embed_tokens.weight": np.asarray(params["embed"]),
        "norm.weight": np.asarray(params["lnf_scale"]),
    }
    for i in range(cfg.n_layers):
        p = f"layers.{i}."
        wqkv = blocks["wqkv"][i]
        sd[p + "self_attn.q_proj.weight"] = wqkv[:, :nh * hd].T
        sd[p + "self_attn.k_proj.weight"] = \
            wqkv[:, nh * hd:(nh + nkv) * hd].T
        sd[p + "self_attn.v_proj.weight"] = wqkv[:, (nh + nkv) * hd:].T
        sd[p + "self_attn.o_proj.weight"] = blocks["wo"][i].T
        sd[p + "input_layernorm.weight"] = blocks["ln1_scale"][i]
        sd[p + "post_attention_layernorm.weight"] = blocks["ln2_scale"][i]
        sd[p + "mlp.gate_proj.weight"] = blocks["w1"][i].T
        sd[p + "mlp.up_proj.weight"] = blocks["w3"][i].T
        sd[p + "mlp.down_proj.weight"] = blocks["w2"][i].T
    if not cfg.tied_head:
        sd["lm_head.weight"] = np.asarray(params["head"]).T
    return sd


def export_to_hf(params, cfg: TransformerConfig, model):
    """Load params into a live transformers Llama ``model``
    (LlamaModel or LlamaForCausalLM); bidirectionally validated."""
    from .hf_common import load_into_hf
    sd = dict(state_dict_from_params(params, cfg))
    target = model.state_dict()
    if cfg.tied_head and any(k.startswith("lm_head.") for k in target):
        sd["lm_head.weight"] = sd["embed_tokens.weight"]
    return load_into_hf(
        sd, model, scope="model.",
        # rope inv_freq buffers on some transformers versions
        skip_target=lambda k: "rotary_emb" in k,
        droppable=("lm_head.",))
