"""Autoregressive generation with a KV cache — TPU-idiomatic decode.

The reference framework stops at training + an inference subexecutor that
re-runs the full forward; it has no incremental decoding. For an LM
framework that is half the user surface, so this module adds it the TPU
way: the whole generate loop is ONE ``lax.scan`` over time steps (static
shapes, no retrace, no host round-trips), each step updating a
(L, B, n_kv_heads, max_len, hd) key/value cache via ``dynamic_update_slice``
(GQA checkpoints keep their kv-cache memory saving at serving time) and
scanning the layer stack exactly like training does
(``models/transformer.py`` keeps per-layer params stacked on a leading L
axis).

Prompt handling: rectangular prompts prefill positions [0, P-1) in ONE
chunked forward (an MXU-shaped matmul; see ``_chunk_hidden``), then the
scan/while loop decodes from the boundary; ragged batches (per-row
``prompt_lens``) teacher-force inside the loop instead, since each row
crosses its own prompt boundary at a different step. Either way the whole
thing is one compiled program.

Dense MLP blocks only (the switch MoE flagship path is a training
configuration; decode asserts ``n_experts == 0``). Decode runs
single-program (``mesh=None``) or distributed: with a mesh, params keep
their Megatron tp layout, the KV cache shards batch-over-dp and
heads-over-tp, and GSPMD inserts the collectives (see
``make_generate_fn``).
"""
from __future__ import annotations

import functools
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from . import transformer as tfm


def _decode_layer(carry, layer_inputs, *, cfg, pos):
    """One transformer block for a CHUNK of C new tokens against the cache
    (C=1 is the classic decode step; C>1 is chunk verification for
    speculative decoding — attention is causal WITHIN the chunk and full
    over the cached prefix).

    carry: h (B, C, D); layer_inputs: (layer_params, k_cache, v_cache) with
    caches (B, nkv, M, hd); the chunk occupies positions [pos, pos+C).
    Returns updated caches alongside the new h.

    LOCKSTEP CONTRACT with ``transformer._block``: every architecture
    dialect knob (post_ln, attn_proj_bias, ln_eps, gelu flavor, future
    additions) must behave identically here, or decode silently runs a
    different network than training —
    test_incremental_logits_match_forward_postln_bias_dialect pins the
    current knob set.
    """
    h = carry
    p, kc, vc = layer_inputs
    B, C, D = h.shape
    nh, hd = cfg.n_heads, cfg.head_dim
    nkv = cfg.kv_heads
    M = kc.shape[2]

    post = cfg.post_ln
    attn_in = h if post else tfm._norm(h, p["ln1_scale"],
                                       p["ln1_bias"], cfg)
    qkv = jnp.einsum("bod,de->boe", attn_in, p["wqkv"].astype(h.dtype),
                     preferred_element_type=jnp.float32).astype(h.dtype)
    if cfg.attn_proj_bias:
        qkv = qkv + p["bqkv"].astype(h.dtype)
    q, k, v = jnp.split(qkv, [nh * hd, (nh + nkv) * hd], axis=-1)
    q = q.reshape(B, C, nh, hd).transpose(0, 2, 1, 3)   # (B, nh, C, hd)
    k = k.reshape(B, C, nkv, hd).transpose(0, 2, 1, 3)
    v = v.reshape(B, C, nkv, hd).transpose(0, 2, 1, 3)
    if cfg.rope:
        # rotate at the chunk's absolute positions; the cache stores
        # ROTATED keys (scores are position-relative after rotation)
        q = tfm._rope(q, pos, cfg.rope_theta)
        k = tfm._rope(k, pos, cfg.rope_theta)
    # gqa: the cache stores the nkv UNBROADCAST heads — the memory saving
    # is the point of a GQA checkpoint at serving time — and the scores
    # ride a grouped einsum (g query heads share each kv head); g=1
    # degenerates to classic MHA with identical math
    kc = jax.lax.dynamic_update_slice(kc, k, (0, 0, pos, 0))
    vc = jax.lax.dynamic_update_slice(vc, v, (0, 0, pos, 0))

    g = nh // nkv
    qg = q.reshape(B, nkv, g, C, hd)
    scores = jnp.einsum("bngqd,bnkd->bngqk", qg, kc,
                        preferred_element_type=jnp.float32) / np.sqrt(hd)
    # query i (global position pos+i) sees cache entries <= pos+i
    kpos = jax.lax.broadcasted_iota(jnp.int32, (1, 1, 1, C, M), 4)
    qpos = pos + jax.lax.broadcasted_iota(jnp.int32, (1, 1, 1, C, M), 3)
    scores = jnp.where(kpos <= qpos, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(h.dtype)
    ctx = jnp.einsum("bngqk,bnkd->bngqd", probs, vc,
                     preferred_element_type=jnp.float32).astype(h.dtype)
    ctx = ctx.reshape(B, nh, C, hd).transpose(0, 2, 1, 3).reshape(B, C, D)
    attn_out = jnp.einsum("bod,de->boe", ctx, p["wo"].astype(h.dtype),
                          preferred_element_type=jnp.float32).astype(h.dtype)
    if cfg.attn_proj_bias:
        attn_out = attn_out + p["bo"].astype(h.dtype)
    h = h + attn_out
    if post:
        h = tfm._norm(h, p["ln1_scale"], p["ln1_bias"], cfg)

    mlp_in = h if post else tfm._norm(h, p["ln2_scale"],
                                      p["ln2_bias"], cfg)
    h = h + tfm._dense_mlp(mlp_in, p, cfg, None)
    if post:
        h = tfm._norm(h, p["ln2_scale"], p["ln2_bias"], cfg)
    return h, (kc, vc)


def _chunk_hidden(params, cfg, toks, kcache, vcache, pos):
    """toks (B, C) int32 occupying positions [pos, pos+C) -> (hidden
    (B, C, D) pre-head, new caches). The cache-building core; callers that
    need logits apply ``tfm.lm_head`` to as little of h as they actually
    read (at V~50k the head dominates, so prefill must not pay it for
    every prompt position)."""
    B, C = toks.shape
    D = cfg.d_model
    h = params["embed"][toks].astype(cfg.dtype)
    if cfg.use_pos_emb:
        pos_emb = jax.lax.dynamic_slice(params["pos"], (pos, 0), (C, D))
        h = h + pos_emb[None].astype(cfg.dtype)
    h, (kcache, vcache) = jax.lax.scan(
        functools.partial(_decode_layer, cfg=cfg, pos=pos), h,
        (params["blocks"], kcache, vcache))
    return h, kcache, vcache


def _chunk_logits(params, cfg, toks, kcache, vcache, pos):
    """toks (B, C) int32 occupying positions [pos, pos+C) -> (logits
    (B, C, V), new caches). C=1 is one decode step."""
    h, kcache, vcache = _chunk_hidden(params, cfg, toks, kcache, vcache,
                                      pos)
    return tfm.lm_head(params, h, cfg), kcache, vcache


def _one_token_logits(params, cfg, tok, kcache, vcache, pos):
    """tok (B,) int32 at position pos -> (logits (B, V), new caches)."""
    logits, kcache, vcache = _chunk_logits(params, cfg, tok[:, None],
                                           kcache, vcache, pos)
    return logits[:, 0], kcache, vcache


def _prefill_prefix(params, cfg, prompt, kcache, vcache, enabled,
                    prompt_lens, want_logits):
    """Shared rectangular-prompt prefill: when ``enabled`` and the batch is
    rectangular (``prompt_lens is None``), positions [0, P-1) run as ONE
    chunked forward. Returns (start, prefix_logits, kcache, vcache) —
    start is the loop's first step (P-1, or 0 when prefill did not apply);
    prefix_logits is the (B, P-1, V) head output when ``want_logits``
    (callers whose contract returns per-position logits), else None."""
    P = prompt.shape[1]
    if not (enabled and prompt_lens is None and P > 1):
        return 0, None, kcache, vcache
    h, kcache, vcache = _chunk_hidden(params, cfg, prompt[:, :P - 1],
                                      kcache, vcache, 0)
    prefix = tfm.lm_head(params, h, cfg) if want_logits else None
    return P - 1, prefix, kcache, vcache


def _check_decode_args(cfg: tfm.TransformerConfig, max_len: int,
                       top_k: int) -> None:
    assert cfg.n_experts == 0, "decode supports dense blocks (no MoE)"
    assert cfg.causal, "decode is autoregressive — causal configs only"
    assert max_len <= cfg.max_seq_len
    assert 0 <= top_k <= cfg.vocab_size, (
        f"top_k {top_k} out of range [0, vocab_size={cfg.vocab_size}]")


def _next_token(logits, rng, sample: bool, top_k: int, temperature):
    """Greedy argmax or (top-k) temperature sampling -> (B,) int32. The
    ONE implementation shared by the scan and while_loop decode paths."""
    if not sample:
        return jnp.argmax(logits, -1).astype(jnp.int32)
    scaled = logits / temperature
    if top_k > 0:
        kth = jax.lax.top_k(scaled, top_k)[0][..., -1:]
        scaled = jnp.where(scaled >= kth, scaled, -jnp.inf)
    return jax.random.categorical(rng, scaled, -1).astype(jnp.int32)


@functools.lru_cache(maxsize=32)
def make_generate_fn(cfg: tfm.TransformerConfig, max_len: int,
                     sample: bool = False, top_k: int = 0,
                     mesh=None, chunked_prefill: bool = True):
    """Returns a jitted ``(params, prompt (B, P) int32, rng_key,
    temperature=1.0, prompt_lens=None) -> (tokens (B, max_len),
    logits (B, max_len, V))`` where tokens[:, :P] echoes the prompt and the
    rest is generated. ``prompt_lens`` (B,) int32 (clamped to [1, P])
    decodes a RAGGED batch in one call: row b teacher-forces its first
    prompt_lens[b] tokens and generates from its own boundary — under
    GREEDY decoding, token-exact vs decoding each row alone with the SAME
    prefill mechanism (sampling draws from a batch-shaped rng stream, so
    batched != solo draws).
    ``sample=False``: greedy argmax (rng/temperature unused);
    ``sample=True``: temperature sampling — temperature is a DYNAMIC
    operand, so sweeping it never recompiles; each time step consumes
    ``fold_in(key, t)``, so the draw at step t does not depend on how the
    prefix was processed. ``top_k > 0`` restricts sampling to the k most
    likely tokens.

    ``chunked_prefill`` (rectangular prompts only — ragged rows have
    per-row boundaries): positions [0, P-1) run as ONE chunked forward
    instead of P-1 sequential single-token steps. The chunk computes the
    same math but XLA may tile/accumulate it differently, so greedy
    results can differ from the tokenwise path in exact-tie cases; pass
    ``chunked_prefill=False`` when bit-parity with the ragged/tokenwise
    path matters more than prefill speed.

    ``mesh``: distributed decode — params stay in their Megatron layout
    (``tfm.param_specs``: qkv/mlp column-parallel over ``tp``), the KV
    cache is sharded batch-over-``dp`` and heads-over-``tp``, and GSPMD
    inserts the same collectives as training. Decode never gathers the
    weights."""
    _check_decode_args(cfg, max_len, top_k)

    cache_sharding = None
    if mesh is not None:
        from jax.sharding import NamedSharding
        # the cache holds the nkv UNBROADCAST heads: shard them over tp
        # only when they divide evenly (GQA/MQA can have fewer kv heads
        # than tp shards — replicate the head axis then; batch stays
        # dp-sharded either way)
        tp = mesh.shape.get("tp", 1)
        head_axis = "tp" if cfg.kv_heads % tp == 0 else None
        cache_sharding = NamedSharding(
            mesh, jax.sharding.PartitionSpec(None, "dp", head_axis,
                                             None, None))

    def gen(params, prompt, key, temperature=1.0, prompt_lens=None):
        B, P = prompt.shape
        assert P <= max_len, f"prompt length {P} > max_len {max_len}"
        # ragged batches: per-row prompt lengths — row b teacher-forces its
        # first prompt_lens[b] tokens and starts generating at its OWN
        # boundary, overwriting the rectangle's padding before any read (the
        # write for position t happens at step t-1, the read at step t), so
        # no pad token ever reaches the model or the KV cache
        plens = (jnp.full((B,), P, jnp.int32) if prompt_lens is None
                 else jnp.clip(jnp.asarray(prompt_lens, jnp.int32), 1, P))
        L, nkv, hd = cfg.n_layers, cfg.kv_heads, cfg.head_dim
        kcache = jnp.zeros((L, B, nkv, max_len, hd), cfg.dtype,
                           device=cache_sharding)
        vcache = jnp.zeros_like(kcache)
        padded = jnp.zeros((B, max_len), jnp.int32)
        padded = jax.lax.dynamic_update_slice(padded, prompt, (0, 0))

        # the per-position logits stay part of the returned contract, so
        # the prefill head runs over the whole prefix as one matmul too
        start, prefix_logits, kcache, vcache = _prefill_prefix(
            params, cfg, prompt, kcache, vcache, chunked_prefill,
            prompt_lens, want_logits=True)

        def step(carry, t):
            tok_seq, kcache, vcache = carry
            tok = jax.lax.dynamic_index_in_dim(tok_seq, t, 1, keepdims=False)
            logits, kcache, vcache = _one_token_logits(
                params, cfg, tok, kcache, vcache, t)
            # fold_in(key, t), NOT a split chain: the draw at step t is a
            # function of (key, t) alone, so skipping prefill steps (or
            # passing prompt_lens for a rectangular batch) never shifts
            # the sampling stream
            nxt = _next_token(logits, jax.random.fold_in(key, t), sample,
                              top_k, temperature)
            # teacher-force while the NEXT position is still in the row's
            # prompt, and never write past the end (the final step's sample
            # has no slot — its logits are still returned)
            idx = jnp.minimum(t + 1, max_len - 1)
            cur_next = jax.lax.dynamic_index_in_dim(tok_seq, idx, 1,
                                                    keepdims=False)
            nxt = jnp.where((t + 1) < plens, cur_next, nxt)
            nxt = jnp.where((t + 1) < max_len, nxt, cur_next)
            tok_seq = jax.lax.dynamic_update_slice(
                tok_seq, nxt[:, None], (0, idx))
            return (tok_seq, kcache, vcache), logits

        (tok_seq, _, _), logits_seq = jax.lax.scan(
            step, (padded, kcache, vcache),
            jnp.arange(start, max_len))
        logits = jnp.swapaxes(logits_seq, 0, 1)         # (B, M-start, V)
        if prefix_logits is not None:
            logits = jnp.concatenate([prefix_logits, logits], axis=1)
        return tok_seq, logits                          # (B, M, V)

    return jax.jit(gen, static_argnames=())


def generate(params, cfg: tfm.TransformerConfig, prompt, max_len: int,
             temperature: float = 0.0, rng: Optional[jax.Array] = None):
    """Convenience one-shot wrapper: ``temperature == 0`` -> greedy."""
    fn = make_generate_fn(cfg, max_len, sample=temperature > 0.0)
    if rng is None:
        rng = jax.random.PRNGKey(0)
    toks, _ = fn(params, jnp.asarray(prompt, jnp.int32), rng,
                 max(temperature, 1e-6))
    return np.asarray(toks)


@functools.lru_cache(maxsize=32)
def make_eos_generate_fn(cfg: tfm.TransformerConfig, max_len: int,
                         eos_id: int, sample: bool = False,
                         top_k: int = 0, chunked_prefill: bool = True):
    """EOS-aware decode: a ``lax.while_loop`` that EXITS EARLY once every
    row has emitted ``eos_id`` — data-dependent control flow the
    compiler-friendly way (the fixed-length scan path pays for max_len
    steps regardless; this pays only for the longest row). Finished rows
    keep emitting eos. Returns (tokens (B, max_len) — tail filled with
    eos — and t, the POSITION the loop stopped at: the number of sequence
    positions processed, counting chunk-prefilled prompt positions; loop
    ITERATIONS executed are t - (P-1) for a chunk-prefilled rectangular
    prompt). ``chunked_prefill`` as in ``make_generate_fn`` (False = the
    tokenwise path, bit-parity with ragged decodes)."""
    _check_decode_args(cfg, max_len, top_k)
    assert 0 <= eos_id < cfg.vocab_size, (
        f"eos_id {eos_id} outside vocab [0, {cfg.vocab_size}) — the model "
        "could never emit it and the loop would never exit early")

    def gen(params, prompt, key, temperature=1.0, prompt_lens=None):
        B, P = prompt.shape
        assert P <= max_len
        plens = (jnp.full((B,), P, jnp.int32) if prompt_lens is None
                 else jnp.clip(jnp.asarray(prompt_lens, jnp.int32), 1, P))
        L, nkv, hd = cfg.n_layers, cfg.kv_heads, cfg.head_dim
        kcache = jnp.zeros((L, B, nkv, max_len, hd), cfg.dtype)
        vcache = jnp.zeros_like(kcache)
        padded = jnp.full((B, max_len), eos_id, jnp.int32)
        padded = jax.lax.dynamic_update_slice(padded, prompt, (0, 0))
        # ragged batches: the rectangle's pad beyond a row's OWN length must
        # not survive an early exit — the documented contract is an
        # eos-filled tail (generation overwrites from plens[b] as it runs)
        pos = jnp.arange(max_len)[None, :]
        padded = jnp.where(pos < plens[:, None], padded, eos_id)
        finished = jnp.zeros((B,), bool)

        # rectangular prompts: chunk-prefill [0, P-1) exactly as the scan
        # path does (the while body then only ever runs decode-shaped
        # iterations — for the common serving case of a long prompt with
        # early exit this removes P-1 sequential single-token steps)
        start, _, kcache, vcache = _prefill_prefix(
            params, cfg, prompt, kcache, vcache, chunked_prefill,
            prompt_lens, want_logits=False)
        t0 = jnp.int32(start)

        def cond(state):
            t, _, _, _, finished = state
            # finished can only be set past the prompt, so this single
            # clause also keeps the teacher-forced prefix running
            return jnp.logical_and(t < max_len - 1,
                                   jnp.logical_not(jnp.all(finished)))

        def body(state):
            t, tok_seq, kcache, vcache, finished = state
            tok = jax.lax.dynamic_index_in_dim(tok_seq, t, 1, keepdims=False)
            logits, kcache, vcache = _one_token_logits(
                params, cfg, tok, kcache, vcache, t)
            # fold_in(key, t): draws depend on (key, t) alone — see
            # make_generate_fn
            nxt = _next_token(logits, jax.random.fold_in(key, t), sample,
                              top_k, temperature)
            in_prompt = (t + 1) < plens    # per-row (ragged batches)
            cur_next = jax.lax.dynamic_index_in_dim(tok_seq, t + 1, 1,
                                                    keepdims=False)
            nxt = jnp.where(in_prompt, cur_next, nxt)
            nxt = jnp.where(finished, eos_id, nxt)   # finished rows: eos
            finished = jnp.logical_or(
                finished,
                jnp.logical_and(jnp.logical_not(in_prompt), nxt == eos_id))
            tok_seq = jax.lax.dynamic_update_slice(tok_seq, nxt[:, None],
                                                   (0, t + 1))
            return (t + 1, tok_seq, kcache, vcache, finished)

        t, tok_seq, _, _, _ = jax.lax.while_loop(
            cond, body, (t0, padded, kcache, vcache, finished))
        return tok_seq, t

    return jax.jit(gen)


@functools.lru_cache(maxsize=32)
def make_beam_search_fn(cfg: tfm.TransformerConfig, max_len: int,
                        beam_size: int):
    """Returns jitted ``(params, prompt (B, P) int32) ->
    (tokens (B, K, max_len), scores (B, K))``, beams sorted best-first by
    total log-probability of the generated suffix. Same one-scan KV-cache
    machinery as sampling; beam reordering gathers the cache along the
    flattened (B*K) batch dim each step.

    Prompts are RECTANGULAR (every row length P): beam expansion starts at
    one shared boundary. For ragged batches use the greedy/sampling paths
    (``prompt_lens``) or call beam per row group of equal lengths."""
    _check_decode_args(cfg, max_len, 0)
    assert beam_size >= 1
    K = beam_size

    def beam(params, prompt):
        B, P = prompt.shape
        assert 1 <= P < max_len, "beam search must generate >= 1 token"
        L, nkv, hd = cfg.n_layers, cfg.kv_heads, cfg.head_dim
        BK = B * K
        V = cfg.vocab_size

        # -- prefill at batch B (NOT B*K: the K copies would be identical):
        # one MXU-shaped chunked forward over the whole prompt instead of
        # P sequential single-token steps; the head runs on the LAST
        # position only (full-prompt logits would be a (B, P, V) dead
        # buffer) --
        kc = jnp.zeros((L, B, nkv, max_len, hd), cfg.dtype)
        vc = jnp.zeros_like(kc)
        h, kc, vc = _chunk_hidden(params, cfg, prompt, kc, vc, 0)
        last_logits = tfm.lm_head(params, h[:, P - 1:P], cfg)[:, 0]

        # first expansion: top-min(K, V) continuations of the prompt seed
        # the beams; with K > V the surplus beams start dead (-inf) and get
        # claimed by real candidates at the next expansion (this is what
        # makes K >= V^n exhaustive)
        logp0 = jax.nn.log_softmax(last_logits.astype(jnp.float32), -1)
        k0 = min(K, V)
        scores, first_tok = jax.lax.top_k(logp0, k0)           # (B, k0)
        if k0 < K:
            scores = jnp.concatenate(
                [scores, jnp.full((B, K - k0), -1e30, jnp.float32)], axis=1)
            first_tok = jnp.concatenate(
                [first_tok, jnp.zeros((B, K - k0), first_tok.dtype)], axis=1)
        toks = jnp.zeros((B, K, max_len), jnp.int32)
        toks = jax.lax.dynamic_update_slice(
            toks, jnp.repeat(prompt[:, None, :], K, 1), (0, 0, 0))
        toks = jax.lax.dynamic_update_slice(
            toks, first_tok[:, :, None].astype(jnp.int32), (0, 0, P))
        # tile the prefilled cache to B*K once
        kcache = jnp.repeat(kc, K, axis=1)
        vcache = jnp.repeat(vc, K, axis=1)

        # -- decode: feed position t, expand into position t+1 -------------
        def step(carry, t):
            toks, scores, kcache, vcache = carry
            tok = jax.lax.dynamic_index_in_dim(
                toks.reshape(BK, max_len), t, 1, keepdims=False)
            logits, kcache, vcache = _one_token_logits(
                params, cfg, tok, kcache, vcache, t)
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
            cand = scores[:, :, None] + logp.reshape(B, K, V)
            top_scores, top_idx = jax.lax.top_k(cand.reshape(B, K * V), K)
            src_beam = top_idx // V                            # (B, K)
            new_tok = (top_idx % V).astype(jnp.int32)
            # reorder beams (and their caches) by ancestry
            toks = jnp.take_along_axis(toks, src_beam[..., None], axis=1)
            gather = (jnp.arange(B)[:, None] * K + src_beam).reshape(BK)
            kcache = jnp.take(kcache, gather, axis=1)
            vcache = jnp.take(vcache, gather, axis=1)
            toks = jax.lax.dynamic_update_slice(
                toks, new_tok[:, :, None], (0, 0, t + 1))
            return (toks, top_scores, kcache, vcache), None

        (toks, scores, _, _), _ = jax.lax.scan(
            step, (toks, scores, kcache, vcache),
            jnp.arange(P, max_len - 1))
        # already best-first: every top_k (first expansion and each decode
        # step) returns descending scores
        return toks, scores

    return jax.jit(beam)


# ---------------------------------------------------------------------------
# speculative decoding (beyond reference, and beyond the plain decode above)
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=16)
def make_speculative_generate_fn(cfg: tfm.TransformerConfig,
                                 draft_cfg: tfm.TransformerConfig,
                                 max_len: int, k: int = 4):
    """Greedy speculative decoding: a cheap DRAFT model proposes ``k``
    tokens per round, the TARGET verifies them in ONE chunked forward
    (``_decode_layer`` with C=k+1 — an MXU-shaped matmul instead of k+1
    bandwidth-bound single-token steps), and the longest agreeing prefix
    is accepted plus the target's own next token. The greedy case of
    arXiv:2211.17192: output is TOKEN-EXACT equal to plain greedy decoding
    with the target (pinned hard on the CPU backend; on TPU the C=k+1
    verify chunk may tile/accumulate differently from the C=1 decode
    step, so an EXACT logit tie can argmax differently — the same caveat
    as ``chunked_prefill``), only faster — each round advances between 1
    and k+1 tokens at one target forward.

    Returns jitted ``(params, draft_params, prompt (1, P) int32) ->
    (tokens (1, max_len), rounds)`` — rounds is the number of verify
    forwards after prefill, so the mean acceptance per round is
    ``(max_len - P - 1) / rounds``. Batch is fixed at 1 (speculation is a
    latency optimization; rows would accept different lengths).

    Both configs must be causal, dense, same vocab; position tables must
    cover ``max_len + k`` (the last round may write a partial chunk past
    the returned window; the tail is sliced off).
    """
    _check_decode_args(cfg, max_len, 0)
    _check_decode_args(draft_cfg, max_len, 0)
    assert cfg.vocab_size == draft_cfg.vocab_size, "vocabularies differ"
    assert k >= 1
    assert max_len + k <= cfg.max_seq_len, (
        f"need max_len + k <= target max_seq_len ({max_len}+{k} > "
        f"{cfg.max_seq_len})")
    assert max_len + k <= draft_cfg.max_seq_len

    M = max_len + k          # cache/buffer room for the last partial chunk

    def gen(params, draft_params, prompt):
        B, P = prompt.shape
        assert B == 1, "speculative decode is B=1 (latency-oriented)"
        assert 1 <= P < max_len

        def cache(c):
            L, nkv, hd = c.n_layers, c.kv_heads, c.head_dim
            return (jnp.zeros((L, B, nkv, M, hd), c.dtype),
                    jnp.zeros((L, B, nkv, M, hd), c.dtype))

        kc_t, vc_t = cache(cfg)
        kc_d, vc_d = cache(draft_cfg)
        toks = jnp.zeros((B, M + 1), jnp.int32)
        toks = jax.lax.dynamic_update_slice(toks, prompt, (0, 0))

        # -- chunked prefill: ONE forward each over the whole prompt; the
        # head runs on the LAST position only (target) or not at all
        # (draft — its prefill exists purely to build the cache) --
        t_h, kc_t, vc_t = _chunk_hidden(params, cfg, prompt, kc_t, vc_t, 0)
        t_last = tfm.lm_head(params, t_h[:, P - 1:P], cfg)[:, 0]
        first = jnp.argmax(t_last, -1).astype(jnp.int32)
        _, kc_d, vc_d = _chunk_hidden(draft_params, draft_cfg, prompt,
                                      kc_d, vc_d, 0)
        toks = jax.lax.dynamic_update_slice(toks, first[:, None], (0, P))
        n0 = jnp.int32(P + 1)
        # invariant at each round start: toks[:, :n] is the sequence, both
        # caches hold positions [0, n-1), and toks[:, n-1] has not been
        # fed to either model yet

        def cond(c):
            return c[1] < max_len

        def body(c):
            toks, n, kc_t, vc_t, kc_d, vc_d, rounds = c

            # draft proposes k tokens, one bandwidth-cheap step each.
            # k+1 steps, not k: the extra step writes the LAST proposal's
            # k/v cache entry (input d_{k-1} at position n+k-1), which the
            # next round needs whenever all k proposals are accepted (the
            # bonus token advances past it) — without it the draft attends
            # a zero entry and its acceptance rate silently degrades (the
            # output stays exact either way; the target always corrects).
            # The extra proposal itself is discarded.
            def dstep(carry, _):
                cur, pos, kc_d, vc_d = carry
                logits, kc_d, vc_d = _one_token_logits(
                    draft_params, draft_cfg, cur, kc_d, vc_d, pos)
                nxt = jnp.argmax(logits, -1).astype(jnp.int32)
                return (nxt, pos + 1, kc_d, vc_d), nxt

            last = jax.lax.dynamic_index_in_dim(toks, n - 1, 1,
                                                keepdims=False)
            (_, _, kc_d, vc_d), drafts = jax.lax.scan(
                dstep, (last, n - 1, kc_d, vc_d), None, length=k + 1)
            drafts = drafts[:k, 0]                             # (k,)

            # target verifies the whole chunk in one forward:
            # [last, d_0..d_{k-1}] at positions [n-1, n+k)
            chunk = jnp.concatenate([last[:, None], drafts[None]], 1)
            v_logits, kc_t, vc_t = _chunk_logits(params, cfg, chunk,
                                                 kc_t, vc_t, n - 1)
            targets = jnp.argmax(v_logits[0], -1).astype(jnp.int32)  # (k+1,)

            # longest agreeing prefix; emit the target's tokens (equal to
            # the draft's on the accepted prefix, its own correction after)
            agree = jnp.cumprod(
                (drafts == targets[:k]).astype(jnp.int32))
            a = jnp.sum(agree)                                 # in [0, k]
            toks = jax.lax.dynamic_update_slice(toks, targets[None], (0, n))
            return (toks, n + a + 1, kc_t, vc_t, kc_d, vc_d, rounds + 1)

        toks, n, *_, rounds = jax.lax.while_loop(
            cond, body, (toks, n0, kc_t, vc_t, kc_d, vc_d, jnp.int32(0)))
        return toks[:, :max_len], rounds

    return jax.jit(gen)
