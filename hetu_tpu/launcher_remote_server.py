"""Remote PS-server entry point for multi-machine launches.

``hetu_tpu.runner`` starts remote servers over ssh as
``SERVER_ID=<i> DMLC_ROLE=server python -m hetu_tpu.launcher_remote_server``
(reference: runner.py spawns remote ps-lite servers via paramiko,
python/runner.py:36-60). All topology comes from the DMLC_* env exported on
the ssh command line; this module just blocks serving until killed.
"""
from hetu_tpu.launcher import start_server

if __name__ == "__main__":
    import os
    start_server(server_id=int(os.environ.get("SERVER_ID", "0")))
