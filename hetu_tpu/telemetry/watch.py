"""hetuwatch — runtime plan-divergence sentinel, live residual streaming,
and SLO watch (observability pillar 6, docs/OBSERVABILITY.md).

hetuplan chooses a layout once at build time; hetutrail measures who
blocked whom but never compares the measurement against what the planner
PROMISED. This module is the runtime judge between them:

- **Prediction stamping.** When a :class:`~hetu_tpu.analysis.planner.Plan`
  is adopted, the executor writes one ``kind:"plan"`` JSONL record
  (:func:`stamp_fields`): the per-leg predicted step decomposition in
  hetutrail's leg space (:func:`predicted_legs`), the per-param decisions
  with their rationale, and the cost-model inputs (calibration source +
  breakdown) — so every later step can be judged against the promise.
- **Live residual stream.** :class:`PlanWatch` joins each step's measured
  critical-path legs (``trail.step_legs``) against the stamped prediction,
  maintaining an EWMA and a windowed mean of the measured/predicted ratio
  per leg (and per op-family, mapped onto the leg each family executes
  in — the ``profiler.roofline_rows`` cp assignment). The executor exports
  ``hetu_plan_residual{leg=…}`` / ``hetu_plan_divergence`` gauges
  (:func:`export_watch`) and ``kind:"watch"`` JSONL rows that
  ``hetulint --plan --calibrate TELEMETRY_DIR`` consumes directly
  (cost_model.load_calibration) — calibration no longer needs a dedicated
  offline run.
- **Divergence detection + SLO watch.** A K-consecutive detector with
  latched hysteresis (:class:`_Latch` — fire once, stay silent while the
  condition persists, re-arm only after K consecutive recoveries below a
  LOWER threshold) turns sustained residuals into one ``plan_divergence``
  event through the resilience event bus, naming the diverging leg and —
  via hetutrail's span join — the blocking server and param, plus the
  bounded plan delta hetuplan would now choose (:func:`recommend`;
  advisory only, rendered as the same suppressible finding shape hetulint
  emits). Declarative SLOs (``HETU_SLO_SPEC``, e.g.
  ``step_ms<25,ps_pull_frac<0.3``) ride the same latch; breaches emit
  ``slo_breach`` events and flush the hetuscope flight ring.

Activation mirrors hetuscope: ``HETU_WATCH`` (or ``HetuConfig(watch=…)``)
resolves to a step cadence via :func:`resolve_watch`, 0 = off. Off — the
default — the executor holds ``plan_watch = None`` and every step pays
exactly one attribute check, nothing else (asserted in tests). Everything
here is stdlib-only so ``bin/hetuwatch`` runs jax-free on a login node or
in CI.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import tempfile
from collections import deque
from typing import Optional

DEFAULT_CADENCE = 10          # steps between residual observations
DEFAULT_WINDOW = 8            # residual-window depth (observations)
DEFAULT_K = 3                 # consecutive windows to fire / recover
DEFAULT_RATIO = 1.5           # measured/predicted breach threshold
DEFAULT_MIN_MS = 1.0          # absolute excess floor (noise guard)
DEFAULT_ALPHA = 0.25          # EWMA smoothing
# a leg the plan prices at ~0 ms still gets a denominator: measured time
# on a "free" leg is exactly the divergence worth flagging, but µs jitter
# must not explode the ratio
PRED_FLOOR_MS = 0.25

_OFFISH = ("", "0", "off", "false", "no", "none")
_ONISH = ("1", "on", "true", "yes")

# mirrors trail.LEGS (self_check pins them equal — one definition of the
# blocking chain, re-stated here so the hot helpers never need the import)
LEGS = ("feed", "ps_pull", "compute", "ps_push", "poststep")

# event names this module owns on the resilience bus
WATCH_EVENTS = ("plan_divergence", "plan_divergence_recovered",
                "slo_breach", "slo_recovered", "watch_abstain")

_TRAIL = None


def _trail():
    """The hetutrail module, loadable BOTH ways this file is: as the
    package module and by file path (bin/hetuwatch — trail.py is
    stdlib-only, so file-path loading it is always safe)."""
    global _TRAIL
    if _TRAIL is None:
        try:
            from . import trail as mod          # package context
        except ImportError:
            import importlib.util
            path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "trail.py")
            spec = importlib.util.spec_from_file_location("_hetuwatch_trail",
                                                          path)
            mod = importlib.util.module_from_spec(spec)
            sys.modules["_hetuwatch_trail"] = mod
            spec.loader.exec_module(mod)
        _TRAIL = mod
    return _TRAIL


# ---------------------------------------------------------------------------
# arming + SLO grammar
# ---------------------------------------------------------------------------

def resolve_watch(value=None) -> int:
    """One spelling of the arming resolution (the ``resolve_introspect``
    contract): returns the observation cadence in steps, 0 = off.
    ``True``/``"on"``/``"1"`` arm at :data:`DEFAULT_CADENCE` (overridable
    via ``HETU_WATCH_EVERY``); an integer >= 1 is an explicit cadence;
    ``None`` falls back to the ``HETU_WATCH`` env var."""
    if value is None:
        value = os.environ.get("HETU_WATCH", "")
    if isinstance(value, bool):
        value = "on" if value else "off"
    if isinstance(value, (int, float)):
        n = int(value)
        if n < 0:
            raise ValueError(f"watch cadence must be >= 0, got {n}")
        return n
    value = str(value).strip().lower()
    if value in _OFFISH:
        return 0
    if value in _ONISH:
        return max(1, int(os.environ.get("HETU_WATCH_EVERY",
                                         str(DEFAULT_CADENCE))))
    n = int(value)
    if n < 0:
        raise ValueError(f"watch cadence must be >= 0, got {n}")
    return max(1, n)


_SLO_METRICS = ("step_ms",) + tuple(f"{leg}_ms" for leg in LEGS) \
    + tuple(f"{leg}_frac" for leg in LEGS)
_SLO_OPS = ("<=", ">=", "<", ">")   # two-char ops first for the scan


def parse_slo_spec(spec: str) -> list:
    """``HETU_SLO_SPEC`` grammar: comma-separated ``METRIC OP LIMIT``
    budgets, e.g. ``step_ms<25,ps_pull_frac<0.3``. Metrics: ``step_ms``,
    ``<leg>_ms``, ``<leg>_frac`` (leg share of the blocking chain). A
    malformed spec raises — a silently ignored SLO is worse than none."""
    rules = []
    for ent in str(spec or "").split(","):
        ent = ent.strip()
        if not ent:
            continue
        op = next((o for o in _SLO_OPS if o in ent), None)
        if op is None:
            raise ValueError(f"SLO entry {ent!r}: no comparison operator "
                             f"(use one of {', '.join(_SLO_OPS)})")
        metric, _, limit = ent.partition(op)
        metric = metric.strip()
        if metric not in _SLO_METRICS:
            raise ValueError(f"SLO entry {ent!r}: unknown metric "
                             f"{metric!r} (know {', '.join(_SLO_METRICS)})")
        try:
            lim = float(limit)
        except ValueError:
            raise ValueError(f"SLO entry {ent!r}: limit {limit!r} is not "
                             "a number") from None
        rules.append({"spec": ent, "metric": metric, "op": op, "limit": lim})
    return rules


def _violates(value: float, rule: dict) -> bool:
    op, lim = rule["op"], rule["limit"]
    if op == "<":
        return not value < lim
    if op == "<=":
        return not value <= lim
    if op == ">":
        return not value > lim
    return not value >= lim


# ---------------------------------------------------------------------------
# prediction stamping
# ---------------------------------------------------------------------------

def predicted_legs(breakdown: dict, pull_frac: float = 0.5,
                   feed_frac: float = 0.5) -> dict:
    """The planner's step breakdown mapped into hetutrail's leg space.

    ``allreduce_ms`` folds into ``compute`` (in-program collectives run
    inside the dispatched XLA program — the same convention as
    ``trail.step_legs``); ``ps_ms`` covers both boundary waits and splits
    pull/push evenly absent a finer model; ``host_ms`` splits across
    feed/poststep. The splits are priors the residual stream corrects —
    what matters is that every measured leg has a judged denominator."""
    b = {k: float(v or 0.0) for k, v in (breakdown or {}).items()}
    ps = b.get("ps_ms", 0.0)
    host = b.get("host_ms", 0.0)
    return {
        "feed": host * feed_frac,
        "ps_pull": ps * pull_frac,
        "compute": b.get("compute_ms", 0.0) + b.get("allreduce_ms", 0.0),
        "ps_push": ps * (1.0 - pull_frac),
        "poststep": host * (1.0 - feed_frac),
    }


def stamp_fields(plan: dict, world_version: int = 0) -> dict:
    """Fields of the ``kind:"plan"`` JSONL record from ``Plan.as_dict()``
    output: the adopted layout, per-leg prediction, per-decision rationale
    and the cost-model inputs. ``candidates`` are deliberately excluded
    (bulky; ``hetulint --plan --json`` renders them offline)."""
    breakdown = plan.get("breakdown") or {}
    return {
        "mesh": plan.get("mesh"),
        "comm_mode": plan.get("comm_mode"),
        "comm_quant": plan.get("comm_quant"),
        "zero1": plan.get("zero1"),
        "remat": plan.get("remat"),
        "predicted_step_ms": plan.get("predicted_step_ms"),
        "breakdown": breakdown,
        "predicted_legs": {k: round(v, 4)
                           for k, v in predicted_legs(breakdown).items()},
        "params": (plan.get("params") or [])[:64],
        "calibration": plan.get("calibration"),
        "world_version": int(world_version),
    }


# ---------------------------------------------------------------------------
# detection: K-consecutive + latched hysteresis
# ---------------------------------------------------------------------------

class _Latch:
    """K-consecutive breach → ONE "fired" signal, then latched: silence
    while the condition persists (a flapping signal can never oscillate
    the detector — the PR 13 StragglerDetector re-fires every K, which is
    right for a ScalePolicy but wrong for an advisory event a human
    reads). K consecutive "clean" observations while latched → one
    "recovered" signal and re-arm. "dead"-zone observations (between the
    breach and re-arm thresholds) reset BOTH streaks without firing."""

    def __init__(self, k: int = DEFAULT_K):
        self.k = max(1, int(k))
        self.latched = False
        self._breach = 0
        self._clean = 0

    def observe(self, state: str) -> Optional[str]:
        if state == "breach":
            self._clean = 0
            if self.latched:
                return None
            self._breach += 1
            if self._breach >= self.k:
                self._breach = 0
                self.latched = True
                return "fired"
            return None
        self._breach = 0
        if state == "clean" and self.latched:
            self._clean += 1
            if self._clean >= self.k:
                self._clean = 0
                self.latched = False
                return "recovered"
        elif state != "clean":
            self._clean = 0
        return None

    def reset(self) -> None:
        self.latched = False
        self._breach = self._clean = 0


class PlanWatch:
    """The runtime judge: per-leg residual stream + divergence/SLO latch.

    ``predicted`` is the stamped per-leg prediction (``None`` for an
    SLO-only watch — no plan, nothing to diverge from); ``families`` maps
    op-family names to the leg each executes in (``profiler.roofline_rows``
    identities), populated lazily by the executor. ``observe`` is the ONLY
    hot entry point and does dict arithmetic over five legs — no I/O, no
    imports; the caller owns gauge export and JSONL emission.

    Elastic abstain: an observation carrying a new ``world_version``
    resets every window and streak and contributes nothing — stale-era
    legs are never compared against the new world's prediction, and the
    straddling step is dropped entirely."""

    def __init__(self, predicted: Optional[dict] = None,
                 predicted_step_ms: Optional[float] = None,
                 every: int = DEFAULT_CADENCE, window: int = DEFAULT_WINDOW,
                 k: int = DEFAULT_K, ratio: float = DEFAULT_RATIO,
                 min_ms: float = DEFAULT_MIN_MS, alpha: float = DEFAULT_ALPHA,
                 slo=None, world_version: int = 0,
                 families: Optional[dict] = None, plan: Optional[dict] = None):
        self.predicted = {leg: float(v) for leg, v in
                          (predicted or {}).items() if v is not None}
        self.predicted_step_ms = (float(predicted_step_ms)
                                  if predicted_step_ms else None)
        self.every = max(1, int(every))
        self.window = max(1, int(window))
        self.k = max(1, int(k))
        self.ratio = float(ratio)
        # re-arm threshold sits BELOW the breach threshold: recovery must
        # clear a margin, so a signal hovering at the line stays latched
        self.rearm = 1.0 + (self.ratio - 1.0) * 0.5
        self.min_ms = float(min_ms)
        self.alpha = float(alpha)
        self.plan = plan or {}
        self.families = families          # {family: leg} | None
        self.slo = (parse_slo_spec(slo) if isinstance(slo, str)
                    else list(slo or []))
        self.world_version = int(world_version)
        self._win = {leg: deque(maxlen=self.window) for leg in LEGS}
        self._ewma: dict = {}
        self._det = _Latch(self.k)
        self._slo_latch = [_Latch(self.k) for _ in self.slo]
        self.observations = 0
        self.abstains = 0

    def reset(self) -> None:
        for d in self._win.values():
            d.clear()
        self._ewma.clear()
        self._det.reset()
        for latch in self._slo_latch:
            latch.reset()

    # ------------------------------------------------------------------
    def observe(self, step: int, phases: Optional[dict] = None,
                step_ms: Optional[float] = None,
                world_version: Optional[int] = None,
                legs: Optional[dict] = None):
        """One cadence observation. Returns ``(row, events)``: ``row`` is
        the ``kind:"watch"`` JSONL payload (or an abstain marker), and
        ``events`` the resilience-bus events that latched this step."""
        events: list = []
        if world_version is not None \
                and int(world_version) != self.world_version:
            old = self.world_version
            self.world_version = int(world_version)
            self.reset()
            self.abstains += 1
            events.append({"name": "watch_abstain", "step": int(step),
                           "from_world": old,
                           "world_version": self.world_version})
            return ({"step": int(step), "abstain": "world_version",
                     "world_version": self.world_version}, events)
        if legs is None:
            legs = _trail().step_legs(phases or {})
        if step_ms is None:
            step_ms = sum(legs.values())
        self.observations += 1

        resid: dict = {}
        win_ratio: dict = {}
        win_excess: dict = {}
        for leg in LEGS:
            pred = self.predicted.get(leg)
            if pred is None:
                continue
            m = float(legs.get(leg, 0.0))
            r = m / max(pred, PRED_FLOOR_MS)
            resid[leg] = r
            prev = self._ewma.get(leg)
            self._ewma[leg] = (r if prev is None
                               else self.alpha * r
                               + (1.0 - self.alpha) * prev)
            d = self._win[leg]
            d.append((r, m - pred))
            win_ratio[leg] = sum(x for x, _ in d) / len(d)
            win_excess[leg] = sum(x for _, x in d) / len(d)

        worst = max(win_ratio, key=win_ratio.get) if win_ratio else None
        divergence = (max(self._ewma.values()) if self._ewma else None)
        if worst is not None:
            wr = win_ratio[worst]
            state = ("breach" if (wr > self.ratio
                                  and win_excess[worst] >= self.min_ms)
                     else "clean" if wr <= self.rearm else "dead")
            sig = self._det.observe(state)
            if sig == "fired":
                pred = self.predicted[worst]
                events.append({
                    "name": "plan_divergence", "leg": worst,
                    "ratio": round(wr, 3),
                    "ewma": round(self._ewma[worst], 3),
                    "predicted_ms": round(pred, 3),
                    "measured_ms": round(win_excess[worst] + pred, 3),
                    "windows": self.k, "step": int(step),
                    "world_version": self.world_version})
            elif sig == "recovered":
                events.append({"name": "plan_divergence_recovered",
                               "leg": worst, "ratio": round(wr, 3),
                               "step": int(step),
                               "world_version": self.world_version})

        total = sum(legs.values())
        slo_vals = {"step_ms": float(step_ms)}
        for leg in LEGS:
            m = float(legs.get(leg, 0.0))
            slo_vals[f"{leg}_ms"] = m
            slo_vals[f"{leg}_frac"] = (m / total) if total > 0 else 0.0
        for rule, latch in zip(self.slo, self._slo_latch):
            val = slo_vals.get(rule["metric"])
            breach = val is not None and _violates(val, rule)
            sig = latch.observe("breach" if breach else "clean")
            if sig == "fired":
                events.append({"name": "slo_breach", "slo": rule["spec"],
                               "value": round(val, 3), "step": int(step),
                               "world_version": self.world_version})
            elif sig == "recovered":
                events.append({"name": "slo_recovered", "slo": rule["spec"],
                               "value": round(val, 3), "step": int(step),
                               "world_version": self.world_version})

        row = {"step": int(step), "step_ms": round(float(step_ms), 4),
               "legs": {k: round(v, 4) for k, v in legs.items()},
               "world_version": self.world_version}
        if resid:
            row["residual"] = {k: round(v, 4) for k, v in resid.items()}
            row["ewma"] = {k: round(v, 4) for k, v in self._ewma.items()}
            row["divergence"] = round(divergence, 4)
            row["worst_leg"] = worst
        if self.predicted_step_ms:
            row["step_residual"] = round(
                float(step_ms) / self.predicted_step_ms, 4)
        if self.families and self._ewma:
            row["families"] = {
                fam: round(self._ewma[leg], 4)
                for fam, leg in self.families.items() if leg in self._ewma}
        return row, events


# ---------------------------------------------------------------------------
# bounded plan-delta recommendation — the PlanDelta registry
# ---------------------------------------------------------------------------

# The ONE registry of bounded plan deltas the watch may recommend and the
# pilot (hetu_tpu/pilot.py) may actuate. Exactly the fault-kind-registry
# discipline (faults.STEP_FAULT_KINDS): both producers and consumers
# reference this dict, hetucheck's surface lint drift-checks it against
# the docs catalogue and the pilot's consumer surface, and make_delta()
# rejects an unknown kind naming this catalogue instead of silently
# passing it through. "reversible" is load-bearing for the pilot: an
# irreversible kind (the scheduler rejects server scale-down) is
# blacklist-on-regression only — there is no revert era.
DELTA_KINDS = {
    # arm/disarm wire quantization on the live PS path (HETU_COMM_QUANT)
    "comm_quant":     {"arg": "mode",  "reversible": True,  "scope": "wire"},
    # flip ONE dense param's comm decision PS<->AllReduce (arg = new mode)
    "comm_mode_flip": {"arg": "mode",  "reversible": True,  "scope": "param"},
    # grow the PS server tier by one (the SIGUSR2/ScalePolicy path)
    "ps_server_grow": {"arg": "count", "reversible": False, "scope": "cluster"},
    # re-adopt a different device mesh via Executor.remesh
    "remesh":         {"arg": "mesh",  "reversible": True,  "scope": "program"},
}


def make_delta(kind: str, target=None, arg=None,
               expected_gain: float = 0.0, confidence: float = 0.0) -> dict:
    """Build one machine-readable ``PlanDelta``: ``kind`` (registry key),
    ``target`` (param name / server index / None), ``arg`` (the new value,
    typed per the registry's ``arg`` field), ``expected_gain`` (fraction
    of the diverging leg the delta should recover) and ``confidence``.
    Unknown kinds raise naming the catalogue — the fault-parser
    convention."""
    if kind not in DELTA_KINDS:
        raise ValueError(
            f"unknown plan-delta kind {kind!r}; known: "
            + ", ".join(sorted(DELTA_KINDS)))
    return {"kind": kind, "target": target, "arg": arg,
            "expected_gain": round(float(expected_gain), 4),
            "confidence": round(float(confidence), 4)}


def recommend(plan: dict, leg: str, ratio: float) -> dict:
    """The bounded delta hetuplan would now choose for a diverging leg —
    comm-mode flip, comm_quant toggle, or PS server count; never a full
    re-plan. Returned in the hetulint finding shape (suppressible id
    ``watch-divergence``, warn severity) so every renderer treats it like
    any other finding, plus a machine-readable ``delta`` (``make_delta``
    schema; ``None`` for host legs, which no bounded delta reaches) the
    pilot actuates."""
    params = plan.get("params") or []
    ps_params = [p for p in params if p.get("mode") == "PS"]
    dense_ps = [p for p in ps_params if not p.get("sparse")]
    # expected gain: the fraction of the diverging leg above its
    # prediction — what a perfect delta would claw back
    gain = max(0.0, 1.0 - 1.0 / ratio) if ratio > 1.0 else 0.0
    delta = None
    if leg in ("ps_pull", "ps_push"):
        if ps_params and (plan.get("comm_quant") or "off") == "off":
            msg = (f"PS {leg} leg at {ratio:.2f}x its prediction — bounded "
                   "delta: arm comm_quant=int8 (HETU_COMM_QUANT=int8); the "
                   "planner's wire algebra cuts PS bytes ~4x before any "
                   "re-layout")
            delta = make_delta("comm_quant", arg="int8",
                               expected_gain=min(gain, 0.75),
                               confidence=0.8)
        elif dense_ps:
            names = ", ".join(p.get("param", "?") for p in dense_ps[:3])
            msg = (f"PS {leg} leg at {ratio:.2f}x its prediction with "
                   f"dense PS param(s) ({names}) — bounded delta: flip the "
                   "dense decisions PS->AllReduce (in-program collective "
                   "beats a slow boundary RPC)")
            delta = make_delta("comm_mode_flip",
                               target=dense_ps[0].get("param"),
                               arg="AllReduce", expected_gain=gain,
                               confidence=0.7)
        else:
            msg = (f"PS {leg} leg at {ratio:.2f}x its prediction — bounded "
                   "delta: raise the PS server count (heturun SIGUSR2 grows "
                   "one live; re-shards hot tables across more appliers)")
            delta = make_delta("ps_server_grow", arg="+1",
                               expected_gain=gain * 0.5, confidence=0.5)
    elif leg == "compute":
        msg = (f"compute leg at {ratio:.2f}x its prediction — recalibrate "
               "(hetulint --plan --calibrate TELEMETRY_DIR now reads this "
               "watch stream) and re-evaluate the dp/tp split; if the gap "
               "is HBM pressure, arm remat")
        delta = make_delta("remesh", arg=plan.get("mesh"),
                           expected_gain=gain * 0.3, confidence=0.3)
    else:
        msg = (f"host leg {leg} at {ratio:.2f}x its prediction — the plan "
               "treats host time as layout-invariant; enable prefetch / "
               "dataloader workers or move feed staging off the step path")
    return {"lint": "watch-divergence", "severity": "warn", "message": msg,
            "delta": delta}


# ---------------------------------------------------------------------------
# gauge export (executor hot path — the export_critical_path shape)
# ---------------------------------------------------------------------------

def export_watch(metrics, ewma: dict, divergence: Optional[float],
                 cache: Optional[dict] = None) -> None:
    """Set ``hetu_plan_residual{leg=…}`` and ``hetu_plan_divergence`` on a
    live registry; ``cache`` avoids the labeled-gauge lookup per step."""
    if cache is not None:
        gauges = cache.get("watch_gauges")
        if gauges is None:
            gauges = cache["watch_gauges"] = {
                leg: metrics.gauge("hetu_plan_residual", {"leg": leg})
                for leg in LEGS}
            cache["watch_div"] = metrics.gauge("hetu_plan_divergence")
        div_g = cache["watch_div"]
    else:
        gauges = {leg: metrics.gauge("hetu_plan_residual", {"leg": leg})
                  for leg in LEGS}
        div_g = metrics.gauge("hetu_plan_divergence")
    for leg, g in gauges.items():
        if leg in ewma:
            g.set(ewma[leg])
    if divergence is not None:
        div_g.set(divergence)


# ---------------------------------------------------------------------------
# offline: load / analyze / render a telemetry directory
# ---------------------------------------------------------------------------

def load_dir(dir_path: str) -> dict:
    """Scan a telemetry directory's rank JSONL (including rotated ``.1``
    backups) for the watch surface: the plan stamp, the watch rows, the
    watch-owned events, and the declared run identity."""
    plan = None
    run_info = None
    rows: list = []
    events: list = []
    paths = sorted(glob.glob(os.path.join(dir_path, "metrics-r*.jsonl"))
                   + glob.glob(os.path.join(dir_path, "metrics-r*.jsonl.1")))
    for path in paths:
        try:
            f = open(path)
        except OSError:
            continue
        with f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if not isinstance(rec, dict):
                    continue
                kind = rec.get("kind")
                if kind == "plan":
                    plan = rec
                elif kind == "watch":
                    rows.append(rec)
                elif kind == "run_info":
                    run_info = rec
                elif kind == "event" and rec.get("name") in WATCH_EVENTS:
                    events.append(rec)
    rows.sort(key=lambda r: int(r.get("step", 0)))
    events.sort(key=lambda e: int(e.get("step", 0)))
    return {"dir": dir_path, "plan": plan, "run_info": run_info,
            "watch": rows, "events": events}


def analyze(dir_path: str) -> dict:
    """Whole-run watch report: residual trajectory, divergence episodes
    (fired → recovered pairs), SLO breaches, abstains, and the
    recommended-vs-declared layout."""
    loaded = load_dir(dir_path)
    rows = [r for r in loaded["watch"] if "abstain" not in r]
    abstains = [r for r in loaded["watch"] if "abstain" in r]
    div_rows = [r for r in rows if r.get("divergence") is not None]
    episodes: list = []
    open_ep: Optional[dict] = None
    for ev in loaded["events"]:
        if ev["name"] == "plan_divergence":
            open_ep = {"leg": ev.get("leg"), "fired_step": ev.get("step"),
                       "ratio": ev.get("ratio"),
                       "server": ev.get("server"),
                       "param": ev.get("param"),
                       "recommendation": ev.get("recommendation")}
            episodes.append(open_ep)
        elif ev["name"] == "plan_divergence_recovered" and open_ep \
                and "recovered_step" not in open_ep:
            open_ep["recovered_step"] = ev.get("step")
    slo_breaches = [ev for ev in loaded["events"]
                    if ev["name"] == "slo_breach"]
    plan = loaded["plan"] or {}
    run_info = loaded["run_info"] or {}
    trajectory = [{"step": r["step"],
                   "divergence": r.get("divergence"),
                   "worst_leg": r.get("worst_leg"),
                   "step_ms": r.get("step_ms")}
                  for r in div_rows[-40:]]
    return {
        "dir": dir_path,
        "plan": {k: plan.get(k) for k in
                 ("mesh", "comm_mode", "comm_quant", "zero1", "remat",
                  "predicted_step_ms", "predicted_legs")} if plan else None,
        "declared_comm_mode": run_info.get("comm_mode"),
        "rows": len(rows),
        "abstains": len(abstains),
        "trajectory": trajectory,
        "divergence_final": (div_rows[-1].get("divergence")
                             if div_rows else None),
        "divergence_max": max((r["divergence"] for r in div_rows),
                              default=None),
        "episodes": episodes,
        "slo_breaches": [{k: ev.get(k) for k in ("slo", "value", "step")}
                         for ev in slo_breaches],
        "events": len(loaded["events"]),
    }


def summary_cells(dir_path: str) -> dict:
    """The watch stream as a hetuprof gate summary: ``{"plan_watch":
    {metrics…}}``. ``divergence``/``residual_*`` gate lower-is-better
    (``metric_direction`` knows the hints) so CI fails a PR that
    regresses plan fidelity. Empty when the dir carries no watch rows."""
    loaded = load_dir(dir_path)
    rows = [r for r in loaded["watch"] if r.get("divergence") is not None]
    if not rows:
        return {}
    tail = rows[-min(len(rows), 8):]
    cell = {
        "divergence": round(sum(r["divergence"] for r in tail)
                            / len(tail), 4),
        "worst_leg_residual": round(max(r["divergence"] for r in rows), 4),
        "step_ms": round(sum(float(r.get("step_ms", 0.0)) for r in tail)
                         / len(tail), 4),
        "watch_rows": len(rows),
        "divergence_events": sum(1 for e in loaded["events"]
                                 if e["name"] == "plan_divergence"),
        "slo_breach_events": sum(1 for e in loaded["events"]
                                 if e["name"] == "slo_breach"),
    }
    last = tail[-1]
    for leg, v in (last.get("ewma") or {}).items():
        cell[f"residual_{leg}"] = round(float(v), 4)
    return {"plan_watch": cell}


def format_report(rep: dict) -> str:
    lines = [f"hetuwatch: {rep['dir']}"]
    if rep["plan"]:
        p = rep["plan"]
        mesh = p.get("mesh") or {}
        mesh_s = (f"dp{mesh.get('dp')}/tp{mesh.get('tp')}/pp{mesh.get('pp')}"
                  if mesh else "none")
        lines.append(
            f"  plan: {mesh_s}, comm_mode={p.get('comm_mode') or 'none'}, "
            f"comm_quant={p.get('comm_quant')}"
            + (", zero1" if p.get("zero1") else "")
            + (", remat" if p.get("remat") else "")
            + f" — predicted step {p.get('predicted_step_ms')} ms")
        if p.get("predicted_legs"):
            lines.append("  predicted legs: " + "  ".join(
                f"{k}={v:.2f}ms" for k, v in p["predicted_legs"].items()))
        declared = rep.get("declared_comm_mode")
        if declared and declared not in ("None", str(p.get("comm_mode"))):
            lines.append(f"  declared comm_mode={declared} (differs from "
                         "the plan — see hetulint plan-divergence)")
    else:
        lines.append("  no plan stamp (run without plan adoption, or "
                     "telemetry off) — SLO-only watch")
    lines.append(f"  watch rows: {rep['rows']}"
                 + (f", abstains (elastic resets): {rep['abstains']}"
                    if rep["abstains"] else ""))
    if rep["divergence_final"] is not None:
        lines.append(f"  divergence: final {rep['divergence_final']:.3f}, "
                     f"max {rep['divergence_max']:.3f} "
                     "(1.0 = on plan; worst-leg EWMA residual)")
        traj = rep["trajectory"]
        if traj:
            lines.append("  trajectory (last %d): " % len(traj) + " ".join(
                f"{t['step']}:{t['divergence']:.2f}" for t in traj[-10:]))
    for ep in rep["episodes"]:
        msg = (f"  DIVERGENCE leg {ep['leg']} @ step {ep['fired_step']}: "
               f"{ep['ratio']}x predicted")
        if ep.get("server") is not None:
            msg += f" — server {ep['server']}"
        if ep.get("param") is not None:
            msg += f", param {ep['param']}"
        msg += (f"; recovered @ step {ep['recovered_step']}"
                if ep.get("recovered_step") is not None
                else "; still diverged at end of stream")
        lines.append(msg)
        if ep.get("recommendation"):
            lines.append(f"    recommended: {ep['recommendation']}")
    for b in rep["slo_breaches"]:
        lines.append(f"  SLO BREACH {b['slo']} @ step {b['step']}: "
                     f"measured {b['value']}")
    if not rep["episodes"] and not rep["slo_breaches"]:
        lines.append("  no divergence episodes, no SLO breaches")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# --check: jax-free self-test (the CI smoke, like hetutrail --check)
# ---------------------------------------------------------------------------

def self_check(out=sys.stdout) -> int:
    """Prove the whole pipeline synthetically: grammar, the clean twin
    stays silent, a seeded ps_pull slowdown fires ONE latched event within
    K windows naming the leg, flapping never oscillates, a world-version
    flip abstains, SLO budgets latch, and the dir round-trip (stamp →
    rows → report → gate cells) holds. Exit 0/1."""
    try:
        assert LEGS == _trail().LEGS, (LEGS, _trail().LEGS)
        # grammar
        assert resolve_watch("0") == 0 and resolve_watch("off") == 0
        assert resolve_watch("7") == 7 and resolve_watch(True) >= 1
        try:
            resolve_watch("-3")
            raise AssertionError("negative cadence accepted")
        except ValueError:
            pass
        rules = parse_slo_spec("step_ms<25, ps_pull_frac<0.3")
        assert [r["metric"] for r in rules] == ["step_ms", "ps_pull_frac"]
        for bad in ("nope<1", "step_ms~25", "step_ms<abc"):
            try:
                parse_slo_spec(bad)
                raise AssertionError(f"malformed SLO accepted: {bad}")
            except ValueError:
                pass
        # prediction mapping conserves the step
        bd = {"compute_ms": 10.0, "allreduce_ms": 2.0, "ps_ms": 6.0,
              "host_ms": 2.0, "bubble_frac": 0.0}
        pl = predicted_legs(bd)
        assert abs(sum(pl.values()) - 20.0) < 1e-9, pl
        assert pl["compute"] == 12.0 and pl["ps_pull"] == 3.0

        pred = {"feed": 1.0, "ps_pull": 3.0, "compute": 12.0,
                "ps_push": 3.0, "poststep": 1.0}

        def phases(pull_ms=3.0, push_ms=3.0, dispatch_ms=12.0, jig=1.0):
            return {"prestep_ms": (1.0 + pull_ms) * jig,
                    "dispatch_ms": dispatch_ms * jig,
                    "poststep_ms": (1.0 + push_ms) * jig,
                    "ps_pull_ms": pull_ms * jig, "ps_push_ms": push_ms * jig}

        # clean twin: 40 on-plan observations with +-6% deterministic
        # jitter -> zero events
        pw = PlanWatch(predicted=pred, predicted_step_ms=20.0, k=3)
        fired = []
        for s in range(40):
            _, evs = pw.observe(s, phases(jig=1.06 if s % 2 else 0.94))
            fired += evs
        assert fired == [], f"clean twin fired: {fired}"

        # seeded divergence: ps_pull 4x from step 40 -> ONE event within
        # K observations naming ps_pull, then silence while it persists
        for s in range(40, 60):
            _, evs = pw.observe(s, phases(pull_ms=12.0))
            fired += evs
        names = [e["name"] for e in fired]
        assert names.count("plan_divergence") == 1, fired
        ev = next(e for e in fired if e["name"] == "plan_divergence")
        assert ev["leg"] == "ps_pull" and ev["step"] <= 40 + 3 * 8, ev
        # recovery -> one recovered event; re-breach -> fires again
        for s in range(60, 80):
            _, evs = pw.observe(s, phases())
            fired += evs
        assert [e["name"] for e in fired].count(
            "plan_divergence_recovered") == 1, fired
        for s in range(80, 95):
            _, evs = pw.observe(s, phases(pull_ms=12.0))
            fired += evs
        assert [e["name"] for e in fired].count("plan_divergence") == 2

        # flapping (alternating breach/clean) never fires: K-consecutive
        pw2 = PlanWatch(predicted=pred, k=3, window=1)
        flap = []
        for s in range(60):
            _, evs = pw2.observe(s, phases(pull_ms=12.0 if s % 2 else 3.0))
            flap += evs
        assert flap == [], f"flapping oscillated the detector: {flap}"

        # world-version flip mid-streak resets the window: 2 breach
        # observations, flip, then 2 more -> no event (streak restarted)
        pw3 = PlanWatch(predicted=pred, k=3)
        evs_all = []
        for s in range(2):
            _, evs = pw3.observe(s, phases(pull_ms=12.0))
            evs_all += evs
        row, evs = pw3.observe(2, phases(pull_ms=12.0), world_version=1)
        assert row.get("abstain") == "world_version", row
        assert [e["name"] for e in evs] == ["watch_abstain"], evs
        for s in range(3, 5):
            _, evs = pw3.observe(s, phases(pull_ms=12.0), world_version=1)
            evs_all += evs
        assert evs_all == [], f"stale-era streak survived the flip: "\
            f"{evs_all}"
        # ...and the fresh world fires after its own K windows
        _, evs = pw3.observe(5, phases(pull_ms=12.0), world_version=1)
        assert any(e["name"] == "plan_divergence" for e in evs), evs

        # SLO latch: sustained breach fires once, flapping stays silent
        pw4 = PlanWatch(slo="step_ms<18", k=3)
        slo_evs = []
        for s in range(10):
            _, evs = pw4.observe(s, phases())   # 20 ms steps, budget 18
            slo_evs += evs
        assert [e["name"] for e in slo_evs] == ["slo_breach"], slo_evs
        assert slo_evs[0]["slo"] == "step_ms<18"

        # recommendation shapes
        plan = {"comm_quant": "off",
                "params": [{"param": "embed", "mode": "PS", "sparse": True}]}
        rec = recommend(plan, "ps_pull", 4.0)
        assert rec["lint"] == "watch-divergence" \
            and "comm_quant" in rec["message"], rec
        assert "AllReduce" in recommend(
            {"comm_quant": "int8",
             "params": [{"param": "w", "mode": "PS", "sparse": False}]},
            "ps_pull", 2.0)["message"]

        # PlanDelta schema: machine-readable, registry-validated
        assert rec["delta"]["kind"] == "comm_quant" \
            and rec["delta"]["arg"] == "int8", rec["delta"]
        flip = recommend(
            {"comm_quant": "int8",
             "params": [{"param": "w", "mode": "PS", "sparse": False}]},
            "ps_push", 2.0)["delta"]
        assert flip == make_delta("comm_mode_flip", target="w",
                                  arg="AllReduce",
                                  expected_gain=flip["expected_gain"],
                                  confidence=0.7), flip
        assert 0.0 < flip["expected_gain"] <= 1.0, flip
        grow = recommend({"comm_quant": "int8", "params": [
            {"param": "e", "mode": "PS", "sparse": True}]},
            "ps_pull", 3.0)["delta"]
        assert grow["kind"] == "ps_server_grow", grow
        assert recommend({}, "feed", 2.0)["delta"] is None
        assert recommend({}, "compute", 2.0)["delta"]["kind"] == "remesh"
        try:
            make_delta("full_replan")
            raise AssertionError("make_delta accepted an unknown kind")
        except ValueError as ve:
            assert "comm_mode_flip" in str(ve), ve

        # dir round-trip: stamp + rows + events -> report + gate cells
        with tempfile.TemporaryDirectory(prefix="hetuwatch_check_") as d:
            with open(os.path.join(d, "metrics-r0.jsonl"), "w") as f:
                f.write(json.dumps(
                    {"kind": "plan", **stamp_fields(
                        {"mesh": {"dp": 2, "tp": 1, "pp": 1},
                         "comm_mode": "PS", "comm_quant": "off",
                         "zero1": False, "remat": False,
                         "predicted_step_ms": 20.0, "breakdown": bd,
                         "params": plan["params"]})}) + "\n")
                pw5 = PlanWatch(predicted=pred, predicted_step_ms=20.0)
                for s in range(30):
                    slow = s >= 10
                    row, evs = pw5.observe(
                        s, phases(pull_ms=12.0 if slow else 3.0))
                    f.write(json.dumps({"kind": "watch", **row}) + "\n")
                    for e in evs:
                        f.write(json.dumps({"kind": "event", **e}) + "\n")
            rep = analyze(d)
            assert rep["plan"]["comm_mode"] == "PS", rep
            assert rep["rows"] == 30 and rep["episodes"], rep
            assert rep["episodes"][0]["leg"] == "ps_pull", rep
            txt = format_report(rep)
            assert "DIVERGENCE leg ps_pull" in txt, txt
            cells = summary_cells(d)
            cell = cells["plan_watch"]
            assert cell["divergence_events"] == 1, cell
            assert cell["worst_leg_residual"] > 2.0, cell
            assert cell["residual_ps_pull"] > 1.5, cell
        print("hetuwatch --check: stamp/residual/divergence/SLO/abstain "
              "pipeline ok", file=out)
        return 0
    except AssertionError as e:
        print(f"hetuwatch --check: FAIL: {e}", file=out)
        return 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="hetuwatch",
        description="runtime plan-divergence sentinel: residual "
                    "trajectory, divergence episodes, SLO breaches "
                    "(docs/OBSERVABILITY.md pillar 6)")
    ap.add_argument("dir", nargs="?",
                    help="telemetry directory (HETU_TELEMETRY_DIR)")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as JSON")
    ap.add_argument("--gate-cells", action="store_true",
                    help="emit the hetuprof gate summary cells for this "
                         "watch stream (what `hetuprof --gate` reads when "
                         "given a directory)")
    ap.add_argument("--check", action="store_true",
                    help="jax-free self-test of the stamp/residual/"
                         "divergence/SLO pipeline, exit 0/1 (CI mode)")
    args = ap.parse_args(argv)
    if args.check:
        return self_check()
    if not args.dir:
        ap.error("a directory is required unless --check")
    try:
        if args.gate_cells:
            print(json.dumps(summary_cells(args.dir), indent=1))
            return 0
        rep = analyze(args.dir)
        print(json.dumps(rep, indent=1) if args.json
              else format_report(rep))
    except BrokenPipeError:
        return 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
