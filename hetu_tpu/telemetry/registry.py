"""Metrics registry: counters, gauges, histograms with a per-step JSONL sink
and a Prometheus-textfile exporter.

Stdlib-only by design — the registry is imported by components that must stay
jax-free (the heturun launcher parent, the PS supervisor, dataloaders running
in light processes). Thread-safe: PS push/pull streams observe latencies from
their own threads while the step loop snapshots.

Export surfaces:

- ``snapshot()`` — flat ``{name: value}`` dict (histograms contribute
  ``name_count/_sum/_p50/_p99``) embedded in each step's JSONL record.
- ``to_prometheus()`` / ``write_prometheus(path)`` — the Prometheus
  text exposition format (textfile-collector style: counters, gauges, and
  cumulative-bucket histograms), written atomically via tmp+rename so a
  scraping node-exporter never reads a torn file.
"""
from __future__ import annotations

import bisect
import collections
import json
import math
import os
import threading
import time
from typing import Optional

# Default histogram buckets: log-spaced milliseconds covering everything from
# a sub-ms cache hit to a multi-minute compile (upper bound +Inf implied).
DEFAULT_BUCKETS_MS = (0.1, 0.25, 0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500,
                      1000, 2500, 5000, 10000, 30000, 60000, 120000)

# recent-sample window per histogram: percentile math runs over this window
# (exact over recent behavior — what a dashboard wants), while count/sum/
# buckets stay cumulative (what Prometheus wants)
_WINDOW = 512


def _esc_label(v) -> str:
    """Prometheus label-value escaping (backslash, quote, newline) — an
    unescaped quote in a user-chosen loader/table name would invalidate
    the whole textfile."""
    return (str(v).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _fmt_labels(labels: Optional[dict]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_esc_label(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class Counter:
    """Monotonic counter. ``inc`` is the only mutator.

    The lock makes cross-thread ``inc`` lossless (float ``+=`` is a
    read-modify-write; PS stream threads and the step loop share e.g.
    ``hetu_events_total``). Uncontended acquire is ~100 ns — noise next
    to the JSONL write it accompanies."""

    __slots__ = ("name", "labels", "value", "_lock")
    prom_type = "counter"

    def __init__(self, name: str, labels: Optional[dict] = None):
        self.name = name
        self.labels = dict(labels) if labels else None
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, v: float = 1.0) -> None:
        with self._lock:
            self.value += v

    def prom_lines(self) -> list[str]:
        return [f"{self.name}{_fmt_labels(self.labels)} {self.value:g}"]


class Gauge:
    """Last-value gauge."""

    __slots__ = ("name", "labels", "value", "_lock")
    prom_type = "gauge"

    def __init__(self, name: str, labels: Optional[dict] = None):
        self.name = name
        self.labels = dict(labels) if labels else None
        self.value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        self.value = float(v)   # single store: atomic enough for a gauge

    def inc(self, v: float = 1.0) -> None:
        with self._lock:
            self.value += v

    def prom_lines(self) -> list[str]:
        return [f"{self.name}{_fmt_labels(self.labels)} {self.value:g}"]


class Histogram:
    """Cumulative-bucket histogram plus an exact recent-sample window.

    Buckets/count/sum are cumulative since process start (the Prometheus
    contract); ``percentile`` answers over the last ``_WINDOW`` samples —
    a live dashboard wants "p99 lately", not "p99 since boot".
    """

    __slots__ = ("name", "labels", "buckets", "bucket_counts", "count",
                 "sum", "min", "max", "_recent", "_lock")
    prom_type = "histogram"

    def __init__(self, name: str, labels: Optional[dict] = None,
                 buckets=DEFAULT_BUCKETS_MS):
        self.name = name
        self.labels = dict(labels) if labels else None
        self.buckets = tuple(sorted(buckets))
        self.bucket_counts = [0] * (len(self.buckets) + 1)  # +Inf last
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self._recent = collections.deque(maxlen=_WINDOW)
        # observe vs percentile/export race: sorted() over a deque being
        # appended to from a PS stream thread raises "deque mutated during
        # iteration" — every mutation and every window read locks
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        idx = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self.bucket_counts[idx] += 1
            self.count += 1
            self.sum += v
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v
            self._recent.append(v)

    def percentile(self, p: float) -> Optional[float]:
        """p in [0, 100] over the recent window; None when empty."""
        with self._lock:
            if not self._recent:
                return None
            s = sorted(self._recent)
        k = min(len(s) - 1, max(0, int(round(p / 100.0 * (len(s) - 1)))))
        return s[k]

    @property
    def mean(self) -> Optional[float]:
        return self.sum / self.count if self.count else None

    def prom_lines(self) -> list[str]:
        lab = self.labels or {}
        with self._lock:
            counts = list(self.bucket_counts)
            total, total_sum = self.count, self.sum
        out = []
        cum = 0
        for bound, n in zip(self.buckets, counts):
            cum += n
            out.append(f"{self.name}_bucket"
                       f"{_fmt_labels({**lab, 'le': f'{bound:g}'})} {cum}")
        out.append(f"{self.name}_bucket"
                   f"{_fmt_labels({**lab, 'le': '+Inf'})} {total}")
        out.append(f"{self.name}_sum{_fmt_labels(self.labels)} "
                   f"{total_sum:g}")
        out.append(f"{self.name}_count{_fmt_labels(self.labels)} "
                   f"{total}")
        return out


class MetricsRegistry:
    """Process-wide named metric store. ``counter/gauge/histogram`` create on
    first use and return the live object; callers may also cache the handle
    (cheaper on hot paths — one dict lookup saved per observation)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict = {}   # (name, labels-key) -> metric

    def _get(self, cls, name: str, labels: Optional[dict], **kw):
        key = (name, tuple(sorted(labels.items())) if labels else None)
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls(name, labels, **kw)
                self._metrics[key] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, requested {cls.__name__}")
            return m

    def counter(self, name: str, labels: Optional[dict] = None) -> Counter:
        return self._get(Counter, name, labels)

    def gauge(self, name: str, labels: Optional[dict] = None) -> Gauge:
        return self._get(Gauge, name, labels)

    def histogram(self, name: str, labels: Optional[dict] = None,
                  buckets=DEFAULT_BUCKETS_MS) -> Histogram:
        return self._get(Histogram, name, labels, buckets=buckets)

    def all_metrics(self) -> list:
        with self._lock:
            return list(self._metrics.values())

    # -- export ------------------------------------------------------------
    def snapshot(self) -> dict:
        """Flat scalar view for the per-step JSONL record."""
        out: dict = {}
        for m in self.all_metrics():
            suffix = _fmt_labels(m.labels)
            key = m.name + suffix
            if isinstance(m, Histogram):
                out[key + "_count"] = m.count
                out[key + "_sum"] = round(m.sum, 6)
                for p in (50, 99):
                    v = m.percentile(p)
                    if v is not None:
                        out[f"{key}_p{p}"] = round(v, 6)
            else:
                out[key] = m.value
        return out

    def to_prometheus(self) -> str:
        # one "# TYPE" line per metric FAMILY with its samples contiguous:
        # labeled children of the same name (hetu_events_total{event=...})
        # share it — a second TYPE line for a name, or interleaved
        # families, make node_exporter reject the whole textfile
        by_name: dict = {}
        for m in self.all_metrics():
            by_name.setdefault(m.name, []).append(m)
        lines: list[str] = []
        for name, members in by_name.items():
            lines.append(f"# TYPE {name} {members[0].prom_type}")
            for m in members:
                lines.extend(m.prom_lines())
        return "\n".join(lines) + "\n" if lines else ""

    def write_prometheus(self, path: str) -> str:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(self.to_prometheus())
        os.replace(tmp, path)
        return path


class JsonlSink:
    """Append-only JSONL writer with periodic flush.

    Every record gains ``ts`` (unix seconds) and the writer's identity
    fields. Flushes at most every ``flush_s`` seconds on write, plus on
    ``close`` — crash-durability for the resilience events comes from the
    explicit ``flush()`` those call sites do before aborting.

    Long-run growth is bounded by ``HETU_TELEMETRY_MAX_MB`` (default off,
    for test stability): when the live file exceeds the cap at a record
    boundary it rotates — the current file is atomically renamed to
    ``<path>.1`` (replacing the previous backup) and a fresh file opens at
    the same path. Readers stay valid through the flip: a tailer holding
    the old fd keeps a complete file; offset-based followers (hetutop's
    Follower, trail's SkewMonitor) observe size < offset and restart, and
    ``--check`` globs never match the ``.1`` backup."""

    def __init__(self, path: str, base_fields: Optional[dict] = None,
                 flush_s: float = 1.0, max_mb: Optional[float] = None):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self.path = path
        if max_mb is None:
            try:
                max_mb = float(os.environ.get("HETU_TELEMETRY_MAX_MB",
                                              "0") or 0)
            except ValueError:
                max_mb = 0.0
        self._max_bytes = int(max_mb * 1e6) if max_mb and max_mb > 0 else 0
        self._f = open(path, "a")
        try:
            self._nbytes = os.path.getsize(path)
        except OSError:
            self._nbytes = 0
        self._base = dict(base_fields or {})
        # identity fields serialized once: the per-step fast path
        # (write_fields) splices this fragment instead of re-dumping the
        # same rank/pid dict thousands of times per second
        self._base_json = "".join(
            json.dumps({k: v}, separators=(",", ":"),
                       default=_json_default)[1:-1] + ","
            for k, v in self._base.items())
        self._flush_s = float(flush_s)
        self._last_flush = time.monotonic()
        self._lock = threading.Lock()

    def write(self, record: dict) -> None:
        rec = {"ts": round(time.time(), 3), **self._base, **record}
        line = json.dumps(rec, separators=(",", ":"),
                          default=_json_default) + "\n"
        self._write_line(line)

    def write_fields(self, fields_json: str) -> None:
        """Hot-path writer: ``fields_json`` is a pre-serialized JSON object
        body (no braces), e.g. ``'"kind":"step","step":7'``. The caller
        guarantees validity; ``ts`` + identity fields are spliced in here."""
        self._write_line(
            f'{{"ts":{time.time():.3f},{self._base_json}{fields_json}}}\n')

    def _write_line(self, line: str) -> None:
        with self._lock:
            if self._f.closed:
                return  # late writer (atexit ordering); drop, don't raise
            self._f.write(line)
            self._nbytes += len(line)
            if self._max_bytes and self._nbytes >= self._max_bytes:
                self._rotate_locked()
            now = time.monotonic()
            if now - self._last_flush >= self._flush_s:
                self._f.flush()
                self._last_flush = now

    def _rotate_locked(self) -> None:
        """Atomic rollover (caller holds the lock): flush, rename the live
        file onto the single ``.1`` backup, reopen fresh. Any failure
        leaves the current file in place and disables rotation rather than
        losing records."""
        try:
            self._f.flush()
            self._f.close()
            os.replace(self.path, self.path + ".1")
            self._f = open(self.path, "a")
            self._nbytes = 0
        except OSError:
            self._max_bytes = 0
            if self._f.closed:   # reopen (append) so writes keep landing
                try:
                    self._f = open(self.path, "a")
                except OSError:
                    pass

    def flush(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.flush()

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.flush()
                self._f.close()


def _json_default(o):
    """Numpy scalars (step counters, metric values) without importing numpy."""
    for attr in ("item",):
        f = getattr(o, attr, None)
        if callable(f):
            return f()
    return str(o)
