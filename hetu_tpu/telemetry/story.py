"""``hetustory`` — the unified run ledger: one registry over every artifact
family a run writes, a causal cross-subsystem timeline, an offline invariant
audit, incident reports, and cross-run diff (docs/OBSERVABILITY.md pillar 7,
docs/FAULT_TOLERANCE.md post-mortem workflow).

After PRs 5/6/7/13/15/16/17/19 a run leaves ~10 disjoint artifact formats on
disk (metrics/scope/watch JSONL, trail client+server spans, flight rings,
``pilot.jsonl``, snapshot manifests, supervisor JSONL, ``run_summary.json``).
This module is the one place that knows all of them:

- :data:`LEDGERS` — one descriptor per family: path globs (including the
  rotated ``.1`` backup every bounded writer keeps), format (JSONL vs
  atomic-rename JSON document), torn-tail policy, and the causal keys
  ``(world_version, era/epoch, step, rank)`` its rows carry.
- :func:`read_rows` / :class:`LedgerFollower` — the shared rotation- and
  torn-tail-tolerant readers that hetutop, hetutrail, hetupilot, and heturun's
  five ad-hoc loaders are built on. A torn final line is a *classification*
  (the crash left it there on purpose), not a crash of the reader.
- :func:`load_timeline` — every source merged into one ordered "who did what
  to whom" stream, cross-process-ordered via the PR 13 trail anchors when all
  ranks share one ``boot_id`` (the same condition ``hetutrace`` uses).
- :func:`audit` — recompute, from the ledgers alone, the algebra the runtime
  asserts live (push accounting, pilot-era consistency, manifest
  completeness, flight/event agreement, era sequencing); exit 0/1.
- :func:`write_incident` — called from every resilience abort path: one
  ``incident-*.json`` collecting the ±K-step window from every registered
  source, so the post-mortem starts from a single file.
- :func:`diff_runs` — two runs aligned by step/era: the gate's
  direction-aware metric comparison plus plan and episode deltas.

Stdlib-only and jax-free at module level (the hetutop/hetutrail contract):
``bin/hetustory`` loads this file by path on a login node or in CI. This
module is a *leaf* — trail/hetutop/pilot import it, never the reverse; the
profiler (for --diff) is resolved lazily through :func:`_profiler_mod` so the
standalone load needs no package.
"""
from __future__ import annotations

import argparse
import collections
import glob
import json
import os
import sys
import tempfile
import time
from typing import Iterable, Iterator, Optional

Row = collections.namedtuple("Row", ("path", "line", "rec"))

# ---------------------------------------------------------------------------
# shared JSONL reader: torn-tail classification + rotation
# ---------------------------------------------------------------------------


def iter_rows(path: str, errors: Optional[list] = None) -> Iterator[Row]:
    """Yield :class:`Row` per valid object line of one JSONL file.

    Malformed input is *classified* into ``errors`` (dicts with ``path``,
    ``line``, ``reason``, ``error``) instead of raised: an undecodable LAST
    line is ``torn-tail`` (the expected signature of a crashed or live
    writer — JsonlSink/TrailWriter append whole lines, so only the tail can
    tear); undecodable earlier lines are ``invalid-json``; a decodable
    non-object is ``not-object``. Callers that tolerate torn tails pass
    ``errors=None``; strict callers (hetutop --check) format every entry."""
    pending = None   # a bad line is only mid-file corruption once another
    try:             # line follows it; at EOF it is the torn tail
        with open(path) as f:
            for i, line in enumerate(f, 1):
                if pending is not None:
                    pending["reason"] = "invalid-json"
                    if errors is not None:
                        errors.append(pending)
                    pending = None
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError as e:
                    pending = {"path": path, "line": i,
                               "reason": "torn-tail",
                               "error": f"invalid JSON ({e})"}
                    continue
                if not isinstance(rec, dict):
                    if errors is not None:
                        errors.append({"path": path, "line": i,
                                       "reason": "not-object",
                                       "error": "record is not an object"})
                    continue
                yield Row(path, i, rec)
    except OSError:
        return
    if pending is not None and errors is not None:
        errors.append(pending)


def read_rows(path: str, errors: Optional[list] = None) -> list:
    return list(iter_rows(path, errors))


def read_jsonl(path: str, errors: Optional[list] = None) -> list:
    """Records only (the drop-in shape trail/pilot's old readers returned)."""
    return [r.rec for r in iter_rows(path, errors)]


def format_error(err: dict) -> str:
    """One classified reader error in hetutop's historical string format."""
    return f"{err['path']}:{err['line']}: {err['error']}"


def rotated_paths(path: str) -> list:
    """Backup-first read order for one bounded JSONL file: the single ``.1``
    generation (JsonlSink/TrailWriter convention), then the live file."""
    return [p for p in (path + ".1", path) if os.path.exists(p)]


def read_rows_rotated(path: str, errors: Optional[list] = None) -> list:
    out = []
    for p in rotated_paths(path):
        out.extend(iter_rows(p, errors))
    return out


def read_jsonl_rotated(path: str, errors: Optional[list] = None) -> list:
    return [r.rec for r in read_rows_rotated(path, errors)]


class LedgerFollower:
    """Shared incremental tailer: byte offset + inode per file, rotation-
    aware. Each :meth:`poll` returns only records appended since the last
    one, so a dashboard frame or monitor tick stays O(new data).

    Closes the PR 13 gap this file exists to fix: the old per-consumer
    tailers detected rotation by inode change and restarted at offset 0,
    silently dropping every record written between their last poll and the
    rename. Here the old generation now sits at ``path + ".1"`` — when its
    inode matches the one we were reading, its tail past our stored offset
    is drained first, then the fresh file is read from 0. ``backlog=True``
    additionally replays an existing ``.1`` backup the first time a path is
    seen (consumers that want history, e.g. the hetutop dashboard warm-up).
    """

    def __init__(self, backlog: bool = False):
        self.backlog = backlog
        self._offsets: dict = {}   # path -> (byte offset, inode)

    def poll(self, path: str) -> list:
        recs: list = []
        try:
            st = os.stat(path)
        except OSError:
            return recs
        off, ino = self._offsets.get(path, (None, None))
        if off is None:
            off = 0
            if self.backlog:
                recs.extend(read_jsonl(path + ".1"))
        elif ino is not None and st.st_ino != ino:
            recs.extend(self._drain_backup(path + ".1", off, ino))
            off = 0
        elif st.st_size < off:
            off = 0   # truncated in place: restart
        if st.st_size > off:
            new, off = self._read_from(path, off)
            recs.extend(new)
        self._offsets[path] = (off, st.st_ino)
        return recs

    def _drain_backup(self, backup: str, off: int, ino: int) -> list:
        # only when the backup IS the generation we were reading (inode
        # match): after a double rotation between polls the middle
        # generation is gone — a stale offset into an unrelated file must
        # not fabricate half-records
        try:
            st = os.stat(backup)
        except OSError:
            return []
        if st.st_ino != ino or st.st_size < off:
            return []
        recs, _ = self._read_from(backup, off)
        return recs

    @staticmethod
    def _read_from(path: str, off: int):
        with open(path, "rb") as f:
            f.seek(off)
            chunk = f.read()
        last_nl = chunk.rfind(b"\n")
        if last_nl < 0:
            return [], off        # partial tail line: retry next poll
        recs = []
        for raw in chunk[:last_nl].split(b"\n"):
            raw = raw.strip()
            if not raw:
                continue
            try:
                rec = json.loads(raw)
            except json.JSONDecodeError:
                continue          # torn/garbage line: skip, stay live
            if isinstance(rec, dict):
                recs.append(rec)
        return recs, off + last_nl + 1


# ---------------------------------------------------------------------------
# the ledger registry
# ---------------------------------------------------------------------------

# Every `kind` value any writer in the tree emits, by family. This literal
# is the contract hetucheck's `ledger-kind-drift` lint parses (the
# DELTA_KINDS pattern): a kind emitted anywhere but absent here — or listed
# here but emitted nowhere — is drift. `report` covers exported report
# documents (hetuprof --roofline --json), which are CLI output, not files
# under the telemetry dir.
LEDGER_KINDS = {
    "metrics": ("step", "event", "final", "ps_server", "scope", "watch",
                "plan", "model_info", "run_info", "xla_trace", "finding"),
    "trail_client": ("rpc", "anchor", "dropped"),
    "trail_server": ("srv", "anchor", "dropped"),
    "trail_events": ("straggler",),
    "pilot": (),            # rows are keyed by `phase`, not `kind`
    "ps_supervisor": ("event",),
    "flight": ("provenance",),
    "job_manifest": (),     # keyed by `format` (recovery.MANIFEST_FORMAT)
    "run_summary": (),
    "report": ("roofline",),
}

# One descriptor per artifact family. `globs` are relative to the telemetry
# directory (the pilot ledger and flight rings may live one level down —
# heturun points HETU_PILOT_DIR at `<dir>/pilot`). `format` is "jsonl"
# (append-only lines; torn tail = crash signature, tolerated) or "doc" (one
# JSON document written tmp + atomic rename; a torn `.tmp` is never read).
# `keys` are the causal keys rows of this family can carry.
LEDGERS = {
    "metrics": {
        "globs": ("metrics-r*.jsonl",), "format": "jsonl", "rotates": True,
        "keys": ("step", "rank", "world_version", "era", "epoch"),
        "desc": "per-rank step/event/plan/watch/scope/ps_server stream",
    },
    "trail_client": {
        "globs": ("trail-client-r*.jsonl",), "format": "jsonl",
        "rotates": True, "keys": ("step", "rank"),
        "desc": "client RPC spans + clock anchors (hetutrail)",
    },
    "trail_server": {
        "globs": ("trail-server-s*.jsonl",), "format": "jsonl",
        "rotates": True, "keys": ("step",),
        "desc": "server request timelines + clock anchors (hetutrail)",
    },
    "trail_events": {
        "globs": ("trail-events.jsonl",), "format": "jsonl",
        "rotates": True, "keys": ("step", "rank"),
        "desc": "cross-rank straggler verdicts",
    },
    "pilot": {
        "globs": ("pilot.jsonl", "pilot/pilot.jsonl"), "format": "jsonl",
        "rotates": False, "keys": ("era", "step"),
        "desc": "actuation ledger: propose/actuate/verdict/abstain phases",
    },
    "ps_supervisor": {
        "globs": ("ps_supervisor.jsonl",), "format": "jsonl",
        "rotates": False, "keys": (),
        "desc": "server liveness lapses / respawns",
    },
    "flight": {
        "globs": ("flight-r*.json", "flight/flight-r*.json"),
        "format": "doc", "rotates": False, "keys": ("step", "rank"),
        "desc": "hetuscope flight-recorder ring, flushed on abort paths",
    },
    "job_manifest": {
        "globs": ("job_epoch_*.json", "*/job_epoch_*.json"),
        "format": "doc", "rotates": False,
        "keys": ("epoch", "step", "world_version"),
        "desc": "hetusave committed job-epoch manifests",
    },
    "run_summary": {
        "globs": ("run_summary.json",), "format": "doc", "rotates": False,
        "keys": (), "desc": "heturun end-of-run digest",
    },
}


def ledger_files(family: str, dir_path: str) -> list:
    """Existing files of one family under ``dir_path``, backups first (so a
    straight concatenation reads in write order). ``.tmp`` siblings of doc
    families are a crash's torn half-write — never matched."""
    led = LEDGERS[family]
    out: list = []
    for pat in led["globs"]:
        for p in sorted(glob.glob(os.path.join(dir_path, pat))):
            if led["rotates"] and os.path.exists(p + ".1"):
                if p + ".1" not in out:
                    out.append(p + ".1")
            if p not in out:
                out.append(p)
    return out


def load_ledgers(dir_path: str, errors: Optional[dict] = None) -> dict:
    """Every registered family under ``dir_path`` → list of :class:`Row`.
    Doc families yield one Row (line 0) per document; an unparsable doc is
    classified into ``errors`` like a torn JSONL line."""
    out: dict = {}
    for family, led in LEDGERS.items():
        errs: list = []
        rows: list = []
        for path in ledger_files(family, dir_path):
            if led["format"] == "doc":
                try:
                    with open(path) as f:
                        doc = json.load(f)
                except (OSError, json.JSONDecodeError) as e:
                    errs.append({"path": path, "line": 0,
                                 "reason": "torn-doc",
                                 "error": f"invalid JSON document ({e})"})
                    continue
                if isinstance(doc, dict):
                    rows.append(Row(path, 0, doc))
            else:
                rows.extend(iter_rows(path, errs))
        out[family] = rows
        if errors is not None:
            errors[family] = errs
    return out


def causal_key(rec: dict) -> dict:
    """The (world_version, era/epoch, step, rank) coordinates a record
    carries — absent keys are simply missing, never fabricated."""
    out = {}
    for k in ("world_version", "era", "epoch", "step", "rank"):
        v = rec.get(k)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out[k] = int(v)
    if "world_version" not in out and "pending_version" in rec:
        try:
            out["world_version"] = int(rec["pending_version"])
        except (TypeError, ValueError):
            pass
    return out


# ---------------------------------------------------------------------------
# causal timeline
# ---------------------------------------------------------------------------


def clock_sync(anchors: Iterable) -> dict:
    """Cross-process ordering from the PR 13 trail anchors. Each anchor
    pairs one rank's CLOCK_MONOTONIC with its wall clock; when every anchor
    carries the same ``boot_id`` (the hetutrace condition: one machine, one
    monotonic clock), the per-rank offset ``wall_s - mono_us/1e6`` measures
    that rank's wall-clock error against the shared clock, and subtracting
    it converts any wall timestamp into the shared monotonic domain.
    Heterogeneous or absent boot ids → ``comparable=False`` (raw wall
    order, the best available)."""
    offsets: dict = {}
    boot_ids = set()
    for a in anchors:
        if a.get("kind") != "anchor":
            continue
        try:
            rank = int(a.get("rank", -1))
            off = float(a["wall_s"]) - float(a["mono_us"]) / 1e6
        except (KeyError, TypeError, ValueError):
            continue
        offsets[rank] = off   # last anchor per rank wins (freshest clock)
        boot_ids.add(a.get("boot_id") or "")
    comparable = len(boot_ids) == 1 and "" not in boot_ids and bool(offsets)
    base = sorted(offsets.values())[len(offsets) // 2] if offsets else 0.0
    return {"comparable": comparable, "offsets": offsets, "base": base,
            "boot_ids": boot_ids}


def _one_line(src: str, rec: dict) -> str:
    """The narrative rendering of one timeline entry."""
    kind = rec.get("kind") or rec.get("phase") or ""
    if src == "metrics" and kind == "event":
        extras = {k: v for k, v in rec.items()
                  if k not in ("ts", "kind", "name", "rank", "pid",
                               "run_id", "inc")}
        return f"event {rec.get('name')} {json.dumps(extras, default=str)}"
    if src == "metrics" and kind == "step":
        return (f"step {rec.get('step')} {rec.get('step_ms')}ms "
                f"sub={rec.get('sub')}")
    if src == "pilot":
        d = rec.get("delta") or {}
        tail = f" verdict={rec['verdict']}" if "verdict" in rec else ""
        return (f"pilot {kind} era={rec.get('era')} "
                f"delta={d.get('kind')}{tail}")
    if src == "flight":
        return (f"flight flush reason={rec.get('reason')} "
                f"k={rec.get('k')} records={len(rec.get('records') or [])}")
    if src == "job_manifest":
        return (f"job epoch {rec.get('epoch')} committed at step "
                f"{rec.get('step')} (world {rec.get('world')})")
    if src == "trail_events":
        return (f"straggler rank={rec.get('rank')} "
                f"step={rec.get('step')} lag_ms={rec.get('lag_ms')}")
    if src == "ps_supervisor":
        return f"supervisor: {rec.get('message')}"
    if src == "run_summary":
        return (f"run ended rc={rec.get('exit_code')} "
                f"final_steps={rec.get('final_steps')}")
    return f"{kind or src} {json.dumps(causal_key(rec), default=str)}"


def load_timeline(dir_path: str, step_range=None) -> dict:
    """The merged causal event stream of one run directory.

    Returns ``{"entries": [...], "clock": ..., "errors": {...}}``; each
    entry is ``{"t", "ts", "src", "what", **causal_key, "rec"}`` sorted by
    the anchor-corrected timestamp (see :func:`clock_sync`), then by step
    and rank. Step records ride along only near narrative entries — or
    throughout ``step_range`` when one is given — so a 100k-step run stays
    readable."""
    errors: dict = {}
    led = load_ledgers(dir_path, errors)
    anchors = [r.rec for fam in ("trail_client", "trail_server")
               for r in led[fam] if r.rec.get("kind") == "anchor"]
    clock = clock_sync(anchors)

    entries: list = []

    def add(src: str, row: Row, ts=None) -> None:
        rec = row.rec
        if ts is None:
            ts = rec.get("ts") or rec.get("flushed_ts")
        try:
            ts = float(ts)
        except (TypeError, ValueError):
            ts = 0.0
        key = causal_key(rec)
        rank = key.get("rank")
        t = ts
        if clock["comparable"] and rank in clock["offsets"]:
            t = ts - clock["offsets"][rank] + clock["base"]
        entries.append({"t": t, "ts": ts, "src": src,
                        "what": _one_line(src, rec), **key, "rec": rec,
                        "_loc": f"{row.path}:{row.line}"})

    narrative_steps: set = set()
    step_rows: list = []
    for row in led["metrics"]:
        kind = row.rec.get("kind")
        if kind == "step":
            step_rows.append(row)
        elif kind == "event":
            add("metrics", row)
            k = causal_key(row.rec)
            if "step" in k:
                narrative_steps.add((k.get("rank"), k["step"]))
        elif kind in ("plan", "run_info", "model_info", "final"):
            add("metrics", row)
        elif kind == "watch" and row.rec.get("divergence"):
            add("metrics", row)
        elif kind == "finding":
            add("metrics", row)
    for fam in ("pilot", "trail_events", "ps_supervisor", "flight",
                "job_manifest", "run_summary"):
        for row in led[fam]:
            add(fam, row)
            k = causal_key(row.rec)
            if "step" in k:
                narrative_steps.add((k.get("rank"), k["step"]))
    lo, hi = step_range if step_range else (None, None)
    for row in step_rows:
        k = causal_key(row.rec)
        s = k.get("step")
        if s is None:
            continue
        if lo is not None and lo <= s <= hi:
            add("metrics", row)
        elif step_range is None and any(
                (k.get("rank"), s + d) in narrative_steps
                for d in (-2, -1, 0, 1, 2)):
            add("metrics", row)

    entries.sort(key=lambda e: (e["t"], e.get("step", -1),
                                e.get("rank", -1), e["_loc"]))
    return {"entries": entries, "clock": clock, "errors": errors}


def render_timeline(tl: dict, out=sys.stdout) -> None:
    clock = tl["clock"]
    mode = ("anchor-corrected (shared boot_id)" if clock["comparable"]
            else "wall-clock (no shared monotonic anchor)")
    print(f"hetustory: {len(tl['entries'])} entries, ordering: {mode}",
          file=out)
    t0 = tl["entries"][0]["t"] if tl["entries"] else 0.0
    for e in tl["entries"]:
        key = " ".join(f"{k}={e[k]}" for k in
                       ("world_version", "era", "epoch", "step", "rank")
                       if k in e)
        print(f"  +{e['t'] - t0:9.3f}s [{e['src']:>13}] {e['what']}"
              f"{('  (' + key + ')') if key else ''}", file=out)
    torn = sum(len(v) for v in tl["errors"].values())
    if torn:
        print(f"hetustory: {torn} torn/invalid line(s) classified "
              "(crash signatures, not reader failures)", file=out)


# ---------------------------------------------------------------------------
# offline invariant audit
# ---------------------------------------------------------------------------


def _row_ref(row: Row) -> dict:
    return {"path": row.path, "line": row.line, "rec": row.rec}


def _violation(invariant: str, message: str, rows: Iterable) -> dict:
    return {"invariant": invariant, "message": message,
            "rows": [_row_ref(r) for r in rows]}


def _last_per(rows: Iterable, key_fn) -> dict:
    out: dict = {}
    for r in rows:
        k = key_fn(r.rec)
        if k is not None:
            out[k] = r
    return out


def _audit_push_accounting(led: dict, violations: list, notes: list) -> None:
    """`pushes_ok == Σ(updates − restored)` — the quiesce algebra recovery
    and chaos assert live (PR 15/16), recomputed from the final metrics
    snapshots alone. Needs every rank's closing `final` row (a crashed run
    has no quiesced endpoint to compare) and the pushes_ok gauge."""
    finals = _last_per((r for r in led["metrics"]
                        if r.rec.get("kind") == "final"),
                       lambda rec: rec.get("rank"))
    servers = _last_per((r for r in led["metrics"]
                         if r.rec.get("kind") == "ps_server"),
                        lambda rec: rec.get("server"))
    if not finals or not servers:
        notes.append("push-accounting: skipped (no final/ps_server rows)")
        return
    pushes = {}
    for rank, row in finals.items():
        m = row.rec.get("metrics") or {}
        if "hetu_ps_pushes_ok_total" in m:
            pushes[rank] = (float(m["hetu_ps_pushes_ok_total"]), row)
    if not pushes:
        notes.append("push-accounting: skipped (no pushes_ok gauge — "
                     "pre-PR 20 run)")
        return
    total_pushed = sum(v for v, _ in pushes.values())
    applied = sum(float(r.rec.get("updates", 0))
                  - max(float(r.rec.get("restored_updates", 0)), 0.0)
                  for r in servers.values())
    if total_pushed != applied:
        worst = max(servers.values(), key=lambda r: r.rec.get("ts", 0))
        first_rank = next(iter(pushes.values()))[1]
        violations.append(_violation(
            "push-accounting",
            f"Σ pushes_ok across {len(pushes)} rank(s) = "
            f"{total_pushed:.0f} but Σ server (updates − restored) across "
            f"{len(servers)} server(s) = {applied:.0f}",
            [first_rank, worst]))


def _audit_pilot_eras(led: dict, violations: list, notes: list) -> None:
    """Every decided pilot era must appear on BOTH sides of the actuation
    protocol: a `verdict` row in pilot.jsonl and the matching
    `pilot_<verdict>` event on the telemetry bus (the ledger row is written
    first, so only the maximal era may lack its event — the crash window).
    `failed`/`interrupted` verdicts deliberately have no event twin."""
    ledger_verdicts = {}   # era -> (verdict, row)
    for r in led["pilot"]:
        rec = r.rec
        if rec.get("phase") == "verdict" and rec.get("era") is not None:
            ledger_verdicts[int(rec["era"])] = (rec.get("verdict"), r)
    event_verdicts = {}    # era -> (verdict, row)
    for r in led["metrics"]:
        rec = r.rec
        name = rec.get("name", "")
        if rec.get("kind") == "event" and name.startswith("pilot_") \
                and name[6:] in ("commit", "rollback", "regressed") \
                and rec.get("era") is not None:
            event_verdicts[int(rec["era"])] = (name[6:], r)
    max_era = max(ledger_verdicts) if ledger_verdicts else -1
    for era, (verdict, row) in sorted(ledger_verdicts.items()):
        if verdict in ("failed", "interrupted"):
            continue
        got = event_verdicts.get(era)
        if got is None:
            if era == max_era:
                notes.append(f"pilot-era-consistency: era {era} verdict "
                             f"'{verdict}' has no bus event (crash window "
                             "on the maximal era — tolerated)")
            else:
                violations.append(_violation(
                    "pilot-era-consistency",
                    f"pilot.jsonl era {era} decided '{verdict}' but no "
                    f"pilot_{verdict} event reached the telemetry bus",
                    [row]))
        elif got[0] != verdict:
            violations.append(_violation(
                "pilot-era-consistency",
                f"era {era}: ledger verdict '{verdict}' != bus event "
                f"'pilot_{got[0]}'", [row, got[1]]))
    for era, (verdict, row) in sorted(event_verdicts.items()):
        if era not in ledger_verdicts:
            violations.append(_violation(
                "pilot-era-consistency",
                f"pilot_{verdict} event for era {era} has no pilot.jsonl "
                "verdict row (the ledger write precedes the event — this "
                "order cannot happen on a healthy run)", [row]))


def _audit_manifests(led: dict, violations: list, notes: list) -> None:
    """Every committed job-epoch manifest must name only durable artifacts:
    the epoch directory, each server snapshot's `manifest.bin`, the
    per-server LATEST pointer flips, each worker state file — the
    stdlib-only mirror of recovery._manifest_complete (recovery.py needs
    numpy, which this login-node CLI must not)."""
    for row in led["job_manifest"]:
        m = row.rec
        if m.get("format") != 1:
            notes.append(f"epoch-manifest-complete: {row.path}: unknown "
                         f"manifest format {m.get('format')!r} (skipped)")
            continue
        jobdir = os.path.dirname(row.path)
        edir = os.path.join(jobdir, f"epoch_{m.get('epoch')}")
        missing = None
        if not os.path.isdir(edir):
            missing = f"epoch dir {edir}"
        else:
            for s in m.get("servers", []):
                snap = os.path.join(edir, str(s.get("snapshot", "")),
                                    "manifest.bin")
                ptr = os.path.join(edir, f"LATEST_s{s.get('rank')}")
                if not os.path.isfile(snap):
                    missing = f"server snapshot manifest {snap}"
                    break
                if not os.path.isfile(ptr):
                    missing = f"pointer flip {ptr}"
                    break
            else:
                for w in m.get("workers", []):
                    sf = os.path.join(edir, str(w.get("state_file", "")))
                    if not os.path.isfile(sf):
                        missing = f"worker state {sf}"
                        break
        if missing:
            violations.append(_violation(
                "epoch-manifest-complete",
                f"committed manifest for epoch {m.get('epoch')} (step "
                f"{m.get('step')}) references a missing artifact: "
                f"{missing}", [row]))


# flight-flush reason prefix -> event names that must accompany it on the
# telemetry bus (the flush and the event are written by the same abort path)
_FLIGHT_EVENTS = {
    "watchdog": ("watchdog_fire",),
    "preempted": ("preempted",),
    "anomaly": ("anomaly", "nan_provenance"),
    "resize": ("resize_drain", "resize_commit", "resize_abort",
               "resize_decommissioned"),
    "slo_breach": ("slo_breach",),
}


def _audit_flight(led: dict, violations: list, notes: list) -> None:
    """A flight-ring flush is the *effect* of an abort path whose *cause*
    is a bus event from the same rank; a doc with no cause means the event
    write was lost. Also re-checks the ring bound: a flush can never hold
    more records than its configured window `k`."""
    events_by_rank: dict = {}
    for r in led["metrics"]:
        if r.rec.get("kind") == "event":
            events_by_rank.setdefault(r.rec.get("rank"), []).append(r)
    for row in led["flight"]:
        doc = row.rec
        k = doc.get("k")
        recs = doc.get("records") or []
        if isinstance(k, int) and len(recs) > k:
            violations.append(_violation(
                "flight-event-consistency",
                f"flight doc holds {len(recs)} records but its ring bound "
                f"is k={k}", [row]))
        reason = str(doc.get("reason", "")).split(":", 1)[0]
        expected = _FLIGHT_EVENTS.get(reason)
        if expected is None:
            if reason != "crash":   # crash flush may precede a restart
                notes.append(f"flight-event-consistency: unrecognized "
                             f"flush reason {doc.get('reason')!r} "
                             f"({row.path})")
            continue
        rank = doc.get("rank")
        cands = [e for e in events_by_rank.get(rank, [])
                 if e.rec.get("name") in expected]
        if not cands:
            violations.append(_violation(
                "flight-event-consistency",
                f"flight flush reason={doc.get('reason')!r} on rank {rank} "
                f"has no {' / '.join(expected)} event on the bus",
                [row]))


def _audit_eras(led: dict, violations: list, notes: list) -> None:
    """Era sequencing, the exactly-once backbone every resize rides: per
    rank, committed world versions strictly increase (a duplicate commit
    would double-count an era partition); each commit is preceded by its
    drain; all ranks agree on the committed world's shape."""
    commits: dict = {}   # rank -> [(world_version, row)]
    drains: dict = {}    # rank -> {pending_version}
    world_shape: dict = {}   # world_version -> ((nw, ns), row)
    for r in led["metrics"]:
        rec = r.rec
        if rec.get("kind") != "event":
            continue
        name, rank = rec.get("name"), rec.get("rank")
        if name == "resize_commit" and rec.get("world_version") is not None:
            wv = int(rec["world_version"])
            commits.setdefault(rank, []).append((wv, r))
            shape = (rec.get("n_workers"), rec.get("n_servers"))
            if shape != (None, None):
                prev = world_shape.get(wv)
                if prev is not None and prev[0] != shape:
                    violations.append(_violation(
                        "era-sequencing",
                        f"ranks disagree on world {wv}'s shape: "
                        f"{prev[0]} vs {shape}", [prev[1], r]))
                else:
                    world_shape[wv] = (shape, r)
        elif name == "resize_drain":
            v = rec.get("pending_version")
            if v is not None:
                drains.setdefault(rank, set()).add(int(v))
    for rank, seq in commits.items():
        seen: dict = {}
        for wv, row in seq:     # file order == write order
            if wv in seen:
                violations.append(_violation(
                    "era-sequencing",
                    f"rank {rank} committed world {wv} twice — era "
                    "partition would be consumed twice", [seen[wv], row]))
                continue
            if seen and wv <= max(seen):
                violations.append(_violation(
                    "era-sequencing",
                    f"rank {rank} commit order regressed: world {wv} "
                    f"after {max(seen)}",
                    [seen[max(seen)], row]))
            if wv not in drains.get(rank, set()):
                violations.append(_violation(
                    "era-sequencing",
                    f"rank {rank} committed world {wv} with no preceding "
                    "resize_drain for it", [row]))
            seen[wv] = row


def audit(dir_path: str):
    """Recompute every cross-ledger invariant from the artifacts alone.
    Returns ``(violations, notes)`` — each violation names the invariant
    and carries the ledger rows (path:line + record) that contradict."""
    led = load_ledgers(dir_path)
    violations: list = []
    notes: list = []
    for check in (_audit_push_accounting, _audit_pilot_eras,
                  _audit_manifests, _audit_flight, _audit_eras):
        check(led, violations, notes)
    return violations, notes


def render_audit(dir_path: str, violations: list, notes: list,
                 out=sys.stdout) -> int:
    for v in violations:
        print(f"hetustory --audit: VIOLATION [{v['invariant']}] "
              f"{v['message']}", file=out)
        for ref in v["rows"]:
            print(f"    {ref['path']}:{ref['line']}: "
                  f"{json.dumps(ref['rec'], default=str)[:300]}", file=out)
    for n in notes:
        print(f"hetustory --audit: note: {n}", file=out)
    verdict = "FAIL" if violations else "OK"
    print(f"hetustory --audit: {verdict} — {len(violations)} violation(s), "
          f"{len(notes)} note(s) over {dir_path}", file=out)
    return 1 if violations else 0


# ---------------------------------------------------------------------------
# incident reports
# ---------------------------------------------------------------------------

INCIDENT_SCHEMA = 1
_INCIDENT_K = 8          # ± steps collected around the incident step
_INCIDENT_TAIL = 32      # rows per source when no step anchors the window


def incident_enabled() -> bool:
    """Abort-path incident capture is on unless explicitly disabled —
    writing one JSON file while the process is already dying is the cheap
    half of a post-mortem."""
    return os.environ.get("HETU_STORY_INCIDENT", "1").strip().lower() \
        not in ("0", "false", "no", "off")


def write_incident(dir_path: str, reason: str, step=None, rank=None,
                   k: Optional[int] = None, extra: Optional[dict] = None):
    """Collect the ±k-step window around (step, rank) from every registered
    ledger into one ``incident-<ms>-<reason>.json`` (tmp + atomic rename,
    the doc-family convention). Called from abort paths — never raises;
    returns the written path or None."""
    try:
        if k is None:
            try:
                k = int(os.environ.get("HETU_STORY_K", _INCIDENT_K))
            except ValueError:
                k = _INCIDENT_K
        led = load_ledgers(dir_path)
        sources: dict = {}
        for family, rows in led.items():
            picked: list = []
            if step is not None:
                for r in rows:
                    key = causal_key(r.rec)
                    s = key.get("step")
                    if s is not None and abs(s - int(step)) <= k:
                        picked.append(r)
            if not picked:     # no step coords (or step unknown): the tail
                picked = [r for r in rows
                          if r.rec.get("kind") != "step"][-_INCIDENT_TAIL:]
            if picked:
                sources[family] = [
                    {"path": r.path, "line": r.line, "rec": r.rec}
                    for r in picked[-4 * _INCIDENT_TAIL:]]
        doc = {"schema": INCIDENT_SCHEMA, "reason": str(reason),
               "ts": round(time.time(), 3), "step": step, "rank": rank,
               "k": k, "run_id": os.environ.get("HETU_RUN_ID"),
               "inc": os.environ.get("HETU_RUN_INCARNATION"),
               "counts": {f: len(v) for f, v in sources.items()},
               "sources": sources}
        if extra:
            doc["extra"] = extra
        safe = "".join(c if c.isalnum() or c in "-_" else "_"
                       for c in str(reason))[:40]
        path = os.path.join(
            dir_path, f"incident-{time.time_ns() // 10**6}-{safe}.json")
        fd, tmp = tempfile.mkstemp(dir=dir_path, suffix=".tmp")
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f, default=str)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return path
    except Exception:  # noqa: BLE001 — the abort must proceed regardless
        return None


def incident_files(dir_path: str) -> list:
    return sorted(glob.glob(os.path.join(dir_path, "incident-*.json")))


def render_incident(path: str, out=sys.stdout) -> int:
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"hetustory --incident: cannot read {path}: {e}", file=out)
        return 1
    print(f"hetustory incident: reason={doc.get('reason')!r} "
          f"step={doc.get('step')} rank={doc.get('rank')} "
          f"±{doc.get('k')} steps  run_id={doc.get('run_id')} "
          f"inc={doc.get('inc')}", file=out)
    merged: list = []
    for family, refs in (doc.get("sources") or {}).items():
        print(f"  {family}: {len(refs)} row(s)", file=out)
        for ref in refs:
            rec = ref.get("rec", {})
            ts = rec.get("ts") or rec.get("flushed_ts") or 0
            try:
                ts = float(ts)
            except (TypeError, ValueError):
                ts = 0.0
            merged.append((ts, family, rec))
    merged.sort(key=lambda x: x[0])
    for ts, family, rec in merged[-80:]:
        print(f"    {ts:14.3f} [{family:>13}] {_one_line(family, rec)}",
              file=out)
    return 0


# ---------------------------------------------------------------------------
# cross-run diff
# ---------------------------------------------------------------------------


def _profiler_mod():
    """profiler.py (the gate's home), importable from BOTH contexts: inside
    the package, or standalone when bin/hetustory loaded this file by path
    (profiler is stdlib-only at module level — the hetutop precedent)."""
    try:
        from . import profiler
        return profiler
    except ImportError:
        import importlib.util
        mod = sys.modules.get("_hetustory_profiler")
        if mod is not None:
            return mod
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "profiler.py")
        spec = importlib.util.spec_from_file_location(
            "_hetustory_profiler", path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules["_hetustory_profiler"] = mod
        spec.loader.exec_module(mod)
        return mod


_PLAN_FIELDS = ("mesh", "comm_mode", "comm_quant", "zero1", "remat",
                "predicted_step_ms", "n_servers", "n_workers")


def _episode_counts(led: dict) -> dict:
    """The structural story of a run: how many times each subsystem acted."""
    out = collections.Counter()
    for r in led["metrics"]:
        rec = r.rec
        kind = rec.get("kind")
        if kind == "event":
            name = rec.get("name", "")
            if name in ("resize_commit", "resize_abort", "anomaly",
                        "rollback", "restart", "preempted", "watchdog_fire",
                        "plan_divergence", "slo_breach", "emergency_save"):
                out[name] += 1
            elif name.startswith("pilot_"):
                out[name] += 1
        elif kind == "step":
            out["steps"] += 1
        elif kind == "watch" and rec.get("divergence"):
            out["watch_divergence_rows"] += 1
    out["straggler"] = sum(1 for r in led["trail_events"]
                           if r.rec.get("kind") == "straggler")
    out["flight_flushes"] = len(led["flight"])
    out["job_epochs"] = len(led["job_manifest"])
    for r in led["pilot"]:
        if r.rec.get("phase") == "verdict":
            out[f"pilot_era_{r.rec.get('verdict')}"] += 1
    return dict(out)


def _pctl(vals: list, p: float) -> float:
    s = sorted(vals)
    return s[min(len(s) - 1, max(0, int(round(p / 100.0 * (len(s) - 1)))))]


def _run_facts(path: str) -> dict:
    """Everything --diff compares about one run: gate cells (metric level)
    plus plan and episode structure (ledger level). ``path`` is a telemetry
    directory or any summary artifact profiler.load_summary accepts."""
    prof = _profiler_mod()
    cells, meta = prof.load_summary(path)
    facts = {"path": path, "cells": dict(cells), "meta": meta, "plan": {},
             "episodes": {}, "final_step": None}
    if os.path.isdir(path):
        led = load_ledgers(path)
        plan = None
        step_ms: list = []
        for r in led["metrics"]:
            if r.rec.get("kind") == "plan":
                plan = r.rec
            elif r.rec.get("kind") == "step":
                s = r.rec.get("step")
                if isinstance(s, int):
                    facts["final_step"] = max(facts["final_step"] or 0, s)
                try:
                    step_ms.append(float(r.rec["step_ms"]))
                except (KeyError, TypeError, ValueError):
                    pass
        if plan:
            facts["plan"] = {k: plan.get(k) for k in _PLAN_FIELDS
                             if plan.get(k) is not None}
        facts["episodes"] = _episode_counts(led)
        if step_ms:
            # a run without hetuwatch rows still gates on its raw step
            # stream (keys end in _ms -> lower-is-better per the gate's
            # direction rules); watch cells, when present, ride alongside
            facts["cells"]["story_steps"] = {
                "p50_step_ms": round(_pctl(step_ms, 50), 4),
                "p99_step_ms": round(_pctl(step_ms, 99), 4),
                "step_rows": len(step_ms)}
            facts["meta"] = {"incomplete": False, "why": None}
    return facts


def diff_runs(a: str, b: str, tolerance_pct: float = 10.0) -> dict:
    """Runs A and B aligned by step/era: the gate's direction-aware metric
    comparison (same regression/improvement semantics as
    ``hetuprof --gate``), plus what the flat numbers can't say — plan
    deltas and episode-count deltas, the *why* behind a step-time shift."""
    fa, fb = _run_facts(a), _run_facts(b)
    prof = _profiler_mod()
    gate = prof.gate(fa["cells"], fb["cells"], tolerance_pct=tolerance_pct,
                     baseline_meta=fa["meta"], current_meta=fb["meta"])
    plan_delta = {}
    for k in sorted(set(fa["plan"]) | set(fb["plan"])):
        va, vb = fa["plan"].get(k), fb["plan"].get(k)
        if va != vb:
            plan_delta[k] = [va, vb]
    episode_delta = {}
    for k in sorted(set(fa["episodes"]) | set(fb["episodes"])):
        va, vb = fa["episodes"].get(k, 0), fb["episodes"].get(k, 0)
        if va != vb:
            episode_delta[k] = [va, vb]
    return {"a": a, "b": b, "gate": {
                "status": gate.status, "verdict": gate.verdict,
                "compared": gate.compared,
                "regressions": gate.regressions,
                "improvements": gate.improvements,
                "report": gate.report()},
            "plan_delta": plan_delta, "episode_delta": episode_delta,
            "final_steps": [fa["final_step"], fb["final_step"]]}


def render_diff(d: dict, out=sys.stdout) -> int:
    print(f"hetustory --diff: A={d['a']}  B={d['b']}", file=out)
    print(d["gate"]["report"], file=out)
    if d["plan_delta"]:
        print("plan deltas (A -> B):", file=out)
        for k, (va, vb) in d["plan_delta"].items():
            print(f"  {k}: {va!r} -> {vb!r}", file=out)
    if d["episode_delta"]:
        print("episode deltas (A -> B):", file=out)
        for k, (va, vb) in d["episode_delta"].items():
            print(f"  {k}: {va} -> {vb}", file=out)
    if not d["plan_delta"] and not d["episode_delta"]:
        print("no structural deltas (same plan, same episode counts)",
              file=out)
    return 0 if d["gate"]["status"] == 0 else d["gate"]["status"]


# ---------------------------------------------------------------------------
# --check: jax-free self-test (the hetuwatch/hetupilot CI pattern)
# ---------------------------------------------------------------------------


def _fixture_run(tmp: str, rank: int = 0, step_ms: float = 10.0,
                 corrupt: bool = False) -> None:
    """One synthetic-but-schema-true run directory for the self-test."""
    mpath = os.path.join(tmp, f"metrics-r{rank}.jsonl")
    with open(mpath, "w") as f:
        def w(rec):
            f.write(json.dumps(rec, separators=(",", ":")) + "\n")
        ts = 1000.0
        w({"ts": ts, "rank": rank, "kind": "run_info",
           "device_kind": "cpu"})
        w({"ts": ts, "rank": rank, "kind": "plan", "mesh": [1, 1, 1],
           "comm_mode": "ps", "predicted_step_ms": step_ms})
        for s in range(8):
            w({"ts": ts + s, "rank": rank, "kind": "step", "sub": "train",
               "step": s, "step_ms": step_ms})
        w({"ts": ts + 3.5, "rank": rank, "kind": "event",
           "name": "resize_drain", "step": 3, "pending_version": 1})
        w({"ts": ts + 3.6, "rank": rank, "kind": "event",
           "name": "resize_commit", "step": 4, "world_version": 1,
           "n_workers": 1, "n_servers": 1})
        w({"ts": ts + 6.0, "rank": rank, "kind": "event",
           "name": "pilot_commit", "era": 0, "step": 6, "ratio": 0.9})
        w({"ts": ts + 7.9, "rank": rank, "kind": "ps_server", "server": 0,
           "updates": 80 if not corrupt else 79, "restored_updates": -1})
        w({"ts": ts + 8.0, "rank": rank, "kind": "final",
           "metrics": {"hetu_ps_pushes_ok_total": 80,
                       "step_ms_p50": step_ms}})
        f.write('{"ts": 1008.1, "kind": "step", "step": 9, "trunc')
    with open(os.path.join(tmp, "pilot.jsonl"), "w") as f:
        for rec in ({"ts": 1005.0, "era": 0, "phase": "propose",
                     "step": 5, "delta": {"kind": "comm_mode_flip"}},
                    {"ts": 1005.1, "era": 0, "phase": "actuate",
                     "step": 5, "delta": {"kind": "comm_mode_flip"}},
                    {"ts": 1006.0, "era": 0, "phase": "verdict",
                     "verdict": "commit", "step": 6,
                     "delta": {"kind": "comm_mode_flip"}}):
            f.write(json.dumps(rec, separators=(",", ":")) + "\n")
    with open(os.path.join(tmp, f"trail-client-r{rank}.jsonl"), "w") as f:
        f.write(json.dumps({"kind": "anchor", "rank": rank,
                            "mono_us": 500_000_000,
                            "wall_s": 1000.0, "boot_id": "fixture-boot"},
                           separators=(",", ":")) + "\n")
    with open(os.path.join(tmp, f"flight-r{rank}.json"), "w") as f:
        json.dump({"schema": 1, "reason": "preempted", "rank": rank,
                   "k": 4, "flushed_ts": 1007.0, "flushes": 1,
                   "records": [{"step": 6}, {"step": 7}]}, f)
    # the preempted flush needs its bus event
    with open(mpath, "r+") as f:
        lines = f.readlines()
    lines.insert(-1, json.dumps(
        {"ts": 1007.0, "rank": rank, "kind": "event", "name": "preempted",
         "step": 7, "signum": 15}, separators=(",", ":")) + "\n")
    with open(mpath, "w") as f:
        f.writelines(lines)


def self_check(out=sys.stdout) -> int:
    """End-to-end proof on synthetic fixtures, no cluster, no jax: reader
    classification, rotation recovery, timeline, audit 0/1, incident
    round-trip, diff regression detection. CI's `bin/hetustory --check`."""
    import shutil
    failures: list = []

    def check(name, ok, detail=""):
        tag = "ok" if ok else "FAIL"
        print(f"hetustory --check: {name}: {tag}"
              f"{(' — ' + detail) if detail and not ok else ''}", file=out)
        if not ok:
            failures.append(name)

    base = tempfile.mkdtemp(prefix="hetustory-check-")
    try:
        # 1. torn-tail classification vs mid-file corruption
        p = os.path.join(base, "probe.jsonl")
        with open(p, "w") as f:
            f.write('{"kind":"step","step":1}\n')
            f.write('garbage not json\n')
            f.write('[1,2,3]\n')
            f.write('{"kind":"step","step":2}\n')
            f.write('{"kind":"step","step":3,"tor')
        errs: list = []
        recs = read_jsonl(p, errs)
        reasons = sorted(e["reason"] for e in errs)
        check("torn-tail classification",
              len(recs) == 2 and reasons ==
              ["invalid-json", "not-object", "torn-tail"],
              f"recs={len(recs)} reasons={reasons}")

        # 2. rotation-under-reader: records written between the reader's
        # poll and the rename must NOT be lost
        rp = os.path.join(base, "rot.jsonl")
        fol = LedgerFollower()
        with open(rp, "w") as f:
            f.write('{"n":1}\n')
        got = [r["n"] for r in fol.poll(rp)]
        with open(rp, "a") as f:
            f.write('{"n":2}\n{"n":3}\n')   # unseen, then rotated away
        os.replace(rp, rp + ".1")
        with open(rp, "w") as f:
            f.write('{"n":4}\n')
        got += [r["n"] for r in fol.poll(rp)]
        check("rotation-under-reader recovery", got == [1, 2, 3, 4],
              f"got={got}")

        # 3/4. clean run: timeline renders, audit passes
        clean = os.path.join(base, "clean")
        os.makedirs(clean)
        _fixture_run(clean)
        tl = load_timeline(clean)
        check("timeline merge",
              len(tl["entries"]) >= 8 and tl["clock"]["comparable"]
              and any(e["src"] == "pilot" for e in tl["entries"])
              and any(e["src"] == "flight" for e in tl["entries"]),
              f"entries={len(tl['entries'])}")
        v, _ = audit(clean)
        check("audit clean run", not v,
              v[0]["invariant"] if v else "")

        # 5. seeded single-row corruption: audit names the invariant + rows
        bad = os.path.join(base, "bad")
        os.makedirs(bad)
        _fixture_run(bad, corrupt=True)
        v, _ = audit(bad)
        check("audit seeded corruption",
              len(v) == 1 and v[0]["invariant"] == "push-accounting"
              and len(v[0]["rows"]) == 2,
              f"violations={[x['invariant'] for x in v]}")

        # 6. incident write + render round-trip
        ip = write_incident(clean, "check-probe", step=6, rank=0, k=2)
        ok = ip is not None and os.path.exists(ip)
        nsrc = 0
        if ok:
            with open(ip) as f:
                doc = json.load(f)
            nsrc = len(doc.get("sources", {}))
            ok = nsrc >= 3 and doc["reason"] == "check-probe"
        check("incident round-trip", ok, f"sources={nsrc}")
        if ok:
            import io
            render_incident(ip, out=io.StringIO())

        # 7. diff: a seeded step-time regression surfaces with plan context
        slow = os.path.join(base, "slow")
        os.makedirs(slow)
        _fixture_run(slow, step_ms=14.0)
        d = diff_runs(clean, slow, tolerance_pct=10.0)
        regressed = [r.get("metric", "") for r in d["gate"]["regressions"]]
        check("diff regression detection",
              d["gate"]["status"] == 1
              and any("step_ms" in m for m in regressed)
              and "predicted_step_ms" in d["plan_delta"],
              f"status={d['gate']['status']} regressed={regressed}")
    finally:
        shutil.rmtree(base, ignore_errors=True)
    n = 7
    if failures:
        print(f"hetustory --check: FAIL ({len(failures)}/{n}): "
              f"{', '.join(failures)}", file=out)
        return 1
    print(f"hetustory --check: all {n} checks passed", file=out)
    return 0


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def _parse_step_range(spec: str):
    a, _, b = spec.partition(":")
    lo = int(a) if a else 0
    hi = int(b) if b else sys.maxsize
    return (lo, hi)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="hetustory",
        description="unified run ledger: causal timeline, offline invariant "
                    "audit, incident reports, cross-run diff")
    ap.add_argument("dir", nargs="?", help="telemetry directory")
    ap.add_argument("--step", metavar="A:B",
                    help="include step records in [A, B]")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    ap.add_argument("--audit", action="store_true",
                    help="offline invariant audit (exit 0 ok / 1 violated)")
    ap.add_argument("--incident", nargs="?", const="", metavar="FILE",
                    help="render an incident report (default: latest in DIR)")
    ap.add_argument("--diff", nargs=2, metavar=("RUN_A", "RUN_B"),
                    help="cross-run diff (telemetry dirs or bench summaries)")
    ap.add_argument("--tolerance", type=float, default=10.0,
                    help="gate tolerance %% for --diff (default 10)")
    ap.add_argument("--check", action="store_true",
                    help="jax-free self-test on synthetic fixtures")
    args = ap.parse_args(argv)

    if args.check:
        return self_check()
    if args.diff:
        d = diff_runs(args.diff[0], args.diff[1],
                      tolerance_pct=args.tolerance)
        if args.json:
            print(json.dumps(d, indent=2, default=str))
            return 0 if d["gate"]["status"] == 0 else d["gate"]["status"]
        return render_diff(d)
    if args.dir is None:
        ap.error("DIR is required (except with --diff/--check)")
    if args.audit:
        violations, notes = audit(args.dir)
        if args.json:
            print(json.dumps({"violations": violations, "notes": notes},
                             indent=2, default=str))
            return 1 if violations else 0
        return render_audit(args.dir, violations, notes)
    if args.incident is not None:
        path = args.incident
        if not path:
            found = incident_files(args.dir)
            if not found:
                print(f"hetustory --incident: no incident-*.json under "
                      f"{args.dir}", file=sys.stderr)
                return 1
            path = found[-1]
        if args.json:
            with open(path) as f:
                sys.stdout.write(f.read())
            return 0
        return render_incident(path)
    tl = load_timeline(args.dir,
                       _parse_step_range(args.step) if args.step else None)
    if args.json:
        slim = [{k: v for k, v in e.items() if k not in ("rec", "_loc")}
                for e in tl["entries"]]
        print(json.dumps({"entries": slim,
                          "comparable": tl["clock"]["comparable"]},
                         indent=2, default=str))
        return 0
    render_timeline(tl)
    return 0


if __name__ == "__main__":
    sys.exit(main())
