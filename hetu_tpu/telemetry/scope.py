"""hetuscope — in-graph training-dynamics introspection, NaN/Inf provenance,
and the crash flight recorder (docs/OBSERVABILITY.md "numeric health").

Three pieces, armed together by ``HetuConfig(introspect=...)`` /
``HETU_INTROSPECT`` (off by default, same None-check-only contract as
telemetry — with introspection off the executor performs ZERO scope work,
asserted by tests/test_scope.py):

- **In-graph stats** — on a step cadence the executor compiles a stats
  variant of the jitted step that fuses per-parameter and per-op scalar
  reductions into the program (grad global/per-layer norm, update/param
  ratio, activation rms/absmax, %-nonfinite), keyed by the ``named_scope``
  op identity hetuprof already uses. The whole table returns as ONE extra
  fetch per cadence step — no per-stat host round trips.
  :func:`traced_stats` builds the reductions (called during jit trace);
  :func:`host_stats` materializes the table host-side.
- **NaN/Inf provenance** — when the anomaly guard trips, the executor
  re-runs the failing step bit-identically (same pre-step state, batch and
  RNG fold; the guard's gated commit preserved all three) through a
  no-donation debug variant of the same stats program, and
  :func:`find_culprit` names the FIRST op in topological order that
  emitted non-finite values from all-finite inputs — turning "step 412 was
  NaN" into "layer3/matmul overflowed, input absmax 6.4e4".
- **Flight recorder** — :class:`FlightRecorder` keeps a bounded ring of
  the last K step records (loss, grad norm, step time, lr, dataloader
  cursors + batch checksum, finiteness) and flushes it atomically to
  ``<telemetry_dir>/flight/flight-r<rank>.json`` on anomaly, watchdog
  abort, preemption (SIGTERM/SIGINT) and crash-restart — every resilience
  abort path calls :func:`flush_flight`.

``bin/hetuscope`` renders the post-mortem report from a telemetry
directory (flight ring + ``kind:"scope"`` JSONL records +
``nan_provenance`` events); ``--check`` is the CI schema smoke.

Stdlib-only at import (``bin/hetuscope`` loads this file by path, jax-free,
like ``bin/hetuprof`` does with profiler.py); jax is imported lazily inside
the two traced/host helpers the executor calls.
"""
from __future__ import annotations

import argparse
import collections
import glob
import json
import os
import sys
import threading
import time
from typing import Any, Optional

DEFAULT_CADENCE = 10          # steps between in-graph stats fetches
DEFAULT_FLIGHT_K = 64         # flight-ring depth (HETU_FLIGHT_K)
FLIGHT_SCHEMA = 1

_OFFISH = ("", "0", "off", "false", "no", "none")
_ONISH = ("1", "on", "true", "yes")


def resolve_introspect(value=None) -> int:
    """One spelling of the arming resolution, returning the stats cadence in
    steps (0 = off). ``True``/``"on"``/``"1"`` arm at :data:`DEFAULT_CADENCE`
    (overridable via ``HETU_INTROSPECT_EVERY``); an integer (or numeric
    string) >= 1 is an explicit cadence; ``None`` falls back to the
    ``HETU_INTROSPECT`` env var; anything falsy is off."""
    if value is None:
        value = os.environ.get("HETU_INTROSPECT", "")
    if isinstance(value, bool):
        value = "on" if value else "off"
    if isinstance(value, (int, float)):
        n = int(value)
        if n < 0:
            raise ValueError(f"introspect cadence must be >= 0, got {n}")
        return n
    value = str(value).strip().lower()
    if value in _OFFISH:
        return 0
    if value in _ONISH:
        return max(1, int(os.environ.get("HETU_INTROSPECT_EVERY",
                                         str(DEFAULT_CADENCE))))
    n = int(value)
    if n < 0:   # same validation as the int branch — "-5" must not arm
        raise ValueError(f"introspect cadence must be >= 0, got {n}")
    return max(1, n)


def json_num(v):
    """A number as a strict-JSON-safe value: non-finite floats become the
    strings "NaN"/"Infinity"/"-Infinity" (Python's ``float()`` parses them
    back). The post-mortem artifacts exist precisely for runs whose losses
    ARE NaN — bare NaN tokens would make them invalid for every non-Python
    consumer (jq, browsers, log pipelines)."""
    try:
        f = float(v)
    except (TypeError, ValueError):
        return v
    if f != f:
        return "NaN"
    if f == float("inf"):
        return "Infinity"
    if f == float("-inf"):
        return "-Infinity"
    return f


def json_safe(obj):
    """Recursively apply :func:`json_num` to a dict/list tree (copies —
    never mutates the flight ring's live records)."""
    if isinstance(obj, dict):
        return {k: json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [json_safe(v) for v in obj]
    if isinstance(obj, float):
        return json_num(obj)
    return obj


def default_rank() -> int:
    """Rank identity for flight file names — the launcher's WORKER_ID, same
    convention as the telemetry package (re-inlined: this module is loaded
    by file path from ``bin/hetuscope``, outside the package)."""
    try:
        return int(os.environ.get("WORKER_ID", "0"))
    except ValueError:
        return 0


# ---------------------------------------------------------------------------
# traced reductions (called from INSIDE the executor's jit trace)
# ---------------------------------------------------------------------------

def traced_stats(op_entries, param_entries, loss_val=None,
                 grad_global_norm=None):
    """The fused in-graph stats, PACKED: returns ``(spec, vector)`` where
    ``vector`` is one stacked f32 array of every scalar reduction and
    ``spec`` names each slot. The step program returns the single vector
    (literally one extra fetch — materializing dozens of separate device
    scalars measured ~3x the whole table's cost); ``spec`` is trace-time
    metadata the executor stores host-side and feeds to
    :func:`host_stats`.

    ``op_entries`` — ``[(scope_key, traced_value)]`` for every float-typed
    node output (activations, grads, comm outputs, fed inputs), in
    topological order. ``param_entries`` — ``[(name, grad, old, new)]`` per
    trainable parameter (``old``/``new`` may be None for PS-resident ones).
    ``grad_global_norm`` reuses a norm an optimizer with ``clip_grad_norm``
    already computed (one computation, two consumers) instead of
    re-reducing."""
    import jax.numpy as jnp
    eps = 1e-12
    spec: list = []
    vals: list = []

    def emit(path, v):
        spec.append(path)
        vals.append(v.astype(jnp.float32))

    for key, v in op_entries:
        vf = v.astype(jnp.float32)
        fin = jnp.isfinite(vf)
        safe = jnp.where(fin, vf, 0.0)
        # absmax/rms over the FINITE values: a single inf must not erase
        # the "how close to overflow was the rest" signal
        emit(("ops", key, "absmax"), jnp.max(jnp.abs(safe)))
        emit(("ops", key, "rms"), jnp.sqrt(jnp.mean(safe * safe)))
        emit(("ops", key, "nonfinite"),
             jnp.mean((~fin).astype(jnp.float32)))
    sq_terms = []
    for name, grad, old, new in param_entries:
        gf = grad.astype(jnp.float32)
        sq = jnp.sum(gf * gf)
        sq_terms.append(sq)
        emit(("params", name, "grad_norm"), jnp.sqrt(sq))
        if old is not None and new is not None:
            of = old.astype(jnp.float32)
            nf = new.astype(jnp.float32)
            den = jnp.sqrt(jnp.sum(of * of))
            # undefined (NaN) for an all-zero parameter — an eps
            # denominator would report a meaningless 1e10 "ratio" for
            # every zero-initialized bias; consumers filter NaN
            emit(("params", name, "update_ratio"),
                 jnp.where(den > 0,
                           jnp.sqrt(jnp.sum((nf - of) ** 2))
                           / jnp.maximum(den, eps),
                           jnp.nan))
    if grad_global_norm is not None:
        gnorm = grad_global_norm
    elif sq_terms:
        gnorm = jnp.sqrt(sum(sq_terms))
    else:
        gnorm = jnp.float32(0.0)
    emit(("grad_norm",), gnorm)
    if loss_val is not None:
        emit(("loss",), jnp.reshape(loss_val, ()))
    return spec, jnp.stack(vals)


def host_stats(spec, vec) -> dict:
    """Rebuild the nested stats dict from the packed vector — ONE host
    fetch; leaves become plain Python floats (JSON- and flight-safe)."""
    import numpy as np
    arr = np.asarray(vec)
    out: dict = {"params": {}, "ops": {}}
    for path, v in zip(spec, arr):
        v = float(v)
        if len(path) == 1:
            out[path[0]] = v
        else:
            group, key, field = path
            out[group].setdefault(key, {})[field] = v
    return out


# ---------------------------------------------------------------------------
# provenance: first non-finite op in topological order
# ---------------------------------------------------------------------------

def find_culprit(order, inputs_map, stats, step) -> dict:
    """Localize the non-finite source from a per-op stats table.

    ``order`` — scope keys in topological order; ``inputs_map`` —
    ``{scope_key: [input scope keys]}`` (both recorded by the executor at
    trace time); ``stats`` — the host-side table from :func:`host_stats`.
    The culprit is the first op whose output is non-finite while every
    table-known input is finite — everything after it is propagation, not
    cause. Returns a provenance dict (``op`` is None when the poison
    entered at the parameter-update/state level, e.g. the ``nan_grads``
    injection, which never flows through an op output)."""
    ops = stats.get("ops", {})
    bad = [k for k in order if ops.get(k, {}).get("nonfinite", 0.0) > 0.0]
    result = {
        "step": int(step),
        "nonfinite_ops": len(bad),
        "grad_norm": stats.get("grad_norm"),
        "loss": stats.get("loss"),
    }
    for k in bad:
        ins = inputs_map.get(k, [])
        if all(ops.get(i, {}).get("nonfinite", 0.0) == 0.0 for i in ins):
            result["op"] = k
            result["output"] = ops[k]
            result["inputs"] = {
                i: {"absmax": ops[i]["absmax"],
                    "nonfinite": ops[i]["nonfinite"]}
                for i in ins if i in ops}
            return result
    result["op"] = None
    result["note"] = ("no op-level culprit: non-finite values entered at "
                      "the parameter-update/optimizer-state level (e.g. an "
                      "update-level injection), not through an op output")
    return result


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

class FlightRecorder:
    """Bounded ring of per-step records, flushed atomically on abort.

    ``record`` is the hot-path mutator (a locked deque append of an
    already-host-side dict); ``flush`` writes the whole ring plus the abort
    reason to ``<dir>/flight-r<rank>.json`` via tmp+rename and NEVER raises
    — it runs on the watchdog/preemption/crash paths, where observability
    must not take recovery down with it."""

    def __init__(self, out_dir: str, rank: Optional[int] = None,
                 k: Optional[int] = None):
        self.dir = out_dir
        self.rank = default_rank() if rank is None else int(rank)
        if k is None:
            k = int(os.environ.get("HETU_FLIGHT_K", str(DEFAULT_FLIGHT_K)))
        self.k = max(1, int(k))
        self._ring: collections.deque = collections.deque(maxlen=self.k)
        self._lock = threading.Lock()
        self.flushes = 0

    @property
    def path(self) -> str:
        return os.path.join(self.dir, f"flight-r{self.rank}.json")

    def record(self, rec: dict) -> None:
        # the SAME dict object enters the ring: a deferred stats
        # resolution (Introspector.resolve_pending) mutates it in place
        rec.setdefault("ts", round(time.time(), 3))
        with self._lock:
            self._ring.append(rec)

    def records(self) -> list:
        with self._lock:
            return list(self._ring)

    def flush(self, reason: str, provenance: Optional[dict] = None
              ) -> Optional[str]:
        try:
            os.makedirs(self.dir, exist_ok=True)
            with self._lock:
                recs = list(self._ring)
                self.flushes += 1
            doc = {"schema": FLIGHT_SCHEMA, "reason": reason,
                   "rank": self.rank, "k": self.k,
                   "flushed_ts": round(time.time(), 3),
                   "flushes": self.flushes,
                   "records": json_safe(recs)}
            run_id = os.environ.get("HETU_RUN_ID")
            if run_id:
                doc["run_id"] = run_id
                try:
                    doc["inc"] = int(
                        os.environ.get("HETU_RUN_INCARNATION", "0"))
                except ValueError:
                    doc["inc"] = 0
            if provenance is not None:
                doc["provenance"] = json_safe(provenance)
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f, separators=(",", ":"), default=str)
            os.replace(tmp, self.path)
            return self.path
        except Exception:  # noqa: BLE001 — abort paths must survive this
            return None


# ---------------------------------------------------------------------------
# the Introspector: per-process hub the executor talks to
# ---------------------------------------------------------------------------

# armed introspectors, for the resilience abort hooks (flush_flight);
# normally one per process, like the telemetry singleton
_armed: list = []
_lock = threading.Lock()


class Introspector:
    """Owns the cadence, the flight ring, and the latest stats/provenance.
    Created by the Executor when ``HetuConfig(introspect=...)`` arms; the
    executor is the only writer, dashboards/post-mortems the readers."""

    def __init__(self, cadence: int, out_dir: str,
                 rank: Optional[int] = None):
        self.cadence = max(1, int(cadence))
        self.dir = out_dir
        self.flight = FlightRecorder(os.path.join(out_dir, "flight"),
                                     rank=rank)
        # deferred cadence fetch: (ring record, resolver) — materializing
        # the packed stats vector right after dispatch would SYNC on the
        # step and stall the dispatch pipeline; instead the executor
        # defers it, and it resolves at the next step boundary (the step
        # has long completed), on flush, or on first read
        self._pending: Optional[tuple] = None
        self._last_stats: Optional[dict] = None
        self.last_provenance: Optional[dict] = None
        with _lock:
            _armed.append(self)

    # -- per-step ----------------------------------------------------------
    @property
    def last_stats(self) -> Optional[dict]:
        """Latest materialized stats table (resolves any pending fetch)."""
        self.resolve_pending()
        return self._last_stats

    def defer(self, rec: dict, resolver) -> None:
        """Park a cadence step's un-materialized stats: ``resolver()``
        returns the host table (and exports it) when called."""
        self.resolve_pending()
        self._pending = (rec, resolver)

    def resolve_pending(self) -> None:
        p, self._pending = self._pending, None
        if p is None:
            return
        rec, resolver = p
        try:
            stats = resolver()
        except Exception:  # noqa: BLE001 — diagnostics only
            return
        rec["stats"] = stats       # the ring holds this same dict
        self._last_stats = stats

    def record_step(self, rec: dict, stats: Optional[dict] = None) -> None:
        """One flight-ring entry per training step; ``stats`` rides along
        immediately only when the caller already synced (a guard trip) —
        cadence steps use :meth:`defer` instead."""
        self.resolve_pending()
        if stats is not None:
            self._last_stats = stats
            rec["stats"] = stats
        self.flight.record(rec)

    def export(self, telemetry, sub: str, step: int, stats: dict) -> None:
        """Cadence-step export: ``hetu_scope_*`` gauges + one
        ``kind:"scope"`` JSONL record (ops trimmed to the interesting rows
        — every non-finite op plus the top absmax — so a wide graph does
        not bloat the stream; the full table lives in the flight ring)."""
        def fin(v):
            return v is not None and v == v and abs(v) != float("inf")

        # gauges only take FINITE values (a NaN gauge would leak bare NaN
        # tokens into every later metrics snapshot); the non-finite story
        # is told by hetu_scope_nonfinite_ops + the provenance event
        g = telemetry.metrics.gauge
        if fin(stats.get("grad_norm")):
            g("hetu_scope_grad_norm").set(stats["grad_norm"])
        if fin(stats.get("loss")):
            g("hetu_scope_loss").set(stats["loss"])
        params = stats.get("params", {})
        ratios = [r for d in params.values()
                  if fin(r := d.get("update_ratio"))]
        if ratios:
            g("hetu_scope_update_ratio_max").set(max(ratios))
        ops = stats.get("ops", {})
        if ops:
            absmaxes = [d["absmax"] for d in ops.values()
                        if fin(d.get("absmax"))]
            if absmaxes:
                g("hetu_scope_act_absmax").set(max(absmaxes))
            g("hetu_scope_nonfinite_ops").set(
                sum(1 for d in ops.values() if d["nonfinite"] > 0.0))
        telemetry.record("scope", sub=sub, step=int(step),
                         grad_norm=json_num(stats.get("grad_norm")),
                         loss=json_num(stats.get("loss")),
                         params=json_safe(params),
                         ops=json_safe(trim_ops(ops)))

    # -- abort paths -------------------------------------------------------
    def flush(self, reason: str, provenance: Optional[dict] = None):
        """Durable flush, resolving any pending stats first — EXCEPT on a
        watchdog abort, where the device is presumed wedged and a blocking
        fetch would hang the dump."""
        if reason != "watchdog":
            self.resolve_pending()
        return self.flight.flush(reason, provenance=provenance)

    def on_anomaly(self, provenance: dict, telemetry=None) -> None:
        """Guard-trip hook: record + durably flush the ring with the
        provenance verdict, and (when telemetry is on) emit the
        ``nan_provenance`` event the acceptance demo reads."""
        self.last_provenance = provenance
        self.flight.record({"kind": "provenance", **provenance})
        self.flush("anomaly", provenance=provenance)
        if telemetry is not None:
            try:
                telemetry.event("nan_provenance", **json_safe(provenance))
                telemetry.flush()
            except Exception:  # noqa: BLE001 — diagnostics only
                pass

    def close(self) -> None:
        with _lock:
            if self in _armed:
                _armed.remove(self)


def get() -> Optional[Introspector]:
    """The most recently armed introspector, or None (the per-call gate)."""
    with _lock:
        return _armed[-1] if _armed else None


def flush_flight(reason: str) -> None:
    """Flush every armed flight ring — called by the resilience abort paths
    (watchdog fire, preemption, crash-restart). Never raises."""
    with _lock:
        recs = list(_armed)
    for intro in recs:
        try:
            intro.flush(reason)
        except Exception:  # noqa: BLE001
            pass


def shutdown() -> None:
    """Detach every armed introspector (tests; also lets a long-lived
    process re-arm against a fresh directory)."""
    with _lock:
        _armed.clear()


def trim_ops(ops: dict, top: int = 8) -> dict:
    """The JSONL-worthy subset of a per-op table: every op with non-finite
    values, plus the ``top`` largest by absmax."""
    keep = {k: v for k, v in ops.items() if v.get("nonfinite", 0.0) > 0.0}
    by_absmax = sorted(ops.items(), key=lambda kv: -kv[1].get("absmax", 0.0))
    for k, v in by_absmax[:top]:
        keep.setdefault(k, v)
    return keep


# ---------------------------------------------------------------------------
# post-mortem report + CI check (bin/hetuscope)
# ---------------------------------------------------------------------------

def flight_files(dir_path: str) -> list:
    return sorted(glob.glob(os.path.join(dir_path, "flight",
                                         "flight-r*.json")))


def _load_flight(path: str, errors: list) -> Optional[dict]:
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        errors.append(f"{path}: unreadable ({e})")
        return None
    for k in ("schema", "reason", "rank", "records"):
        if k not in doc:
            errors.append(f"{path}: missing {k!r}")
            return None
    if not isinstance(doc["records"], list):
        errors.append(f"{path}: 'records' is not a list")
        return None
    for i, rec in enumerate(doc["records"]):
        if not isinstance(rec, dict):
            errors.append(f"{path}: record {i} is not an object")
            return None
        if rec.get("kind") != "provenance" and "step" not in rec:
            errors.append(f"{path}: step record {i} missing 'step'")
            return None
    return doc


def _scan_metrics(dir_path: str):
    """Scope records + nan_provenance events from the metrics JSONL (absent
    files are fine — introspection also runs with telemetry off)."""
    scopes, provs = [], []
    for path in sorted(glob.glob(os.path.join(dir_path,
                                              "metrics-r*.jsonl"))):
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if rec.get("kind") == "scope":
                        scopes.append(rec)
                    elif rec.get("kind") == "event" \
                            and rec.get("name") == "nan_provenance":
                        provs.append(rec)
        except OSError:
            continue
    return scopes, provs


def _fmt_num(v, spec=".3g") -> str:
    if v is None:
        return "n/a"
    try:
        return format(float(v), spec)
    except (TypeError, ValueError):
        return str(v)


def _render_provenance(prov: dict, lines: list) -> None:
    op = prov.get("op")
    if op:
        out = prov.get("output", {})
        lines.append(
            f"  first non-finite op (topological order): {op}"
            f"  [step {prov.get('step')}]")
        lines.append(
            f"    output: nonfinite={_fmt_num(out.get('nonfinite'), '.1%')} "
            f"absmax={_fmt_num(out.get('absmax'))} "
            f"rms={_fmt_num(out.get('rms'))}")
        for name, st in (prov.get("inputs") or {}).items():
            lines.append(
                f"    input {name}: absmax={_fmt_num(st.get('absmax'))} "
                f"nonfinite={_fmt_num(st.get('nonfinite'), '.1%')}")
    else:
        lines.append(f"  [step {prov.get('step')}] "
                     + prov.get("note", "no op-level culprit"))
    lines.append(
        f"    at trip: loss={_fmt_num(prov.get('loss'))} "
        f"grad_norm={_fmt_num(prov.get('grad_norm'))} "
        f"nonfinite ops downstream: {prov.get('nonfinite_ops')}")


def render_report(dir_path: str, last: int = 12) -> str:
    """The hetuscope post-mortem: flight ring tail, layer health, op
    health, and the provenance verdict, from whatever the directory holds."""
    lines = [f"hetuscope — numeric-health post-mortem of {dir_path}"]
    errors: list = []
    docs = [d for p in flight_files(dir_path)
            if (d := _load_flight(p, errors)) is not None]
    scopes, provs = _scan_metrics(dir_path)
    if not docs and not scopes and not provs:
        lines.append("  (no flight/flight-r*.json and no scope/"
                     "nan_provenance records — was the run armed with "
                     "HETU_INTROSPECT?)")
        return "\n".join(lines)
    for doc in docs:
        recs = doc["records"]
        steps = [r for r in recs if r.get("kind") != "provenance"]
        lines.append(
            f"rank {doc['rank']}: flight ring flushed on "
            f"{doc['reason']!r} at "
            f"{time.strftime('%H:%M:%S', time.localtime(doc.get('flushed_ts', 0)))}"
            f" ({len(steps)} step record(s), ring depth {doc.get('k')})")
        lines.append("  step     loss  grad_norm  step_ms  finite"
                     "  batch_crc32")
        for r in steps[-last:]:
            st = r.get("stats") or {}
            lines.append(
                f"  {r.get('step', '?'):>4}"
                f"  {_fmt_num(st.get('loss'), '9.4g'):>9}"
                f"  {_fmt_num(st.get('grad_norm'), '9.4g'):>9}"
                f"  {_fmt_num(r.get('step_ms'), '7.2f'):>7}"
                f"  {str(r.get('finite', '?')):>6}"
                f"  {r.get('batch_crc32', 'n/a')}")
        latest = None
        for r in reversed(steps):
            if r.get("stats"):
                latest = r["stats"]
                break
        if latest and latest.get("params"):
            lines.append("  layer health (latest stats step):")
            lines.append("    parameter            grad_norm  update/param")
            for name, d in latest["params"].items():
                lines.append(
                    f"    {name[:20]:<20} {_fmt_num(d.get('grad_norm'), '9.4g'):>9}"
                    f"  {_fmt_num(d.get('update_ratio'), '12.4g'):>12}")
        if latest and latest.get("ops"):
            ops = latest["ops"]
            nonfin = [k for k, v in ops.items()
                      if v.get("nonfinite", 0.0) > 0.0]
            hot = sorted(ops.items(),
                         key=lambda kv: -kv[1].get("absmax", 0.0))[:5]
            lines.append(
                f"  op health: {len(ops)} instrumented, "
                f"{len(nonfin)} non-finite"
                + (f" ({', '.join(nonfin[:5])})" if nonfin else ""))
            for k, v in hot:
                lines.append(f"    absmax {k}: {_fmt_num(v.get('absmax'))}"
                             f" (rms {_fmt_num(v.get('rms'))})")
        prov = doc.get("provenance")
        if prov:
            lines.append("  NaN/Inf provenance:")
            _render_provenance(prov, lines)
    if provs:
        lines.append("nan_provenance events (telemetry JSONL):")
        for p in provs:
            _render_provenance(p, lines)
    elif scopes:
        s = scopes[-1]
        lines.append(
            f"latest scope record: sub={s.get('sub')} step={s.get('step')} "
            f"grad_norm={_fmt_num(s.get('grad_norm'))} "
            f"loss={_fmt_num(s.get('loss'))}")
    for e in errors:
        lines.append(f"  warning: {e}")
    return "\n".join(lines)


def check_dir(dir_path: str, out=sys.stdout) -> int:
    """CI validation of a flight directory (exit 0 valid / 1 invalid)."""
    files = flight_files(dir_path)
    if not files:
        print(f"hetuscope --check: no flight/flight-r*.json under "
              f"{dir_path}", file=out)
        return 1
    errors: list = []
    n_steps = n_prov = 0
    for path in files:
        doc = _load_flight(path, errors)
        if doc is None:
            continue
        n_steps += sum(1 for r in doc["records"]
                       if r.get("kind") != "provenance")
        if doc.get("provenance") is not None:
            n_prov += 1
    for msg in errors[:20]:
        print(f"hetuscope --check: {msg}", file=out)
    if errors:
        return 1
    print(f"hetuscope --check: {len(files)} flight file(s), {n_steps} step "
          f"record(s), {n_prov} with provenance", file=out)
    return 0


def self_check(out=sys.stdout) -> int:
    """Dependency-free CI smoke (``hetuscope --check`` with no directory):
    exercises the recorder -> flush -> validate -> render pipeline on
    synthetic records in a temp dir; exit 0 iff the whole loop holds."""
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        intro = Introspector(cadence=2, out_dir=td, rank=0)
        try:
            stats = {"loss": 0.7, "grad_norm": 1.25,
                     "params": {"w": {"grad_norm": 1.2,
                                      "update_ratio": 0.01}},
                     "ops": {"MatMulOp_1": {"absmax": 3.0, "rms": 0.5,
                                            "nonfinite": 0.0},
                             "ReluOp_2": {"absmax": 3.0, "rms": 0.4,
                                          "nonfinite": 0.5}}}
            for step in range(4):
                intro.record_step(
                    {"sub": "train", "step": step, "step_ms": 1.0,
                     "finite": step != 3, "batch_crc32": 12345},
                    stats=stats if step % 2 == 0 else None)
            prov = find_culprit(["MatMulOp_1", "ReluOp_2"],
                                {"ReluOp_2": ["MatMulOp_1"]}, stats, step=3)
            if prov.get("op") != "ReluOp_2":
                print("hetuscope --check: self-test culprit mismatch: "
                      f"{prov}", file=out)
                return 1
            intro.on_anomaly(prov)
            rc = check_dir(td, out=out)
            if rc != 0:
                return rc
            report = render_report(td)
            for needle in ("ReluOp_2", "flight ring flushed on 'anomaly'",
                           "layer health"):
                if needle not in report:
                    print(f"hetuscope --check: self-test report missing "
                          f"{needle!r}", file=out)
                    return 1
            print("hetuscope --check: self-test ok (record/flush/validate/"
                  "render)", file=out)
            return 0
        finally:
            intro.close()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="hetuscope",
        description="numeric-health post-mortem over a hetu_tpu telemetry "
                    "directory (flight recorder + scope records + NaN/Inf "
                    "provenance)")
    ap.add_argument("dir", nargs="?",
                    help="telemetry directory (HETU_TELEMETRY_DIR); "
                         "optional with --check (self-test)")
    ap.add_argument("--check", action="store_true",
                    help="validate the flight schema and exit 0/1 (CI "
                         "mode); with no dir, run the built-in self-test")
    ap.add_argument("--last", type=int, default=12,
                    help="step records to show per rank (default 12)")
    args = ap.parse_args(argv)
    if args.check:
        return self_check() if args.dir is None else check_dir(args.dir)
    if args.dir is None:
        ap.error("dir is required unless --check")
    print(render_report(args.dir, last=args.last))
    return 0


if __name__ == "__main__":
    sys.exit(main())
