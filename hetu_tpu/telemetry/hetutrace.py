"""``hetutrace`` — merge per-rank Chrome-trace files into ONE timeline with
rank lanes, plus the ``--check`` validator CI uses (exit 0/1).

Each rank's :class:`~hetu_tpu.telemetry.tracing.Tracer` writes
``trace-r<N>.json`` with ``pid = rank`` and a unix clock anchor in
``otherData``; the merge re-anchors every rank onto the earliest anchor so
spans line up in absolute time (bounded by host clock skew), keeps the
process-name metadata ("rank N" lanes in Perfetto), and emits one
``trace.json`` loadable by ``chrome://tracing`` or https://ui.perfetto.dev.
Stdlib-only and jax-free.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Optional


def _load_doc(path: str):
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, list):            # bare-array Chrome trace form
        doc = {"traceEvents": doc}
    if not isinstance(doc, dict) or not isinstance(
            doc.get("traceEvents"), list):
        raise ValueError(f"{path}: not a Chrome trace "
                         "(no traceEvents array)")
    return doc


# ---------------------------------------------------------------------------
# --check
# ---------------------------------------------------------------------------

def check_file(path: str, out=sys.stdout) -> int:
    """Validate one trace file; returns a process exit code (0 ok, 1 bad)."""
    try:
        doc = _load_doc(path)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"hetutrace --check: {e}", file=out)
        return 1
    errors = []
    n_spans = 0
    names = set()
    for i, ev in enumerate(doc["traceEvents"]):
        if not isinstance(ev, dict) or "ph" not in ev:
            errors.append(f"event[{i}]: not an object with 'ph'")
            continue
        if ev["ph"] == "X":
            missing = [k for k in ("name", "ts", "dur", "pid", "tid")
                       if k not in ev]
            if missing:
                errors.append(f"event[{i}] ({ev.get('name')!r}): "
                              f"missing {missing}")
                continue
            if ev["dur"] < 0:
                errors.append(f"event[{i}] ({ev['name']!r}): negative dur")
                continue
            n_spans += 1
            names.add(ev["name"])
    for msg in errors[:20]:
        print(f"hetutrace --check: {path}: {msg}", file=out)
    if len(errors) > 20:
        print(f"hetutrace --check: ... and {len(errors) - 20} more",
              file=out)
    if n_spans == 0:
        print(f"hetutrace --check: {path}: no complete ('X') spans",
              file=out)
        return 1
    print(f"hetutrace --check: {path}: {n_spans} span(s), "
          f"{len(names)} distinct name(s): "
          f"{', '.join(sorted(names)[:10])}", file=out)
    return 1 if errors else 0


# ---------------------------------------------------------------------------
# merge
# ---------------------------------------------------------------------------

def merge(inputs: list[str], out_path: str) -> str:
    """Merge trace files (or expand directories) into one timeline.

    Re-anchoring prefers each rank's MONOTONIC anchor
    (``clock_anchor_mono_s`` — raw perf_counter = CLOCK_MONOTONIC on
    Linux, counting from kernel boot) whenever every input carries one
    and they all report the same kernel ``boot_id`` — the exact condition
    under which monotonic origins coincide (hostnames can collide across
    machines; boot ids cannot). An NTP step mid-run moves the wall
    anchors but not the mono ones, so merged lanes stay aligned. Wall
    anchors (``clock_anchor_unix_s``) remain the cross-boot fallback,
    bounded by host clock skew as before.
    """
    paths: list[str] = []
    for p in inputs:
        if os.path.isdir(p):
            paths.extend(sorted(glob.glob(os.path.join(p, "trace-r*.json"))))
        else:
            paths.append(p)
    if not paths:
        raise FileNotFoundError(f"no trace files in {inputs}")
    docs = [(p, _load_doc(p)) for p in paths]
    others = [d.get("otherData", {}) for _, d in docs]
    monos = [o.get("clock_anchor_mono_s") for o in others]
    boots = {o.get("boot_id") for o in others}
    use_mono = (len(docs) > 1 and all(a is not None for a in monos)
                and len(boots) == 1 and "" not in boots
                and None not in boots)
    anchors = monos if use_mono else \
        [o.get("clock_anchor_unix_s") for o in others]
    base: Optional[float] = min((a for a in anchors if a is not None),
                                default=None)
    events: list[dict] = []
    for idx, ((path, doc), anchor) in enumerate(zip(docs, anchors)):
        rank = doc.get("otherData", {}).get("rank", idx)
        # re-anchor this rank's clock onto the earliest rank's
        shift_us = ((anchor - base) * 1e6
                    if anchor is not None and base is not None else 0.0)
        for ev in doc["traceEvents"]:
            ev = dict(ev)
            ev["pid"] = rank
            if "ts" in ev:
                ev["ts"] = round(ev["ts"] + shift_us, 1)
            events.append(ev)
    merged = {"displayTimeUnit": "ms",
              "otherData": {"merged_from": [p for p, _ in docs],
                            "anchor_clock": ("monotonic" if use_mono
                                             else "unix")},
              "traceEvents": events}
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(merged, f, separators=(",", ":"))
    os.replace(tmp, out_path)
    return out_path


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="hetutrace",
        description="merge per-rank hetu_tpu trace files into one "
                    "Perfetto-loadable timeline, or --check one file")
    ap.add_argument("paths", nargs="+",
                    help="trace file(s) or telemetry director(ies)")
    ap.add_argument("--check", action="store_true",
                    help="validate Chrome-trace schema and exit 0/1 "
                         "(single file; CI mode)")
    ap.add_argument("-o", "--out", default="trace.json",
                    help="merged output path (default trace.json)")
    args = ap.parse_args(argv)
    if args.check:
        if len(args.paths) != 1:
            print("hetutrace --check takes exactly one file",
                  file=sys.stderr)
            return 2
        return check_file(args.paths[0])
    try:
        out = merge(args.paths, args.out)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"hetutrace: {e}", file=sys.stderr)
        return 1
    print(f"hetutrace: wrote {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
