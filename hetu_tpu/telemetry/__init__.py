"""Runtime telemetry: metrics registry + structured tracing + JSONL sinks.

Pillars (docs/OBSERVABILITY.md; numeric health lives in :mod:`.scope` —
hetuscope introspection, NaN/Inf provenance, flight recorder — and is
armed separately via ``HetuConfig(introspect=...)``):

- **Metrics** — process-wide counters/gauges/histograms
  (:mod:`.registry`), snapshotted into a per-step JSONL record and exported
  as a Prometheus textfile.
- **Tracing** — Chrome-trace spans for step phases (:mod:`.tracing`),
  Perfetto-loadable, merged across ranks by ``bin/hetutrace``.
- **Dashboards** — ``bin/hetutop`` tails the JSONL live;
  ``--check`` modes on both CLIs validate the schemas for CI.
- **Distributed tracing** — hetutrail (:mod:`.trail`, pillar 5): PS-wire
  client/server span rings joined by (client_id, req_id), per-step
  critical-path attribution, straggler detection; armed separately by
  ``HETU_TRAIL_DIR`` (``bin/hetutrail`` analyzes/validates).

Activation contract (the zero-overhead-when-off design):

- :func:`get` returns the process's active :class:`Telemetry` or **None**.
  Every instrumented call site does ``tel = telemetry.get()`` followed by an
  ``if tel is None`` early-out — when telemetry is off, the per-step cost is
  that None check and nothing else (no allocations, no syscalls; asserted by
  ``tests/test_telemetry.py``).
- :func:`activate` creates the singleton (first call wins; later calls may
  only *upgrade* ``metrics`` → ``trace``). ``HetuConfig(telemetry=...)``
  calls it from the Executor; standalone components (dataloaders, the PS
  supervisor) only ever :func:`get`.
- Config surface: ``HetuConfig(telemetry="off"|"metrics"|"trace")`` or env
  ``HETU_TELEMETRY`` (same values); output lands in ``HETU_TELEMETRY_DIR``
  (default ``./hetu_telemetry``), one ``metrics-r<rank>.jsonl`` +
  ``trace-r<rank>.json`` + ``metrics-r<rank>.prom`` per rank.

This package is stdlib-only: the heturun launcher parent and the PS
supervisor import it jax-free.
"""
from __future__ import annotations

import atexit
import contextlib
import os
import threading
from typing import Optional

from .registry import (Counter, Gauge, Histogram, MetricsRegistry,  # noqa: F401
                       JsonlSink, DEFAULT_BUCKETS_MS)
from .tracing import Tracer, XlaTraceWindow  # noqa: F401

MODES = ("off", "metrics", "trace")

_lock = threading.Lock()
_active: Optional["Telemetry"] = None


def resolve_mode(mode: Optional[str]) -> str:
    """One spelling of the mode resolution: explicit value wins, env
    ``HETU_TELEMETRY`` fills the default, anything falsy is off."""
    if mode is None:
        mode = os.environ.get("HETU_TELEMETRY", "off") or "off"
    mode = str(mode).strip().lower()
    if mode in ("0", "false", "no", ""):
        mode = "off"
    if mode == "1":  # HETU_TELEMETRY=1 == metrics (the common toggle)
        mode = "metrics"
    if mode not in MODES:
        raise ValueError(f"telemetry must be one of {MODES}, got {mode!r}")
    return mode


def default_rank() -> int:
    """Rank identity for file names: the launcher's WORKER_ID (set by
    heturun/launcher for every worker) — resolvable before jax initializes."""
    try:
        return int(os.environ.get("WORKER_ID", "0"))
    except ValueError:
        return 0


def run_identity():
    """``(run_id, incarnation)`` for this process, or ``(None, 0)``.

    heturun mints ``HETU_RUN_ID`` and every role inherits it; a process
    started outside heturun (tests, notebooks) simply has no run identity —
    nothing is fabricated, so rows stay byte-stable for such runs. The
    incarnation counts supervisor restarts (heturun bumps it per respawned
    worker and per inherited relaunch)."""
    run_id = os.environ.get("HETU_RUN_ID") or None
    inc = 0
    if run_id:
        try:
            inc = int(os.environ.get("HETU_RUN_INCARNATION", "0"))
        except ValueError:
            inc = 0
    return run_id, inc


class Telemetry:
    """One per process: registry + sinks + (in trace mode) the tracer."""

    def __init__(self, mode: str, out_dir: str, rank: int):
        self.mode = mode
        self.dir = out_dir
        self.rank = int(rank)
        self.metrics = MetricsRegistry()
        base_fields = {"rank": self.rank, "pid": os.getpid()}
        run_id, inc = run_identity()
        if run_id:
            # preserialized with the rest of the base fields: the hot-path
            # step record pays zero extra serialization for run identity
            base_fields["run_id"] = run_id
            base_fields["inc"] = inc
        self.sink = JsonlSink(
            os.path.join(out_dir, f"metrics-r{self.rank}.jsonl"),
            base_fields=base_fields)
        self.tracer: Optional[Tracer] = (
            Tracer(os.path.join(out_dir, f"trace-r{self.rank}.json"),
                   rank=self.rank) if mode == "trace" else None)
        self.xla_window = XlaTraceWindow.from_env()
        if self.xla_window is not None:
            # advertise the deep-dive window in the JSONL so hetuprof can
            # locate the XLA trace dir and normalize per-op times per step
            # without re-reading the caller's environment
            self.sink.write({"kind": "xla_trace",
                             "dir": self.xla_window.dir,
                             "start_step": self.xla_window.start_step,
                             "n_steps": self.xla_window.n_steps})
        self._prom_path = os.path.join(out_dir,
                                       f"metrics-r{self.rank}.prom")
        # full registry snapshots ride only every Nth step record: the
        # snapshot sorts each histogram's recent window for percentiles,
        # which would dominate sub-ms steps if taken per step (measured:
        # ~0.4 ms vs ~15 µs for the plain record). hetutop reads the
        # latest record that HAS metrics; every step still records
        # step/step_ms/phases.
        self._snapshot_every = max(1, int(os.environ.get(
            "HETU_TELEMETRY_SNAPSHOT_EVERY", "20")))
        self._closed = False

    # -- tracing -----------------------------------------------------------
    def span(self, name: str, cat: str = "step",
             args: Optional[dict] = None):
        """Span context manager; a no-op context in metrics mode so call
        sites need not branch on the mode."""
        if self.tracer is not None:
            return self.tracer.span(name, cat, args)
        return contextlib.nullcontext()

    # -- events ------------------------------------------------------------
    def event(self, name: str, **fields) -> None:
        """Typed event: one JSONL record + a labeled counter + (trace mode)
        an instant marker on the timeline."""
        self.metrics.counter("hetu_events_total", {"event": name}).inc()
        self.sink.write({"kind": "event", "name": name, **fields})
        if self.tracer is not None:
            self.tracer.instant(name, args=fields or None)

    # -- per-step record ---------------------------------------------------
    def step_record(self, sub: str, step: int, step_ms: float,
                    phases: Optional[dict] = None, **extra) -> None:
        if extra or step % self._snapshot_every == 0 \
                or not sub.isidentifier():
            rec = {"kind": "step", "sub": sub, "step": int(step),
                   "step_ms": round(float(step_ms), 4)}
            if phases:
                rec["phases"] = {k: round(float(v), 4)
                                 for k, v in phases.items()}
            if extra:
                rec.update(extra)
            if step % self._snapshot_every == 0:
                rec["metrics"] = self.metrics.snapshot()
            self.sink.write(rec)
            return
        # hot path (every non-snapshot step): direct string formatting —
        # json.dumps over the merged dict measured ~4x the cost; phase keys
        # are fixed identifiers and values finite floats, so the fragment
        # is valid JSON by construction
        body = (f'"kind":"step","sub":"{sub}","step":{int(step)},'
                f'"step_ms":{float(step_ms):.4f}')
        if phases:
            body += (',"phases":{'
                     + ",".join(f'"{k}":{float(v):.4f}'
                                for k, v in phases.items()) + "}")
        self.sink.write_fields(body)

    def record(self, kind: str, **fields) -> None:
        """Free-form record (``ps_server`` health rows etc.)."""
        self.sink.write({"kind": kind, **fields})

    # -- lifecycle ---------------------------------------------------------
    def flush(self) -> None:
        """Crash-durability point: resilience abort paths call this before
        ``os._exit``; also runs at interpreter exit via atexit. Writes a
        closing ``final`` record so the JSONL tail always carries current
        counter values even between snapshot-cadence steps."""
        try:
            self.sink.write({"kind": "final",
                             "metrics": self.metrics.snapshot()})
        except Exception:  # noqa: BLE001
            pass
        self.sink.flush()
        if self.tracer is not None:
            self.tracer.flush()
        if self.xla_window is not None:
            # a run that ends (or aborts) inside the HETU_XLA_TRACE window
            # must still stop_trace, or jax discards the buffered profile —
            # exactly the short/crashing runs the window is for
            try:
                self.xla_window.stop()
            except Exception:  # noqa: BLE001
                pass
        try:
            self.metrics.write_prometheus(self._prom_path)
        except OSError:
            pass  # a full/readonly disk must not take the abort path down

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.flush()
        self.sink.close()


def get() -> Optional[Telemetry]:
    """The active telemetry, or None when off — the per-call-site gate."""
    return _active


def record_model_info(**fields) -> None:
    """Advertise model geometry (``n_layers``, ``d_model``, ``seq_len``,
    ``causal``, optionally ``n_params``) to the dashboards: hetutop uses it
    to report MFU under the attention-inclusive denominator next to 6ND
    (docs/ROOFLINE.md). No-op when telemetry is off — trainers call this
    unconditionally after building their model."""
    t = get()
    if t is not None:
        t.record("model_info", **fields)


def activate(mode: Optional[str] = None, out_dir: Optional[str] = None,
             rank: Optional[int] = None) -> Optional[Telemetry]:
    """Create (or return) the process singleton. ``mode`` resolves via
    :func:`resolve_mode`; "off" returns None without touching an existing
    active instance (a metrics-enabled trainer is not disarmed by a later
    eval Executor constructed with defaults). A later ``trace`` request
    upgrades a ``metrics`` instance in place (same registry, tracer added)."""
    global _active
    mode = resolve_mode(mode)
    if mode == "off":
        return None
    with _lock:
        if _active is not None:
            if mode == "trace" and _active.tracer is None:
                _active.mode = "trace"
                _active.tracer = Tracer(
                    os.path.join(_active.dir,
                                 f"trace-r{_active.rank}.json"),
                    rank=_active.rank)
            return _active
        out_dir = out_dir or os.environ.get("HETU_TELEMETRY_DIR",
                                            "hetu_telemetry")
        rank = default_rank() if rank is None else int(rank)
        _active = Telemetry(mode, out_dir, rank)
        atexit.register(_shutdown_atexit)
        return _active


def _shutdown_atexit() -> None:
    t = _active
    if t is not None:
        t.close()


def shutdown() -> None:
    """Close and detach the singleton (tests; also lets a long-lived process
    rotate output directories by re-activating)."""
    global _active
    with _lock:
        t, _active = _active, None
    if t is not None:
        t.close()
