"""hetuprof — op-level performance attribution, roofline analysis, HBM
memory observability, and the perf-regression gate (docs/PROFILING.md).

Three pillars on top of the telemetry bus:

1. **Op attribution** — the executor lowers every Op under
   ``jax.named_scope(op.name)``, so the optimized HLO's ``op_name`` metadata
   carries graph-op identity per instruction. This module joins a bounded
   ``HETU_XLA_TRACE`` profiler window (Chrome-trace ``*.trace.json.gz``)
   against that metadata: device-lane event durations land on the graph op
   that generated them (backward work resolves through the ``jvp(...)`` /
   ``transpose(...)`` wrappers to its forward op), collectives land in a
   ``<collective>`` bucket, and the per-step compute / collective-comm /
   PS-RPC / host breakdown falls out of the join with the step-record phases.
2. **Roofline** — per-op analytic flops/bytes from the abstract shape
   inference (hetulint's substrate) classify each op family compute- vs
   HBM-bound against the assumed peaks; measured times from pillar 1 turn
   the prediction into a residual — the calibration data the cost-model
   planner (ROADMAP item 3) consumes.
3. **Perf-regression gate** — ``gate()`` diffs two bench/telemetry summaries
   cell-by-cell with a tolerance, and distinguishes *regressed* from *could
   not measure*: exit 0 clean, 1 regressed, 2 current run incomplete,
   3 baseline unusable — a partial run (the BENCH_r05 rc=124 mode) can
   never read as a win or a loss.

Import contract: module-level imports are **stdlib only**, and there are no
package-relative imports — ``bench.py``'s jax-free driver parent and
``bin/hetuprof`` load this file directly via
``importlib.util.spec_from_file_location`` (importing the ``hetu_tpu``
package would pull jax). Anything that needs the graph/executor imports it
lazily inside the function that uses it.
"""
from __future__ import annotations

import argparse
import glob
import gzip
import json
import math
import os
import re
import sys
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

# Assumed hardware peaks (docs/ROOFLINE.md: assumptions, not readings; both
# recorded next to every number they produce).
DEFAULT_PEAK_TFLOPS = float(os.environ.get("HETU_PEAK_TFLOPS", "197"))
DEFAULT_PEAK_GBS = float(os.environ.get("HETU_PEAK_GBS", "819"))

# gate exit codes — the contract CI scripts key on
GATE_OK = 0
GATE_REGRESSED = 1
GATE_INCOMPLETE_CURRENT = 2
GATE_INCOMPLETE_BASELINE = 3


def attn_flops(batch, seq, n_layers, d_model, causal):
    """Attention-score matmul FLOPs per training step (fwd+bwd), which the
    6ND rule EXCLUDES (they scale with T^2, not with N): per layer the
    forward QK^T and PV matmuls cost 2*2*B*T^2*d; backward doubles it ->
    12*B*T^2*d*L for a bidirectional encoder. A causal decoder only
    computes the lower triangle (the flash kernel skips upper blocks), so
    half. Reporting MFU against 6ND alone OVERSTATES utilization at long
    seq — report both denominators (bench.py and hetutop do)."""
    full = 12.0 * batch * seq * seq * d_model * n_layers
    return full / 2.0 if causal else full


# ---------------------------------------------------------------------------
# pillar 1 — Chrome-trace parsing and op attribution
# ---------------------------------------------------------------------------

def load_trace_events(path: str) -> List[dict]:
    """Events of one Chrome-trace file (.json or .json.gz; the jax profiler
    writes the object form, our own Tracer too; a bare event list also
    loads)."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        doc = json.load(f)
    if isinstance(doc, dict):
        return doc.get("traceEvents", [])
    return doc if isinstance(doc, list) else []


def find_xla_traces(root: str) -> List[str]:
    """All profiler trace files under a ``jax.profiler`` output dir (the
    layout is ``<dir>/plugins/profile/<run>/<host>.trace.json.gz``)."""
    out = []
    for base, _dirs, files in os.walk(root):
        for fn in files:
            if fn.endswith(".trace.json.gz") or fn.endswith(".trace.json"):
                out.append(os.path.join(base, fn))
    return sorted(out)


_HLO_META = re.compile(r"%([\w.\-]+)\s*=\s*[^\n]*?op_name=\"([^\"]+)\"")
_HLO_CALL = re.compile(
    r"%(call[\w.\-]*)\s*=\s*[^\n]*?to_apply=%parallel_([\w.\-]+)")


def hlo_op_map(hlo_text: str) -> Dict[str, str]:
    """HLO instruction name -> ``op_name`` metadata path, parsed from the
    optimized-HLO text (``SubExecutor.dump_hlo(stage="optimized")``). The
    trace's device events are named after these instructions — this map is
    the join key back to graph ops.

    Second pass: the CPU backend wraps parallelized fusions in metadata-less
    ``%call.N = call(...), to_apply=%parallel_<fusion>`` instructions whose
    trace events would otherwise be unattributable — they inherit the
    wrapped fusion's path."""
    out = {m.group(1): m.group(2) for m in _HLO_META.finditer(hlo_text)}
    for m in _HLO_CALL.finditer(hlo_text):
        call_name, fused = m.group(1), m.group(2)
        if call_name in out:
            continue
        for cand in (fused, fused + ".clone",
                     re.sub(r"\.\d+$", "", fused),
                     re.sub(r"\.\d+$", "", fused) + ".clone"):
            if cand in out:
                out[call_name] = out[cand]
                break
    return out


_WRAPPER = re.compile(r"^(?:jvp|vjp|transpose|remat|checkpoint)\((.+)\)$")
_OPNAME_GUESS = re.compile(r"^[\w().\-]+_\d+$")


def scope_of(op_path: str, known_ops=None) -> Tuple[Optional[str], bool]:
    """Graph-op identity of one HLO ``op_name`` path.

    Returns ``(op, is_backward)``. The INNERMOST known-op segment wins:
    ``Gradient(w)/transpose(Gradient(w))/jvp(MatMul_3)/transpose`` is
    backward work OF ``MatMul_3``, not of the Gradient node. Without a
    ``known_ops`` set, segments shaped like hetu op names (``Name_<id>``)
    are accepted."""
    best = None
    bwd = False
    for seg in op_path.split("/"):
        if seg.startswith("jit("):
            continue
        if seg.startswith("transpose("):
            bwd = True
        inner = seg
        while True:
            m = _WRAPPER.match(inner)
            if m is None:
                break
            inner = m.group(1)
        if known_ops is not None:
            if inner in known_ops:
                best = inner
        elif _OPNAME_GUESS.match(inner):
            best = inner
    return best, bwd


# collective bases as they appear in device-lane event / HLO names
COLLECTIVE_BASES = ("all-reduce", "all-gather", "reduce-scatter",
                    "all-to-all", "collective-permute",
                    "collective-broadcast", "send", "recv", "send-done",
                    "recv-done")

# host-side profiler noise that must never be attributed as device time
_NOISE_PREFIXES = ("ThreadpoolListener", "Thunk", "TaskDispatcher",
                   "H2D ", "D2H ", "$", "Tfrt", "DevicePut", "copy_",
                   "BufferFromHostBuffer")


def _base_name(event_name: str) -> str:
    """``dot.9`` -> ``dot``; ``broadcast_maximum_fusion.clone`` ->
    ``broadcast_maximum_fusion``."""
    return event_name.split(".", 1)[0]


def _union_us(intervals: List[Tuple[float, float]]) -> float:
    """Total covered microseconds of possibly-overlapping [t0, t1) spans —
    the wall-clock footprint of an op whose slices ran on several worker
    threads/cores in parallel (summing durations would overcount)."""
    if not intervals:
        return 0.0
    intervals.sort()
    total = 0.0
    cur0, cur1 = intervals[0]
    for t0, t1 in intervals[1:]:
        if t0 > cur1:
            total += cur1 - cur0
            cur0, cur1 = t0, t1
        else:
            cur1 = max(cur1, t1)
    return total + (cur1 - cur0)


def op_family(op: str) -> str:
    """``MatMul_3`` -> ``MatMul``; ``Gradient(w)`` -> ``Gradient``."""
    m = re.match(r"^(.*?)_\d+$", op)
    base = m.group(1) if m else op
    return re.sub(r"\(.*\)$", "", base) or base


@dataclass
class OpRow:
    op: str
    family: str
    count: int = 0
    total_us: float = 0.0      # summed slice durations (CPU/core time)
    bwd_us: float = 0.0        # share attributed through jvp/transpose
    wall_us: float = 0.0       # interval union (parallel slices merged)
    intervals: list = field(default_factory=list)

    def finish(self):
        self.wall_us = _union_us(self.intervals)
        self.intervals = []
        return self


class Attribution:
    """Per-op time table for one profiler window."""

    def __init__(self, rows: Dict[str, OpRow], steps: int,
                 span_us: float = 0.0):
        self.rows = rows
        self.steps = max(1, int(steps))
        # global interval union over every device event: the wall-clock
        # footprint of the window's device work (parallel slices and
        # parent/child call spans collapse) — the number to hold against
        # the executor's measured compute span
        self.span_us = span_us

    @property
    def device_wall_us(self) -> float:
        return sum(r.wall_us for r in self.rows.values())

    @property
    def unattributed_us(self) -> float:
        """Device time visible in the trace but not resolvable to a graph
        op (sub-computation instructions, renamed fusion clones)."""
        return sum(r.wall_us for r in self.rows.values()
                   if r.op.startswith("<") and r.family != "<collective>")

    @property
    def attributed_fraction(self) -> float:
        wall = self.device_wall_us
        return (wall - self.unattributed_us) / wall if wall else 0.0

    @property
    def collective_wall_us(self) -> float:
        return sum(r.wall_us for r in self.rows.values()
                   if r.family == "<collective>")

    def families(self) -> Dict[str, dict]:
        fams: Dict[str, dict] = {}
        for r in self.rows.values():
            f = fams.setdefault(r.family, {"family": r.family, "n_ops": 0,
                                           "count": 0, "total_us": 0.0,
                                           "wall_us": 0.0, "bwd_us": 0.0})
            f["n_ops"] += 1
            f["count"] += r.count
            f["total_us"] += r.total_us
            f["wall_us"] += r.wall_us
            f["bwd_us"] += r.bwd_us
        return fams

    def table(self, top: Optional[int] = None) -> str:
        rows = sorted(self.rows.values(), key=lambda r: -r.wall_us)
        if top:
            rows = rows[:top]
        wall = self.device_wall_us or 1.0
        lines = [f"{'op':<40} {'family':<18} {'count':>7} "
                 f"{'us/step':>10} {'bwd%':>6} {'share%':>7}"]
        for r in rows:
            bwd = 100.0 * r.bwd_us / r.total_us if r.total_us else 0.0
            lines.append(
                f"{r.op[:40]:<40} {r.family[:18]:<18} {r.count:>7} "
                f"{r.wall_us / self.steps:>10.1f} {bwd:>6.1f} "
                f"{100.0 * r.wall_us / wall:>7.2f}")
        lines.append(
            f"{'TOTAL (device busy)':<40} {'':<18} "
            f"{sum(r.count for r in self.rows.values()):>7} "
            f"{self.device_wall_us / self.steps:>10.1f} {'':>6} {100.0:>7.2f}")
        lines.append(
            f"# device wall span {self.span_us / self.steps:.1f} us/step "
            f"over {self.steps} step(s); "
            f"{100.0 * self.attributed_fraction:.1f}% of busy time "
            "attributed to graph ops")
        return "\n".join(lines)

    def as_dict(self) -> dict:
        return {
            "steps": self.steps,
            "device_busy_us_per_step": self.device_wall_us / self.steps,
            "device_span_us_per_step": self.span_us / self.steps,
            "attributed_fraction": round(self.attributed_fraction, 4),
            "collective_us_per_step":
                self.collective_wall_us / self.steps,
            "unattributed_us_per_step": self.unattributed_us / self.steps,
            "ops": [{"op": r.op, "family": r.family, "count": r.count,
                     "total_us": round(r.total_us, 1),
                     "bwd_us": round(r.bwd_us, 1),
                     "wall_us": round(r.wall_us, 1),
                     "us_per_step": round(r.wall_us / self.steps, 2)}
                    for r in sorted(self.rows.values(),
                                    key=lambda r: -r.wall_us)],
        }


def device_lanes(events: List[dict]) -> Optional[set]:
    """(pid, tid) lanes that carry DEVICE work, from the trace's own
    metadata: XLA executor/client threads are named ``tf_*`` on the CPU
    backend, and TPU device timelines live under processes named
    ``/device:...``. None when the trace carries no lane metadata (our
    synthetic test traces) — callers fall back to name-shape filtering."""
    tf_tids = set()
    dev_pids = set()
    saw_meta = False
    for ev in events:
        if ev.get("ph") != "M":
            continue
        name = (ev.get("args") or {}).get("name", "")
        if ev.get("name") == "thread_name":
            saw_meta = True
            if name.startswith("tf_"):
                tf_tids.add((ev.get("pid"), ev.get("tid")))
        elif ev.get("name") == "process_name":
            saw_meta = True
            if "/device:" in name:
                dev_pids.add(ev.get("pid"))
    if not saw_meta:
        return None
    return {(p, t) for (p, t) in tf_tids} | {(p, None) for p in dev_pids}


def attribute(events: List[dict], op_map: Optional[Dict[str, str]] = None,
              known_ops=None, steps: Optional[int] = None) -> Attribution:
    """Attribute device-lane trace events to graph ops.

    ``op_map`` (HLO instruction -> op_name path, from :func:`hlo_op_map`)
    is the precise join; events on a device lane the map doesn't cover are
    bucketed per HLO base name (``<dot>``, ``<fusion>`` ...) so nothing is
    silently dropped. Host lanes (the python TraceMe firehose) are excluded
    via the trace's own lane metadata. ``steps`` defaults to the number of
    ``hetu_step`` StepTraceAnnotation events in the window (the executor
    opens one per step while a profiler trace is active)."""
    lanes = device_lanes(events)
    rows: Dict[str, OpRow] = {}
    all_intervals: List[Tuple[float, float]] = []
    n_steps = 0
    for ev in events:
        if ev.get("ph") != "X":
            continue
        name = ev.get("name", "")
        if name.startswith("hetu_step"):
            n_steps += 1
            continue
        dur = float(ev.get("dur", 0.0) or 0.0)
        if dur <= 0 or any(name.startswith(p) for p in _NOISE_PREFIXES):
            continue
        if lanes is not None:
            lane_ok = (ev.get("pid"), ev.get("tid")) in lanes \
                or (ev.get("pid"), None) in lanes
            if not lane_ok:
                continue              # host lane: not device time
        elif not re.match(r"^[a-z][\w.\-]*$", name):
            continue                  # no metadata: keep HLO-shaped names
        base = _base_name(name)
        bwd = False
        mapped = None
        if op_map is not None:
            # event names and HLO instruction names drift by rename
            # suffixes (".clone", trailing ".N") — try the variants
            for cand in (name, name + ".clone", base, base + ".clone"):
                mapped = op_map.get(cand)
                if mapped is not None:
                    break
        if base in COLLECTIVE_BASES or name in COLLECTIVE_BASES:
            op, fam = name, "<collective>"
        elif mapped is not None:
            op, bwd = scope_of(mapped, known_ops)
            if op is None:
                op, fam = f"<{base}>", f"<{base}>"
            else:
                fam = op_family(op)
        else:
            # a device event the HLO map has no entry for (sub-computation
            # instruction, renamed clone): visible, not silently dropped
            op = f"<{base}>"
            fam = "<fusion>" if "fusion" in base else f"<{base}>"
        row = rows.get(op)
        if row is None:
            row = rows[op] = OpRow(op=op, family=fam)
        row.count += 1
        row.total_us += dur
        if bwd:
            row.bwd_us += dur
        t0 = float(ev.get("ts", 0.0))
        row.intervals.append((t0, t0 + dur))
        all_intervals.append((t0, t0 + dur))
    span = _union_us(all_intervals)
    for row in rows.values():
        row.finish()
    if steps is None:
        steps = n_steps or 1
    return Attribution(rows, steps, span_us=span)


# ---------------------------------------------------------------------------
# telemetry-dir readers (shared by the CLI and profile_dir)
# ---------------------------------------------------------------------------

def read_metrics_records(tel_dir: str) -> List[dict]:
    recs = []
    for path in sorted(glob.glob(os.path.join(tel_dir,
                                              "metrics-r*.jsonl"))):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(rec, dict):
                    recs.append(rec)
    return recs


def step_phase_means(records: List[dict]) -> dict:
    """Mean per-phase milliseconds over the run's step records, compile
    steps excluded (a compile step's dispatch carries the XLA compile and
    would poison the steady-state mean)."""
    sums: Dict[str, float] = {}
    n = 0
    for rec in records:
        if rec.get("kind") != "step":
            continue
        phases = rec.get("phases") or {}
        if "compile_ms" in phases:
            continue
        n += 1
        sums["step_ms"] = sums.get("step_ms", 0.0) + float(rec["step_ms"])
        for k, v in phases.items():
            sums[k] = sums.get(k, 0.0) + float(v)
    if n == 0:
        return {}
    return {k: v / n for k, v in sums.items()} | {"n_steps": n}


def last_metrics_snapshot(records: List[dict]) -> dict:
    snap: dict = {}
    for rec in records:
        if rec.get("kind") in ("step", "final") \
                and isinstance(rec.get("metrics"), dict):
            snap = rec["metrics"]
    return snap


def step_breakdown(phase_means: dict, attribution=None) -> dict:
    """Per-step compute / collective-comm / PS-RPC / host milliseconds.

    ``dispatch_ms`` is the on-device window (compute + in-program
    collectives); the device trace (when present) splits the collective
    share out of it. PS RPC time is the executor's critical-path stamp;
    host is everything else (feed staging, python, bookkeeping)."""
    if not phase_means:
        return {}
    step = phase_means.get("step_ms", 0.0)
    dispatch = phase_means.get("dispatch_ms", 0.0)
    ps_rpc = phase_means.get("ps_comm_ms", 0.0)
    coll = 0.0
    if attribution is not None and attribution.steps:
        coll = attribution.collective_wall_us / attribution.steps / 1e3
    out = {
        "step_ms": step,
        "compute_ms": max(0.0, dispatch - coll),
        "collective_ms": coll,
        "ps_rpc_ms": ps_rpc,
        "host_ms": max(0.0, step - dispatch - ps_rpc),
    }
    if step > 0:
        out["comm_fraction"] = min(1.0, (coll + ps_rpc) / step)
    # hetutrail critical path (trail.step_legs' decomposition, inlined so
    # this module stays loadable by file path): who-blocked-whom per mean
    # step, not just totals — the planner's calibration signal
    legs = cp_legs(phase_means)
    total = sum(legs.values())
    if total > 0:
        dom = max(legs, key=legs.get)
        out["cp_legs_ms"] = {k: round(v, 4) for k, v in legs.items()}
        out["cp_dominant"] = dom
        out["cp_fraction"] = round(legs[dom] / total, 4)
    return out


_TRAIL_MOD = None
_WATCH_MOD = None


def _trail_mod():
    """The hetutrail module, loadable BOTH ways this file is: as the
    package module (tests) and by file path (bin/hetuprof, which must not
    import the jax-bearing ``hetu_tpu`` package root) — the sibling
    trail.py is stdlib-only, so file-path loading it is always safe."""
    global _TRAIL_MOD
    if _TRAIL_MOD is None:
        import importlib.util
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "trail.py")
        spec = importlib.util.spec_from_file_location("_hetuprof_trail",
                                                      path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules["_hetuprof_trail"] = mod
        spec.loader.exec_module(mod)
        _TRAIL_MOD = mod
    return _TRAIL_MOD


def cp_legs(phase_means: dict) -> dict:
    """The per-step blocking chain from mean phases — ONE definition,
    ``trail.step_legs`` (feed → PS pull wait → compute → PS push →
    poststep); zero-valued on runs that predate the ps_pull/ps_push phase
    split."""
    return _trail_mod().step_legs(phase_means)


def profile_dir(tel_dir: str, trace_dir: Optional[str] = None,
                hlo_path: Optional[str] = None, known_ops=None,
                steps: Optional[int] = None) -> dict:
    """One-stop offline report over a telemetry directory: reads the step
    records, locates the ``HETU_XLA_TRACE`` window (advertised by the
    ``xla_trace`` record), attributes the device trace, and assembles the
    breakdown + memory view. Every absence degrades a section to None
    instead of failing — a partial run yields a partial report that SAYS
    it is partial. The report is plain JSON (``json.dumps``-safe); use
    :func:`profile_dir_with_attribution` to also get the live
    :class:`Attribution` for table rendering."""
    report, _att = profile_dir_with_attribution(
        tel_dir, trace_dir=trace_dir, hlo_path=hlo_path,
        known_ops=known_ops, steps=steps)
    return report


def profile_dir_with_attribution(
        tel_dir: str, trace_dir: Optional[str] = None,
        hlo_path: Optional[str] = None, known_ops=None,
        steps: Optional[int] = None) -> Tuple[dict, Optional["Attribution"]]:
    records = read_metrics_records(tel_dir)
    phase_means = step_phase_means(records)
    snap = last_metrics_snapshot(records)
    window = next((r for r in records if r.get("kind") == "xla_trace"), None)
    if trace_dir is None and window is not None:
        trace_dir = window.get("dir")
    attribution = None
    trace_files: List[str] = []
    if trace_dir and os.path.isdir(trace_dir):
        trace_files = find_xla_traces(trace_dir)
        events: List[dict] = []
        for p in trace_files:
            events.extend(load_trace_events(p))
        op_map = None
        if hlo_path and os.path.exists(hlo_path):
            with open(hlo_path) as f:
                op_map = hlo_op_map(f.read())
        if events:
            attribution = attribute(events, op_map=op_map,
                                    known_ops=known_ops, steps=steps)
    report = {
        "telemetry_dir": tel_dir,
        "xla_trace_dir": trace_dir,
        "trace_files": len(trace_files),
        "phase_means_ms": phase_means or None,
        "breakdown": step_breakdown(phase_means, attribution) or None,
        "attribution": attribution.as_dict() if attribution else None,
        "memory": {k: v for k, v in snap.items()
                   if k.startswith("hetu_hbm_")} or None,
        "model_info": next((
            {k: v for k, v in r.items()
             if k not in ("kind", "ts", "rank", "pid")}
            for r in records if r.get("kind") == "model_info"), None),
        "incomplete": [],
    }
    if not phase_means:
        report["incomplete"].append("no step records")
    if attribution is None:
        report["incomplete"].append("no XLA trace window captured")
    return report, attribution


def profile_executor(executor, name: str = "train",
                     trace_dir: Optional[str] = None,
                     steps: Optional[int] = None) -> dict:
    """In-process attribution for a live Executor: uses the subexecutor's
    own optimized HLO (exact instruction->op join) plus its topo as the
    known-op set. ``trace_dir`` defaults to the active telemetry's
    ``HETU_XLA_TRACE`` window dir."""
    from hetu_tpu import telemetry as _tel
    from hetu_tpu.graph.executor import _op_scope
    sub = executor.subexecutors[name]
    known = {_op_scope(op) for op in sub.topo}
    hlo = sub.dump_hlo(stage="optimized")
    tel = _tel.get()
    if trace_dir is None and tel is not None and tel.xla_window is not None:
        trace_dir = tel.xla_window.dir
    events: List[dict] = []
    for p in find_xla_traces(trace_dir) if trace_dir else []:
        events.extend(load_trace_events(p))
    attribution = attribute(
        events, op_map=hlo_op_map(hlo) if hlo else None,
        known_ops=known, steps=steps)
    phases = sub.last_phases or {}
    return {
        "attribution": attribution,
        "hlo_ops": len(known),
        "last_phases": phases,
        "memory": sub.last_memory_analysis(),
        "cost": sub.last_cost_analysis(),
    }


# ---------------------------------------------------------------------------
# pillar 2 — roofline: predicted flops/bytes per op vs measured time
# ---------------------------------------------------------------------------

# op families whose flops scale with a contraction (fwd cost 2*out*K); the
# backward pass re-runs two such matmuls -> 3x under training
_MATMUL_FAMILIES = {"MatMul", "BatchMatMul", "Linear", "MatMulwithBias"}
_CONV_FAMILIES = {"Conv2d", "Conv2dAddBias"}
# elementwise-ish flop multipliers per output element (coarse by design:
# the roofline wants orders of magnitude, the residual column absorbs it)
_FLOPS_PER_ELEM = {"Softmax": 5.0, "SoftmaxCrossEntropy": 8.0,
                   "LayerNorm": 8.0, "BatchNorm": 8.0, "Gelu": 10.0,
                   "Relu": 1.0, "Dropout": 2.0}


def _nbytes(meta) -> int:
    try:
        n = 1
        for s in meta.shape:
            n *= int(s)
        return n * meta.dtype.itemsize
    except Exception:  # noqa: BLE001 — unknown meta contributes nothing
        return 0


def _prod(shape) -> int:
    n = 1
    for s in shape:
        n *= int(s)
    return n


def op_cost_estimate(node, meta_of) -> Tuple[float, float]:
    """(flops, bytes) analytic estimate for one op's FORWARD evaluation.

    ``meta_of(node) -> ShapeDtypeStruct | None`` supplies abstract shapes.
    Bytes = inputs + output traffic (the HBM-side roofline axis); flops by
    family formula — exact for the matmul/conv heavy hitters, coarse
    multipliers elsewhere."""
    out_meta = meta_of(node)
    in_metas = [meta_of(i) for i in node.inputs]
    bytes_ = _nbytes(out_meta) + sum(_nbytes(m) for m in in_metas
                                     if m is not None)
    if out_meta is None or not hasattr(out_meta, "shape"):
        return 0.0, float(bytes_)
    out_elems = _prod(out_meta.shape)
    fam = op_family(node.name)
    if fam in _MATMUL_FAMILIES and in_metas and in_metas[0] is not None \
            and getattr(in_metas[0], "shape", None):
        k = int(in_metas[0].shape[-1])
        return 2.0 * out_elems * k, float(bytes_)
    if fam in _CONV_FAMILIES and len(in_metas) > 1 \
            and in_metas[1] is not None \
            and len(getattr(in_metas[1], "shape", ())) == 4:
        _o, i, kh, kw = in_metas[1].shape
        return 2.0 * out_elems * int(i) * int(kh) * int(kw), float(bytes_)
    # hetukern families (docs/KERNELS.md): the fused-embed-grad and
    # csr-spmm tiers are HBM-roof citizens — flops are the segment adds
    # (one per input element / two per nnz·feature), bytes dominate
    if fam == "EmbeddingLookUpGradient":
        in_elems = (_prod(in_metas[0].shape)
                    if in_metas and in_metas[0] is not None
                    and getattr(in_metas[0], "shape", None) else out_elems)
        return float(in_elems), float(bytes_)   # one add per grad element
    if fam in ("CSRMatMat", "CSRMatVec"):
        # nnz is runtime-fed (COO feed); 2·out_elems is the dense-output
        # floor — the residual column absorbs the per-graph density
        return 2.0 * out_elems, float(bytes_)
    if fam.startswith("Embedding"):
        return 0.0, float(bytes_)   # a gather: pure HBM traffic
    return _FLOPS_PER_ELEM.get(fam, 1.0) * out_elems, float(bytes_)


@dataclass
class RooflineRow:
    family: str
    n_ops: int
    flops: float
    bytes: float
    intensity: float            # flops per byte
    bound: str                  # "compute" | "memory"
    predicted_us: float
    measured_us: Optional[float] = None
    residual: Optional[float] = None   # measured / predicted
    # hetutrail: share of the step's measured blocking chain held by the
    # leg this family executes in (compute for on-device families, the PS
    # legs for boundary comm) — a 3x residual on a family at 90% of the
    # critical path is a planner problem; the same residual at 2% is not
    cp_fraction: Optional[float] = None


def roofline_rows(nodes, training: bool = True, target: Optional[str] = None,
                  peak_tflops: float = DEFAULT_PEAK_TFLOPS,
                  peak_gbs: float = DEFAULT_PEAK_GBS,
                  attribution: Optional[Attribution] = None,
                  cp: Optional[dict] = None) -> List[RooflineRow]:
    """Roofline classification per op family over a graph (eval-node list,
    topo, or Executor). Needs hetu_tpu — call sites that only gate/parse
    traces never reach here."""
    from hetu_tpu.graph.node import find_topo_sort
    from hetu_tpu.analysis.abstract import AbstractGraph

    if hasattr(nodes, "subexecutors"):          # an Executor
        subs = nodes.subexecutors
        sub = subs.get(target) or next(iter(subs.values()))
        topo = sub.topo
        training = sub.training
    elif nodes and hasattr(nodes[0], "inputs"):
        topo = find_topo_sort(list(nodes))
    else:
        topo = list(nodes)
    ag = AbstractGraph(topo, target=target).evaluate()

    def meta_of(n):
        return ag.meta.get(id(n))

    # training multiplier: matmul/conv backward re-runs two GEMMs (3x),
    # everything else roughly doubles (fwd + elementwise vjp)
    fams: Dict[str, dict] = {}
    # hetukern fused-optimizer family (docs/KERNELS.md): the apply runs
    # inside the step under its own named_scope, so the measured join works
    # — give it a predicted row too. Adam reads grad+m+v+param and writes
    # param+m+v (~10 flops and 7 f32 transfers per element); SGD reads
    # grad+param, writes param (2 flops, 3 transfers).
    # per-element (flops, f32 transfers) by update rule: Adam reads
    # grad+m+v+param / writes param+m+v; Momentum reads grad+v+param /
    # writes param+v; AdaGrad reads grad+accum+param / writes param+accum;
    # SGD reads grad+param / writes param
    _OPT_COST = {"AdamOptimizer": (10.0, 7.0), "AdamWOptimizer": (10.0, 7.0),
                 "MomentumOptimizer": (4.0, 5.0),
                 "AdaGradOptimizer": (6.0, 5.0),
                 "SGDOptimizer": (2.0, 3.0)}
    for node in topo:
        if not node.is_optimizer:
            continue
        opt_name = type(node.optimizer).__name__
        per_flops, per_moves = _OPT_COST.get(opt_name, (2.0, 3.0))
        elems = 0
        for var in getattr(node, "vars", ()):
            m = meta_of(var)
            shape = (getattr(m, "shape", None)
                     or getattr(var, "shape", None))
            if shape:
                elems += _prod(shape)
        if elems:
            fam = op_family(node.name)      # e.g. Optimizer_AdamOptimizer
            f = fams.setdefault(fam, {"n_ops": 0, "flops": 0.0,
                                      "bytes": 0.0})
            f["n_ops"] += 1
            f["flops"] += per_flops * elems
            f["bytes"] += per_moves * 4.0 * elems
    for node in topo:
        if node.is_placeholder or node.is_dataloader or node.is_optimizer \
                or node.is_gradient:
            continue
        flops, bytes_ = op_cost_estimate(node, meta_of)
        fam = op_family(node.name)
        if training:
            mult = 3.0 if (fam in _MATMUL_FAMILIES
                           or fam in _CONV_FAMILIES) else 2.0
            flops *= mult
            bytes_ *= mult
        f = fams.setdefault(fam, {"n_ops": 0, "flops": 0.0, "bytes": 0.0})
        f["n_ops"] += 1
        f["flops"] += flops
        f["bytes"] += bytes_

    measured: Dict[str, float] = {}
    if attribution is not None:
        for fam, agg in attribution.families().items():
            measured[fam] = agg["wall_us"] / attribution.steps

    # hetutrail cp column: `cp` is a blocking-chain legs dict (profiler
    # cp_legs / trail.step_legs output, typically from the measured run's
    # telemetry dir). Families that execute at the PS boundary get the PS
    # legs' share; everything else runs inside the dispatched program and
    # gets the compute leg's.
    cp_compute = cp_ps = None
    cp_total = sum(cp.values()) if cp else 0.0
    if cp and cp_total > 0:
        cp_compute = cp.get("compute", 0.0) / cp_total
        cp_ps = (cp.get("ps_pull", 0.0) + cp.get("ps_push", 0.0)) / cp_total
    ridge = (peak_tflops * 1e12) / (peak_gbs * 1e9)   # flops per byte
    rows = []
    for fam, f in fams.items():
        inten = f["flops"] / f["bytes"] if f["bytes"] else math.inf
        pred_us = max(f["flops"] / (peak_tflops * 1e12),
                      f["bytes"] / (peak_gbs * 1e9)) * 1e6
        m = measured.get(fam)
        cp_frac = None
        if cp_compute is not None:
            is_ps = any(t in fam.lower()
                        for t in ("embeddinglookup", "embedding_lookup",
                                  "parameterserver", "allreduce", "comm"))
            cp_frac = round(cp_ps if is_ps else cp_compute, 4)
        rows.append(RooflineRow(
            family=fam, n_ops=f["n_ops"], flops=f["flops"],
            bytes=f["bytes"], intensity=inten,
            bound="compute" if inten >= ridge else "memory",
            predicted_us=pred_us, measured_us=m,
            residual=(m / pred_us) if (m and pred_us > 0) else None,
            cp_fraction=cp_frac))
    rows.sort(key=lambda r: -r.predicted_us)
    return rows


def roofline_report(rows: List[RooflineRow],
                    peak_tflops: float = DEFAULT_PEAK_TFLOPS,
                    peak_gbs: float = DEFAULT_PEAK_GBS) -> dict:
    """The machine-readable residual table (``--roofline --json``): op
    family, predicted, measured, residual per row, with the assumed peaks
    the predictions were computed against (an MFU or residual without its
    peak is not a measurement — docs/ROOFLINE.md). This document is the
    calibration input ``hetulint --plan --calibrate`` consumes and the
    thing CI diffs run-over-run."""
    return {
        "kind": "roofline",
        "peak_tflops": peak_tflops,
        "peak_gbs": peak_gbs,
        "rows": [r.__dict__ for r in rows],
    }


def format_roofline(rows: List[RooflineRow],
                    peak_tflops: float = DEFAULT_PEAK_TFLOPS,
                    peak_gbs: float = DEFAULT_PEAK_GBS) -> str:
    ridge = (peak_tflops * 1e12) / (peak_gbs * 1e9)
    lines = [f"# assumed peaks: {peak_tflops:g} TFLOP/s, {peak_gbs:g} GB/s "
             f"-> ridge {ridge:.1f} flop/byte (docs/ROOFLINE.md: "
             "assumptions, not readings)",
             f"{'family':<22} {'ops':>4} {'GFLOP/step':>11} {'MB/step':>9} "
             f"{'flop/B':>8} {'bound':>8} {'pred us':>9} {'meas us':>9} "
             f"{'resid':>6}"
             + ("  cp_frac" if any(r.cp_fraction is not None
                                   for r in rows) else "")]
    for r in rows:
        lines.append(
            f"{r.family[:22]:<22} {r.n_ops:>4} {r.flops / 1e9:>11.3f} "
            f"{r.bytes / 1e6:>9.2f} "
            f"{min(r.intensity, 1e6):>8.1f} {r.bound:>8} "
            f"{r.predicted_us:>9.1f} "
            f"{r.measured_us if r.measured_us is not None else float('nan'):>9.1f} "
            f"{r.residual if r.residual is not None else float('nan'):>6.2f}"
            + (f"  {r.cp_fraction:>7.3f}" if r.cp_fraction is not None
               else ""))
    tf = sum(r.flops for r in rows)
    tb = sum(r.bytes for r in rows)
    tp = max(tf / (peak_tflops * 1e12), tb / (peak_gbs * 1e9)) * 1e6
    lines.append(f"{'TOTAL':<22} {sum(r.n_ops for r in rows):>4} "
                 f"{tf / 1e9:>11.3f} {tb / 1e6:>9.2f} {'':>8} {'':>8} "
                 f"{tp:>9.1f}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# pillar 3 — the perf-regression gate
# ---------------------------------------------------------------------------

def _watch_mod():
    """The hetuwatch module, loadable BOTH ways this file is (the
    ``_trail_mod`` pattern) — watch.py is stdlib-only."""
    global _WATCH_MOD
    if _WATCH_MOD is None:
        import importlib.util
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "watch.py")
        spec = importlib.util.spec_from_file_location("_hetuprof_watch",
                                                      path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules["_hetuprof_watch"] = mod
        spec.loader.exec_module(mod)
        _WATCH_MOD = mod
    return _WATCH_MOD


def load_summary(path: str) -> Tuple[Dict[str, dict], dict]:
    """Normalize any of the bench artifacts into ``(cells, meta)``:

    - the bench final line (``{"metric", ..., "detail": {cell: {...}}}``),
    - a driver ``BENCH_rNN.json`` wrapper (``{"rc", "parsed": <line>}``),
    - a ``BENCH_PARTIAL.json`` ledger (``{"cells": {k: {"result": ...}}}``),
    - a bare ``{cell: {...}}`` mapping,
    - or a telemetry DIRECTORY carrying a live hetuwatch residual stream
      (``kind:"watch"`` rows -> a ``plan_watch`` cell whose ``divergence``
      / ``residual_*`` metrics gate lower-is-better — CI fails a PR that
      regresses plan fidelity).

    ``meta['incomplete']`` is True when the artifact itself says the run
    did not finish (rc != 0, ``error``/``incomplete_cells`` markers, or a
    null ``parsed``)."""
    if os.path.isdir(path):
        cells = _watch_mod().summary_cells(path)
        if not cells:
            return {}, {"incomplete": True,
                        "why": f"no hetuwatch rows under {path}"}
        return cells, {"incomplete": False, "why": None}
    with open(path) as f:
        data = json.load(f)
    return normalize_summary(data)


def normalize_summary(data) -> Tuple[Dict[str, dict], dict]:
    meta = {"incomplete": False, "why": None}
    if not isinstance(data, dict):
        return {}, {"incomplete": True, "why": "not a JSON object"}
    if "parsed" in data and ("rc" in data or "cmd" in data):
        if data.get("rc") not in (0, None):
            meta["incomplete"] = True
            meta["why"] = f"driver rc={data.get('rc')}"
        if data["parsed"] is None:
            return {}, {"incomplete": True,
                        "why": meta["why"] or "parsed is null"}
        cells, inner = normalize_summary(data["parsed"])
        inner["incomplete"] = inner["incomplete"] or meta["incomplete"]
        inner["why"] = inner["why"] or meta["why"]
        return cells, inner
    if isinstance(data.get("cells"), dict):       # ledger
        cells = {}
        for k, ent in data["cells"].items():
            if isinstance(ent, dict) and isinstance(ent.get("result"), dict):
                cells[k] = ent["result"]
        return cells, meta
    if isinstance(data.get("detail"), dict):      # bench final line
        cells = {k: v for k, v in data["detail"].items()
                 if isinstance(v, dict) and not k.startswith("_")}
        if data.get("error") or data.get("incomplete_cells"):
            meta["incomplete"] = True
            meta["why"] = data.get("error") or "incomplete_cells present"
        if data.get("value") is None:
            meta["incomplete"] = True
            meta["why"] = meta["why"] or "null headline value"
        return cells, meta
    cells = {k: v for k, v in data.items()
             if isinstance(v, dict) and not k.startswith("_")}
    return cells, meta


_HIGHER_HINTS = ("per_sec", "speedup", "samples_per", "tokens_per")
_LOWER_SUFFIXES = ("_ms", "_mib", "_bytes", "_us", "_s")
# hetuwatch plan-fidelity metrics: a residual ratio of 1.0 is on-plan and
# anything above is drift, so lower always wins (event COUNTS stay
# ungated — an extra recovered event is not a regression)
_LOWER_HINTS = ("residual", "divergence")


def metric_direction(key: str) -> Optional[int]:
    """+1 higher-is-better, -1 lower-is-better, None not gated."""
    leaf = key.rsplit(".", 1)[-1]
    if leaf.endswith("_events") or leaf.endswith("_rows"):
        return None
    if leaf.startswith("mfu") or any(h in leaf for h in _HIGHER_HINTS):
        return 1
    if leaf.startswith("ms_") or leaf.endswith(_LOWER_SUFFIXES) \
            or any(h in leaf for h in _LOWER_HINTS):
        return -1
    return None


def _flatten_cell(cell: dict, prefix: str = "") -> Dict[str, float]:
    out: Dict[str, float] = {}
    for k, v in cell.items():
        if k.startswith("_"):
            continue
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten_cell(v, key + "."))
        elif isinstance(v, (int, float)) and not isinstance(v, bool) \
                and math.isfinite(v):
            out[key] = float(v)
    return out


def summary_has_measurement(cells: Dict[str, dict]) -> bool:
    """Does this summary contain at least one gateable number? (bench.py's
    baseline-selection predicate: a round of nothing but errors — BENCH_r05
    — must not become the trajectory anchor.)"""
    for data in cells.values():
        if isinstance(data, dict) and "error" not in data and any(
                metric_direction(k) is not None
                for k in _flatten_cell(data)):
            return True
    return False


@dataclass
class GateResult:
    status: int
    regressions: list
    improvements: list
    incomplete: list            # baseline-measured cells missing/errored now
    skipped: list               # cells the baseline could not measure
    compared: int
    tolerance_pct: float
    baseline: str = ""
    current: str = ""
    notes: Tuple[str, ...] = ()   # provenance caveats (partial baseline...)

    def as_dict(self) -> dict:
        d = self.__dict__.copy()
        d["verdict"] = self.verdict
        return d

    @property
    def verdict(self) -> str:
        return {GATE_OK: "clean", GATE_REGRESSED: "regressed",
                GATE_INCOMPLETE_CURRENT: "incomplete-current",
                GATE_INCOMPLETE_BASELINE: "incomplete-baseline"}[self.status]

    def report(self) -> str:
        lines = [f"hetuprof gate: {self.verdict} (exit {self.status}) — "
                 f"{self.compared} metric(s) compared at "
                 f"±{self.tolerance_pct:g}% tolerance"]
        for r in self.regressions:
            lines.append(f"  REGRESSED {r['cell']}.{r['metric']}: "
                         f"{r['baseline']:g} -> {r['current']:g} "
                         f"({r['delta_pct']:+.1f}%)")
        for r in self.improvements[:5]:
            lines.append(f"  improved  {r['cell']}.{r['metric']}: "
                         f"{r['baseline']:g} -> {r['current']:g} "
                         f"({r['delta_pct']:+.1f}%)")
        if self.incomplete:
            lines.append("  could NOT measure (baseline had these, current "
                         "run did not): " + ", ".join(self.incomplete))
        if self.skipped:
            lines.append("  baseline has no measurement (skipped): "
                         + ", ".join(self.skipped))
        for n in self.notes:
            lines.append(f"  note: {n}")
        return "\n".join(lines)


def gate(baseline_cells: Dict[str, dict], current_cells: Dict[str, dict],
         tolerance_pct: float = 10.0,
         baseline_meta: Optional[dict] = None,
         current_meta: Optional[dict] = None) -> GateResult:
    """Cell-by-cell perf diff with could-not-measure semantics.

    A *regression* needs both sides measured and a directed metric moving
    the wrong way past the tolerance. A baseline cell the current run
    errored on (or never reached) is *incomplete*, never a win or a loss:
    status 2 keeps partial runs from polluting the trajectory — the
    BENCH_r05 failure mode this gate exists for. A PARTIAL baseline
    (``baseline_meta['incomplete']``) still gates its measured cells,
    flagged in ``notes``; only one with nothing measurable is status 3."""
    notes = []
    if (baseline_meta or {}).get("incomplete"):
        why = (baseline_meta or {}).get("why") or "marked incomplete"
        notes.append(f"baseline run was partial ({why}); gating only its "
                     "measured cells")
    measurable: Dict[str, Dict[str, float]] = {}
    for cell, data in baseline_cells.items():
        if not isinstance(data, dict) or "error" in data:
            continue
        flat = {k: v for k, v in _flatten_cell(data).items()
                if metric_direction(k) is not None}
        if flat:
            measurable[cell] = flat
    if not measurable:
        return GateResult(GATE_INCOMPLETE_BASELINE, [], [], [],
                          sorted(baseline_cells), 0, tolerance_pct,
                          notes=tuple(notes))

    regressions, improvements, incomplete = [], [], []
    compared = 0
    tol = tolerance_pct / 100.0
    for cell, base_flat in sorted(measurable.items()):
        cur = current_cells.get(cell)
        if not isinstance(cur, dict) or "error" in cur:
            incomplete.append(cell)
            continue
        cur_flat = _flatten_cell(cur)
        seen_any = False
        for metric, bval in base_flat.items():
            if metric not in cur_flat:
                continue
            direction = metric_direction(metric)
            cval = cur_flat[metric]
            seen_any = True
            compared += 1
            if bval == 0:
                continue
            delta = (cval - bval) / abs(bval)
            entry = {"cell": cell, "metric": metric, "baseline": bval,
                     "current": cval, "delta_pct": 100.0 * delta}
            if direction * delta < -tol:
                regressions.append(entry)
            elif direction * delta > tol:
                improvements.append(entry)
        if not seen_any:
            incomplete.append(cell)
    skipped = sorted(set(current_cells) - set(measurable))
    if (current_meta or {}).get("incomplete"):
        # the current artifact says it was cut short: any baseline cell it
        # did not reproduce is already in `incomplete` above; make sure a
        # formally-complete-looking diff still cannot claim a clean pass
        if not incomplete and compared == 0:
            incomplete = sorted(measurable)
    if regressions:
        status = GATE_REGRESSED
    elif incomplete:
        status = GATE_INCOMPLETE_CURRENT
    else:
        status = GATE_OK
    return GateResult(status, regressions, improvements, incomplete,
                      skipped, compared, tolerance_pct,
                      notes=tuple(notes))


def gate_files(baseline_path: str, current_path: Optional[str] = None,
               current_data=None, tolerance_pct: float = 10.0) -> GateResult:
    try:
        base_cells, base_meta = load_summary(baseline_path)
    except (OSError, ValueError) as e:
        return GateResult(GATE_INCOMPLETE_BASELINE, [], [], [], [], 0,
                          tolerance_pct, baseline=f"{baseline_path}: {e}")
    if current_data is not None:
        cur_cells, cur_meta = normalize_summary(current_data)
    else:
        try:
            cur_cells, cur_meta = load_summary(current_path)
        except (OSError, ValueError) as e:
            r = GateResult(GATE_INCOMPLETE_CURRENT, [], [],
                           sorted(base_cells), [], 0, tolerance_pct)
            r.current = f"{current_path}: {e}"
            return r
    res = gate(base_cells, cur_cells, tolerance_pct,
               baseline_meta=base_meta, current_meta=cur_meta)
    res.baseline = baseline_path
    res.current = current_path or "<inline>"
    return res


def gate_self_check(out=sys.stdout) -> int:
    """Tier-1-safe smoke: exercises all four gate verdicts on synthetic
    summaries and verifies the exit-code contract. Returns 0 when the
    contract holds (the verify-skill/CI hook)."""
    good = {"detail": {"cell_a": {"samples_per_sec": 100.0, "step_ms": 10.0},
                       "cell_b": {"mfu": 0.4}},
            "value": 100.0}
    slow = {"detail": {"cell_a": {"samples_per_sec": 50.0, "step_ms": 20.0},
                       "cell_b": {"mfu": 0.4}},
            "value": 50.0}
    partial = {"detail": {"cell_a": {"samples_per_sec": 100.0,
                                     "step_ms": 10.0},
                          "cell_b": {"error": "rc=124"}},
               "value": 100.0, "incomplete_cells": ["cell_b"]}
    empty = {"detail": {"cell_a": {"error": "skipped"}}, "value": None}
    cases = [
        ("clean", good, good, GATE_OK),
        ("regressed", good, slow, GATE_REGRESSED),
        ("incomplete-current", good, partial, GATE_INCOMPLETE_CURRENT),
        ("incomplete-baseline", empty, good, GATE_INCOMPLETE_BASELINE),
    ]
    ok = True
    for label, base, cur, want in cases:
        bc, bm = normalize_summary(base)
        cc, cm = normalize_summary(cur)
        got = gate(bc, cc, 10.0, baseline_meta=bm, current_meta=cm).status
        state = "ok" if got == want else f"FAIL (got {got})"
        if got != want:
            ok = False
        print(f"hetuprof --gate --check: {label} -> exit {want} {state}",
              file=out)
    return 0 if ok else 1


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="hetuprof",
        description="op-level performance attribution, roofline analysis "
                    "and the perf-regression gate (docs/PROFILING.md)")
    ap.add_argument("target", nargs="?",
                    help="telemetry dir (attribution mode) or "
                         "MODULE:BUILDER (--roofline mode)")
    ap.add_argument("--roofline", action="store_true",
                    help="predicted roofline table for a graph builder "
                         "(hetulint's MODULE:BUILDER convention)")
    ap.add_argument("--gate", nargs="?", const="", metavar="BASELINE",
                    help="diff a bench/telemetry summary against BASELINE; "
                         "exit 0 clean / 1 regressed / 2 incomplete run / "
                         "3 unusable baseline")
    ap.add_argument("--current", metavar="SUMMARY",
                    help="current summary for --gate: a bench artifact, "
                         "or a telemetry dir carrying a hetuwatch "
                         "residual stream (gates plan fidelity — "
                         "hetu_plan_divergence / worst-leg residual)")
    ap.add_argument("--tolerance", type=float, default=10.0, metavar="PCT",
                    help="gate tolerance percent (default 10)")
    ap.add_argument("--check", action="store_true",
                    help="with --gate: self-check the exit-code contract "
                         "(CI smoke, no files needed)")
    ap.add_argument("--trace-dir", help="XLA profiler dir override")
    ap.add_argument("--cp-from", metavar="TEL_DIR",
                    help="with --roofline: telemetry dir whose measured "
                         "critical-path legs fill the cp_frac column "
                         "(hetutrail, docs/OBSERVABILITY.md pillar 5)")
    ap.add_argument("--hlo", help="optimized-HLO text file for the exact "
                                  "instruction->op join")
    ap.add_argument("--steps", type=int, help="steps in the trace window "
                    "(default: count of hetu_step annotations)")
    ap.add_argument("--top", type=int, default=25,
                    help="rows in the attribution table")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--peak-tflops", type=float, default=DEFAULT_PEAK_TFLOPS)
    ap.add_argument("--peak-gbs", type=float, default=DEFAULT_PEAK_GBS)
    args = ap.parse_args(argv)

    if args.gate is not None:
        if args.check:
            return gate_self_check()
        if not args.gate:
            print("hetuprof: --gate needs a BASELINE file (or --check)",
                  file=sys.stderr)
            return GATE_INCOMPLETE_BASELINE
        if not args.current:
            print("hetuprof: --gate needs --current SUMMARY",
                  file=sys.stderr)
            return GATE_INCOMPLETE_CURRENT
        res = gate_files(args.gate, args.current,
                         tolerance_pct=args.tolerance)
        print(json.dumps(res.as_dict(), indent=2) if args.as_json
              else res.report())
        return res.status

    if args.roofline:
        if not args.target:
            print("hetuprof: --roofline needs a MODULE:BUILDER target",
                  file=sys.stderr)
            return 2
        from hetu_tpu.analysis.cli import load_builder
        result = load_builder(args.target)()
        graph = result[0] if (isinstance(result, tuple)
                              and len(result) == 2) else result
        if isinstance(graph, dict):
            graph = [n for nodes in graph.values() for n in nodes]
        elif not isinstance(graph, (list, tuple)):
            graph = [graph]
        attribution = None
        if args.trace_dir:
            events: List[dict] = []
            for p in find_xla_traces(args.trace_dir):
                events.extend(load_trace_events(p))
            op_map = None
            if args.hlo:
                with open(args.hlo) as f:
                    op_map = hlo_op_map(f.read())
            if events:
                attribution = attribute(events, op_map=op_map,
                                        steps=args.steps)
        cp = None
        if args.cp_from:
            means = step_phase_means(read_metrics_records(args.cp_from))
            cp = cp_legs(means) if means else None
        rows = roofline_rows(list(graph), peak_tflops=args.peak_tflops,
                             peak_gbs=args.peak_gbs,
                             attribution=attribution, cp=cp)
        if args.as_json:
            # structured residual table — the hetulint --plan --calibrate
            # input; cost_model.load_calibration also accepts the bare
            # row-list form this replaced
            print(json.dumps(roofline_report(
                rows, args.peak_tflops, args.peak_gbs), indent=2))
        else:
            print(format_roofline(rows, args.peak_tflops, args.peak_gbs))
        return 0

    if not args.target:
        ap.print_usage(sys.stderr)
        return 2
    report, attribution = profile_dir_with_attribution(
        args.target, trace_dir=args.trace_dir, hlo_path=args.hlo,
        steps=args.steps)
    if args.as_json:
        print(json.dumps(report, indent=2))
        return 0
    if report["breakdown"]:
        b = report["breakdown"]
        print(f"per-step breakdown over {report['phase_means_ms']['n_steps']}"
              f" steady-state steps: step {b['step_ms']:.2f} ms = compute "
              f"{b['compute_ms']:.2f} + collectives {b['collective_ms']:.2f}"
              f" + ps-rpc {b['ps_rpc_ms']:.2f} + host {b['host_ms']:.2f}"
              + (f"  (comm fraction {b['comm_fraction']:.1%})"
                 if "comm_fraction" in b else ""))
        if "cp_dominant" in b:
            legs = "  ".join(f"{k}={v:.2f}" for k, v in
                             b["cp_legs_ms"].items())
            print(f"critical path (hetutrail): {legs} ms — dominant "
                  f"{b['cp_dominant']} at {b['cp_fraction']:.1%} of the "
                  "blocking chain")
    if report["memory"]:
        mem = report["memory"]
        parts = [f"{k.replace('hetu_hbm_', '').replace('_bytes', '')} "
                 f"{v / 2**20:.1f} MiB" for k, v in sorted(mem.items())]
        print("HBM (compiled program vs live): " + ", ".join(parts))
    if attribution is not None:
        print(attribution.table(top=args.top))
    for why in report["incomplete"]:
        print(f"# incomplete: {why}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
