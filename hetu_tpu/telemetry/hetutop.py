"""``hetutop`` — live terminal dashboard over a telemetry directory, plus the
``--check`` schema validator CI uses (exit 0 valid / 1 invalid, mirroring the
``hetulint --json`` pattern).

Reads the per-rank ``metrics-r<N>.jsonl`` files a run writes (see
docs/OBSERVABILITY.md for the record schemas) and renders throughput, step-
time percentiles, MFU against the assumed peak (docs/ROOFLINE.md), PS-tier
health and cache hit rate. Stdlib-only and jax-free: it runs on a login node
against a shared filesystem while the job trains.
"""
from __future__ import annotations

import argparse
import collections
import glob
import json
import math
import os
import sys
import time
from typing import Optional

from . import story as _story      # shared ledger readers (stdlib-only)
from .profiler import attn_flops   # stdlib-only module (shared w/ bench.py)

# MFU denominator when no peak rides in the records: same default as
# bench.py / docs/ROOFLINE.md (assumption, not a reading)
DEFAULT_PEAK_TFLOPS = float(os.environ.get("HETU_PEAK_TFLOPS", "197"))

# metrics snapshots ride only every Nth step record (plus every "final"
# record) — the per-step cost of percentile math is paid on a cadence
STEP_REQUIRED = ("sub", "step", "step_ms")
WINDOW = 200   # dashboard statistics run over the last N step records


def metrics_files(dir_path: str) -> list[str]:
    return sorted(glob.glob(os.path.join(dir_path, "metrics-r*.jsonl")))


def load_records(path: str, errors: Optional[list] = None,
                 rotated: bool = False) -> list[dict]:
    """One metrics file's records via the shared hetustory reader —
    --check stays strict (every classified line, torn tails included,
    formats into ``errors``); ``rotated=True`` prepends the ``.1`` backup
    so rotation can't hide records from the validator."""
    errs: Optional[list] = [] if errors is not None else None
    reader = _story.read_rows_rotated if rotated else _story.read_rows
    out = [r.rec for r in reader(path, errs)]
    if errors is not None:
        errors.extend(_story.format_error(e) for e in errs)
    return out


# ---------------------------------------------------------------------------
# --check: schema validation
# ---------------------------------------------------------------------------

def check_dir(dir_path: str, out=sys.stdout) -> int:
    """Validate every record in the directory; print a summary of what a
    dashboard would read. Returns a process exit code (0 ok, 1 invalid)."""
    files = metrics_files(dir_path)
    if not files:
        print(f"hetutop --check: no metrics-r*.jsonl under {dir_path}",
              file=out)
        return 1
    errors: list[str] = []
    n_steps = n_events = n_ps = n_scope = 0
    step_ms: list[float] = []
    last_metrics: Optional[dict] = None   # None = no snapshot seen at all
    ps_last: dict = {}
    for path in files:
        for rec in load_records(path, errors, rotated=True):
            kind = rec.get("kind")
            if kind == "step":
                missing = [k for k in STEP_REQUIRED if k not in rec]
                if missing:
                    errors.append(f"{path}: step record missing {missing}")
                    continue
                if "metrics" in rec and not isinstance(rec["metrics"], dict):
                    errors.append(f"{path}: step 'metrics' is not an object")
                    continue
                n_steps += 1
                step_ms.append(float(rec["step_ms"]))
                if isinstance(rec.get("metrics"), dict):
                    last_metrics = rec["metrics"]
            elif kind == "final":
                if not isinstance(rec.get("metrics"), dict):
                    errors.append(f"{path}: final record missing 'metrics'")
                    continue
                last_metrics = rec["metrics"]
            elif kind == "event":
                if "name" not in rec:
                    errors.append(f"{path}: event record missing 'name'")
                    continue
                n_events += 1
            elif kind == "ps_server":
                if "server" not in rec:
                    errors.append(f"{path}: ps_server record missing "
                                  "'server'")
                    continue
                n_ps += 1
                ps_last[rec["server"]] = rec
            elif kind == "scope":
                # hetuscope numeric-health row (cadence steps only)
                missing = [k for k in ("sub", "step") if k not in rec]
                if missing:
                    errors.append(f"{path}: scope record missing {missing}")
                    continue
                n_scope += 1
            elif kind is None:
                errors.append(f"{path}: record missing 'kind'")
    for msg in errors[:20]:
        print(f"hetutop --check: {msg}", file=out)
    if len(errors) > 20:
        print(f"hetutop --check: ... and {len(errors) - 20} more", file=out)
    if n_steps == 0:
        print("hetutop --check: no valid step records", file=out)
        return 1
    if last_metrics is None:
        print("hetutop --check: no metrics snapshot (step-with-metrics or "
              "final record) found", file=out)
        return 1
    # the summary below is the CI-readable proof of what the dashboard
    # reads: step time, recompile count, PS latency + snapshot age
    rec_count = last_metrics.get("hetu_recompiles_total")
    print(f"hetutop --check: {len(files)} rank file(s), {n_steps} step, "
          f"{n_events} event, {n_ps} ps_server, {n_scope} scope record(s); "
          f"step_ms p50={_pctl(step_ms, 50):.3f} "
          f"recompiles={rec_count if rec_count is not None else 'n/a'}",
          file=out)
    for sid in sorted(ps_last):
        r = ps_last[sid]
        print(f"hetutop --check: ps server {sid}: "
              f"updates={r.get('updates')} "
              f"snapshot_age_ms={r.get('snapshot_age_ms')} "
              f"rpc p50={last_metrics.get('hetu_ps_pull_ms_p50', 'n/a')}",
              file=out)
    return 1 if errors else 0


def _pctl(vals: list[float], p: float) -> float:
    if not vals:
        return float("nan")
    s = sorted(vals)
    k = min(len(s) - 1, max(0, int(round(p / 100.0 * (len(s) - 1)))))
    return s[k]


# ---------------------------------------------------------------------------
# dashboard
# ---------------------------------------------------------------------------

def gather(dir_path: str) -> dict:
    """One dashboard frame's worth of state from the directory (full
    parse — one-shot use: ``--once``, tests). The live loop uses
    :class:`Follower`, which tails incrementally."""
    return _aggregate({p: load_records(p, rotated=True)
                       for p in metrics_files(dir_path)})


class Follower:
    """Incremental reader for live mode: keeps a byte offset and a bounded
    record buffer per file, so each frame parses only appended lines —
    frame cost stays O(new data) instead of growing with run length."""

    # per-file history: enough for the WINDOW step stats plus the
    # interleaved snapshot/event/ps rows that ride between step records
    BUFFER = 4 * WINDOW

    def __init__(self, dir_path: str):
        self.dir = dir_path
        # shared rotation-aware tailer (hetustory): on rotation the old
        # generation's unread tail is drained from the .1 backup instead of
        # dropped, and an existing backup seeds the dashboard's history
        self._follow = _story.LedgerFollower(backlog=True)
        self._recs: dict = {}
        # once-per-run records (run_info/model_info) and slow-cadence rows
        # (ps_server, hetuscope scope) must survive eviction from the
        # bounded buffers
        self._sticky_run_info: dict = {}
        self._sticky_model: dict = {}
        self._sticky_ps: dict = {}
        self._sticky_scope: dict = {}

    def _poll_file(self, path: str):
        buf = self._recs.get(path)
        if buf is None:
            buf = self._recs[path] = collections.deque(
                maxlen=self.BUFFER)
        buf.extend(self._follow.poll(path))
        return buf

    def poll(self) -> dict:
        state = _aggregate({p: self._poll_file(p)
                            for p in metrics_files(self.dir)})
        self._sticky_run_info.update(state["run_info"])
        self._sticky_model.update(state["model"])
        self._sticky_ps.update(state["ps"])
        self._sticky_scope.update(state["scope"])
        state["run_info"] = dict(self._sticky_run_info)
        state["model"] = dict(self._sticky_model)
        state["ps"] = dict(self._sticky_ps)
        state["scope"] = dict(self._sticky_scope)
        return state


def _aggregate(recs_by_file: dict) -> dict:
    state: dict = {"ranks": {}, "events": [], "ps": {}, "run_info": {},
                   "model": {}, "scope": {}}
    for path, recs in recs_by_file.items():
        steps = [r for r in recs if r.get("kind") == "step"
                 and all(k in r for k in STEP_REQUIRED)]
        m = {}
        snaps = []   # (ts, metrics) of every snapshot-bearing record
        for r in recs:
            kind = r.get("kind")
            if kind == "event":
                state["events"].append(r)
            elif kind == "ps_server":
                state["ps"][r.get("server")] = r
            elif kind == "run_info":
                state["run_info"].update(r)
            elif kind == "model_info":
                # model geometry (telemetry.record_model_info) unlocks the
                # analytic attention-inclusive MFU denominator
                state["model"].update(r)
            elif kind == "scope":
                # latest hetuscope numeric-health row per rank
                state["scope"][r.get("rank", 0)] = r
            if kind in ("step", "final") and isinstance(
                    r.get("metrics"), dict):
                m = r["metrics"]   # latest snapshot wins
                if "ts" in r:
                    snaps.append((r["ts"], r["metrics"]))
        if not steps:
            continue
        rank = steps[-1].get("rank", 0)
        window = steps[-WINDOW:]
        t = [r["step_ms"] for r in window]
        span_s = (window[-1]["ts"] - window[0]["ts"]) if len(window) > 1 \
            else 0.0
        ex_rate = None
        if len(snaps) > 1 and snaps[-1][0] > snaps[0][0]:
            ex_rate = ((snaps[-1][1].get("hetu_examples_total", 0)
                        - snaps[0][1].get("hetu_examples_total", 0))
                       / (snaps[-1][0] - snaps[0][0]))
        state["ranks"][rank] = {
            "last_step": window[-1]["step"],
            "sub": window[-1]["sub"],
            "steps_per_s": (len(window) - 1) / span_s if span_s > 0 else None,
            "examples_per_s": ex_rate,
            "p50": _pctl(t, 50), "p90": _pctl(t, 90), "p99": _pctl(t, 99),
            "max": max(t),
            "metrics": m,
            "last_ts": window[-1]["ts"],
        }
    state["events"] = state["events"][-5:]
    return state


def _fmt(v, spec=".1f", na="  n/a") -> str:
    return na if v is None else format(v, spec)


def _defloat(v):
    """A recorded number back as a float — hetuscope serializes non-finite
    values as the strings "NaN"/"Infinity" to keep the JSONL strict JSON;
    float() parses them back. None on anything non-numeric."""
    try:
        return float(v)
    except (TypeError, ValueError):
        return None


def _finite(v):
    f = _defloat(v)
    return f if f is not None and math.isfinite(f) else None


def _metric_children(m: dict, base: str, suffix: str):
    """Snapshot entries for one metric family: the unlabeled parent
    (``<base><suffix>``) and/or its labeled children
    (``base{k="v"}suffix`` -> child tag ``k=v``)."""
    out = []
    exact = base + suffix
    for k, v in m.items():
        if k == exact:
            out.append(("", v))
        elif k.startswith(base + "{") and k.endswith(suffix):
            labels = k[len(base) + 1:len(k) - len(suffix) - 1]
            out.append((labels.replace('"', ""), v))
    return sorted(out)


def _mfu_pair(m: dict, model: dict, p50_ms, peak_tflops: float):
    """MFU under BOTH denominators (docs/ROOFLINE.md: 6ND alone overstates
    utilization at long seq): 6ND from the executor's
    ``hetu_flops_per_step_6nd`` gauge; attention-inclusive as 6ND + the
    analytic attention add-on when model geometry is known
    (``telemetry.record_model_info``), else the measured XLA cost-analysis
    flops — which count the score matmuls by construction."""
    if not p50_ms:
        return None, None
    denom = (p50_ms / 1e3) * peak_tflops * 1e12
    f6 = m.get("hetu_flops_per_step_6nd")
    mfu6 = 100.0 * f6 / denom if f6 else None
    f_attn = None
    if f6 and all(k in model for k in ("n_layers", "d_model", "seq_len")):
        # invert tokens with the SAME N that produced the gauge
        # (hetu_params_total; the executor's count includes PS-resident
        # tables) — a user-supplied model n_params may count differently
        # and would scale the recovered token count by the ratio
        n = m.get("hetu_params_total") or model.get("n_params")
        if n:
            tokens = f6 / (6.0 * n)
            seq = float(model["seq_len"])
            f_attn = f6 + attn_flops(tokens / seq, seq,
                                     model["n_layers"], model["d_model"],
                                     bool(model.get("causal")))
    if f_attn is None:
        f_attn = m.get("hetu_flops_per_step")
    mfu_a = 100.0 * f_attn / denom if f_attn else None
    return mfu6, mfu_a


def render_frame(state: dict, peak_tflops: float = DEFAULT_PEAK_TFLOPS
                 ) -> str:
    lines = []
    info = state["run_info"]
    dev = info.get("device_kind", "?")
    peak = float(info.get("peak_tflops_assumed", peak_tflops))
    lines.append(f"hetutop — device {dev}, assumed peak {peak:g} TFLOP/s "
                 f"(see docs/ROOFLINE.md)")
    lines.append("rank  sub        step   steps/s    ex/s   p50ms   p90ms"
                 "   p99ms   maxms MFU6nd% MFUatt%  recompiles  anomalies")
    for rank in sorted(state["ranks"]):
        r = state["ranks"][rank]
        m = r["metrics"]
        mfu6, mfu_a = _mfu_pair(m, state.get("model", {}), r["p50"], peak)
        lines.append(
            f"{rank:>4}  {r['sub'][:9]:<9}{r['last_step']:>7}"
            f"{_fmt(r['steps_per_s'], '8.2f'):>9}"
            f"{_fmt(r['examples_per_s'], '8.0f'):>8}"
            f"{r['p50']:>8.2f}{r['p90']:>8.2f}{r['p99']:>8.2f}"
            f"{r['max']:>8.2f}"
            f"{_fmt(mfu6, '7.1f'):>8}"
            f"{_fmt(mfu_a, '7.1f'):>8}"
            f"{m.get('hetu_recompiles_total', 0):>11g}"
            f"{m.get('hetu_anomaly_trips_total', 0):>10g}")
        extras = []
        for base, suffix, label in (
                ("hetu_dataloader_wait_ms", "_p50", "dl wait p50"),
                ("hetu_ps_pull_ms", "_p50", "ps pull p50"),
                ("hetu_ps_push_ms", "_p50", "ps push p50"),
                ("hetu_cache_hit_rate", "", "cache hit"),
                ("hetu_comm_fraction", "", "comm frac"),
                ("hetu_comm_quant_ratio", "", "quant ratio")):
            unit = "" if base.endswith(("rate", "fraction", "ratio")) \
                else "ms"
            for child, v in _metric_children(m, base, suffix):
                tag = f"[{child}]" if child else ""
                extras.append(f"{label}{tag} {v:.3g}{unit}")
        hbm = m.get("hetu_hbm_peak_bytes")
        if hbm:
            live = m.get("hetu_hbm_live_bytes")
            extras.append(f"hbm compiled {hbm / 2**20:.0f}MiB"
                          + (f" live {live / 2**20:.0f}MiB" if live else ""))
        if extras:
            lines.append("      " + "  |  ".join(extras))
    if state.get("scope"):
        # hetuscope numeric health (docs/OBSERVABILITY.md): latest cadence
        # row per rank — global grad norm, worst layer, update ratio,
        # non-finite op count
        lines.append("numeric health (hetuscope):")
        for rank in sorted(state["scope"]):
            s = state["scope"][rank]
            params = s.get("params") or {}
            worst = max(params.items(),
                        key=lambda kv: _finite(kv[1].get("grad_norm"))
                        or 0.0,
                        default=None)
            # _finite filters None, NaN (zero-norm params) and the "NaN"
            # strings a trip row serializes
            ratios = [r for d in params.values()
                      if (r := _finite(d.get("update_ratio"))) is not None]
            ops = s.get("ops") or {}
            nonfin = [k for k, v in ops.items()
                      if (_defloat(v.get("nonfinite")) or 0.0) > 0]
            line = (f"  r{rank} step {s.get('step')}: "
                    f"loss {_fmt(_defloat(s.get('loss')), '.4g', 'n/a')} "
                    f"grad_norm "
                    f"{_fmt(_defloat(s.get('grad_norm')), '.4g', 'n/a')}")
            if worst is not None:
                line += (f"  worst layer {worst[0]} "
                         f"({_finite(worst[1].get('grad_norm')) or 0.0:.3g})")
            if ratios:
                line += f"  upd/param max {max(ratios):.3g}"
            line += (f"  NONFINITE: {', '.join(nonfin[:4])}" if nonfin
                     else "  nonfinite ops: 0")
            lines.append(line)
    # hetukern dispatch panel (docs/KERNELS.md): per-kernel pallas vs
    # fallback vs off tallies from hetu_kernel_dispatch_total — which tier
    # served each op family in the programs now compiled. Absent (no line)
    # when nothing ever dispatched (kernel tier untouched).
    kern: dict = {}
    for rk in state["ranks"].values():
        for child, v in _metric_children(
                rk["metrics"], "hetu_kernel_dispatch_total", ""):
            if not child:
                continue
            labels = dict(p.split("=", 1) for p in child.split(",")
                          if "=" in p)
            name = labels.get("kernel")
            path = labels.get("path")
            if name and path:
                ent = kern.setdefault(name, {})
                ent[path] = ent.get(path, 0) + (_defloat(v) or 0)
    if kern:
        parts = []
        for name in sorted(kern):
            ent = kern[name]
            parts.append(name + " " + "/".join(
                f"{p}:{int(ent[p])}" for p in ("pallas", "forced", "fallback", "off")
                if p in ent))
        lines.append("kernels: " + "  ".join(parts))
    # hetu-elastic membership (docs/FAULT_TOLERANCE.md): current world
    # version, live workers/servers, last resize cost — fed by the
    # ElasticAgent's gauges; absent (no line) for non-elastic runs
    wv = None
    memb = {}
    for rk in state["ranks"].values():
        m = rk["metrics"]
        v = _defloat(m.get("hetu_world_version"))
        if v is None:
            continue
        if wv is None or v > wv:
            wv, memb = v, {}
        if v == wv:
            # ranks at the same world merge per-key maxima: a fresh
            # JOINER reports resizes=0 next to a survivor's true count
            for k in ("hetu_world_workers", "hetu_world_servers",
                      "hetu_resizes_total", "hetu_resize_duration_ms"):
                x = _defloat(m.get(k))
                if x is not None and (memb.get(k) is None
                                      or x > memb[k]):
                    memb[k] = x
    if wv is not None:
        live_ranks = len(state["ranks"])
        line = (f"membership: world v{int(wv)}  "
                f"workers {_fmt(memb.get('hetu_world_workers'), '.0f')}"
                f" ({live_ranks} reporting)  "
                f"servers {_fmt(memb.get('hetu_world_servers'), '.0f')}  "
                f"resizes {_fmt(memb.get('hetu_resizes_total'), '.0f')}")
        if memb.get("hetu_resize_duration_ms") is not None:
            line += (f"  last resize "
                     f"{memb['hetu_resize_duration_ms']:.0f}ms")
        lines.append(line)
    # hetutrail (docs/OBSERVABILITY.md pillar 5): per-step blocking chain
    # from the hetu_critical_path_ms{leg=...} gauges (latest-reporting
    # rank) + cross-rank p50 skew straight from the rank table. Absent (no
    # line) when the executor never exported critical-path gauges.
    cp_rank = None
    for rk in sorted(state["ranks"].values(),
                     key=lambda r: r.get("last_ts") or 0):
        if any(k.startswith("hetu_critical_path_ms")
               for k in rk["metrics"]):
            cp_rank = rk
    if cp_rank is not None:
        m = cp_rank["metrics"]
        legs = {child.split("=", 1)[1]: _defloat(v) or 0.0
                for child, v in _metric_children(
                    m, "hetu_critical_path_ms", "") if "=" in child}
        parts = [f"{leg} {legs[leg]:.2f}" for leg in
                 ("feed", "ps_pull", "compute", "ps_push", "poststep")
                 if leg in legs]
        line = "trail: cp(ms) " + " | ".join(parts)
        frac = _defloat(m.get("hetu_cp_fraction"))
        if legs and frac is not None:
            line += (f"  dominant {max(legs, key=legs.get)} "
                     f"{100.0 * frac:.0f}%")
        if len(state["ranks"]) > 1:
            p50s = {r: rk["p50"] for r, rk in state["ranks"].items()}
            slowest = max(p50s, key=p50s.get)
            line += (f"  skew(p50) "
                     f"{max(p50s.values()) - min(p50s.values()):.2f}ms "
                     f"slowest r{slowest}")
        stragglers = 0.0
        for rk in state["ranks"].values():
            for child, v in _metric_children(
                    rk["metrics"], "hetu_events_total", ""):
                if child == "event=straggler":
                    stragglers += _defloat(v) or 0.0
        if stragglers:
            line += f"  stragglers {int(stragglers)}"
        lines.append(line)
    # hetuwatch plan-divergence sentinel (docs/OBSERVABILITY.md pillar 6):
    # per-leg measured/predicted residual EWMAs + the worst-leg divergence
    # gauge (1.0 = on plan) from the latest-reporting watched rank, plus
    # any latched divergence / SLO-breach event counts. Absent (no line)
    # when no rank armed the watch.
    w_rank = None
    for rk in sorted(state["ranks"].values(),
                     key=lambda r: r.get("last_ts") or 0):
        if any(k.startswith("hetu_plan_residual") for k in rk["metrics"]):
            w_rank = rk
    if w_rank is not None:
        m = w_rank["metrics"]
        resid = {child.split("=", 1)[1]: _defloat(v) or 0.0
                 for child, v in _metric_children(
                     m, "hetu_plan_residual", "") if "=" in child}
        parts = [f"{leg} {resid[leg]:.2f}x" for leg in
                 ("feed", "ps_pull", "compute", "ps_push", "poststep")
                 if leg in resid]
        line = "watch: residual " + " | ".join(parts)
        div = _defloat(m.get("hetu_plan_divergence"))
        if div is not None:
            line += f"  divergence {div:.2f}"
            if div > 1.5:
                line += " DIVERGED"
        div_evs = slo_evs = 0.0
        for rk in state["ranks"].values():
            for child, v in _metric_children(
                    rk["metrics"], "hetu_events_total", ""):
                if child == "event=plan_divergence":
                    div_evs += _defloat(v) or 0.0
                elif child == "event=slo_breach":
                    slo_evs += _defloat(v) or 0.0
        if div_evs:
            line += f"  divergence events {int(div_evs)}"
        if slo_evs:
            line += f"  slo breaches {int(slo_evs)}"
        lines.append(line)
    # hetupilot self-tuning controller (docs/FAULT_TOLERANCE.md
    # "Self-tuning with guardrails"): actuation/rollback era counts plus
    # whether a verdict is still measuring, from the controller's gauges.
    # Absent (no line) when no rank armed the pilot.
    p_state = p_act = p_rb = None
    for rk in state["ranks"].values():
        m = rk["metrics"]
        if "hetu_pilot_state" not in m:
            continue
        p_state = max(p_state or 0.0, _defloat(m.get("hetu_pilot_state"))
                      or 0.0)
        p_act = (p_act or 0.0) + (_defloat(
            m.get("hetu_pilot_actuations_total")) or 0.0)
        p_rb = (p_rb or 0.0) + (_defloat(
            m.get("hetu_pilot_rollbacks_total")) or 0.0)
    if p_state is not None:
        line = (f"pilot: actuations {int(p_act or 0)}  "
                f"rollbacks {int(p_rb or 0)}  "
                + ("MEASURING" if p_state >= 1.0 else "idle"))
        lines.append(line)
    # hetuchaos transport hardening (docs/FAULT_TOLERANCE.md "Chaos
    # testing & transport hardening"): retry/timeout/CRC health summed
    # across ranks, plus any injected-fault count when a chaos schedule
    # is armed (test runs only). Absent (no line) while every counter is
    # zero — the healthy-wire steady state.
    ch = {k: 0.0 for k in ("hetu_rpc_timeouts_total", "hetu_rpc_backoff_ms",
                           "hetu_crc_rejects_total",
                           "hetu_chaos_faults_total")}
    for rk in state["ranks"].values():
        m = rk["metrics"]
        for k in ch:
            ch[k] += _defloat(m.get(k)) or 0.0
    if any(ch.values()):
        line = (f"chaos: timeouts {int(ch['hetu_rpc_timeouts_total'])}  "
                f"backoff {ch['hetu_rpc_backoff_ms']:.0f}ms  "
                f"crc rejects {int(ch['hetu_crc_rejects_total'])}")
        if ch["hetu_chaos_faults_total"]:
            line += (f"  injected faults "
                     f"{int(ch['hetu_chaos_faults_total'])} (chaos armed)")
        lines.append(line)
    # hetusave coordinated job snapshots (docs/FAULT_TOLERANCE.md
    # "Coordinated job snapshots"): newest committed epoch + the wall
    # cost of taking it, from take_job_snapshot's gauges. Absent (no
    # line) for jobs that never committed a coordinated epoch.
    ep, ep_ms = None, None
    for rk in state["ranks"].values():
        m = rk["metrics"]
        v = _defloat(m.get("hetu_job_epoch"))
        if v is not None and (ep is None or v > ep):
            ep = v
            ep_ms = _defloat(m.get("hetu_snapshot_last_ms"))
    if ep is not None:
        line = f"snapshot: job epoch {int(ep)} committed"
        if ep_ms is not None:
            line += f"  last stall {ep_ms:.0f}ms"
        lines.append(line)
    if state["ps"]:
        lines.append("PS servers:")
        for sid in sorted(state["ps"]):
            r = state["ps"][sid]
            lines.append(
                f"  s{sid}: updates={r.get('updates')} "
                f"reqs={r.get('requests')} "
                f"apply_avg_ms={_fmt(r.get('apply_ms_avg'), '.3f')} "
                f"snap v{r.get('snapshot_version')} "
                f"age={_fmt(r.get('snapshot_age_ms'), '.0f')}ms "
                f"dedup_clients={r.get('dedup_clients')}")
        # hetuq wire accounting (docs/COMM_QUANT.md): worker-side raw-vs-
        # wire byte counters over every quantizable value payload — with
        # quantization off raw == wire and the ratio reads 1.00x
        qraw = qwire = 0.0
        for rk in state["ranks"].values():
            m = rk["metrics"]
            qraw += _defloat(m.get("hetu_comm_quant_raw_bytes_total")) or 0.0
            qwire += _defloat(m.get("hetu_comm_quant_wire_bytes_total")) \
                or 0.0
        if qwire:
            lines.append(
                f"  comm quant: raw {qraw / 2**20:.1f}MiB -> wire "
                f"{qwire / 2**20:.1f}MiB  ratio {qraw / qwire:.2f}x")
    if state["events"]:
        lines.append("recent events:")
        for e in state["events"]:
            fields = {k: v for k, v in e.items()
                      if k not in ("kind", "name", "ts", "rank", "pid")}
            lines.append(f"  [{time.strftime('%H:%M:%S', time.localtime(e.get('ts', 0)))}] "
                         f"r{e.get('rank', '?')} {e.get('name')} {fields}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="hetutop",
        description="live dashboard / schema check over a hetu_tpu "
                    "telemetry directory")
    ap.add_argument("dir", help="telemetry directory (HETU_TELEMETRY_DIR)")
    ap.add_argument("--check", action="store_true",
                    help="validate the JSONL schema and exit 0/1 (CI mode)")
    ap.add_argument("--once", action="store_true",
                    help="render one frame and exit (no screen clearing)")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="refresh seconds in live mode (default 2)")
    args = ap.parse_args(argv)
    if args.check:
        return check_dir(args.dir)
    if not metrics_files(args.dir):
        print(f"hetutop: no metrics-r*.jsonl under {args.dir} (yet)",
              file=sys.stderr)
    if args.once:
        print(render_frame(gather(args.dir)))
        return 0
    follower = Follower(args.dir)   # incremental tail: O(new data)/frame
    try:
        while True:
            frame = render_frame(follower.poll())
            # ANSI clear + home; fall back gracefully on dumb terminals
            sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
