"""hetutrail — cross-process distributed tracing over the PS wire, per-step
critical-path attribution, and straggler/skew detection (observability
pillar 5, docs/OBSERVABILITY.md).

Three cooperating pieces, all stdlib-only (the CLI runs on a login node or
in CI without jax):

- **Span plumbing.** Workers stamp every PS RPC with a span context — the
  existing ``(client_id, req_id)`` pair from the PR 4 resend-dedup/
  incarnation machinery IS the context, so the wire format is unchanged.
  The native worker keeps a bounded ring of client RPC spans
  (``csrc/ps/worker.h``), drained here into
  ``trail-client-r<rank>.jsonl``; each server keeps a bounded ring of
  per-request timelines (recv → queue/lock wait → apply → respond,
  ``csrc/ps/server.h``) flushed as ``trail-server-s<rank>.jsonl``.
  :func:`join_spans` matches them by ``(client_id, req_id)`` into
  parent-child flows. Both sides timestamp with CLOCK_MONOTONIC
  (``trail_mono_us``), shared by every process on a host — immune to the
  NTP steps that bit the PR 4 req_id seeding.
- **Critical-path attribution.** :func:`step_legs` decomposes a step
  record's phases into the blocking chain (feed → PS pull wait → compute
  → PS push → poststep); :func:`dominant` names the longest leg; for PS
  legs :func:`attribute_step` names the specific server and param from
  the joined spans. The executor exports ``hetu_critical_path_ms{leg=…}``
  and ``hetu_cp_fraction`` gauges per step via
  :func:`export_critical_path`.
- **Straggler/skew detection.** :class:`StragglerDetector` turns per-step
  per-rank step times into K-consecutive straggler events;
  :class:`SkewMonitor` tails a telemetry directory's per-rank JSONL,
  exports ``hetu_step_skew_ms`` / ``hetu_straggler_rank``, and emits the
  events through the resilience event bus (``telemetry.event``) so
  elastic's ``ScalePolicy.note_straggler`` can act on them.

Activation: everything is armed by ``HETU_TRAIL_DIR`` (the telemetry dir
is the natural value; ``heturun --telemetry-dir`` + ``HETU_TRAIL=1`` sets
it for every role). Off — the default — costs one attribute/env check per
step and one relaxed atomic load per RPC, nothing else.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import statistics
import sys
import tempfile
import time
from typing import Callable, Iterable, Optional

# i64 row layout of csrc/ps/worker.h drain_trail (capi DrainTrailSpans)
CLIENT_COLS = ("req_id", "client", "server", "psf", "tensor", "step",
               "t0_us", "dur_us", "req_bytes", "rsp_bytes")

# the blocking chain, in step order (docs/OBSERVABILITY.md pillar 5);
# "compute" is the jit dispatch window, which contains in-program AllReduce
# — hetuprof splits the collective share out of it offline
LEGS = ("feed", "ps_pull", "compute", "ps_push", "poststep")

# PsfType names for reports (csrc/ps/net.h); unknown ids print as the int
PSF_NAMES = {
    7: "server_stats", 10: "dense_push", 11: "dense_pull", 12: "dd_pushpull",
    20: "sparse_push", 21: "sparse_pull", 22: "sd_pushpull",
    23: "ss_pushpull", 30: "param_init", 34: "param_assign",
    35: "param_assign_rows", 40: "sync_embedding", 41: "push_embedding",
    42: "push_sync_embedding", 50: "data_push", 51: "data_pull",
    70: "test_slow_apply",
}


def _active_telemetry():
    """The process's live Telemetry or None. Tolerates file-path loading
    (bin/hetutrail runs this module packageless, where the relative import
    has no parent)."""
    try:
        from . import get as _tel_get
    except ImportError:
        return None
    return _tel_get()


def _story_mod():
    """The shared ledger reader's home (telemetry/story.py), importable
    from BOTH contexts: inside the hetu_tpu package, or standalone when
    bin/hetutrail loaded this file by path (story.py is stdlib-only at
    module level, so the fallback never drags jax in)."""
    try:
        from . import story
        return story
    except ImportError:
        import importlib.util
        mod = sys.modules.get("_hetustory")
        if mod is not None:
            return mod
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "story.py")
        spec = importlib.util.spec_from_file_location("_hetustory", path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules["_hetustory"] = mod
        spec.loader.exec_module(mod)
        return mod


try:
    with open("/proc/sys/kernel/random/boot_id") as _f:
        _BOOT_ID = _f.read().strip()
except OSError:  # non-Linux: anchors stay comparable only within a process
    _BOOT_ID = ""


def armed() -> Optional[str]:
    """The trail output directory, or None when trail is off (the single
    gate every Python-side call site checks)."""
    d = os.environ.get("HETU_TRAIL_DIR", "")
    return d or None


def mono_us() -> int:
    """CLOCK_MONOTONIC µs — the same clock as the native spans'
    ``trail_mono_us`` (CPython's time.monotonic on Linux)."""
    return int(time.monotonic() * 1e6)


# ---------------------------------------------------------------------------
# span plumbing: writer + drain + loaders + join
# ---------------------------------------------------------------------------

class TrailWriter:
    """Append-only JSONL writer for one rank's client spans. The first line
    of each file generation is an anchor pairing this process's monotonic
    clock with the wall clock (spans themselves carry only monotonic
    stamps).

    Bounded like every other always-on trail surface: past
    ``HETU_TRAIL_MAX_MB`` (default 512) the file rotates to one ``.1``
    backup (atomic rename, fresh anchor in the new generation), so a
    week-long armed run holds at most two generations per rank."""

    def __init__(self, path: str, rank: int, max_mb: Optional[float] = None):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self.path = path
        self.rank = int(rank)
        if max_mb is None:
            try:
                max_mb = float(os.environ.get("HETU_TRAIL_MAX_MB",
                                              "512") or 0)
            except ValueError:
                max_mb = 512.0
        self._max_bytes = int(max_mb * 1e6) if max_mb > 0 else 0
        self._f = open(path, "a")
        try:
            self._nbytes = os.path.getsize(path)
        except OSError:
            self._nbytes = 0
        self._write_anchor()

    def _write_anchor(self) -> None:
        # boot_id makes the anchor the cross-process ordering proof
        # hetustory's timeline needs (same condition as hetutrace: one
        # boot_id = one shared CLOCK_MONOTONIC); run_id/inc disambiguate
        # generations from restarted or interleaved runs
        rec = {"kind": "anchor", "rank": self.rank, "mono_us": mono_us(),
               "wall_s": round(time.time(), 3), "boot_id": _BOOT_ID}
        run_id = os.environ.get("HETU_RUN_ID")
        if run_id:
            rec["run_id"] = run_id
            try:
                rec["inc"] = int(os.environ.get("HETU_RUN_INCARNATION",
                                                "0"))
            except ValueError:
                pass
        line = json.dumps(rec, separators=(",", ":")) + "\n"
        self._f.write(line)
        self._nbytes += len(line)
        self._f.flush()

    def write_rows(self, rows: Iterable) -> int:
        n = 0
        nbytes = 0
        rank = self.rank
        write = self._f.write
        for row in rows:
            # direct f-string: every field is an int and the keys are
            # fixed, and json.dumps over a built dict measured ~8x this
            # (the drain rides the step boundary, so per-row cost is the
            # trail overhead budget)
            r = [int(v) for v in row]
            nbytes += write(
                f'{{"kind":"rpc","rank":{rank},"req_id":{r[0]},'
                f'"client":{r[1]},"server":{r[2]},"psf":{r[3]},'
                f'"tensor":{r[4]},"step":{r[5]},"t0_us":{r[6]},'
                f'"dur_us":{r[7]},"req_bytes":{r[8]},'
                f'"rsp_bytes":{r[9]}}}\n')
            n += 1
        if n:
            self._f.flush()
            self._nbytes += nbytes
            if self._max_bytes and self._nbytes >= self._max_bytes:
                self._rotate()
        return n

    def write_dropped(self, n: int) -> None:
        """Record ring overflow (the client twin of the server writer's
        ``dropped`` records): without it a saturated ring silently
        deflates span counts and skews per-server attribution."""
        line = json.dumps({"kind": "dropped", "rank": self.rank,
                           "n": int(n)}, separators=(",", ":")) + "\n"
        self._f.write(line)
        self._nbytes += len(line)
        self._f.flush()

    def _rotate(self) -> None:
        """Atomic rollover to one .1 backup (the JsonlSink convention);
        failures leave the live file in place and disable rotation rather
        than losing spans."""
        try:
            self._f.close()
            os.replace(self.path, self.path + ".1")
            self._f = open(self.path, "a")
            self._nbytes = 0
            self._write_anchor()
        except OSError:
            self._max_bytes = 0
            if self._f.closed:
                try:
                    self._f = open(self.path, "a")
                except OSError:
                    pass

    def close(self) -> None:
        if not self._f.closed:
            self._f.flush()
            self._f.close()


def drain_client_spans(comm, writer: TrailWriter, batch: int = 4096) -> int:
    """Drain the native client-span ring through ``comm``
    (:class:`~hetu_tpu.ps.client.PSClient`) into ``writer``; returns the
    span count. Never raises — span drain must not take training down."""
    total = 0
    try:
        while True:
            rows = comm.DrainTrailSpans(batch)
            if not len(rows):
                break
            total += writer.write_rows(rows)
            if len(rows) < batch:
                break
        # surface ring overflow next to the spans (cumulative native
        # counter -> per-writer delta), like the server-side records
        dropped = int(comm.TrailDropped())
        seen = getattr(writer, "_dropped_seen", 0)
        if dropped > seen:
            writer.write_dropped(dropped - seen)
            writer._dropped_seen = dropped
    except Exception:  # noqa: BLE001 — observability only
        pass
    return total


def _read_jsonl(path: str) -> list:
    """One JSONL file's object rows, torn tail tolerated — now the shared
    hetustory reader (the classification, not the behavior, changed)."""
    return _story_mod().read_jsonl(path)


def load_dir(dir_path: str) -> dict:
    """Everything hetutrail needs from one directory: client spans, server
    spans, anchors, drop counters, and the per-step metrics records (the
    phases the critical path decomposes). Reads each file's rotated ``.1``
    backup first (the PR 20 fix: a span drained just before rotation used
    to vanish from every report)."""
    _read = _story_mod().read_jsonl_rotated
    client, server, anchors = [], [], []
    dropped = dropped_client = 0
    for p in sorted(glob.glob(os.path.join(dir_path,
                                           "trail-client-r*.jsonl"))):
        for rec in _read(p):
            if rec.get("kind") == "rpc":
                client.append(rec)
            elif rec.get("kind") == "anchor":
                anchors.append(rec)
            elif rec.get("kind") == "dropped":
                dropped_client += int(rec.get("n", 0))
    for p in sorted(glob.glob(os.path.join(dir_path,
                                           "trail-server-s*.jsonl"))):
        for rec in _read(p):
            if rec.get("kind") == "srv":
                server.append(rec)
            elif rec.get("kind") == "anchor":
                anchors.append(rec)
            elif rec.get("kind") == "dropped":
                dropped += int(rec.get("n", 0))
    steps: dict = {}
    for p in sorted(glob.glob(os.path.join(dir_path, "metrics-r*.jsonl"))):
        for rec in _read(p):
            if rec.get("kind") == "step" and "step" in rec:
                steps[(int(rec.get("rank", 0)), int(rec["step"]))] = rec
    return {"client": client, "server": server, "anchors": anchors,
            "dropped": dropped, "dropped_client": dropped_client,
            "steps": steps}


def join_spans(client: list, server: list):
    """Match client RPC spans to server request timelines by the span
    context that rode the wire: ``(client_id, req_id)``. Returns
    ``(joined, join_rate)`` — each joined record is the client span plus a
    ``srv`` field (None when unmatched); rate is None with no client
    spans. Duplicates (failover re-issues) keep the first server record."""
    srv_by: dict = {}
    for s in server:
        key = (int(s.get("client", -1)), int(s.get("req_id", 0)))
        srv_by.setdefault(key, s)
    joined = []
    matched = 0
    for c in client:
        s = srv_by.get((int(c.get("client", -1)), int(c.get("req_id", 0))))
        if s is not None:
            matched += 1
        joined.append({**c, "srv": s})
    rate = (matched / len(client)) if client else None
    return joined, rate


# ---------------------------------------------------------------------------
# critical path
# ---------------------------------------------------------------------------

def step_legs(phases: dict) -> dict:
    """Decompose one step record's phases into the blocking chain. The
    executor's prestep contains the PS pull wait and its poststep the PS
    push issue; both are measured separately (``ps_pull_ms`` /
    ``ps_push_ms``) so the non-PS remainder is feed/bookkeeping."""
    prestep = float(phases.get("prestep_ms", 0.0))
    dispatch = float(phases.get("dispatch_ms", 0.0))
    poststep = float(phases.get("poststep_ms", 0.0))
    pull = float(phases.get("ps_pull_ms", 0.0))
    push = float(phases.get("ps_push_ms", 0.0))
    return {"feed": max(0.0, prestep - pull), "ps_pull": pull,
            "compute": dispatch, "ps_push": push,
            "poststep": max(0.0, poststep - push)}


def dominant(legs: dict):
    """(leg name, fraction of the chain) for the longest blocking leg;
    (None, 0.0) for an all-zero chain."""
    total = sum(legs.values())
    if total <= 0.0:
        return None, 0.0
    leg = max(legs, key=legs.get)
    return leg, legs[leg] / total


def export_critical_path(metrics, legs: dict, cache: Optional[dict] = None):
    """Set the per-step ``hetu_critical_path_ms{leg=…}`` gauges and the
    ``hetu_cp_fraction`` gauge (dominant leg's share of the blocking
    chain) on a live registry. ``cache`` avoids the labeled-gauge lookup
    on the hot path. Returns (dominant leg, fraction)."""
    if cache is not None:
        gauges = cache.get("cp_gauges")
        if gauges is None:
            gauges = cache["cp_gauges"] = {
                leg: metrics.gauge("hetu_critical_path_ms", {"leg": leg})
                for leg in LEGS}
            cache["cp_fraction"] = metrics.gauge("hetu_cp_fraction")
        for leg, g in gauges.items():
            g.set(legs.get(leg, 0.0))
        frac_g = cache["cp_fraction"]
    else:
        for leg in LEGS:
            metrics.gauge("hetu_critical_path_ms",
                          {"leg": leg}).set(legs.get(leg, 0.0))
        frac_g = metrics.gauge("hetu_cp_fraction")
    dom, frac = dominant(legs)
    frac_g.set(frac)
    return dom, frac


def _ps_attribution(joined: list, step: int, rank: Optional[int] = None):
    """For one step's PS leg: per-server and per-param blocking time from
    the joined spans (server-side queue+handle when joined, client
    round-trip otherwise). The window includes spans stamped with the
    PRECEDING step too: an async push queued at the previous boundary is
    exactly the in-flight work a blocked pull waits on, and its stamp
    races the boundary's step advance by design."""
    by_server: dict = {}
    by_tensor: dict = {}
    window = (int(step) - 1, int(step))
    for c in joined:
        if int(c.get("step", -1)) not in window:
            continue
        if rank is not None and int(c.get("rank", 0)) != int(rank):
            continue
        s = c.get("srv")
        us = (int(s["q_us"]) + int(s["handle_us"]) + int(s["send_us"])
              if s is not None else int(c.get("dur_us", 0)))
        by_server[int(c["server"])] = by_server.get(int(c["server"]), 0) + us
        t = int(c.get("tensor", -1))
        if t >= 0:
            by_tensor[t] = by_tensor.get(t, 0) + us
    return by_server, by_tensor


def attribute_step(loaded: dict, step: int) -> dict:
    """Per-rank critical-path verdict for one step: the legs, the dominant
    leg, and — when a PS leg dominates — the specific server and param it
    blocked on. ``loaded`` is :func:`load_dir` output."""
    joined, rate = join_spans(loaded["client"], loaded["server"])
    out: dict = {"step": int(step), "join_rate": rate, "ranks": {}}
    for (rank, s), rec in sorted(loaded["steps"].items()):
        if s != int(step):
            continue
        legs = step_legs(rec.get("phases") or {})
        dom, frac = dominant(legs)
        entry = {"legs": {k: round(v, 3) for k, v in legs.items()},
                 "dominant": dom, "fraction": round(frac, 4),
                 "step_ms": rec.get("step_ms")}
        if dom in ("ps_pull", "ps_push"):
            by_server, by_tensor = _ps_attribution(joined, step, rank)
            if by_server:
                top = max(by_server, key=by_server.get)
                entry["server"] = top
                entry["server_ms"] = round(by_server[top] / 1e3, 3)
                entry["servers_ms"] = {k: round(v / 1e3, 3)
                                       for k, v in sorted(by_server.items())}
            if by_tensor:
                tt = max(by_tensor, key=by_tensor.get)
                entry["tensor"] = tt
                entry["tensor_ms"] = round(by_tensor[tt] / 1e3, 3)
        out["ranks"][rank] = entry
    return out


# ---------------------------------------------------------------------------
# straggler / skew
# ---------------------------------------------------------------------------

class StragglerDetector:
    """K-consecutive straggler events from per-step per-rank step times.

    A rank straggles on a step when its time exceeds ``ratio`` × the median
    of the other ranks by at least ``min_ms`` (the floor keeps µs-scale
    noise on fast steps from counting). ``k`` consecutive straggling steps
    fire ONE event, then the streak restarts — a persistently slow rank
    re-fires every k steps, which is the cadence a ScalePolicy wants."""

    def __init__(self, k: int = 3, ratio: float = 1.5, min_ms: float = 1.0):
        self.k = max(1, int(k))
        self.ratio = float(ratio)
        self.min_ms = float(min_ms)
        self._streak: dict = {}

    def observe(self, step: int, rank_ms: dict) -> Optional[dict]:
        if len(rank_ms) < 2:
            return None
        worst = max(rank_ms, key=rank_ms.get)
        others = [v for r, v in rank_ms.items() if r != worst]
        med = statistics.median(others)
        is_straggler = (rank_ms[worst] > self.ratio * med
                        and rank_ms[worst] - med >= self.min_ms)
        for r in list(self._streak):
            if r != worst or not is_straggler:
                self._streak.pop(r, None)
        if not is_straggler:
            return None
        streak = self._streak.get(worst, 0) + 1
        if streak < self.k:
            self._streak[worst] = streak
            return None
        self._streak.pop(worst, None)
        return {"kind": "straggler", "rank": int(worst), "step": int(step),
                "step_ms": round(rank_ms[worst], 3),
                "median_ms": round(med, 3), "streak": self.k,
                "n_ranks": len(rank_ms)}


class SkewMonitor:
    """Tail a telemetry directory's per-rank step records, compute
    cross-rank per-step skew, and emit straggler events.

    Incremental (byte offsets per file, like hetutop's Follower) so a
    supervisor can poll it cheaply. Exports ``hetu_step_skew_ms`` and
    ``hetu_straggler_rank`` (-1 = none) when telemetry is active in the
    polling process; events go through the resilience event bus
    (``telemetry.event("straggler", …)``), into ``trail-events.jsonl``
    next to the rank files, and to ``on_event`` (how heturun hands them to
    elastic's ScalePolicy).

    When the straggling rank's blocking chain at the event step is
    PS-dominated, the event is enriched with the blocking ``server`` (top
    server by round-trip time over that rank's recent client spans, when
    trail files sit in the same directory) and ``n_servers`` — the shape
    ``ScalePolicy.note_straggler`` turns into a grow recommendation. A
    compute-bound straggler stays a rank-level event: more PS servers
    would not fix it."""

    # recent client spans kept per rank for event attribution
    _SPAN_WINDOW = 4096

    def __init__(self, dir_path: str,
                 detector: Optional[StragglerDetector] = None,
                 on_event: Optional[Callable[[dict], None]] = None,
                 write_events: bool = True):
        import collections
        self.dir = dir_path
        self.detector = detector or StragglerDetector()
        self.on_event = on_event
        self.write_events = write_events
        # shared rotation-aware tailer (hetustory): records written between
        # a poll and a rotation are drained from the .1 backup, not lost
        self._follow = _story_mod().LedgerFollower(backlog=True)
        self._pending: dict = {}    # step -> {rank: step_ms}
        self._phases: dict = {}     # (step, rank) -> phases (bounded below)
        self._spans: dict = {}      # rank -> deque[(step, server, dur_us)]
        self._deque = collections.deque
        self._done_through = -1
        self.last_skew_ms: Optional[float] = None
        self.last_slowest: Optional[int] = None
        self.events: list = []

    def _tail(self, path: str) -> list:
        return self._follow.poll(path)

    def poll(self) -> list:
        """Ingest new records; returns the straggler events fired by this
        poll (also accumulated on ``self.events``)."""
        files = sorted(glob.glob(os.path.join(self.dir,
                                              "metrics-r*.jsonl")))
        n_ranks = len(files)
        for p in files:
            for rec in self._tail(p):
                if rec.get("kind") != "step" or "step" not in rec:
                    continue
                s = int(rec["step"])
                if s <= self._done_through:
                    continue
                rank = int(rec.get("rank", 0))
                self._pending.setdefault(s, {})[rank] = \
                    float(rec.get("step_ms", 0.0))
                if rec.get("phases"):
                    self._phases[(s, rank)] = rec["phases"]
        # client spans (same dir when HETU_TRAIL_DIR = telemetry dir):
        # the attribution source for PS-blocked straggler events
        for p in glob.glob(os.path.join(self.dir, "trail-client-r*.jsonl")):
            for rec in self._tail(p):
                if rec.get("kind") != "rpc":
                    continue
                rank = int(rec.get("rank", 0))
                dq = self._spans.get(rank)
                if dq is None:
                    dq = self._spans[rank] = self._deque(
                        maxlen=self._SPAN_WINDOW)
                dq.append((int(rec.get("step", -1)),
                           int(rec.get("server", -1)),
                           int(rec.get("dur_us", 0))))
        fired = []
        for s in sorted(self._pending):
            if s <= self._done_through:   # acted while this rank lagged
                del self._pending[s]
                continue
            ranks = self._pending[s]
            # act once every reporting rank landed; a step more than one
            # WINDOW behind the newest acts with whoever reported (a rank
            # that stopped writing must not wedge detection forever)
            newest = max(self._pending)
            if len(ranks) < n_ranks and newest - s < 64:
                continue
            del self._pending[s]
            self._done_through = max(self._done_through, s)
            if len(ranks) >= 2:
                vals = list(ranks.values())
                self.last_skew_ms = max(vals) - min(vals)
                slowest = max(ranks, key=ranks.get)
                self.last_slowest = slowest
                self._export_gauges(none=False)
                ev = self.detector.observe(s, ranks)
                if ev is not None:
                    self._attribute(ev)
                    fired.append(ev)
            for r in ranks:   # every acted step releases its phase rows
                self._phases.pop((s, r), None)
        for ev in fired:
            self.events.append(ev)
            self._emit(ev)
        return fired

    def _attribute(self, ev: dict) -> None:
        """Attach the blocking PS server to a straggler event whose
        dominant leg is a PS leg (see the class docstring). Mutates
        ``ev`` in place; a compute-bound straggler is left rank-level."""
        phases = self._phases.get((ev["step"], ev["rank"]))
        if not phases:
            return
        dom, _ = dominant(step_legs(phases))
        if dom not in ("ps_pull", "ps_push"):
            return
        lo = ev["step"] - self.detector.k
        by_server: dict = {}
        for step, server, dur_us in self._spans.get(ev["rank"], ()):
            if lo <= step <= ev["step"] and server >= 0:
                by_server[server] = by_server.get(server, 0) + dur_us
        if not by_server:
            return
        ev["server"] = max(by_server, key=by_server.get)
        ev["n_servers"] = len(by_server)

    def _export_gauges(self, none: bool) -> None:
        tel = _active_telemetry()
        if tel is None:
            return
        try:
            tel.metrics.gauge("hetu_step_skew_ms").set(
                0.0 if none else (self.last_skew_ms or 0.0))
            tel.metrics.gauge("hetu_straggler_rank").set(
                -1 if none or self.last_slowest is None
                else self.last_slowest)
        except Exception:  # noqa: BLE001
            pass

    def _emit(self, ev: dict) -> None:
        tel = _active_telemetry()
        if tel is not None:
            try:
                tel.event("straggler", **{k: v for k, v in ev.items()
                                          if k != "kind"})
            except Exception:  # noqa: BLE001
                pass
        if self.write_events:
            try:
                with open(os.path.join(self.dir, "trail-events.jsonl"),
                          "a") as f:
                    f.write(json.dumps(
                        {"ts": round(time.time(), 3), **ev},
                        separators=(",", ":")) + "\n")
            except OSError:
                pass
        if self.on_event is not None:
            try:
                self.on_event(ev)
            except Exception:  # noqa: BLE001
                pass


# ---------------------------------------------------------------------------
# reports
# ---------------------------------------------------------------------------

def analyze(dir_path: str) -> dict:
    """Whole-run report over a trail/telemetry directory: join rate,
    per-server blocking totals, mean critical-path legs + dominant-leg
    histogram, cross-rank skew series, and straggler events."""
    loaded = load_dir(dir_path)
    joined, rate = join_spans(loaded["client"], loaded["server"])
    by_server: dict = {}
    for c in joined:
        s = c.get("srv")
        sid = int(c["server"])
        ent = by_server.setdefault(sid, {"rpcs": 0, "client_ms": 0.0,
                                         "srv_ms": 0.0, "apply_ms": 0.0,
                                         "q_ms": 0.0, "joined": 0})
        ent["rpcs"] += 1
        ent["client_ms"] += int(c.get("dur_us", 0)) / 1e3
        if s is not None:
            ent["joined"] += 1
            ent["srv_ms"] += (int(s["q_us"]) + int(s["handle_us"])
                              + int(s["send_us"])) / 1e3
            ent["apply_ms"] += int(s["apply_us"]) / 1e3
            ent["q_ms"] += int(s["q_us"]) / 1e3
    leg_sums = {leg: 0.0 for leg in LEGS}
    dom_hist: dict = {}
    by_step: dict = {}
    n_steps = 0
    for (rank, s), rec in loaded["steps"].items():
        legs = step_legs(rec.get("phases") or {})
        n_steps += 1
        for k, v in legs.items():
            leg_sums[k] += v
        dom, _ = dominant(legs)
        if dom:
            dom_hist[dom] = dom_hist.get(dom, 0) + 1
        by_step.setdefault(s, {})[rank] = float(rec.get("step_ms", 0.0))
    det = StragglerDetector()
    skew = []
    stragglers = []
    for s in sorted(by_step):
        ranks = by_step[s]
        if len(ranks) < 2:
            continue
        vals = list(ranks.values())
        skew.append({"step": s, "skew_ms": round(max(vals) - min(vals), 3),
                     "slowest": max(ranks, key=ranks.get)})
        ev = det.observe(s, ranks)
        if ev is not None:
            stragglers.append(ev)
    return {
        "dir": dir_path,
        "client_spans": len(loaded["client"]),
        "server_spans": len(loaded["server"]),
        "dropped_client_spans": loaded["dropped_client"],
        "dropped_server_spans": loaded["dropped"],
        "join_rate": round(rate, 4) if rate is not None else None,
        "servers": {k: {kk: (round(vv, 3) if isinstance(vv, float) else vv)
                        for kk, vv in v.items()}
                    for k, v in sorted(by_server.items())},
        "steps": n_steps,
        "mean_legs_ms": {k: round(v / n_steps, 3) if n_steps else 0.0
                         for k, v in leg_sums.items()},
        "dominant_hist": dom_hist,
        "skew": skew[-50:],
        "max_skew_ms": max((e["skew_ms"] for e in skew), default=None),
        "stragglers": stragglers,
    }


def format_step_report(rep: dict) -> str:
    lines = [f"hetutrail --step {rep['step']}: join rate "
             f"{rep['join_rate'] if rep['join_rate'] is not None else 'n/a'}"]
    if not rep["ranks"]:
        lines.append("  no step records for this step (is this the "
                     "telemetry dir, with HETU_TRAIL_DIR pointed at it?)")
    for rank, e in sorted(rep["ranks"].items()):
        legs = "  ".join(f"{k}={v:.2f}ms" for k, v in e["legs"].items())
        lines.append(f"  rank {rank}: step_ms={e.get('step_ms')}  {legs}")
        msg = (f"  rank {rank}: dominant leg {e['dominant']} "
               f"({100.0 * e['fraction']:.1f}% of the blocking chain)")
        if "server" in e:
            msg += (f" — server {e['server']} "
                    f"({e['server_ms']:.2f}ms blocked)")
        if "tensor" in e:
            msg += f", param {e['tensor']} ({e['tensor_ms']:.2f}ms)"
        lines.append(msg)
    return "\n".join(lines)


def format_report(rep: dict) -> str:
    lines = [f"hetutrail: {rep['dir']}",
             f"  spans: {rep['client_spans']} client / "
             f"{rep['server_spans']} server, join rate {rep['join_rate']}"
             + (f", dropped {rep['dropped_client_spans']} client / "
                f"{rep['dropped_server_spans']} server"
                if rep["dropped_server_spans"]
                or rep["dropped_client_spans"] else "")]
    for sid, e in rep["servers"].items():
        lines.append(f"  server {sid}: {e['rpcs']} rpcs  "
                     f"client {e['client_ms']:.1f}ms  "
                     f"server {e['srv_ms']:.1f}ms "
                     f"(queue {e['q_ms']:.1f}, apply {e['apply_ms']:.1f})")
    if rep["steps"]:
        legs = "  ".join(f"{k}={v:.2f}ms"
                         for k, v in rep["mean_legs_ms"].items())
        lines.append(f"  critical path over {rep['steps']} step rec(s): "
                     f"{legs}")
        lines.append("  dominant-leg histogram: "
                     + ", ".join(f"{k}:{v}" for k, v in sorted(
                         rep["dominant_hist"].items(), key=lambda kv: -kv[1])))
    if rep["max_skew_ms"] is not None:
        lines.append(f"  cross-rank skew: max {rep['max_skew_ms']:.2f}ms "
                     f"over {len(rep['skew'])} multi-rank step(s)")
    for ev in rep["stragglers"]:
        lines.append(f"  STRAGGLER rank {ev['rank']} @ step {ev['step']}: "
                     f"{ev['step_ms']}ms vs median {ev['median_ms']}ms "
                     f"({ev['streak']} consecutive)")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# --check: jax-free self-test (the CI smoke, like hetuscope --check)
# ---------------------------------------------------------------------------

def self_check(out=sys.stdout) -> int:
    """Build a synthetic two-server, two-rank run in a tempdir, then prove
    the whole pipeline: spans join by (client, req_id), the critical path
    names the slow PS leg AND the slow server, and the straggler detector
    fires on the slowed rank. Exit 0/1."""
    try:
        with tempfile.TemporaryDirectory(prefix="hetutrail_check_") as d:
            w = TrailWriter(os.path.join(d, "trail-client-r0.jsonl"), 0)
            srv_path = os.path.join(d, "trail-server-s%d.jsonl")
            srv_f = {s: open(srv_path % s, "w") for s in (0, 1)}
            for s, f in srv_f.items():
                f.write(json.dumps({"kind": "anchor", "server": s,
                                    "mono_us": 0, "wall_s": 0.0}) + "\n")
            rows = []
            req_id = 1000
            for step in range(6):
                for server in (0, 1):
                    req_id += 1
                    slow = server == 1 and step == 3
                    dur = 80_000 if slow else 900
                    rows.append((req_id, 0, server, 21, 7, step,
                                 step * 1_000_000 + server, dur, 256, 4096))
                    srv_f[server].write(json.dumps(
                        {"kind": "srv", "server": server, "client": 0,
                         "req_id": req_id, "psf": 21, "tensor": 7,
                         "t0_us": step * 1_000_000 + server + 100,
                         "q_us": 50, "handle_us": dur - 200,
                         "apply_us": dur - 200, "send_us": 50}) + "\n")
            w.write_rows(rows)
            w.close()
            for f in srv_f.values():
                f.close()
            # per-rank metrics: rank 1 straggles from step 2 on; step 3's
            # blocking chain is PS-pull-dominated on rank 0
            for rank in (0, 1):
                with open(os.path.join(d, f"metrics-r{rank}.jsonl"),
                          "w") as f:
                    for step in range(6):
                        slow_rank = rank == 1 and step >= 2
                        ps_pull = 20.0 if (rank == 0 and step == 3) else 1.0
                        phases = {"prestep_ms": ps_pull + 0.5,
                                  "dispatch_ms": 5.0,
                                  "poststep_ms": 1.0, "ps_pull_ms": ps_pull,
                                  "ps_push_ms": 0.4,
                                  "ps_comm_ms": ps_pull + 0.4}
                        step_ms = (300.0 if slow_rank else
                                   phases["prestep_ms"]
                                   + phases["dispatch_ms"]
                                   + phases["poststep_ms"])
                        f.write(json.dumps(
                            {"ts": step * 0.1, "rank": rank, "kind": "step",
                             "sub": "train", "step": step,
                             "step_ms": step_ms,
                             "phases": phases}) + "\n")
            loaded = load_dir(d)
            _, rate = join_spans(loaded["client"], loaded["server"])
            assert rate == 1.0, f"join rate {rate} != 1.0"
            rep = attribute_step(loaded, 3)
            e = rep["ranks"][0]
            assert e["dominant"] == "ps_pull", e
            assert e.get("server") == 1, (
                f"slow server misattributed: {e}")
            assert e.get("tensor") == 7, e
            full = analyze(d)
            assert full["join_rate"] == 1.0, full["join_rate"]
            assert any(ev["rank"] == 1 for ev in full["stragglers"]), (
                "no straggler event for the slowed rank: "
                f"{full['stragglers']}")
            # SkewMonitor path: same events via the incremental tailer
            seen = []
            mon = SkewMonitor(d, on_event=seen.append, write_events=False)
            mon.poll()
            assert any(ev["rank"] == 1 for ev in seen), seen
            det = StragglerDetector(k=2)
            assert det.observe(0, {0: 1.0, 1: 10.0}) is None
            assert det.observe(1, {0: 1.0, 1: 10.0})["rank"] == 1
            # a recovered rank resets the streak
            assert det.observe(2, {0: 1.0, 1: 1.0}) is None
            assert det.observe(3, {0: 1.0, 1: 10.0}) is None
        print("hetutrail --check: join/critical-path/straggler pipeline ok",
              file=out)
        return 0
    except AssertionError as e:
        print(f"hetutrail --check: FAIL: {e}", file=out)
        return 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="hetutrail",
        description="distributed PS-wire tracing: span join, per-step "
                    "critical-path attribution, straggler detection "
                    "(docs/OBSERVABILITY.md pillar 5)")
    ap.add_argument("dir", nargs="?",
                    help="telemetry/trail directory (HETU_TRAIL_DIR)")
    ap.add_argument("--step", type=int, default=None,
                    help="report one step's critical path (names the "
                         "dominant leg and, for PS legs, the blocking "
                         "server and param)")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as JSON")
    ap.add_argument("--check", action="store_true",
                    help="jax-free self-test of the join/critical-path/"
                         "straggler pipeline, exit 0/1 (CI mode)")
    args = ap.parse_args(argv)
    if args.check:
        return self_check()
    if not args.dir:
        ap.error("a directory is required unless --check")
    try:
        if args.step is not None:
            rep = attribute_step(load_dir(args.dir), args.step)
            print(json.dumps(rep, indent=1) if args.json
                  else format_step_report(rep))
            return 0
        rep = analyze(args.dir)
        print(json.dumps(rep, indent=1) if args.json
              else format_report(rep))
    except BrokenPipeError:
        return 0   # report piped into head/less that closed early
    return 0


if __name__ == "__main__":
    sys.exit(main())
