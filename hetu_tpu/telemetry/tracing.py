"""Structured step/op tracing: Chrome-trace-format JSON (Perfetto-loadable).

``Tracer`` records complete ("ph": "X") events with microsecond timestamps,
one lane per thread (the PS push/pull streams show up as their own rows under
the worker's process lane). Per-rank files are merged into one timeline with
rank lanes by ``bin/hetutrace``.

Deep dives escalate in two env-gated stages, both owned by
:class:`XlaTraceWindow`:

- ``jax.profiler.StepTraceAnnotation`` — when the step runs inside an active
  jax profiler trace, each step gets its own named region in the device
  timeline (no-op context otherwise; the annotation itself is cheap).
- ``HETU_XLA_TRACE=dir[:start_step[:n_steps]]`` — a bounded
  ``jax.profiler.start_trace``/``stop_trace`` window around the configured
  steps, so a production job can capture an XLA-level trace of steps
  1000..1009 without tracing the whole run.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

# trace clock: perf_counter in µs, with BOTH anchors recorded in metadata —
# the unix wall clock and the raw perf_counter value. On Linux perf_counter
# reads CLOCK_MONOTONIC (since boot, shared by every process on a host), so
# hetutrace's merge can re-anchor same-host ranks on the monotonic deltas:
# an NTP step mid-run moves the wall anchors but not the mono ones, which is
# exactly the bug class that bit the PR 4 req_id seeding. Cross-HOST merges
# fall back to the wall anchors (mono origins differ per boot) — the host
# name rides along so the merge can tell.
_T0_PERF = time.perf_counter()
_T0_UNIX = time.time()

# jax.profiler.StepTraceAnnotation, resolved lazily on first use
# (None = unresolved, False = jax unavailable — stay stdlib-importable)
_STEP_ANNOT = None


try:
    _HOST = os.uname().nodename
except (AttributeError, OSError):  # non-POSIX fallback
    _HOST = "localhost"

# the CORRECT mono-comparability key: CLOCK_MONOTONIC counts from kernel
# boot, and the kernel's boot_id uniquely names that boot — two processes
# share a monotonic origin iff they share it (containers with identical
# image hostnames do; distinct machines never do, whatever their names)
try:
    with open("/proc/sys/kernel/random/boot_id") as _f:
        _BOOT_ID = _f.read().strip()
except OSError:
    _BOOT_ID = ""   # non-Linux: merge falls back to wall anchors


def _now_us() -> float:
    return (time.perf_counter() - _T0_PERF) * 1e6


class _SpanCtx:
    """Context manager for one span; re-entrant use creates nested events
    (Perfetto nests same-tid "X" events by containment)."""

    __slots__ = ("_tracer", "name", "cat", "args", "_t0")

    def __init__(self, tracer: "Tracer", name: str, cat: str,
                 args: Optional[dict]):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.args = args
        self._t0 = 0.0

    def __enter__(self) -> "_SpanCtx":
        self._t0 = _now_us()
        return self

    def __exit__(self, *exc) -> None:
        self._tracer._emit(self.name, self.cat, self._t0,
                           _now_us() - self._t0, self.args)


class Tracer:
    """Chrome-trace event buffer for ONE process (= one rank).

    Events buffer in memory and are written as a complete JSON object on
    ``flush()`` (rewrite-in-place via tmp+rename: the file on disk is always
    valid JSON, even mid-run). A step loop flushes every ``flush_every``
    spans; resilience abort paths flush explicitly before ``os._exit``.
    """

    def __init__(self, path: str, rank: int = 0, flush_every: int = 2048,
                 max_events: Optional[int] = None):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self.path = path
        self.rank = int(rank)
        self.flush_every = int(flush_every)
        # the file is rewritten whole on each flush (that is what keeps it
        # valid JSON at every instant), so the buffer must be bounded —
        # past the cap new events are counted as dropped, not appended;
        # trace mode is for bounded diagnosis windows, not week-long runs
        self.max_events = (int(os.environ.get("HETU_TRACE_MAX_EVENTS",
                                              "200000"))
                           if max_events is None else int(max_events))
        self.dropped = 0
        self._lock = threading.Lock()
        self._flush_lock = threading.Lock()   # serializes tmp+rename
        self._events: list[dict] = []
        self._thread_named: set[int] = set()
        self._metadata = [
            {"ph": "M", "pid": self.rank, "name": "process_name",
             "args": {"name": f"rank {self.rank}"}},
        ]
        self._since_flush = 0

    def span(self, name: str, cat: str = "step",
             args: Optional[dict] = None) -> _SpanCtx:
        return _SpanCtx(self, name, cat, args)

    def instant(self, name: str, cat: str = "event",
                args: Optional[dict] = None) -> None:
        ev = {"name": name, "cat": cat, "ph": "i", "s": "p",
              "ts": round(_now_us(), 1), "pid": self.rank,
              "tid": self._tid()}
        if args:
            ev["args"] = args
        self._append(ev)

    def complete(self, name: str, t0_perf: float, t1_perf: float,
                 cat: str = "step", args: Optional[dict] = None) -> None:
        """Emit a finished span from two ``time.perf_counter()`` readings —
        the executor's hot path records bare timestamps and emits post-hoc,
        so the traced and untraced step bodies stay structurally identical
        (no nested with-blocks to keep in sync)."""
        self._emit(name, cat, (t0_perf - _T0_PERF) * 1e6,
                   (t1_perf - t0_perf) * 1e6, args)

    def _tid(self) -> int:
        t = threading.current_thread()
        tid = t.ident or 0
        if tid not in self._thread_named:
            self._thread_named.add(tid)
            self._metadata.append(
                {"ph": "M", "pid": self.rank, "tid": tid,
                 "name": "thread_name", "args": {"name": t.name}})
        return tid

    def _emit(self, name: str, cat: str, ts_us: float, dur_us: float,
              args: Optional[dict]) -> None:
        ev = {"name": name, "cat": cat, "ph": "X",
              "ts": round(ts_us, 1), "dur": round(dur_us, 1),
              "pid": self.rank, "tid": self._tid()}
        if args:
            ev["args"] = args
        self._append(ev)

    def _append(self, ev: dict) -> None:
        with self._lock:
            if len(self._events) >= self.max_events:
                self.dropped += 1
                return
            self._events.append(ev)
            self._since_flush += 1
            need_flush = self._since_flush >= self.flush_every
        if need_flush:
            self.flush()

    def flush(self) -> str:
        """Write the complete trace file (valid JSON at every point).

        The event list is COPIED under the buffer lock (concat) — the dump
        below must not iterate a list a stream thread is appending to —
        and the tmp+rename pair is serialized by its own lock: two
        concurrent flushes (step loop + PS stream crossing ``flush_every``,
        or an abort-path flush) each publish a complete file, last one
        wins, instead of interleaving writes into one shared .tmp."""
        with self._lock:
            other = {"clock_anchor_unix_s": round(_T0_UNIX, 3),
                     "clock_anchor_mono_s": round(_T0_PERF, 6),
                     "host": _HOST,
                     "boot_id": _BOOT_ID,
                     "rank": self.rank}
            if self.dropped:
                other["dropped_events"] = self.dropped
            events = self._metadata + self._events
            self._since_flush = 0
        doc = {
            "displayTimeUnit": "ms",
            "otherData": other,
            "traceEvents": events,
        }
        with self._flush_lock:
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f, separators=(",", ":"))
            os.replace(tmp, self.path)
        return self.path


class XlaTraceWindow:
    """Bounded jax.profiler trace window + per-step annotations.

    ``spec`` is ``dir[:start_step[:n_steps]]`` (defaults: start 0, 10 steps).
    ``step_annotation(step)`` returns a context manager for the step body:
    a ``jax.profiler.StepTraceAnnotation`` while jax is importable, else a
    no-op. ``on_step(step)`` opens/closes the profiler window; call it at
    every step boundary — two integer compares when outside the window.
    """

    def __init__(self, spec: str):
        parts = spec.split(":")
        self.dir = parts[0]
        self.start_step = int(parts[1]) if len(parts) > 1 and parts[1] else 0
        self.n_steps = int(parts[2]) if len(parts) > 2 and parts[2] else 10
        self._active = False
        self._done = False

    @classmethod
    def from_env(cls) -> Optional["XlaTraceWindow"]:
        spec = os.environ.get("HETU_XLA_TRACE")
        return cls(spec) if spec else None

    def on_step(self, step: int) -> None:
        if self._done:
            return
        end = self.start_step + self.n_steps
        if not self._active:
            if step >= end:
                # resumed past the window (auto-resume restores the step
                # counter): never open — a late start would capture the
                # wrong steps, not the configured ones
                self._done = True
            elif step >= self.start_step:
                import jax.profiler
                jax.profiler.start_trace(self.dir)
                self._active = True
        elif step >= end:
            self.stop()

    def stop(self) -> None:
        if self._active:
            import jax.profiler
            jax.profiler.stop_trace()
            self._active = False
            self._done = True

    @staticmethod
    def step_annotation(step: int):
        global _STEP_ANNOT
        if _STEP_ANNOT is None:   # resolve once, not per step
            try:
                import jax.profiler
                _STEP_ANNOT = jax.profiler.StepTraceAnnotation
            except Exception:  # noqa: BLE001 — annotation is best-effort
                _STEP_ANNOT = False
        if _STEP_ANNOT:
            return _STEP_ANNOT("hetu_step", step_num=int(step))
        import contextlib
        return contextlib.nullcontext()
