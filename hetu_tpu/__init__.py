"""hetu_tpu — a TPU-native deep-learning framework with the capabilities of
Hetu (PKU DAIR Lab), built on JAX/XLA/Pallas/pjit.

Public surface mirrors the reference's ``python/hetu/__init__.py`` so model
code written against the reference imports unchanged:

    import hetu_tpu as ht
    x = ht.Variable(name='x', trainable=False)
    w = ht.init.random_normal((784, 10), stddev=0.1, name='w')
    loss = ht.reduce_mean_op(ht.softmaxcrossentropy_op(ht.matmul_op(x, w), y), [0])
    train_op = ht.optim.SGDOptimizer(0.1).minimize(loss)
    executor = ht.Executor({'train': [loss, train_op]}, ctx=ht.tpu(0))
    executor.run('train', feed_dict={...})
"""
from .graph.ops import *  # noqa: F401,F403 — the ~55-op registry
from .graph.node import Variable, placeholder_op, Op, find_topo_sort
from .graph.gradients import gradients
from .graph.executor import (
    Executor, HetuConfig, SubExecutor,
    wrapped_mpi_nccl_init, mpi_nccl_init, mpi_nccl_finish, new_group_comm,
    scheduler_init, scheduler_finish, server_init, server_finish,
    worker_init, worker_finish, get_worker_communicate,
)
from .context import context, get_current_context, DeviceGroup
from .dataloader import dataloader_op, Dataloader, DataloaderOp, GNNDataLoaderOp
from .ndarray import (
    cpu, gpu, tpu, rcpu, rgpu, rtpu, array, sparse_array, empty,
    is_gpu_ctx, is_tpu_ctx, NDArray, ND_Sparse_Array, IndexedSlices, DLContext,
)
from .cstable import CacheSparseTable
# re-bind the real PS package: `from .graph.ops import *` above leaks the
# graph-level ops.ps MODULE under the name `ps`, shadowing hetu_tpu.ps
from . import ps
from . import optimizer as optim
from . import resilience
from . import analysis
from . import lr_scheduler as lr
from . import initializers as init
from . import data
from . import metrics
from . import onnx
from . import graphboard
from . import telemetry
from . import tokenizers

__version__ = "0.1.0"
