"""Training supervision layer — what keeps a long-running job alive ABOVE
the parameter-server fault tolerance (ps-lite resender/heartbeats,
``hetu_tpu/ps/``): NaN'd steps, preempted TPU workers, hung collectives, and
crashed loops that would otherwise restart from step 0.

Four cooperating pieces, each usable alone:

- **Anomaly detection** — the executor's in-trace finite-check
  (``HetuConfig(anomaly_guard=True)``) gates the parameter/optimizer-state
  commit on every float output, updated parameter and slot being finite; a
  NaN/Inf step leaves params bit-identical to pre-step. :class:`AnomalyPolicy`
  turns the per-step verdict into skip / loss-scale backoff / rollback-to-
  checkpoint decisions.
- **Preemption handling** — :class:`PreemptionHandler` installs
  SIGTERM/SIGINT handlers that only set a flag; at the next step boundary the
  :class:`Supervisor` takes a coordinated emergency checkpoint
  (``TrainCheckpointer.save_step(..., force=True)``, all hosts — orbax writes
  are already multi-process-coordinated) and raises :class:`Preempted`, which
  ``supervise()`` converts into a clean exit with :data:`EXIT_PREEMPTED`.
- **Hang watchdog** — :class:`Watchdog` is a monitor thread fed by
  ``beat()`` at step boundaries (and around multihost barriers,
  ``multihost.barrier(deadline_s=...)``); when a step exceeds its deadline it
  dumps every live thread's Python stack plus the last-known phase/step to
  stderr and aborts with :data:`EXIT_WATCHDOG` instead of hanging forever —
  a wedged collective cannot be unwound by an exception, so
  abort-then-auto-resume is the recovery path.
- **Auto-resume** — :func:`supervise` restores the latest checkpoint
  (params, optimizer slots, op state, dataloader cursors/RNG — see
  :func:`capture_executor_state`) and re-enters the loop on recoverable
  failure, with bounded restarts and exponential backoff. ``heturun
  --max-restarts N`` applies the same policy one level up, at worker-process
  granularity.

Deterministic fault injection (``HETU_FAULT_SPEC``, inert unless
``HETU_TEST_MODE`` is set) makes every path testable on CPU: NaN grads,
step stalls, signals, crashes. See docs/FAULT_TOLERANCE.md.
"""
from __future__ import annotations

import os
import signal as _signal
import sys
import threading
import time
import traceback
from typing import Any, Callable, Optional

import numpy as np

from . import faults

# Distinct exit codes so a process supervisor (heturun, k8s, the operator)
# can tell the exits apart without parsing logs:
#   EXIT_PREEMPTED — clean preemption: emergency checkpoint written, do NOT
#     count against restart budgets (BSD EX_TEMPFAIL: "try again later").
#   EXIT_WATCHDOG — hang watchdog abort: stacks were dumped to stderr; a
#     restart resumes from the latest checkpoint.
EXIT_PREEMPTED = 75
EXIT_WATCHDOG = 85

_TRUTHY = ("1", "true", "yes", "on")


def env_truthy(name: str) -> bool:
    """The one spelling of 'is this env knob on': explicitly truthy values
    only, so ``FOO=false`` and ``FOO=0`` mean OFF (bench.py's jax-free
    driver re-inlines the same tuple rather than import this package)."""
    return os.environ.get(name, "").strip().lower() in _TRUTHY


def test_mode_enabled() -> bool:
    """The single gate for every destructive test hook (fault injection,
    the PS kill-server hook): ``HETU_TEST_MODE`` must be explicitly truthy.
    A fault spec or kill index leaked into a production environment is
    inert without it."""
    return env_truthy("HETU_TEST_MODE")


def _tel_event(name: str, flush: bool = False, **fields) -> None:
    """Typed resilience event into the telemetry JSONL (no-op when telemetry
    is off). ``flush=True`` on the abort/exit paths — the record must be on
    disk before ``os._exit``/``Preempted`` ends the process. Never raises:
    observability must not take the recovery path down with it. Event names
    map to metrics as documented in docs/OBSERVABILITY.md."""
    from . import telemetry as _telemetry
    tel = _telemetry.get()
    if tel is None:
        return
    try:
        tel.event(name, **fields)
        if flush:
            tel.flush()
    except Exception:  # noqa: BLE001
        pass


def _flight_flush(reason: str) -> None:
    """Flush any armed hetuscope flight recorder (telemetry/scope.py) on an
    abort path — the ring of recent step records must be on disk before the
    process dies. No-op when introspection is off; never raises."""
    try:
        from .telemetry import scope as _scope
        _scope.flush_flight(reason)
    except Exception:  # noqa: BLE001
        pass


def _incident(reason: str, step=None, **extra) -> None:
    """Freeze a hetustory incident report (telemetry/story.py): the ±K-step
    window from EVERY ledger family in the telemetry dir, one JSON doc,
    rendered offline by ``hetustory --incident``. Called AFTER the event /
    flight flush of the same abort path so the window includes them. Gated
    by HETU_STORY_INCIDENT (default on); no-op when telemetry is off; never
    raises — post-mortem capture must not take the abort path down."""
    try:
        from . import telemetry as _telemetry
        from .telemetry import story as _story
        tel = _telemetry.get()
        if tel is None or not _story.incident_enabled():
            return
        # the snapshot reads the ledgers from disk: push any buffered rows
        # (the triggering event itself) out first
        try:
            tel.sink.flush()
        except Exception:  # noqa: BLE001
            pass
        _story.write_incident(tel.dir, reason, step=step, rank=tel.rank,
                              extra=extra or None)
    except Exception:  # noqa: BLE001
        pass


# ---------------------------------------------------------------------------
# Fault injection
# ---------------------------------------------------------------------------

class FaultInjected(RuntimeError):
    """Raised by the ``crash`` fault kind (a stand-in for an arbitrary
    training-loop exception in auto-resume tests)."""


class FaultInjector:
    """Deterministic fault schedule: ``HETU_FAULT_SPEC="kind@step[:arg],..."``.

    Kinds (each entry fires at most once, at its step's boundary):

    - ``nan_grads@S`` — the executor poisons that step's parameter update
      with NaN inside the trace (exercises the anomaly guard end to end).
    - ``nan_op@S[:OPNAME]`` — the executor NaN-poisons one op's OUTPUT
      inside the trace at step S (``OPNAME`` is the op's named_scope
      identity, ``/``/whitespace replaced by ``_``; default: the first
      computing op in topological order) — the deterministic seed the
      hetuscope NaN/Inf provenance pass must localize.
    - ``stall@S:SECONDS`` — sleep at the step boundary (trips the watchdog).
    - ``sigterm@S`` / ``sigint@S`` — deliver the signal to this process
      (exercises preemption handling).
    - ``crash@S`` — raise :class:`FaultInjected` (exercises auto-resume).
    - ``ps_kill@S[:IDX]`` — SIGKILL live PS server ``IDX`` (default 0) of
      this process's ``ps.local_cluster`` (exercises the PS
      snapshot/respawn/failover stack end to end; bounds-checked in
      ``local_cluster.kill_live_server`` like ``resolve_test_kill_index``).
    - ``quant_corrupt@S[:NODE]`` — flip the scale bytes of the next
      quantized PS message this worker sends (``NODE`` = tensor id filter,
      default any; requires ``HetuConfig(comm_quant=...)`` traffic) — the
      server's length/scale validation must reject the malformed payload
      as an error response instead of applying garbage
      (docs/COMM_QUANT.md; the C++ hook is additionally gated on
      HETU_TEST_MODE in capi.cc).
    - ``worker_lost@S[:RANK]`` — this process SIGKILLs ITSELF at step S
      when its WORKER_ID matches RANK (default: any rank) — the
      deterministic elastic scale-down trigger: under ``heturun
      --elastic`` the launcher observes the death and proposes a world
      shrink (docs/FAULT_TOLERANCE.md "Elastic membership").
    - ``ps_join@S`` — grow this process's live ``ps.local_cluster`` by one
      PS server at step S (spawns the server + runs the resize
      coordinator in a daemon thread; the executor's ElasticAgent
      drains/commits at the same boundary and the key ranges migrate
      live).
    - ``ps_slow@S[:MS]`` — delay one PS server's NEXT optimizer apply by
      MS milliseconds (default 100) at step S — the deterministic lever
      the hetutrail critical-path and straggler tests drive
      (docs/OBSERVABILITY.md pillar 5). The target server is
      ``HETU_PS_SLOW_SERVER`` (default 0); the server-side hook
      (``kTestSlowApply``) is additionally HETU_TEST_MODE-gated in capi
      AND on the server.
    - ``plan_flap@S[:PERIOD]`` — from step S onward, alternate the
      injected ``ps_slow`` delay on/off every PERIOD steps (default 8;
      delay ``HETU_PLAN_FLAP_MS`` ms, default 40, re-armed at every
      boundary of an "on" half-period since the server hook is one-shot
      per arming). The ONLY persistent entry in the schedule — it never
      burns out — and it is deliberately adversarial: the period is
      chosen to entice a naive controller into oscillating (slow →
      actuate → fault pauses → "improvement" → commit → fault returns →
      actuate back...). The hetupilot governor's anti-flap regression
      test drives it (docs/FAULT_TOLERANCE.md "Self-tuning with
      guardrails"); a huge PERIOD degenerates to a sustained slow
      server, the pilot's genuine-improvement fixture.
    - ``ps_partition@S[:SERVER]`` — arm a transient directed partition
      between this worker and PS server ``SERVER`` (default 0) at step S
      via the hetuchaos engine: the next ``HETU_PS_PARTITION_ATTEMPTS``
      (default 2) RPC attempts *per wire channel* (bulk push + fast pull
      — up to 2x that many attempts total) to that server fail,
      exercising the retry-with-backoff path (a window past the
      per-channel retry budget escalates to the failover/departure path
      instead — docs/FAULT_TOLERANCE.md "Chaos testing & transport
      hardening"). For full seeded schedules use ``HETU_CHAOS_SPEC`` /
      ``bin/hetuchaos`` directly.
    - ``job_kill@S[:PHASE]`` — whole-job death (hetusave,
      docs/FAULT_TOLERANCE.md "Coordinated job snapshots"). With no
      PHASE: at step S every live local-cluster PS process is SIGKILLed
      and then this worker SIGKILLs itself — the power-loss/pool-sweep
      shape only a committed job epoch recovers from. With PHASE (one of
      ``pre_barrier|server_write|pre_commit|post_commit``): arms the
      crash window INSIDE the next coordinated snapshot at step >= S,
      consumed by ``recovery.take_job_snapshot`` at exactly that phase —
      how the soak proves torn epochs are never restore-eligible.

    The full injector catalogue (args, gating, which subsystem each kind
    exercises, plus the native ``HETU_PS_TEST_EXIT_AFTER_UPDATES`` and
    ``HETU_CHAOS_SPEC`` hooks) lives in docs/FAULT_TOLERANCE.md
    "Fault-kind catalogue".

    ``from_env()`` (the only path wired into the executor by default) returns
    None unless :func:`test_mode_enabled` — direct construction is itself an
    explicit opt-in for tests.
    """

    # the shared registry (hetu_tpu.faults) owns the catalogue; kept as a
    # class attribute for the tests and docs that enumerate kinds here
    KINDS = faults.STEP_FAULT_NAMES

    def __init__(self, spec: str):
        self.entries: list[dict] = []
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            # nan_op's arg is an OP NAME, job_kill's a snapshot PHASE,
            # every other kind's a number — faults.parse_step_entry
            # rejects unknown kinds/phases with the shared catalogue
            entry = faults.parse_step_entry(part)
            entry["fired"] = False
            self.entries.append(entry)

    @classmethod
    def from_env(cls) -> Optional["FaultInjector"]:
        spec = os.environ.get("HETU_FAULT_SPEC")
        if not spec or not test_mode_enabled():
            return None
        return cls(spec)

    def take(self, kind: str, step: int) -> Optional[dict]:
        """Consume (mark fired) the first unfired entry matching
        (kind, step); None when nothing matches."""
        for e in self.entries:
            if e["kind"] == kind and e["step"] == int(step) and not e["fired"]:
                e["fired"] = True
                return e
        return None

    def fires(self, kind: str, step: int) -> bool:
        return self.take(kind, step) is not None

    def inject_host(self, step: int, ex=None) -> None:
        """Host-side faults for this step boundary (stall / signals /
        crash). ``nan_grads`` is NOT handled here — it rides into the jitted
        step as a scalar argument (see SubExecutor). ``ex`` (when the
        Supervisor passes it) lets elastic faults reach the executor's
        membership agent."""
        e = self.take("stall", step)
        if e is not None:
            time.sleep(e["arg"] if e["arg"] is not None else 3600.0)
        e = self.take("ps_kill", step)
        if e is not None:
            from .ps.local_cluster import kill_live_server
            kill_live_server(0 if e["arg"] is None else int(e["arg"]))
        e = self.take("quant_corrupt", step)
        if e is not None:
            from . import ps as ps_pkg
            comm = ps_pkg.get_worker_communicate()
            comm.TestCorruptNextQuant(-1 if e["arg"] is None
                                      else int(e["arg"]))
        e = self.take("worker_lost", step)
        if e is not None:
            my_rank = int(os.environ.get("WORKER_ID", "0"))
            if e["arg"] is None or int(e["arg"]) == my_rank:
                # die like a preempted host: no checkout, no cleanup — the
                # elastic launcher must absorb it as a planned departure.
                # Progress flushes first (a real preemption's SIGTERM grace
                # window gives the same guarantee), so the departed tail is
                # redistributed exactly: `step` boundaries completed =
                # `step` batches consumed.
                ela = getattr(ex, "elastic", None) if ex is not None else None
                if ela is not None:
                    ela.write_progress(step)
                print(f"# hetu fault: worker_lost — rank {my_rank} "
                      f"SIGKILLing itself at step {step}", file=sys.stderr,
                      flush=True)
                os.kill(os.getpid(), _signal.SIGKILL)
        e = self.take("ps_join", step)
        if e is not None:
            from .elastic import grow_local_cluster_server
            grow_local_cluster_server()
        e = self.take("ps_slow", step)
        if e is not None:
            from . import ps as ps_pkg
            comm = ps_pkg.get_worker_communicate()
            comm.TestSlowApply(
                server=int(os.environ.get("HETU_PS_SLOW_SERVER", "0")),
                ms=100 if e["arg"] is None else int(e["arg"]))
        # plan_flap is the one persistent kind: it re-arms the one-shot
        # server delay at every boundary of an "on" half-period and never
        # marks itself fired — take() is deliberately bypassed
        for e in self.entries:
            if e["kind"] != "plan_flap" or int(step) < e["step"]:
                continue
            period = max(1, int(e["arg"])) if e["arg"] else 8
            if ((int(step) - e["step"]) // period) % 2 == 0:
                from . import ps as ps_pkg
                comm = ps_pkg.get_worker_communicate()
                comm.TestSlowApply(
                    server=int(os.environ.get("HETU_PS_SLOW_SERVER", "0")),
                    ms=int(os.environ.get("HETU_PLAN_FLAP_MS", "40")))
        e = self.take("ps_partition", step)
        if e is not None:
            from . import ps as ps_pkg
            comm = ps_pkg.get_worker_communicate()
            srv = 0 if e["arg"] is None else int(e["arg"])
            n = int(os.environ.get("HETU_PS_PARTITION_ATTEMPTS", "2"))
            # chaos-engine partition window over the next n attempts to
            # srv (SetChaos is HETU_TEST_MODE-gated like this injector)
            comm.SetChaos(f"seed={step},partition={srv}:0:{n}")
        e = self.take("job_kill", step)
        if e is not None:
            from . import recovery
            if e["arg"] is None:
                # whole-job death at a step boundary: every PS process dies
                # with the worker, no grace, no cleanup — only a committed
                # hetusave epoch can bring the job back
                recovery.kill_whole_job(step)
            else:
                # phase-targeted: arm the crash window inside the NEXT
                # coordinated snapshot (consumed by take_job_snapshot)
                recovery.arm_job_kill(e["arg"])
        if self.take("sigterm", step) is not None:
            os.kill(os.getpid(), _signal.SIGTERM)
        if self.take("sigint", step) is not None:
            os.kill(os.getpid(), _signal.SIGINT)
        if self.take("crash", step) is not None:
            raise FaultInjected(f"injected crash at step {step}")


# ---------------------------------------------------------------------------
# Hang watchdog
# ---------------------------------------------------------------------------

class Watchdog:
    """Monitor thread: fires when no ``beat()`` arrives within
    ``deadline_s``. On fire it writes the last-known phase/step and every
    live thread's Python stack to ``stream`` (default stderr), then calls
    ``on_timeout()`` if given, else ``os._exit(exit_code)`` — a hung device
    call or collective sits in C and cannot be interrupted by an exception,
    so the only useful outputs are the diagnosis and a restartable corpse.
    """

    def __init__(self, deadline_s: float, on_timeout: Optional[Callable] = None,
                 stream=None, exit_code: int = EXIT_WATCHDOG,
                 poll_s: Optional[float] = None):
        self.deadline_s = float(deadline_s)
        self.on_timeout = on_timeout
        self.stream = stream
        self.exit_code = exit_code
        self.poll_s = poll_s if poll_s is not None else min(
            1.0, self.deadline_s / 4)
        self.fired = False
        self._lock = threading.Lock()
        self._last = time.monotonic()
        self._phase = "start"
        self._step: Optional[int] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def beat(self, phase: str = "step", step: Optional[int] = None) -> None:
        with self._lock:
            self._last = time.monotonic()
            self._phase = phase
            self._step = step

    def start(self) -> "Watchdog":
        if self._thread is None:
            self.beat("start")
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._watch, name="hetu-watchdog", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)

    def __enter__(self) -> "Watchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _watch(self) -> None:
        while not self._stop.wait(self.poll_s):
            with self._lock:
                elapsed = time.monotonic() - self._last
                phase, step = self._phase, self._step
            if elapsed > self.deadline_s:
                self._fire(elapsed, phase, step)
                return

    def dump_stacks(self, stream=None) -> None:
        """Every live thread's Python stack (pure-Python, works with any
        stream — a thread blocked in a C call still shows its Python frames,
        which is exactly the 'where is it stuck' answer)."""
        stream = stream or self.stream or sys.stderr
        names = {t.ident: t.name for t in threading.enumerate()}
        for ident, frame in sys._current_frames().items():
            print(f"--- Thread {names.get(ident, '?')} (ident {ident}) ---",
                  file=stream)
            traceback.print_stack(frame, file=stream)

    def _fire(self, elapsed: float, phase: str, step) -> None:
        self.fired = True
        stream = self.stream or sys.stderr
        print(f"hetu watchdog: no progress for {elapsed:.1f}s "
              f"(deadline {self.deadline_s:.1f}s); last phase={phase!r} "
              f"step={step}; dumping thread stacks and aborting "
              f"(exit {self.exit_code})", file=stream)
        try:
            self.dump_stacks(stream)
        finally:
            _tel_event("watchdog_fire", flush=True, phase=phase, step=step,
                       elapsed_s=round(elapsed, 1))
            _flight_flush("watchdog")
            _incident("watchdog", step=step, phase=phase,
                      elapsed_s=round(elapsed, 1))
            try:
                stream.flush()
            except Exception:  # noqa: BLE001 — never let flush mask the abort
                pass
            if self.on_timeout is not None:
                self.on_timeout()
            else:
                os._exit(self.exit_code)


# ---------------------------------------------------------------------------
# Preemption
# ---------------------------------------------------------------------------

class Preempted(BaseException):
    """Control-flow, not an error (like KeyboardInterrupt — deliberately NOT
    an Exception subclass, so broad ``except Exception`` recovery paths and
    ``supervise()``'s restart logic cannot swallow it). Raised at a step
    boundary after any emergency checkpoint is durable. ``step`` is the
    last COMPLETED step; the latest durable checkpoint may be earlier (no
    checkpointer attached, or the same boundary rolled back) — resume from
    the checkpointer's ``latest_step()``, as ``supervise()`` does, not from
    ``step``."""

    def __init__(self, step: int):
        super().__init__(f"preempted after step {step}")
        self.step = step


class PreemptionHandler:
    """SIGTERM/SIGINT → a flag checked at step boundaries; the signal
    context itself does nothing else (async-signal-safe by construction).

    ``should_stop()`` is the COORDINATED check: under a multi-process world
    it is True on every host once any host got the signal, so the emergency
    checkpoint (a collective orbax write) starts on all hosts at the same
    step instead of deadlocking on the one host that was told to die.
    """

    def __init__(self, signals=(_signal.SIGTERM, _signal.SIGINT)):
        self.signals = tuple(signals)
        self.installed = False
        self._flag = False
        self.signum: Optional[int] = None
        self._prev: dict = {}

    def _handler(self, signum, frame):
        self._flag = True
        self.signum = signum

    def install(self) -> "PreemptionHandler":
        if not self.installed:
            for s in self.signals:
                self._prev[s] = _signal.signal(s, self._handler)
            self.installed = True
        return self

    def uninstall(self) -> None:
        if self.installed:
            for s, prev in self._prev.items():
                _signal.signal(s, prev)
            self._prev.clear()
            self.installed = False

    def __enter__(self) -> "PreemptionHandler":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    @property
    def requested(self) -> bool:
        """This process's local flag (no collective)."""
        return self._flag

    def should_stop(self) -> bool:
        from .parallel import multihost
        return multihost.any_process_flag(self._flag)


# ---------------------------------------------------------------------------
# Anomaly policy + loss scaling
# ---------------------------------------------------------------------------

class LossScaler:
    """Dynamic loss scale with backoff-on-anomaly / growth-on-streak (the
    standard mixed-precision recipe). The executor path does not scale losses
    itself (its guard skips the whole update); flagship loops multiply
    ``scaler.scale`` into the loss, divide it out of grads (``unscale``), and
    call ``update(finite)`` each step — the :class:`AnomalyPolicy` does the
    ``update`` call when it owns one."""

    def __init__(self, init_scale: float = 2.0 ** 15, backoff: float = 0.5,
                 growth: float = 2.0, growth_interval: int = 200,
                 min_scale: float = 1.0, max_scale: float = 2.0 ** 24):
        self.scale = float(init_scale)
        self.backoff = float(backoff)
        self.growth = float(growth)
        self.growth_interval = int(growth_interval)
        self.min_scale = float(min_scale)
        self.max_scale = float(max_scale)
        self._good_steps = 0

    def scale_loss(self, loss):
        return loss * self.scale

    def unscale(self, grads):
        import jax
        inv = 1.0 / self.scale
        return jax.tree.map(lambda g: g * inv, grads)

    def update(self, finite: bool) -> None:
        if not finite:
            self.scale = max(self.scale * self.backoff, self.min_scale)
            self._good_steps = 0
        else:
            self._good_steps += 1
            if self._good_steps >= self.growth_interval:
                self.scale = min(self.scale * self.growth, self.max_scale)
                self._good_steps = 0


class AnomalyPolicy:
    """Turns per-step finite verdicts into actions: ``"ok"`` (finite),
    ``"skip"`` (anomalous — the in-trace guard already kept params
    unchanged), or ``"rollback"`` (``max_consecutive`` anomalies in a row —
    restore the latest checkpoint; a stretch of skipped steps that long
    means the divergence is in surviving state, not the batch)."""

    def __init__(self, max_consecutive: int = 3, max_rollbacks: int = 3,
                 loss_scaler: Optional[LossScaler] = None):
        if max_consecutive < 1:
            raise ValueError(f"max_consecutive must be >= 1, "
                             f"got {max_consecutive}")
        self.max_consecutive = int(max_consecutive)
        # restore is deterministic (params AND dataloader position), so a
        # NaN with a deterministic cause replays identically after every
        # rollback — without a bound that is a silent livelock, not
        # recovery. Exceeding it raises out of the loop instead.
        self.max_rollbacks = int(max_rollbacks)
        self.loss_scaler = loss_scaler
        self.streak = 0
        self.total = 0
        self.rollbacks = 0

    def note(self, finite: bool) -> str:
        if self.loss_scaler is not None:
            self.loss_scaler.update(finite)
        if finite:
            self.streak = 0
            return "ok"
        self.streak += 1
        self.total += 1
        if self.streak >= self.max_consecutive:
            self.streak = 0
            self.rollbacks += 1
            return "rollback"
        return "skip"


# ---------------------------------------------------------------------------
# Executor state capture/restore (what a supervision checkpoint holds)
# ---------------------------------------------------------------------------

def capture_executor_state(ex) -> dict:
    """Everything a resume needs, as a numpy pytree TrainCheckpointer can
    save: params (by stable file name), optimizer slots, op state, the step
    counter (which also positions every per-step RNG fold), host dataloader
    cursors/RNG/peeked batch, and device-resident dataset cursors.

    ``Executor.save/load`` (directory-of-.npy) remains the graph-API
    surface; this pytree form is what the Supervisor/supervise() path
    feeds through TrainCheckpointer's atomic, retained, multi-host-
    coordinated step checkpoints."""
    import jax

    def host_np(x):
        """Host value of a possibly-sharded leaf: np.asarray raises on
        arrays spanning non-addressable devices (multi-host meshes — the
        exact world the coordinated preemption save exists for), so those
        go through the allgather path."""
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            from .parallel.multihost import fetch_replicated
            return fetch_replicated(x)
        return np.asarray(x)

    names = ex._param_file_names()
    state: dict[str, Any] = {
        "step": np.asarray(ex.state["step"], np.int64),
        "params": {name: host_np(ex.state["params"][id(n)])
                   for name, n in zip(names, ex.param_nodes)},
    }
    slots = {str(i): jax.tree.map(host_np, ex.state["slots"][id(n)])
             for i, n in enumerate(ex._opt_nodes())}
    if slots:
        state["slots"] = slots
    op_state = {str(i): jax.tree.map(host_np, ex.state["op_state"][id(n)])
                for i, n in enumerate(ex._stateful_nodes())}
    if op_state:
        state["op_state"] = op_state
    dls: dict[str, Any] = {}
    res: dict[str, Any] = {}
    for sub_name, sub in ex.subexecutors.items():
        per = {}
        for j, node in enumerate(getattr(sub, "dataloader_nodes", [])):
            sd = (node.state_dict(sub_name)
                  if hasattr(node, "state_dict") else None)
            if sd:
                per[str(j)] = sd
        if per:
            dls[sub_name] = per
        cursors = getattr(sub, "_dl_cursor", None)
        if cursors:
            res[sub_name] = {
                str(j): np.asarray(cursors[id(n)], np.int64)
                for j, n in enumerate(sub.res_dl_nodes) if id(n) in cursors}
    if dls:
        state["dataloaders"] = dls
    if res:
        state["resident_cursors"] = res
    return state


def load_executor_state(ex, state: dict) -> None:
    """Inverse of :func:`capture_executor_state` onto a live Executor (same
    graph; values may come from TrainCheckpointer's raw-numpy restore)."""
    import jax
    import jax.numpy as jnp

    def like_current(current, restored):
        """Re-impose the LIVE state's tree structure on restored leaves:
        orbax's raw restore returns tuples as lists, and the jitted step's
        pytrees must keep their exact treedef across a rollback."""
        leaves = [jnp.asarray(l) for l in jax.tree.leaves(restored)]
        return jax.tree.unflatten(jax.tree.structure(current), leaves)

    names = ex._param_file_names()
    params = state.get("params", {})
    for name, node in zip(names, ex.param_nodes):
        if name in params:
            ex.state["params"][id(node)] = ex._place_param(node, params[name])
    for i, n in enumerate(ex._opt_nodes()):
        if str(i) in state.get("slots", {}):
            ex.state["slots"][id(n)] = like_current(
                ex.state["slots"][id(n)], state["slots"][str(i)])
    for i, n in enumerate(ex._stateful_nodes()):
        if str(i) in state.get("op_state", {}):
            ex.state["op_state"][id(n)] = like_current(
                ex.state["op_state"][id(n)], state["op_state"][str(i)])
    ex.state["step"] = int(state["step"])
    ex.state["anomaly_streak"] = 0
    for sub_name, sub in ex.subexecutors.items():
        per = state.get("dataloaders", {}).get(sub_name, {})
        for j, node in enumerate(getattr(sub, "dataloader_nodes", [])):
            if str(j) in per and hasattr(node, "load_state_dict"):
                node.load_state_dict(sub_name, per[str(j)])
        # stale device-side prefetches were issued from pre-restore cursors
        if hasattr(sub, "_dev_prefetch"):
            sub._dev_prefetch.clear()
        cursors = state.get("resident_cursors", {}).get(sub_name, {})
        for j, node in enumerate(getattr(sub, "res_dl_nodes", [])):
            if str(j) in cursors:
                sub._dl_cursor[id(node)] = int(cursors[str(j)])


# ---------------------------------------------------------------------------
# The Supervisor: step-boundary hook object for Executor training loops
# ---------------------------------------------------------------------------

class Supervisor:
    """Ties the four pieces together for the graph-API path. Attach with
    ``executor.attach_supervisor(sup)``; ``SubExecutor.run`` then calls
    ``pre_step`` (watchdog beat + host fault injection) before dispatch and
    ``post_step`` (anomaly policy incl. rollback, periodic checkpoint,
    preemption check → emergency save + :class:`Preempted`) after the state
    commit. Use as a context manager (or call start/stop) so the watchdog
    thread and signal handlers are installed/removed deterministically.

    The gpipe/flagship loops drive the same pieces directly (beat/
    should_stop/AnomalyPolicy.note) — only plain SubExecutor gets the
    automatic wiring.
    """

    def __init__(self, ckptr=None, ckpt_every: Optional[int] = None,
                 anomaly: Optional[AnomalyPolicy] = None,
                 watchdog: Optional[Watchdog] = None,
                 preemption: Optional[PreemptionHandler] = None,
                 fault_injector: Any = "env", job_ckptr=None):
        # job_ckptr: a recovery.JobCheckpointer — when attached (the job
        # runs under a live hetusave coordinator), the SIGTERM grace window
        # upgrades from a worker-local emergency save to a COORDINATED job
        # snapshot, so the preemption leaves a globally consistent epoch
        # (worker + PS shards + cursors) instead of worker state alone.
        self.job_ckptr = job_ckptr
        self.ckptr = ckptr
        self.ckpt_every = ckpt_every
        self.anomaly = anomaly if anomaly is not None else AnomalyPolicy()
        self.watchdog = watchdog
        self.preemption = preemption
        self.fault_injector = (FaultInjector.from_env()
                               if fault_injector == "env" else fault_injector)
        self.last_saved_step: Optional[int] = None

    def start(self) -> "Supervisor":
        if self.watchdog is not None:
            self.watchdog.start()
        if self.preemption is not None:
            self.preemption.install()
        return self

    def stop(self) -> None:
        if self.watchdog is not None:
            self.watchdog.stop()
        if self.preemption is not None:
            self.preemption.uninstall()

    def __enter__(self) -> "Supervisor":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- hooks called by SubExecutor.run -----------------------------------
    def pre_step(self, ex, sub, step: int) -> None:
        if self.watchdog is not None:
            self.watchdog.beat(phase=f"{sub.name}:pre_step", step=step)
        if self.fault_injector is not None:
            self.fault_injector.inject_host(step, ex=ex)

    def inject_nan(self, step: int) -> bool:
        """Whether this step's in-trace update should be NaN-poisoned
        (consumes the fault entry)."""
        fi = self.fault_injector
        return fi is not None and fi.fires("nan_grads", step)

    def poison_op(self, step: int) -> Optional[str]:
        """The op whose output this step's trace should NaN-poison
        (consumes the ``nan_op`` fault entry): None = no poison, ``""`` =
        the executor's default first op, else the op's scope name."""
        fi = self.fault_injector
        if fi is None:
            return None
        e = fi.take("nan_op", step)
        if e is None:
            return None
        return e["arg"] or ""

    def post_step(self, ex, sub, step: int, finite: bool = True,
                  loss=None, grad_norm=None) -> None:
        """``loss``/``grad_norm`` are the at-trip headline numbers the
        executor passes on a non-finite step (loss is NaN/Inf by
        construction — that IS the headline; grad_norm arrives when the
        hetuscope provenance pass ran) — recorded in the anomaly event so
        post-mortems need not open the flight recorder for them."""
        if self.watchdog is not None:
            self.watchdog.beat(phase=f"{sub.name}:post_step", step=step)
        action = self.anomaly.note(bool(finite))
        if not finite:
            from .telemetry.scope import json_num
            extra = {}
            if loss is not None:
                # non-finite (the usual case at a trip) serializes as the
                # string "NaN"/"Infinity" — the JSONL must stay strict JSON
                extra["loss"] = json_num(loss)
            if grad_norm is not None:
                extra["grad_norm"] = json_num(grad_norm)
            _tel_event("anomaly", step=step, action=action,
                       streak=self.anomaly.streak, **extra)
        if action == "rollback":
            # freeze the incident BEFORE rolling back: the window must show
            # the poisoned steps, not the restored state overwriting them
            _incident("anomaly", step=step,
                      streak=self.anomaly.streak)
            self._rollback(ex)
        elif action == "ok" and self.ckptr is not None and self.ckpt_every \
                and (step + 1) % self.ckpt_every == 0:
            self.save(ex, step)
        if self.preemption is not None and self.preemption.should_stop():
            # Skip the emergency save when (a) the periodic cadence just
            # wrote this exact step (that save IS the emergency checkpoint)
            # or (b) this call rolled back — the executor now holds the
            # already-durable checkpoint's state, and writing it under id
            # ``step`` would break the 'checkpoint id = last completed
            # step' invariant resume arithmetic relies on.
            coordinated = False
            if self.job_ckptr is not None and action != "rollback":
                # coordinated upgrade: quiesce the whole job and commit one
                # consistent epoch inside the grace window. Best-effort —
                # a failed coordination (e.g. scheduler already gone) falls
                # back to the worker-local emergency save below.
                # save_preempt bounds the drain barrier by the grace
                # budget (JobCheckpointer grace_s / HETU_PREEMPT_GRACE_S,
                # minus headroom) so a hung barrier fails with time LEFT
                # in the window — otherwise the SIGKILL would land
                # mid-coordination and cost the worker-local save too.
                try:
                    self.job_ckptr.save_preempt(ex, step)
                    coordinated = True
                    self.last_saved_step = step
                    _tel_event("emergency_save", step=step,
                               coordinated=True)
                except Exception as je:  # noqa: BLE001 — grace window:
                    # any failure must not cost the worker-local save
                    print(f"# hetu supervisor: coordinated snapshot failed "
                          f"({je!r}); falling back to worker-local save",
                          file=sys.stderr)
            if not coordinated and self.ckptr is not None \
                    and self.last_saved_step != step \
                    and action != "rollback":
                self.save(ex, step)
                _tel_event("emergency_save", step=step)
            durable = (f"durable coordinated epoch: step "
                       f"{self.last_saved_step} (heturun --restore)"
                       if coordinated else
                       "no checkpointer attached — resume will cold-start"
                       if self.ckptr is None else
                       f"durable checkpoint: step {self.last_saved_step}")
            print(f"# hetu supervisor: preemption signal "
                  f"({self.preemption.signum}) at step {step}; {durable}; "
                  f"exiting", file=sys.stderr)
            _tel_event("preempted", flush=True, step=step,
                       signum=self.preemption.signum,
                       durable_step=self.last_saved_step)
            _flight_flush("preempted")
            _incident("preempted", step=step,
                      durable_step=self.last_saved_step)
            raise Preempted(step)

    # -- checkpoint plumbing ------------------------------------------------
    def save(self, ex, step: int) -> None:
        """Checkpoint id = last COMPLETED step; the state inside carries
        ``step+1`` (the next step to run), so resume needs no arithmetic.
        force=True lets an emergency save land on a step the periodic
        cadence already wrote."""
        t0 = time.perf_counter()
        self.ckptr.save_step(step, capture_executor_state(ex), force=True)
        self.last_saved_step = step
        from . import telemetry as _telemetry
        tel = _telemetry.get()
        if tel is not None:
            tel.metrics.histogram("hetu_checkpoint_save_ms").observe(
                (time.perf_counter() - t0) * 1e3)

    def _rollback(self, ex) -> None:
        if self.ckptr is None:
            raise RuntimeError(
                f"{self.anomaly.max_consecutive} consecutive non-finite "
                "steps and no checkpointer to roll back to")
        if self.anomaly.rollbacks > self.anomaly.max_rollbacks:
            raise RuntimeError(
                f"anomaly rollback requested {self.anomaly.rollbacks} times "
                f"(max_rollbacks={self.anomaly.max_rollbacks}); the "
                "divergence survives restore — a deterministic NaN source, "
                "not a transient")
        state, ck_step = self.ckptr.restore_latest()
        if state is None:
            raise RuntimeError(
                f"{self.anomaly.max_consecutive} consecutive non-finite "
                "steps and no checkpoint exists yet to roll back to")
        load_executor_state(ex, state)
        _tel_event("rollback", ckpt_step=int(ck_step),
                   rollbacks=self.anomaly.rollbacks)
        print(f"# hetu supervisor: anomaly streak hit "
              f"{self.anomaly.max_consecutive}; rolled back to checkpoint "
              f"step {ck_step}", file=sys.stderr)


# ---------------------------------------------------------------------------
# Auto-resume driver
# ---------------------------------------------------------------------------

def supervise(loop_fn, ckptr=None, *, max_restarts: int = 3,
              backoff_s: float = 0.5, backoff_factor: float = 2.0,
              recoverable=(Exception,), like=None, mesh=None, specs=None,
              on_preempt: str = "exit", sleep=time.sleep):
    """Run ``loop_fn(state, start_step)`` under restart supervision.

    Before each attempt the latest checkpoint is restored (``state`` is its
    pytree, None on cold start) and ``start_step`` is the first step to run
    — checkpoints are numbered by last COMPLETED step, so
    ``start_step = latest + 1``. On a ``recoverable`` exception the attempt
    counts against ``max_restarts`` and the next one starts after an
    exponentially growing backoff; anything else (and exhaustion) propagates.

    :class:`Preempted` is never retried: with ``on_preempt="exit"`` (the
    default, for __main__ scripts under heturun/k8s) it becomes
    ``SystemExit(EXIT_PREEMPTED)``; ``on_preempt="raise"`` hands it to an
    embedding caller.

    ``like``/``mesh``/``specs`` pass through to
    ``TrainCheckpointer.restore_latest`` for sharded (flagship-path)
    states; the graph-API path restores raw numpy and feeds it to
    :func:`load_executor_state` inside ``loop_fn``.
    """
    if on_preempt not in ("exit", "raise"):
        raise ValueError(f"on_preempt must be 'exit' or 'raise', "
                         f"got {on_preempt!r}")
    restarts = 0
    delay = float(backoff_s)
    while True:
        state, ck_step = (None, None)
        if ckptr is not None:
            state, ck_step = ckptr.restore_latest(like=like, mesh=mesh,
                                                  specs=specs)
        start_step = 0 if ck_step is None else int(ck_step) + 1
        try:
            return loop_fn(state, start_step)
        except Preempted as e:
            if on_preempt == "raise":
                raise
            print(f"# hetu supervise: preempted after step {e.step}; "
                  f"exiting {EXIT_PREEMPTED}", file=sys.stderr)
            raise SystemExit(EXIT_PREEMPTED)
        except recoverable as e:
            _flight_flush("crash")
            restarts += 1
            if restarts > max_restarts:
                _incident("crash", error=type(e).__name__,
                          restarts=restarts - 1)
                raise
            _tel_event("restart", flush=True, attempt=restarts,
                       max_restarts=max_restarts, error=type(e).__name__)
            _incident("crash", error=type(e).__name__, attempt=restarts)
            print(f"# hetu supervise: {type(e).__name__}: {e} — restart "
                  f"{restarts}/{max_restarts} after {delay:.1f}s backoff",
                  file=sys.stderr)
            sleep(delay)
            delay *= backoff_factor
