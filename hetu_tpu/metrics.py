"""Numpy evaluation metrics (reference ``python/hetu/metrics.py``): softmax,
thresholded confusion matrices, ROC/PR AUC, accuracy, precision/recall/F-beta.
Host-side numpy by design — these run on eval results, not in the step.
"""
from __future__ import annotations

import warnings

import numpy as np


def softmax_func(y):
    y = np.asarray(y, dtype=np.float64)
    e = np.exp(y - y.max(axis=-1, keepdims=True))
    return e / e.sum(axis=-1, keepdims=True)


def confusion_matrix_at_thresholds(labels, predictions, thresholds,
                                   includes=None):
    """Per-threshold TP/FN/TN/FP dict (reference metrics.py:17)."""
    labels = np.asarray(labels).reshape(-1).astype(bool)
    predictions = np.asarray(predictions).reshape(-1)
    if includes is None:
        includes = ("tp", "fn", "tn", "fp")
    out = {k: np.zeros(len(thresholds), dtype=np.float64) for k in includes}
    for i, t in enumerate(thresholds):
        pred_pos = predictions > t
        if "tp" in out:
            out["tp"][i] = np.sum(pred_pos & labels)
        if "fn" in out:
            out["fn"][i] = np.sum(~pred_pos & labels)
        if "tn" in out:
            out["tn"][i] = np.sum(~pred_pos & ~labels)
        if "fp" in out:
            out["fp"][i] = np.sum(pred_pos & ~labels)
    return out


def roc_pr_curve(values, curve="ROC"):
    tp, fn, tn, fp = values["tp"], values["fn"], values["tn"], values["fp"]
    eps = 1e-7
    if curve == "ROC":
        x = fp / (fp + tn + eps)
        y = tp / (tp + fn + eps)
    else:  # PR
        x = tp / (tp + fn + eps)
        y = tp / (tp + fp + eps)
    return x, y


def auc(labels, predictions, num_thresholds=200, curve="ROC"):
    """Trapezoidal AUC over thresholded confusion matrices
    (reference metrics.py:120).

    Degenerate inputs return NaN with a warning instead of an ``eps``-fudged
    arbitrary number: empty inputs, and single-class labels — ROC needs
    both classes (TPR or FPR is 0/0 at every threshold), PR needs at least
    one positive. The previous behavior silently returned a value like
    ~0.5 whose magnitude was pure epsilon artifact.
    """
    flat_labels = np.asarray(labels).reshape(-1).astype(bool)
    flat_preds = np.asarray(predictions).reshape(-1)
    n_pos = int(flat_labels.sum())
    n_neg = flat_labels.size - n_pos
    degenerate = None
    if flat_preds.size == 0 or flat_labels.size == 0:
        degenerate = "empty labels/predictions"
    elif curve == "ROC" and (n_pos == 0 or n_neg == 0):
        degenerate = (f"single-class labels ({n_pos} positive, {n_neg} "
                      "negative) — ROC AUC needs both classes")
    elif curve != "ROC" and n_pos == 0:
        degenerate = "no positive labels — PR AUC needs at least one"
    if degenerate is not None:
        warnings.warn(f"auc({curve}) is undefined for {degenerate}; "
                      "returning NaN", stacklevel=2)
        return float("nan")
    eps = 1e-7
    thresholds = [(i + 1) * 1.0 / (num_thresholds - 1)
                  for i in range(num_thresholds - 2)]
    thresholds = [0.0 - eps] + thresholds + [1.0 + eps]
    values = confusion_matrix_at_thresholds(flat_labels, flat_preds,
                                            thresholds)
    x, y = roc_pr_curve(values, curve=curve)
    return float(np.sum(np.abs(np.diff(x)) * (y[:-1] + y[1:]) / 2.0))


def accuracy(labels, predictions):
    labels = np.asarray(labels)
    predictions = np.asarray(predictions)
    if labels.ndim > 1:
        labels = labels.argmax(-1)
    if predictions.ndim > 1:
        predictions = predictions.argmax(-1)
    return float(np.mean(labels == predictions))


def confusion_matrix_one_hot(labels, predictions):
    labels = np.asarray(labels).argmax(-1)
    predictions = np.asarray(predictions).argmax(-1)
    n = max(labels.max(), predictions.max()) + 1
    cm = np.zeros((n, n), dtype=np.int64)
    for t, p in zip(labels, predictions):
        cm[t, p] += 1
    return cm


def _prf_counts(labels, predictions):
    cm = confusion_matrix_one_hot(labels, predictions)
    tp = np.diag(cm).astype(np.float64)
    fp = cm.sum(axis=0) - tp
    fn = cm.sum(axis=1) - tp
    return tp, fp, fn


def precision_score_one_hot(labels, predictions, average=None):
    tp, fp, _ = _prf_counts(labels, predictions)
    if average == "micro":
        return float(tp.sum() / max(tp.sum() + fp.sum(), 1e-7))
    per_class = tp / np.maximum(tp + fp, 1e-7)
    if average == "macro":
        return float(per_class.mean())
    return per_class


def recall_score_one_hot(labels, predictions, average=None):
    tp, _, fn = _prf_counts(labels, predictions)
    if average == "micro":
        return float(tp.sum() / max(tp.sum() + fn.sum(), 1e-7))
    per_class = tp / np.maximum(tp + fn, 1e-7)
    if average == "macro":
        return float(per_class.mean())
    return per_class


def f_score_one_hot(labels, predictions, beta=1.0, average=None):
    p = precision_score_one_hot(labels, predictions, average=average)
    r = recall_score_one_hot(labels, predictions, average=average)
    b2 = beta * beta
    return (1 + b2) * p * r / np.maximum(b2 * p + r, 1e-7) if not np.isscalar(p) \
        else float((1 + b2) * p * r / max(b2 * p + r, 1e-7))
