"""BERT WordPiece tokenizer (reference
``python/hetu/tokenizers/bert_tokenizer.py:12-19`` — basic tokenization +
greedy longest-match wordpiece).

Self-contained: vocabularies load from local files (this image has no
egress, so the reference's S3 vocab-download map is names-only here; pass a
vocab path). The algorithm matches the canonical BERT behavior: text
cleanup, optional lowercasing with accent stripping, punctuation splitting,
CJK character isolation, then greedy ``##``-continuation wordpieces.
"""
from __future__ import annotations

import collections
import unicodedata

# kept for API parity with the reference's PRETRAINED_VOCAB_ARCHIVE_MAP;
# this environment cannot download, so these are names only
PRETRAINED_VOCAB_NAMES = [
    "bert-base-uncased", "bert-large-uncased", "bert-base-cased",
    "bert-large-cased", "bert-base-multilingual-uncased",
    "bert-base-multilingual-cased", "bert-base-chinese",
]
VOCAB_NAME = "vocab.txt"


def load_vocab(vocab_file):
    """Load a vocabulary file into an ordered token -> id dict."""
    vocab = collections.OrderedDict()
    with open(vocab_file, "r", encoding="utf-8") as reader:
        for index, line in enumerate(reader):
            token = line.rstrip("\n")
            if token:
                vocab[token] = index
    return vocab


def whitespace_tokenize(text):
    text = text.strip()
    return text.split() if text else []


def _is_whitespace(ch):
    if ch in (" ", "\t", "\n", "\r"):
        return True
    return unicodedata.category(ch) == "Zs"


def _is_control(ch):
    if ch in ("\t", "\n", "\r"):
        return False
    return unicodedata.category(ch).startswith("C")


def _is_punctuation(ch):
    cp = ord(ch)
    # ASCII non-alphanumerics count as punctuation (BERT convention: "$" or
    # "@" split too, even though unicode doesn't class them as P*)
    if (33 <= cp <= 47) or (58 <= cp <= 64) or (91 <= cp <= 96) \
            or (123 <= cp <= 126):
        return True
    return unicodedata.category(ch).startswith("P")


def _is_cjk(cp):
    return ((0x4E00 <= cp <= 0x9FFF) or (0x3400 <= cp <= 0x4DBF)
            or (0x20000 <= cp <= 0x2A6DF) or (0x2A700 <= cp <= 0x2B73F)
            or (0x2B740 <= cp <= 0x2B81F) or (0x2B820 <= cp <= 0x2CEAF)
            or (0xF900 <= cp <= 0xFAFF) or (0x2F800 <= cp <= 0x2FA1F))


class BasicTokenizer:
    """Cleanup + punctuation/CJK splitting (+ lowercase/accent-strip)."""

    def __init__(self, do_lower_case=True,
                 never_split=("[UNK]", "[SEP]", "[PAD]", "[CLS]", "[MASK]")):
        self.do_lower_case = do_lower_case
        self.never_split = set(never_split)

    def tokenize(self, text):
        text = self._clean_text(text)
        text = self._pad_cjk(text)
        tokens = whitespace_tokenize(text)
        out = []
        for tok in tokens:
            if tok in self.never_split:
                out.append(tok)
                continue
            if self.do_lower_case:
                tok = self._strip_accents(tok.lower())
            out.extend(self._split_punct(tok))
        return whitespace_tokenize(" ".join(out))

    def _clean_text(self, text):
        out = []
        for ch in text:
            cp = ord(ch)
            if cp == 0 or cp == 0xFFFD or _is_control(ch):
                continue
            out.append(" " if _is_whitespace(ch) else ch)
        return "".join(out)

    @staticmethod
    def _pad_cjk(text):
        out = []
        for ch in text:
            if _is_cjk(ord(ch)):
                out.extend((" ", ch, " "))
            else:
                out.append(ch)
        return "".join(out)

    @staticmethod
    def _strip_accents(text):
        return "".join(ch for ch in unicodedata.normalize("NFD", text)
                       if unicodedata.category(ch) != "Mn")

    @staticmethod
    def _split_punct(text):
        pieces = []
        cur = []
        for ch in text:
            if _is_punctuation(ch):
                if cur:
                    pieces.append("".join(cur))
                    cur = []
                pieces.append(ch)
            else:
                cur.append(ch)
        if cur:
            pieces.append("".join(cur))
        return pieces


class WordpieceTokenizer:
    """Greedy longest-match-first wordpiece with ``##`` continuations."""

    def __init__(self, vocab, unk_token="[UNK]", max_input_chars_per_word=100):
        self.vocab = vocab
        self.unk_token = unk_token
        self.max_input_chars_per_word = max_input_chars_per_word

    def tokenize(self, text):
        out = []
        for token in whitespace_tokenize(text):
            chars = list(token)
            if len(chars) > self.max_input_chars_per_word:
                out.append(self.unk_token)
                continue
            pieces = []
            start = 0
            bad = False
            while start < len(chars):
                end = len(chars)
                cur = None
                while start < end:
                    sub = "".join(chars[start:end])
                    if start > 0:
                        sub = "##" + sub
                    if sub in self.vocab:
                        cur = sub
                        break
                    end -= 1
                if cur is None:
                    bad = True
                    break
                pieces.append(cur)
                start = end
            out.extend([self.unk_token] if bad else pieces)
        return out


class BertTokenizer:
    """End-to-end: basic tokenization then wordpiece
    (reference BertTokenizer)."""

    def __init__(self, vocab_file, do_lower_case=True, max_len=None,
                 never_split=("[UNK]", "[SEP]", "[PAD]", "[CLS]", "[MASK]")):
        self.vocab = (vocab_file if isinstance(vocab_file, dict)
                      else load_vocab(vocab_file))
        self.ids_to_tokens = {i: t for t, i in self.vocab.items()}
        self.basic_tokenizer = BasicTokenizer(do_lower_case, never_split)
        self.wordpiece_tokenizer = WordpieceTokenizer(self.vocab)
        self.max_len = max_len if max_len is not None else int(1e12)

    def tokenize(self, text):
        tokens = []
        for tok in self.basic_tokenizer.tokenize(text):
            if tok in self.basic_tokenizer.never_split:
                tokens.append(tok)
            else:
                tokens.extend(self.wordpiece_tokenizer.tokenize(tok))
        return tokens

    def convert_tokens_to_ids(self, tokens):
        ids = [self.vocab.get(t, self.vocab.get("[UNK]")) for t in tokens]
        if len(ids) > self.max_len:
            raise ValueError(
                f"sequence length {len(ids)} exceeds max_len {self.max_len}")
        return ids

    def convert_ids_to_tokens(self, ids):
        return [self.ids_to_tokens[i] for i in ids]

    def encode(self, text):
        return self.convert_tokens_to_ids(self.tokenize(text))
