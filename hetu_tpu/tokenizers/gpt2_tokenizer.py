"""GPT-2 byte-level BPE tokenizer — completes the GPT-2 inference path
(tokenize -> ``models/hf_gpt2`` checkpoint -> ``models/generate`` decode).

Beyond reference parity: the reference ships only the BERT WordPiece
tokenizer (``python/hetu/tokenizers``); it has no BPE. This is an
independent implementation of the canonical algorithm (Radford et al.
2019): UTF-8 bytes are mapped to printable unicode proxies, text is
pre-split by the GPT-2 regex pattern, and each pre-token is merged
greedily by ascending merge rank. ``tests/test_gpt2_tokenizer.py`` pins
token-for-token equality against ``transformers.GPT2Tokenizer`` over
byte-level-odd inputs (emoji, CJK, control chars, long words).

Vocabulary files are the standard ``vocab.json`` + ``merges.txt`` pair
(this image has no egress — point at local files; any HF GPT-2 tokenizer
directory works).
"""
from __future__ import annotations

import json
from functools import lru_cache

try:                      # the canonical pattern needs \p classes;
    import regex as _re   # transformers depends on `regex`, so it is
    _HAS_REGEX = True     # present wherever the oracle is
except ImportError:       # pragma: no cover - exercised only without regex
    _re = None
    _HAS_REGEX = False

# GPT-2's pre-tokenization pattern: contractions, letter runs (with an
# optional leading space), number runs, other-symbol runs, trailing spaces
_PATTERN = (r"'s|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+"
            r"| ?[^\s\p{L}\p{N}]+|\s+(?!\S)|\s+")


@lru_cache()
def bytes_to_unicode():
    """The GPT-2 byte->printable-unicode table: printable ASCII and two
    Latin-1 ranges map to themselves, the remaining 68 bytes map to
    256+i so every byte has a visible, json-safe proxy character."""
    bs = (list(range(ord("!"), ord("~") + 1))
          + list(range(ord("\xa1"), ord("\xac") + 1))
          + list(range(ord("\xae"), ord("\xff") + 1)))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, [chr(c) for c in cs]))


def _pairs(word):
    return {(word[i], word[i + 1]) for i in range(len(word) - 1)}


class GPT2Tokenizer:
    """vocab.json + merges.txt -> encode/decode matching HF's GPT2Tokenizer
    (the slow/reference implementation) token for token."""

    def __init__(self, vocab_file, merges_file, errors="replace",
                 special_tokens=("<|endoftext|>",)):
        with open(vocab_file, encoding="utf-8") as f:
            self.encoder = json.load(f)
        # special tokens are never split by BPE; ones absent from the
        # vocab are appended in the GIVEN order. HF appends its specials
        # in special-token-ATTRIBUTE order (bos, eos, unk, sep, pad, cls,
        # mask, additional) — pass yours in that order and the appended
        # ids line up with the transformers oracle (pinned by test)
        self.special_tokens = tuple(dict.fromkeys(special_tokens))
        for tok in self.special_tokens:
            if tok not in self.encoder:
                self.encoder[tok] = len(self.encoder)
        self.decoder = {v: k for k, v in self.encoder.items()}
        with open(merges_file, encoding="utf-8") as f:
            # HF drops the first line (assumed #version header) and the
            # last (assumed empty from the trailing newline) UNCONDITIONALLY
            # — mirror that exactly, or ranks shift by one on files
            # without a header / without a trailing newline
            lines = f.read().split("\n")[1:-1]
        merges = [tuple(line.split()) for line in lines]
        self.bpe_ranks = dict(zip(merges, range(len(merges))))
        self.byte_encoder = bytes_to_unicode()
        self.byte_decoder = {v: k for k, v in self.byte_encoder.items()}
        self.errors = errors
        self._cache = {}
        if not _HAS_REGEX:
            raise ImportError(
                "GPT2Tokenizer needs the `regex` module for the canonical "
                "\\p{L}/\\p{N} pre-tokenization pattern")
        self._pat = _re.compile(_PATTERN)

    # -- BPE over one pre-token (already byte-mapped) ---------------------
    def _bpe(self, token: str) -> list[str]:
        if token in self._cache:
            return self._cache[token]
        word = tuple(token)
        while len(word) > 1:
            pair = min(_pairs(word),
                       key=lambda p: self.bpe_ranks.get(p, float("inf")))
            if pair not in self.bpe_ranks:
                break
            a, b = pair
            merged, i = [], 0
            while i < len(word):
                if (i < len(word) - 1 and word[i] == a and word[i + 1] == b):
                    merged.append(a + b)
                    i += 2
                else:
                    merged.append(word[i])
                    i += 1
            word = tuple(merged)
        out = list(word)
        self._cache[token] = out
        return out

    def _split_specials(self, text: str) -> list[str]:
        """Split into alternating plain-text / special-token chunks; BPE
        never crosses a special-token boundary."""
        chunks = [text]
        # longest-first: a special that is a substring of another (e.g.
        # "<|end|>" vs "<|endoftext|>") must not tear the longer one apart
        # — HF matches added tokens longest-first the same way
        for tok in sorted(self.special_tokens, key=len, reverse=True):
            nxt = []
            for c in chunks:
                if c in self.special_tokens:
                    nxt.append(c)
                    continue
                parts = c.split(tok)
                for i, p in enumerate(parts):
                    if i:
                        nxt.append(tok)
                    if p:
                        nxt.append(p)
            chunks = nxt
        return chunks

    def tokenize(self, text: str) -> list[str]:
        toks = []
        for chunk in self._split_specials(text):
            if chunk in self.special_tokens:
                toks.append(chunk)
                continue
            for pre in self._pat.findall(chunk):
                mapped = "".join(self.byte_encoder[b]
                                 for b in pre.encode("utf-8"))
                toks.extend(self._bpe(mapped))
        return toks

    def encode(self, text: str) -> list[int]:
        out = []
        for t in self.tokenize(text):
            try:
                out.append(self.encoder[t])
            except KeyError:
                raise ValueError(
                    f"token {t!r} produced by merges.txt is absent from "
                    f"vocab.json — the vocab/merges pair is mismatched "
                    f"(files from different checkpoints?)") from None
        return out

    def decode(self, ids) -> str:
        # byte proxies must be concatenated ACROSS tokens before UTF-8
        # decoding (a multi-byte char can span BPE tokens); specials are
        # literal text and flush the pending byte run
        out, run = [], []

        def flush():
            if run:
                out.append(bytearray(self.byte_decoder[c]
                                     for c in "".join(run))
                           .decode("utf-8", errors=self.errors))
                run.clear()

        for i in ids:
            tok = self.decoder[int(i)]
            if tok in self.special_tokens:
                flush()
                out.append(tok)
            else:
                run.append(tok)
        flush()
        return "".join(out)

    @property
    def vocab_size(self) -> int:
        return len(self.encoder)
