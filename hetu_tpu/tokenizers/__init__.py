from .bert_tokenizer import (
    BasicTokenizer, WordpieceTokenizer, BertTokenizer, load_vocab,
    whitespace_tokenize,
)

__all__ = ["BasicTokenizer", "WordpieceTokenizer", "BertTokenizer",
           "load_vocab", "whitespace_tokenize"]
