from .bert_tokenizer import (
    BasicTokenizer, WordpieceTokenizer, BertTokenizer, load_vocab,
    whitespace_tokenize,
)
from .gpt2_tokenizer import GPT2Tokenizer, bytes_to_unicode

__all__ = ["BasicTokenizer", "WordpieceTokenizer", "BertTokenizer",
           "load_vocab", "whitespace_tokenize", "GPT2Tokenizer",
           "bytes_to_unicode"]
