"""Distributed checkpoint / resume for the flagship (jax-native) path.

Three checkpoint surfaces exist in the framework, mirroring and extending
the reference's (``Executor.save/load`` in the reference saves parameter
NDArrays; PS ``SaveParam/LoadParam`` snapshots server shards):

- graph API: ``Executor.save/load`` (params + optimizer slots + step),
- parameter server: ``ParamSave``/``ParamLoad`` PSFs + crash recovery that
  restores a replacement server's shard before it serves,
- THIS module: sharded multi-chip/multi-host checkpoints for the flagship
  models, built on orbax (OCDBT): every process writes only its own shards,
  restore re-applies any target sharding — including onto a DIFFERENT mesh
  than the one that saved (resharding happens on load), which the
  reference cannot do at all.
"""
from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp


def _abstract_like(tree, mesh, specs):
    """Build the abstract target (shapes/dtypes + shardings) restore needs."""
    from jax.sharding import NamedSharding

    def one(x, spec):
        sh = (NamedSharding(mesh, spec) if mesh is not None and spec is not None
              else None)
        return jax.ShapeDtypeStruct(np.shape(x), x.dtype, sharding=sh)

    if specs is None:
        return jax.tree.map(lambda x: one(x, None), tree)
    from jax.sharding import PartitionSpec as P
    return jax.tree.map(one, tree, specs,
                        is_leaf=lambda x: isinstance(x, P))


def save(path: str, state: Any, force: bool = False) -> None:
    """Write ``state`` (any pytree of arrays) to ``path``. Under a
    multi-process world every process participates and writes only the
    shards it owns; the call blocks until the checkpoint is durable.
    ``force=True`` overwrites an existing checkpoint at ``path`` (fixed
    latest-checkpoint patterns); the default refuses, like the PS
    ``ParamSave`` tmp+rename discipline, so a crash mid-save can never
    destroy the previous good checkpoint by accident."""
    path = os.path.abspath(os.fspath(path))
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, state, force=force)
        ckptr.wait_until_finished()


def restore(path: str, like: Any = None, mesh=None, specs: Any = None):
    """Read a checkpoint back.

    - ``like``: a pytree of arrays or ShapeDtypeStructs giving the expected
      structure. With ``mesh``+``specs`` the restored arrays come back
      SHARDED to those specs (any mesh — resharding on load).
    - with no ``like``: raw numpy restore (host-local, inspection/tools).
    """
    path = os.path.abspath(os.fspath(path))
    if like is None:
        # raw numpy restore works regardless of which devices/processes
        # wrote the checkpoint (inspection, cross-world recovery)
        with ocp.PyTreeCheckpointer() as ckptr:
            meta = ckptr.metadata(path)
            # orbax <=0.7 returns the metadata tree directly; newer wraps
            # it in CheckpointMetadata.item_metadata.tree
            item = getattr(meta, "item_metadata", None)
            tree = getattr(item, "tree", None) if item is not None else meta
            args = jax.tree.map(
                lambda m: ocp.RestoreArgs(restore_type=np.ndarray), tree)
            return ckptr.restore(path, args=ocp.args.PyTreeRestore(
                restore_args=args))
    with ocp.StandardCheckpointer() as ckptr:
        target = _abstract_like(like, mesh, specs)
        return ckptr.restore(path, target)


class TrainCheckpointer:
    """Step-numbered checkpoints with retention (resume-from-latest).

    ``hetu_tpu.checkpoint.TrainCheckpointer(dir, keep=3)``:
    ``save_step(step, state)`` / ``latest_step()`` /
    ``restore_latest(like, mesh, specs)``.
    """

    def __init__(self, directory: str, keep: int = 3):
        self._mgr = ocp.CheckpointManager(
            os.path.abspath(os.fspath(directory)),
            options=ocp.CheckpointManagerOptions(max_to_keep=keep,
                                                 create=True))

    def save_step(self, step: int, state: Any, force: bool = False) -> None:
        """Write the checkpoint for ``step`` and block until durable.
        ``force=True`` overwrites an existing checkpoint at the same step —
        the preemption-emergency path (resilience.Supervisor.save) may land
        on a step the periodic cadence already wrote, and losing the save
        to a refusal would lose the preemption guarantee. (orbax's own
        ``force`` only bypasses should_save policies; an existing step still
        raises StepAlreadyExistsError, so it is deleted first — older
        retained steps stay untouched if the rewrite dies midway.)"""
        if force and step in (self._mgr.all_steps() or ()):
            self._mgr.delete(step)
        self._mgr.save(step, args=ocp.args.StandardSave(state), force=force)
        self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore_latest(self, like: Any = None, mesh=None, specs: Any = None):
        step = self._mgr.latest_step()
        if step is None:
            return None, None
        if like is None:
            # raw numpy restore (inspection / different-topology recovery),
            # same semantics as module-level restore(path)
            d = os.path.join(str(self._mgr.directory), str(step), "default")
            if not os.path.isdir(d):
                d = os.path.join(str(self._mgr.directory), str(step))
            return restore(d), step
        target = _abstract_like(like, mesh, specs)
        return self._mgr.restore(
            step, args=ocp.args.StandardRestore(target)), step

    def close(self):
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
