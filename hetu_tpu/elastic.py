"""hetu-elastic: live worker/PS membership changes without a job restart.

The robustness stack through PR 4/8 survives *faults* (server SIGKILL →
snapshot respawn, worker crash → supervised restart) but any *planned*
membership change — a preempted host leaving, capacity arriving — still
meant killing and relaunching the whole job. This module closes that gap
(SURVEY.md "no elastic training"; ROADMAP item 4): a running job can lose
or gain workers and PS servers at a step boundary with exact accounting.

Three cooperating legs (docs/FAULT_TOLERANCE.md "Elastic membership"):

1. **Membership epochs in the scheduler** (``csrc/ps/scheduler.h``): the
   registry that already tracks per-rank incarnation epochs grows a
   *world-version* counter and a two-phase resize handshake —
   ``kProposeResize`` (phase 1: capacity grows immediately so joining
   servers can register; nothing else changes) → surviving workers park in
   ``kCommitResize`` at their next step boundary (the drain barrier: all
   in-flight PS traffic completed first) → the coordinator migrates state
   → ``kFinishResize`` (phase 2: the world atomically flips and every
   parked worker is released with the new membership). Requests stamped
   with an old world version are rejected at the server the same way
   resend-dedup rejects duplicates (``MsgHeader.world_ver``; 0 =
   unversioned legacy traffic, always accepted).

2. **dp re-partition in the trainer**: at the commit boundary each
   survivor recomputes its data-parallel position from the scheduler's
   world log and re-partitions every ``Dataloader`` over the *remaining*
   (unconsumed) samples — :func:`era_partitions` proves each retained
   sample is consumed exactly once across any sequence of resizes. Device
   state re-shards through the existing checkpoint capture/restore path
   (``Executor.remesh``; no new serialization format).

3. **Live PS key-range split/migration**: a joining server registers
   empty; donors stream the affected rows using the v2 snapshot shard
   format as the transfer medium (``kParamSave`` under the per-param
   shared locks — serving never pauses during the save), this module
   re-partitions rows/optimizer-slots/version-counters into the new
   key-ranges (:func:`repartition_key`), and every server loads its
   new shard. Update-counter stamps (``kServerStats`` slot 0) give exact
   lost-update accounting across the move: a clean migration preserves
   the sum bit-for-bit.

Everything here is stdlib + numpy over raw sockets (the wire mirror the
PSSupervisor already speaks), so the coordinator can live in the jax-free
launcher parent (``heturun --elastic``).
"""
from __future__ import annotations

import os
import socket
import sys
import threading
import time
from typing import Optional, Sequence

import numpy as np

# ONE wire mirror of csrc/ps/net.h for the whole Python control plane:
# ps.wire_constants owns the header structs, PsfType/ArgType values and
# every reply slot layout (bin/hetucheck asserts it against the C++
# headers); the supervisor owns the recv loop. This module reuses both
# rather than growing copies that could drift. MsgHeader is 32 bytes; the
# last i32 is the hetu-elastic world-version stamp (0 = unversioned).
from .ps import wire_constants as wire
from .ps.supervisor import (SchedulerUnreachable, _ARG_HDR, _MSG_HDR,
                            _recv_exact as _recv_exact_sock)

# PsfType values (net.h via wire_constants)
K_QUERY_SERVERS = wire.K_QUERY_SERVERS
K_SERVER_STATS = wire.K_SERVER_STATS
K_PARAM_SAVE = wire.K_PARAM_SAVE
K_PARAM_LOAD = wire.K_PARAM_LOAD
K_PROPOSE_RESIZE = wire.K_PROPOSE_RESIZE
K_RESIZE_STATE = wire.K_RESIZE_STATE
K_COMMIT_RESIZE = wire.K_COMMIT_RESIZE
K_FINISH_RESIZE = wire.K_FINISH_RESIZE
K_RESIZE_LOG = wire.K_RESIZE_LOG
K_LIST_PARAMS = wire.K_LIST_PARAMS
K_SET_WORLD_VERSION = wire.K_SET_WORLD_VERSION
K_SNAPSHOT_NOW = wire.K_SNAPSHOT_NOW

# ArgType values (net.h via wire_constants)
_AT_F32, _AT_I64, _AT_F64, _AT_BYTES, _AT_I32, _AT_U64 = (
    wire.AT_F32, wire.AT_I64, wire.AT_F64, wire.AT_BYTES, wire.AT_I32,
    wire.AT_U64)


def _arg_bytes(dtype: int, payload: bytes) -> bytes:
    return _ARG_HDR.pack(dtype, 0, len(payload)) + payload


def _arg_i32(vals) -> bytes:
    return _arg_bytes(_AT_I32, np.asarray(vals, np.int32).tobytes())


def _arg_i64(vals) -> bytes:
    return _arg_bytes(_AT_I64, np.asarray(vals, np.int64).tobytes())


def _arg_str(s: str) -> bytes:
    return _arg_bytes(_AT_BYTES, s.encode())


_recv_exact = _recv_exact_sock  # tests/tools address it under this name too


def _rpc(host: str, port: int, msg_type: int, args: Sequence[bytes] = (),
         timeout: Optional[float] = 5.0, who: str = "scheduler",
         tensor_id: int = 0):
    """One request/response round trip on a fresh connection. Returns
    ``(head, [arg_bytes, ...])``. ``tensor_id`` rides the header for the
    per-key param PSFs (save/load). An error response (flags == -1) raises
    RuntimeError with the server's message; transport failures raise
    :class:`SchedulerUnreachable` naming the address."""
    try:
        with socket.create_connection((host, int(port)),
                                      timeout=timeout) as s:
            s.settimeout(timeout)
            payload = _MSG_HDR.pack(msg_type, int(tensor_id), 0, len(args),
                                    0, -1, 0)
            s.sendall(payload + b"".join(args))
            head = _MSG_HDR.unpack(_recv_exact(s, _MSG_HDR.size))
            out = []
            for _ in range(head[3]):
                _, _, nbytes = _ARG_HDR.unpack(_recv_exact(s, _ARG_HDR.size))
                out.append(_recv_exact(s, int(nbytes)))
    except (socket.timeout, OSError) as e:
        raise SchedulerUnreachable(
            f"{who} at {host}:{port} unreachable ({e!r})") from e
    if head[4] == -1:  # flags == -1: application-level error response
        raise RuntimeError(
            f"{who} at {host}:{port}: "
            f"{out[0].decode(errors='replace') if out else 'error'}")
    return head, out


def _i64s(buf: bytes) -> np.ndarray:
    return np.frombuffer(buf, np.int64)


def _i32s(buf: bytes) -> np.ndarray:
    return np.frombuffer(buf, np.int32)


def _split_addr(addr: str):
    host, _, port = addr.rpartition(":")
    return host, int(port)


def sched_addr_from_env() -> tuple[str, int]:
    return (os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1"),
            int(os.environ.get("DMLC_PS_ROOT_PORT", "13200")))


# ---------------------------------------------------------------------------
# Scheduler control plane (the two-phase resize handshake)
# ---------------------------------------------------------------------------

def propose_resize(host, port, new_n_workers: int, new_n_servers: int,
                   removed: Sequence[int] = (),
                   removed_steps: Sequence[int] = ()) -> int:
    """Phase 1: record a pending resize with the scheduler and grow the
    registry's capacity so joining servers can register. Idempotent for an
    identical pending proposal; a conflicting one is an error. Returns the
    proposed world version.

    ``removed_steps[i]`` is removed rank ``removed[i]``'s last COMPLETED
    step (from its progress file / checkpoint cursor) — what makes the
    departed rank's unconsumed samples redistributable exactly once. -1 =
    unknown: the scheduler records the rank as having consumed its WHOLE
    chunk, so its unconsumed tail is LOST but nothing is ever trained
    twice — the honest at-most-once semantics when a rank crashes
    without a progress record."""
    args = [_arg_i32([int(new_n_workers), int(new_n_servers),
                      *map(int, removed)])]
    if removed:
        steps = list(removed_steps) + [-1] * (len(removed)
                                              - len(removed_steps))
        args.append(_arg_i64(steps))
    _, out = _rpc(host, port, K_PROPOSE_RESIZE, args)
    return int(_i64s(out[0])[0])


def resize_state(host, port, timeout: float = 5.0) -> dict:
    """Current world + pending-resize drain progress (coordinator's poll
    surface and the workers' cheap per-step pending check)."""
    _, out = _rpc(host, port, K_RESIZE_STATE, timeout=timeout)
    v = _i64s(out[0])
    members = _i32s(out[1]).tolist() if len(out) > 1 else []
    # slots 10+ (snapshot_epochs, pilot_*_epochs) are suffix extensions —
    # accept the 10-slot prefix a pre-hetusave scheduler replies with
    raw = wire.unpack_fields(wire.RESIZE_STATE_FIELDS[:10], v)
    state = {"world_version": raw["world_version"],
             "pending_version": raw["pending_version"],
             "n_workers": raw["num_workers"], "n_servers": raw["num_servers"],
             "pending_n_workers": raw["pending_nw"],
             "pending_n_servers": raw["pending_ns"],
             "drain_count": raw["drained"], "drain_needed": raw["survivors"],
             "new_servers_ready": bool(raw["new_servers_ready"]),
             "members": members}
    # era-counter suffix: completed coordinated-snapshot epochs (hetusave)
    # and commit/rollback-sealed actuation eras (hetupilot) this scheduler
    # incarnation — each advanced only by a matching tagged finish_resize
    # abort, so every counter attributes its eras to their cause
    for i, field in enumerate(wire.RESIZE_STATE_FIELDS[10:], start=10):
        if len(v) > i:
            state[field] = int(v[i])
    return state


def commit_resize(host, port, rank: int, step: int,
                  timeout: Optional[float] = 120.0) -> dict:
    """Drain barrier (BLOCKS): parks this worker with the scheduler until
    the coordinator finishes (or aborts) the pending resize, then returns
    the now-current world: version, counts, the member rank list, this
    worker's dp position among them, and the era's global start step."""
    _, out = _rpc(host, port, K_COMMIT_RESIZE,
                  [_arg_i32([1, int(rank)]), _arg_i64([int(step)])],
                  timeout=timeout)
    w = wire.unpack_fields(wire.WORLD_REPLY_FIELDS, _i64s(out[0]))
    return {"world_version": w["world_version"],
            "n_workers": w["num_workers"], "n_servers": w["num_servers"],
            "dp_rank": w["dp_rank"], "start_step": w["start_step"],
            "members": _i32s(out[1]).tolist() if len(out) > 1 else [],
            "book": out[2].decode() if len(out) > 2 else ""}


def finish_resize(host, port, abort: bool = False,
                  snapshot: bool = False, tag: Optional[str] = None) -> int:
    """Phase 2: atomically flip the world (or abort the pending proposal)
    and release every parked worker. Requires the drain barrier to be
    complete unless aborting. ``tag`` (an ``ACTUATION_TAGS`` name, with
    ``abort=True`` only) attributes the barrier era to its cause:
    ``"snapshot"`` — hetusave's success path, releasing a COMMITTED
    coordinated-snapshot epoch (``snapshot=True`` is the back-compat
    spelling) — advances the scheduler's monotonic ``snapshot_epochs``;
    ``"pilot_commit"`` / ``"pilot_rollback"`` — a hetupilot actuation era
    sealed with its verdict — advance ``pilot_commit_epochs`` /
    ``pilot_rollback_epochs``. Untagged aborts — drain timeouts, failed
    migrations, a snapshot or actuation that died before its outcome
    committed — never count. Returns the now-current world version."""
    if tag is None:
        tag = "snapshot" if snapshot else "none"
    tag_val = wire.ACTUATION_TAGS[tag]   # KeyError names a bad tag early
    _, out = _rpc(host, port, K_FINISH_RESIZE,
                  [_arg_i32([1 if abort else 0, tag_val])])
    return int(_i64s(out[0])[0])


def resize_log(host, port) -> list[dict]:
    """The committed world history: one era per row with PER-MEMBER step
    accounting — ``{version, n_workers, n_servers, members, start_steps,
    end_steps}``. ``start_steps[j]`` is member ``members[j]``'s global step
    when it entered the era (survivor: its drain-commit step; joiner: the
    era's assigned start; era 0: 0); ``end_steps[j]`` is its step when the
    era closed (-1 while the era is still open). Survivors may drain at
    DIFFERENT local steps — per-member bounds are what keep the
    exactly-once sample accounting honest (see :func:`era_partitions`).
    Era 0 is the launch world. This is also what lets a late-joining
    worker reconstruct exactly which samples every earlier era consumed."""
    _, out = _rpc(host, port, K_RESIZE_LOG)
    v = _i64s(out[0])
    eras, i = [], 0
    while i + 4 <= len(v):
        ver, nw, ns, nm = (int(v[i]), int(v[i + 1]), int(v[i + 2]),
                           int(v[i + 3]))
        rows = v[i + 4:i + 4 + 3 * nm].reshape(nm, 3)
        eras.append({"version": ver, "n_workers": nw, "n_servers": ns,
                     "members": [int(r[0]) for r in rows],
                     "start_steps": [int(r[1]) for r in rows],
                     "end_steps": [int(r[2]) for r in rows]})
        i += 4 + 3 * nm
    return eras


# ---------------------------------------------------------------------------
# Server control plane (key-range migration + stale-epoch arming)
# ---------------------------------------------------------------------------

def server_list_params(addr: str) -> list[dict]:
    """Param inventory of one server shard: key, kind (0 dense / 1 sparse /
    2 cache table), rows-or-len, width, optimizer type."""
    host, port = _split_addr(addr)
    _, out = _rpc(host, port, K_LIST_PARAMS, who=f"ps server {addr}")
    v = _i64s(out[0])
    stride = wire.LIST_PARAMS_STRIDE
    return [{"key": int(v[i]), "kind": int(v[i + 1]), "rows": int(v[i + 2]),
             "width": int(v[i + 3]), "otype": int(v[i + 4])}
            for i in range(0, len(v), stride)]


def server_param_save(addr: str, key: int, directory: str) -> None:
    """kParamSave for one key (the key rides in the header's tensor_id):
    the server writes ``param_<key>_shard<rank>.bin`` in v2 format under
    the param's shared lock — serving never pauses."""
    _rpc_with_tensor(addr, K_PARAM_SAVE, key, [_arg_str(directory)])


def server_param_load(addr: str, key: int, directory: str) -> None:
    """kParamLoad for one key: the server rebuilds the param (data +
    optimizer slots + row versions) from its rank's v2 shard file — the
    param need not pre-exist, which is exactly what lets a joining server
    come up empty and receive its key range."""
    _rpc_with_tensor(addr, K_PARAM_LOAD, key, [_arg_str(directory)])


def server_set_world(addr: str, version: int) -> None:
    """Arm (or advance) a server's stale-epoch rejection: requests stamped
    with a DIFFERENT non-zero world version are answered with an error
    response instead of being applied — the membership analogue of
    resend-dedup's duplicate rejection."""
    host, port = _split_addr(addr)
    _rpc(host, port, K_SET_WORLD_VERSION, [_arg_i64([int(version)])],
         who=f"ps server {addr}")


def server_stats_raw(addr: str, timeout: float = 3.0) -> list[int]:
    """kServerStats over a raw socket (no native lib): the HA/health
    slots in ``wire_constants.SERVER_STATS_FIELDS`` order. The jax-free
    twin of ``PSClient.ServerStats`` for supervisor-side scale
    policies."""
    host, port = _split_addr(addr)
    _, out = _rpc(host, port, K_SERVER_STATS, timeout=timeout,
                  who=f"ps server {addr}")
    return [int(x) for x in _i64s(out[0])]


def server_snapshot_now(addr: str, epoch: int = -1,
                        timeout: float = 60.0) -> dict:
    """kSnapshotNow over a raw socket (no native lib): drive one PS
    server's epoch-stamped full-state snapshot and return
    {version, counter, updates, epoch}. Synchronous — the snapshot dir is
    published and its LATEST pointer flipped before the reply. The
    jax-free twin of ``PSClient.SnapshotNow`` for coordinator tooling
    (bin/hetusave) that must not import jax."""
    host, port = _split_addr(addr)
    _, out = _rpc(host, port, K_SNAPSHOT_NOW, [_arg_i64([int(epoch)])],
                  timeout=timeout, who=f"ps server {addr}")
    return wire.unpack_fields(wire.SNAPSHOT_NOW_FIELDS, _i64s(out[0]))


def _rpc_with_tensor(addr: str, msg_type: int, tensor_id: int,
                     args: Sequence[bytes], timeout: float = 30.0):
    """Per-key param PSF (save/load) to one server: _rpc with the key in
    the header's tensor_id slot."""
    host, port = _split_addr(addr)
    return _rpc(host, port, msg_type, args, timeout=timeout,
                who=f"ps server {addr}", tensor_id=tensor_id)


# ---------------------------------------------------------------------------
# v2 shard format IO (csrc/ps/server.h save_param_file / load_param_file)
# ---------------------------------------------------------------------------

_SHARD_MAGIC_V2 = wire.SHARD_MAGIC_V2
# accum/accum2 sizing per OptType (store.h alloc_slots): sgd none,
# momentum/nesterov/adagrad one slot, adam two
_SLOT_COUNTS = wire.OPT_SLOT_COUNTS


def read_v2_shard(path: str) -> dict:
    """Parse one v2 shard file into numpy arrays. Layout: i64 meta[8] =
    {MAGIC(-2), kind, rows|len, width, otype, step, n_lrs, n_versions},
    f32 lrs[], f32 data[], f32 accum[], f32 accum2[], i64 versions[]."""
    with open(path, "rb") as f:
        meta = np.fromfile(f, np.int64, wire.SHARD_META_LEN)
        if meta.size != wire.SHARD_META_LEN or meta[0] != _SHARD_MAGIC_V2:
            raise ValueError(f"{path}: not a v2 shard file")
        kind, n0, width, otype, step, n_lrs, n_ver = (
            int(meta[1]), int(meta[2]), int(meta[3]), int(meta[4]),
            int(meta[5]), int(meta[6]), int(meta[7]))
        length = n0 if kind == 0 else n0 * width
        lrs = np.fromfile(f, np.float32, n_lrs)
        data = np.fromfile(f, np.float32, length)
        nslots = _SLOT_COUNTS.get(otype, 0)
        accum = np.fromfile(f, np.float32, length if nslots >= 1 else 0)
        accum2 = np.fromfile(f, np.float32, length if nslots >= 2 else 0)
        versions = np.fromfile(f, np.int64, n_ver)
    # validate EVERY section, not just data: np.fromfile short-reads
    # silently, and a shard truncated inside accum/accum2/versions would
    # otherwise re-split into shards whose meta disagrees with their
    # payload — exactly the silent corruption migration must fail loud on
    for name, arr, want in (("data", data, length),
                            ("lrs", lrs, n_lrs),
                            ("accum", accum,
                             length if nslots >= 1 else 0),
                            ("accum2", accum2,
                             length if nslots >= 2 else 0),
                            ("versions", versions, n_ver)):
        if arr.size != want:
            raise ValueError(f"{path}: truncated shard ({name} carries "
                             f"{arr.size}/{want} entries)")
    return {"kind": kind, "rows": 0 if kind == 0 else n0,
            "len": length, "width": width if kind != 0 else 1,
            "otype": otype, "step": step, "lrs": lrs, "data": data,
            "accum": accum, "accum2": accum2, "versions": versions}


def write_v2_shard(path: str, d: dict) -> None:
    """Inverse of :func:`read_v2_shard` (bit-compatible with the server's
    load_param_file)."""
    n0 = d["rows"] if d["kind"] != 0 else d["len"]
    meta = np.asarray([_SHARD_MAGIC_V2, d["kind"], n0,
                       d["width"] if d["kind"] != 0 else 1, d["otype"],
                       d.get("step", 0), len(d["lrs"]),
                       len(d["versions"])], np.int64)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        meta.tofile(f)
        np.asarray(d["lrs"], np.float32).tofile(f)
        np.asarray(d["data"], np.float32).tofile(f)
        np.asarray(d["accum"], np.float32).tofile(f)
        np.asarray(d["accum2"], np.float32).tofile(f)
        np.asarray(d["versions"], np.int64).tofile(f)
    os.replace(tmp, path)


def _range_split(total: int, n_shards: int) -> list[tuple[int, int]]:
    """The worker partitioner's exact split (worker.h dense_range /
    row_range): shard s covers [s*total/S, (s+1)*total/S)."""
    return [(s * total // n_shards, (s + 1) * total // n_shards)
            for s in range(n_shards)]


def repartition_key(shards: list[dict], new_n: int) -> list[dict]:
    """Re-split one param's old shard set (server order) into ``new_n``
    shards under the same partitioner formula. Rows move WITH their
    optimizer slots and version counters — a migrated Adam row keeps its
    m/v state bit-for-bit, so training dynamics are unchanged by the
    move."""
    first = shards[0]
    kind, width, otype = first["kind"], first["width"], first["otype"]
    data = np.concatenate([s["data"] for s in shards])
    accum = np.concatenate([s["accum"] for s in shards])
    accum2 = np.concatenate([s["accum2"] for s in shards])
    versions = np.concatenate([s["versions"] for s in shards])
    step = max(int(s.get("step", 0)) for s in shards)
    lrs = first["lrs"]
    if kind == 0:
        total = int(data.size)
        ranges = [(lo, hi) for lo, hi in _range_split(total, new_n)]
        unit = 1
    else:
        total = sum(int(s["rows"]) for s in shards)
        ranges = _range_split(total, new_n)
        unit = width
    out = []
    for lo, hi in ranges:
        sl = slice(lo * unit, hi * unit)
        out.append({"kind": kind, "rows": 0 if kind == 0 else hi - lo,
                    "len": (hi - lo) * unit, "width": width,
                    "otype": otype, "step": step, "lrs": lrs,
                    "data": data[sl],
                    "accum": accum[sl] if accum.size else accum,
                    "accum2": accum2[sl] if accum2.size else accum2,
                    "versions": versions[lo:hi] if versions.size
                    else versions})
    return out


def migrate_key_ranges(server_addrs: list[str], old_n: int, new_n: int,
                       workdir: str, log=None) -> dict:
    """Move PS state from ``old_n`` to ``new_n`` key-range shards using the
    v2 shard format as the transfer medium. MUST run inside the drain
    window (workers parked in ``kCommitResize``): donors save under the
    per-param shared locks (serving never pauses), rows+slots+versions are
    re-split host-side, and every new-world server loads its new shard.

    Returns an accounting report: per-key element counts and the summed
    server update counters before/after (equal for a clean migration —
    the exact "zero lost updates" proof)."""
    log = log or (lambda m: print(f"# hetu elastic: {m}", file=sys.stderr,
                                  flush=True))
    stage = os.path.join(workdir, "stage")
    commit = os.path.join(workdir, "commit")
    os.makedirs(stage, exist_ok=True)
    os.makedirs(commit, exist_ok=True)
    params = server_list_params(server_addrs[0])
    updates_before = sum(server_stats_raw(a)[0]
                        for a in server_addrs[:old_n])
    # donors stream their shards (tmp+rename server-side; shared locks)
    for key in (p["key"] for p in params):
        for s in range(old_n):
            server_param_save(server_addrs[s], key, stage)
    # per-donor inventories: kListParams reports each server's SHARD meta,
    # so staged shards verify against their own donor, not donor 0
    inventories = [
        {q["key"]: q for q in server_list_params(server_addrs[s])}
        for s in range(old_n)]
    report_keys = {}
    for p in params:
        key = p["key"]
        shards = [read_v2_shard(os.path.join(
            stage, f"param_{key}_shard{s}.bin")) for s in range(old_n)]
        # every staged shard must match its donor's live inventory: a
        # mismatch means the stage holds something other than this world's
        # param (torn write, stale file, racing membership change) — fail
        # LOUD here so the coordinator aborts with state untouched,
        # instead of loading a silently-corrupted split
        for s, sh in enumerate(shards):
            inv = inventories[s].get(key)
            got = sh["rows"] if sh["kind"] != 0 else sh["len"]
            want = None if inv is None else inv["rows"]
            if want != got:
                raise RuntimeError(
                    f"migration staging mismatch for param {key} shard "
                    f"{s}: staged file carries {got} rows/elements, the "
                    f"donor's inventory says {want} — aborting the resize")
        new_shards = repartition_key(shards, new_n)
        for s, sh in enumerate(new_shards):
            write_v2_shard(os.path.join(
                commit, f"param_{key}_shard{s}.bin"), sh)
        report_keys[key] = {"elements": int(sum(s["data"].size
                                                for s in shards)),
                            "kind": p["kind"]}
    keys = [p["key"] for p in params]
    # JOINING servers load first: a failure here aborts with every donor
    # still holding its full old-world shard — the abort is truly safe
    for key in keys:
        for s in range(old_n, new_n):
            server_param_load(server_addrs[s], key, commit)
    # donors last, with rollback: once a donor holds a re-split shard the
    # OLD world's key ranges no longer match it, so a mid-loop failure
    # reloads every touched donor from the stage dir (which IS the exact
    # pre-migration state) before the coordinator aborts
    attempted = 0
    try:
        for key in keys:
            attempted += 1
            for s in range(old_n):
                server_param_load(server_addrs[s], key, commit)
    except Exception:
        rollback_failed = []
        for key in keys[:attempted]:
            for s in range(old_n):
                try:
                    server_param_load(server_addrs[s], key, stage)
                except Exception:  # noqa: BLE001
                    rollback_failed.append((key, s))
        if rollback_failed:
            raise RuntimeError(
                "migration failed AND donor rollback failed for "
                f"{rollback_failed} — old-world PS state is inconsistent; "
                f"restore donors manually from {stage} (v2 shard files) "
                "before resuming") from None
        log(f"donor load failed; rolled {attempted} key(s) back from "
            f"{stage} — old world intact")
        raise
    updates_after = sum(server_stats_raw(a)[0]
                       for a in server_addrs[:old_n])
    log(f"migrated {len(report_keys)} param(s) {old_n} -> {new_n} shards; "
        f"update counters {updates_before} -> {updates_after}")
    return {"keys": report_keys, "n_keys": len(report_keys),
            "updates_before": int(updates_before),
            "updates_after": int(updates_after)}


# ---------------------------------------------------------------------------
# Exact-once dataloader accounting across resizes
# ---------------------------------------------------------------------------

def _chunk_bounds(n: int, m: int, batch_size: int) -> list[tuple[int, int]]:
    """Contiguous per-member chunk bounds over ``n`` remaining samples:
    whole batches distributed as evenly as possible (first ``nb % m``
    members get one extra batch). Splitting on raw ``n // m`` instead
    would strand up to ``m * batch_size`` samples per resize behind
    drop_last — found live by the demo's exact-accounting check."""
    nb = n // batch_size
    base, extra = divmod(nb, m)
    bounds, lo = [], 0
    for j in range(m):
        hi = lo + (base + (1 if j < extra else 0)) * batch_size
        bounds.append((lo, hi))
        lo = hi
    return bounds


def _era_bounds(n: int, m: int, batch_size: int,
                first_era: bool) -> list[tuple[int, int]]:
    """Per-member chunk bounds for one era. The LAUNCH era must model what
    ``Dataloader.init_states`` actually did — a plain ``n // nrank`` split
    (not batch-aligned) — while post-resize eras use the batch-aligned
    bounds ``load_elastic_partition`` was handed. Mixing the formulas on a
    non-divisible dataset would double-consume the straddle samples."""
    if first_era:
        per = n // m
        return [(j * per, (j + 1) * per) for j in range(m)]
    return _chunk_bounds(n, m, batch_size)


def era_partitions(n_samples: int, batch_size: int, eras: list[dict]):
    """Partition the samples a sequence of worlds has NOT yet consumed.

    ``eras`` is the scheduler's resize log: within a closed era, member j
    consumed the first ``(end_steps[j] - start_steps[j]) * batch_size``
    entries of its contiguous chunk — exactly what a sequential
    (no-shuffle, drop_last) Dataloader does, with PER-MEMBER bounds
    because survivors drain at different local steps. Returns
    ``(per_member_chunks, unassigned_tail)`` for the LAST (open) era, or
    ``None`` when any era wrapped its epoch (consumption is no longer a
    prefix and exact-once no longer holds; callers fall back to plain
    rank re-sharding).

    The union of what every closed era consumed, the returned chunks, and
    the tail is exactly ``arange(n_samples)`` with no overlaps — the
    exactly-once invariant ``tests/test_elastic.py`` pins.
    """
    remaining = np.arange(n_samples, dtype=np.int64)
    for i, era in enumerate(eras[:-1]):
        m = len(era["members"])
        if m <= 0:
            return None
        bounds = _era_bounds(remaining.size, m, batch_size, i == 0)
        keep = []
        for j, (lo, hi) in enumerate(bounds):
            end = int(era["end_steps"][j])
            if end == -2:
                # unknown progress (scheduler sentinel): assume the whole
                # chunk was consumed — its tail is LOST, never re-applied
                k = hi - lo
            else:
                k = max(0, end - int(era["start_steps"][j])) \
                    * int(batch_size)
            if k > hi - lo:
                return None  # epoch wrapped inside this era
            keep.append(remaining[lo + k:hi])
        keep.append(remaining[bounds[-1][1]:])  # sub-batch tail rides along
        remaining = np.concatenate(keep)
    m = len(eras[-1]["members"])
    if m <= 0:
        return None
    bounds = _era_bounds(remaining.size, m, batch_size, len(eras) == 1)
    return ([remaining[lo:hi] for lo, hi in bounds],
            remaining[bounds[-1][1]:])


def consumed_samples(n_samples: int, batch_size: int, eras: list[dict],
                     final_steps: dict):
    """The set of sample indices consumed by ALL members across every era
    (the closed-form companion of :func:`era_partitions`; tests state the
    exactly-once oracle with it). ``final_steps`` maps each LAST-era
    member rank to its final global step."""
    closed = [dict(e) for e in eras]
    closed[-1] = dict(closed[-1], end_steps=[
        int(final_steps[r]) for r in closed[-1]["members"]])
    out = []
    remaining = np.arange(n_samples, dtype=np.int64)
    for i, era in enumerate(closed):
        m = len(era["members"])
        bounds = _era_bounds(remaining.size, m, batch_size, i == 0)
        keep = []
        for j, (lo, hi) in enumerate(bounds):
            end = int(era["end_steps"][j])
            if end == -2:
                k = hi - lo
            else:
                k = max(0, end - int(era["start_steps"][j])) \
                    * int(batch_size)
            if k > hi - lo:
                return None
            out.append(remaining[lo:lo + k])
            keep.append(remaining[lo + k:hi])
        keep.append(remaining[bounds[-1][1]:])
        remaining = np.concatenate(keep)
    return np.concatenate(out) if out else np.empty(0, np.int64)


# ---------------------------------------------------------------------------
# Coordinator (launcher parent / test harness side)
# ---------------------------------------------------------------------------

class ElasticCoordinator:
    """Drives one membership change end to end against the scheduler:
    propose → (spawn joining servers so they can register) → wait for the
    drain barrier + server registration → migrate PS key-ranges if the
    server count changed → finish → (spawn joining workers). The caller
    owns process management via the ``spawn_*`` callbacks — the same class
    serves ``heturun --elastic``, the ``ps_join`` fault kind, and tests."""

    def __init__(self, sched_host: str, sched_port: int,
                 workdir: Optional[str] = None, log=None,
                 drain_timeout_s: float = 120.0):
        self.host, self.port = sched_host, int(sched_port)
        self.workdir = workdir
        self.drain_timeout_s = float(drain_timeout_s)
        self.log = log or (lambda m: print(f"# hetu elastic: {m}",
                                           file=sys.stderr, flush=True))
        self.last_report: Optional[dict] = None

    def resize(self, new_n_workers: int, new_n_servers: int,
               removed: Sequence[int] = (), removed_steps: Sequence[int] = (),
               spawn_server=None, spawn_worker=None) -> dict:
        t0 = time.perf_counter()
        st0 = resize_state(self.host, self.port)
        old_ns = st0["n_servers"]
        version = propose_resize(self.host, self.port, new_n_workers,
                                 new_n_servers, removed, removed_steps)
        self.log(f"resize proposed: world v{version} "
                 f"({st0['n_workers']}w/{old_ns}s -> "
                 f"{new_n_workers}w/{new_n_servers}s, removed "
                 f"{list(removed)})")
        new_server_ids = list(range(old_ns, new_n_servers))
        if spawn_server is not None:
            for sid in new_server_ids:
                spawn_server(sid)
        deadline = time.monotonic() + self.drain_timeout_s
        while True:
            st = resize_state(self.host, self.port)
            if st["drain_count"] >= st["drain_needed"] \
                    and st["new_servers_ready"]:
                break
            if time.monotonic() > deadline:
                finish_resize(self.host, self.port, abort=True)
                raise TimeoutError(
                    f"resize v{version} drain timed out "
                    f"({st['drain_count']}/{st['drain_needed']} workers "
                    f"drained, servers_ready={st['new_servers_ready']}) — "
                    "aborted; the old world continues")
            time.sleep(0.05)
        migration = None
        try:
            if new_n_servers != old_ns:
                import tempfile
                workdir = self.workdir or tempfile.mkdtemp(
                    prefix="hetu_elastic_migr_")
                addrs, _ = _query_book(self.host, self.port)
                migration = migrate_key_ranges(addrs, old_ns, new_n_servers,
                                               workdir, log=self.log)
            # arm stale-epoch rejection under the NEW version everywhere
            addrs, _ = _query_book(self.host, self.port)
            for a in addrs[:new_n_servers]:
                if a:
                    server_set_world(a, version)
            finish_resize(self.host, self.port)
        except Exception:
            # Abort: release the parked workers under the OLD world rather
            # than leaving them waiting forever and the proposal wedged.
            # ORDER MATTERS: servers already armed with the NEW version
            # must be re-armed to the old epoch BEFORE the workers are
            # released — a released worker's first push to a new-armed
            # server is a no-retry stale-epoch error, crashing the
            # survivor the abort exists to protect. Old-world PS state is
            # intact: migrate_key_ranges loads joining servers first and
            # rolls donors back from the stage dir on a donor-load
            # failure. Best-effort throughout; if finish itself
            # half-landed, the abort answers "no resize is pending" —
            # fine, the workers are already released.
            try:
                addrs, _ = _query_book(self.host, self.port)
                for a in addrs:
                    if a:
                        server_set_world(a, st0["world_version"])
            except Exception:  # noqa: BLE001
                pass
            try:
                finish_resize(self.host, self.port, abort=True)
            except Exception:  # noqa: BLE001
                pass
            raise
        new_worker_ranks = []
        st = resize_state(self.host, self.port)
        if spawn_worker is not None:
            prev = set(st0["members"]) - set(removed)
            new_worker_ranks = [r for r in st["members"] if r not in prev]
            for r in new_worker_ranks:
                spawn_worker(r)
        dur_ms = (time.perf_counter() - t0) * 1e3
        self.last_report = {
            "world_version": version, "duration_ms": round(dur_ms, 1),
            "n_workers": new_n_workers, "n_servers": new_n_servers,
            "members": st["members"], "removed": list(removed),
            "joined_workers": new_worker_ranks, "migration": migration}
        self.log(f"resize v{version} committed in {dur_ms:.0f} ms; "
                 f"members {st['members']}")
        return self.last_report


def _query_book(host, port):
    """kQueryServers: (addrs, alive) — the supervisor's implementation,
    re-exported under the name the coordinator/tests use."""
    from .ps.supervisor import query_servers
    return query_servers(host, port, timeout=5.0)


# ---------------------------------------------------------------------------
# Worker-side agent (step-boundary hook)
# ---------------------------------------------------------------------------

class ElasticAgent:
    """Per-worker elastic membership agent, armed by ``HETU_ELASTIC``.

    Checked at every training-step boundary (``SubExecutor.run`` calls
    :meth:`step_boundary`): when the scheduler has a pending resize the
    agent drains this worker's PS traffic, parks in the drain barrier
    (``kCommitResize``), and on release applies the new world — native
    world-version stamp, server-connection refresh (the partitioner
    denominator), exact-once dataloader re-partition, telemetry gauges and
    a flight-recorder event. Costs one small scheduler round trip every
    ``poll_steps`` steps when idle."""

    def __init__(self, executor, sched_host: str, sched_port: int,
                 rank: int, poll_steps: Optional[int] = None):
        self.ex = executor
        self.host, self.port = sched_host, int(sched_port)
        self.rank = int(rank)
        self.poll_steps = max(1, int(
            poll_steps if poll_steps is not None
            else os.environ.get("HETU_ELASTIC_POLL_STEPS", "1")))
        self.world_version = 1
        self.dp_rank = self.rank
        self.n_members = 1
        self.eras: list[dict] = []
        self.last_resize_ms: Optional[float] = None
        self.resizes = 0
        # progress file: the launcher reads a dead rank's last completed
        # step from here (propose_resize removed_steps) so its unconsumed
        # samples can be redistributed exactly once
        d = os.environ.get("HETU_ELASTIC_DIR")
        self._progress_path = (os.path.join(d, f"progress_r{self.rank}")
                               if d else None)

    @classmethod
    def from_env(cls, executor) -> "ElasticAgent":
        host, port = sched_addr_from_env()
        return cls(executor, host, port,
                   int(os.environ.get("WORKER_ID", "0")))

    # -- lifecycle ---------------------------------------------------------
    def bootstrap(self) -> None:
        """Sync with the scheduler's current world at executor build time.
        A late-joining worker (``HETU_ELASTIC_JOIN``) additionally aligns
        its step counter with the era's global start step and loads its
        exact-once dataloader partition from the world log."""
        try:
            eras = resize_log(self.host, self.port)
        except SchedulerUnreachable as e:
            print(f"# hetu elastic: bootstrap skipped ({e})",
                  file=sys.stderr)
            return
        if not eras:
            return
        self.eras = eras
        cur = eras[-1]
        self.world_version = cur["version"]
        self.n_members = len(cur["members"])
        self.dp_rank = (cur["members"].index(self.rank)
                        if self.rank in cur["members"] else self.rank)
        comm = getattr(self.ex.ps_runtime, "comm", None) \
            if self.ex is not None else None
        if comm is not None and hasattr(comm, "SetWorldVersion"):
            comm.SetWorldVersion(self.world_version)
        if self.ex is not None and os.environ.get("HETU_ELASTIC_JOIN") \
                and self.rank in cur["members"]:
            # joiner: my batches count from my assigned era start step
            self.ex.state["step"] = int(
                cur["start_steps"][cur["members"].index(self.rank)])
            self._repartition_dataloaders(cur)
        self._export(None)

    # -- the per-step hook --------------------------------------------------
    def write_progress(self, completed_steps: int) -> None:
        """Record this rank's completed-step count (= batches consumed)
        for the launcher's departure accounting. Called at every step
        boundary AND by the worker_lost fault right before the SIGKILL, so
        a planned departure's tail is redistributed exactly."""
        if not self._progress_path:
            return
        try:
            tmp = self._progress_path + ".tmp"
            with open(tmp, "w") as f:
                f.write(str(int(completed_steps)))
            os.replace(tmp, self._progress_path)
        except OSError:
            pass  # progress is advisory; never take training down

    def step_boundary(self, sub, step: int) -> None:
        # progress EVERY boundary (a couple of µs): the poll cadence only
        # throttles the scheduler round trip — a stale progress file would
        # make a real preemption double-consume the dead rank's tail
        self.write_progress(step)
        if step % self.poll_steps:
            return
        try:
            st = resize_state(self.host, self.port, timeout=3.0)
        except SchedulerUnreachable as e:
            print(f"# hetu elastic: membership poll failed ({e}); "
                  "continuing under the current world", file=sys.stderr)
            return
        if st["pending_version"] == 0 \
                or st["pending_version"] <= self.world_version:
            return
        self._do_resize(sub, step, st)

    def _do_resize(self, sub, step: int, st: dict) -> None:
        from .resilience import _tel_event
        t0 = time.perf_counter()
        ps = self.ex.ps_runtime if self.ex is not None else None
        if ps is not None:
            ps.drain()                 # every in-flight push/pull lands
            ps._prefetched.clear()     # row locations may move
        _tel_event("resize_drain", step=step,
                   pending_version=st["pending_version"])
        # the park can legitimately outlast a socket timeout (a large
        # key-range migration runs while we wait), so the deadline is
        # generous and a timeout RETRIES the commit: re-parking just
        # overwrites our drain record, and if the resize finished while we
        # were disconnected the retry returns the new world immediately
        commit_timeout = float(os.environ.get(
            "HETU_ELASTIC_COMMIT_TIMEOUT_S", "600"))
        world = None
        for attempt in range(3):
            try:
                world = commit_resize(self.host, self.port, self.rank,
                                      step, timeout=commit_timeout)
                break
            except SchedulerUnreachable as e:
                print(f"# hetu elastic: drain commit attempt "
                      f"{attempt + 1}/3 failed ({e}); retrying",
                      file=sys.stderr)
        if world is None:
            # scheduler gone: keep training under the current world — the
            # next boundary re-polls, and a later-committed resize is
            # caught by the servers' stale-epoch rejection
            print(f"# hetu elastic: worker {self.rank} could not commit "
                  "the resize; continuing under the current world",
                  file=sys.stderr)
            return
        if world["world_version"] <= self.world_version:
            # the coordinator ABORTED (drain timeout, failed migration):
            # the old world continues — applying anything here would reset
            # cursors and re-consume already-trained samples
            print(f"# hetu elastic: worker {self.rank} released from an "
                  f"aborted resize; world v{self.world_version} continues",
                  file=sys.stderr)
            _tel_event("resize_abort", step=step,
                       world_version=self.world_version)
            return
        if world["members"] and self.rank not in world["members"]:
            # this rank was DECOMMISSIONED by the resize (an unnamed
            # shrink dropped it): its samples were redistributed to the
            # survivors, so continuing to train would double-consume them
            # under a perfectly valid epoch stamp. Leave like a preempted
            # host — supervise() turns this into a clean exit-75, a bare
            # loop exits nonzero, and either way the launcher records a
            # departure that is already accounted for.
            from .resilience import Preempted
            print(f"# hetu elastic: worker {self.rank} is not a member of "
                  f"world v{world['world_version']}; decommissioned — "
                  "stopping", file=sys.stderr)
            _tel_event("resize_decommissioned", flush=True, step=step,
                       world_version=world["world_version"])
            raise Preempted(step)
        comm = getattr(ps, "comm", None)
        if comm is not None and hasattr(comm, "SetWorldVersion"):
            comm.SetWorldVersion(world["world_version"])
        if comm is not None and hasattr(comm, "RefreshServers") \
                and world["n_servers"] != comm.num_servers:
            n = comm.RefreshServers()
            print(f"# hetu elastic: worker {self.rank} now sees {n} "
                  "server shard(s)", file=sys.stderr)
        # the scheduler's log is the one authoritative era history (it
        # merged every survivor's drain step and the removed ranks'
        # progress) — re-fetch it rather than reconstructing locally
        eras = None
        for _ in range(3):  # it answered the commit moments ago; retry
            try:
                eras = resize_log(self.host, self.port)
                break
            except SchedulerUnreachable:
                time.sleep(0.2)
        if eras:
            self.eras = eras
            self._repartition_dataloaders(self.eras[-1])
        else:
            # WITHOUT the log there is no exact remaining-sample set, and
            # resetting loaders (init_states) would replay consumed
            # batches — keep the current partitions and say so loudly;
            # the sample accounting degrades to at-most-once for the
            # redistributed tails until the next successful resize
            print(f"# hetu elastic: worker {self.rank} could not fetch "
                  "the world log after the commit; dataloader partitions "
                  "left unchanged (exact-once redistribution skipped)",
                  file=sys.stderr)
        self.world_version = world["world_version"]
        self.n_members = len(world["members"])
        self.dp_rank = world["dp_rank"] if world["dp_rank"] >= 0 \
            else self.rank
        self.resizes += 1
        self.last_resize_ms = (time.perf_counter() - t0) * 1e3
        self._export(sub)
        _tel_event("resize_commit", step=step,
                   world_version=self.world_version,
                   n_workers=world["n_workers"],
                   n_servers=world["n_servers"],
                   dp_rank=self.dp_rank,
                   duration_ms=round(self.last_resize_ms, 1))
        intro = getattr(self.ex, "introspector", None) \
            if self.ex is not None else None
        if intro is not None:
            # the resize shows up in the flight ring so hetuscope
            # post-mortems carry the membership timeline — and the ring is
            # flushed NOW: a membership change is exactly the kind of
            # boundary a later post-mortem wants on disk, and the next
            # abort-path flush would overwrite context otherwise
            intro.record_step({"sub": getattr(sub, "name", "elastic"),
                               "step": int(step), "event": "resize",
                               "world_version": self.world_version,
                               "members": world["members"],
                               "n_servers": world["n_servers"],
                               "duration_ms": round(self.last_resize_ms,
                                                    1)})
            try:
                from .telemetry.scope import flush_flight
                flush_flight("resize")
            except Exception:  # noqa: BLE001 — observability only
                pass
        print(f"# hetu elastic: worker {self.rank} joined world "
              f"v{self.world_version} as dp rank {self.dp_rank}/"
              f"{self.n_members} in {self.last_resize_ms:.0f} ms",
              file=sys.stderr)

    # -- dataloader re-partition -------------------------------------------
    def _repartition_dataloaders(self, era: dict) -> None:
        if self.ex is None:
            return
        pos = (era["members"].index(self.rank)
               if self.rank in era["members"] else None)
        if pos is None:
            return
        m = len(era["members"])
        for sub in self.ex.subexecutors.values():
            for node in getattr(sub, "dataloader_nodes", []):
                for dl in getattr(node, "dataloaders", {}).values():
                    self._repartition_one(dl, pos, m)
                # device-RESIDENT datasets slice on device from an uploaded
                # copy — re-upload the new partition and reset the traced
                # cursor, or the step would keep slicing the pre-resize
                # data (jit retraces on the new data shape by itself)
                nid = id(node)
                if nid in getattr(sub, "resident_dl", {}):
                    dl = node.dataloaders.get(sub.name)
                    if dl is not None:
                        sub.resident_dl[nid] = (
                            self.ex._prepare_input(dl._data, batch=False),
                            dl.batch_size, dl.batch_num)
                        sub._dl_cursor[nid] = 0

    def _repartition_one(self, dl, pos: int, m: int) -> None:
        if not hasattr(dl, "load_elastic_partition"):
            return
        plan = None
        if not dl.shuffle and dl.func is None and dl.drop_last \
                and len(self.eras) > 0:
            plan = era_partitions(int(dl.raw_data.shape[0]),
                                  int(dl.batch_size), self.eras)
        if plan is not None:
            chunks, _tail = plan
            dl.load_elastic_partition(chunks[pos])
        else:
            # shuffled/transformed loaders (or a wrapped epoch): exact-once
            # prefix accounting does not apply — fall back to plain rank
            # re-sharding, same semantics as a restart at this boundary
            dl.init_states(pos, m)

    # -- telemetry ----------------------------------------------------------
    def _export(self, sub) -> None:
        from . import telemetry as _telemetry
        tel = _telemetry.get()
        if tel is None:
            return
        g = tel.metrics.gauge
        g("hetu_world_version").set(float(self.world_version))
        g("hetu_world_workers").set(float(self.n_members))
        g("hetu_world_servers").set(float(
            self.eras[-1]["n_servers"] if self.eras else 1))
        g("hetu_resizes_total").set(float(self.resizes))
        if self.last_resize_ms is not None:
            g("hetu_resize_duration_ms").set(
                round(self.last_resize_ms, 2))


# ---------------------------------------------------------------------------
# local_cluster grow (the ps_join fault kind's executor)
# ---------------------------------------------------------------------------

def grow_local_cluster_server() -> threading.Thread:
    """Add one PS server to THIS process's live ``local_cluster`` and run
    the coordinator in a daemon thread (the worker side of the handshake
    runs in the training loop's :class:`ElasticAgent`, so the coordinator
    must not block it). Drives the ``ps_join@step`` fault kind."""
    from .ps.local_cluster import get_live_cluster, spawn_light_server
    live = get_live_cluster()
    if not live:
        raise RuntimeError("ps_join: no live local_cluster in this process")
    port = live["port"]
    st = resize_state("127.0.0.1", port)
    old_ns = st["n_servers"]
    new_ns = old_ns + 1

    def spawn(sid: int):
        base = dict(live.get("base_env") or {})
        base["DMLC_NUM_SERVER"] = str(new_ns)
        p = spawn_light_server(sid, base, live["stopfile"])
        live["servers"][sid] = p
        live.setdefault("procs", []).append(p)
        live["n_servers"] = new_ns

    coord = ElasticCoordinator("127.0.0.1", port)

    def run():
        try:
            coord.resize(st["n_workers"], new_ns, spawn_server=spawn)
        except Exception as e:  # noqa: BLE001 — surfaced via stderr; the
            # training loop would otherwise hang parked in the drain
            # barrier with no diagnosis
            print(f"# hetu elastic: ps_join grow failed: {e!r}",
                  file=sys.stderr, flush=True)

    t = threading.Thread(target=run, name="hetu-elastic-grow", daemon=True)
    t.start()
    return t


# ---------------------------------------------------------------------------
# Scale policy (telemetry-driven resize decisions for PSSupervisor)
# ---------------------------------------------------------------------------

class ScalePolicy:
    """Decide when the PS tier should grow, from the same kServerStats
    rows the telemetry poll reads: sustained apply-latency pressure or
    request-queue growth across ``sustain`` consecutive supervisor polls
    recommends one more server (bounded by ``max_servers``). Deliberately
    conservative — it recommends, the operator's ``on_scale`` hook (or
    ``heturun --elastic``) acts."""

    def __init__(self, max_servers: int, apply_ms_hi: float = 5.0,
                 req_rate_hi: float = 2000.0, sustain: int = 3,
                 cooldown_s: float = 30.0):
        self.max_servers = int(max_servers)
        self.apply_ms_hi = float(apply_ms_hi)
        self.req_rate_hi = float(req_rate_hi)
        self.sustain = max(1, int(sustain))
        self.cooldown_s = float(cooldown_s)
        self._hot_polls = 0
        self._last = None  # (t, per-server [requests, apply_ns, applies])
        self._last_decision_t = 0.0
        self.stragglers_seen = 0   # hetutrail events fed via note_straggler
        # observe() runs on the PS supervisor's poll thread while
        # note_straggler() arrives from the launcher's reap loop — the
        # shared cooldown state must not double-recommend for one episode
        self._lock = threading.Lock()

    def observe(self, stats_rows: list[list[int]],
                now: Optional[float] = None) -> Optional[dict]:
        now = time.monotonic() if now is None else now
        with self._lock:
            cur = [(r[5], r[6], r[7]) for r in stats_rows if len(r) >= 8]
            prev, self._last = self._last, (now, cur)
            if not cur or prev is None or len(prev[1]) != len(cur):
                self._hot_polls = 0
                return None
            dt = max(1e-6, now - prev[0])
            hot = False
            for (req0, ns0, ap0), (req1, ns1, ap1) in zip(prev[1], cur):
                d_ap = ap1 - ap0
                if d_ap > 0 and (ns1 - ns0) / d_ap / 1e6 > self.apply_ms_hi:
                    hot = True
                if (req1 - req0) / dt > self.req_rate_hi:
                    hot = True
            self._hot_polls = self._hot_polls + 1 if hot else 0
            if self._hot_polls < self.sustain:
                return None
            if len(cur) >= self.max_servers:
                return None
            if now - self._last_decision_t < self.cooldown_s:
                return None
            self._hot_polls = 0
            self._last_decision_t = now
            return {"action": "grow_server", "n_servers": len(cur) + 1}

    def note_straggler(self, event: dict,
                       now: Optional[float] = None) -> Optional[dict]:
        """hetutrail straggler events (trail.SkewMonitor /
        trail-events.jsonl) as a scale signal. A rank-level straggler is
        recorded but recommends nothing by itself — a slow WORKER is not
        fixed by more PS servers; when the event's critical-path
        attribution names a PS server (``server`` key, from ``hetutrail
        --step``'s verdict riding the event), it counts like sustained
        apply-latency pressure and recommends one more server, under the
        same cooldown/max bounds as :meth:`observe`."""
        now = time.monotonic() if now is None else now
        with self._lock:
            self.stragglers_seen += 1
            if event.get("server") is None:
                return None
            # the policy's own stats view (observe() feeds it from real
            # kServerStats rows) is the ONLY acceptable cluster size for
            # the cap check: the event's n_servers — distinct servers SEEN
            # in the straggler's recent spans — is a lower bound that
            # could grow past max_servers. No stats yet => no
            # recommendation (the real wiring polls observe() alongside).
            n_servers = len(self._last[1]) if self._last else 0
            if not n_servers or n_servers >= self.max_servers:
                return None
            if now - self._last_decision_t < self.cooldown_s:
                return None
            self._last_decision_t = now
            return {"action": "grow_server", "n_servers": int(n_servers) + 1,
                    "reason": f"straggler server {event['server']}"}
