"""hetuchaos: deterministic network-fault chaos engine, invariant checkers,
and the soak driver (docs/FAULT_TOLERANCE.md "Chaos testing & transport
hardening").

The C++ engine (csrc/ps/chaos.h, armed via ``PSClient.SetChaos`` /
``HETU_CHAOS_SPEC``, HETU_TEST_MODE-gated) injects message-level faults —
drop, delay, duplicate, reorder, corrupt-bytes, directed partitions —
into the PS transport from a seeded PRNG, logging every injection to a
bounded event ring. This module is everything above the wire:

- the **spec grammar** (:func:`parse_spec` / :func:`render_spec` /
  :func:`random_spec`) mirrored against the C++ parser;
- the **backoff schedule mirror** (:func:`backoff_ms` /
  :func:`backoff_schedule`), bit-identical to ``csrc/ps/chaos.h`` — the
  fake-clock tests pin both sides;
- the **invariant checkers** past PRs proved ad hoc, formalized as
  reusable functions: exactly-once sample consumption (the era algebra of
  PR 11), no-double-apply / exact update-counter accounting (the dedup
  ledger of PR 4, now checkable as ``client pushes_ok == Σ server
  updates``), and params-untouched-on-reject (the kQI8 contract of PR 8,
  generalized to CRC);
- the **soak driver** (:func:`run_soak`): a live ``local_cluster``
  training job under a seeded random schedule, all checkers asserted,
  final loss/params compared BIT-IDENTICALLY to the fault-free twin.

Everything above ``run_job`` is stdlib+numpy (``bin/hetuchaos --check``
must run jax-free); jax/hetu imports are lazy inside the drivers.
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from . import faults
from .ps import wire_constants as wire

# ---------------------------------------------------------------------------
# Kind ids: the drain contract with csrc/ps/chaos.h ChaosKind
# (wire_constants.CHAOS_KINDS is the enum mirror hetucheck verifies)
# ---------------------------------------------------------------------------

KIND_NAMES = {v: k[1:].lower() for k, v in wire.CHAOS_KINDS.items()
              if v != 0}  # {1: "drop", ..., 7: "droprsp"}
KIND_IDS = {v: k for k, v in KIND_NAMES.items()}
# columns of one drained chaos event row (PSClient.DrainChaosEvents)
EVENT_COLS = wire.CHAOS_EVENT_FIELDS

_MASK64 = (1 << 64) - 1


def splitmix64(x: int) -> int:
    """Python mirror of ``hetups::splitmix64`` (csrc/ps/chaos.h)."""
    x = (x + 0x9E3779B97F4A7C15) & _MASK64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _MASK64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _MASK64
    return x ^ (x >> 31)


def backoff_ms(attempt: int, base_ms: int = 10, cap_ms: int = 2000,
               key: int = 0) -> int:
    """Retry backoff for attempt N (1-based): exponential ``base << (N-1)``
    capped at ``cap_ms``, scaled by a deterministic jitter in [0.5, 1.0)
    derived from splitmix64. Bit-identical to ``hetups::backoff_ms`` —
    pure integer math on both sides, so the schedule the C++ transport
    actually sleeps is exactly what these tests assert about."""
    attempt = max(1, int(attempt))
    exp = min(int(base_ms) << min(attempt - 1, 20), int(cap_ms))
    j = splitmix64((int(key) ^ attempt) & _MASK64) % 500
    return exp * (500 + j) // 1000


def backoff_schedule(attempts: int, base_ms: int = 10, cap_ms: int = 2000,
                     key: int = 0) -> list[int]:
    """Per-attempt backoffs for a whole retry sequence (what a clock would
    observe between attempt N and N+1)."""
    return [backoff_ms(a, base_ms, cap_ms, key)
            for a in range(1, int(attempts) + 1)]


# ---------------------------------------------------------------------------
# Spec grammar (mirror of csrc/ps/chaos.h ChaosEngine::parse)
# ---------------------------------------------------------------------------

@dataclass
class ChaosSpec:
    """Parsed ``HETU_CHAOS_SPEC``. Probabilities are per-message and
    cumulative-walked in the fixed order drop, droprsp, dup, corrupt,
    delay, reorder (at most ONE scheduled fault per message); partitions
    are (server, from, count) windows over per-(server, channel) RPC
    ATTEMPTS — they block retries too, until the window closes."""

    seed: int = 0
    drop: float = 0.0
    droprsp: float = 0.0
    dup: float = 0.0
    corrupt: float = 0.0
    delay: float = 0.0
    delay_ms: int = 20
    reorder: float = 0.0
    reorder_ms: int = 10
    partitions: list = field(default_factory=list)  # [(server, from, count)]


# grammar vocabulary owned by the shared fault registry (hetu_tpu.faults)
_PROB_KEYS = faults.CHAOS_PROB_KEYS


def parse_spec(spec: str) -> ChaosSpec:
    """Parse a chaos spec string, rejecting unknown kinds with the known
    list (the HETU_FAULT_SPEC convention). Mirrors the C++ parser — the
    round-trip test pins them to the same grammar."""
    cs = ChaosSpec()
    for ent in (spec or "").split(","):
        ent = ent.strip()
        if not ent:
            continue
        key, sep, val = ent.partition("=")
        if not sep:
            raise ValueError(f"chaos spec entry {ent!r}: expected key=value")
        if key == "seed":
            cs.seed = int(val)
        elif key in _PROB_KEYS:
            setattr(cs, key, _parse_p(ent, val))
        elif key in ("delay", "reorder"):
            p, _, ms = val.partition(":")
            setattr(cs, key, _parse_p(ent, p))
            if ms:
                setattr(cs, key + "_ms", max(1, int(ms)))
        elif key == "partition":
            parts = val.split(":")
            if len(parts) != 3:
                raise ValueError(f"chaos spec entry {ent!r}: "
                                 "partition=SERVER:FROM:COUNT")
            cs.partitions.append((int(parts[0]), int(parts[1]),
                                  int(parts[2])))
        else:
            raise ValueError(
                f"chaos spec entry {ent!r}: unknown kind {key!r} — known: "
                + faults.chaos_catalogue())
    return cs


def _parse_p(ent: str, val: str) -> float:
    p = float(val)
    if not 0.0 <= p <= 1.0:
        raise ValueError(f"chaos spec entry {ent!r}: probability must be "
                         "in [0, 1]")
    return p


def render_spec(cs: ChaosSpec) -> str:
    """Inverse of :func:`parse_spec` (parse(render(x)) == x)."""
    parts = [f"seed={cs.seed}"]
    for k in _PROB_KEYS:
        v = getattr(cs, k)
        if v > 0:
            parts.append(f"{k}={v:g}")
    if cs.delay > 0:
        parts.append(f"delay={cs.delay:g}:{cs.delay_ms}")
    if cs.reorder > 0:
        parts.append(f"reorder={cs.reorder:g}:{cs.reorder_ms}")
    for srv, frm, cnt in cs.partitions:
        parts.append(f"partition={srv}:{frm}:{cnt}")
    return ",".join(parts)


def random_spec(seed: int, servers: int = 2, intensity: float = 0.06,
                partition: bool = True) -> str:
    """A seeded random schedule mixing every fault kind — what
    ``bin/hetuchaos --seed S`` runs. Deterministic: the same seed yields
    the same spec string. ``intensity`` bounds each per-message fault
    probability; the partition window (when enabled) is short enough for
    the default retry budget (DMLC_PS_MAX_RETRY=3 means a window of <= 3
    attempts heals within one RPC's retries, exercising the path without
    requiring failover to be armed)."""
    rng = np.random.RandomState(int(seed) & 0x7FFFFFFF)
    cs = ChaosSpec(seed=int(seed))
    kinds = ["drop", "droprsp", "dup", "corrupt", "delay", "reorder"]
    # 3-5 scheduled kinds active per spec, probabilities in (0, intensity]
    active = rng.choice(kinds, size=rng.randint(3, len(kinds) + 1),
                        replace=False)
    for k in active:
        setattr(cs, k, round(float(rng.uniform(0.01, intensity)), 4))
    cs.delay_ms = int(rng.randint(1, 8))
    cs.reorder_ms = int(rng.randint(1, 8))
    if partition and servers > 0:
        # a transient directed partition: 1-2 consecutive failed attempts
        # against one server, somewhere in the first ~40 attempts
        cs.partitions.append((int(rng.randint(0, servers)),
                              int(rng.randint(0, 40)),
                              int(rng.randint(1, 3))))
    return render_spec(cs)


# ---------------------------------------------------------------------------
# Event-log helpers
# ---------------------------------------------------------------------------

def events_to_dicts(rows) -> list[dict]:
    """(n, 6) int64 drain rows -> dict rows with named kinds."""
    out = []
    for r in np.asarray(rows, np.int64).reshape(-1, len(EVENT_COLS)):
        d = dict(zip(EVENT_COLS, (int(x) for x in r)))
        d["kind"] = KIND_NAMES.get(d["kind"], str(d["kind"]))
        out.append(d)
    return out


def canonical_log(rows) -> list[tuple]:
    """The ORDER-FREE canonical form of a chaos event log: sorted tuples.
    Ring append order depends on thread interleaving (the pool races
    servers); the DECISIONS do not — each is a pure function of (seed,
    server, psf, tensor, per-triple seq). Two runs of the same workload
    under the same spec must produce EQUAL canonical logs; that equality
    is the determinism acceptance test."""
    return sorted(tuple(int(x) for x in r)
                  for r in np.asarray(rows, np.int64)
                  .reshape(-1, len(EVENT_COLS)))


def fault_counts(rows) -> dict:
    """Per-kind injected-fault totals (the hetu_chaos_faults_total{kind}
    export)."""
    out: dict[str, int] = {}
    for d in events_to_dicts(rows):
        out[d["kind"]] = out.get(d["kind"], 0) + 1
    return out


# ---------------------------------------------------------------------------
# Invariant checkers (the library past PRs proved ad hoc)
# ---------------------------------------------------------------------------

class InvariantViolation(AssertionError):
    """An invariant checker found the system in a state the transport
    hardening is supposed to make impossible."""


def check_update_accounting(client_stats: dict,
                            server_stats: list[dict]) -> dict:
    """Exact no-double-apply / no-lost-update accounting (PR 4's dedup
    ledger, as one equation): each LOGICAL write RPC the client completed
    (``pushes_ok`` — counted once however many retries, duplicates, or
    re-issues it took) must equal the servers' summed optimizer update
    counters. A double-apply (a duplicate that escaped the dedup slot)
    pushes the right side high; a lost-but-acked update pushes it low.
    Valid for fresh servers (``restored_updates == -1``) serving one
    worker — the soak's shape."""
    expected = int(client_stats["pushes_ok"])
    applied = sum(int(s["updates"]) for s in server_stats)
    ok = expected == applied
    report = {"name": "update_accounting", "ok": ok,
              "client_pushes_ok": expected, "server_updates": applied}
    if not ok:
        raise InvariantViolation(
            f"update-counter accounting broken: client completed {expected} "
            f"write RPCs but servers applied {applied} updates "
            f"({'double-apply' if applied > expected else 'lost update'})")
    return report


def check_exactly_once_consumption(consumed, expected) -> dict:
    """Exactly-once sample consumption: the multiset of consumed sample
    indices equals the expected multiset — no sample trained twice, none
    skipped. The single-worker form of PR 11's era algebra (for elastic
    resizes, ``elastic.era_partitions`` produces ``expected`` per
    member)."""
    c = np.sort(np.asarray(consumed).ravel())
    e = np.sort(np.asarray(expected).ravel())
    ok = c.shape == e.shape and bool(np.array_equal(c, e))
    report = {"name": "exactly_once_consumption", "ok": ok,
              "consumed": int(c.size), "expected": int(e.size)}
    if not ok:
        raise InvariantViolation(
            f"sample consumption not exactly-once: consumed {c.size} vs "
            f"expected {e.size} (or differing multisets)")
    return report


def check_bit_identical(chaos_values, baseline_values,
                        what: str = "params") -> dict:
    """Bit-identical final state vs the fault-free twin: every fault the
    schedule injected was fully absorbed by the transport (retry applied
    exactly once, rejects left params untouched, duplicates were served
    from the dedup slot). ``allclose`` would hide a half-applied update;
    only equality proves absorption."""
    ca = [np.asarray(a) for a in chaos_values]
    ba = [np.asarray(b) for b in baseline_values]
    ok = len(ca) == len(ba) and all(
        a.shape == b.shape and bool(np.array_equal(a, b))
        for a, b in zip(ca, ba))
    report = {"name": f"bit_identical_{what}", "ok": ok, "n": len(ca)}
    if not ok:
        bad = [i for i, (a, b) in enumerate(zip(ca, ba))
               if a.shape != b.shape or not np.array_equal(a, b)]
        raise InvariantViolation(
            f"{what} diverged from the fault-free run at indices {bad[:8]} "
            f"— a fault leaked through the transport hardening")
    return report


def check_rejects_left_params_untouched(client_stats: dict,
                                        server_stats: list[dict],
                                        parity_report: dict) -> dict:
    """Params-untouched-on-reject: every CRC reject the servers issued
    was a clean refusal. Meaningful only alongside bit-identical parity —
    a reject that half-applied would break parity; this checker pins that
    the schedule actually EXERCISED the reject path (rejects observed on
    both sides) so the parity proof covers it."""
    srv = sum(int(s.get("crc_rejects", 0)) for s in server_stats)
    cli = int(client_stats.get("crc_rejects", 0))
    ok = bool(parity_report.get("ok")) and cli >= srv > 0
    report = {"name": "params_untouched_on_reject", "ok": ok,
              "server_rejects": srv, "client_rejects_observed": cli}
    if not ok:
        raise InvariantViolation(
            f"reject path not proven: servers rejected {srv}, client "
            f"observed {cli}, parity={parity_report.get('ok')} — with a "
            "corrupt fault armed the schedule must produce rejects AND "
            "bit-identical final state")
    return report


# ---------------------------------------------------------------------------
# Soak driver (live local_cluster training job)
# ---------------------------------------------------------------------------

#: the soak job's fixed shape (kept tiny: the CI soak must stay <= 60 s)
SOAK_ROWS, SOAK_WIDTH, SOAK_SLOTS, SOAK_BATCH = 60, 8, 4, 16


def run_job(seed: int, steps: int, n_servers: int = 2,
            chaos_spec: Optional[str] = None) -> dict:
    """One live training run: scheduler + ``n_servers`` PS servers
    (local_cluster), this process the worker, a CTR-shaped model (sparse
    embedding + dense head, both PS-hosted via comm_mode='PS') trained
    ``steps`` steps on deterministic batches. Synchronous I/O
    (prefetch=False) so the run is bit-reproducible — the determinism the
    parity checker needs is the job's, leaving any divergence
    attributable to the transport.

    Returns losses, final param values, client/server stats, the drained
    chaos event log, and the consumed sample indices."""
    from .ps.local_cluster import local_cluster
    from . import ps as ps_pkg

    with local_cluster(n_servers=n_servers, n_workers=1):
        import hetu_tpu as ht
        ps_pkg.worker_init()
        comm = ps_pkg.get_worker_communicate()
        embed = ht.init.random_normal((SOAK_ROWS, SOAK_WIDTH), stddev=0.1,
                                      name="chaos_embed", is_embed=True)
        idx = ht.Variable(name="idx", trainable=False)
        y_ = ht.Variable(name="y_", trainable=False)
        vec = ht.embedding_lookup_op(embed, idx)
        flat = ht.array_reshape_op(vec, (-1, SOAK_SLOTS * SOAK_WIDTH))
        w = ht.init.xavier_uniform((SOAK_SLOTS * SOAK_WIDTH, 1), name="w")
        prob = ht.sigmoid_op(ht.matmul_op(flat, w))
        loss = ht.reduce_mean_op(ht.binarycrossentropy_op(prob, y_), [0])
        train_op = ht.optim.SGDOptimizer(0.1).minimize(loss)
        ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0),
                         seed=0, comm_mode="PS", prefetch=False)
        try:
            if chaos_spec:
                comm.SetChaos(chaos_spec)
            rng = np.random.RandomState(seed)
            losses, consumed, step_errors = [], [], []
            for step in range(int(steps)):
                bidx = rng.randint(0, SOAK_ROWS,
                                   (SOAK_BATCH, SOAK_SLOTS)).astype(
                                       np.float32)
                by = ((bidx >= SOAK_ROWS // 2).sum(axis=1) >
                      SOAK_SLOTS // 2).reshape(-1, 1).astype(np.float32)
                try:
                    out = ex.run("train", feed_dict={idx: bidx, y_: by})
                except Exception as e:  # noqa: BLE001 — a fault the
                    # hardening failed to absorb: record the hole and keep
                    # going, so the CHECKERS (not a traceback) report it —
                    # this step's samples are missing from `consumed`,
                    # breaking exactly-once; its loss is missing, breaking
                    # loss parity
                    step_errors.append((step, repr(e)))
                    continue
                losses.append(float(out[0].asnumpy()))
                # recorded only for steps that COMPLETED: the consumption
                # multiset is an observation of delivered work, falsified
                # by any step the transport lost
                consumed.append(step * SOAK_BATCH +
                                np.arange(SOAK_BATCH))
            rt = ex.ps_runtime
            rt.drain()
            finals = []
            for p in sorted(rt.params.values(), key=lambda p: p.ps_id):
                if p.sparse:
                    finals.append(rt.pull_sparse_rows(
                        p, np.arange(SOAK_ROWS)))
                else:
                    finals.append(rt.pull_dense_value(p))
            client_stats = comm.ClientStats()
            server_stats = [comm.ServerStats(s) for s in range(n_servers)]
            events = comm.DrainChaosEvents()
            if chaos_spec:
                comm.SetChaos(None)
        finally:
            ex.close()
            ps_pkg.worker_finish()
        return {"losses": losses, "finals": finals,
                "step_errors": step_errors,
                "client_stats": client_stats, "server_stats": server_stats,
                "events": events,
                "consumed": np.concatenate(consumed) if consumed else
                np.zeros(0, np.int64)}


def run_soak(seed: int, steps: int = 24, n_servers: int = 2,
             spec: Optional[str] = None) -> dict:
    """The acceptance loop of one seeded schedule: fault-free twin first,
    then the chaos run under ``spec`` (default: :func:`random_spec`),
    then every invariant checker. Requires HETU_TEST_MODE (set it before
    calling, as bin/hetuchaos does) — SetChaos refuses otherwise.

    Raises :class:`InvariantViolation` on any broken invariant; returns
    the full report dict on success."""
    spec = spec or random_spec(seed, servers=n_servers)
    cs = parse_spec(spec)
    base = run_job(seed, steps, n_servers, chaos_spec=None)
    chaos = run_job(seed, steps, n_servers, chaos_spec=spec)

    if chaos["step_errors"]:
        # surfaced FIRST with the actual exceptions: the checkers below
        # would also fail (missing consumption/losses), but "step 7 raised
        # X" beats "multiset differs" as a diagnosis
        raise InvariantViolation(
            f"{len(chaos['step_errors'])} step(s) raised under {spec!r} — "
            "the hardening failed to absorb a fault: "
            f"{chaos['step_errors'][:4]}")
    checks = [
        check_update_accounting(chaos["client_stats"],
                                chaos["server_stats"]),
        # single-worker form: the chaos run COMPLETED exactly the steps
        # the fault-free twin did (consumption is recorded per completed
        # step and a failed step is skipped-not-raised in run_job, so a
        # lost step breaks the multiset instead of aborting the job).
        # The multi-member era-algebra form of this checker is exercised
        # with real resize partitions in tests/test_elastic.py.
        check_exactly_once_consumption(chaos["consumed"],
                                       base["consumed"]),
        check_bit_identical(chaos["finals"], base["finals"], "params"),
        check_bit_identical([np.asarray(chaos["losses"])],
                            [np.asarray(base["losses"])], "losses"),
    ]
    parity = checks[2]
    counts = fault_counts(chaos["events"])
    # gate on INJECTED corrupts, not the configured probability: a small
    # p over a short soak can legitimately roll zero corrupts, and the
    # reject proof is only owed for faults that actually fired
    if counts.get("corrupt", 0) > 0:
        checks.append(check_rejects_left_params_untouched(
            chaos["client_stats"], chaos["server_stats"], parity))
    # the schedule must have actually injected something, or the soak
    # proved nothing (a zero-probability spec silently "passing" is the
    # no-silent-caps failure mode)
    if not counts:
        raise InvariantViolation(
            f"schedule {spec!r} injected zero faults over {steps} steps — "
            "raise intensity or steps; a faultless soak proves nothing")
    report = {
        "seed": int(seed), "steps": int(steps), "spec": spec,
        "faults": counts,
        "checks": checks,
        "client_stats": chaos["client_stats"],
        "final_loss": chaos["losses"][-1] if chaos["losses"] else None,
        "ok": all(c["ok"] for c in checks),
    }
    _export_telemetry(report)
    return report


def _export_telemetry(report: dict) -> None:
    """hetu_chaos_faults_total{kind} + hardening counters through the
    telemetry bus (no-op when telemetry is off). Never raises."""
    try:
        from . import telemetry as _telemetry
        tel = _telemetry.get()
        if tel is None:
            return
        reg = tel.metrics
        for kind, n in report.get("faults", {}).items():
            reg.gauge("hetu_chaos_faults_total", {"kind": kind}).set(n)
        cs = report.get("client_stats", {})
        reg.gauge("hetu_rpc_timeouts_total").set(cs.get("timeouts", 0))
        reg.gauge("hetu_rpc_backoff_ms").set(cs.get("backoff_ms", 0))
        reg.gauge("hetu_crc_rejects_total").set(cs.get("crc_rejects", 0))
    except Exception:  # noqa: BLE001 — observability only
        pass


# ---------------------------------------------------------------------------
# jax-free self-test (bin/hetuchaos --check)
# ---------------------------------------------------------------------------

def self_check(out=None) -> int:
    """CI smoke with no cluster and no jax: grammar round-trip, unknown-
    kind rejection, backoff mirror values, random_spec determinism,
    canonical-log algebra, and each checker's accept AND reject paths.
    Returns 0 on success (the bin/hetu* --check convention)."""
    import sys
    out = out or sys.stdout

    cs = parse_spec("seed=42,drop=0.1,delay=0.2:7,partition=1:10:30")
    assert cs.seed == 42 and cs.drop == 0.1 and cs.delay_ms == 7
    assert cs.partitions == [(1, 10, 30)]
    assert parse_spec(render_spec(cs)) == cs
    for bad in ("flood=0.5", "drop=1.5", "partition=1:2"):
        try:
            parse_spec(bad)
            raise AssertionError(f"{bad!r} accepted")
        except ValueError:
            pass

    sched = backoff_schedule(4, base_ms=10, cap_ms=2000, key=7)
    assert len(sched) == 4 and all(b >= 1 for b in sched)
    for a, b in enumerate(sched, 1):
        exp = min(10 << (a - 1), 2000)
        assert exp // 2 <= b < exp, (a, b)   # jitter in [0.5, 1.0)
    assert sched == backoff_schedule(4, base_ms=10, cap_ms=2000, key=7)

    assert random_spec(3) == random_spec(3)
    assert random_spec(3) != random_spec(4)
    parse_spec(random_spec(5))  # every generated spec must parse

    rows = np.array([[1, 0, 20, 1, 3, 0], [5, 1, 20, 1, 1, 9]], np.int64)
    assert canonical_log(rows) == canonical_log(rows[::-1])
    assert fault_counts(rows) == {"drop": 1, "corrupt": 1}

    ok_cs = {"pushes_ok": 4, "crc_rejects": 2}
    ok_ss = [{"updates": 3, "crc_rejects": 1}, {"updates": 1,
                                                "crc_rejects": 1}]
    assert check_update_accounting(ok_cs, ok_ss)["ok"]
    try:
        check_update_accounting({"pushes_ok": 4}, [{"updates": 5}])
        raise AssertionError("double-apply not caught")
    except InvariantViolation:
        pass
    assert check_exactly_once_consumption([2, 0, 1], [0, 1, 2])["ok"]
    try:
        check_exactly_once_consumption([0, 0, 1], [0, 1, 2])
        raise AssertionError("double-consumption not caught")
    except InvariantViolation:
        pass
    a = [np.arange(6).reshape(2, 3).astype(np.float32)]
    assert check_bit_identical(a, [a[0].copy()])["ok"]
    try:
        check_bit_identical(a, [a[0] + 1e-7])
        raise AssertionError("divergence not caught")
    except InvariantViolation:
        pass
    parity = {"ok": True}
    assert check_rejects_left_params_untouched(ok_cs, ok_ss, parity)["ok"]
    try:
        check_rejects_left_params_untouched(
            {"pushes_ok": 4, "crc_rejects": 0},
            [{"updates": 4, "crc_rejects": 0}], parity)
        raise AssertionError("unexercised reject path not caught")
    except InvariantViolation:
        pass

    print("hetuchaos --check: spec grammar, backoff mirror, canonical "
          "log, and all invariant checkers OK", file=out)
    return 0


# ---------------------------------------------------------------------------
# CLI (bin/hetuchaos)
# ---------------------------------------------------------------------------

def main(argv=None) -> int:
    """``hetuchaos --seed S --steps N``: live seeded soak (fault-free twin
    + chaos run + every invariant checker). ``--seeds A,B,C`` runs several
    schedules; ``--spec`` overrides the generated schedule; ``--check``
    is the jax-free CI self-test. Exit 0 = all invariants green."""
    import argparse
    import json as _json
    import sys

    ap = argparse.ArgumentParser(
        prog="hetuchaos",
        description="deterministic PS-transport chaos soak "
                    "(docs/FAULT_TOLERANCE.md)")
    ap.add_argument("--check", action="store_true",
                    help="jax-free self-test (CI smoke); exit 0/1")
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--seeds", type=str, default=None,
                    help="comma-separated seed list (overrides --seed)")
    ap.add_argument("--steps", type=int, default=24)
    ap.add_argument("--servers", type=int, default=2)
    ap.add_argument("--spec", type=str, default=None,
                    help="explicit chaos spec (default: random_spec(seed))")
    ap.add_argument("--json", action="store_true",
                    help="one JSON report line per seed")
    args = ap.parse_args(argv)

    if args.check:
        try:
            return self_check()
        except AssertionError as e:
            print(f"hetuchaos --check FAILED: {e}", file=sys.stderr)
            return 1

    # the soak arms destructive hooks by definition — it IS the test mode
    os.environ.setdefault("HETU_TEST_MODE", "1")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # the package form of this module (the bin script loads this file by
    # path, which cannot resolve the relative imports the drivers need)
    import hetu_tpu.chaos as chaos_pkg

    seeds = ([int(s) for s in args.seeds.split(",") if s.strip()]
             if args.seeds else [args.seed])
    rc = 0
    for seed in seeds:
        try:
            rep = chaos_pkg.run_soak(seed, steps=args.steps,
                                     n_servers=args.servers,
                                     spec=args.spec)
        except chaos_pkg.InvariantViolation as e:
            spec = args.spec or chaos_pkg.random_spec(
                seed, servers=args.servers)
            print(f"# seed {seed} VIOLATION under {spec!r}: {e}",
                  file=sys.stderr)
            rc = 1
            continue
        if args.json:
            print(_json.dumps(rep, default=str))
        else:
            faults = " ".join(f"{k}:{v}" for k, v in
                              sorted(rep["faults"].items()))
            checks = " ".join(
                f"{c['name']}={'ok' if c['ok'] else 'FAIL'}"
                for c in rep["checks"])
            print(f"# seed {seed} spec {rep['spec']!r}\n"
                  f"#   faults {faults}\n"
                  f"#   {checks}\n"
                  f"#   final loss {rep['final_loss']:.6f} "
                  f"(bit-identical to fault-free twin)")
    return rc
