"""Dataset loaders + augmentation (reference ``python/hetu/data.py``).

Loads MNIST/CIFAR from local files when present (same filenames the reference
expects); in hermetic environments with no dataset on disk, falls back to a
deterministic synthetic dataset with the same shapes/dtypes so examples,
tests and benchmarks run anywhere. All metrics/augmentation are numpy.
"""
from __future__ import annotations

import gzip
import os
import pickle

import numpy as np

_DATA_SEARCH_PATHS = [
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "datasets"),
    os.path.expanduser("~/.hetu_tpu/datasets"),
    ".",
]


def _find(path):
    if os.path.isabs(path) and os.path.exists(path):
        return path
    for root in _DATA_SEARCH_PATHS:
        p = os.path.join(root, path)
        if os.path.exists(p):
            return p
    return None


def convert_to_one_hot(vals, max_val=0):
    """One-hot encode int labels (reference data.py:212)."""
    vals = np.asarray(vals).astype(np.int64)
    if max_val == 0:
        max_val = vals.max() + 1
    one_hot = np.zeros((vals.size, max_val), dtype=np.float32)
    one_hot[np.arange(vals.size), vals.reshape(-1)] = 1.0
    return one_hot


def _synthetic_classification(n, feature_shape, num_classes, seed):
    """Deterministic, linearly-separable-ish synthetic data: class centroids +
    gaussian noise, so models measurably learn (loss decreases, acc >> chance)."""
    rng = np.random.RandomState(seed)
    dim = int(np.prod(feature_shape))
    centroids = rng.randn(num_classes, dim).astype(np.float32) * 2.0
    labels = rng.randint(0, num_classes, size=n)
    x = centroids[labels] + rng.randn(n, dim).astype(np.float32)
    x = (x - x.mean()) / (x.std() + 1e-6)
    return x.reshape((n,) + tuple(feature_shape)).astype(np.float32), labels


def mnist(dataset="mnist.pkl.gz", onehot=True):
    """Returns [(train_x, train_y), (valid_x, valid_y), (test_x, test_y)]
    with x: (N, 784) float32 (reference data.py:5)."""
    path = _find(dataset)
    if path is not None:
        with gzip.open(path, "rb") as f:
            train_set, valid_set, test_set = pickle.load(f, encoding="latin1")
        sets = [train_set, valid_set, test_set]
    else:
        sets = []
        for n, seed in ((50000, 1), (10000, 2), (10000, 3)):
            x, y = _synthetic_classification(n, (784,), 10, seed)
            sets.append((x, y))
    out = []
    for x, y in sets:
        y = convert_to_one_hot(y, max_val=10) if onehot else np.asarray(y)
        out.append((np.asarray(x, dtype=np.float32), y))
    return out


def _load_cifar_pickled(directory, files, label_key):
    xs, ys = [], []
    for fname in files:
        with open(fname, "rb") as f:
            batch = pickle.load(f, encoding="latin1")
        xs.append(np.asarray(batch["data"], dtype=np.float32))
        ys.append(np.asarray(batch[label_key], dtype=np.int64))
    return np.concatenate(xs), np.concatenate(ys)


def cifar10(directory="CIFAR_10", onehot=True, num_class=10):
    root = _find(directory)
    if root is not None:
        train_files = [os.path.join(root, f"data_batch_{i}") for i in range(1, 6)]
        test_files = [os.path.join(root, "test_batch")]
        train_x, train_y = _load_cifar_pickled(root, train_files, "labels")
        test_x, test_y = _load_cifar_pickled(root, test_files, "labels")
        train_x = train_x.reshape(-1, 3, 32, 32)
        test_x = test_x.reshape(-1, 3, 32, 32)
    else:
        train_x, train_y = _synthetic_classification(50000, (3, 32, 32), num_class, 11)
        test_x, test_y = _synthetic_classification(10000, (3, 32, 32), num_class, 12)
    if onehot:
        train_y = convert_to_one_hot(train_y, max_val=num_class)
        test_y = convert_to_one_hot(test_y, max_val=num_class)
    return train_x, train_y, test_x, test_y


def cifar100(directory="CIFAR_100", onehot=True):
    return cifar10(directory, onehot, num_class=100)


def normalize_cifar(num_class=10, onehot=True):
    """Channel-normalized CIFAR (reference data.py:153): returns
    (train_x, train_y, valid_x, valid_y) in NCHW."""
    if num_class == 10:
        train_x, train_y, test_x, test_y = cifar10(onehot=onehot)
    else:
        train_x, train_y, test_x, test_y = cifar100(onehot=onehot)
    mean = train_x.mean(axis=(0, 2, 3), keepdims=True)
    std = train_x.std(axis=(0, 2, 3), keepdims=True) + 1e-7
    train_x = (train_x - mean) / std
    test_x = (test_x - mean) / std
    return (train_x.astype(np.float32), train_y,
            test_x.astype(np.float32), test_y)


tf_normalize_cifar = normalize_cifar


# ---------------------------------------------------------------------------
# augmentation (reference data.py:225-299) — numpy, host-side
# ---------------------------------------------------------------------------

def _image_crop(images, shape, rng=None):
    rng = rng or np.random
    n, c, h, w = images.shape
    pad = 4
    padded = np.pad(images, ((0, 0), (0, 0), (pad, pad), (pad, pad)), "constant")
    out = np.empty_like(images)
    for i in range(n):
        top = rng.randint(0, 2 * pad + 1)
        left = rng.randint(0, 2 * pad + 1)
        out[i] = padded[i, :, top:top + h, left:left + w]
    return out


def _image_flip(images, rng=None):
    rng = rng or np.random
    flip = rng.rand(images.shape[0]) < 0.5
    out = images.copy()
    out[flip] = out[flip][:, :, :, ::-1]
    return out


def _image_whitening(images):
    mean = images.mean(axis=(1, 2, 3), keepdims=True)
    std = np.maximum(images.std(axis=(1, 2, 3), keepdims=True),
                     1.0 / np.sqrt(np.prod(images.shape[1:])))
    return (images - mean) / std


def _image_noise(images, mean=0, std=0.01, rng=None):
    rng = rng or np.random
    return images + rng.normal(mean, std, size=images.shape).astype(images.dtype)


def data_augmentation(images, mode="train", flip=False, crop=False,
                      whiten=False, noise=False):
    images = np.asarray(images, dtype=np.float32)
    if mode == "train":
        if crop:
            images = _image_crop(images, images.shape)
        if flip:
            images = _image_flip(images)
    if whiten:
        images = _image_whitening(images)
    if noise and mode == "train":
        images = _image_noise(images)
    return images
