"""Pipeline parallelism: GPipe microbatch schedule over the ``pp`` mesh axis.

Reference mechanism: per-stage ``ht.context(...)`` blocks, auto-inserted
NCCL PipelineSend/Recv with a runtime shape handshake, and a Python microbatch
loop (``SubExecutor4Gpipe``, executor.py:435-767) that runs all forwards then
all backwards and applies the optimizer once.

TPU-native redesign: the whole pipeline — all stages, all microbatches,
forward AND backward — is ONE jitted program. Stage weights are stacked on a
leading axis sharded over ``pp``; inside a ``jax.shard_map`` (manual over
``pp``, GSPMD-auto over dp/tp/sp/ep) activations advance between stages with
``lax.ppermute`` over ICI. ``jax.grad`` differentiates straight through the
ppermute (its transpose is the reverse permute), so the 1F1B-ish reverse
schedule emerges from XLA's dataflow rather than host code, and the optimizer
applies once per step like GPipe. Shapes are static — the reference's dynamic
shape handshake (PipelineSend.py:30-44) is unnecessary by construction.
"""
from __future__ import annotations

import functools
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models import transformer as tfm


def _stack_stages(params, pp: int):
    """Reshape per-layer stacked block params (L, ...) -> (pp, L//pp, ...)."""
    def reshape(x):
        L = x.shape[0]
        assert L % pp == 0, f"n_layers {L} not divisible by pp {pp}"
        return x.reshape(pp, L // pp, *x.shape[1:])
    return jax.tree.map(reshape, params)


def pipeline_spec(cfg: tfm.TransformerConfig, pp: int):
    """Sharding for pipeline params: blocks get a leading 'pp' dim; embed/pos/
    head/final-norm are replicated — they are consumed inside the manual-pp
    region, where a tp-sharded gather trips a CHECK in XLA's SPMD partitioner
    (observed on XLA@jax0.9: PartitionGatherTrivialSlicedOperandDimensions),
    and stage 0 / stage pp-1 need them everywhere anyway."""
    base = tfm.param_specs(cfg)
    blocks = {k: P("pp", *s) for k, s in base["blocks"].items()}
    replicated = {k: P() for k in base if k != "blocks"}
    return {**replicated, "blocks": blocks}


def make_pipeline_train_step(cfg: tfm.TransformerConfig, mesh: Mesh,
                             num_microbatches: int, lr: float = 1e-3,
                             aux_weight: float = 0.01):
    """Build the jitted GPipe step.

    tokens/targets: (M, mb, T) — M microbatches. Returns
    (loss, params, opt_state).
    """
    pp = mesh.shape["pp"]
    M = num_microbatches
    assert cfg.n_layers % pp == 0
    layers_per_stage = cfg.n_layers // pp
    use_dropout = cfg.dropout_rate > 0.0

    def stage_fn(h, stage_blocks, stage, rng_mb):
        """Run this device's layers over one microbatch activation.

        ``rng_mb``: this microbatch's dropout key (None when dropout is
        off). Each layer folds in its GLOBAL index, so key(mb, layer)
        matches the non-pipelined trunk's grad-accumulation schedule
        (make_train_step: fold_in(rng, mi) then encode's fold_in(·, li))."""
        block = functools.partial(tfm._block, cfg=cfg, mesh=None)
        if cfg.remat:
            block = jax.checkpoint(block)
        first_layer = stage * layers_per_stage

        def body(carry, xs):
            h, aux = carry
            layer_params, li = xs
            rng = (None if rng_mb is None
                   else jax.random.fold_in(rng_mb, first_layer + li))
            h, a = block(h, layer_params, dropout_rng=rng)
            return (h, aux + a), None

        aux0 = jax.lax.pcast(jnp.zeros((), jnp.float32), ("pp",), to="varying")
        (h, aux), _ = jax.lax.scan(
            body, (h, aux0), (stage_blocks, jnp.arange(layers_per_stage)))
        return h, aux

    def fwd_loss(params, tokens, targets, dropout_rng=None):
        """Pipelined forward + loss, manual over pp via shard_map."""
        stage_blocks = params["blocks"]  # (1, L/pp, ...) local slice per stage
        other = {k: v for k, v in params.items() if k != "blocks"}
        B, T = tokens.shape[1], tokens.shape[2]
        state0 = jnp.zeros((B, T, cfg.d_model), cfg.dtype)

        def pipelined(stage_blocks, other, tokens, targets, state0,
                      dropout_rng=None):
            # inside: manual over 'pp' — axis_index tells us our stage
            stage = jax.lax.axis_index("pp")
            local_blocks = jax.tree.map(lambda x: x[0], stage_blocks)

            perm = [(i, (i + 1) % pp) for i in range(pp)]
            n_ticks = M + pp - 1
            # carries vary per pp-shard: mark them 'varying' for the vma type
            # system before entering the scan
            varying = lambda x: jax.lax.pcast(x, ("pp",), to="varying")
            state = varying(state0)
            loss_sum = varying(jnp.zeros((), jnp.float32))
            aux_sum = varying(jnp.zeros((), jnp.float32))

            def tick(carry, t):
                state, loss_sum, aux_sum = carry
                # stage 0 ingests microbatch t (if any); others use received
                mb_idx = jnp.clip(t, 0, M - 1)
                mb_tokens = jax.lax.dynamic_index_in_dim(
                    tokens, mb_idx, 0, keepdims=False)
                inject = tfm.embed_tokens(other, mb_tokens, cfg)
                state = jnp.where((stage == 0) & (t < M), inject, state)
                # the microbatch THIS stage is working on at tick t (garbage
                # outside the [stage, stage+M) window — its loss is never
                # taken, so the garbage dropout key is harmless)
                rng_mb = (None if dropout_rng is None
                          else jax.random.fold_in(
                              dropout_rng, jnp.clip(t - stage, 0, M - 1)))
                out, aux = stage_fn(state, local_blocks, stage, rng_mb)
                # this stage holds a real microbatch only during its window
                valid = (t >= stage) & (t < stage + M)
                aux_sum = aux_sum + jnp.where(valid, aux, 0.0)
                # last stage computes loss for the microbatch that has now
                # passed through all stages: microbatch t-(pp-1)
                done_idx = jnp.clip(t - (pp - 1), 0, M - 1)
                mb_targets = jax.lax.dynamic_index_in_dim(
                    targets, done_idx, 0, keepdims=False)
                mb_loss = tfm.nll_loss(tfm.lm_head(other, out, cfg),
                                       mb_targets)
                take = (stage == pp - 1) & (t >= pp - 1)
                loss_sum = loss_sum + jnp.where(take, mb_loss, 0.0)
                # advance activations to the next stage
                state = jax.lax.ppermute(out, "pp", perm)
                return (state, loss_sum, aux_sum), None

            (state, loss_sum, aux_sum), _ = jax.lax.scan(
                tick, (state, loss_sum, aux_sum), jnp.arange(n_ticks))
            # NLL lives on the last stage, aux is spread over stages; combine
            loss = jax.lax.psum(loss_sum, "pp") / M
            aux = jax.lax.psum(aux_sum, "pp") / M
            return loss + aux_weight * aux

        block_in_spec = jax.tree.map(lambda _: P("pp"), stage_blocks)
        other_spec = jax.tree.map(lambda _: P(), other)
        in_specs = [block_in_spec, other_spec, P(), P(), P()]
        args = [stage_blocks, other, tokens, targets, state0]
        if dropout_rng is not None:
            in_specs.append(P())
            args.append(dropout_rng)
        return jax.shard_map(
            pipelined,
            mesh=mesh,
            in_specs=tuple(in_specs),
            out_specs=P(),
            axis_names=frozenset({"pp"}),
        )(*args)

    def step(params, opt_state, tokens, targets, dropout_rng=None):
        if use_dropout:
            # a forgotten key must not silently train WITHOUT dropout
            assert dropout_rng is not None, (
                "cfg.dropout_rate > 0: pass dropout_rng to the pipeline step")
        loss, grads = jax.value_and_grad(fwd_loss)(
            params, tokens, targets, dropout_rng=dropout_rng)
        new_params, new_opt = tfm.adamw_update(params, grads, opt_state, lr=lr)
        return loss, new_params, new_opt

    specs = pipeline_spec(cfg, pp)
    pshard = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                          is_leaf=lambda x: isinstance(x, P))
    opt_shard = {"m": pshard, "v": pshard, "t": NamedSharding(mesh, P())}
    data_shard = NamedSharding(mesh, P(None, "dp", None))
    in_sh = [pshard, opt_shard, data_shard, data_shard]
    if use_dropout:
        step_fn = step
        in_sh.append(NamedSharding(mesh, P()))
    else:
        # keep the historical 4-arg signature for deterministic configs
        step_fn = lambda params, opt_state, tokens, targets: step(  # noqa: E731
            params, opt_state, tokens, targets)
    return jax.jit(
        step_fn,
        in_shardings=tuple(in_sh),
        out_shardings=(NamedSharding(mesh, P()), pshard, opt_shard),
        donate_argnums=(0, 1),
    )


def init_pipeline_params(rng, cfg: tfm.TransformerConfig, mesh: Mesh):
    pp = mesh.shape["pp"]
    params = tfm.init_params(rng, cfg)
    params = {**params, "blocks": _stack_stages(params["blocks"], pp)}
    specs = pipeline_spec(cfg, pp)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs)
