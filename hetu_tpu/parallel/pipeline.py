"""Pipeline parallelism: GPipe microbatch schedule over the ``pp`` mesh axis.

Reference mechanism: per-stage ``ht.context(...)`` blocks, auto-inserted
NCCL PipelineSend/Recv with a runtime shape handshake, and a Python microbatch
loop (``SubExecutor4Gpipe``, executor.py:435-767) that runs all forwards then
all backwards and applies the optimizer once.

TPU-native redesign: the whole pipeline — all stages, all microbatches,
forward AND backward — is ONE jitted program. Stage weights are stacked on a
leading axis sharded over ``pp``; inside a ``jax.shard_map`` (manual over
``pp``, GSPMD-auto over dp/tp/sp/ep) activations advance between stages with
``lax.ppermute`` over ICI. ``jax.grad`` differentiates straight through the
ppermute (its transpose is the reverse permute), so the 1F1B-ish reverse
schedule emerges from XLA's dataflow rather than host code, and the optimizer
applies once per step like GPipe. Shapes are static — the reference's dynamic
shape handshake (PipelineSend.py:30-44) is unnecessary by construction.
"""
from __future__ import annotations

import functools
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models import transformer as tfm
from ..utils import pvary, shard_map


def _stack_stages(params, pp: int):
    """Reshape per-layer stacked block params (L, ...) -> (pp, L//pp, ...)."""
    def reshape(x):
        L = x.shape[0]
        assert L % pp == 0, f"n_layers {L} not divisible by pp {pp}"
        return x.reshape(pp, L // pp, *x.shape[1:])
    return jax.tree.map(reshape, params)


def pipeline_spec(cfg: tfm.TransformerConfig, pp: int):
    """Sharding for pipeline params: blocks get a leading 'pp' dim; embed/pos/
    head/final-norm are replicated — they are consumed inside the manual-pp
    region, where a tp-sharded gather trips a CHECK in XLA's SPMD partitioner
    (observed on XLA@jax0.9: PartitionGatherTrivialSlicedOperandDimensions),
    and stage 0 / stage pp-1 need them everywhere anyway."""
    base = tfm.param_specs(cfg)
    blocks = {k: P("pp", *s) for k, s in base["blocks"].items()}
    replicated = {k: P() for k in base if k != "blocks"}
    return {**replicated, "blocks": blocks}


def _make_stage_fn(cfg: tfm.TransformerConfig, layers_per_stage: int):
    """One stage's forward: this device's layers over one microbatch
    activation — SHARED by the GPipe and 1F1B builders, so 'identical
    math between schedules' is true by construction, not by keeping two
    copies in sync.

    ``rng_mb``: this microbatch's dropout key (None when dropout is
    off). Each layer folds in its GLOBAL index, so key(mb, layer)
    matches the non-pipelined trunk's grad-accumulation schedule
    (make_train_step: fold_in(rng, mi) then encode's fold_in(·, li))."""
    def stage_fn(h, stage_blocks, stage, rng_mb):
        block = functools.partial(tfm._block, cfg=cfg, mesh=None)
        if cfg.remat:
            block = jax.checkpoint(block)
        first_layer = stage * layers_per_stage

        def body(carry, xs):
            h, aux = carry
            layer_params, li = xs
            rng = (None if rng_mb is None
                   else jax.random.fold_in(rng_mb, first_layer + li))
            h, a = block(h, layer_params, dropout_rng=rng)
            return (h, aux + a), None

        aux0 = pvary(jnp.zeros((), jnp.float32), ("pp",))
        (h, aux), _ = jax.lax.scan(
            body, (h, aux0), (stage_blocks, jnp.arange(layers_per_stage)))
        return h, aux

    return stage_fn


@functools.lru_cache(maxsize=8)
def zero1_pipeline_opt_specs(cfg: tfm.TransformerConfig, mesh: Mesh):
    """ZeRO-1 slot layout for pipeline params: each AdamW m/v leaf is
    additionally sharded over ``dp`` on its first free, dp-divisible dim
    (blocks keep their leading ``pp`` dim). Same recipe — and the same
    GSPMD-materialized reduce-scatter/sharded-update/all-gather dataflow
    — as ``transformer.zero1_opt_specs``; memory for optimizer state
    drops ~dp x with bit-identical step math. Cached per (cfg, mesh):
    both the step builder and ``shard_pipeline_opt_state`` need it, and
    the abstract init trace is pure in its arguments."""
    pp, dp = mesh.shape["pp"], mesh.shape["dp"]
    specs = pipeline_spec(cfg, pp)
    shapes = jax.eval_shape(lambda: {
        **(p := tfm.init_params(jax.random.PRNGKey(0), cfg)),
        "blocks": _stack_stages(p["blocks"], pp)})
    return jax.tree.map(
        lambda s, sh: tfm.shard_first_free_dim(s, sh, dp), specs, shapes,
        is_leaf=lambda x: isinstance(x, P))


def shard_pipeline_opt_state(opt_state, cfg: tfm.TransformerConfig,
                             mesh: Mesh, zero1: bool = False):
    """Place a pipeline optimizer state on the mesh (the ZeRO-1 layout
    when ``zero1`` — jit pins committed input shardings, so place the
    state before the first step)."""
    specs = (zero1_pipeline_opt_specs(cfg, mesh) if zero1
             else pipeline_spec(cfg, mesh.shape["pp"]))
    return tfm.place_opt_state(opt_state, specs, mesh)


def _wrap_step(step, cfg: tfm.TransformerConfig, mesh: Mesh, pp: int,
               use_dropout: bool, zero1: bool = False):
    """Shared jit wrapper for both schedule builders: identical
    shardings, donation, and the dropout arity switch — the two steps
    stay drop-in interchangeable (same input layouts) by construction."""
    specs = pipeline_spec(cfg, pp)
    pshard = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                          is_leaf=lambda x: isinstance(x, P))
    if zero1:
        oshard = jax.tree.map(lambda s: NamedSharding(mesh, s),
                              zero1_pipeline_opt_specs(cfg, mesh),
                              is_leaf=lambda x: isinstance(x, P))
    else:
        oshard = pshard
    opt_shard = {"m": oshard, "v": oshard, "t": NamedSharding(mesh, P())}
    data_shard = NamedSharding(mesh, P(None, "dp", None))
    in_sh = [pshard, opt_shard, data_shard, data_shard]
    if use_dropout:
        step_fn = step
        in_sh.append(NamedSharding(mesh, P()))
    else:
        # keep the historical 4-arg signature for deterministic configs
        step_fn = lambda params, opt_state, tokens, targets: step(  # noqa: E731
            params, opt_state, tokens, targets)
    return jax.jit(
        step_fn,
        in_shardings=tuple(in_sh),
        out_shardings=(NamedSharding(mesh, P()), pshard, opt_shard),
        donate_argnums=(0, 1),
    )


def make_pipeline_train_step(cfg: tfm.TransformerConfig, mesh: Mesh,
                             num_microbatches: int, lr: float = 1e-3,
                             aux_weight: float = 0.01,
                             zero1: bool = False):
    """Build the jitted GPipe step.

    tokens/targets: (M, mb, T) — M microbatches. Returns
    (loss, params, opt_state). ``zero1``: shard AdamW m/v over dp
    (place the state with ``shard_pipeline_opt_state(..., zero1=True)``
    before the first step; step math is bit-identical).
    """
    pp = mesh.shape["pp"]
    M = num_microbatches
    assert cfg.n_layers % pp == 0
    layers_per_stage = cfg.n_layers // pp
    use_dropout = cfg.dropout_rate > 0.0
    stage_fn = _make_stage_fn(cfg, layers_per_stage)

    def fwd_loss(params, tokens, targets, dropout_rng=None):
        """Pipelined forward + loss, manual over pp via shard_map."""
        stage_blocks = params["blocks"]  # (1, L/pp, ...) local slice per stage
        other = {k: v for k, v in params.items() if k != "blocks"}
        B, T = tokens.shape[1], tokens.shape[2]
        state0 = jnp.zeros((B, T, cfg.d_model), cfg.dtype)

        def pipelined(stage_blocks, other, tokens, targets, state0,
                      dropout_rng=None):
            # inside: manual over 'pp' — axis_index tells us our stage
            stage = jax.lax.axis_index("pp")
            local_blocks = jax.tree.map(lambda x: x[0], stage_blocks)

            perm = [(i, (i + 1) % pp) for i in range(pp)]
            n_ticks = M + pp - 1
            # carries vary per pp-shard: mark them 'varying' for the vma type
            # system before entering the scan
            varying = lambda x: pvary(x, ("pp",))
            state = varying(state0)
            loss_sum = varying(jnp.zeros((), jnp.float32))
            aux_sum = varying(jnp.zeros((), jnp.float32))

            def tick(carry, t):
                state, loss_sum, aux_sum = carry
                # stage 0 ingests microbatch t (if any); others use received
                mb_idx = jnp.clip(t, 0, M - 1)
                mb_tokens = jax.lax.dynamic_index_in_dim(
                    tokens, mb_idx, 0, keepdims=False)
                inject = tfm.embed_tokens(other, mb_tokens, cfg)
                state = jnp.where((stage == 0) & (t < M), inject, state)
                # the microbatch THIS stage is working on at tick t (garbage
                # outside the [stage, stage+M) window — its loss is never
                # taken, so the garbage dropout key is harmless)
                rng_mb = (None if dropout_rng is None
                          else jax.random.fold_in(
                              dropout_rng, jnp.clip(t - stage, 0, M - 1)))
                out, aux = stage_fn(state, local_blocks, stage, rng_mb)
                # this stage holds a real microbatch only during its window
                valid = (t >= stage) & (t < stage + M)
                aux_sum = aux_sum + jnp.where(valid, aux, 0.0)
                # last stage computes loss for the microbatch that has now
                # passed through all stages: microbatch t-(pp-1)
                done_idx = jnp.clip(t - (pp - 1), 0, M - 1)
                mb_targets = jax.lax.dynamic_index_in_dim(
                    targets, done_idx, 0, keepdims=False)
                mb_loss = tfm.nll_loss(tfm.lm_head(other, out, cfg),
                                       mb_targets)
                take = (stage == pp - 1) & (t >= pp - 1)
                loss_sum = loss_sum + jnp.where(take, mb_loss, 0.0)
                # advance activations to the next stage
                state = jax.lax.ppermute(out, "pp", perm)
                return (state, loss_sum, aux_sum), None

            (state, loss_sum, aux_sum), _ = jax.lax.scan(
                tick, (state, loss_sum, aux_sum), jnp.arange(n_ticks))
            # NLL lives on the last stage, aux is spread over stages; combine
            loss = jax.lax.psum(loss_sum, "pp") / M
            aux = jax.lax.psum(aux_sum, "pp") / M
            return loss + aux_weight * aux

        block_in_spec = jax.tree.map(lambda _: P("pp"), stage_blocks)
        other_spec = jax.tree.map(lambda _: P(), other)
        in_specs = [block_in_spec, other_spec, P(), P(), P()]
        args = [stage_blocks, other, tokens, targets, state0]
        if dropout_rng is not None:
            in_specs.append(P())
            args.append(dropout_rng)
        return shard_map(
            pipelined,
            mesh=mesh,
            in_specs=tuple(in_specs),
            out_specs=P(),
            axis_names=frozenset({"pp"}),
        )(*args)

    def step(params, opt_state, tokens, targets, dropout_rng=None):
        if use_dropout:
            # a forgotten key must not silently train WITHOUT dropout
            assert dropout_rng is not None, (
                "cfg.dropout_rate > 0: pass dropout_rng to the pipeline step")
        loss, grads = jax.value_and_grad(fwd_loss)(
            params, tokens, targets, dropout_rng=dropout_rng)
        new_params, new_opt = tfm.adamw_update(params, grads, opt_state, lr=lr)
        return loss, new_params, new_opt

    jitted = _wrap_step(step, cfg, mesh, pp, use_dropout, zero1=zero1)
    # the raw loss function, for grad-level parity tests against the 1F1B
    # twin (jax.grad(fwd_loss) is this schedule's exact gradient)
    jitted.fwd_loss = fwd_loss
    return jitted


def init_pipeline_params(rng, cfg: tfm.TransformerConfig, mesh: Mesh):
    pp = mesh.shape["pp"]
    params = tfm.init_params(rng, cfg)
    params = {**params, "blocks": _stack_stages(params["blocks"], pp)}
    specs = pipeline_spec(cfg, pp)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs)


# ---------------------------------------------------------------------------
# 1F1B (PipeDream-flush) schedule — beyond reference (the reference has only
# the GPipe all-forwards-then-all-backwards schedule, executor.py:675-746).
#
# Same math, different memory law: GPipe's one-scan forward stashes an
# activation per TICK for the outer jax.grad (peak ~ M + pp - 1 per stage);
# 1F1B hand-rolls the backward INSIDE the scan, so each stage keeps only a
# ring of at most ``pp`` stashed stage-INPUT activations and recomputes its
# block forward in the per-microbatch vjp (remat at stage granularity).
# Peak activation memory per stage drops from O(M) to O(pp) — the enabler
# for large microbatch counts, where GPipe's stash is the OOM.
# ---------------------------------------------------------------------------

def resolve_inflight_window(pp: int, max_inflight: int = None) -> int:
    """The one place the dual-slot window defaults to 2*pp — the
    simulator, the stats, and the step builder's ring depth must agree
    or the table and the activation ring drift apart. Only None means
    "default" (a former ``or`` silently turned an explicit 0 into 2*pp);
    sub-1 windows cannot schedule anything and are rejected."""
    window = 2 * pp if max_inflight is None else int(max_inflight)
    if window < 1:
        raise ValueError(f"max_inflight must be >= 1, got {max_inflight}")
    return window


def simulate_1f1b_schedule(pp: int, num_microbatches: int,
                           max_inflight: int = None):
    """Greedy dependency-driven 1F1B schedule table (host-side, static).

    DUAL-SLOT ticks: each tick, each stage may fire its next forward AND
    its next backward (the literal one-forward-one-backward) — the
    runtime tick body executes one fwd micro-op and one bwd micro-op
    anyway, so a denser table converts the masked lowering's idle halves
    into scheduled work and roughly halves the tick count
    (~M + 2(pp-1) ticks instead of ~2(M+pp-1)).

    Firing rules (producers move one hop per tick, so deps must be
    STRICTLY earlier; backpressure keeps the single-slot receive buffers
    and the pp-deep activation ring sound):
    - F(m) on stage s: upstream F(m) done earlier (s>0); downstream has
      consumed F(m-1) (send would overwrite its recv slot); in-flight
      microbatches (next_f - next_b) < max_inflight (default 2*pp —
      the activation-ring capacity).
    - B(m) on stage s: downstream B(m) done earlier (s<pp-1) or own F(m)
      done earlier (last stage); upstream has consumed B(m-1).

    ``max_inflight`` bounds each stage's un-backproped microbatches (its
    activation-ring depth). Default 2*pp: the backward round trip takes
    ~2*pp lockstep ticks, so a 2*pp window is what keeps BOTH slots busy
    in steady state — still O(pp) memory (vs GPipe's O(M)); pass pp for
    the classic minimum-memory 1F1B, which halves the steady-state duty
    cycle in this lockstep model.

    Returns ``table``: list over ticks of per-stage ``(fm, bm)`` pairs,
    each entry an int microbatch or None. Baked into the jitted step as
    constant arrays, so the runtime program is lockstep-static."""
    M = num_microbatches
    W = resolve_inflight_window(pp, max_inflight)
    next_f = [0] * pp
    next_b = [0] * pp
    fwd_done = [[None] * M for _ in range(pp)]
    bwd_done = [[None] * M for _ in range(pp)]
    table = []
    t = 0
    while any(next_b[s] < M for s in range(pp)):
        # Backpressure may be released by a SAME-tick consumption: the
        # receiver reads its single recv slot during its micro-op, and
        # the sender's replacement only lands at end-of-tick (ppermute) —
        # so "receiver consumed my previous send" includes this tick.
        # Evaluate receivers before senders so those credits are final:
        # B flows toward stage 0 (ascending order decides s-1 before s),
        # F flows toward stage pp-1 (descending decides s+1 before s).
        # B decisions also precede F: the runtime tick body runs the
        # backward micro-op FIRST, so a same-tick B frees its ring slot
        # (and its window unit) for the same-tick F.
        brow = [None] * pp
        for s in range(pp):
            m = next_b[s]
            if m < M:
                if s == pp - 1:
                    ready = (fwd_done[s][m] is not None
                             and fwd_done[s][m] < t)
                else:
                    ready = (bwd_done[s + 1][m] is not None
                             and bwd_done[s + 1][m] < t)
                if ready and s > 0 and m > 0:
                    ready = (brow[s - 1] == m - 1
                             or (bwd_done[s - 1][m - 1] is not None
                                 and bwd_done[s - 1][m - 1] <= t))
                if ready:
                    brow[s] = m
        frow = [None] * pp
        for s in range(pp - 1, -1, -1):
            m = next_f[s]
            inflight = (next_f[s] - next_b[s]
                        - (1 if brow[s] is not None else 0))
            if m < M and inflight < W:
                ready = s == 0 or (fwd_done[s - 1][m] is not None
                                   and fwd_done[s - 1][m] < t)
                if ready and s < pp - 1 and m > 0:
                    ready = (frow[s + 1] == m - 1
                             or (fwd_done[s + 1][m - 1] is not None
                                 and fwd_done[s + 1][m - 1] <= t))
                if ready:
                    frow[s] = m
        row = list(zip(frow, brow))
        fired = False
        for s, (fm, bm) in enumerate(row):
            if fm is not None:
                fwd_done[s][fm] = t
                next_f[s] += 1
                fired = True
            if bm is not None:
                bwd_done[s][bm] = t
                next_b[s] += 1
                fired = True
        assert fired, f"1F1B schedule deadlock at tick {t} (pp={pp}, M={M})"
        table.append(row)
        t += 1
    return table


def schedule_stats(pp: int, num_microbatches: int,
                   max_inflight: int = None) -> dict:
    """Per-stage bubble accounting for both schedules (printed by the
    dryrun; the numbers a pipeline tuning session starts from).

    - gpipe: one fwd wave of M+pp-1 ticks and its autodiff mirror; every
      stage is busy M of each wave -> bubble = (pp-1)/(M+pp-1). Peak
      activation stash per stage ~ one per TICK (the scan saves its
      carry for the outer grad): M + pp - 1.
    - 1f1b: measured on the simulated table; peak stash is the ring
      high-water mark of in-flight (forwarded, not-yet-backproped)
      microbatches — bounded by max_inflight (default 2*pp)."""
    M = num_microbatches
    table = simulate_1f1b_schedule(pp, M, max_inflight)
    n_ticks = len(table)
    busy = [0] * pp          # ops fired per stage (out of 2 slots/tick)
    inflight = [0] * pp
    peak = [0] * pp
    for row in table:
        for s, (fm, bm) in enumerate(row):
            # B first, like the runtime tick body: a same-tick B frees
            # its ring slot before the F stashes into it
            if bm is not None:
                busy[s] += 1
                inflight[s] -= 1
            if fm is not None:
                busy[s] += 1
                inflight[s] += 1
                peak[s] = max(peak[s], inflight[s])
    g_ticks = M + pp - 1
    return {
        "gpipe": {"ticks_per_wave": g_ticks,
                  "bubble_fraction": round((pp - 1) / g_ticks, 4),
                  "peak_act_stash_per_stage": g_ticks},
        "1f1b": {"ticks": n_ticks,
                 "per_stage_busy": busy,
                 # each tick offers an F and a B slot; unused slots are
                 # the bubble (what the masked lowering pays for)
                 "bubble_fraction": round(
                     1.0 - sum(busy) / (2.0 * pp * n_ticks), 4),
                 "peak_act_stash_per_stage": max(peak)},
    }


def make_pipeline_train_step_1f1b(cfg: tfm.TransformerConfig, mesh: Mesh,
                                  num_microbatches: int, lr: float = 1e-3,
                                  aux_weight: float = 0.01,
                                  zero1: bool = False,
                                  predication: str = "masked",
                                  max_inflight: int = None):
    """1F1B twin of ``make_pipeline_train_step`` — same signature plus
    the 1F1B-only ``predication`` knob, identical math (bit-matching
    dropout keys per (microbatch, layer)), different memory law (see
    module section comment).

    Mechanics: one ``lax.scan`` over the simulated schedule's ticks inside
    a ``shard_map`` manual over ``pp``. Each tick, each stage runs its
    scheduled micro-op (``predication``: "masked" default — computed
    everywhere, effects selected; "cond" opt-in — lax.cond branches,
    idle ticks free, but see the lowering comment below for why that is
    only sound when no GSPMD collective lands inside a branch), then
    activations hop forward and gradients hop backward via two
    unconditional ``ppermute``s. The backward micro-op re-runs the stage
    forward from the stashed stage INPUT under ``jax.vjp``
    (stage-granular remat) — the last stage differentiates through the
    head+NLL with cotangent 1/M, others seed with the grad received from
    downstream."""
    pp = mesh.shape["pp"]
    M = num_microbatches
    assert cfg.n_layers % pp == 0
    layers_per_stage = cfg.n_layers // pp
    use_dropout = cfg.dropout_rate > 0.0
    # Micro-op gating has two lowerings. "masked" (the default) computes
    # every micro-op on every device and selects effects by the schedule
    # — idle ticks cost FLOPs, but every GSPMD-inserted collective runs
    # on every device's path. "cond" puts the micro-ops behind lax.cond
    # (idle ticks free) but is UNSOUND whenever GSPMD lowers ANY inner
    # op to a collective, because stages diverge on the predicate and
    # the collective's peers never arrive: observed deadlocks include tp
    # all-reduces of the Megatron matmuls, AND — even on a pure dp x pp
    # mesh — a reshard collective-permute GSPMD inserted for the
    # pos-table gradient when max_seq_len > T. Since GSPMD's choices
    # aren't statically checkable here, cond is opt-in for configs the
    # caller has validated; it additionally refuses model axes outright.
    assert predication in ("masked", "cond"), predication
    use_cond = predication == "cond"
    if use_cond:
        assert (mesh.shape.get("tp", 1) * mesh.shape.get("sp", 1)
                * mesh.shape.get("ep", 1)) == 1, (
            "predication='cond' deadlocks with tp/sp/ep in the mesh "
            "(GSPMD collectives inside divergent branches)")

    W = resolve_inflight_window(pp, max_inflight)
    table = simulate_1f1b_schedule(pp, M, W)
    ring = min(W, M)   # activation stash depth per stage (the memory law)
    n_ticks = len(table)
    is_f = np.zeros((n_ticks, pp), np.bool_)
    f_mb = np.zeros((n_ticks, pp), np.int32)
    is_b = np.zeros((n_ticks, pp), np.bool_)
    b_mb = np.zeros((n_ticks, pp), np.int32)
    for t, row in enumerate(table):
        for s, (fm, bm) in enumerate(row):
            if fm is not None:
                is_f[t, s], f_mb[t, s] = True, fm
            if bm is not None:
                is_b[t, s], b_mb[t, s] = True, bm

    stage_fn = _make_stage_fn(cfg, layers_per_stage)

    def fwd_bwd(params, tokens, targets, dropout_rng=None):
        """Fused pipelined forward+backward: returns (loss, grads)."""
        stage_blocks = params["blocks"]
        other = {k: v for k, v in params.items() if k != "blocks"}
        B, T = tokens.shape[1], tokens.shape[2]
        # the second OBSERVED cond deadlock is checkable here: with
        # max_seq_len > T, GSPMD lowers the pos-table slice/grad to a
        # reshard collective-permute inside the stage-0 branch
        assert not (use_cond and cfg.use_pos_emb and cfg.max_seq_len > T), (
            "predication='cond' deadlocks when max_seq_len > T with a "
            "positional table (GSPMD reshard inside a divergent branch); "
            "use the masked default or set max_seq_len == T")

        tis_f, tf_mb = jnp.asarray(is_f), jnp.asarray(f_mb)
        tis_b, tb_mb = jnp.asarray(is_b), jnp.asarray(b_mb)

        def pipelined(stage_blocks, other, tokens, targets, dropout_rng=None):
            stage = jax.lax.axis_index("pp")
            local_blocks = jax.tree.map(lambda x: x[0], stage_blocks)
            perm_f = [(i, (i + 1) % pp) for i in range(pp)]
            perm_b = [(i, (i - 1) % pp) for i in range(pp)]
            varying = lambda x: pvary(x, ("pp",))

            zero_act = jnp.zeros((B, T, cfg.d_model), cfg.dtype)
            carry0 = (
                varying(jnp.zeros((ring, B, T, cfg.d_model), cfg.dtype)),
                varying(zero_act),                       # recv_f
                varying(zero_act),                       # recv_b
                # zeros_like(local_blocks) is born varying (sliced from the
                # pp-sharded input); zeros_like(other) is born invariant
                jax.tree.map(jnp.zeros_like, local_blocks),   # g_blocks
                jax.tree.map(lambda x: varying(jnp.zeros_like(x)), other),
                varying(jnp.zeros((), jnp.float32)),     # loss_sum
                varying(jnp.zeros((), jnp.float32)),     # aux_sum
            )

            def mb_rng(m):
                return (None if dropout_rng is None
                        else jax.random.fold_in(dropout_rng, m))

            def tick(carry, t):
                act_buf, recv_f, recv_b, g_blocks, g_other, loss_sum, \
                    aux_sum = carry
                isf = jax.lax.dynamic_index_in_dim(
                    jax.lax.dynamic_index_in_dim(tis_f, t, 0, False),
                    stage, 0, False)
                fm = jax.lax.dynamic_index_in_dim(
                    jax.lax.dynamic_index_in_dim(tf_mb, t, 0, False),
                    stage, 0, False)
                isb = jax.lax.dynamic_index_in_dim(
                    jax.lax.dynamic_index_in_dim(tis_b, t, 0, False),
                    stage, 0, False)
                bm = jax.lax.dynamic_index_in_dim(
                    jax.lax.dynamic_index_in_dim(tb_mb, t, 0, False),
                    stage, 0, False)

                # ---- backward micro-op FIRST (stage-granular remat vjp):
                # it reads the ring slot its microbatch stashed earlier,
                # and the same-tick forward may REUSE that slot (the
                # schedule's window credit assumes this B-before-F order)
                # shared preamble: cheap ring/table reads and the ONE
                # function both lowerings differentiate — defined once so
                # the cond and masked paths cannot drift apart.
                # ``other_v``: differentiate wrt a VARYING copy of the
                # replicated params — the vjp of an invariant input would
                # insert a psum (a collective inside a cond branch, where
                # idle stages never arrive -> deadlock). The per-stage
                # partial grads are psum'd once, outside the scan.
                h_in_b = jax.lax.dynamic_index_in_dim(act_buf, bm % ring,
                                                      0, False)
                tgt_m = jax.lax.dynamic_index_in_dim(targets, bm, 0, False)
                tok_b = jax.lax.dynamic_index_in_dim(tokens, bm, 0, False)
                rng_b = mb_rng(bm)
                is_last = stage == pp - 1
                other_v = jax.tree.map(varying, other)

                def through_head(blocks_, other_, h_):
                    h2, aux2 = stage_fn(h_, blocks_, stage, rng_b)
                    nll = tfm.nll_loss(tfm.lm_head(other_, h2, cfg), tgt_m)
                    return h2, aux2, nll

                def embed_grads(dh):
                    """d(embed output)/d(other) applied to dh — stage 0's
                    dh is the grad of the embedding output."""
                    _, evjp = jax.vjp(
                        lambda o: tfm.embed_tokens(o, tok_b, cfg), other_v)
                    (de,) = evjp(dh)
                    return de

                def do_bwd(g_blocks, g_other, recv_b, loss_sum):
                    def mid_only(blocks_, other_, h_):
                        h2, aux2 = stage_fn(h_, blocks_, stage, rng_b)
                        # varying like through_head's nll, so both cond
                        # branches type-match and take the same cotangent
                        return h2, aux2, varying(jnp.zeros((), jnp.float32))

                    def run_vjp(fn, ct_h2, ct_nll):
                        (h2, aux2, nll), vjp = jax.vjp(fn, local_blocks,
                                                       other_v, h_in_b)
                        # cotangents must carry the same varying-over-pp
                        # vma type as the outputs they correspond to
                        db, dother, dh = vjp(
                            (ct_h2,
                             varying(jnp.full((), aux_weight / M,
                                              jnp.float32)),
                             varying(ct_nll)))
                        return db, dother, dh, nll

                    db, dother, dh, nll = jax.lax.cond(
                        is_last,
                        lambda: run_vjp(through_head,
                                        jnp.zeros_like(recv_b),
                                        jnp.full((), 1.0 / M, jnp.float32)),
                        lambda: run_vjp(mid_only, recv_b,
                                        jnp.zeros((), jnp.float32)))
                    dother = jax.lax.cond(
                        stage == 0,
                        lambda d: jax.tree.map(jnp.add, d, embed_grads(dh)),
                        lambda d: d, dother)
                    g_blocks = jax.tree.map(jnp.add, g_blocks, db)
                    g_other = jax.tree.map(jnp.add, g_other, dother)
                    loss_sum = loss_sum + jnp.where(is_last, nll / M, 0.0)
                    send_b = jnp.where(stage == 0, jnp.zeros_like(dh), dh)
                    return g_blocks, g_other, send_b, loss_sum

                def do_bwd_masked(g_blocks, g_other, recv_b, loss_sum):
                    """Branch-free twin of do_bwd: ONE vjp through the
                    head for every stage with where-selected cotangents
                    (vjp is linear in cotangents, so ct_nll=0 makes the
                    head contribution exactly zero for middle stages),
                    embedding vjp always computed, all effects masked by
                    isb/stage. Costs head FLOPs on every stage but keeps
                    every GSPMD-inserted tp/dp collective on every
                    device's path."""
                    (h2, aux2, nll), vjp = jax.vjp(through_head,
                                                   local_blocks, other_v,
                                                   h_in_b)
                    ct_h2 = jnp.where(is_last, jnp.zeros_like(recv_b),
                                      recv_b)
                    # already varying: is_last derives from axis_index
                    ct_nll = jnp.where(is_last, 1.0 / M,
                                       0.0).astype(jnp.float32)
                    db, dother, dh = vjp(
                        (ct_h2,
                         varying(jnp.full((), aux_weight / M, jnp.float32)),
                         ct_nll))
                    de = embed_grads(dh)
                    dother = jax.tree.map(
                        lambda a, e: a + jnp.where(stage == 0, e,
                                                   jnp.zeros_like(e)),
                        dother, de)
                    g_blocks = jax.tree.map(
                        lambda g, d: g + jnp.where(isb, d,
                                                   jnp.zeros_like(d)),
                        g_blocks, db)
                    g_other = jax.tree.map(
                        lambda g, d: g + jnp.where(isb, d,
                                                   jnp.zeros_like(d)),
                        g_other, dother)
                    loss_sum = loss_sum + jnp.where(isb & is_last,
                                                    nll / M, 0.0)
                    send_b = jnp.where(isb & (stage > 0), dh,
                                       jnp.zeros_like(dh))
                    return g_blocks, g_other, send_b, loss_sum

                if use_cond:
                    g_blocks, g_other, send_b, loss_sum = jax.lax.cond(
                        isb, do_bwd,
                        lambda gb, go, rb, ls: (gb, go, jnp.zeros_like(rb),
                                                ls),
                        g_blocks, g_other, recv_b, loss_sum)
                else:
                    g_blocks, g_other, send_b, loss_sum = do_bwd_masked(
                        g_blocks, g_other, recv_b, loss_sum)

                # ---- forward micro-op -------------------------------
                def do_fwd(act_buf, recv_f, aux_sum):
                    tok_m = jax.lax.dynamic_index_in_dim(tokens, fm, 0,
                                                         False)
                    h0 = tfm.embed_tokens(other, tok_m, cfg)
                    h_in = jnp.where(stage == 0, h0, recv_f)
                    h_out, aux = stage_fn(h_in, local_blocks, stage,
                                          mb_rng(fm))
                    act_buf = jax.lax.dynamic_update_index_in_dim(
                        act_buf, h_in, fm % ring, 0)
                    return act_buf, h_out, aux_sum + aux

                if use_cond:
                    # real branch: idle ticks are free
                    act_buf, send_f, aux_sum = jax.lax.cond(
                        isf, do_fwd,
                        lambda ab, rf, ax: (ab, jnp.zeros_like(rf), ax),
                        act_buf, recv_f, aux_sum)
                else:
                    # masked: compute unconditionally, select the effect
                    nb, h_out, na = do_fwd(act_buf, recv_f, aux_sum)
                    act_buf = jnp.where(isf, nb, act_buf)
                    send_f = jnp.where(isf, h_out, jnp.zeros_like(h_out))
                    aux_sum = jnp.where(isf, na, aux_sum)

                # ---- unconditional hops (collectives stay out of conds).
                # Receives are STICKY: a hop only replaces the buffer when
                # the sender actually sent this tick (flag rides along),
                # so an idle sender's zeros can't clobber an activation the
                # receiver consumes on a later tick. The schedule's
                # backpressure rule guarantees one slot suffices.
                sent_f = jnp.where(isf & (stage < pp - 1), 1.0, 0.0)
                sent_b = jnp.where(isb & (stage > 0), 1.0, 0.0)
                got_f = jax.lax.ppermute(sent_f, "pp", perm_f)
                got_b = jax.lax.ppermute(sent_b, "pp", perm_b)
                new_f = jax.lax.ppermute(send_f, "pp", perm_f)
                new_b = jax.lax.ppermute(send_b, "pp", perm_b)
                recv_f = jnp.where(got_f > 0, new_f, recv_f)
                recv_b = jnp.where(got_b > 0, new_b, recv_b)
                return (act_buf, recv_f, recv_b, g_blocks, g_other,
                        loss_sum, aux_sum), None

            carry, _ = jax.lax.scan(tick, carry0, jnp.arange(n_ticks))
            _, _, _, g_blocks, g_other, loss_sum, aux_sum = carry
            loss = jax.lax.psum(loss_sum, "pp")        # lives on last stage
            aux = jax.lax.psum(aux_sum, "pp") / M
            g_other = jax.tree.map(lambda g: jax.lax.psum(g, "pp"), g_other)
            g_blocks = jax.tree.map(lambda g: g[None], g_blocks)
            return loss + aux_weight * aux, g_blocks, g_other

        block_in_spec = jax.tree.map(lambda _: P("pp"), stage_blocks)
        other_spec = jax.tree.map(lambda _: P(), other)
        in_specs = [block_in_spec, other_spec, P(), P()]
        args = [stage_blocks, other, tokens, targets]
        if dropout_rng is not None:
            in_specs.append(P())
            args.append(dropout_rng)
        loss, g_blocks, g_other = shard_map(
            pipelined,
            mesh=mesh,
            in_specs=tuple(in_specs),
            out_specs=(P(), block_in_spec, other_spec),
            axis_names=frozenset({"pp"}),
        )(*args)
        return loss, {**g_other, "blocks": g_blocks}

    def step(params, opt_state, tokens, targets, dropout_rng=None):
        if use_dropout:
            assert dropout_rng is not None, (
                "cfg.dropout_rate > 0: pass dropout_rng to the pipeline step")
        loss, grads = fwd_bwd(params, tokens, targets,
                              dropout_rng=dropout_rng)
        new_params, new_opt = tfm.adamw_update(params, grads, opt_state,
                                               lr=lr)
        return loss, new_params, new_opt

    jitted = _wrap_step(step, cfg, mesh, pp, use_dropout, zero1=zero1)
    # the hand-rolled (loss, grads) function, for grad-level parity tests
    # against jax.grad of the GPipe twin's fwd_loss
    jitted.fwd_bwd = fwd_bwd
    return jitted
