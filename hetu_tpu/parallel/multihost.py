"""Multi-host distributed backend — scale-out across processes/hosts.

The reference bootstraps its multi-node world with MPI (rank discovery by
hostname hashing, ``communicator/mpi_nccl_comm.py:114-134``), builds NCCL
communicators over it, and launches ranks with ``mpirun``
(``python/runner.py:204``). The TPU-native equivalent is JAX's coordination
service: one process per host joins via ``jax.distributed`` (gRPC over DCN),
after which ``jax.devices()`` is the GLOBAL device list and one
``jax.sharding.Mesh`` spans every chip in the job — GSPMD collectives ride
ICI inside a slice and DCN across slices, no hand-written communicator layer.

``heturun`` (hetu_tpu/runner.py) exports ``JAX_COORDINATOR_ADDRESS`` /
``JAX_NUM_PROCESSES`` / ``JAX_PROCESS_ID`` to each remote worker;
``initialize()`` consumes them. On real TPU pods the three values are
auto-detected from the pod metadata and may all be omitted.

Off-TPU (CI, the virtual-mesh tests), the same path runs with multiple CPU
processes: each process provisions ``local_device_count`` virtual CPU
devices and cross-process collectives go through Gloo. This mirrors the
reference's local-process-cluster test strategy (SURVEY.md §4) at the
multi-HOST level.
"""
from __future__ import annotations

import os
from typing import Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_initialized = False


def is_initialized() -> bool:
    return _initialized


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               local_device_count: Optional[int] = None,
               auto_detect: bool = False) -> bool:
    """Join (or create) the multi-process JAX world. Idempotent.

    Args fall back to the env vars exported by ``heturun``
    (``JAX_COORDINATOR_ADDRESS``, ``JAX_NUM_PROCESSES``, ``JAX_PROCESS_ID``);
    with none present and no args this is a single-process no-op (returns
    False) so scripts can call it unconditionally. On a real TPU pod slice
    pass ``auto_detect=True`` (or set ``HETU_MULTIHOST=auto``): the three
    values then come from the pod metadata via no-arg
    ``jax.distributed.initialize()``.

    ``local_device_count``: CI/testing mode — FORCES a virtual-CPU Gloo
    world with this many devices per process (the multi-host analogue of the
    test suite's virtual 8-device mesh). Never pass it on real TPUs; it is
    mutually exclusive with ``auto_detect``.
    """
    global _initialized
    if _initialized:
        return True
    auto_detect = auto_detect or os.environ.get("HETU_MULTIHOST") == "auto"
    if auto_detect and local_device_count is not None:
        raise ValueError(
            "local_device_count forces a virtual-CPU world and cannot be "
            "combined with auto_detect (TPU pod metadata)")
    coordinator_address = (coordinator_address
                           or os.environ.get("JAX_COORDINATOR_ADDRESS"))
    if num_processes is None and "JAX_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and "JAX_PROCESS_ID" in os.environ:
        process_id = int(os.environ["JAX_PROCESS_ID"])
    if coordinator_address is None and num_processes is None and not auto_detect:
        if local_device_count is not None:
            raise ValueError(
                "local_device_count was given but no coordinator/world was "
                "specified (args, JAX_* env, or auto_detect) — for a "
                "single-process virtual mesh use hetu_tpu.utils."
                "ensure_devices instead")
        return False

    if local_device_count is not None:
        # must happen before the backend initializes; a sitecustomize may pin
        # another platform, so config updates, not env vars (see conftest)
        jax.config.update("jax_platforms", "cpu")
        try:
            jax.config.update("jax_num_cpu_devices", int(local_device_count))
        except AttributeError:
            # jax 0.4.x predates the config option; the XLA flag read at
            # backend init is its exact equivalent (backend not yet live
            # here — initialize() is the process's first jax touch)
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count="
                  f"{int(local_device_count)}")
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    _initialized = True
    return True


def shutdown() -> None:
    global _initialized
    if _initialized:
        jax.distributed.shutdown()
        _initialized = False


def global_mesh(dp: int = 0, pp: int = 1, tp: int = 1, sp: int = 1,
                ep: int = 1) -> Mesh:
    """A mesh over EVERY device in the job (all processes). ``dp=0`` means
    "fill dp with whatever remains after the model axes" — the common case
    where adding hosts grows the data-parallel degree."""
    from .mesh import auto_mesh, make_mesh
    if dp == 0:
        return auto_mesh(tp=tp, pp=pp, sp=sp, ep=ep)
    return make_mesh(dp=dp, pp=pp, tp=tp, sp=sp, ep=ep, devices=jax.devices())


def host_local_batch(mesh: Mesh, spec: P, host_data: np.ndarray):
    """Assemble a GLOBAL array from this process's local shard of the batch.

    Each process feeds only the rows its own devices will hold (the
    reference's dataloader rank-sharding, ``dataloader.py:19-24``, lifted to
    host granularity); no cross-host data movement happens here.
    """
    return jax.make_array_from_process_local_data(
        NamedSharding(mesh, spec), np.asarray(host_data))


def barrier(name: str = "hetu_barrier",
            deadline_s: Optional[float] = None) -> None:
    """Block until every process arrives (reference: PS worker barrier /
    MPI_Barrier).

    ``deadline_s`` arms a one-shot hang watchdog around the wait: a barrier
    a dead peer will never reach dumps thread stacks and aborts with
    ``resilience.EXIT_WATCHDOG`` instead of hanging the job forever (the
    supervising launcher then restarts from the latest checkpoint)."""
    from jax.experimental import multihost_utils
    if deadline_s is None:
        multihost_utils.sync_global_devices(name)
        return
    from ..resilience import Watchdog
    with Watchdog(deadline_s) as wd:
        wd.beat(phase=f"barrier:{name}")
        multihost_utils.sync_global_devices(name)


def any_process_flag(flag) -> bool:
    """True iff ANY process passed a truthy flag — the coordinated-decision
    primitive for preemption (one host gets SIGTERM; every host must join
    the emergency checkpoint at the same step or the collective write
    deadlocks). Plain local bool outside a multi-process world."""
    if not _initialized or jax.process_count() <= 1:
        return bool(flag)
    flags = process_allgather(np.asarray(bool(flag), np.int32))
    return bool(np.max(flags) > 0)


def process_allgather(x):
    """Gather a host-local value from every process (returns stacked array on
    each host). Reference analogue: MPI allgather on the CPU world."""
    from jax.experimental import multihost_utils
    return multihost_utils.process_allgather(x)


def broadcast_from_chief(x):
    """Replicate chief's (process 0's) host value to every process — e.g. a
    seed or a config blob decided at rank 0."""
    from jax.experimental import multihost_utils
    return multihost_utils.broadcast_one_to_all(x)


def fetch_replicated(garr) -> np.ndarray:
    """Bring a global array to the host as numpy, same shape whether this
    process holds every shard (single-process / fully-addressable) or not
    (multi-host, where the value is first replicated across processes)."""
    if garr.is_fully_addressable:
        return np.asarray(jax.device_get(garr))
    from jax.experimental import multihost_utils
    return np.asarray(multihost_utils.process_allgather(garr, tiled=True))


def local_devices() -> Sequence:
    return jax.local_devices()


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()
