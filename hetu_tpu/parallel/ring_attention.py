"""Ring attention: exact causal attention over a sequence-sharded mesh axis.

Long-context capability beyond the reference (its longest-sequence support is
a plain BatchMatMul transformer, SURVEY §5): the sequence axis is sharded
over the mesh's ``sp`` axis; k/v chunks rotate around the ring via
``lax.ppermute`` over ICI while each device accumulates its q-chunk's output
with a log-sum-exp merge — no device ever holds the full sequence, and
compute overlaps the rotation (XLA schedules the ppermute DMA against the
local block's matmuls).

Differentiable: autodiff flows through ppermute (its transpose is the
reverse rotation). Each step is rematerialized (jax.checkpoint) so the
backward's live set is one k/v chunk, matching flash-attention scaling.

Usage inside shard_map (q/k/v already sequence-sharded on ``axis_name``):
    out = ring_attention(q, k, v, axis_name="sp", causal=True)
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def _block_attend(q, k, v, scale, mask, k_bias=None):
    """One (local_q x chunk_k) attention block.

    ``k_bias``: optional (b, chunk_k) additive per-key bias (key-padding
    form, 0 valid / -1e9 padded), applied before the causal mask.
    Returns (out, lse): ``out`` is the chunk-local softmax(s) @ v (normalized
    within the chunk) and ``lse`` its log-sum-exp, so two results combine
    exactly as out_new = Σ out_c * exp(lse_c - logaddexp(lse...))."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if k_bias is not None:
        s = s + k_bias.astype(jnp.float32)[:, None, None, :]
    s = jnp.where(mask, s, _NEG_INF)
    m = jnp.max(s, axis=-1)                                  # (b,h,q)
    # rows with no visible keys: exp(-inf - -inf) guards via max clamp
    m_safe = jnp.maximum(m, _NEG_INF / 2)
    p = jnp.exp(s - m_safe[..., None])
    l = jnp.sum(p, axis=-1)
    num = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    out = num / jnp.maximum(l, 1e-30)[..., None]
    lse = jnp.where(l > 0, m_safe + jnp.log(jnp.maximum(l, 1e-30)), _NEG_INF)
    return out, lse


def _merge(acc_num, acc_lse, num, lse):
    """Log-sum-exp merge of two partial attention results."""
    new_lse = jnp.logaddexp(acc_lse, lse)
    a = jnp.exp(acc_lse - new_lse)
    b = jnp.exp(lse - new_lse)
    return acc_num * a[..., None] + num * b[..., None], new_lse


def ring_attention(q, k, v, k_bias=None, *, axis_name: str,
                   causal: bool = True, scale: float | None = None):
    """q/k/v: (batch, heads, local_seq, head_dim), sequence-sharded over
    ``axis_name``; ``k_bias``: optional (batch, local_seq) per-key additive
    bias, sharded like k's sequence axis — it rotates around the ring with
    its k/v chunk. Returns the local output chunk."""
    n_dev = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    local_s = q.shape[2]
    b, h = q.shape[0], q.shape[1]

    q_pos = my_idx * local_s + jnp.arange(local_s)            # absolute rows

    use_bias = k_bias is not None

    @functools.partial(jax.checkpoint, prevent_cse=False)
    def step_compute(q, k_chunk, bias_chunk, src_idx, acc_num, acc_lse):
        k_pos = src_idx * local_s + jnp.arange(local_s)
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
        else:
            mask = jnp.ones((local_s, local_s), bool)
        num, lse = _block_attend(q, k_chunk[0], k_chunk[1], scale,
                                 mask[None, None], bias_chunk)
        return _merge(acc_num, acc_lse, num, lse)

    def body(carry, _):
        kv, bias, src_idx, acc_num, acc_lse = carry
        acc_num, acc_lse = step_compute(q, kv, bias, src_idx, acc_num,
                                        acc_lse)
        # rotate: receive the previous device's chunk (ring over ICI);
        # the bias column travels with its k/v chunk
        perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]
        kv_next = jax.lax.ppermute(kv, axis_name, perm)
        bias_next = (jax.lax.ppermute(bias, axis_name, perm) if use_bias
                     else bias)
        src_next = jax.lax.ppermute(src_idx, axis_name, perm)
        return (kv_next, bias_next, src_next, acc_num, acc_lse), None

    # derive the accumulators from q so they carry the same device-varying
    # manual axes as the per-step outputs (scan requires matching carry types
    # under shard_map)
    acc_num = jnp.zeros_like(q, jnp.float32) + 0.0 * q.astype(jnp.float32)
    acc_lse = jnp.sum(0.0 * q.astype(jnp.float32), axis=-1) + _NEG_INF
    kv0 = jnp.stack([k.astype(jnp.float32), v.astype(jnp.float32)])
    bias0 = k_bias.astype(jnp.float32) if use_bias else None
    src0 = jnp.asarray(my_idx, jnp.int32)
    (_, _, _, acc_num, acc_lse), _ = jax.lax.scan(
        body, (kv0, bias0, src0, acc_num, acc_lse), None, length=n_dev)

    # rows with zero visible keys (none under causal with self-block) -> 0
    safe = acc_lse > _NEG_INF / 2
    out = jnp.where(safe[..., None], acc_num, 0.0)
    return out.astype(q.dtype)
