"""DistGCN 1.5D hybrid-parallel sparse matmul (reference
``gpu_ops/DistGCN_15d.py:19-60``).

The reference's algorithm on ``size`` GPUs with replication factor ``r``:
the adjacency matrix is row-partitioned over ``size/r`` row shards and its
contraction (column) range is split over ``r`` replicas; each step of the
stage loop **broadcasts** one feature block within a column group
(``col_groups[rank_col].dlarrayBroadcast``), accumulates a local ``csrmm``
over that block, and finally **all-reduces** the partial products across the
row group (``row_groups[rank_c].dlarrayNcclAllReduce``).

TPU-native redesign: the same movement expressed over a 2-axis device mesh
``(gr=size/r, gc=r)`` inside one ``shard_map``:

- features ``H`` are row-sharded over BOTH axes (gc-major, matching the
  reference's global row partition over all ``size`` processes);
- ``all_gather(H, 'gr')`` materializes exactly the column slice the stage
  loop's broadcasts deliver (same bytes, one fused ICI collective instead of
  ``stages`` point broadcasts);
- each device multiplies its local COO block (rows = its gr shard, columns =
  its gc slice) against the gathered slice;
- ``psum(partial, 'gc')`` is the row-group allreduce.

XLA lowers the gather/psum to ICI collectives and overlaps them with the
segment-sum compute — the scheduling the reference hand-writes with streams.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ..utils import shard_map


def partition_adjacency(rows: np.ndarray, cols: np.ndarray,
                        values: np.ndarray, n_nodes: int,
                        gr: int, gc: int):
    """Partition a COO adjacency for the (gr, gc) mesh.

    Returns ``(vals, local_rows, local_cols)`` each shaped
    ``(gr, gc, nnz_max)`` — device (i, j) owns entries with
    ``row in [i*Nr, (i+1)*Nr)`` and ``col in [j*Nc, (j+1)*Nc)``, with local
    indices. Zero-padded to the max block nnz (padded entries have value 0
    and indices 0, contributing nothing to the segment sum).
    """
    assert n_nodes % gr == 0 and n_nodes % gc == 0, \
        "pad the graph so n_nodes divides both mesh axes"
    nr, nc = n_nodes // gr, n_nodes // gc
    # single sort pass instead of gr*gc boolean scans of the nnz arrays
    bi = rows // nr
    bj = cols // nc
    order = np.lexsort((bj, bi))
    rows, cols, values = rows[order], cols[order], values[order]
    block_key = bi[order] * gc + bj[order]
    splits = np.searchsorted(block_key, np.arange(gr * gc + 1))
    counts = np.diff(splits)
    nnz_max = int(counts.max()) if counts.size else 0
    vals = np.zeros((gr, gc, nnz_max), np.float32)
    lrows = np.zeros((gr, gc, nnz_max), np.int32)
    lcols = np.zeros((gr, gc, nnz_max), np.int32)
    for k in range(gr * gc):
        i, j = divmod(k, gc)
        lo, hi = splits[k], splits[k + 1]
        vals[i, j, :hi - lo] = values[lo:hi]
        lrows[i, j, :hi - lo] = rows[lo:hi] - i * nr
        lcols[i, j, :hi - lo] = cols[lo:hi] - j * nc
    return vals, lrows, lcols


def spmm_15d(mesh: Mesh, adj_parts, h, n_nodes: int,
             gr_axis: str = "gr", gc_axis: str = "gc"):
    """``Z = A @ H`` with the 1.5D schedule on ``mesh``.

    ``adj_parts``: output of :func:`partition_adjacency`, device-put with
    leading dims sharded ``P(gr_axis, gc_axis)``. ``h``: (N, F) sharded
    ``P((gc_axis, gr_axis), None)``. Returns Z with the same sharding as h's
    row partition over gr (replicated over gc).
    """
    gr = mesh.shape[gr_axis]
    nr = n_nodes // gr

    def local(vals, lrows, lcols, h_local):
        from ..kernels import csr_spmm
        vals, lrows, lcols = vals[0, 0], lrows[0, 0], lcols[0, 0]
        # the column-group broadcast stages: one tiled all_gather over gr
        h_slice = jax.lax.all_gather(h_local, gr_axis, axis=0, tiled=True)
        # hetukern csr_spmm (docs/KERNELS.md): the local block product goes
        # through the kernel registry — inside this shard_map the named-axis
        # eligibility guard keeps auto mode on the gather+segment_sum
        # fallback (identical to the pre-hetukern expression)
        z = csr_spmm.coo_matmat(vals, lrows, lcols, nr, h_slice)
        # the row-group allreduce over the contraction split
        return jax.lax.psum(z, gc_axis)

    spec_adj = P(gr_axis, gc_axis, None)
    spec_h = P((gc_axis, gr_axis), None)
    spec_z = P(gr_axis, None)
    return shard_map(local, mesh=mesh,
                     in_specs=(spec_adj, spec_adj, spec_adj, spec_h),
                     out_specs=spec_z)(*adj_parts, h)


def shard_gcn_inputs(mesh: Mesh, rows, cols, values, h, n_nodes,
                     gr_axis="gr", gc_axis="gc"):
    """Host-side helper: partition + device_put the adjacency and features
    with the shardings :func:`spmm_15d` expects."""
    gr, gc = mesh.shape[gr_axis], mesh.shape[gc_axis]
    parts = partition_adjacency(np.asarray(rows), np.asarray(cols),
                                np.asarray(values), n_nodes, gr, gc)
    spec_adj = NamedSharding(mesh, P(gr_axis, gc_axis, None))
    adj = tuple(jax.device_put(p, spec_adj) for p in parts)
    h = jax.device_put(np.asarray(h, np.float32),
                       NamedSharding(mesh, P((gc_axis, gr_axis), None)))
    return adj, h


def gcn_forward(mesh, adj_parts, h, weights, n_nodes,
                gr_axis="gr", gc_axis="gc"):
    """Multi-layer GCN forward: Z_l = relu(A @ H_l @ W_l); final layer has no
    relu (logits). Weights are replicated; XLA keeps Z row-sharded over gr."""
    for i, w in enumerate(weights):
        z = spmm_15d(mesh, adj_parts, h, n_nodes, gr_axis, gc_axis)
        h = z @ w
        if i < len(weights) - 1:
            # re-shard activations to the (gc, gr) row partition for the
            # next layer's gather (the logits keep their natural P(gr) shard)
            h = jax.lax.with_sharding_constraint(
                jax.nn.relu(h),
                NamedSharding(mesh, P((gc_axis, gr_axis), None)))
    return h
