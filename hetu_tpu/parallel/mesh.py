"""Device-mesh construction for dp/pp/tp/sp/ep parallelism.

The reference expresses placement as DeviceGroups + per-op `deduce_states`
tuples and drives NCCL groups from Python (communicator/mpi_nccl_comm.py:145).
The TPU-native equivalent is one ``jax.sharding.Mesh`` with named axes; all
collectives are compiled (GSPMD or explicit lax collectives inside shard_map)
and ride ICI. Axis order is chosen so the innermost axes (tp/sp) map to
physically adjacent devices — tensor-parallel collectives are
latency-sensitive, data-parallel ones are not.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("dp", "pp", "ep", "sp", "tp")


def make_mesh(dp: int = 1, pp: int = 1, tp: int = 1, sp: int = 1, ep: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a 5-axis mesh (dp, pp, ep, sp, tp); size-1 axes cost nothing."""
    if devices is None:
        devices = jax.devices()
    want = dp * pp * tp * sp * ep
    assert want == len(devices), (
        f"mesh {dp}x{pp}x{ep}x{sp}x{tp}={want} != {len(devices)} devices")
    arr = np.asarray(devices).reshape(dp, pp, ep, sp, tp)
    return Mesh(arr, AXES)


def auto_mesh(n_devices: Optional[int] = None, tp: int = 1, pp: int = 1,
              sp: int = 1, ep: int = 1) -> Mesh:
    """Fill dp with whatever devices remain after the model axes."""
    devices = jax.devices()
    n = n_devices or len(devices)
    model = tp * pp * sp * ep
    assert n % model == 0, f"{n} devices not divisible by tp*pp*sp*ep={model}"
    return make_mesh(dp=n // model, pp=pp, tp=tp, sp=sp, ep=ep,
                     devices=devices[:n])


def named(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))
