"""Parameter initializers (reference ``python/hetu/initializers.py``).

Same class hierarchy and ``init.*`` helper surface; values are produced with
``jax.random`` on device at executor construction (the reference runs curand
kernels, numpy, or an on-PS init RPC — the PS path is handled by
``hetu_tpu.ps`` when a variable is PS-hosted).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .graph.node import Variable


class BaseInit:
    def __init__(self, shape):
        self.shape = tuple(int(s) for s in shape)

    def init(self, rng_key, dtype=np.float32):
        raise NotImplementedError

    # fan sizes with the reference's conv-aware convention
    def _fans(self):
        shape = self.shape
        if len(shape) == 2:
            return shape[0], shape[1]
        if len(shape) in (3, 4, 5):
            receptive = int(np.prod(shape[2:]))
            return shape[1] * receptive, shape[0] * receptive
        n = int(np.prod(shape))
        return n, n


class ConstantInit(BaseInit):
    def __init__(self, constant, shape):
        super().__init__(shape)
        self.constant = float(constant)

    def init(self, rng_key, dtype=np.float32):
        return jnp.full(self.shape, self.constant, dtype=dtype)


class ZerosInit(ConstantInit):
    def __init__(self, shape):
        super().__init__(0.0, shape)


class OnesInit(ConstantInit):
    def __init__(self, shape):
        super().__init__(1.0, shape)


class UniformInit(BaseInit):
    def __init__(self, low, high, shape):
        super().__init__(shape)
        self.low = float(low)
        self.high = float(high)

    def init(self, rng_key, dtype=np.float32):
        return jax.random.uniform(rng_key, self.shape, dtype=jnp.float32,
                                  minval=self.low, maxval=self.high).astype(dtype)


class NormalInit(BaseInit):
    def __init__(self, mean, stddev, shape):
        super().__init__(shape)
        self.mean = float(mean)
        self.stddev = float(stddev)

    def init(self, rng_key, dtype=np.float32):
        return (self.mean + self.stddev *
                jax.random.normal(rng_key, self.shape, dtype=jnp.float32)).astype(dtype)


class TruncatedNormalInit(BaseInit):
    def __init__(self, mean, stddev, shape):
        super().__init__(shape)
        self.mean = float(mean)
        self.stddev = float(stddev)

    def init(self, rng_key, dtype=np.float32):
        z = jax.random.truncated_normal(rng_key, -2.0, 2.0, self.shape, jnp.float32)
        return (self.mean + self.stddev * z).astype(dtype)


class GeneralizedXavierUniformInit(UniformInit):
    def __init__(self, gain, mode, shape):
        fan_in, fan_out = BaseInit(shape)._fans()
        fan = {"fan_in": fan_in, "fan_out": fan_out,
               "avg": (fan_in + fan_out) / 2.0}[mode]
        limit = float(np.sqrt(gain / fan))
        super().__init__(-limit, limit, shape)


class XavierUniformInit(GeneralizedXavierUniformInit):
    def __init__(self, shape):
        super().__init__(3.0, "avg", shape)


class HeUniformInit(GeneralizedXavierUniformInit):
    def __init__(self, shape):
        super().__init__(6.0, "fan_in", shape)


class LecunUniformInit(GeneralizedXavierUniformInit):
    def __init__(self, shape):
        super().__init__(3.0, "fan_in", shape)


class GeneralizedXavierNormalInit(NormalInit):
    def __init__(self, gain, mode, shape):
        fan_in, fan_out = BaseInit(shape)._fans()
        fan = {"fan_in": fan_in, "fan_out": fan_out,
               "avg": (fan_in + fan_out) / 2.0}[mode]
        stddev = float(np.sqrt(gain / fan))
        super().__init__(0.0, stddev, shape)


class XavierNormalInit(GeneralizedXavierNormalInit):
    def __init__(self, shape):
        super().__init__(1.0, "avg", shape)


class HeNormalInit(GeneralizedXavierNormalInit):
    def __init__(self, shape):
        super().__init__(2.0, "fan_in", shape)


class LecunNormalInit(GeneralizedXavierNormalInit):
    def __init__(self, shape):
        super().__init__(1.0, "fan_in", shape)


# ---------------------------------------------------------------------------
# user-facing helpers (reference initializers.py:214-297): each returns a
# Variable node carrying its initializer.
# ---------------------------------------------------------------------------

def _make(initializer, name, trainable, ctx, **kwargs):
    return Variable(name=name, initializer=initializer, trainable=trainable,
                    ctx=ctx, **kwargs)


def zeros(shape, name=None, trainable=True, ctx=None, **kwargs):
    return _make(ZerosInit(shape), name, trainable, ctx, **kwargs)


def ones(shape, name=None, trainable=True, ctx=None, **kwargs):
    return _make(OnesInit(shape), name, trainable, ctx, **kwargs)


def constant(shape, fill_value=0.0, name=None, trainable=True, ctx=None, **kwargs):
    return _make(ConstantInit(fill_value, shape), name, trainable, ctx, **kwargs)


def truncated_normal(shape, mean=0.0, stddev=1.0, name=None, trainable=True,
                     ctx=None, **kwargs):
    return _make(TruncatedNormalInit(mean, stddev, shape), name, trainable, ctx,
                 **kwargs)


def random_normal(shape, mean=0.0, stddev=1.0, name=None, trainable=True,
                  ctx=None, **kwargs):
    return _make(NormalInit(mean, stddev, shape), name, trainable, ctx, **kwargs)


def random_uniform(shape, minval=-1.0, maxval=1.0, name=None, trainable=True,
                   ctx=None, **kwargs):
    return _make(UniformInit(minval, maxval, shape), name, trainable, ctx, **kwargs)


def xavier_normal(shape, name=None, trainable=True, ctx=None, **kwargs):
    return _make(XavierNormalInit(shape), name, trainable, ctx, **kwargs)


def xavier_uniform(shape, name=None, trainable=True, ctx=None, **kwargs):
    return _make(XavierUniformInit(shape), name, trainable, ctx, **kwargs)


def he_normal(shape, name=None, trainable=True, ctx=None, **kwargs):
    return _make(HeNormalInit(shape), name, trainable, ctx, **kwargs)


def he_uniform(shape, name=None, trainable=True, ctx=None, **kwargs):
    return _make(HeUniformInit(shape), name, trainable, ctx, **kwargs)


def lecun_normal(shape, name=None, trainable=True, ctx=None, **kwargs):
    return _make(LecunNormalInit(shape), name, trainable, ctx, **kwargs)


def lecun_uniform(shape, name=None, trainable=True, ctx=None, **kwargs):
    return _make(LecunUniformInit(shape), name, trainable, ctx, **kwargs)
