"""CacheSparseTable: client-side embedding cache with bounded staleness.

Capability parity with the reference's ``python/hetu/cstable.py`` (policy
selection :25-33, async lookup/update/push-pull returning wait handles
:47-119, perf counters and miss-rate/data-rate helpers :126-187). The backing
store is the C++ cache in ``hetu_tpu/csrc/cache`` via ctypes (the reference
uses a pybind11 ``hetu_cache`` module).

hetuq interplay (docs/COMM_QUANT.md): with ``comm_quant`` active the
kSyncEmbedding/kPushSyncEmbedding wire payloads the cache's server traffic
rides are quantized, but the worker agent dequantizes every pulled row
BEFORE it reaches the cache (``worker.h rsp_view``) and the server applies
pushed grads in f32 — cached lines are always plain f32 rows and the
bounded-staleness version algebra is untouched; quantization exists only on
the wire between them.
"""
from __future__ import annotations

import ctypes
import json

import numpy as np

from .csrc.build import build

_POLICY = {"lru": 0, "lfu": 1, "lfuopt": 2}

_u64p = ctypes.POINTER(ctypes.c_ulonglong)
_f32p = ctypes.POINTER(ctypes.c_float)
_i64p = ctypes.POINTER(ctypes.c_long)

_lib = None


def _load():
    global _lib
    if _lib is None:
        _lib = ctypes.CDLL(build("libhetu_ps.so"))
        _lib.CacheCreate.restype = ctypes.c_void_p
        _lib.CacheCreate.argtypes = [ctypes.c_int, ctypes.c_long,
                                     ctypes.c_long, ctypes.c_long,
                                     ctypes.c_int]
        for name in ("CacheEmbeddingLookup", "CacheEmbeddingUpdate",
                     "CacheEmbeddingPushPull", "CacheSize", "CacheLimit",
                     "CacheKeys"):
            getattr(_lib, name).restype = ctypes.c_long
        for name in ("CacheLastError", "CachePerfJson", "CacheRepr"):
            getattr(_lib, name).restype = ctypes.c_char_p
        for name in ("CacheDestroy", "CacheSetBounds", "CacheBypass",
                     "CachePerfEnabled", "CacheInsertOne",
                     "CachePerfRollup"):
            getattr(_lib, name).restype = None
        _lib.CachePerfRollup.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_longlong),
            ctypes.c_int]
        _lib.CacheWait.restype = ctypes.c_int
        _lib.CacheCount.restype = ctypes.c_int
        _lib.CacheLookupOne.restype = ctypes.c_int
        for name in ("CacheDestroy", "CacheSetBounds", "CacheBypass",
                     "CachePerfEnabled", "CacheWait", "CacheSize",
                     "CacheLimit", "CachePerfJson", "CacheRepr"):
            fn = getattr(_lib, name)
            fn.argtypes = ([ctypes.c_void_p] +
                           {"CacheSetBounds": [ctypes.c_long, ctypes.c_long],
                            "CacheBypass": [ctypes.c_int],
                            "CachePerfEnabled": [ctypes.c_int],
                            "CacheWait": [ctypes.c_long]}.get(name, []))
    return _lib


class _Wait:
    """Async wait handle (reference wait_t futures, cstable.py:47)."""

    def __init__(self, lib, handle, ticket, keepalive):
        self._lib = lib
        self._handle = handle
        self._ticket = ticket
        # OUTPUT buffers must outlive the async op (results land in them);
        # input keys/grads are copied at enqueue in the C++ layer, so a
        # discarded wait handle is safe for fire-and-forget updates
        self._keepalive = keepalive

    def wait(self):
        if self._lib.CacheWait(ctypes.c_void_p(self._handle),
                               ctypes.c_long(self._ticket)) != 0:
            raise RuntimeError(self._lib.CacheLastError().decode())
        self._keepalive = None


def _keys_arr(keys):
    if hasattr(keys, "asnumpy"):
        keys = keys.asnumpy()
    arr = np.asarray(keys)
    if arr.dtype != np.uint64:
        arr = arr.astype(np.uint64)
    return np.ascontiguousarray(arr).ravel()


def _f32_arr(x):
    if hasattr(x, "asnumpy"):
        x = x.asnumpy()
    return np.ascontiguousarray(x, dtype=np.float32)


class CacheSparseTable:
    """Bounded-staleness cached view of a PS cache-table parameter.

    Args mirror the reference (cstable.py:20): limit = max cached lines,
    (length, width) = full table shape, node_id = PS tensor id, policy in
    {LRU, LFU, LFUOpt}, bound = staleness bound for both pull and push.
    """

    def __init__(self, limit, length, width, node_id, policy="LRU",
                 bound=100):
        from . import ps as ps_pkg
        comm = ps_pkg.get_worker_communicate()  # ensures worker Init ran
        lib = _load()
        self._lib = lib
        self._width = int(width)
        self._length = int(length)
        self._node_id = int(node_id)
        self._handle = lib.CacheCreate(
            ctypes.c_int(_POLICY[policy.lower()]), ctypes.c_long(int(limit)),
            ctypes.c_long(int(length)), ctypes.c_long(int(width)),
            ctypes.c_int(int(node_id)))
        if not self._handle:
            raise RuntimeError(lib.CacheLastError().decode())
        lib.CacheSetBounds(ctypes.c_void_p(self._handle),
                           ctypes.c_long(int(bound)),
                           ctypes.c_long(int(bound)))
        comm.BarrierWorker()

    def __del__(self):
        if getattr(self, "_handle", None):
            self._lib.CacheDestroy(ctypes.c_void_p(self._handle))
            self._handle = None

    # -- main async API ----------------------------------------------------
    def embedding_lookup(self, keys, dest, sync=False):
        k = _keys_arr(keys)
        d = _f32_arr(dest)
        assert d.shape == (k.size, self._width), (d.shape, k.size, self._width)
        ticket = self._lib.CacheEmbeddingLookup(
            ctypes.c_void_p(self._handle), k.ctypes.data_as(_u64p),
            ctypes.c_long(k.size), d.ctypes.data_as(_f32p))
        wait = _Wait(self._lib, self._handle, ticket, (d,))
        if sync:
            wait.wait()
            return d
        return wait

    def embedding_update(self, keys, grads, sync=False):
        k = _keys_arr(keys)
        g = _f32_arr(grads)
        assert g.shape == (k.size, self._width)
        ticket = self._lib.CacheEmbeddingUpdate(
            ctypes.c_void_p(self._handle), k.ctypes.data_as(_u64p),
            g.ctypes.data_as(_f32p), ctypes.c_long(k.size))
        wait = _Wait(self._lib, self._handle, ticket, None)
        if sync:
            wait.wait()
            return None
        return wait

    def embedding_push_pull(self, pullkeys, dest, pushkeys, grads,
                            sync=False):
        pk = _keys_arr(pullkeys)
        d = _f32_arr(dest)
        uk = _keys_arr(pushkeys)
        g = _f32_arr(grads)
        assert d.shape == (pk.size, self._width)
        assert g.shape == (uk.size, self._width)
        ticket = self._lib.CacheEmbeddingPushPull(
            ctypes.c_void_p(self._handle), pk.ctypes.data_as(_u64p),
            ctypes.c_long(pk.size), d.ctypes.data_as(_f32p),
            uk.ctypes.data_as(_u64p), g.ctypes.data_as(_f32p),
            ctypes.c_long(uk.size))
        wait = _Wait(self._lib, self._handle, ticket, (d,))
        if sync:
            wait.wait()
            return d
        return wait

    # -- properties / config ----------------------------------------------
    @property
    def width(self):
        return self._width

    @property
    def limit(self):
        return self._lib.CacheLimit(ctypes.c_void_p(self._handle))

    def __len__(self):
        return self._lib.CacheSize(ctypes.c_void_p(self._handle))

    def bypass(self):
        self._lib.CacheBypass(ctypes.c_void_p(self._handle), 1)

    def undobypass(self):
        self._lib.CacheBypass(ctypes.c_void_p(self._handle), 0)

    def perf_enabled(self, enable=True, rollup_only=False):
        """Arm perf accounting. ``rollup_only=True`` keeps only the O(1)
        cumulative counters (:meth:`telemetry_summary`) and skips the
        per-batch log behind :attr:`perf` — bounded memory on long runs."""
        self._lib.CachePerfEnabled(
            ctypes.c_void_p(self._handle),
            2 if (enable and rollup_only) else int(bool(enable)))

    @property
    def perf(self):
        return json.loads(
            self._lib.CachePerfJson(ctypes.c_void_p(self._handle)).decode())

    # -- perf helpers (reference cstable.py:165-187) -----------------------
    def overall_miss_rate(self, include_cold_start=False):
        perf = self.perf
        if not include_cold_start:
            perf = [x for x in perf if x["is_full"]]
        pull = [x for x in perf if x["type"] == "Pull"]
        if not pull:
            return -1
        return (sum(x["num_miss"] for x in pull)
                / max(1, sum(x["num_unique"] for x in pull)))

    def overall_data_rate(self, include_cold_start=False):
        perf = self.perf
        if not include_cold_start:
            perf = [x for x in perf if x["is_full"]]
        if not perf:
            return -1
        return (sum(x["num_transfered"] for x in perf)
                / max(1, sum(x["num_all"] for x in perf)))

    def telemetry_summary(self) -> dict:
        """O(1) rollup for the telemetry poll (docs/OBSERVABILITY.md):
        miss/data rates over ALL traffic (cold start included — an operator
        reconciles against total RPC counts) plus cumulative evictions.
        Rates are -1 until any traffic of that type exists. Requires
        ``perf_enabled(True)`` (the PS runtime arms it when telemetry is
        active). Reads the native running totals (``CachePerfRollup``) —
        unlike :attr:`perf`, no per-batch log crosses the ctypes boundary,
        so the poll stays cheap on arbitrarily long runs."""
        out = (ctypes.c_longlong * 6)()
        self._lib.CachePerfRollup(ctypes.c_void_p(self._handle), out, 6)
        batches, evictions, pull_miss, pull_uniq, transfered, num_all = (
            int(v) for v in out)
        return {
            "batches": batches,
            "evictions": evictions,
            "miss_rate": pull_miss / pull_uniq if pull_uniq else -1,
            "data_rate": transfered / num_all if num_all else -1,
        }

    # -- single-key debug API ----------------------------------------------
    def lookup(self, key):
        out = np.zeros(self._width, np.float32)
        ver = ctypes.c_long()
        ups = ctypes.c_long()
        found = self._lib.CacheLookupOne(
            ctypes.c_void_p(self._handle), ctypes.c_ulonglong(int(key)),
            out.ctypes.data_as(_f32p), ctypes.byref(ver), ctypes.byref(ups))
        if not found:
            return None
        return {"key": int(key), "data": out, "version": ver.value,
                "updates": ups.value}

    def count(self, key):
        return self._lib.CacheCount(ctypes.c_void_p(self._handle),
                                    ctypes.c_ulonglong(int(key)))

    def insert(self, key, embedding):
        e = _f32_arr(embedding).ravel()
        assert e.size == self._width
        self._lib.CacheInsertOne(ctypes.c_void_p(self._handle),
                                 ctypes.c_ulonglong(int(key)),
                                 e.ctypes.data_as(_f32p))

    def keys(self):
        cap = self._lib.CacheSize(ctypes.c_void_p(self._handle)) + 16
        out = np.zeros(cap, np.uint64)
        n = self._lib.CacheKeys(ctypes.c_void_p(self._handle),
                                out.ctypes.data_as(_u64p), ctypes.c_long(cap))
        return out[:min(n, cap)].tolist()

    def __repr__(self):
        return self._lib.CacheRepr(ctypes.c_void_p(self._handle)).decode()
