"""Device placement language: DeviceGroup + ``with ht.context(...)`` scoping.

Capability parity with the reference's ``python/hetu/context.py`` (DeviceGroup
:6, context() :117). On TPU the placement language maps onto a
``jax.sharding.Mesh``: a flat DeviceGroup of N devices is a data-parallel mesh
axis; a tuple inside the group (model-parallel subgroup in the reference)
becomes a model/tensor axis; multiple sequential ``context`` blocks become
pipeline stages. The graph-rewriting the reference does here (inserting
PipelineSend/Recv, split/concat combinations, context.py:173-408) is replaced
by sharding deduction in ``hetu_tpu/parallel``.
"""
from __future__ import annotations

import contextlib
import re

from .ndarray import DLContext, cpu, tpu, rcpu, rtpu

_context_stack: list["DeviceGroup"] = []


def _parse_ctx_literal(c):
    """Parse one context literal: DLContext | 'hostname:tpu:N' | 'tpu:N' | 'cpu:0'."""
    if isinstance(c, DLContext):
        return c
    if isinstance(c, str):
        c = c.lower().strip()
        m = re.fullmatch(r"(?:(?P<host>[\w\.\-]+):)?(?P<type>cpu|gpu|tpu):?(?P<id>\d+)?", c)
        if m is None:
            raise ValueError(f"Cannot parse context {c!r}")
        host = m.group("host") or "localhost"
        dtype = m.group("type")
        dev_id = int(m.group("id") or 0)
        if dtype == "cpu":
            return cpu(dev_id) if host == "localhost" else rcpu(host, dev_id)
        return tpu(dev_id) if host == "localhost" else rtpu(host, dev_id)
    raise ValueError(f"Cannot parse context {c!r}")


class DeviceGroup:
    """An ordered group of devices a (sub)graph is placed on.

    Reference context.py:6 — accepts a single context, a list, or nested
    tuples; a tuple denotes a model-parallel worker group (reference
    context.py:22-35). ``mp_device_num`` counts leaf devices.
    """

    def __init__(self, ctxs):
        self._contexts = self._parse_contexts(ctxs)
        self._is_mp = any(isinstance(c, tuple) for c in self._contexts)

    @staticmethod
    def _parse_contexts(ctxs):
        if isinstance(ctxs, DeviceGroup):
            return ctxs._contexts
        if isinstance(ctxs, str):
            ctxs = [s for s in ctxs.split(",") if s.strip()]
        # a bare tuple is ONE model-parallel subgroup; a list is the group list
        if not isinstance(ctxs, list):
            ctxs = [ctxs]
        result = []
        for c in ctxs:
            if isinstance(c, tuple):
                result.append(tuple(_parse_ctx_literal(x) for x in c))
            else:
                result.append(_parse_ctx_literal(c))
        return result

    @property
    def worker_num(self) -> int:
        return len(self._contexts)

    @property
    def mp_device_num(self) -> int:
        n = 0
        for c in self._contexts:
            n += len(c) if isinstance(c, tuple) else 1
        return n

    @property
    def is_mp(self) -> bool:
        return self._is_mp

    def __getitem__(self, i):
        return self._contexts[i]

    def __iter__(self):
        return iter(self._contexts)

    def __len__(self):
        return len(self._contexts)

    def flat(self):
        out = []
        for c in self._contexts:
            out.extend(c) if isinstance(c, tuple) else out.append(c)
        return out

    def __eq__(self, other):
        return isinstance(other, DeviceGroup) and self._contexts == other._contexts

    def __hash__(self):
        return hash(tuple(tuple(c) if isinstance(c, tuple) else c for c in self._contexts))

    def __repr__(self):
        return f"DeviceGroup({self._contexts})"


@contextlib.contextmanager
def context(ctx):
    """``with ht.context('tpu:0')`` — ops built inside get this placement.

    Reference context.py:117-124.
    """
    group = ctx if isinstance(ctx, DeviceGroup) else DeviceGroup(ctx)
    _context_stack.append(group)
    try:
        yield group
    finally:
        _context_stack.pop()


def get_current_context():
    return _context_stack[-1] if _context_stack else None


def mesh_device_group(dp: int, tp: int = 1, device: str = "tpu",
                      start: int = 0) -> DeviceGroup:
    """The DeviceGroup literal for a (dp, tp) mesh in the placement
    language: a flat group of ``dp`` devices, or ``dp`` uniform
    ``tp``-tuples (the model-parallel tuple syntax) when ``tp > 1`` —
    exactly what ``HetuConfig._deduce_mesh`` turns back into a
    ``jax.sharding.Mesh``. This is how a hetuplan mesh choice
    (``Plan.device_group()``, docs/ANALYSIS.md "Tier C") maps onto
    ``Executor(ctx=...)`` without hand-writing device literals."""
    if dp < 1 or tp < 1:
        raise ValueError(f"mesh_device_group needs dp>=1, tp>=1; "
                         f"got dp={dp}, tp={tp}")
    ids = iter(range(start, start + dp * tp))
    if tp == 1:
        return DeviceGroup([f"{device}:{i}" for i in ids])
    return DeviceGroup([tuple(f"{device}:{next(ids)}" for _ in range(tp))
                        for _ in range(dp)])
