"""Server/scheduler role entry points (ctypes over the native lib).

Reference parity: ``server_init``/``scheduler_init`` in gpu_ops/executor.py:80-100
load libps.so and call Init()/StartServer(); role and topology come from
DMLC_* env vars (runner.py:186-190). Same here — the env var names are kept so
reference cluster ymls (tests/pstests/local_s2_w2.yml) work unchanged.
"""
from __future__ import annotations

import ctypes

from ..csrc.build import build

_lib = None


def _load():
    global _lib
    if _lib is None:
        _lib = ctypes.CDLL(build("libhetu_ps.so"))
        _lib.LastError.restype = ctypes.c_char_p
    return _lib


def _check(lib):
    err = lib.LastError()
    if err:
        raise RuntimeError(err.decode())


def start_scheduler_from_env():
    lib = _load()
    lib.Init()
    _check(lib)


def scheduler_wait():
    """Block until every node has checked out (clean teardown) — bounded by
    DMLC_PS_SCHED_WAIT_TIMEOUT_MS (default 5 min), armed at the FIRST
    checkout and re-armed on each further one (training itself may run
    arbitrarily long): a node that died before checkout used to hang this
    forever; now a progress-free window raises with a diagnostic naming
    the ranks that never checked out."""
    lib = _load()
    lib.SchedulerWait()
    _check(lib)


def stop_scheduler():
    lib = _load()
    lib.Finalize()


def start_server_from_env():
    lib = _load()
    lib.Init()
    _check(lib)
    lib.StartServer()


def stop_server():
    lib = _load()
    lib.Finalize()
