"""PS high availability: supervised server auto-respawn.

``PSSupervisor`` polls the scheduler's liveness ledger (the ``kQueryServers``
wire message — implemented here over a raw socket so the supervisor needs
neither the native lib nor jax; it typically runs inside the launcher
parent) and, when a server's heartbeat lapses, respawns a replacement under
the SAME server id with ``DMLC_PS_RESTORE_DIR`` pointed at the snapshot
root. The replacement re-registers (the scheduler's recovery re-add path),
rebuilds its store from the freshest complete snapshot (params + optimizer
slots + row versions + resend-dedup ledger, see ``csrc/ps/server.h``), and
workers running with ``DMLC_PS_FAILOVER_DEADLINE_MS`` reconnect and re-issue
their in-flight requests — a server SIGKILL costs seconds and a bounded,
reported number of updates instead of the whole run.

Respawns are bounded (``max_respawns``, the ``heturun --ps-max-respawns``
knob); exhausting the budget records a ``fatal`` diagnostic instead of
looping, so the launcher can preserve the first real failure's exit code
exactly like the PR 1 worker-restart conventions.
"""
from __future__ import annotations

import json
import os
import socket
import struct
import sys
import threading
import time

from . import wire_constants as wire

# Wire format mirror of csrc/ps/net.h (host byte order, same-arch cluster —
# the same assumption the native van makes). MsgHeader is 32 bytes with no
# implicit padding; ArgHeader is 16. The structs live in wire_constants
# (the ONE Python mirror, hetucheck-verified); the historical _MSG_HDR /
# _ARG_HDR names stay because elastic.py and tests import them from here.
_MSG_HDR = wire.MSG_HDR               # type, tensor_id, req_id, n_args,
#                                       flags, client_id, world_ver (0 =
#                                       unversioned; hetu-elastic stamp)
_ARG_HDR = wire.ARG_HDR               # dtype, pad/crc, nbytes
_K_QUERY_SERVERS = wire.K_QUERY_SERVERS


class SchedulerUnreachable(ConnectionError):
    """The scheduler did not answer (dead, unreachable, or timed out).
    Replaces the opaque ``socket.timeout`` traceback a dead scheduler used
    to produce with a message naming the address. Subclasses
    ``ConnectionError`` (hence ``OSError``) so the supervisor's
    keep-polling path still treats it as the transient it usually is."""


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("scheduler closed mid-message")
        buf += chunk
    return buf


def query_servers(host: str, port: int, timeout: float = 2.0):
    """One ``kQueryServers`` round trip: returns ``(addrs, alive)`` where
    ``addrs[i]`` is server i's registered address ("" before registration)
    and ``alive[i]`` is 1 while its heartbeat is fresh. Empty lists until
    the first server registers. Raises :class:`SchedulerUnreachable`
    (naming the address) when the scheduler does not answer."""
    try:
        with socket.create_connection((host, port), timeout=timeout) as s:
            s.settimeout(timeout)
            s.sendall(_MSG_HDR.pack(_K_QUERY_SERVERS, 0, 0, 0, 0, -1, 0))
            head = _MSG_HDR.unpack(_recv_exact(s, _MSG_HDR.size))
            args = []
            for _ in range(head[3]):
                _, _, nbytes = _ARG_HDR.unpack(_recv_exact(s, _ARG_HDR.size))
                args.append(_recv_exact(s, nbytes))
    except (socket.timeout, OSError) as e:
        raise SchedulerUnreachable(
            f"scheduler at {host}:{port} unreachable ({e!r})") from e
    book = args[0].decode() if args else ""
    # one "addr\n" per server, "" before that server registered — keep the
    # empties (drop only the trailing terminator) so addrs[i] stays server i
    addrs = book.split("\n")[:-1] if book else []
    alive = list(struct.unpack(f"<{len(args[1]) // 4}i", args[1])) \
        if len(args) > 1 else []
    return addrs, alive


def apply_ha_env_defaults(env: dict):
    """Fill the PS-HA env knobs a launcher hands its roles — snapshot dir
    (a fresh tempdir when unset), snapshot cadence, worker failover
    deadline. Explicit values always win; shared by ``heturun
    --ps-max-respawns`` and ``launcher.launch`` so the two never drift.

    Returns the snapshot-root path THIS call created (the caller owns its
    cleanup at teardown — snapshots hold full PS state and would otherwise
    accumulate per run), or None when the env already named one."""
    import tempfile
    created = None
    if not env.get("DMLC_PS_SNAPSHOT_DIR"):
        created = tempfile.mkdtemp(prefix="hetu_ps_snap_")
        env["DMLC_PS_SNAPSHOT_DIR"] = created
    env.setdefault("DMLC_PS_SNAPSHOT_MS", "5000")
    env.setdefault("DMLC_PS_FAILOVER_DEADLINE_MS", "60000")
    return created


def mp_respawn_fn(ctx, target, env: dict, on_spawn=None):
    """Respawn callable for launchers whose servers are
    ``ctx.Process(target, (server_id, env))`` entries: the replacement gets
    the same env plus ``DMLC_PS_RESTORE_DIR`` -> the snapshot root.
    ``on_spawn(proc)`` (e.g. ``_procs.append``) keeps the launcher's
    teardown list aware of replacements."""
    def _respawn(i):
        renv = dict(env)
        renv["DMLC_PS_RESTORE_DIR"] = env["DMLC_PS_SNAPSHOT_DIR"]
        p = ctx.Process(target=target, args=(i, renv))
        p.start()
        if on_spawn is not None:
            on_spawn(p)
        return p
    return _respawn


def start_mp_supervisor(ctx, server_target, env: dict, server_procs: dict,
                        on_spawn, *, max_respawns: int) -> "PSSupervisor":
    """Build and start the launcher-side supervisor for ``ctx.Process``
    server children — the one wiring shared by ``heturun --ps-max-respawns``
    and ``launcher.launch`` so the two never drift. Replacements run
    ``server_target(server_id, env)`` with ``DMLC_PS_RESTORE_DIR`` pointed
    at the snapshot root; the scheduler address comes from the env block
    both launchers already hand their roles."""
    sup = PSSupervisor(env.get("DMLC_PS_ROOT_URI", "127.0.0.1"),
                       int(env.get("DMLC_PS_ROOT_PORT", 13200)),
                       len(server_procs),
                       mp_respawn_fn(ctx, server_target, env, on_spawn),
                       procs=server_procs, max_respawns=max_respawns)
    sup.start()
    return sup


def cleanup_snapshot_root(created) -> None:
    """Teardown half of ``apply_ha_env_defaults``: remove the snapshot root
    that call minted (it holds full PS state, twice over — repeated
    supervised runs must not accumulate them). No-op when the operator
    named their own dir (``created`` is None)."""
    if created:
        import shutil
        shutil.rmtree(created, ignore_errors=True)


def _proc_dead(proc) -> bool:
    """True when a child handle (subprocess.Popen or mp.Process) has
    exited; unknown handles are treated as dead (respawn is idempotent)."""
    if proc is None:
        return True
    if hasattr(proc, "poll"):          # subprocess.Popen
        return proc.poll() is not None
    if hasattr(proc, "is_alive"):      # multiprocessing.Process
        return not proc.is_alive()
    return True


def _proc_kill(proc) -> None:
    try:
        if hasattr(proc, "kill"):
            proc.kill()
        elif hasattr(proc, "terminate"):
            proc.terminate()
        if hasattr(proc, "wait"):
            proc.wait(timeout=5)
        elif hasattr(proc, "join"):
            proc.join(timeout=5)
    except Exception:  # noqa: BLE001 — teardown of a corpse must not throw
        pass


class PSSupervisor(threading.Thread):
    """Liveness-ledger poller + bounded auto-respawner (daemon thread).

    ``respawn(server_id) -> proc`` must start a replacement server process
    under the same id with ``DMLC_PS_RESTORE_DIR`` pointing at the snapshot
    root; the supervisor never builds environments itself, so the same class
    drives light subprocess clusters (``local_cluster``), ``heturun``'s
    mp.Process servers, and test harnesses.

    A server is respawned only after it has been seen alive once (its
    initial registration completed) and its heartbeat then lapsed for
    ``grace_polls`` consecutive polls; a still-running-but-silent process is
    killed first so the replacement can bind cleanly. After a respawn the
    server must register again before it is eligible for another one.
    """

    def __init__(self, sched_host: str, sched_port: int, n_servers: int,
                 respawn, procs=None, *, poll_s: float = 0.5,
                 max_respawns: int = 3, grace_polls: int = 2,
                 log=None, scale_policy=None, on_scale=None):
        super().__init__(name="hetu-ps-supervisor", daemon=True)
        self.sched_host = sched_host
        self.sched_port = int(sched_port)
        self.n_servers = int(n_servers)
        self.respawn = respawn
        # server id -> current process handle. Held BY REFERENCE: callers
        # (local_cluster, heturun, test harnesses) kill/replace entries in
        # their own dict, and the wedged-process check must see the same
        # handles — a private copy would silently desync.
        self.procs = procs if procs is not None else {}
        self.poll_s = float(poll_s)
        self.max_respawns = int(max_respawns)
        self.grace_polls = max(1, int(grace_polls))
        self.log = log or (lambda msg: print(f"# hetu ps-supervisor: {msg}",
                                             file=sys.stderr, flush=True))
        self.respawns = 0
        self.lapses = 0                  # heartbeat lapses detected
        self.fatal: str | None = None    # set when the budget is exhausted
        self.events: list[tuple[float, str]] = []
        self._seen_alive = [False] * self.n_servers
        self._dead_polls = [0] * self.n_servers
        # hetu-elastic scale hook: ``scale_policy.observe(stats_rows)`` is
        # fed raw kServerStats rows from every live server each poll; a
        # non-None recommendation goes to ``on_scale(decision)`` (e.g.
        # heturun --elastic's grow-server path). The supervisor only
        # RELAYS — it never resizes the world itself.
        self.scale_policy = scale_policy
        self.on_scale = on_scale
        self._stop_evt = threading.Event()
        # telemetry export: the supervisor lives in the (jax-free) launcher
        # parent, so it appends its own JSONL next to the workers' files
        # when a telemetry dir is configured (docs/OBSERVABILITY.md)
        tel_dir = os.environ.get("HETU_TELEMETRY_DIR")
        self._tel_path = (os.path.join(tel_dir, "ps_supervisor.jsonl")
                          if tel_dir else None)

    # -- lifecycle ---------------------------------------------------------
    def stop(self) -> None:
        self._stop_evt.set()
        if self.is_alive():
            self.join(timeout=10)

    def __enter__(self) -> "PSSupervisor":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- the poll loop -----------------------------------------------------
    def run(self) -> None:
        while not self._stop_evt.wait(self.poll_s):
            # nothing may kill this thread: an escaped exception would end
            # supervision with `fatal` still None, so the launchers would
            # keep treating HA as armed while no one respawns anything —
            # log best-effort and keep polling instead
            try:
                self._poll_once()
            except Exception as e:  # noqa: BLE001
                try:
                    self._note(f"supervisor poll error ({e!r}); continuing")
                except Exception:  # noqa: BLE001 — even logging may fail
                    pass

    def watch_server(self, sid: int, proc) -> None:
        """Extend supervision to a server that JOINED via an elastic grow:
        it gets the same heartbeat watch + respawn budget as the launch
        set."""
        while len(self._seen_alive) <= sid:
            self._seen_alive.append(False)
            self._dead_polls.append(0)
        self.procs[sid] = proc
        self.n_servers = max(self.n_servers, sid + 1)

    def unwatch_server(self, sid: int) -> None:
        """Stop supervising a server whose elastic grow ABORTED: it never
        became part of the committed world, so its death must not burn
        respawn budget. (The never-registered + no-process combination
        makes the poll skip the id.)"""
        if sid < len(self._seen_alive):
            self._seen_alive[sid] = False
            self._dead_polls[sid] = 0
        self.procs[sid] = None

    _scale_poll_count = 0
    SCALE_POLL_EVERY = 4  # stats cadence relative to the health poll

    def _poll_once(self) -> None:
        try:
            addrs, alive = query_servers(self.sched_host, self.sched_port)
        except OSError:
            return  # scheduler not up yet / transient — keep polling
        self._run_liveness(alive)
        # scale-policy stats LAST and on a reduced cadence with a short
        # timeout: the collection is advisory, and a wedged server's 3s
        # stats stall must not delay death-detection/respawn above
        if self.scale_policy is not None and self.on_scale is not None:
            self._scale_poll_count += 1
            if self._scale_poll_count % self.SCALE_POLL_EVERY:
                return
            try:
                from ..elastic import server_stats_raw
                # one shared deadline across the sweep: several wedged
                # servers must not stack their timeouts and stretch the
                # NEXT liveness poll past its cadence
                deadline = time.monotonic() + 2.0
                rows = []
                for a, al in zip(addrs, alive):
                    if not (a and al):
                        continue
                    left = deadline - time.monotonic()
                    if left <= 0.05:
                        break  # partial sweep; the policy sees fewer rows
                    rows.append(server_stats_raw(a, timeout=min(1.0, left)))
                decision = self.scale_policy.observe(rows)
                if decision:
                    self._note(f"scale policy recommends {decision}")
                    self.on_scale(decision)
            except Exception as e:  # noqa: BLE001 — advisory only
                self._note(f"scale policy poll failed ({e!r}); continuing")

    def _run_liveness(self, alive) -> None:
        # the scheduler's book only grows on kRegister, so a server that
        # died before ANY registration is invisible in `alive` — iterate
        # every expected id and treat the missing tail as not-alive, or
        # the dead-process path below could never run pre-registration
        for i in range(self.n_servers):
            if i < len(alive) and alive[i]:
                self._seen_alive[i] = True
                self._dead_polls[i] = 0
                continue
            if not self._seen_alive[i]:
                # never registered: initial bringup or a respawn in
                # flight — benign while the process is alive, but a
                # process that DIED before ever sending kRegister
                # (corrupt snapshot, bind failure) would stall
                # supervision forever if we only watched heartbeats
                h = self.procs.get(i)
                if h is None or not _proc_dead(h):
                    continue
            self._dead_polls[i] += 1
            if self._dead_polls[i] < self.grace_polls:
                continue
            self._dead_polls[i] = 0
            self.lapses += 1
            self._respawn(i)

    def stats(self) -> dict:
        """Health counters (telemetry surface): heartbeat lapses detected,
        respawns spent/budgeted, and the fatal diagnostic if any."""
        return {"lapses": self.lapses, "respawns": self.respawns,
                "max_respawns": self.max_respawns, "fatal": self.fatal}

    def _note(self, msg: str) -> None:
        self.events.append((time.time(), msg))
        self.log(msg)
        if self._tel_path:
            try:
                with open(self._tel_path, "a") as f:
                    f.write(json.dumps(
                        {"ts": round(time.time(), 3), "kind": "event",
                         "name": "ps_supervisor", "message": msg,
                         **self.stats()}) + "\n")
            except OSError:
                pass  # telemetry must not take supervision down

    def _respawn(self, i: int) -> None:
        if self.respawns >= self.max_respawns:
            if self.fatal is None:
                self.fatal = (f"server {i} heartbeat lapsed but the respawn "
                              f"budget ({self.max_respawns}) is exhausted")
                self._note(self.fatal)
            return
        old = self.procs.get(i)
        if old is not None and not _proc_dead(old):
            # silent-but-running (wedged) server: clear the id before the
            # replacement tries to serve under it
            self._note(f"server {i} heartbeat lapsed but process still "
                       "running; killing the wedged process")
            _proc_kill(old)
        self.respawns += 1
        self._note(f"server {i} dead; respawning replacement "
                   f"{self.respawns}/{self.max_respawns} from snapshots")
        try:
            self.procs[i] = self.respawn(i)
        except Exception as e:  # noqa: BLE001
            # a failed spawn consumed budget (respawns was already bumped);
            # latch fatal only when none is left — a transient start()
            # failure (EAGAIN under load) retries on the next lapse instead
            # of tearing the whole run down while recovery is still possible
            if self.respawns >= self.max_respawns:
                self.fatal = f"respawn of server {i} failed: {e}"
                self._note(self.fatal)
            else:
                self._note(f"respawn of server {i} failed: {e}; retrying on "
                           "next poll")
            return
        # must register again before another death counts
        self._seen_alive[i] = False
