"""Host-side ring allreduce/allgather communicator (reference
``src/communication/c_communication_nthread.cc`` — the legacy ZMQ ring used
for CPU data parallelism without NCCL; here raw TCP, see
``csrc/ps/ring.h``).

On TPU the data-parallel gradient reduction is GSPMD's psum over ICI; this
communicator exists for capability parity and for accelerator-less workers
(e.g. host-only preprocessing jobs averaging statistics).
"""
from __future__ import annotations

import ctypes

import numpy as np

from .client import _load_lib

_f32p = ctypes.POINTER(ctypes.c_float)


class RingCommunicator:
    """One per process. ``rank``/``nranks`` + a shared host/base_port define
    the ring: rank r listens at base_port+r and connects to rank (r+1)%n."""

    def __init__(self, rank: int, nranks: int, host: str = "127.0.0.1",
                 base_port: int = 14400):
        self._lib = _load_lib()
        self._lib.RingInit(ctypes.c_int(rank), ctypes.c_int(nranks),
                           host.encode(), ctypes.c_int(base_port))
        self._check()
        self.rank = rank
        self.nranks = nranks

    def _check(self):
        err = self._lib.LastError()
        if err:
            raise RuntimeError(err.decode())

    def allreduce(self, arr: np.ndarray) -> np.ndarray:
        """In-place sum-allreduce of a float32 array; returns it."""
        arr = np.ascontiguousarray(arr, dtype=np.float32)
        self._lib.RingAllReduce(arr.ctypes.data_as(_f32p),
                                ctypes.c_long(arr.size))
        self._check()
        return arr

    def allgather(self, arr: np.ndarray) -> np.ndarray:
        """Gather equal-sized float32 arrays from all ranks; returns
        (nranks, *arr.shape)."""
        arr = np.ascontiguousarray(arr, dtype=np.float32)
        out = np.empty((self.nranks,) + arr.shape, np.float32)
        self._lib.RingAllGather(arr.ctypes.data_as(_f32p),
                                out.ctypes.data_as(_f32p),
                                ctypes.c_long(arr.size))
        self._check()
        return out

    def barrier(self):
        self._lib.RingBarrier()
        self._check()

    def finalize(self):
        self._lib.RingFinalize()
        self._check()
